#!/bin/bash
# One-shot on-chip capture for a healthy relay window (round-4 VERDICT
# #1-#5,#7: the full on-chip queue). Runs the measure stage directly
# with a generous budget and every rung enabled, then stamps the result
# into BENCH_local_tpu.json. Run from the repo root:
#
#   bash scripts/tpu_capture.sh [budget_seconds]
#
# The driver's own bench run keeps its 540 s budget; this script is the
# builder-local capture with room for sim256 + sim256_sync + verify1024
# + msm1024 + the Pallas probes.
set -u
cd "$(dirname "$0")/.."
BUDGET="${1:-1500}"

echo "probing relay first (90 s timeout)..."
if ! timeout 90 python -c "
import jax
print('relay OK:', jax.devices())
"; then
    echo "relay did not answer; aborting capture" >&2
    exit 1
fi

OUT="/tmp/tpu_capture_$$.json"
LOG="/tmp/tpu_capture_$$.log"
env DAGRIDER_BENCH_STAGE=measure \
    DAGRIDER_BENCH_SECONDS="$BUDGET" \
    DAGRIDER_BENCH_SIM_S=60 \
    DAGRIDER_BENCH_SIM256_S=90 \
    DAGRIDER_BENCH_SIM256_SYNC_S=40 \
    DAGRIDER_BENCH_SIM256_BUCKET="${SIM256_BUCKET:-512}" \
    DAGRIDER_BENCH_HOSTSIM_S=12 \
    DAGRIDER_BENCH_HOSTSIM256_S=12 \
    DAGRIDER_BENCH_MARK_FILE="$PWD/bench_marks.log" \
    timeout $((BUDGET + 120)) python -u bench.py > "$OUT" 2> "$LOG"
rc=$?
tail -5 "$LOG" >&2
if [ $rc -ne 0 ] || ! tail -1 "$OUT" | python -c "
import json, sys
d = json.loads(sys.stdin.read())
assert d.get('value', 0) > 0, d
print('value', d['value'], d['unit'], 'backend', d['backend'])
"; then
    echo "capture failed (rc=$rc); partial output in $OUT, log in $LOG" >&2
    exit 1
fi
tail -1 "$OUT" | python -c "
import datetime, json, sys
d = json.loads(sys.stdin.read())
d['captured_at'] = datetime.datetime.now().isoformat(timespec='seconds')
d['round'] = 5
json.dump(d, open('BENCH_local_tpu.json', 'w'), indent=1)
print('wrote BENCH_local_tpu.json:', d['value'], d['unit'],
      'on', d.get('device_kind'))
"
