"""Latency-attribution CLI over trace rings and flight dumps.

Four modes, all joining on the obs event schema:

    python scripts/obs_report.py report trace.json
        Per-percentile submit->deliver decomposition (mempool queue /
        propose stage / wave commit, with the wave split into host-pump
        / verify / cert / transport-wait by phase-span occupancy).

    python scripts/obs_report.py chrome trace.json out.json
        Chrome Trace Event Format (chrome://tracing, Perfetto).

    python scripts/obs_report.py flight dump.json
        Summarize one flight-recorder dump: trigger, metrics snapshot
        keys, event mix of the last-N ring.

    JAX_PLATFORMS=cpu python scripts/obs_report.py capture --out t.json
        Run a small traced mempool-fronted simulation and report on the
        captured ring (the CI smoke: proves the whole capture ->
        export -> decompose path end to end).

Accepts raw event lists, flight dumps, and chrome traces produced by
this package interchangeably (``obs.export.load_events`` sniffs the
container shape).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cmd_report(args: argparse.Namespace) -> int:
    from dag_rider_tpu.obs import export, report

    events = export.load_events(args.path)
    rep = report.decompose(events)
    print(report.format_report(rep))
    if args.json:
        print(json.dumps(rep, indent=2, default=repr))
    return 0


def _cmd_chrome(args: argparse.Namespace) -> int:
    from dag_rider_tpu.obs import export

    events = export.load_events(args.path)
    export.write_chrome_trace(events, args.out)
    print(f"{len(events)} events -> {args.out}")
    return 0


def _cmd_flight(args: argparse.Namespace) -> int:
    from dag_rider_tpu.obs import export

    dump = export.load_flight(args.path)
    if dump is None:
        print(f"{args.path}: not a flight dump", file=sys.stderr)
        return 1
    events = dump.get("events", [])
    mix: dict = {}
    for rec in events:
        name = rec.get("event", "?")
        mix[name] = mix.get(name, 0) + 1
    print(f"reason:  {dump.get('reason')}")
    print(f"trigger: {dump.get('trigger')}")
    print(f"events:  {len(events)} retained, {dump.get('dropped')} dropped")
    for name in sorted(mix, key=mix.get, reverse=True):
        print(f"  {name:24s} {mix[name]}")
    metrics = dump.get("metrics", {})
    for src in sorted(metrics):
        counters = metrics[src].get("counters", metrics[src])
        nonzero = sum(1 for v in counters.values() if v)
        print(f"metrics[{src}]: {nonzero} nonzero counters")
    return 0


def _cmd_capture(args: argparse.Namespace) -> int:
    # force the knob on for this process: the whole point of the smoke
    # is exercising the knob-gated auto-wiring inside Simulation
    os.environ["DAGRIDER_TRACE"] = "1"
    from dag_rider_tpu.config import Config, MempoolConfig
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.mempool.loadgen import ClusterLoadDriver, LoadGenerator
    from dag_rider_tpu.obs import report

    sim = Simulation(
        Config(
            n=args.n,
            propose_empty=True,
            sync_request_cooldown_s=0.0,
            sync_serve_cooldown_s=0.0,
        )
    )
    assert sim.recorder is not None, "DAGRIDER_TRACE wiring failed"
    gen = LoadGenerator(
        clients=4, rate=args.rate, tx_bytes=32, seed=args.seed
    )
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(cap=4096, batch_bytes=1024),
        wall=True,
    )
    entry = drv.run(args.seconds, drain_s=max(10.0, args.seconds))
    events = sim.recorder.events()
    if args.out:
        sim.recorder.write_json(args.out)
        print(f"{len(events)} events -> {args.out}")
    rep = report.decompose(events)
    print(report.format_report(rep))
    print(
        f"committed {entry['committed_tx']}/{entry['offered_tx']} tx, "
        f"ring dropped {sim.recorder.dropped}"
    )
    if not rep["txs"]:
        print("capture produced no complete chains", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="trace ring / flight dump latency attribution"
    )
    sub = ap.add_subparsers(dest="mode", required=True)

    p = sub.add_parser("report", help="latency-attribution table")
    p.add_argument("path")
    p.add_argument("--json", action="store_true", help="also dump JSON")
    p.set_defaults(fn=_cmd_report)

    p = sub.add_parser("chrome", help="export Chrome Trace Event JSON")
    p.add_argument("path")
    p.add_argument("out")
    p.set_defaults(fn=_cmd_chrome)

    p = sub.add_parser("flight", help="summarize a flight dump")
    p.add_argument("path")
    p.set_defaults(fn=_cmd_flight)

    p = sub.add_parser("capture", help="run a small traced sim + report")
    p.add_argument("--n", type=int, default=4)
    p.add_argument("--seconds", type=float, default=3.0)
    p.add_argument("--rate", type=float, default=400.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="")
    p.set_defaults(fn=_cmd_capture)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
