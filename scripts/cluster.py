"""Cluster launcher: N OS processes, real sockets, real kill -9.

One command boots a full DAG-Rider committee as separate processes over
gRPC (UDS by default, TCP with --transport tcp), drives seeded open-loop
load through the wire-level Submit door, injects process-level faults on
a wall-clock plan, stops everything cleanly, and audits the logs:
commit-order agreement, zero lost accepted transactions, liveness, and
an empty distributed flight recorder. Exit code 0 iff the audit is
clean.

    JAX_PLATFORMS=cpu python scripts/cluster.py --n 4 --seconds 6 \
        --rate 300 --kill auto            # one seeded kill -9 + rejoin

    python scripts/cluster.py --n 4 --plan plan.json --adversary \
        equivocate@3                      # Byzantine node over sockets

Fault plans are JSON lists of {"t": seconds-from-load-start, "action":
"kill" | "restart" | "term", "node": i}. --kill auto generates a seeded
kill-and-rejoin plan (one victim, never node 0). Env defaults:
DAGRIDER_CLUSTER_TRANSPORT, DAGRIDER_CLUSTER_BOOT_S,
DAGRIDER_CLUSTER_KEEP, DAGRIDER_CLUSTER_OUT (see README knob table).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dag_rider_tpu import config as _cfg
from dag_rider_tpu.cluster import audit as audit_mod
from dag_rider_tpu.cluster import client as client_mod
from dag_rider_tpu.cluster.directory import build_cluster
from dag_rider_tpu.cluster.supervisor import ClusterSupervisor, seeded_kill_plan


def run_cluster(args) -> dict:
    root = args.root or tempfile.mkdtemp(prefix="dagrider-cluster-")
    adversaries = {}
    for spec_str in args.adversary or ():
        kind, _, node = spec_str.partition("@")
        adversaries[int(node)] = {"kind": kind, "seed": args.seed}
    overrides = json.loads(args.node_overrides) if args.node_overrides else None
    wan = json.loads(args.wan) if args.wan else None

    spec = build_cluster(
        root,
        args.n,
        transport=args.transport,
        seed=args.seed,
        cert=args.cert,
        adversaries=adversaries or None,
        wan=wan,
        node_overrides=overrides,
    )

    plan = []
    if args.plan:
        with open(args.plan) as fh:
            plan = json.load(fh)
    elif args.kill == "auto":
        plan = seeded_kill_plan(
            args.seed,
            args.n,
            kill_at_s=args.kill_at,
            restart_after_s=args.restart_after,
        )
    elif args.kill:
        plan = [
            {"t": args.kill_at, "action": "kill", "node": int(args.kill)},
            {
                "t": args.kill_at + args.restart_after,
                "action": "restart",
                "node": int(args.kill),
            },
        ]

    sup = ClusterSupervisor(spec)
    sup.start_all()
    not_ready = sup.wait_ready(args.boot_timeout)
    if not_ready:
        sup.stop_all()
        return {
            "ok": False,
            "violations": [
                {
                    "check": "boot",
                    "detail": f"nodes {not_ready} not ready within "
                    f"{args.boot_timeout}s (see stderr.log)",
                }
            ],
            "root": root,
        }

    load_result: dict = {}

    def _load():
        load_result.update(
            client_mod.drive_load(
                spec,
                duration_s=args.seconds,
                rate=args.rate,
                clients=args.clients,
                seed=args.seed,
                profile=args.profile,
            )
        )

    loader = threading.Thread(target=_load, daemon=True)
    loader.start()
    executed = sup.run_plan(plan)
    loader.join(timeout=args.seconds + 30)

    # rejoiners need to be back before the audit asks for their final
    # report — give any restarted node its boot window
    if any(ev["action"] == "restart" for ev in executed):
        sup.wait_ready(args.boot_timeout)
    if args.settle > 0:
        threading.Event().wait(args.settle)

    forced = sup.stop_all()
    report = audit_mod.audit_cluster(
        spec,
        restarted=sup.restart_counts.keys(),
        byzantine=adversaries.keys(),
    )
    report["root"] = root
    report["load"] = load_result
    report["fault_plan"] = executed
    report["forced_stops"] = forced
    report["kills"] = dict(sup.kill_counts)
    report["restarts"] = dict(sup.restart_counts)
    report["exit_codes"] = {
        str(i): c for i, c in sup.exit_codes().items()
    }
    report["commit_prefixes"] = {
        str(i): {"len": ln, "sha256": hx}
        for i, (ln, hx) in audit_mod.commit_prefix_digest(spec).items()
    }

    keep = args.keep or not report["ok"]
    if not keep and not args.root:
        shutil.rmtree(root, ignore_errors=True)
        report["root"] = "(removed — pass --keep to retain)"
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/cluster.py",
        description="multi-process DAG-Rider cluster with fault injection",
    )
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument(
        "--transport",
        choices=("uds", "tcp"),
        default=_cfg.env_choice("DAGRIDER_CLUSTER_TRANSPORT"),
    )
    ap.add_argument("--seconds", type=float, default=6.0)
    ap.add_argument("--rate", type=float, default=300.0)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--profile", default="poisson")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--cert", default="off", choices=("off", "agg"))
    ap.add_argument(
        "--kill",
        default=None,
        help="node index to kill -9 mid-load, or 'auto' for a seeded pick",
    )
    ap.add_argument("--kill-at", type=float, default=2.0)
    ap.add_argument("--restart-after", type=float, default=1.5)
    ap.add_argument("--plan", default=None, help="fault-plan JSON file")
    ap.add_argument(
        "--adversary",
        action="append",
        help="kind@node, e.g. equivocate@3 (repeatable)",
    )
    ap.add_argument("--wan", default=None, help="WanFault config JSON")
    ap.add_argument(
        "--node-overrides", default=None, help="extra node-config JSON"
    )
    ap.add_argument(
        "--settle",
        type=float,
        default=1.5,
        help="post-load quiesce window before shutdown",
    )
    ap.add_argument(
        "--boot-timeout",
        type=float,
        default=_cfg.env_float("DAGRIDER_CLUSTER_BOOT_S"),
    )
    ap.add_argument("--root", default=None, help="workspace dir (kept)")
    ap.add_argument(
        "--keep",
        action="store_true",
        default=_cfg.env_flag("DAGRIDER_CLUSTER_KEEP"),
    )
    ap.add_argument("--out", default=None, help="write report JSON here")
    args = ap.parse_args(argv)

    report = run_cluster(args)
    print(json.dumps(report, indent=1, default=repr))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, default=repr)
    if not report["ok"]:
        print(
            f"AUDIT FAILED: {[v['check'] for v in report['violations']]}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
