"""Full-stack soak: the whole framework running as deployed, for minutes.

4-node localhost committee over MAC'd gRPC with Bracha RBC, the
threshold-BLS coin, GC pruning, periodic checkpoints, one node verifying
through a gRPC sidecar, a mid-run crash + checkpoint-restart, and
end-of-run assertions: prefix-consistent delivery, bounded live state,
zero auth rejects / pump errors, bounded RSS high-water growth.

Not a pytest (runtime is minutes); run manually or from CI's slow lane:
    JAX_PLATFORMS=cpu python scripts/soak.py [seconds]
"""

from __future__ import annotations

import json
import os
import resource
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dag_rider_tpu import node as node_mod
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.sidecar import VerifierSidecarServer


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main(box_s: float) -> int:
    tmp = tempfile.mkdtemp(prefix="dagrider-soak-")
    keys_path = os.path.join(tmp, "keys.json")
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", keys_path]
    )
    reg, _, _ = node_mod.load_keys(json.load(open(keys_path)))
    sidecar = VerifierSidecarServer(CPUVerifier(reg), "127.0.0.1:0")

    listen_addrs: dict = {}

    def mk(i):
        cfg = {
            "index": i,
            "n": 4,
            # stable addresses, like a real deployment: a restarted node
            # reappears on the same port and peers' channels reconnect
            "listen": listen_addrs.get(i, "127.0.0.1:0"),
            "peers": {},
            "keys": keys_path,
            "rbc": True,
            "coin": "threshold_bls",
            "propose_empty": True,
            "gc_depth": 16,
            "auth_master": "50" * 32,
            "checkpoint_dir": os.path.join(tmp, f"ckpt{i}"),
            "checkpoint_every_s": 10,
            "submit_interval_s": 0.5,
            # node 3 exercises the sidecar deployment shape; the rest
            # verify in-process
            "verifier": "remote" if i == 3 else "cpu",
            "verifier_address": f"127.0.0.1:{sidecar.bound_port}",
        }
        return node_mod.Node(cfg)

    nodes = {i: mk(i) for i in range(4)}
    addrs = {i: f"127.0.0.1:{nd.net.bound_port}" for i, nd in nodes.items()}
    listen_addrs.update(addrs)
    for i, nd in nodes.items():
        nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
    for nd in nodes.values():
        nd.start()
    for nd in nodes.values():
        nd.submit(Block((b"soak-seed",)))

    t0 = time.monotonic()
    rss0 = rss_mb()
    crashed_at = None
    restarted = False
    report_at = 30.0
    while time.monotonic() - t0 < box_s:
        time.sleep(1.0)
        el = time.monotonic() - t0
        # crash node 2 a third of the way in; restart it from its
        # checkpoint at the halfway mark (elastic recovery, live)
        if crashed_at is None and el > box_s / 3:
            nodes[2].stop()
            crashed_at = el
            print(f"[soak +{el:5.0f}s] node 2 stopped (checkpointed)")
        if crashed_at is not None and not restarted and el > box_s / 2:
            # same stable address: surviving peers' channels reconnect by
            # themselves, and the new node takes its peer table via the
            # supported config path
            nodes[2] = mk(2)
            nodes[2].net._peers.update(
                {j: a for j, a in addrs.items() if j != 2}
            )
            nodes[2].start()
            restarted = True
            print(
                f"[soak +{el:5.0f}s] node 2 restarted from checkpoint "
                f"at round {nodes[2].process.round}"
            )
        if el >= report_at:
            report_at += 30.0
            p0 = nodes[0].process
            print(
                f"[soak +{el:5.0f}s] round={p0.round} base={p0.dag.base_round} "
                f"live={len(p0.dag.vertices)} delivered={len(nodes[0].delivered)} "
                f"rss={rss_mb():.0f}MB"
            )
    for nd in nodes.values():
        nd.stop()
    sidecar.stop()

    # ---- assertions -----------------------------------------------------
    failures = []
    logs = {
        i: [(v.id.round, v.id.source, v.digest()) for v in nd.delivered]
        for i, nd in nodes.items()
    }
    # prefix consistency among the always-up nodes
    up = [logs[i] for i in (0, 1, 3)]
    k = min(len(l) for l in up)
    if not all(l[:k] == up[0][:k] for l in up):
        failures.append("divergent delivery among up nodes")
    # the restarted node's log is order-consistent with node 0's
    pos = {e: i for i, e in enumerate(logs[0])}
    got = [pos[e] for e in logs[2] if e in pos]
    if got != sorted(got):
        failures.append("restarted node delivery order diverged")
    for i, nd in nodes.items():
        snap = nd.process.metrics.snapshot()
        if snap.get("net_auth_rejects"):
            failures.append(f"node {i}: auth rejects {snap['net_auth_rejects']}")
        if snap.get("pump_errors"):
            failures.append(f"node {i}: pump errors {snap['pump_errors']}")
        window = nd.process.dag.max_round - nd.process.dag.base_round + 1
        if len(nd.process.dag.vertices) > 4 * (window + 1):
            failures.append(f"node {i}: live vertices exceed the window")
        if nd.process.dag.base_round == 0 and nd.process.round > 40:
            failures.append(f"node {i}: never pruned")
    # the restarted node actually rejoined the live frontier (its
    # checkpoint was far below the cluster's GC horizon, so this
    # exercised nack-quorum -> snapshot state transfer -> catch-up)
    if nodes[2].process.round < nodes[0].process.round - 60:
        failures.append("restarted node failed to catch up")
    if not nodes[2].process.metrics.counters.get("state_transfers"):
        failures.append("restarted node never state-transferred")
    growth = rss_mb() - rss0
    if growth > 300.0:
        failures.append(f"RSS high-water grew {growth:.0f}MB")
    p0 = nodes[0].process
    print(
        f"[soak] done: round={p0.round} base={p0.dag.base_round} "
        f"delivered={len(nodes[0].delivered)} restarted_round="
        f"{nodes[2].process.round} rss_growth={growth:.0f}MB"
    )
    if failures:
        print("[soak] FAILURES:", failures)
        return 1
    print("[soak] OK: agreement, bounded window, clean metrics, restart recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main(float(sys.argv[1]) if len(sys.argv) > 1 else 480.0))
