"""Joint-Feldman DKG (crypto/dkg.py) — the dealerless PKI for the
threshold-BLS coin (reference TODO: process/process.go:388).

The properties that matter: every honest participant derives the SAME
group pk / share pks, only its own secret share, the output drives the
existing threshold machinery (sign_share/aggregate/verify_group)
unchanged, and Byzantine dealers (bad shares, silence, malformed or
out-of-subgroup commitments) are disqualified without stalling the run.
"""

from __future__ import annotations

import pytest

from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import dkg, ed25519 as ed
from dag_rider_tpu.crypto import threshold as th


def _seeds(n: int):
    return [bytes([i]) * 32 for i in range(n)]


def test_honest_run_agrees_and_signs():
    n, t = 4, 2
    results = dkg.run_dkg(n, t, _seeds(n))
    assert len(results) == n
    r0 = results[0]
    for r in results[1:]:
        assert r.group_pk == r0.group_pk
        assert r.share_pks == r0.share_pks
        assert r.qualified == r0.qualified
    # each share sk matches its public counterpart
    for r in results:
        assert bls.pk_of(r.share_sk) == r.share_pks[r.index]
    # and t shares drive the EXISTING coin machinery end to end
    wave = 7
    shares = {r.index: th.sign_share(r.share_sk, wave) for r in results[:t]}
    sigma = th.aggregate(shares, t)
    assert sigma is not None
    assert th.verify_group(r0.group_pk, wave, sigma)
    # a different t-subset interpolates the SAME sigma (uniqueness)
    shares2 = {r.index: th.sign_share(r.share_sk, wave) for r in results[-t:]}
    assert th.aggregate(shares2, t) == sigma
    # nobody's ThresholdKeys view carries anyone else's secret
    keys0 = r0.to_keys()
    assert keys0.share_sks[r0.index] == r0.share_sk
    assert all(
        sk is None for i, sk in enumerate(keys0.share_sks) if i != r0.index
    )


def test_bad_share_dealer_disqualified():
    n, t = 4, 2
    results = dkg.run_dkg(n, t, _seeds(n), byzantine={1: "bad_share"})
    assert len(results) == 3
    for r in results:
        assert 1 not in r.qualified
        assert set(r.qualified) == {0, 2, 3}
    # the surviving quorum still signs
    wave = 3
    shares = {r.index: th.sign_share(r.share_sk, wave) for r in results[:t]}
    sigma = th.aggregate(shares, t)
    assert sigma and th.verify_group(results[0].group_pk, wave, sigma)


def test_silent_dealer_disqualified():
    n, t = 4, 2
    results = dkg.run_dkg(n, t, _seeds(n), byzantine={2: "silent"})
    for r in results:
        assert 2 not in r.qualified


def test_too_few_qualified_fails_loudly():
    with pytest.raises(RuntimeError, match="qualified"):
        dkg.run_dkg(
            3, 3, _seeds(3), byzantine={0: "silent"}
        )


def test_malformed_commitments_disqualify():
    n, t = 4, 2
    pks = [ed.generate_keypair(s)[1] for s in _seeds(n)]
    sess = dkg.DkgSession(0, n, t, _seeds(n)[0], pks)
    # wrong length
    assert not sess.on_commitments(1, b"\x00" * 10)
    assert 1 in sess.disqualified
    # right length, garbage bytes (off-curve)
    assert not sess.on_commitments(2, b"\x01" * (t * 192))
    assert 2 in sess.disqualified


def test_unreduced_ladder_detects_non_subgroup_points():
    """The [r]P == O membership primitive (bls._ec_mul_raw — the same
    ladder g2_deserialize/g2_decode gate commitments through) must NOT
    reduce its scalar mod r (bls.g2_mul does, correctly for its
    r-torsion domain — using it would accept every point). Validated on
    E(Fp), whose cofactor > 1 makes full-group points a square-root
    scan away: a random curve point is (overwhelmingly) outside the
    r-subgroup and the ladder must say so, while r-subgroup points and
    the scan point scaled by the cofactor must pass."""
    h1 = 0x396C8C005555E1568C00AAAB0000AAAB  # E(Fp) cofactor

    def g1_raw(k, p):
        return bls._ec_mul_raw(bls._FP_OPS, k, p)

    def g2_raw(k, p):
        return bls._ec_mul_raw(bls._FP2_OPS, k, p)

    found = None
    for x in range(1, 200):
        rhs = (pow(x, 3, bls.P) + 4) % bls.P
        y = pow(rhs, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == rhs:
            p = (x, y)
            if g1_raw(bls.R, p) is not None:
                found = p
                break
    assert found is not None, "scan found no out-of-subgroup E(Fp) point"
    # clearing the cofactor lands it in the r-subgroup...
    cleared = g1_raw(h1, found)
    assert g1_raw(bls.R, cleared) is None
    # ...and genuine subgroup points pass on both curves
    assert g1_raw(bls.R, bls.G1_GEN) is None
    assert g2_raw(bls.R, bls.G2_GEN) is None
    assert g2_raw(bls.R, bls.g2_mul(987654321)) is None
    # the identity encoding is refused as a commitment
    assert dkg.g2_decode(bytes(192)) is None


def test_g2_decode_rejects_tampered_subgroup_blob():
    """Flipping coordinate bytes of a valid commitment must fail the
    twist-or-subgroup validation, never decode to a different point."""
    blob = bytearray(dkg.g2_encode(bls.g2_mul(424242)))
    for i in (0, 48, 96, 144, 191):
        bad = bytearray(blob)
        bad[i] ^= 0x01
        assert dkg.g2_decode(bytes(bad)) is None


def test_g2_roundtrip_and_subgroup_accepts_generator():
    blob = dkg.g2_encode(bls.G2_GEN)
    assert dkg.g2_decode(blob) == bls.G2_GEN
    p = bls.g2_mul(12345)
    assert dkg.g2_decode(dkg.g2_encode(p)) == p


def test_channel_key_symmetry_and_share_encryption():
    seeds = _seeds(3)
    pks = [ed.generate_keypair(s)[1] for s in seeds]
    k01 = dkg.channel_key(seeds[0], pks[1])
    k10 = dkg.channel_key(seeds[1], pks[0])
    assert k01 == k10 and k01 is not None
    blob = dkg.encrypt_share(k01, 0, 1, 123456789)
    assert dkg.decrypt_share(k10, 0, 1, blob) == 123456789
    # direction is bound: decrypting with swapped roles fails
    assert dkg.decrypt_share(k10, 1, 0, blob) is None
    # tampering fails closed
    bad = bytes([blob[0] ^ 1]) + blob[1:]
    assert dkg.decrypt_share(k10, 0, 1, bad) is None


def test_false_complaint_reveals_but_keeps_dealer():
    """A Byzantine complainer cannot disqualify an honest dealer: the
    reveal satisfies everyone and the dealer stays qualified."""
    n, t = 4, 2
    seeds = _seeds(n)
    pks = [ed.generate_keypair(s)[1] for s in seeds]
    sessions = [dkg.DkgSession(i, n, t, seeds[i], pks) for i in range(n)]
    for d, s in enumerate(sessions):
        cb = s.commitment_blob()
        for j, o in enumerate(sessions):
            if j != d:
                o.on_commitments(d, cb)
                o.on_share(d, s.share_blob_for(j))
    # participant 3 falsely complains about dealer 0
    for s in sessions:
        s.on_complaint(3, 0)
    reveal = sessions[0].reveal_blob(3)
    for s in sessions:
        s.on_reveal(0, 3, reveal)
    results = [s.finalize() for s in sessions]
    for r in results:
        assert 0 in r.qualified
    assert results[0].group_pk == results[3].group_pk


def test_networked_dkg_over_grpc_agrees_and_signs():
    """4 participants over real localhost gRPC (BlobBus): same group pk
    everywhere, a t-subset signs, and the whole run is dealerless."""
    import threading

    from dag_rider_tpu.transport.blobbus import BlobBus

    n, t = 4, 2
    seeds = _seeds(n)
    pks = [ed.generate_keypair(s)[1] for s in seeds]
    buses = [BlobBus(i, "127.0.0.1:0", {}) for i in range(n)]
    addrs = {i: f"127.0.0.1:{b.bound_port}" for i, b in enumerate(buses)}
    for b in buses:
        b._peers.update(addrs)
    results = [None] * n
    errors = []

    def run(i):
        try:
            results[i] = dkg.run_dkg_networked(
                buses[i], n, t, seeds[i], pks, phase_timeout_s=30.0
            )
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append((i, e))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for th_ in threads:
        th_.start()
    for th_ in threads:
        th_.join(timeout=60)
    for b in buses:
        b.close()
    assert not errors, errors
    assert all(r is not None for r in results)
    r0 = results[0]
    assert r0.qualified == tuple(range(n))  # all honest: nobody dropped
    for r in results[1:]:
        assert r.group_pk == r0.group_pk and r.share_pks == r0.share_pks
    wave = 11
    shares = {r.index: th.sign_share(r.share_sk, wave) for r in results[:t]}
    sigma = th.aggregate(shares, t)
    assert sigma and th.verify_group(r0.group_pk, wave, sigma)


def test_node_dkg_cli_roundtrip(tmp_path):
    """The node-level flow: keygen (identities) -> per-node `dkg`
    subcommand over gRPC -> per-node key files that load_keys accepts,
    carrying ONLY that node's secret share."""
    import threading

    from dag_rider_tpu import node as node_mod

    n, t = 4, 2
    keys_path = str(tmp_path / "keys.json")
    ident_dir = str(tmp_path / "identities")
    node_mod.main(
        [
            "keygen", "--n", str(n), "--threshold", str(t),
            "--out", keys_path, "--per-node-dir", ident_dir,
        ]
    )
    # the recommended ceremony input: per-node identity files holding
    # ONLY that node's seed (a combined all-seeds file would let any
    # single holder decrypt every DKG share on the wire)
    import json as _json

    ident0 = _json.load(open(f"{ident_dir}/node0-identity.json"))
    assert ident0["ed25519_seeds"][0] and ident0["ed25519_seeds"][1] is None
    assert ident0["bls_share_sks"][1] is None
    # pre-bind ports so every CLI invocation can name all peers
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    peer_arg = ",".join(f"{i}=127.0.0.1:{p}" for i, p in enumerate(ports))
    outs = [str(tmp_path / f"node{i}.json") for i in range(n)]
    errs = []

    def run(i):
        try:
            node_mod.main(
                [
                    "dkg",
                    "--keys", f"{ident_dir}/node{i}-identity.json",
                    "--index", str(i),
                    "--threshold", str(t),
                    "--listen", f"127.0.0.1:{ports[i]}",
                    "--peers", peer_arg,
                    "--out", outs[i],
                    "--timeout", "30",
                ]
            )
        except Exception:  # noqa: BLE001
            import traceback

            errs.append((i, traceback.format_exc()))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for th_ in threads:
        th_.start()
    for th_ in threads:
        th_.join(timeout=90)
    assert not errs, errs
    loaded = [node_mod.load_keys(_json.load(open(o))) for o in outs]
    _, _, ck0 = loaded[0]
    for i, (_, _, ck) in enumerate(loaded):
        assert ck.group_pk == ck0.group_pk
        assert ck.share_pks == ck0.share_pks
        # dealerless: only own secret present
        assert ck.share_sks[i] is not None
        assert all(
            sk is None for j, sk in enumerate(ck.share_sks) if j != i
        )
    # the shares drive the coin machinery
    wave = 5
    shares = {
        i: th.sign_share(loaded[i][2].share_sks[i], wave) for i in range(t)
    }
    sigma = th.aggregate(shares, t)
    assert sigma and th.verify_group(ck0.group_pk, wave, sigma)


def test_networked_dkg_survives_false_complaint():
    """Round-5 review repro: one forged complaint against an honest
    dealer previously aborted every networked ceremony (the runner never
    fed its own complaint/reveal broadcasts into its own session). Now
    the dealer reveals, everyone settles, and the ceremony succeeds with
    the dealer still qualified."""
    import threading

    from dag_rider_tpu.transport import blobbus
    from dag_rider_tpu.transport.blobbus import BlobBus

    n, t = 3, 2
    seeds = _seeds(n)
    pks = [ed.generate_keypair(s)[1] for s in seeds]
    buses = [BlobBus(i, "127.0.0.1:0", {}) for i in range(n)]
    addrs = {i: f"127.0.0.1:{b.bound_port}" for i, b in enumerate(buses)}
    for b in buses:
        b._peers.update(addrs)
    # inject a forged complaint "from node 2" naming dealer 0 BEFORE the
    # ceremony starts — it sits in node 0/1's inboxes and is consumed in
    # their first pump (no auth on this bus, so the sender stamp is
    # trusted: exactly the Byzantine frame the recovery round must eat)
    forged = blobbus._frame(2, "dkg_complaint", bytes([0]))
    import grpc as _grpc

    for target in (0, 1):
        chan = _grpc.insecure_channel(addrs[target])
        chan.unary_unary(
            "/dagrider.BlobBus/Post",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(forged, timeout=5)
        chan.close()

    results = [None] * n
    errors = []

    def run(i):
        try:
            results[i] = dkg.run_dkg_networked(
                buses[i], n, t, seeds[i], pks, phase_timeout_s=30.0
            )
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for th_ in threads:
        th_.start()
    for th_ in threads:
        th_.join(timeout=120)
    for b in buses:
        b.close()
    assert not errors, errors
    r0 = results[0]
    assert r0.qualified == tuple(range(n))  # dealer 0 survives
    for r in results[1:]:
        assert r.group_pk == r0.group_pk and r.share_pks == r0.share_pks


def test_session_tolerates_share_and_reveal_before_commitments():
    """Separate frames race over a real network: a share (or reveal)
    arriving before its dealer's commitments must be stashed and
    re-judged when they land — not misread as dealer fault (round-5
    flake: a late-starting participant complained about every dealer,
    then rejected their valid reveals for want of commitments,
    diverging the qualified set)."""
    n, t = 3, 2
    seeds = _seeds(n)
    pks = [ed.generate_keypair(s)[1] for s in seeds]
    dealer = dkg.DkgSession(0, n, t, seeds[0], pks)
    late = dkg.DkgSession(1, n, t, seeds[1], pks)
    share = dealer.share_blob_for(1)
    # share first: no verdict, no complaint
    assert not late.on_share(0, share)
    assert 0 not in late._my_complaints
    # commitments land -> stashed share is adopted
    assert late.on_commitments(0, dealer.commitment_blob())
    assert late.shares[0] is not None and 0 not in late._my_complaints
    # reveal-before-commitments on a third session
    judge = dkg.DkgSession(2, n, t, seeds[2], pks)
    judge.on_complaint(1, 0)  # participant 1 complains about dealer 0
    reveal = dealer.reveal_blob(1)
    judge.on_reveal(0, 1, reveal)   # can't be judged yet
    assert (0, 1) in judge._open_complaints
    assert 0 not in judge.disqualified
    judge.on_commitments(0, dealer.commitment_blob())  # replays the reveal
    assert (0, 1) not in judge._open_complaints
    assert 0 not in judge.disqualified
