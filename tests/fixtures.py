"""The Figure-1 golden DAG fixture.

Faithful port of the reference's ``createDag`` fixture
(``process/process_internal_test.go:86-283``), which reproduces Figure 1,
page 4 of the DAG-Rider paper: 4 processes, rounds 0-4, explicit strong
edges, one weak edge (4, p1) -> (2, p4).

Sources here are 0-based (reference is 1-based): source i here = reference
source i+1. Rounds are identical.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

N = 4
ROUNDS = 5  # rounds 0..4

# strong edges per (round, source) -> set of round-(r-1) sources.
# Reference lines: r1 edges :103-158, r2 :161-216, r3 :219-256, r4 :259-272.
STRONG: Dict[Tuple[int, int], Tuple[int, ...]] = {
    (1, 0): (0, 1, 2),
    (1, 1): (0, 1, 2),
    (1, 2): (0, 1, 2),
    (1, 3): (0, 1, 2),
    (2, 0): (0, 1, 3),
    (2, 1): (0, 1, 3),
    (2, 2): (0, 2, 3),
    (2, 3): (0, 1, 3),
    (3, 0): (0, 2),
    (3, 1): (0, 1, 2),
    (3, 2): (0, 1, 2),
    # (3, 3): no edges — vertex exists but is disconnected in the fixture
    (4, 0): (0, 1, 2),
}

# weak edges: (round, source) -> list of (round, source) targets.
# Reference: process_internal_test.go:275-280, (4,1)->(2,4) 1-based.
WEAK: Dict[Tuple[int, int], Tuple[Tuple[int, int], ...]] = {
    (4, 0): ((2, 3),),
}


def figure1_tensors() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense encoding of the fixture.

    Returns:
        exists: bool[ROUNDS, N] — all vertices present (the reference fixture
            materializes every (r, p) vertex, including edge-less ones).
        strong: bool[ROUNDS, N, N] — strong[r, i, j]: (r,i) -> (r-1,j).
        weak:   bool[ROUNDS, N, ROUNDS, N] — dense weak-edge form.
    """
    exists = np.ones((ROUNDS, N), dtype=bool)
    strong = np.zeros((ROUNDS, N, N), dtype=bool)
    for (r, i), targets in STRONG.items():
        for j in targets:
            strong[r, i, j] = True
    weak = np.zeros((ROUNDS, N, ROUNDS, N), dtype=bool)
    for (r, i), targets in WEAK.items():
        for r2, j in targets:
            weak[r, i, r2, j] = True
    return exists, strong, weak


def figure1_vertices() -> List:
    """The fixture as a list of Vertex objects (for host-state tests)."""
    from dag_rider_tpu.core.types import Block, Vertex, VertexID

    out = []
    for r in range(ROUNDS):
        for i in range(N):
            strong_edges = tuple(
                VertexID(r - 1, j) for j in STRONG.get((r, i), ())
            )
            weak_edges = tuple(
                VertexID(r2, j) for (r2, j) in WEAK.get((r, i), ())
            )
            out.append(
                Vertex(
                    id=VertexID(r, i),
                    block=Block((f"tx-{r}-{i}".encode(),)),
                    strong_edges=strong_edges,
                    weak_edges=weak_edges,
                )
            )
    return out
