"""Full-stack end-to-end: every subsystem composed in one simulation.

Round-2 VERDICT weak #6: RBC, Ed25519 signing + the sharded device
verifier, the threshold-BLS coin, and fault injection were each tested,
but never all together — yet the north-star claim is exactly this
composition. This runs the whole stack on the virtual 8-device CPU mesh
(tests/conftest.py): Bracha RBC over a faulty transport (delays + one
*signing* equivocator), ShardedTPUVerifier checking every admitted
vertex, and the real (f+1)-of-n threshold coin electing leaders — and
asserts agreement, liveness, and that the equivocation was actually
exercised and contained.
"""

import dataclasses

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.coin import ThresholdCoin
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.crypto import threshold as th
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier


@pytest.fixture(scope="module")
def coin_keys():
    return th.ThresholdKeys.generate(4, 2)  # (f+1)=2-of-4


def test_full_stack_agreement_liveness_equivocation(coin_keys):
    n = 4
    cfg = Config(n=n, coin="threshold_bls", propose_empty=False)
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    shared_verifier = ShardedTPUVerifier(reg)

    transport = FaultyTransport(
        FaultPlan(delay=0.10, equivocators=(2,), seed=11)
    )

    # A *signing* equivocator: the Byzantine source re-signs its
    # conflicting copy with its real key, so signature checks pass and
    # only Bracha consistency can contain the divergence.
    def resigning_mutator(v):
        stripped = dataclasses.replace(
            v, block=Block((b"equivocation",)), signature=None
        )
        return signers[v.source].sign_vertex(stripped)

    transport.set_equivocation_mutator(resigning_mutator)

    sim = Simulation(
        cfg,
        transport=transport,
        coin_factory=lambda i: ThresholdCoin(coin_keys, i, n),
        verifier_factory=lambda i: shared_verifier,
        signer_factory=lambda i: signers[i],
        rbc=True,
    )
    # 14 blocks/process: wave boundaries at rounds 4, 8, 12 — enough for
    # a multi-wave leader chain even with delays in the way.
    sim.submit_blocks(per_process=14)
    for _ in range(40):
        sim.run(max_messages=30_000)
        if transport.flush_delayed() == 0 and transport.pending == 0:
            break

    # --- liveness: waves decided, vertices delivered everywhere
    decided = [p.metrics.counters["waves_decided"] for p in sim.processes]
    assert any(d >= 1 for d in decided), decided
    delivered = [len(d) for d in sim.deliveries]
    assert all(d >= 1 for d in delivered), delivered

    # --- agreement: identical delivered digests across all processes
    sim.check_agreement()

    # --- the fault plan actually engaged
    assert transport.stats["equivocated"] > 0
    assert transport.stats["delayed"] > 0

    # --- the coin really ran: every decided wave used the same group
    # signature at every process that evaluated it
    sigmas = {}
    for p in sim.processes:
        for wave, sigma in p.coin._sigma.items():
            sigmas.setdefault(wave, set()).add(sigma)
    assert sigmas, "no threshold coin was ever evaluated"
    assert all(len(v) == 1 for v in sigmas.values()), sigmas

    # --- the device verifier was in the loop for every admission
    total_verified = sum(
        p.metrics.verify_sigs_total for p in sim.processes
    )
    assert total_verified > 0
    # every admitted remote vertex passed through a verify batch
    admitted = sum(
        p.metrics.counters["vertices_admitted"] for p in sim.processes
    )
    assert total_verified >= admitted

    # --- equivocation containment: at most one digest per slot delivered
    # (Bracha consistency), even though the equivocator's copies were
    # validly signed. RBC amplification means honest processes may see
    # only one of the two copies; divergence would have tripped
    # check_agreement above. Belt-and-braces: recompute per-slot digests.
    slot_digests = {}
    for d in sim.deliveries:
        for v in d:
            slot_digests.setdefault((v.round, v.source), set()).add(v.digest())
    assert all(len(s) == 1 for s in slot_digests.values())


def test_full_stack_byzantine_coin_share_plus_faults(coin_keys):
    """Same composition, plus the equivocator also poisons its coin
    shares — the batched RLC recovery must keep the coin live."""
    n = 4
    cfg = Config(n=n, coin="threshold_bls", propose_empty=False)
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    shared_verifier = ShardedTPUVerifier(reg)
    transport = FaultyTransport(FaultPlan(delay=0.05, seed=7))

    class BadShareCoin(ThresholdCoin):
        def my_share(self, wave):
            return th.sign_share(self.keys.share_sks[self.index], wave + 77)

    coins = {}

    def coin_factory(i):
        cls = BadShareCoin if i == 1 else ThresholdCoin
        coins[i] = cls(coin_keys, i, n)
        return coins[i]

    sim = Simulation(
        cfg,
        transport=transport,
        coin_factory=coin_factory,
        verifier_factory=lambda i: shared_verifier,
        signer_factory=lambda i: signers[i],
        rbc=True,
    )
    sim.submit_blocks(per_process=14)
    for _ in range(40):
        sim.run(max_messages=30_000)
        if transport.flush_delayed() == 0 and transport.pending == 0:
            break

    sim.check_agreement()
    assert any(
        p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
    )
    # an honest process filtered the poisoned share out of its pool
    filtered = any(
        1 not in coin._shares.get(w, {1: None})
        for i, coin in coins.items()
        if i != 1
        for w in coin._sigma
    )
    assert filtered


def test_signature_flood_does_not_stall_consensus():
    """A Byzantine source floods garbage-signed vertices every round; the
    batched verifier must reject them all (counted) while the honest
    quorum keeps committing waves."""
    import dataclasses as _dc

    n = 4
    cfg = Config(n=n, coin="round_robin", propose_empty=False)
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    shared = ShardedTPUVerifier(reg)

    class FloodingSigner:
        """Source 2 signs nothing validly — every vertex carries junk."""

        def sign_vertex(self, v):
            return _dc.replace(v, signature=b"\x5a" * 64)

    sim = Simulation(
        cfg,
        verifier_factory=lambda i: shared,
        signer_factory=lambda i: FloodingSigner() if i == 2 else signers[i],
    )
    sim.submit_blocks(per_process=12)
    sim.run(max_messages=50_000)
    sim.check_agreement()
    # honest nodes rejected every flooded vertex...
    rejected = [
        p.metrics.counters.get("msgs_rejected_signature", 0)
        for p in sim.processes
        if p.index != 2
    ]
    assert all(r > 0 for r in rejected), rejected
    # ...and no vertex authored by the flooder was ever delivered
    for d in sim.deliveries:
        assert all(v.source != 2 for v in d)
    # liveness held without the flooder (n=4 tolerates f=1)
    assert any(
        p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
    )


def test_seven_nodes_two_equivocators_with_rbc(coin_keys):
    """n=7, f=2: two re-signing equivocators under RBC — Bracha
    consistency must contain both; agreement and liveness hold."""
    n = 7
    cfg = Config(n=n, coin="round_robin", propose_empty=False)
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    transport = FaultyTransport(FaultPlan(equivocators=(1, 5), seed=3))

    def resign(v):
        stripped = dataclasses.replace(
            v, block=Block((b"evil-" + bytes([v.source]),)), signature=None
        )
        return signers[v.source].sign_vertex(stripped)

    transport.set_equivocation_mutator(resign)
    shared = ShardedTPUVerifier(reg)
    sim = Simulation(
        cfg,
        transport=transport,
        verifier_factory=lambda i: shared,
        signer_factory=lambda i: signers[i],
        rbc=True,
    )
    sim.submit_blocks(per_process=10)
    sim.run(max_messages=200_000)
    sim.check_agreement()
    assert transport.stats["equivocated"] > 0
    assert any(
        p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
    )
    # per-slot digest uniqueness across ALL deliveries
    slot_digests = {}
    for d in sim.deliveries:
        for v in d:
            slot_digests.setdefault((v.round, v.source), set()).add(v.digest())
    assert all(len(s) == 1 for s in slot_digests.values())


def test_unsigned_equivocator_own_log_outside_agreement():
    """Without signatures, an equivocating sender's OWN log keeps its
    original vertex while honest nodes RBC-agree on one (possibly
    mutated) version — the BFT agreement property covers honest
    processes only. check_agreement(exclude=) encodes that: the full
    check must flag the Byzantine node's divergence, the honest-subset
    check must pass. (Deterministic repro from the round-5 randomized
    RBC sweep, seed 533502199; no delay in the plan, so a single pump
    drive suffices.)"""
    plan = FaultPlan(seed=533502199, equivocators=(3,))
    faulty = FaultyTransport(plan)
    cfg = Config(n=4, propose_empty=True, gc_depth=16)
    sim = Simulation(cfg, transport=faulty, rbc=True)
    sim.submit_blocks(3)
    sim.run(max_messages=60_000)
    assert faulty.stats["equivocated"] > 0
    sim.check_agreement(exclude=(3,))  # honest subset agrees
    with pytest.raises(AssertionError, match="divergence"):
        sim.check_agreement()  # the equivocator's own log diverges
