import pytest

from dag_rider_tpu import Config


def test_defaults():
    cfg = Config(n=4)
    assert cfg.f == 1
    assert cfg.quorum == 3
    assert cfg.wave_length == 4


def test_f_derivation():
    assert Config(n=1).f == 0
    assert Config(n=4).f == 1
    assert Config(n=7).f == 2
    assert Config(n=256).f == 85


def test_resilience_bound_enforced():
    with pytest.raises(ValueError):
        Config(n=4, f=2)  # 3f+1 = 7 > 4


def test_wave_round_arithmetic():
    # round(w, k) = 4(w-1)+k, mirroring reference process/process.go:394-402.
    cfg = Config(n=4)
    assert cfg.wave_round(1, 1) == 1
    assert cfg.wave_round(1, 4) == 4
    assert cfg.wave_round(2, 1) == 5
    assert cfg.wave_round(3, 4) == 12
    assert cfg.wave_of_round(1) == 1
    assert cfg.wave_of_round(4) == 1
    assert cfg.wave_of_round(5) == 2
    assert cfg.wave_of_round(12) == 3
    with pytest.raises(ValueError):
        cfg.wave_round(1, 5)
    with pytest.raises(ValueError):
        cfg.wave_of_round(0)


def test_invalid_knobs():
    with pytest.raises(ValueError):
        Config(n=0)
    with pytest.raises(ValueError):
        Config(n=4, signature_scheme="rsa")
    with pytest.raises(ValueError):
        Config(n=4, verifier_backend="gpu")
    with pytest.raises(ValueError):
        Config(n=4, coin="lava_lamp")
