"""Process state-machine tests — coverage the reference entirely lacks
(SURVEY.md §4: "no test of Start, waveReady, orderVertices,
createNewVertex, or Transport itself")."""


from dag_rider_tpu import Config
from dag_rider_tpu.consensus import FixedCoin, Process, Simulation
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport import InMemoryTransport


def mk_cfg(**kw):
    kw.setdefault("n", 4)
    kw.setdefault("coin", "round_robin")
    return Config(**kw)


def test_genesis_seeding_fixes_d2():
    # One genesis vertex per distinct source (reference gives every genesis
    # vertex the caller's own id, process.go:43-49).
    p = Process(mk_cfg(), 2, InMemoryTransport())
    for i in range(4):
        assert p.dag.present(VertexID(0, i))
    assert p.dag.round_size(0) == 4


def test_start_advances_and_broadcasts():
    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp)
    p.submit(Block((b"tx",)))
    p.start()
    # genesis quorum -> round 1 vertex proposed with strong edges to genesis
    assert p.round == 1
    v = p.dag.get(VertexID(1, 0))
    assert v is not None and len(v.strong_edges) == 4
    assert v.block.transactions == (b"tx",)
    # broadcast queued to the other 3 processes once they subscribe...
    # (broker fans out to current subscribers; p is alone, so 0 queued)
    assert tp.pending == 0


def test_rejects_bad_stamps_and_thin_vertices():
    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp)
    p.start()
    good_edges = tuple(VertexID(0, i) for i in range(3))
    v = Vertex(id=VertexID(1, 1), strong_edges=good_edges)
    # stamp mismatch: sender != source
    p.on_message(BroadcastMessage(vertex=v, round=1, sender=2))
    assert p.metrics.counters["msgs_rejected_stamp"] == 1
    # too few strong edges (< 2f+1)
    thin = Vertex(id=VertexID(1, 1), strong_edges=good_edges[:2])
    p.on_message(BroadcastMessage(vertex=thin, round=1, sender=1))
    assert p.metrics.counters["msgs_rejected_edges"] == 1
    # duplicate strong edges must not fake a quorum
    padded = Vertex(
        id=VertexID(1, 1),
        strong_edges=(good_edges[0], good_edges[0], good_edges[1]),
    )
    p.on_message(BroadcastMessage(vertex=padded, round=1, sender=1))
    assert p.metrics.counters["msgs_rejected_edges"] == 2
    # well-formed vertex admitted
    p.on_message(BroadcastMessage(vertex=v, round=1, sender=1))
    assert p.dag.present(VertexID(1, 1))


def test_equivocation_detected():
    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp)
    p.start()
    edges = tuple(VertexID(0, i) for i in range(3))
    v1 = Vertex(id=VertexID(1, 1), block=Block((b"a",)), strong_edges=edges)
    v2 = Vertex(id=VertexID(1, 1), block=Block((b"b",)), strong_edges=edges)
    p.on_message(BroadcastMessage(vertex=v1, round=1, sender=1))
    p.on_message(BroadcastMessage(vertex=v2, round=1, sender=1))
    assert p.metrics.counters["equivocations_detected"] == 1
    # first one wins
    assert p.dag.get(VertexID(1, 1)).block.transactions == (b"a",)


def test_future_round_vertex_stays_buffered():
    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp)
    p.start()  # p.round == 1
    far = Vertex(
        id=VertexID(3, 1),
        strong_edges=tuple(VertexID(2, i) for i in range(3)),
    )
    p.on_message(BroadcastMessage(vertex=far, round=3, sender=1))
    assert not p.dag.present(far.id)
    # parked, not dropped (pump-agnostic probe: the property flattens
    # the vector round groups and the scalar list alike)
    assert far.id in {v.id for v in p.buffer}


def test_wave_commit_and_total_order_four_nodes():
    """The minimum end-to-end slice (BASELINE.json config #1): 4 nodes,
    f=1, blocks in -> identical total order out, waves actually decide."""
    sim = Simulation(mk_cfg())
    sim.submit_blocks(per_process=4)
    sim.run(max_messages=3000)
    sim.check_agreement()
    waves = [p.metrics.counters["waves_decided"] for p in sim.processes]
    assert all(w >= 1 for w in waves), waves
    # every process delivered a non-trivial log
    assert all(len(d) > 8 for d in sim.deliveries)
    # delivery dedup (D8): no vertex delivered twice
    for i in range(4):
        ids = sim.delivered_ids(i)
        assert len(ids) == len(set(ids))
    # a_deliver carries real payloads (D6): submitted blocks show up
    seen = {
        tx
        for v in sim.deliveries[0]
        for tx in v.block.transactions
        if tx.startswith(b"p")
    }
    assert any(tx.startswith(b"p0-blk0") for tx in seen)


def test_fixed_coin_matches_reference_stub_semantics():
    # FixedCoin(1) = the reference's `return 1` (process.go:390-392).
    sim = Simulation(
        mk_cfg(coin="fixed"),
        coin_factory=lambda i: FixedCoin(1),
    )
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=3000)
    sim.check_agreement()
    assert all(
        p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
    )


def test_out_of_range_edge_sources_rejected():
    """Byzantine edge sources must not crash or alias (numpy negative
    wraparound) — regression for the range-check gap."""
    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp)
    p.start()
    bad_hi = Vertex(
        id=VertexID(1, 1),
        strong_edges=(VertexID(0, 0), VertexID(0, 1), VertexID(0, 7)),
    )
    p.on_message(BroadcastMessage(vertex=bad_hi, round=1, sender=1))
    bad_neg = Vertex(
        id=VertexID(1, 1),
        strong_edges=(VertexID(0, 0), VertexID(0, 1), VertexID(0, -1)),
    )
    p.on_message(BroadcastMessage(vertex=bad_neg, round=1, sender=1))
    bad_weak = Vertex(
        id=VertexID(4, 1),
        strong_edges=tuple(VertexID(3, i) for i in range(3)),
        weak_edges=(VertexID(0, 2),),  # weak must target rounds [1, r-2]
    )
    p.on_message(BroadcastMessage(vertex=bad_weak, round=4, sender=1))
    assert p.metrics.counters["msgs_rejected_edges"] == 3
    assert not p.dag.present(VertexID(1, 1))


def test_duplicate_while_pending_verify_not_double_admitted():
    """A duplicate arriving while the first copy awaits batch verification
    must be deduped, not admitted twice (regression)."""

    class YesVerifier:
        def verify_batch(self, batch):
            return [True] * len(batch)

    tp = InMemoryTransport()
    p = Process(mk_cfg(), 0, tp, verifier=YesVerifier())
    edges = tuple(VertexID(0, i) for i in range(3))
    v = Vertex(id=VertexID(1, 1), strong_edges=edges)
    # not started: messages queue in _pending_verify without step()
    p.on_message(BroadcastMessage(vertex=v, round=1, sender=1))
    p.on_message(BroadcastMessage(vertex=v, round=1, sender=1))
    assert p.metrics.counters["msgs_duplicate"] == 1
    p.start()  # drains verify + admits exactly once
    assert p.dag.present(v.id)
    assert p.metrics.counters["vertices_admitted"] == 1


def test_wave_commits_with_idle_client_and_no_propose_empty():
    """propose_empty=False must stall *proposals*, never wave commits: a
    completed wave is delivered even while every client is idle."""
    sim = Simulation(mk_cfg(propose_empty=False))
    # exactly enough blocks to finish wave 1 + its commit trigger round
    for p in sim.processes:
        for k in range(5):
            p.submit(Block((f"p{p.index}-b{k}".encode(),)))
    sim.run(max_messages=4000)
    sim.check_agreement()
    # all blocks consumed; processes stalled awaiting new blocks...
    assert all(not p.blocks_to_propose for p in sim.processes)
    # ...but wave 1 still decided and delivered
    assert all(p.decided_wave >= 1 for p in sim.processes)
    assert all(len(d) > 0 for d in sim.deliveries)


def test_submit_resumes_quiescent_cluster():
    """D7 regression: submit() alone must restart a propose_empty=False
    cluster — no manual step() or in-flight messages required."""
    sim = Simulation(mk_cfg(propose_empty=False))
    for p in sim.processes:
        p.submit(Block((b"x",)))
    sim.run(max_messages=4000)
    assert sim.transport.pending == 0  # quiescent
    rounds_before = [p.round for p in sim.processes]
    for p in sim.processes:
        p.submit(Block((b"y",)))
    sim.transport.pump(4000)
    assert [p.round for p in sim.processes] > rounds_before


def test_propose_empty_false_stalls_without_blocks():
    cfg = mk_cfg(propose_empty=False)
    tp = InMemoryTransport()
    p = Process(cfg, 0, tp)
    p.start()
    assert p.round == 0  # no block -> no proposal (paper's wait-until)
    p.submit(Block((b"tx",)))
    p.step()
    assert p.round == 1


# ----------------------------------------------------------------------
# Weak-edge construction (round-2 VERDICT weak #5: single-sweep rewrite)
# ----------------------------------------------------------------------


def _brute_weak_edges(p, rnd, strong):
    """The paper-literal oracle: recompute a full closure per candidate
    (the pre-round-3 implementation) — O(missing * R * n^2)."""
    if rnd < 3:
        return ()
    reached = p.dag.closure(list(strong), strong_only=False)
    weak = []
    for r in range(rnd - 2, 0, -1):
        for u in p.dag.vertices_in_round(r):
            if not reached[r, u.source]:
                weak.append(u.id)
                reached |= p.dag.closure([u.id], strong_only=False)
    return tuple(weak)


def _build_straggler_dag(n=7, rounds=8, seed=3, weak_prob=0.3):
    """A DAG where each vertex strong-links a random quorum of the prior
    round (so ~(n-quorum)/n of each round are stragglers) and occasionally
    carries weak edges of its own (exercising sparse-map propagation)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cfg = Config(n=n)
    p = Process(cfg, 0, InMemoryTransport())
    for r in range(1, rounds):
        for s in range(n):
            targets = rng.permutation(n)[: cfg.quorum]
            weak = ()
            if r >= 3 and rng.random() < weak_prob:
                wr = int(rng.integers(1, r - 1))
                weak = (VertexID(wr, int(rng.integers(0, n))),)
            p.dag.insert(
                Vertex(
                    id=VertexID(r, s),
                    strong_edges=tuple(
                        VertexID(r - 1, int(t)) for t in targets
                    ),
                    weak_edges=weak,
                )
            )
    return p


def test_weak_edges_single_sweep_matches_oracle():
    for seed in range(6):
        p = _build_straggler_dag(seed=seed)
        rnd = 8
        strong = tuple(
            VertexID(rnd - 1, u.source)
            for u in p.dag.vertices_in_round(rnd - 1)
        )
        got = p._weak_edges_for(rnd, strong)
        want = _brute_weak_edges(p, rnd, strong)
        assert got == want, f"seed={seed}: {got} != {want}"


def test_weak_edges_truncated_sweep_matches_oracle():
    """The production shape the round-4 truncation optimizes: consecutive
    proposals with stragglers arriving rounds late. The marker-truncated
    sweep (dag.insert_min_round) must match the from-scratch oracle at
    EVERY proposal — not just the cold first one."""
    import numpy as np

    rng = np.random.default_rng(9)
    cfg = Config(n=7)  # quorum = 5
    p = Process(cfg, 0, InMemoryTransport())
    late = {}  # release_round -> [vertex]
    weak_total = 0
    for rnd in range(1, 14):
        for v in late.pop(rnd, []):
            p.dag.insert(v)
        # Propose at rnd exactly as _create_vertex does: strong-link the
        # whole present frontier, compute weak edges via the (truncated)
        # sweep, check against the oracle, then insert with those edges.
        prev = [u.id for u in p.dag.vertices_in_round(rnd - 1)]
        strong = tuple(prev)
        got = p._weak_edges_for(rnd, strong)
        want = _brute_weak_edges(p, rnd, strong)
        assert got == want, f"rnd={rnd}: {got} != {want}"
        weak_total += len(got)
        p.dag.insert(
            Vertex(id=VertexID(rnd, 0), strong_edges=strong, weak_edges=got)
        )
        # Peers for round rnd: 4 on time (5 with ours = quorum), 2 late.
        for s in range(1, cfg.n):
            targets = rng.permutation(len(prev))[: cfg.quorum]
            v = Vertex(
                id=VertexID(rnd, s),
                strong_edges=tuple(prev[t] for t in targets),
            )
            if s <= 4:
                p.dag.insert(v)
            else:
                late.setdefault(rnd + int(rng.integers(2, 4)), []).append(v)
    assert weak_total > 0  # the scenario actually produced stragglers


def test_weak_edges_partial_frontier_matches_oracle():
    """With a sub-quorum strong frontier the sweep must not treat
    unlinked round-(rnd-1) vertices as covered."""
    p = _build_straggler_dag(seed=11)
    rnd = 8
    frontier = p.dag.vertices_in_round(rnd - 1)[:5]
    strong = tuple(VertexID(rnd - 1, u.source) for u in frontier)
    assert p._weak_edges_for(rnd, strong) == _brute_weak_edges(
        p, rnd, strong
    )


def test_retro_chain_defers_on_unready_in_window_link():
    """An IN-WINDOW chain link whose coin shares are still in flight must
    defer the whole commit (skipping would diverge the total order;
    raising crashed the process pre-round-4) and complete once ready."""
    from dag_rider_tpu.consensus.coin import CommonCoin

    class FlakyCoin(CommonCoin):
        def __init__(self, n, slow):
            self.n, self.slow, self.released = n, slow, False

        def ready(self, wave):
            return self.released or wave != self.slow

        def choose_leader(self, wave):
            if not self.ready(wave):
                raise RuntimeError(f"coin for wave {wave} not ready")
            return wave % self.n

    cfg = Config(n=4, coin="round_robin", propose_empty=True)
    coin = FlakyCoin(4, slow=1)
    p = Process(cfg, 0, InMemoryTransport(), coin=coin)
    for r in range(1, 9):
        prev = tuple(VertexID(r - 1, s) for s in range(4))
        for s in range(4):
            p.dag.insert(Vertex(id=VertexID(r, s), strong_edges=prev))
    p.round = 8
    p._try_wave(2)  # chain must walk to wave 1, whose coin is not ready
    assert p.decided_wave == 0 and 2 in p._pending_waves
    coin.released = True
    p._retry_pending_waves()
    assert p.decided_wave == 2
    assert len(p.delivered_log) > 0
