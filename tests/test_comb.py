"""Comb verify path vs windowed path vs CPU oracle — bit-identical masks.

The comb path (ops/comb.py, TPUVerifier default) replaces the per-vertex
variable-base scalar multiplication with fixed-key table sums; its accept
mask must match both the original windowed device program and the host
RFC 8032 oracle on every batch, including adversarial ones — the
north-star CPU-vs-TPU commit-order equivalence reduces to this.
"""

import dataclasses

import pytest

from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.crypto import ed25519
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def setup():
    reg, seeds = KeyRegistry.generate(6)
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(6):
        v = Vertex(
            id=VertexID(2, i),
            block=Block((f"tx-{i}".encode(),)),
            strong_edges=(VertexID(1, 0), VertexID(1, 1), VertexID(1, 2)),
        )
        vs.append(signers[i].sign_vertex(v))
    return reg, vs


def _adversarial(vs):
    s_big = int.to_bytes(
        int.from_bytes(vs[2].signature[32:], "little") + ed25519.L,
        32,
        "little",
    )
    y_bad = int.to_bytes(2**255 - 10, 32, "little")
    flip = bytearray(vs[4].signature)
    flip[17] ^= 0x40
    return [
        dataclasses.replace(vs[0], signature=b"\x00" * 64),
        dataclasses.replace(vs[1], block=Block((b"tampered",))),
        dataclasses.replace(vs[2], signature=vs[2].signature[:32] + s_big),
        dataclasses.replace(vs[3], signature=y_bad + vs[3].signature[32:]),
        dataclasses.replace(vs[4], signature=bytes(flip)),
        dataclasses.replace(vs[5], id=VertexID(2, 999)),
    ]


def test_comb_mask_matches_windowed_and_cpu(setup, monkeypatch):
    reg, vs = setup
    batch = vs + _adversarial(vs)
    cpu = CPUVerifier(reg).verify_batch(batch)
    windowed = TPUVerifier(reg, comb=False).verify_batch(batch)
    monkeypatch.setenv("DAGRIDER_COMB_BITS", "4")
    comb4 = TPUVerifier(reg, comb=True).verify_batch(batch)
    monkeypatch.setenv("DAGRIDER_COMB_BITS", "8")
    comb8 = TPUVerifier(reg, comb=True).verify_batch(batch)
    assert cpu == windowed == comb4 == comb8
    assert cpu[: len(vs)] == [True] * len(vs)
    assert not any(cpu[len(vs) :])


def test_comb_fuzz_masks_match_cpu_oracle(setup):
    """Seeded corruption fuzz: random byte/bit damage across signature,
    R, key index, block and edges must always produce the oracle's mask
    through the comb path (the north-star equivalence is only as strong
    as its behavior on garbage)."""
    import random

    reg, vs = setup
    rng = random.Random(1234)
    tv = TPUVerifier(reg, comb=True)
    cpu = CPUVerifier(reg)
    batch = []
    for _ in range(24):
        v = rng.choice(vs)
        mode = rng.randrange(5)
        if mode == 0:  # signature damage
            sig = bytearray(v.signature)
            for _ in range(rng.randrange(1, 4)):
                sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            v = dataclasses.replace(v, signature=bytes(sig))
        elif mode == 1:  # payload damage (signed bytes change)
            v = dataclasses.replace(
                v, block=Block((rng.randbytes(rng.randrange(1, 40)),))
            )
        elif mode == 2:  # source redirect (wrong key)
            v = dataclasses.replace(
                v, id=VertexID(v.id.round, rng.randrange(reg.n))
            )
        elif mode == 3:  # edge tamper
            v = dataclasses.replace(
                v,
                strong_edges=tuple(
                    VertexID(e.round, (e.source + 1) % reg.n)
                    for e in v.strong_edges
                ),
            )
        # mode 4: leave valid
        batch.append(v)
    assert tv.verify_batch(batch) == cpu.verify_batch(batch)


def test_invalid_comb_bits_env_rejected(setup, monkeypatch):
    reg, _ = setup
    monkeypatch.setenv("DAGRIDER_COMB_BITS", "16")
    with pytest.raises(ValueError, match="DAGRIDER_COMB_BITS"):
        TPUVerifier(reg, comb=True)


def test_verify_rounds_merged_matches_per_round(setup):
    reg, vs = setup
    v = TPUVerifier(reg, comb=True)
    rounds = [vs[:2], [], vs[2:5], _adversarial(vs)[:3]]
    merged = v.verify_rounds(rounds)
    per_round = [v.verify_batch(r) for r in rounds]
    assert merged == per_round
    assert merged[1] == []


def _affine(p4x22):
    from dag_rider_tpu.ops import field as F

    X = F.from_limbs(p4x22[0]) % F.P_INT
    Y = F.from_limbs(p4x22[1]) % F.P_INT
    Z = F.from_limbs(p4x22[2]) % F.P_INT
    zi = pow(Z, F.P_INT - 2, F.P_INT)
    return X * zi % F.P_INT, Y * zi % F.P_INT


def _host_affine(pt):
    from dag_rider_tpu.ops import field as F

    X, Y, Z, _ = pt
    zi = pow(Z, F.P_INT - 2, F.P_INT)
    return X * zi % F.P_INT, Y * zi % F.P_INT


def test_comb_key_table_entries_match_host(setup, monkeypatch):
    """Spot-check device-built comb tables: TABLE[key, w, d] == d*base^w*A
    for both the 4-bit and 8-bit window builders."""
    import numpy as np

    from dag_rider_tpu.crypto import ed25519 as host
    from dag_rider_tpu.ops import field as F

    reg, _ = setup
    monkeypatch.setenv("DAGRIDER_COMB_BITS", "4")
    tv = TPUVerifier(reg, comb=True)
    tables, _ = tv._comb_tables()  # padded [rows, 128] gather layout
    tab = np.asarray(tables)[:, : 4 * F.LIMBS].reshape(
        reg.n, 64, 16, 4, F.LIMBS
    )
    for key, w, d in [(0, 0, 1), (1, 0, 7), (2, 3, 15), (5, 63, 9)]:
        a_pt = host.point_decompress(reg.public_keys[key])
        want = _host_affine(host.scalar_mult(d * (16**w), a_pt))
        assert _affine(tab[key, w, d]) == want, (key, w, d)

    monkeypatch.setenv("DAGRIDER_COMB_BITS", "8")
    tv8 = TPUVerifier(reg, comb=True)
    tables8, _ = tv8._comb_tables()
    tab8 = np.asarray(tables8)[:, : 4 * F.LIMBS].reshape(
        reg.n, 32, 256, 4, F.LIMBS
    )
    from dag_rider_tpu.ops.comb import DIGIT_POS8

    for key, w, d in [(0, 0, 1), (1, 0, 255), (3, 2, 17), (5, 31, 128)]:
        a_pt = host.point_decompress(reg.public_keys[key])
        want = _host_affine(host.scalar_mult(d * (256**w), a_pt))
        assert _affine(tab8[key, w, DIGIT_POS8[d]]) == want, (key, w, d)
