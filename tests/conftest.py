"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so multi-chip sharding (Mesh/pjit/shard_map) is exercised in every
test run without TPU hardware. The driver separately dry-runs the multi-chip
path via ``__graft_entry__.dryrun_multichip``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
