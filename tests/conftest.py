"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so multi-chip sharding (Mesh/pjit/shard_map) is exercised in every
test run without TPU hardware. The driver separately dry-runs the multi-chip
path via ``__graft_entry__.dryrun_multichip``.
"""

import os

# Force, don't setdefault: the axon TPU harness presets JAX_PLATFORMS=axon
# and its sitecustomize both registers a PJRT plugin at interpreter start
# (before this conftest) and calls jax.config.update("jax_platforms",
# "axon,cpu"), which overrides the env var. Undo both so the suite runs on
# the virtual 8-device CPU mesh regardless of launch environment.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (sitecustomize may have imported it already)

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the limb-field/curve programs cost ~20s+
# each to compile on CPU; caching them under the repo makes repeated suite
# runs (and the driver's) skip the XLA compile entirely.
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from dag_rider_tpu.utils.jaxcache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
