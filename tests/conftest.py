"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so multi-chip sharding (Mesh/pjit/shard_map) is exercised in every
test run without TPU hardware. The driver separately dry-runs the multi-chip
path via ``__graft_entry__.dryrun_multichip``.
"""

import os

# Force, don't setdefault: the axon TPU harness presets JAX_PLATFORMS=axon
# and its sitecustomize both registers a PJRT plugin at interpreter start
# (before this conftest) and calls jax.config.update("jax_platforms",
# "axon,cpu"), which overrides the env var. Undo both so the suite runs on
# the virtual 8-device CPU mesh regardless of launch environment.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (sitecustomize may have imported it already)

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the limb-field/curve programs cost ~20s+
# each to compile on CPU; caching them under the repo makes repeated suite
# runs (and the driver's) skip the XLA compile entirely.
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from dag_rider_tpu.utils.jaxcache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

# Dynamic lock-race harness (round 14, analysis/races.py): under
# DAGRIDER_RACE=1 every package lock is order-tracked (deadlock cycles
# raise at the acquire attempt) and the declared guarded-field /
# serialized-method classes are enforced on every instance the suite
# builds — the chaos/fuzz tests become the race driver with zero
# per-test code. Installed at conftest import so it precedes any
# instance construction; violations raised in pool threads (which a
# Future would swallow) are re-checked session-wide below.
from dag_rider_tpu.config import env_flag as _env_flag  # noqa: E402

_RACE = _env_flag("DAGRIDER_RACE")
if _RACE:
    from dag_rider_tpu.analysis import races as _races  # noqa: E402

    _races.install()


def pytest_sessionfinish(session, exitstatus):
    if _RACE:
        leftover = _races.drain_violations()
        if leftover:
            raise _races.RaceViolation(
                "race harness recorded violation(s) the tests did not "
                "surface (worker-thread raises swallowed by Futures):\n"
                + "\n".join(leftover)
            )


# Long-tail tests (>= ~10 s each on this host, measured with
# --durations=50; together ~75% of suite wall time). Kept here as the
# single source of truth instead of scattering @pytest.mark.slow
# decorators — re-measure and update when the profile shifts.
_SLOW = {
    "test_pallas_group381.py::test_msm_kernel_pallas_impl_traces",
    "test_pallas_group381.py::test_padd381_pallas_program_traces",
    "test_bls_msm.py::test_scalar_mul_matches_host",
    "test_bls_msm.py::test_field_ring_ops_match_host",
    "test_bls_msm.py::test_msm_matches_host[1]",
    "test_bls_msm.py::test_msm_matches_host[5]",
    "test_net_transport.py::test_grpc_16_node_cluster_with_rbc_reaches_consensus",
    "test_full_stack.py::test_seven_nodes_two_equivocators_with_rbc",
    "test_full_stack.py::test_full_stack_byzantine_coin_share_plus_faults",
    "test_comb.py::test_comb_mask_matches_windowed_and_cpu",
    "test_parallel.py::test_sharded_comb_pallas_path_traces",
    "test_parallel.py::test_sharded_mask_equals_single_device_and_cpu",
    "test_parallel.py::test_sharded_msm_matches_host_oracle",
    "test_parallel.py::test_sharded_verifier_large_batch_matches_cpu_oracle",
    "test_parallel.py::test_round_step_matches_host_twins_on_figure1",
    # round-7 mesh-sharded async/AOT/pipeline seam (tier1-mesh CI lane
    # runs these with the slow marker included)
    "test_parallel.py::test_sharded_async_seam_dispatches_on_mesh",
    "test_parallel.py::test_sharded_sim_commit_order_matches_cpu",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[None-1]",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[None-2]",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[None-4]",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[16-1]",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[16-2]",
    "test_pipeline.py::test_sharded_pipeline_masks_byte_identical[16-4]",
    # round-8 parallel host-prep engine, mesh side (tier1-mesh and
    # tier1-prep CI lanes run these with the slow marker included)
    "test_prep.py::test_sharded_prep_masks_byte_identical[2]",
    "test_prep.py::test_sharded_prep_masks_byte_identical[4]",
    "test_pallas_group.py::test_finish_kernel_matches_jnp_tail",
    "test_pallas_group.py::test_pow22523_kernel_matches_field",
    "test_node.py::test_churn_restored_logs_stay_prefix_consistent",
    "test_node.py::test_node_restart_from_checkpoint_catches_up",
    "test_determinism.py::test_pipelined_coalesced_path_matches_sync_path",
    "test_determinism.py::test_device_verify_is_deterministic",
    "test_determinism.py::test_cpu_vs_device_verifier_commit_order_byte_identical",
    "test_determinism.py::test_dedup_coalesced_dispatch_is_delivery_identical",
    "test_determinism.py::test_dedup_does_not_conflate_corrupted_copies",
    "test_coin_e2e.py::test_byzantine_share_cannot_stall_the_coin",
    # round-20 multi-process cluster smoke: 4 OS processes over UDS w/
    # a real SIGKILL + rejoin (tier1-cluster CI lane runs it with the
    # slow marker included)
    "test_cluster.py::test_cluster_kill9_rejoin_zero_loss",
    # bench-rung mechanics: real consensus runs w/ device verifier
    "test_bench_rungs.py::test_sim_rung_reports_breakdown_and_progress",
    "test_bench_rungs.py::test_sim_rung_extends_past_box_until_target_met",
    "test_bench_rungs.py::test_sim_rung_pipeline_off_runs_and_restores_seam",
}


def pytest_collection_modifyitems(config, items):
    """Two-tier lanes (SURVEY §4): tests in _SLOW get @slow, everything
    else gets @fast — so `pytest -m fast` (inner loop, ~3 min) and
    `pytest -m slow` (long tail) partition the suite; a bare `pytest`
    still runs everything."""
    import pytest as _pytest

    for item in items:
        name = item.nodeid.split("/")[-1]
        if name in _SLOW or "slow" in item.keywords:
            item.add_marker(_pytest.mark.slow)
        else:
            item.add_marker(_pytest.mark.fast)
