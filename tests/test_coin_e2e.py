"""Threshold-BLS common coin, end to end through the consensus pipeline.

Round-1 review: ``ThresholdCoin`` was unit-tested but never ran inside a
``Process``/``Simulation`` — the share-piggyback path
(``process.py`` round(w,4) share attach + ``observe_share`` on admission)
and the pending-wave retry machinery had no e2e coverage. These tests run
the real coin (crypto/threshold.py over crypto/bls12381.py, the design the
reference's TODO names at ``process/process.go:388``) inside N-node
simulations, including a Byzantine share in flight.
"""

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.coin import ThresholdCoin
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.crypto import threshold as th


@pytest.fixture(scope="module")
def keys():
    # n=4, f=1 -> (f+1)=2-of-4 threshold, as the reference TODO specifies
    return th.ThresholdKeys.generate(4, 2)


def run_sim(keys, coin_factory, blocks=6):
    cfg = Config(n=4, coin="threshold_bls", propose_empty=False)
    sim = Simulation(cfg, coin_factory=coin_factory)
    sim.submit_blocks(per_process=blocks)
    sim.run(max_messages=20_000)
    return sim


def test_threshold_coin_decides_waves_and_agrees(keys):
    coins = {}

    def factory(i):
        coins[i] = ThresholdCoin(keys, i, 4)
        return coins[i]

    sim = run_sim(keys, factory)
    sim.check_agreement()
    decided = [p.metrics.counters["waves_decided"] for p in sim.processes]
    assert any(d >= 1 for d in decided), decided
    # Coin agreement: every process that evaluated wave w's coin got the
    # same group signature, hence the same leader.
    sigmas = {}
    for i, coin in coins.items():
        for wave, sigma in coin._sigma.items():
            sigmas.setdefault(wave, set()).add(sigma)
    assert sigmas, "no coin was ever evaluated"
    for wave, values in sigmas.items():
        assert len(values) == 1, f"wave {wave} coin diverged"
    # Unpredictability sanity: the leader must come from the group
    # signature, not a fixed index pattern.
    leaders = {w: th.leader_from_sigma(next(iter(v)), 4) for w, v in sigmas.items()}
    assert all(0 <= l < 4 for l in leaders.values())


class LaggyCoin:
    """Round-robin coin whose readiness lags: ``ready(w)`` is False for the
    first ``lag`` polls of each wave. Forces the wave boundary down the
    ``_pending_waves`` path so the retry machinery
    (``Process._retry_pending_waves``) is what actually commits."""

    def __init__(self, n: int, lag: int = 3):
        self.n = n
        self.lag = lag
        self.polls = {}

    def ready(self, wave: int) -> bool:
        c = self.polls.get(wave, 0) + 1
        self.polls[wave] = c
        return c > self.lag

    def choose_leader(self, wave: int) -> int:
        return wave % self.n

    def my_share(self, wave):
        return None

    def observe_share(self, wave, source, share):
        pass


def test_pending_wave_retry_commits_when_coin_becomes_ready():
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    coins = {}

    def factory(i):
        coins[i] = LaggyCoin(4)
        return coins[i]

    sim = Simulation(cfg, coin_factory=factory)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=20_000)
    sim.check_agreement()
    assert any(p.metrics.counters["waves_decided"] >= 1 for p in sim.processes)
    # the lag really engaged: every coin was polled more than once per wave
    assert all(any(c > 1 for c in coin.polls.values()) for coin in coins.values())


class ByzantineShareCoin(ThresholdCoin):
    """Signs the wrong message — a share that decompresses fine but fails
    the pairing check, poisoning the first aggregation attempt."""

    def my_share(self, wave: int):
        return th.sign_share(self.keys.share_sks[self.index], wave + 991)


def test_byzantine_share_cannot_stall_the_coin(keys):
    """Process 0 contributes corrupt shares every wave. Its index sorts
    first, so the lazy first combination includes the bad share and fails
    the group check — the individual-filter path must discard it and the
    remaining honest shares must still produce the (identical) coin."""
    coins = {}

    def factory(i):
        cls = ByzantineShareCoin if i == 0 else ThresholdCoin
        coins[i] = cls(keys, i, 4)
        return coins[i]

    sim = run_sim(keys, factory)
    sim.check_agreement()
    assert any(
        p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
    )
    # honest coins agree despite the poisoned share
    sigmas = {}
    for i, coin in coins.items():
        if i == 0:
            continue
        for wave, sigma in coin._sigma.items():
            sigmas.setdefault(wave, set()).add(sigma)
    assert sigmas and all(len(v) == 1 for v in sigmas.values())
    # the filter actually fired somewhere: some honest process dropped the
    # bad share from its pool after a failed combination
    filtered = any(
        0 not in coin._shares.get(wave, {0: None})
        for i, coin in coins.items()
        if i != 0
        for wave in coin._sigma
    )
    assert filtered
