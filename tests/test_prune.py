"""DAG garbage collection / memory bounding (round-4 VERDICT missing #4).

The reference keeps its DAG forever (``process/process.go:72-85``); so did
rounds 1-3 here. With ``cfg.gc_depth`` set, the ordering rule excludes
vertices below ``leader_round - gc_depth`` deterministically at every
process, which makes retiring that state (DagState.prune_below) safe: the
total order cannot diverge on vertices nobody may deliver.
"""

from __future__ import annotations

import numpy as np
import pytest

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import Process, Simulation
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport import InMemoryTransport
from dag_rider_tpu.utils import checkpoint

GC = Config(
    n=4,
    coin="round_robin",
    propose_empty=True,
    gc_depth=16,
    sync_window=8,
)


def _run_rounds(sim: Simulation, target_round: int) -> None:
    # small chunks: tests below stage scenarios at specific rounds, so a
    # call must not overshoot the target by thousands of rounds
    for _ in range(20 * target_round):
        sim.run(max_messages=100)
        if max(p.round for p in sim.processes) >= target_round:
            return
    raise AssertionError("simulation failed to reach target round")


def test_gc_depth_config_validation():
    with pytest.raises(ValueError):
        Config(n=4, gc_depth=4)  # below sync_window + 2*wave_length
    Config(n=4, gc_depth=16, sync_window=8)  # ok


def test_long_run_memory_bounded_and_agreement_holds():
    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 120)
    sim.check_agreement()
    for p in sim.processes:
        # pruning actually happened and tracked the frontier
        assert p.dag.base_round > 0
        assert p.dag.base_round >= p.round - 3 * GC.gc_depth
        # dense capacity is REUSED, not doubled forever: the initial
        # allocation (max_rounds=64 rows) never needs to grow when the
        # live window is ~gc_depth rounds
        assert p.dag._capacity <= 64
        # the vertex map holds only the live window
        window = p.dag.max_round - p.dag.base_round + 1
        assert len(p.dag.vertices) <= GC.n * (window + 1)
        assert window <= GC.gc_depth + 3 * GC.wave_length
        # book-keeping is windowed too
        assert len(p.delivered_log) <= GC.n * (window + GC.gc_depth + 8)
        assert p.delivered_trimmed > 0
    # cumulative delivery kept going far past the window: the protocol
    # ran unbounded history over bounded state
    total = sum(len(d) for d in sim.deliveries)
    assert total > 4 * GC.n * GC.gc_depth


def test_unpruned_and_pruned_total_order_agree():
    """GC exclusion is part of the ordering rule, not a local heuristic —
    but with every process configured identically, the delivered order
    must equal the unpruned run's order *for the delivered prefix above
    the horizon*. Deliveries happen in lockstep here, so the GC run's
    sink is a subsequence-free exact match of the unpruned sink except
    for vertices the rule excludes (none, in a lockstep run with no
    stragglers)."""
    cfg_plain = Config(n=4, coin="round_robin", propose_empty=True)
    sims = []
    for cfg in (cfg_plain, GC):
        sim = Simulation(cfg)
        sim.submit_blocks(per_process=2)
        _run_rounds(sim, 60)
        sims.append(sim)
    a = [(v.id.round, v.id.source, v.digest()) for v in sims[0].deliveries[0]]
    b = [(v.id.round, v.id.source, v.digest()) for v in sims[1].deliveries[0]]
    k = min(len(a), len(b))
    assert k > 150  # several waves' worth of common prefix
    assert a[:k] == b[:k]


def test_pruned_node_serves_sync_within_window_refuses_below():
    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 80)
    p = sim.processes[0]
    base = p.dag.base_round
    assert base > 1
    outbox = []
    # nacks broadcast; window serves unicast to the requester (round 11)
    p.transport.broadcast = lambda msg: outbox.append(msg)
    p.transport.enqueue = lambda dest, msg: outbox.append(msg)

    # request below the horizon -> clean refusal: no vertices served,
    # just the sync_nack that steers the requester to state transfer
    p._sync_last_serve.clear()
    p._serve_sync(
        BroadcastMessage(
            vertex=None, round=base - 1, sender=1, kind="sync", origin=base
        )
    )
    assert [m.kind for m in outbox] == ["sync_nack"]
    assert outbox[0].round == base and outbox[0].origin == 1
    assert p.metrics.counters.get("sync_refused_pruned", 0) == 1
    outbox.clear()

    # request within the live window -> served from the original vertices
    lo = base + 1
    p._sync_last_serve.clear()
    p._serve_sync(
        BroadcastMessage(
            vertex=None, round=lo, sender=1, kind="sync", origin=lo + 2
        )
    )
    assert outbox, "live-window sync must serve vertices"
    assert all(m.vertex.id.round >= lo for m in outbox)


def test_checkpoint_roundtrip_preserves_gc_window(tmp_path):
    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 80)
    p = sim.processes[0]
    assert p.dag.base_round > 0
    checkpoint.save(p, str(tmp_path))

    fresh = Process(GC, 0, InMemoryTransport())
    checkpoint.restore(fresh, str(tmp_path))
    assert fresh.dag.base_round == p.dag.base_round
    assert fresh.delivered_trimmed == p.delivered_trimmed
    assert fresh.delivered_log == p.delivered_log
    assert sorted(fresh.dag.vertices) == sorted(p.dag.vertices)
    # dense mirrors landed in the right (shifted) rows
    np.testing.assert_array_equal(
        fresh.dag.exists[: fresh.dag.max_round + 1 - fresh.dag.base_round],
        p.dag.exists[: p.dag.max_round + 1 - p.dag.base_round],
    )
    # and the restored machine still runs
    fresh._started = True
    fresh.step()


def test_below_horizon_vertex_is_dropped_not_wedged():
    """A straggler broadcast from under the GC floor must be discarded
    (it can never be delivered anywhere), not buffered forever."""
    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 80)
    p = sim.processes[0]
    base = p.dag.base_round
    ghost = Vertex(
        id=VertexID(max(1, base - 4), 1),
        block=Block((b"ghost",)),
        strong_edges=tuple(
            VertexID(max(0, base - 5), s) for s in range(GC.quorum)
        ),
    )
    p.on_message(
        BroadcastMessage(vertex=ghost, round=ghost.round, sender=1)
    )
    p.step()
    assert ghost.id not in p._buffered_ids
    assert not p.dag.present(ghost.id)


def test_blocked_memo_reevaluated_after_prune_passes_weak_target():
    """A vertex blocked on a weak target that later falls under the GC
    floor must be re-evaluated and admitted (the below-base weak rule),
    not held forever by the stale blocked-on memo (round-4 review).
    Driven directly (a full sim's retroactive chains jump the floor
    several waves per commit, racing the observation window). Pinned to
    the scalar pump: the ``_blocked_on`` memo it asserts is a scalar
    drain internal (the vector drain re-checks batches wholesale)."""
    p = Process(
        Config(
            n=4,
            coin="round_robin",
            propose_empty=True,
            gc_depth=16,
            sync_window=8,
            pump="scalar",
        ),
        0,
        InMemoryTransport(),
    )
    # full rounds 1..8 from sources 0..2; source 3 is permanently absent
    for r in range(1, 9):
        prev = tuple(
            VertexID(r - 1, s)
            for s in (range(GC.n) if r == 1 else range(3))
        )[: max(GC.quorum, 3)]
        for s in range(3):
            p.dag.insert(Vertex(id=VertexID(r, s), strong_edges=prev))
    p.round = 8
    v = Vertex(
        id=VertexID(8, 3),
        block=Block((b"straggler",)),
        strong_edges=tuple(VertexID(7, s) for s in range(3)),
        weak_edges=(VertexID(2, 3),),  # absent forever
    )
    p.on_message(BroadcastMessage(vertex=v, round=8, sender=3))
    p._started = True
    p.step()
    assert v.id in p._buffered_ids  # blocked: memo points at (2, 3)
    assert p._blocked_on[v.id] == VertexID(2, 3)

    # a wave decision whose GC floor passes the weak target: floor =
    # r1(decided) - gc_depth = 21 - 16 = 5 > 2
    p.decided_wave = 6
    removed = p.maybe_prune()
    assert removed > 0 and p.dag.base_round == 5

    p.step()  # memo must re-evaluate, below-base weak rule admits v
    assert p.dag.present(v.id)
    assert v.id not in p._buffered_ids


def test_restore_rejects_corrupt_delivered_log(tmp_path):
    import json
    import os

    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 40)
    p = sim.processes[0]
    checkpoint.save(p, str(tmp_path))
    mpath = os.path.join(str(tmp_path), "manifest.json")
    manifest = json.load(open(mpath))
    assert manifest["delivered_log"]
    for bad in ([5, -3], [10**9, 1], [manifest["base_round"] - 1, 0]):
        manifest["delivered_log"][-1] = bad
        json.dump(manifest, open(mpath, "w"))
        fresh = Process(GC, 0, InMemoryTransport())
        with pytest.raises(ValueError, match="corrupt checkpoint"):
            checkpoint.restore(fresh, str(tmp_path))


def test_rbc_vote_books_pruned_with_dag():
    """The Bracha stage's per-slot state must follow the Process's GC
    floor (round-4: without this, RBC nodes leaked exactly the state
    class DagState.prune_below bounds), and frames for retired slots
    must be dropped, not re-admitted into fresh books."""
    sim = Simulation(GC, rbc=True)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 90)
    sim.check_agreement()
    p = sim.processes[0]
    rbc = p.transport
    assert p.dag.base_round > 0
    assert rbc.floor == p.dag.base_round
    # every slot map is windowed to the live rounds
    for d in (rbc._val, rbc._decided):
        assert all(k[0] >= rbc.floor for k in d)
    for s in (rbc._echoed, rbc._readied, rbc._delivered):
        assert all(k[0] >= rbc.floor for k in s)
    for book in (rbc._echoes, rbc._readies):
        assert all(k[0][0] >= rbc.floor for k in book)
    live_slots = len(rbc._delivered)
    assert live_slots <= GC.n * (GC.gc_depth + 4 * GC.wave_length)

    # a replayed VAL for a retired slot is dropped silently
    old = BroadcastMessage(
        vertex=Vertex(
            id=VertexID(1, 1),
            strong_edges=tuple(VertexID(0, s) for s in range(GC.quorum)),
        ),
        round=1,
        sender=1,
    )
    before = len(rbc._val)
    rbc._on_inner(old)
    assert len(rbc._val) == before
    assert (1, 1) not in rbc._echoed


def test_rbc_floor_follows_restores():
    """checkpoint restore and snapshot transfer must re-establish the
    RBC slot floor, or replayed frames for retired rounds regrow the
    vote books until the next wave decision (round-4 review)."""
    from dag_rider_tpu.transport.rbc import RbcTransport

    sim = Simulation(GC, rbc=True)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 80)
    donor = sim.processes[0]
    assert donor.dag.base_round > 0
    blob = checkpoint.snapshot_bytes(donor)

    broker = InMemoryTransport()
    rbc = RbcTransport(broker, 0, GC.n, GC.f)
    fresh = Process(GC, 0, rbc)
    assert checkpoint.restore_from_snapshot(fresh, blob)
    assert rbc.floor == fresh.dag.base_round > 0


def test_rbc_floor_follows_checkpoint_restore(tmp_path):
    from dag_rider_tpu.transport.rbc import RbcTransport

    sim = Simulation(GC, rbc=True)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 80)
    donor = sim.processes[0]
    checkpoint.save(donor, str(tmp_path))

    broker = InMemoryTransport()
    rbc = RbcTransport(broker, 0, GC.n, GC.f)
    fresh = Process(GC, 0, rbc)
    checkpoint.restore(fresh, str(tmp_path))
    assert rbc.floor == fresh.dag.base_round > 0


def test_threshold_coin_books_pruned_with_dag():
    """The coin's per-wave share/sigma books follow the GC floor — the
    last unbounded-state holdout after DAG + RBC pruning."""
    from dag_rider_tpu.consensus.coin import ThresholdCoin
    from dag_rider_tpu.crypto import threshold as th

    n, f = 4, 1
    keys = th.ThresholdKeys.generate(n, f + 1)
    oracle = ThresholdCoin(keys, 0, n)

    def coin_factory(i):
        c = ThresholdCoin(keys, i, n)
        c._shares = oracle._shares
        c._sigma = oracle._sigma
        c._tried_at = oracle._tried_at
        return c

    cfg = Config(
        n=n, coin="threshold_bls", propose_empty=True, gc_depth=16
    )
    sim = Simulation(cfg, coin_factory=coin_factory)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 60)
    sim.check_agreement()
    p = sim.processes[0]
    assert p.dag.base_round > 4
    floor_wave = cfg.wave_of_round(p.dag.base_round)
    assert oracle._sigma, "coin actually decided waves"
    assert all(w >= floor_wave for w in oracle._shares)
    assert all(w >= floor_wave for w in oracle._sigma)


def test_retro_chain_survives_pruned_coin_after_state_transfer():
    """After a state transfer, decided_wave resets to 0 while the coin
    books below the imported floor are pruned — the first wave commit's
    retroactive walk must skip those unknowable links (their deliveries
    are floor-excluded here) instead of raising 'coin not ready'
    (round-4 review)."""
    from dag_rider_tpu.consensus.coin import ThresholdCoin
    from dag_rider_tpu.crypto import threshold as th

    n = 4
    keys = th.ThresholdKeys.generate(n, 2)
    oracle = ThresholdCoin(keys, 0, n)

    def cf(i):
        c = ThresholdCoin(keys, i, n)
        c._shares = oracle._shares
        c._sigma = oracle._sigma
        c._tried_at = oracle._tried_at
        return c

    cfg = Config(n=n, coin="threshold_bls", propose_empty=True, gc_depth=16)
    sim = Simulation(cfg, coin_factory=cf)
    sim.submit_blocks(per_process=2)
    _run_rounds(sim, 60)
    donor = sim.processes[0]
    assert donor.dag.base_round > 4
    blob = checkpoint.snapshot_bytes(donor)

    fresh = Process(cfg, 0, InMemoryTransport(), coin=cf(0))
    assert checkpoint.restore_from_snapshot(fresh, blob)
    assert fresh.decided_wave == 0
    # waves below the imported floor have no books anymore
    floor_wave = cfg.wave_of_round(fresh.dag.base_round)
    assert all(w >= floor_wave for w in fresh.coin._shares)
    # the next wave commit walks the retro chain back to decided_wave=0
    # straight through the pruned-coin waves — it must skip them, not
    # raise "coin for wave w not ready"
    committed = False
    for w in range(cfg.wave_of_round(fresh.dag.max_round), 0, -1):
        if cfg.wave_round(w, cfg.wave_length) > fresh.dag.max_round:
            continue  # wave not fully inside the imported window
        if cfg.wave_round(w, 1) <= fresh.dag.base_round:
            break  # below the floor: nothing left to try
        fresh._try_wave(w)
        if fresh.decided_wave == w:
            committed = True
            break
    assert committed, "restored node could not commit any window wave"
    assert not any(
        cfg.wave_round(w, 1) <= fresh.dag.base_round
        for w in fresh._pending_waves
    )


@pytest.mark.parametrize("seed", [2, 19, 101, 977])
def test_gc_agreement_under_random_interleavings(seed):
    """Adversarial delivery order WITH pruning active: the interaction
    zone of stragglers, retro chains, floor exclusion and the blocked
    memo. Any interleaving must preserve total-order agreement and the
    bounded window — exactly where a GC determinism bug would surface."""
    from dag_rider_tpu.consensus import RandomizedScheduler

    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    for p in sim.processes:
        p.start()
    sched = RandomizedScheduler(sim.transport, seed)
    for _ in range(400):
        if not sched.run(max_messages=200):
            break
        for p in sim.processes:
            p.step()
    sim.check_agreement()
    assert any(p.dag.base_round > 0 for p in sim.processes), "never pruned"
    for p in sim.processes:
        window = p.dag.max_round - p.dag.base_round + 1
        assert len(p.dag.vertices) <= GC.n * (window + 1)
