"""Causal tracing + flight recorder (round 16 tentpole).

Covers the whole obs surface: bounded ring, zero cost when off,
deterministic payload-hash sampling, injectable clock, lifecycle-chain
decomposition whose components sum to the measured total, chrome
export, flight dumps on planted triggers (including a real invariant
violation under transport chaos and the scenario-runner post-hoc path),
trace-on/off commit-order byte identity across committee sizes and
fault seeds, and the driderlint events checker shown non-vacuous by a
planted unregistered event.
"""

import ast
import json
import os

import pytest

from dag_rider_tpu import obs
from dag_rider_tpu.analysis import events as events_checker
from dag_rider_tpu.config import Config, MempoolConfig
from dag_rider_tpu.consensus.invariants import (
    InvariantMonitor,
    InvariantViolation,
)
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.obs import export, report
from dag_rider_tpu.obs.flight import FlightRecorder
from dag_rider_tpu.obs.recorder import TraceRecorder
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport
from dag_rider_tpu.utils.slog import KNOWN_EVENTS, NOOP, EventLog


# -- ring recorder -----------------------------------------------------------


def test_ring_stays_bounded_over_long_run():
    rec = TraceRecorder(capacity=256)
    log = EventLog(rec, clock=lambda: 0.0)
    for i in range(10_000):
        log.event("admit", round=i)
    assert len(rec) == 256
    assert rec.dropped == 10_000 - 256
    evs = rec.events()
    assert evs[-1]["round"] == 9_999  # newest retained, oldest evicted
    rec.clear()
    assert len(rec) == 0


def test_ring_bounded_under_traced_simulation():
    tracing = obs.build_tracing(ring=128, flight_dir="")
    sim = Simulation(
        Config(n=4, coin="round_robin", propose_empty=True), log=tracing.log
    )
    sim.submit_blocks(per_process=2)
    for _ in range(60):
        sim.run(max_messages=4 * 3)
    assert len(tracing.recorder) <= 128
    assert tracing.recorder.dropped > 0  # the run genuinely overflowed


# -- zero cost when off ------------------------------------------------------


def test_trace_off_is_a_single_attribute_test(monkeypatch):
    monkeypatch.delenv("DAGRIDER_TRACE", raising=False)
    sim = Simulation(Config(n=4, coin="round_robin"))
    assert sim.recorder is None and sim.flight is None
    assert sim.log is NOOP and not sim.log.enabled
    assert all(not p.log.enabled for p in sim.processes)
    NOOP.event("tx_submit", tx=1)  # no sink: returns before any work


def test_trace_knob_autowires_simulation(monkeypatch, tmp_path):
    monkeypatch.setenv("DAGRIDER_TRACE", "1")
    monkeypatch.setenv("DAGRIDER_FLIGHT_DIR", str(tmp_path))
    sim = Simulation(Config(n=4, coin="round_robin", propose_empty=True))
    assert sim.recorder is not None and sim.flight is not None
    sim.submit_blocks(per_process=1)
    sim.run(max_messages=2_000)
    names = {r["event"] for r in sim.recorder.events()}
    assert "phase_pump" in names and "tx_propose" in names


# -- sampling + clock --------------------------------------------------------


def test_sampling_is_deterministic_and_edge_exact():
    txs = [f"tx-{i}".encode() for i in range(400)]
    assert all(obs.sample_tx(t, 1.0) for t in txs)
    assert not any(obs.sample_tx(t, 0.0) for t in txs)
    first = [obs.sample_tx(t, 0.25) for t in txs]
    assert first == [obs.sample_tx(t, 0.25) for t in txs]
    frac = sum(first) / len(first)
    assert 0.1 < frac < 0.4  # crc32 spreads ~uniformly


def test_injected_clock_stamps_events():
    t = [100.0]
    tracing = obs.build_tracing(clock=lambda: t[0], flight_dir="")
    tracing.log.event("wave_decided", round=1)
    t[0] = 250.0
    tracing.log.event("wave_decided", round=2)
    ts = [r["ts"] for r in tracing.recorder.events()]
    assert ts == [100.0, 250.0]


# -- lifecycle chains + decomposition ---------------------------------------


def _traced_loaded_sim(seconds=0.6):
    from dag_rider_tpu.mempool.loadgen import ClusterLoadDriver, LoadGenerator

    tracing = obs.build_tracing(sample_rate=1.0, flight_dir="")
    sim = Simulation(
        Config(
            n=4,
            coin="round_robin",
            propose_empty=True,
            sync_request_cooldown_s=0.0,
            sync_serve_cooldown_s=0.0,
        ),
        log=tracing.log,
    )
    gen = LoadGenerator(clients=4, rate=300.0, tx_bytes=32, seed=3)
    drv = ClusterLoadDriver(
        sim, gen, mcfg=MempoolConfig(cap=4096, batch_bytes=512)
    )
    drv.run(seconds)
    return tracing, sim


def test_lifecycle_chain_components_sum_to_total():
    tracing, _sim = _traced_loaded_sim()
    events = tracing.recorder.events()
    chains = report.chains(events)
    rep = report.decompose(events)
    assert chains, "traced load produced no complete submit->deliver chains"
    assert rep["txs"] == len(chains)
    for c in chains:
        parts = (
            c["mempool_queue_s"] + c["propose_stage_s"] + c["wave_commit_s"]
        )
        assert parts == pytest.approx(c["total_s"], rel=1e-9, abs=1e-9)
    # the acceptance gate: the per-phase breakdown at p50 sums within
    # 10% of the measured submit->deliver p50
    p50 = rep["percentiles"]["p50"]
    parts = (
        p50["mempool_queue_s"]
        + p50["propose_stage_s"]
        + p50["wave_host_pump_s"]
        + p50["wave_verify_s"]
        + p50["wave_cert_s"]
        + p50["wave_transport_wait_s"]
    )
    assert parts == pytest.approx(p50["total_s"], rel=0.10)
    occ = rep["phase_occupancy"]
    assert occ["pump_s"] > 0.0 and occ["wall_s"] > 0.0


def test_chrome_export_roundtrips(tmp_path):
    tracing, _sim = _traced_loaded_sim(seconds=0.3)
    evs = tracing.recorder.events()
    path = str(tmp_path / "trace.json")
    export.write_chrome_trace(evs, path)
    doc = json.load(open(path))
    assert len(doc["traceEvents"]) == len(evs)
    phases = {e["ph"] for e in doc["traceEvents"]}
    assert phases <= {"X", "i"}
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert spans and all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)
    # load_events re-flattens a chrome trace into joinable records
    back = export.load_events(path)
    assert len(back) == len(evs)
    # and a raw ring dump loads identically
    raw = str(tmp_path / "ring.json")
    tracing.recorder.write_json(raw)
    assert len(export.load_events(raw)) == len(evs)


# -- flight recorder ---------------------------------------------------------


def test_flight_dump_on_trigger_and_budget(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=8, clock=lambda: 7.0)
    fr.add_metrics_source("p0", lambda: {"counters": {"admitted": 3}})
    log = EventLog(fr.sink, clock=lambda: 7.0)
    for i in range(20):
        log.event("admit", round=i)
    assert fr.dumps == []  # no trigger yet
    log.event("invariant_violation", kind="planted", detail="x")
    assert len(fr.dumps) == 1
    dump = export.load_flight(fr.dumps[0])
    assert dump is not None and dump["reason"] == "invariant_violation"
    assert dump["trigger"]["kind"] == "planted"
    assert len(dump["events"]) <= 8 + 1
    assert dump["metrics"]["p0"]["counters"]["admitted"] == 3
    # dump budget: a crash loop cannot fill the disk
    for _ in range(50):
        log.event("pump_error", error="boom")
    assert len(fr.dumps) <= 8


def test_flight_dump_left_by_violation_under_chaos(tmp_path):
    """A real InvariantViolation raised mid-pump under transport chaos
    leaves a loadable post-mortem dump even though the exception unwinds
    straight out of the delivery callback."""
    tracing = obs.build_tracing(flight_dir=str(tmp_path), flight_events=64)
    tp = FaultyTransport(FaultPlan(duplicate=0.05, seed=2))
    sim = Simulation(
        Config(n=4, coin="round_robin", propose_empty=True),
        transport=tp,
        log=tracing.log,
    )
    monitor = sim.attach_invariant_monitor()
    for p in sim.processes:  # externally-built tracing: wire sources
        tracing.flight.add_metrics_source(str(p.index), p.metrics.snapshot)
    sim.submit_blocks(per_process=2)
    for _ in range(10):
        sim.run(max_messages=200)
    # plant the violation: replay an already-observed slot at view 0
    assert monitor.observed > 0
    v = Vertex(
        id=VertexID(1, 0), block=Block((b"tx",)), strong_edges=()
    )
    monitor._seen_slots.setdefault(0, set()).add((1, 0))
    with pytest.raises(InvariantViolation, match="twice"):
        monitor.observe(0, v)
    assert len(tracing.flight.dumps) == 1
    dump = export.load_flight(tracing.flight.dumps[0])
    assert dump["trigger"]["kind"] == "double_delivery"
    assert any(r["event"] == "phase_pump" for r in dump["events"])
    assert dump["metrics"]  # per-process snapshots rode along


def test_scenario_posthoc_violation_dumps_flight(monkeypatch, tmp_path):
    """The scenario runner's post-hoc audits route through the flight
    recorder: an impossible liveness floor must raise AND leave a dump."""
    from dag_rider_tpu.consensus.scenarios import Scenario, run_scenario

    monkeypatch.setenv("DAGRIDER_TRACE", "1")
    monkeypatch.setenv("DAGRIDER_FLIGHT_DIR", str(tmp_path))
    sc = Scenario(n=4, cycles=8, min_waves=999)
    with pytest.raises(InvariantViolation):
        run_scenario(sc)
    dumps = sorted(tmp_path.glob("flight_*.json"))
    assert dumps, "post-hoc violation left no flight dump"
    dump = export.load_flight(str(dumps[0]))
    assert dump["trigger"]["view"] == "posthoc"


def test_scenario_report_carries_flight_dumps(monkeypatch, tmp_path):
    from dag_rider_tpu.consensus.scenarios import Scenario, run_scenario

    monkeypatch.setenv("DAGRIDER_TRACE", "1")
    monkeypatch.setenv("DAGRIDER_FLIGHT_DIR", str(tmp_path))
    rep = run_scenario(Scenario(n=4, cycles=24))
    assert rep["flight_dumps"] == []  # clean run: no triggers fired


# -- trace on/off byte identity ----------------------------------------------


@pytest.mark.parametrize(
    "n,seed", [(4, 0), (4, 1), (16, 0), (16, 1), (32, 0), (32, 1)]
)
def test_trace_onoff_commit_order_byte_identical(n, seed):
    """Tracing observes — it must never perturb the protocol. Same
    committee, same fault seed, identical pump schedule: the delivery
    sequences (id + digest, every view) must match byte for byte."""
    orders = {}
    for path in ("off", "on"):
        tracing = (
            obs.build_tracing(sample_rate=1.0, flight_dir="")
            if path == "on"
            else None
        )
        tp = FaultyTransport(FaultPlan(duplicate=0.05, seed=seed))
        sim = Simulation(
            Config(n=n, coin="round_robin", propose_empty=True),
            transport=tp,
            log=tracing.log if tracing is not None else None,
        )
        sim.submit_blocks(per_process=2)
        for _ in range(12):  # fixed schedule: both sides do the same work
            sim.run(max_messages=2 * n * n)
        orders[path] = [
            [(v.id, v.digest()) for v in d] for d in sim.deliveries
        ]
    assert any(orders["off"]), "no deliveries — the A/B was vacuous"
    assert orders["off"] == orders["on"]


# -- events checker (driderlint) --------------------------------------------


def _synthetic(src):
    return [("dag_rider_tpu/fake.py", ast.parse(src), src)]


def test_events_checker_catches_planted_unregistered_event():
    src = 'log.event("definitely_not_registered", x=1)\n'
    findings = events_checker.run(_synthetic(src), "/nonexistent")
    assert len(findings) == 1
    assert "definitely_not_registered" in findings[0].message
    assert findings[0].checker == "events"


def test_events_checker_accepts_registered_and_dynamic_names():
    src = (
        'log.event("tx_submit", tx=1)\n'
        "log.event(name, x=1)\n"  # non-literal: out of scope
        'other.event_like("nope")\n'
    )
    assert events_checker.run(_synthetic(src), "/nonexistent") == []


def test_events_checker_wired_into_run_static():
    from dag_rider_tpu.analysis.core import run_static

    src = 'log.event("typo_event_name")\n'
    kept, _sup, _unused = run_static("/root/repo", files=_synthetic(src))
    assert any(
        f.checker == "events" and "typo_event_name" in f.message
        for f in kept
    )


def test_every_emitted_event_name_is_registered():
    # the live-tree guarantee the checker enforces, asserted directly
    from dag_rider_tpu.analysis.core import discover

    findings = events_checker.run(discover("/root/repo"), "/root/repo")
    assert findings == []
    assert "tx_deliver" in KNOWN_EVENTS  # the join the report depends on


# -- obs_report CLI ----------------------------------------------------------


def test_obs_report_cli_report_and_flight_modes(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)

    tracing, _sim = _traced_loaded_sim(seconds=0.3)
    ring = str(tmp_path / "ring.json")
    tracing.recorder.write_json(ring)
    assert obs_report.main(["report", ring]) == 0
    out = capsys.readouterr().out
    assert "p50" in out and "wave_commit" in out

    chrome = str(tmp_path / "chrome.json")
    assert obs_report.main(["chrome", ring, chrome]) == 0
    assert json.load(open(chrome))["traceEvents"]

    fr = FlightRecorder(str(tmp_path), capacity=8, clock=lambda: 1.0)
    log = EventLog(fr.sink, clock=lambda: 1.0)
    log.event("pump_error", error="planted")
    assert obs_report.main(["flight", fr.dumps[0]]) == 0
    out = capsys.readouterr().out
    assert "pump_error" in out
