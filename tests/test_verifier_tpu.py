"""TPUVerifier vs CPUVerifier — byte-identical accept masks and commit order.

The north star (BASELINE.json): "CPU-vs-TPU commit order byte-identical".
The consensus state machine is a deterministic function of the accept masks
and the delivery schedule, so mask equality on every batch (including
adversarial ones) implies commit-order equality; the end-to-end sim test
checks the full pipeline anyway.
"""

import dataclasses
import random

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.crypto import ed25519
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(8)


@pytest.fixture(scope="module")
def signed_vertices(keys):
    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]
    out = []
    for i in range(8):
        v = Vertex(
            id=VertexID(3, i),
            block=Block((f"tx-{i}".encode(),)),
            strong_edges=(VertexID(2, 0), VertexID(2, 1), VertexID(2, 2)),
        )
        out.append(signers[i].sign_vertex(v))
    return out


def corruptions(vs):
    rng = random.Random(99)
    bad = [
        dataclasses.replace(vs[0], signature=b"\x00" * 64),
        dataclasses.replace(vs[1], signature=vs[2].signature),
        dataclasses.replace(vs[3], block=Block((b"tampered",))),
        dataclasses.replace(vs[6], signature=None),
    ]
    # s >= L (malleability)
    s_big = int.to_bytes(
        int.from_bytes(vs[4].signature[32:], "little") + ed25519.L,
        32,
        "little",
    )
    bad.append(
        dataclasses.replace(vs[4], signature=vs[4].signature[:32] + s_big)
    )
    # R.y >= p
    ybad = int.to_bytes(2**255 - 10, 32, "little")
    bad.append(
        dataclasses.replace(vs[5], signature=ybad + vs[5].signature[32:])
    )
    # random bit flips across R, s
    for i in range(6):
        sig = bytearray(vs[i].signature)
        sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
        bad.append(dataclasses.replace(vs[i], signature=bytes(sig)))
    return bad


def test_masks_byte_identical(keys, signed_vertices):
    reg, _ = keys
    batch = signed_vertices + corruptions(signed_vertices)
    cpu = CPUVerifier(reg).verify_batch(batch)
    tpu = TPUVerifier(reg).verify_batch(batch)
    assert cpu == tpu
    assert cpu[: len(signed_vertices)] == [True] * len(signed_vertices)
    assert not any(cpu[len(signed_vertices) :])


def test_empty_and_padding(keys, signed_vertices):
    reg, _ = keys
    tpu = TPUVerifier(reg)
    assert tpu.verify_batch([]) == []
    # batch sizes straddling the bucket boundary behave identically
    assert tpu.verify_batch(signed_vertices[:1]) == [True]
    assert tpu.verify_batch(signed_vertices[:3]) == [True] * 3


def test_out_of_range_source(keys, signed_vertices):
    reg, _ = keys
    v = dataclasses.replace(
        signed_vertices[0], id=VertexID(3, 999)
    )
    assert TPUVerifier(reg).verify_batch([v]) == [False]
    assert CPUVerifier(reg).verify_batch([v]) == [False]


def test_invalid_registry_key():
    reg, seeds = KeyRegistry.generate(4)
    # replace key 2 with a non-decompressible encoding (y = 2 not on curve)
    pubs = list(reg.public_keys)
    pubs[2] = int.to_bytes(2, 32, "little")
    broken = KeyRegistry(tuple(pubs))
    signer = VertexSigner(seeds[2])
    v = signer.sign_vertex(
        Vertex(id=VertexID(1, 2), strong_edges=(VertexID(0, 0),))
    )
    assert TPUVerifier(broken).verify_batch([v]) == [False]
    assert CPUVerifier(broken).verify_batch([v]) == [False]


def test_commit_order_byte_identical_cpu_vs_tpu():
    """4-node simulation run twice — once with the CPU verifier, once with
    the TPU verifier — must deliver the identical vertex sequence on every
    node (the north-star equivalence, end to end).

    ``propose_empty=False`` + a finite block supply makes the cluster
    quiesce on its own after ~2 waves, which bounds the number of device
    dispatches (the round-1 version ran to ``max_messages`` and took >9
    minutes on the CPU backend)."""
    logs = {}
    for backend in ("cpu", "tpu"):
        cfg = Config(n=4, signature_scheme="ed25519", propose_empty=False)
        reg, seeds = KeyRegistry.generate(cfg.n)
        make = CPUVerifier if backend == "cpu" else TPUVerifier
        sim = Simulation(
            cfg,
            verifier_factory=lambda i: make(reg),
            signer_factory=lambda i: VertexSigner(seeds[i]),
        )
        sim.submit_blocks(8)
        sim.run(max_messages=4000)
        sim.check_agreement()
        logs[backend] = [
            [(vid.round, vid.source) for vid in p.delivered_log]
            for p in sim.processes
        ]
        assert any(logs[backend]), "no deliveries happened"
        assert any(
            p.metrics.counters["waves_decided"] >= 1 for p in sim.processes
        )
        # Live-pipeline batching (north star: one round per dispatch): the
        # burst pump must hand the Verifier round-sized batches, not
        # singletons.
        sizes = [s for p in sim.processes for s in p.metrics.verify_batch_sizes]
        assert sizes and sum(sizes) / len(sizes) >= 2.0, sizes
    assert logs["cpu"] == logs["tpu"]


def test_verify_batch_survives_pipeline_off_flag(keys, signed_vertices):
    """bench.py's sim256_sync rung flips pipeline_enabled False to force
    the synchronous depth-1 path (this flag replaced the round-5
    instance-attribute None shadow, whose failure mode was verify_batch
    calling None mid-ladder); verify_batch and the chunked verify_rounds
    must keep working — and produce identical masks — in both states."""
    reg, _ = keys
    v = TPUVerifier(reg)
    v.fixed_bucket = 16
    baseline = v.verify_batch(signed_vertices)
    rounds_base = v.verify_rounds([signed_vertices, signed_vertices])
    v.pipeline_enabled = False
    try:
        assert v.verify_batch(signed_vertices) == baseline
        assert v.verify_rounds([signed_vertices, signed_vertices]) == (
            rounds_base
        )
        assert all(baseline)
    finally:
        v.pipeline_enabled = True
    # flag restored: the async seam is usable again
    pending = v.dispatch_batch(signed_vertices)
    assert v.resolve_batch(pending) == baseline
