"""Span-attested snapshot sync (ISSUE 20).

A snapshot donor envelopes its window with the verified cert-of-certs
span chain; a joiner verifies ONE combined pairing per span and admits
every vertex whose digest a verified span restates without a per-vertex
signature check. Attestation removes work, never trust:

- a tampered span chain (or a window whose vertices no longer match the
  attested digests) is refused wholesale,
- a torn envelope is refused wholesale — never degraded to
  "unattested",
- a plain pre-attestation (and pre-epoch) snapshot still restores,
- the attested joiner's state is byte-identical to a replaying joiner
  that re-verified every vertex signature (n in {4, 16} seeded fuzz),
  at a pairing budget of <= ceil(window / k_span) checks.
"""

from __future__ import annotations

import dataclasses
import math
import struct

import pytest

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import Process, Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, EpochOp
from dag_rider_tpu.transport import InMemoryTransport
from dag_rider_tpu.utils import checkpoint

K_SPAN = 2

#: donor sims are ~20s to grow; cache per shape and NEVER mutate a
#: cached donor without restoring it (see the window-tamper test)
_DONORS: dict = {}


def _span_donor(n=4, seed=0, target_round=48, epoch=False):
    key = (n, seed, epoch)
    if key not in _DONORS:
        _DONORS[key] = _build_donor(n, seed, target_round, epoch)
    return _DONORS[key]


def _build_donor(n, seed, target_round, epoch):
    cfg = Config(
        n=n,
        coin="round_robin",
        propose_empty=True,
        gc_depth=16,
        cert_span=K_SPAN,
        epoch=epoch,
        epoch_waves=4,
    )
    sim = Simulation(cfg, verifier="cpu", cert=True)
    for i in range(n):
        sim.processes[i].submit(
            Block((f"sn{seed}-p{i}".encode().ljust(32, b"."),))
        )
    if epoch:
        sim.processes[0].submit(
            Block((codec.encode_epoch_op(EpochOp("rotate", 0, seed, b"")),))
        )
    for _ in range(40 * target_round):
        sim.run(max_messages=200)
        donor = sim.processes[0]
        if (
            donor.round >= target_round
            and donor.dag.base_round > 0
            and donor._span_chain
            and (not epoch or donor.epoch_mgr.epoch >= 1)
        ):
            break
    donor = sim.processes[0]
    assert donor.dag.base_round > 0 and donor._span_chain
    return sim, donor


def _fresh(sim):
    return Process(sim.cfg, 0, InMemoryTransport())


def test_attested_roundtrip_and_pairing_budget():
    sim, donor = _span_donor()
    blob = checkpoint.attested_snapshot_bytes(donor)
    assert blob.startswith(checkpoint.SNAP_ATTEST_MAGIC)
    assert donor.metrics.counters["snapshot_spans_attached"] > 0

    joiner = _fresh(sim)
    assert checkpoint.restore_from_snapshot(
        joiner, blob, span_verifier=sim.cert_verifier
    )
    assert joiner.dag.base_round == donor.dag.base_round
    assert sorted(joiner.dag.vertices) == sorted(donor.dag.vertices)
    checks = joiner.metrics.counters["snapshot_pairing_checks"]
    assert checks == joiner.metrics.counters["snapshot_spans_verified"]
    # the acceptance budget: <= ceil(window rounds / k_span) pairings
    assert 0 < checks <= math.ceil(donor.dag.max_round / K_SPAN)
    assert joiner.metrics.counters["snapshot_attest_rejects"] == 0


@pytest.mark.parametrize(
    "n,seed",
    [(4, 0), (4, 1), pytest.param(16, 0, marks=pytest.mark.slow)],
)
def test_attested_joiner_identical_to_replaying_joiner(n, seed):
    """The fast path changes which CHECK admits a vertex, never the
    resulting state: an attested joiner and a signature-replaying
    joiner land byte-identical."""
    sim, donor = _span_donor(n=n, seed=seed)
    attested = checkpoint.attested_snapshot_bytes(donor)
    plain = checkpoint.snapshot_bytes(donor)

    fast = _fresh(sim)
    assert checkpoint.restore_from_snapshot(
        fast, attested, span_verifier=sim.cert_verifier
    )
    slow = _fresh(sim)
    assert checkpoint.restore_from_snapshot(
        slow, plain, verifier=donor.verifier
    )

    def state(p):
        return (
            p.dag.base_round,
            p.dag.max_round,
            p.round,
            sorted((vid, v.digest()) for vid, v in p.dag.vertices.items()),
        )

    assert state(fast) == state(slow)
    assert fast.metrics.counters["snapshot_pairing_checks"] <= math.ceil(
        donor.dag.max_round / K_SPAN
    )


def test_tampered_span_chain_refused_wholesale():
    sim, donor = _span_donor()
    plain = checkpoint.snapshot_bytes(donor)
    spans = [donor._span_chain[e] for e in sorted(donor._span_chain)]
    bad_digests = tuple(
        tuple(b"\x13" * 32 for _ in row) for row in spans[0].digests
    )
    forged = [dataclasses.replace(spans[0], digests=bad_digests)] + spans[1:]
    blob = checkpoint.wrap_attested(plain, forged)
    joiner = _fresh(sim)
    assert not checkpoint.restore_from_snapshot(
        joiner, blob, span_verifier=sim.cert_verifier
    )
    assert joiner.metrics.counters["snapshot_attest_rejects"] == 1
    # untouched: still the genesis-only fresh process
    assert joiner.dag.max_round == 0 and joiner.round == 0


def test_tampered_window_vertex_refused_on_digest_mismatch():
    """Valid span chain, tampered vertex bytes: the attested digest no
    longer matches, which is donor tampering — refuse wholesale rather
    than admit a payload the quorum never co-signed."""
    sim, donor = _span_donor()
    victim_round = donor.dag.base_round + 1
    victim = donor.dag.vertices_in_round(victim_round)[0]
    forged = dataclasses.replace(
        victim, block=Block((b"forged-payload",)), signature=victim.signature
    )
    del donor.dag.vertices[victim.id]
    donor.dag.vertices[forged.id] = forged
    try:
        blob = checkpoint.attested_snapshot_bytes(donor)
    finally:
        # the donor sim is cached across tests: undo the tamper
        del donor.dag.vertices[forged.id]
        donor.dag.vertices[victim.id] = victim
    joiner = _fresh(sim)
    assert not checkpoint.restore_from_snapshot(
        joiner, blob, span_verifier=sim.cert_verifier
    )
    assert joiner.metrics.counters["snapshot_attest_rejects"] == 1
    assert joiner.dag.max_round == 0


def test_torn_envelope_refused_never_degraded():
    sim, donor = _span_donor()
    blob = checkpoint.attested_snapshot_bytes(donor)
    torn = blob[: len(checkpoint.SNAP_ATTEST_MAGIC) + 4 + 2]
    joiner = _fresh(sim)
    assert not checkpoint.restore_from_snapshot(
        joiner, torn, span_verifier=sim.cert_verifier
    )
    assert joiner.metrics.counters["snapshot_attest_rejects"] == 1
    # same refusal when the receiver has no span verifier at all: a
    # magic-prefixed blob that does not parse is torn for everyone
    joiner2 = _fresh(sim)
    assert not checkpoint.restore_from_snapshot(joiner2, torn)
    with pytest.raises(ValueError):
        checkpoint.unwrap_attested(torn)


def test_plain_blob_passthrough_and_span_verifier_none():
    sim, donor = _span_donor()
    plain = checkpoint.snapshot_bytes(donor)
    spans, inner = checkpoint.unwrap_attested(plain)
    assert spans is None and inner == plain
    # attested blob + no span verifier: spans are ignored, the full
    # per-vertex verify path runs — attestation removes work, not trust
    attested = checkpoint.attested_snapshot_bytes(donor)
    joiner = _fresh(sim)
    assert checkpoint.restore_from_snapshot(
        joiner, attested, verifier=donor.verifier
    )
    assert joiner.metrics.counters["snapshot_pairing_checks"] == 0
    assert sorted(joiner.dag.vertices) == sorted(donor.dag.vertices)


def test_pre_epoch_snapshot_restores_with_epoch_zero():
    """A snapshot from a pre-epoch donor (no epoch section in the head)
    restores into an epoch-enabled joiner at epoch 0."""
    sim, donor = _span_donor()  # epoch off: head carries no epoch key
    blob = checkpoint.snapshot_bytes(donor)
    (hlen,) = struct.unpack_from("<I", blob, 0)
    assert b'"epoch"' not in blob[4 : 4 + hlen]
    cfg = dataclasses.replace(sim.cfg, epoch=True)
    joiner = Process(cfg, 0, InMemoryTransport())
    assert checkpoint.restore_from_snapshot(joiner, blob)
    assert joiner.epoch_mgr.epoch == 0


def test_epoch_head_roundtrips_through_snapshot():
    sim, donor = _span_donor(epoch=True)
    assert donor.epoch_mgr.epoch >= 1
    blob = checkpoint.attested_snapshot_bytes(donor)
    joiner = _fresh(sim)
    assert checkpoint.restore_from_snapshot(
        joiner, blob, span_verifier=sim.cert_verifier
    )
    assert joiner.epoch_mgr.epoch == donor.epoch_mgr.epoch
    assert joiner.epoch_mgr.seed == donor.epoch_mgr.seed
    assert (
        joiner.metrics.counters["epoch_current"] == donor.epoch_mgr.epoch
    )
    # malformed epoch head: refused wholesale BEFORE any commit
    (hlen,) = struct.unpack_from("<I", checkpoint.snapshot_bytes(donor), 0)
    plain = checkpoint.snapshot_bytes(donor)
    import json as _json

    head = _json.loads(plain[4 : 4 + hlen])
    head["epoch"]["seed"] = "not-hex!"
    forged_head = _json.dumps(head).encode()
    forged = (
        struct.pack("<I", len(forged_head)) + forged_head + plain[4 + hlen :]
    )
    j2 = _fresh(sim)
    assert not checkpoint.restore_from_snapshot(j2, forged)
    assert j2.dag.max_round == 0
