"""driderlint non-vacuity suite (round 14).

Every checker is proven by a PLANTED violation, mirroring the
consensus/invariants.py pattern: a checker that cannot fail is not a
checker. Synthetic files are fed through the same ``run(files, root)``
entry the production runner uses, so these tests exercise the real
code path, not a parallel one. The clean-tree test at the bottom is
the other half of the acceptance criterion: the suite must pass on
today's repo with zero unexplained allowlist entries.
"""

import ast
import os
import threading
import time

import pytest

from dag_rider_tpu.analysis import (
    determinism,
    jitpure,
    knobs,
    metricsreg,
    oracle,
    races,
)
from dag_rider_tpu.analysis.core import (
    Allow,
    Finding,
    apply_allowlist,
    run_static,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def F(path, src):
    """One synthetic (relpath, tree, source) triple."""
    return (path, ast.parse(src), src)


def _msgs(findings):
    return [f.message for f in findings]


# -- knob discipline --------------------------------------------------------


def test_knobs_flags_direct_env_read_outside_config():
    got = knobs.run(
        [
            F(
                "dag_rider_tpu/evil.py",
                "import os\nx = os.environ.get('DAGRIDER_EVIL')\n",
            )
        ],
        REPO,
    )
    assert any("DAGRIDER_EVIL" in m for m in _msgs(got))


def test_knobs_flags_subscript_and_getenv_spellings():
    got = knobs.run(
        [
            F(
                "dag_rider_tpu/evil.py",
                "import os\n"
                "a = os.environ['DAGRIDER_A']\n"
                "b = os.getenv('DAGRIDER_B')\n",
            )
        ],
        REPO,
    )
    assert sum("DAGRIDER_A" in m for m in _msgs(got)) == 1
    assert sum("DAGRIDER_B" in m for m in _msgs(got)) == 1


def test_knobs_allows_config_and_bench_namespace():
    got = knobs.run(
        [
            F(
                "dag_rider_tpu/config.py",
                "import os\nx = os.environ.get('DAGRIDER_PUMP')\n",
            ),
            F(
                "bench.py",
                "import os\nx = os.environ.get('DAGRIDER_BENCH_FOO')\n",
            ),
        ],
        REPO,
    )
    assert got == []


def test_knobs_bench_cannot_read_package_namespace():
    got = knobs.run(
        [F("bench.py", "import os\nx = os.environ.get('DAGRIDER_PUMP')\n")],
        REPO,
    )
    assert any("DAGRIDER_PUMP" in m for m in _msgs(got))


def test_knobs_flags_unregistered_accessor_name():
    got = knobs.run(
        [
            F(
                "dag_rider_tpu/evil.py",
                "from dag_rider_tpu import config\n"
                "x = config.env_int('DAGRIDER_NOT_A_KNOB')\n",
            )
        ],
        REPO,
    )
    assert any("DAGRIDER_NOT_A_KNOB" in m for m in _msgs(got))


def test_knob_accessors_reject_unregistered_at_runtime():
    from dag_rider_tpu import config

    with pytest.raises(KeyError):
        config.env_flag("DAGRIDER_NOT_A_KNOB")


# -- determinism ------------------------------------------------------------


def test_determinism_flags_wall_clock_call():
    got = determinism.run(
        [F("dag_rider_tpu/evil.py", "import time\nt = time.time()\n")],
        REPO,
    )
    assert any("time.time()" in m for m in _msgs(got))


def test_determinism_allows_monotonic_and_clock_reference():
    got = determinism.run(
        [
            F(
                "dag_rider_tpu/ok.py",
                "import time\n"
                "t = time.monotonic()\n"
                "def f(clock=time.time):\n"
                "    return clock()\n",
            )
        ],
        REPO,
    )
    assert got == []


def test_determinism_flags_unseeded_random():
    got = determinism.run(
        [
            F(
                "dag_rider_tpu/evil.py",
                "import random\n"
                "a = random.random()\n"
                "r = random.Random()\n"
                "import numpy as np\n"
                "b = np.random.rand(3)\n",
            )
        ],
        REPO,
    )
    msgs = _msgs(got)
    assert any("random.random" in m for m in msgs)
    assert any("without a seed" in m for m in msgs)
    assert any("np.random.rand" in m for m in msgs)


def test_determinism_allows_seeded_rng():
    got = determinism.run(
        [
            F(
                "dag_rider_tpu/ok.py",
                "import random\nimport numpy as np\n"
                "r = random.Random(7)\n"
                "g = np.random.default_rng(7)\n",
            )
        ],
        REPO,
    )
    assert got == []


def test_determinism_flags_set_iteration_on_consensus_path():
    src = (
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pending = set()\n"
        "    def step(self):\n"
        "        for v in self._pending:\n"
        "            pass\n"
        "        for w in {1, 2}:\n"
        "            pass\n"
    )
    got = determinism.run([F("dag_rider_tpu/consensus/evil.py", src)], REPO)
    assert sum("set" in m for m in _msgs(got)) == 2
    # identical code OUTSIDE consensus/ is not in scope for this rule
    assert determinism.run([F("dag_rider_tpu/utils/x.py", src)], REPO) == []


def test_determinism_allows_sorted_set_iteration():
    src = (
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pending = set()\n"
        "    def step(self):\n"
        "        for v in sorted(self._pending):\n"
        "            pass\n"
    )
    assert (
        determinism.run([F("dag_rider_tpu/consensus/ok.py", src)], REPO)
        == []
    )


# -- oracle purity ----------------------------------------------------------


def test_oracle_flags_scalar_state_write_in_vector_branch():
    src = (
        "class P:\n"
        "    def step(self):\n"
        "        if self._vector:\n"
        "            self._buffer[1] = 2\n"
    )
    got = oracle.run([F("dag_rider_tpu/consensus/evil.py", src)], REPO)
    assert any("_buffer" in m for m in _msgs(got))


def test_oracle_flags_vector_state_write_in_scalar_branch():
    src = (
        "class P:\n"
        "    def step(self):\n"
        "        if self._vector:\n"
        "            pass\n"
        "        else:\n"
        "            self._inbox.append(1)\n"
        "    def other(self):\n"
        "        if not self._vector:\n"
        "            self._buffer_rounds = {}\n"
    )
    got = oracle.run([F("dag_rider_tpu/consensus/evil.py", src)], REPO)
    msgs = _msgs(got)
    assert any("_inbox" in m for m in msgs)
    assert any("_buffer_rounds" in m for m in msgs)


def test_oracle_flags_vector_only_method_and_cert_branch():
    src = (
        "class P:\n"
        "    def _drain_buffer_vector(self):\n"
        "        self._blocked_on.pop(3)\n"
        "    def go(self):\n"
        "        if self._cert:\n"
        "            self._buffered_ids.add(7)\n"
    )
    got = oracle.run([F("dag_rider_tpu/consensus/evil.py", src)], REPO)
    msgs = _msgs(got)
    assert any("_blocked_on" in m for m in msgs)
    assert any("_buffered_ids" in m for m in msgs)


def test_oracle_allows_legal_mutations():
    # cert path pushing into per-vertex re-verify is the degradation
    # seam, and vector code touching its OWN state is fine
    src = (
        "class P:\n"
        "    def _apply_certificate(self, c):\n"
        "        self._pending_verify.append(c)\n"
        "    def _process_inbox(self):\n"
        "        self._inbox.clear()\n"
    )
    assert oracle.run([F("dag_rider_tpu/consensus/ok.py", src)], REPO) == []


# -- jit purity -------------------------------------------------------------


def test_jitpure_flags_side_effects_in_jitted_fns():
    src = (
        "import functools, os, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    print(x)\n"
        "    return x\n"
        "@functools.partial(jax.jit, static_argnames=('n',))\n"
        "def g(x, n):\n"
        "    y = os.environ.get('HOME')\n"
        "    return x\n"
        "def h(x):\n"
        "    import time\n"
        "    time.time()\n"
        "    return x\n"
        "h = jax.jit(h)\n"
    )
    got = jitpure.run([F("dag_rider_tpu/ops/evil.py", src)], REPO)
    msgs = _msgs(got)
    assert any("print" in m and "f()" in m for m in msgs)
    assert any("os.environ.get" in m and "g()" in m for m in msgs)
    assert any("time.time" in m and "h()" in m for m in msgs)


def test_jitpure_ignores_unjitted_fns_and_other_dirs():
    src = "def f(x):\n    print(x)\n    return x\n"
    assert jitpure.run([F("dag_rider_tpu/ops/ok.py", src)], REPO) == []
    jitted = "import jax\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n"
    # consensus/ is host code — out of jitpure's scope by design
    assert (
        jitpure.run([F("dag_rider_tpu/consensus/x.py", jitted)], REPO) == []
    )


# -- metrics discipline -----------------------------------------------------


def test_metrics_flags_unregistered_counter():
    src = (
        "def f(m):\n"
        "    m.inc('totally_new_counter')\n"
        "    m.counters['another_rogue'] += 1\n"
        "    m.inc('msgs_received')\n"
    )
    got = metricsreg.run([F("dag_rider_tpu/evil.py", src)], REPO)
    msgs = _msgs(got)
    assert any("totally_new_counter" in m for m in msgs)
    assert any("another_rogue" in m for m in msgs)
    assert not any("msgs_received" in m for m in msgs)


# -- allowlist semantics ----------------------------------------------------


def test_allowlist_suppresses_and_reports_stale_entries():
    f1 = Finding("determinism", "a.py", 3, "wall-clock time.time() call")
    allows = [
        Allow("determinism", "a.py", "time.time()", "justified"),
        Allow("determinism", "b.py", "never matches", "stale"),
    ]
    kept, suppressed, unused = apply_allowlist([f1], allows)
    assert kept == [] and suppressed == [f1]
    assert len(unused) == 1 and unused[0].path == "b.py"


# -- race harness -----------------------------------------------------------


@pytest.fixture
def harness():
    installed_here = not races.active()
    if installed_here:
        races.install(auto_guard=False)
    yield races
    races.drain_violations()  # consume what this test planted
    if installed_here:
        races.uninstall()


def test_lock_order_cycle_detected(harness):
    g = races.LockGraph()
    a = races.TrackedLock(g, "siteA")
    b = races.TrackedLock(g, "siteB")
    with a:
        with b:
            pass
    with pytest.raises(races.RaceViolation, match="cycle"):
        with b:
            with a:
                pass


def test_three_lock_cycle_detected(harness):
    g = races.LockGraph()
    a = races.TrackedLock(g, "sA")
    b = races.TrackedLock(g, "sB")
    c = races.TrackedLock(g, "sC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(races.RaceViolation, match="cycle"):
        with c:
            with a:
                pass


def test_nonreentrant_reacquire_raises_reentrant_allowed(harness):
    g = races.LockGraph()
    lk = races.TrackedLock(g, "siteL")
    with pytest.raises(races.RaceViolation, match="re-acquire"):
        with lk:
            lk.acquire()
    rl = races.TrackedRLock(g, "siteR")
    with rl:
        with rl:
            assert rl.held_by_current()
    assert not rl.held_by_current()


def test_unguarded_shared_field_write_raises(harness):
    from dag_rider_tpu.transport.memory import InMemoryTransport

    t = InMemoryTransport()
    races.guard(t)
    t.subscribe(0, lambda m: None)  # mutates under the lock: legal
    with pytest.raises(races.RaceViolation, match="unguarded write"):
        t._handlers[9] = lambda m: None
    with pytest.raises(races.RaceViolation, match="unguarded write"):
        t._queue.append((0, None))
    with pytest.raises(races.RaceViolation, match="unguarded write"):
        t._fanout = []
    # and with the lock held, all of those are legal
    with t._lock:
        t._handlers[9] = lambda m: None
        t._queue.append((0, None))
        t._fanout = [0, 9]


def test_guarded_transport_still_works_end_to_end(harness):
    from dag_rider_tpu.core.types import BroadcastMessage
    from dag_rider_tpu.transport.memory import InMemoryTransport

    t = InMemoryTransport()
    races.guard(t)
    got = []
    t.subscribe(0, got.append)
    t.subscribe(1, got.append)
    t.broadcast(BroadcastMessage(vertex=None, round=0, sender=0))
    t.pump()
    assert len(got) >= 1
    assert races.VIOLATIONS == []


def test_prep_gauges_are_lock_guarded(harness):
    from dag_rider_tpu.verifier.prep import PrepEngine

    eng = PrepEngine(workers=1)
    races.guard(eng)
    with pytest.raises(races.RaceViolation, match="unguarded write"):
        eng.dispatches += 1
    # the engine's own path takes the gauge lock
    eng.run_blocks(lambda lo, hi: None, eng.plan(64))
    assert eng.dispatches == 1
    eng.close()


def test_serialized_method_overlap_raises(harness):
    class SingleOwner:
        def work(self, dwell):
            time.sleep(dwell)
            return "ok"

    obj = SingleOwner()
    races.guard_serial(obj, ("work",))
    assert obj.work(0.0) == "ok"  # plain reuse by one thread

    errs = []
    started = threading.Event()

    def first():
        started.set()
        obj.work(0.3)

    def second():
        started.wait()
        time.sleep(0.05)
        try:
            obj.work(0.0)
        except races.RaceViolation as e:
            errs.append(e)

    t1 = threading.Thread(target=first)
    t2 = threading.Thread(target=second)
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert len(errs) == 1 and "overlap" in str(errs[0])


def test_violations_recorded_for_session_hook(harness):
    g = races.LockGraph()
    lk = races.TrackedLock(g, "siteV")
    with pytest.raises(races.RaceViolation):
        with lk:
            lk.acquire()
    assert any("re-acquire" in v for v in races.drain_violations())
    assert races.drain_violations() == []


# -- the tree itself is clean ----------------------------------------------


def test_driderlint_clean_on_this_repo():
    kept, suppressed, unused = run_static(REPO)
    assert kept == [], "\n".join(str(f) for f in kept)
    assert unused == [], f"stale allowlist entries: {unused}"
    # every suppressed finding is explained
    from dag_rider_tpu.analysis.allowlist import ALLOWS

    assert all(a.reason.strip() for a in ALLOWS)


def test_runner_main_exits_zero_on_this_repo(capsys):
    from dag_rider_tpu.analysis.__main__ import main

    assert main(["--root", REPO]) == 0
    out = capsys.readouterr().out
    assert "clean" in out
