"""Native C++ host component vs the pure-Python oracle.

SURVEY §2a: host-side native code in C++ where the runtime needs it. The
challenge-scalar batch (SHA-512(R||A||M) mod L) is the verify host path's
last per-row loop; the native path must be byte-identical to hashlib and
the verifier must produce identical masks with it on or off.
"""

import hashlib

import numpy as np
import pytest

from dag_rider_tpu.crypto import ed25519
from dag_rider_tpu.utils import native

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native toolchain unavailable"
)


def test_challenge_batch_matches_hashlib():
    rng = np.random.default_rng(0)
    n = 300
    rs = rng.integers(0, 256, size=(n, 32)).astype(np.uint8)
    pks = rng.integers(0, 256, size=(n, 32)).astype(np.uint8)
    msgs = [
        rng.integers(0, 256, size=int(ln)).astype(np.uint8).tobytes()
        for ln in rng.integers(0, 400, size=n)
    ]
    out = native.challenge_batch(rs, pks, msgs)
    for i in range(n):
        k = (
            int.from_bytes(
                hashlib.sha512(
                    rs[i].tobytes() + pks[i].tobytes() + msgs[i]
                ).digest(),
                "little",
            )
            % ed25519.L
        )
        assert out[i].tobytes() == k.to_bytes(32, "little"), f"row {i}"


def test_challenge_batch_extreme_digests():
    """Rows engineered near the reduction's edge: all-0xFF digest inputs
    and empty messages."""
    rs = np.full((4, 32), 0xFF, dtype=np.uint8)
    pks = np.full((4, 32), 0xFF, dtype=np.uint8)
    msgs = [b"", b"\xff" * 500, b"\x00", b"x" * 127]
    out = native.challenge_batch(rs, pks, msgs)
    for i in range(4):
        k = (
            int.from_bytes(
                hashlib.sha512(
                    rs[i].tobytes() + pks[i].tobytes() + msgs[i]
                ).digest(),
                "little",
            )
            % ed25519.L
        )
        assert out[i].tobytes() == k.to_bytes(32, "little")


def test_verifier_masks_identical_native_on_off(monkeypatch):
    import dataclasses

    from dag_rider_tpu.core.types import Block, Vertex, VertexID
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    reg, seeds = KeyRegistry.generate(4)
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(4):
        v = Vertex(
            id=VertexID(1, i),
            block=Block((f"tx{i}".encode(),)),
            strong_edges=(VertexID(0, 0), VertexID(0, 1), VertexID(0, 2)),
        )
        vs.append(signers[i].sign_vertex(v))
    vs.append(dataclasses.replace(vs[1], signature=b"\x11" * 64))
    ver = TPUVerifier(reg)
    monkeypatch.setenv("DAGRIDER_NATIVE", "1")
    with_native = ver.verify_batch(vs)
    monkeypatch.setenv("DAGRIDER_NATIVE", "0")
    without = ver.verify_batch(vs)
    assert with_native == without == [True, True, True, True, False]
