"""Scalar-vs-vector consensus pump equivalence (round 12).

The vectorized pump is an EXECUTION STRATEGY, not a protocol change: for
every schedule the scalar path can see, the vector path must produce
byte-identical per-process delivery sequences (same vertex ids, same
digests, same order). This suite pins that contract three ways:

- unit: the batch codec roundtrips, and every numpy host twin in
  ops/dag_kernels.py agrees with its jitted sibling on random inputs
  (the twins are what the vector drain/ordering actually call on the
  1-core host; the jitted forms remain the device reference);
- transport: pump_grouped preserves per-destination FIFO, treats
  control messages as barriers, and falls back per-message when no
  batch handler is registered;
- end-to-end fuzz: paired simulations (identical seeds, transports,
  adversaries — only cfg.pump differs) across committee sizes, Byzantine
  scenarios from the round-11 suite, and the Bracha RBC stage, compared
  delivery-log to delivery-log.
"""

from __future__ import annotations

import numpy as np
import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus import Process, Simulation
from dag_rider_tpu.consensus.adversary import ByzantineProcess, make_behavior
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport import InMemoryTransport
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport

# ---------------------------------------------------------------------------
# knob plumbing
# ---------------------------------------------------------------------------


def test_pump_defaults_to_scalar(monkeypatch):
    monkeypatch.delenv("DAGRIDER_PUMP", raising=False)
    assert Config(n=4).pump == "scalar"


def test_pump_env_resolution(monkeypatch):
    monkeypatch.setenv("DAGRIDER_PUMP", "vector")
    assert Config(n=4).pump == "vector"


def test_pump_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("DAGRIDER_PUMP", "vector")
    assert Config(n=4, pump="scalar").pump == "scalar"


def test_pump_validation():
    with pytest.raises(ValueError):
        Config(n=4, pump="simd")


# ---------------------------------------------------------------------------
# batch codec
# ---------------------------------------------------------------------------


def _val(sender: int, rnd: int) -> BroadcastMessage:
    v = Vertex(
        id=VertexID(rnd, sender),
        block=Block((f"b{sender}-{rnd}".encode(),)),
        strong_edges=tuple(VertexID(rnd - 1, s) for s in range(3)),
        weak_edges=(VertexID(max(0, rnd - 2), 3),) if rnd > 1 else (),
    )
    return BroadcastMessage(vertex=v, round=rnd, sender=sender)


def test_encode_decode_many_roundtrip():
    msgs = [_val(s, r) for r in (1, 2, 3) for s in range(4)]
    # a control message in the middle: the batch frame is kind-agnostic
    msgs.insert(
        3,
        BroadcastMessage(
            vertex=None,
            round=2,
            sender=1,
            kind="echo",
            origin=0,
            digest=b"\x00" * 32,
        ),
    )
    out = codec.decode_many(codec.encode_many(msgs))
    assert len(out) == len(msgs)
    for a, b in zip(msgs, out):
        assert (a.kind, a.round, a.sender, a.origin, a.digest) == (
            b.kind,
            b.round,
            b.sender,
            b.origin,
            b.digest,
        )
        if a.vertex is None:
            assert b.vertex is None
        else:
            assert a.vertex.id == b.vertex.id
            assert a.vertex.digest() == b.vertex.digest()


def test_encode_decode_many_empty():
    assert codec.decode_many(codec.encode_many([])) == []


def test_decode_many_rejects_trailing_bytes():
    blob = codec.encode_many([_val(0, 1)])
    with pytest.raises(ValueError):
        codec.decode_many(blob + b"x")


def test_decode_many_rejects_bad_magic():
    with pytest.raises(ValueError):
        codec.decode_many(b"XXXX\x00\x00\x00\x00")


# ---------------------------------------------------------------------------
# numpy host twins == jitted kernels
# ---------------------------------------------------------------------------


def test_host_twins_match_jitted_kernels():
    from dag_rider_tpu.ops import dag_kernels as dk

    rng = np.random.default_rng(7)
    n, quorum = 8, 6
    for k in (1, 2, 4):
        stack = rng.random((k, n, n)) < 0.3
        jit_reach = np.asarray(dk.reach_chain(stack))
        np.testing.assert_array_equal(jit_reach, dk.reach_chain_np(stack))
        for hi in range(n):
            np.testing.assert_array_equal(
                np.asarray(dk.leader_reach(stack, hi)),
                dk.leader_reach_np(stack, hi),
            )
    for _ in range(8):
        row = rng.random(n) < 0.7
        assert bool(
            dk.round_complete(row, quorum=quorum)
        ) == dk.round_complete_np(row, quorum=quorum)
        sp = rng.random((5, n)) < 0.6
        np.testing.assert_array_equal(
            np.asarray(dk.strong_edge_quorum(sp, quorum=quorum)),
            dk.strong_edge_quorum_np(sp, quorum=quorum),
        )
        ex = rng.random((6, n)) < 0.5
        wp = rng.random((5, 6, n)) < 0.1
        np.testing.assert_array_equal(
            np.asarray(dk.admission_mask(sp, ex[2], wp, ex)),
            dk.admission_mask_np(sp, ex[2], wp, ex),
        )


# ---------------------------------------------------------------------------
# pump_grouped transport semantics
# ---------------------------------------------------------------------------


def test_pump_grouped_batches_val_runs_and_barriers_controls():
    tp = InMemoryTransport()
    events = []
    tp.subscribe(0, lambda m: events.append(("one", m.kind, m.round)))
    tp.subscribe(1, lambda m: events.append(("other", m.kind, m.round)))
    tp.subscribe_many(
        0, lambda ms: events.append(("batch", [m.round for m in ms]))
    )
    for r in (1, 2):
        tp.enqueue(0, _val(1, r))
    ctrl = BroadcastMessage(
        vertex=None, round=2, sender=1, kind="echo", origin=1, digest=b"d"
    )
    tp.enqueue(0, ctrl)
    tp.enqueue(1, _val(0, 3))  # no batch handler: per-message fallback
    tp.enqueue(0, _val(1, 4))
    assert tp.pump_grouped() == 5
    assert events == [
        ("batch", [1, 2]),  # VAL run, per-dest FIFO preserved
        ("one", "echo", 2),  # control barrier in exact queue position
        ("other", "val", 3),  # fallback path
        ("batch", [4]),
    ]


def test_subscribe_many_requires_existing_subscription():
    tp = InMemoryTransport()
    with pytest.raises(KeyError):
        tp.subscribe_many(0, lambda ms: None)


# ---------------------------------------------------------------------------
# FaultyTransport grouped-pump path (round 13 satellite)
# ---------------------------------------------------------------------------


def _run_faulty(transport, pump: str, *, flushes: int = 0):
    cfg = Config(n=4, coin="round_robin", pump=pump, propose_empty=False)
    kwargs = {"transport": transport} if transport is not None else {}
    sim = Simulation(cfg, **kwargs)
    sim.submit_blocks(per_process=8)
    sim.run(max_messages=40_000)
    for _ in range(flushes):
        transport.flush_delayed()
        sim.run(max_messages=40_000)
    sim.check_agreement()
    return _delivery_logs(sim, range(cfg.n))


def test_faulty_grouped_zero_plan_byte_identical():
    """A delay-free FaultyTransport grows the grouped-pump seam and the
    fan-out sentinel forward; under an all-zero plan the vector run is
    byte-identical to one over a bare InMemoryTransport."""
    tp = FaultyTransport(FaultPlan(seed=3))
    assert callable(getattr(tp, "pump_grouped", None))
    wrapped = _run_faulty(tp, "vector")
    plain = _run_faulty(None, "vector")
    assert any(wrapped)
    assert wrapped == plain
    # the sentinel write-through reached the inner transport
    assert tp.fanout_sentinel is True
    assert tp.inner.fanout_sentinel is True


def test_faulty_grouped_duplicate_plan_live():
    """Delay-free fault plans ride the grouped path: rolls land per
    message inside the batch wrapper, stats count them, and dedup keeps
    agreement byte-identical across processes."""
    tp = FaultyTransport(FaultPlan(duplicate=0.3, seed=5))
    assert callable(getattr(tp, "pump_grouped", None))
    logs = _run_faulty(tp, "vector")
    assert any(logs)
    assert tp.stats["duplicated"] > 0


def test_faulty_delay_plan_falls_back_byte_identical():
    """Fallback contract: a plan that can HOLD a message never grows
    pump_grouped (the Simulation's callable-probe then picks per-message
    pumping), and the vector run's delivery log equals the scalar run's
    under the same plan and seed — same rolls, same schedule, same
    bytes."""
    tp_vec = FaultyTransport(FaultPlan(delay=0.2, seed=9))
    assert getattr(tp_vec, "pump_grouped", None) is None
    vec = _run_faulty(tp_vec, "vector", flushes=8)
    assert tp_vec.stats["delayed"] > 0
    tp_sca = FaultyTransport(FaultPlan(delay=0.2, seed=9))
    sca = _run_faulty(tp_sca, "scalar", flushes=8)
    assert any(vec)
    assert vec == sca


def test_faulty_wan_topology_falls_back():
    from dag_rider_tpu.transport.faults import WanTopology

    tp = FaultyTransport(
        FaultPlan(seed=1), topology=WanTopology.regions(4)
    )
    assert getattr(tp, "pump_grouped", None) is None


# ---------------------------------------------------------------------------
# end-to-end equivalence fuzz
# ---------------------------------------------------------------------------


def _delivery_logs(sim: Simulation, honest) -> list:
    return [
        [(v.id, v.digest()) for v in sim.deliveries[i]] for i in honest
    ]


def _run_clean(n: int, seed: int, pump: str, *, rbc: bool, target: int):
    cfg = Config(
        n=n, coin="round_robin", propose_empty=True, gc_depth=24, pump=pump
    )
    sim = Simulation(cfg, rbc=rbc)
    for i in range(n):
        for k in range(2):
            sim.processes[i].submit(
                Block((f"s{seed}-p{i}-b{k}".encode().ljust(32, b"."),))
            )
    chunk = n * (n - 1) * (2 * n if rbc else 1)
    for _ in range(100 * target):
        sim.run(max_messages=chunk)
        if max(p.round for p in sim.processes) >= target:
            break
    else:
        raise AssertionError("failed to reach target round")
    sim.check_agreement()
    return _delivery_logs(sim, range(n))


@pytest.mark.parametrize("n", [4, 16, 32])
@pytest.mark.parametrize("seed", [0, 1])
def test_clean_equivalence(n, seed):
    target = 12 if n == 4 else 8
    scalar = _run_clean(n, seed, "scalar", rbc=False, target=target)
    vector = _run_clean(n, seed, "vector", rbc=False, target=target)
    assert any(scalar)  # non-vacuous: something was delivered
    assert scalar == vector


@pytest.mark.parametrize("seed", [0, 1])
def test_clean_equivalence_under_rbc(seed):
    scalar = _run_clean(4, seed, "scalar", rbc=True, target=12)
    vector = _run_clean(4, seed, "vector", rbc=True, target=12)
    assert any(scalar)
    assert scalar == vector


def _run_adversary(
    n: int, seed: int, pump: str, adversary: str, *, rbc: bool, cycles: int
):
    """Mirror of scenarios.run_scenario's core loop with cfg.pump pinned:
    seeded behaviors at the low f indices, a seeded fault transport, a
    fixed virtual-time schedule — only the pump flavor differs between
    the paired calls, so the delivery logs must match byte for byte."""
    cfg = Config(
        n=n,
        propose_empty=True,
        pump=pump,
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
    )
    byz = tuple(range(cfg.f))
    behaviors = {
        i: make_behavior(adversary, seed=seed + 1000 + i) for i in byz
    }
    tp = FaultyTransport(FaultPlan(seed=seed))

    def factory(pcfg, i, ptp, **kwargs):
        if i in behaviors:
            return ByzantineProcess(
                pcfg, i, ptp, behavior=behaviors[i], **kwargs
            )
        return Process(pcfg, i, ptp, **kwargs)

    sim = Simulation(cfg, transport=tp, rbc=rbc, process_factory=factory)
    honest = [i for i in range(n) if i not in set(byz)]
    for i in honest:
        for k in range(2):
            sim.processes[i].submit(
                Block((f"s{seed}-p{i}-b{k}".encode().ljust(32, b"."),))
            )
    chunk = 2 * n * n * (2 * n if rbc else 1)
    for _ in range(cycles):
        if sim.run(max_messages=chunk) == 0:
            for _ in range(cfg.sync_patience or 4):
                sim.run(max_messages=chunk)
        tp.advance(0.01)
    for _ in range(6):
        tp.flush_delayed()
        sim.run(max_messages=2 * chunk)
    return _delivery_logs(sim, honest)


@pytest.mark.parametrize(
    "adversary",
    [
        "equivocate",
        "withhold",
        "invalid_edges",
        "garbage_coin",
        "equivocate_split",
    ],
)
@pytest.mark.parametrize("seed", [0, 1])
def test_adversary_equivalence(adversary, seed):
    scalar = _run_adversary(
        4, seed, "scalar", adversary, rbc=False, cycles=36
    )
    vector = _run_adversary(
        4, seed, "vector", adversary, rbc=False, cycles=36
    )
    assert any(scalar)
    assert scalar == vector


def test_adversary_equivalence_under_rbc():
    scalar = _run_adversary(
        4, 0, "scalar", "equivocate", rbc=True, cycles=36
    )
    vector = _run_adversary(
        4, 0, "vector", "equivocate", rbc=True, cycles=36
    )
    assert any(scalar)
    assert scalar == vector
