"""Ed25519 host implementation tests, anchored to RFC 8032 test vectors."""

import pytest

from dag_rider_tpu.crypto import ed25519


# RFC 8032 §7.1 test vectors (TEST 1-3).
RFC_VECTORS = [
    {
        "seed": "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "pub": "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "msg": "",
        "sig": (
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
            "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
        ),
    },
    {
        "seed": "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "pub": "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "msg": "72",
        "sig": (
            "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
            "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
        ),
    },
    {
        "seed": "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "pub": "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "msg": "af82",
        "sig": (
            "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
            "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
        ),
    },
]


@pytest.mark.parametrize("vec", RFC_VECTORS, ids=["test1", "test2", "test3"])
def test_rfc8032_vectors(vec):
    seed = bytes.fromhex(vec["seed"])
    msg = bytes.fromhex(vec["msg"])
    _, pub = ed25519.generate_keypair(seed)
    assert pub == bytes.fromhex(vec["pub"])
    sig = ed25519.sign(seed, msg)
    assert sig == bytes.fromhex(vec["sig"])
    assert ed25519.verify(pub, msg, sig)


def test_verify_rejects_wrong_message_and_key():
    seed, pub = ed25519.generate_keypair(b"\x01" * 32)
    sig = ed25519.sign(seed, b"hello")
    assert ed25519.verify(pub, b"hello", sig)
    assert not ed25519.verify(pub, b"hellp", sig)
    _, other = ed25519.generate_keypair(b"\x02" * 32)
    assert not ed25519.verify(other, b"hello", sig)


def test_verify_rejects_tampered_signature():
    seed, pub = ed25519.generate_keypair(b"\x03" * 32)
    sig = ed25519.sign(seed, b"msg")
    bad = bytes([sig[0] ^ 1]) + sig[1:]
    assert not ed25519.verify(pub, b"msg", bad)
    # malleability: s >= L rejected
    s = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + int.to_bytes(s + ed25519.L, 32, "little")
    assert not ed25519.verify(pub, b"msg", mall)


def test_verify_rejects_garbage_inputs():
    assert not ed25519.verify(b"\x00" * 32, b"m", b"\x00" * 64)
    assert not ed25519.verify(b"\x00" * 31, b"m", b"\x00" * 64)
    assert not ed25519.verify(b"\xff" * 32, b"m", b"\xff" * 64)
    assert not ed25519.verify(b"\x00" * 32, b"m", b"\x00" * 63)


def test_point_ops_consistency():
    B = ed25519.B
    assert ed25519.on_curve(B)
    two_b = ed25519.point_double(B)
    assert ed25519.on_curve(two_b)
    assert ed25519.point_equal(two_b, ed25519.point_add(B, B))
    # [L]B == identity (B generates the prime-order subgroup)
    assert ed25519.point_equal(
        ed25519.scalar_mult(ed25519.L, B), ed25519.IDENTITY
    )
    # compress/decompress roundtrip
    for k in (1, 2, 7, 12345):
        pt = ed25519.scalar_mult(k, B)
        assert ed25519.point_equal(
            ed25519.point_decompress(ed25519.point_compress(pt)), pt
        )
    # negation: P + (-P) == identity
    assert ed25519.point_equal(
        ed25519.point_add(B, ed25519.point_neg(B)), ed25519.IDENTITY
    )


def test_verify_precomputed_matches_full():
    import hashlib

    seed, pub = ed25519.generate_keypair(b"\x04" * 32)
    msg = b"split-path"
    sig = ed25519.sign(seed, msg)
    k = (
        int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
        )
        % ed25519.L
    )
    assert ed25519.verify_precomputed(pub, k, sig)
    assert not ed25519.verify_precomputed(pub, (k + 1) % ed25519.L, sig)


def test_fixed_base_comb_matches_ladder():
    """scalar_mult_base (the signing hot path's comb) is the same group
    element as the double-and-add ladder for edge and random scalars —
    including scalars at/above L, 2^255-1, and the >=2^256 ladder
    fallback."""
    import random

    rng = random.Random(5)
    cases = [0, 1, 2, 15, 16, ed25519.L - 1, ed25519.L, ed25519.L + 7,
             2**255 - 1, 2**256, 2**256 + 3] + [
        rng.randrange(0, 2**256) for _ in range(40)
    ]
    for s in cases:
        want = ed25519.scalar_mult(s, ed25519.B)
        got = ed25519.scalar_mult_base(s)
        assert ed25519.point_equal(got, want), s
        assert ed25519.point_compress(got) == ed25519.point_compress(want), s
