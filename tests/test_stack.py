"""Stack tests — counterpart of reference ``stack/stack_test.go`` plus the
empty-pop guard the reference lacks (SURVEY.md D11)."""

import pytest

from dag_rider_tpu.core import Stack


def test_push_pop_roundtrip():
    s = Stack()
    assert s.is_empty()
    s.push(1)
    s.push(2)
    s.push(3)
    assert not s.is_empty()
    assert len(s) == 3
    assert s.pop() == 3
    assert s.pop() == 2
    assert s.pop() == 1
    assert s.is_empty()


def test_pop_empty_raises():
    s = Stack()
    with pytest.raises(IndexError):
        s.pop()
    with pytest.raises(IndexError):
        s.peek()


def test_iter_is_pop_order():
    s = Stack()
    for i in range(5):
        s.push(i)
    assert list(s) == [4, 3, 2, 1, 0]
    assert len(s) == 5  # iteration does not consume


def test_generic_over_objects():
    s = Stack()
    s.push(("vertex", 1))
    s.push(("vertex", 2))
    assert s.peek() == ("vertex", 2)
    assert s.pop() == ("vertex", 2)
