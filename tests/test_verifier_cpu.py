"""Signed consensus pipeline with the CPU Verifier (BASELINE config #2
shape: Ed25519-signed vertices, batched verification, D10 fixed)."""

import dataclasses

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import Simulation
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.verifier import CPUVerifier, KeyRegistry, VertexSigner


def build_signed_sim(n=4):
    cfg = Config(n=n, signature_scheme="ed25519")
    registry, seeds = KeyRegistry.generate(n)
    sim = Simulation(
        cfg,
        verifier_factory=lambda i: CPUVerifier(registry),
        signer_factory=lambda i: VertexSigner(seeds[i]),
    )
    return sim, registry, seeds


def test_signed_pipeline_reaches_agreement():
    sim, _, _ = build_signed_sim()
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=1200)
    sim.check_agreement()
    p0 = sim.processes[0]
    assert p0.metrics.counters["waves_decided"] >= 1
    # every admitted remote vertex went through a verify batch
    assert sum(p0.metrics.verify_batch_sizes) == p0.metrics.counters[
        "vertices_admitted"
    ]
    assert p0.metrics.sigs_per_sec() > 0


def test_forged_vertex_rejected():
    """A vertex signed by the wrong key (or unsigned) must never enter the
    DAG — the authentication the reference lacks entirely (D10). After a
    rejection the id leaves the pending set, so the genuine copy is
    re-verified (not deduped) and admitted."""
    sim, registry, seeds = build_signed_sim()
    p0 = sim.processes[0]
    p0.start()
    edges = tuple(VertexID(0, i) for i in range(3))
    v = Vertex(id=VertexID(1, 1), strong_edges=edges)
    # unsigned
    p0.on_message(BroadcastMessage(vertex=v, round=1, sender=1))
    # signed by the wrong key (source 2's key on source 1's vertex)
    wrong = VertexSigner(seeds[2]).sign_vertex(v)
    p0.on_message(BroadcastMessage(vertex=wrong, round=1, sender=1))
    assert not p0.dag.present(VertexID(1, 1))
    assert p0.metrics.counters["msgs_rejected_signature"] == 2
    # correctly signed version now accepted by the same process
    good = VertexSigner(seeds[1]).sign_vertex(v)
    p0.on_message(BroadcastMessage(vertex=good, round=1, sender=1))
    assert p0.dag.present(VertexID(1, 1))


def test_tampered_payload_rejected():
    """Flipping the block payload after signing invalidates the vertex."""
    sim, registry, seeds = build_signed_sim()
    p0 = sim.processes[0]
    p0.start()
    edges = tuple(VertexID(0, i) for i in range(3))
    v = VertexSigner(seeds[1]).sign_vertex(
        Vertex(id=VertexID(1, 1), block=Block((b"real",)), strong_edges=edges)
    )
    tampered = dataclasses.replace(v, block=Block((b"fake",)))
    p0.on_message(BroadcastMessage(vertex=tampered, round=1, sender=1))
    assert not p0.dag.present(VertexID(1, 1))
    assert p0.metrics.counters["msgs_rejected_signature"] == 1
