"""Catch-up sync (anti-entropy) — elastic recovery, SURVEY §5.

A process that joins (or rejoins) after the cluster has advanced holds
buffered vertices whose predecessors nobody re-broadcasts. These tests
build that exact situation: run a 3-quorum of a 4-node committee to round
~10, then attach the 4th process cold and assert it syncs, catches up,
and reaches the same delivered prefix — with and without the Bracha RBC
stage in the path.
"""

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.transport.rbc import RbcTransport


def _run_quorum_then_join(rbc: bool):
    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=False,
        sync_patience=3,
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
    )
    broker = InMemoryTransport()
    delivered = [[] for _ in range(4)]

    def build(i):
        tp = RbcTransport(broker, i, cfg.n, cfg.f) if rbc else broker
        return Process(cfg, i, tp, on_deliver=delivered[i].append)

    # only processes 0..2 exist at first — process 3 is "down"
    procs = [build(i) for i in range(3)]
    for p in procs:
        p.defer_steps = True
        for k in range(12):
            p.submit(Block((f"p{p.index}-b{k}".encode(),)))
    for p in procs:
        p.start()
    for _ in range(200):
        moved = broker.pump(10_000)
        for p in procs:
            p.step()
        if moved == 0:
            break
    assert procs[0].round >= 8, "quorum failed to advance without node 3"
    assert any(p.metrics.counters["waves_decided"] >= 1 for p in procs)

    # node 3 rejoins cold: it subscribes now, so it missed every earlier
    # broadcast. The cluster keeps working (fresh client blocks), so its
    # new vertices reach node 3 referencing rounds node 3 lacks — the
    # exact stuck-buffer state sync exists to resolve.
    late = build(3)
    late.defer_steps = True
    for p in procs:
        for k in range(8):
            p.submit(Block((f"p{p.index}-late-{k}".encode(),)))
    # enough blocks that the late joiner's own proposals can track the
    # cluster's head (round advancement consumes one block per round
    # with propose_empty=False)
    for k in range(24):
        late.submit(Block((f"p3-late-{k}".encode(),)))
    late.start()
    procs.append(late)
    for _ in range(400):
        moved = broker.pump(10_000)
        for p in procs:
            p.step()
        if moved == 0 and not late.buffer and late.round >= procs[0].round - 1:
            break
    return cfg, procs, delivered, late


@pytest.mark.parametrize("rbc", [False, True], ids=["plain", "rbc"])
def test_late_joiner_catches_up(rbc):
    cfg, procs, delivered, late = _run_quorum_then_join(rbc)
    # the sync machinery actually fired on both sides
    assert late.metrics.counters["sync_requested"] >= 1
    assert any(p.metrics.counters.get("sync_served", 0) > 0 for p in procs[:3])
    # the laggard caught up to the cluster's round and delivered vertices
    assert late.round >= procs[0].round - 1, (late.round, procs[0].round)
    assert late.metrics.counters["vertices_delivered"] > 0
    # agreement: late's delivered prefix matches an up-to-date process's
    a = [(v.id.round, v.id.source, v.digest()) for v in delivered[3]]
    b = [(v.id.round, v.id.source, v.digest()) for v in delivered[0]]
    k = min(len(a), len(b))
    assert k > 0 and a[:k] == b[:k]


def test_sync_serve_is_rate_limited_not_wedged():
    """Serve throttling is a per-requester cooldown: replayed (or
    window-rotated) requests inside the window are throttled, but the
    budget recovers with time — a lost response can always be re-asked
    (no lifetime cap to exhaust)."""
    cfg = Config(n=4, coin="round_robin", sync_window=4, sync_serve_cooldown_s=30.0)
    broker = InMemoryTransport()
    p = Process(cfg, 0, broker)
    p.submit(Block((b"x",)))
    p.start()
    for r in range(1, 4):
        for s in range(1, 4):
            v = Vertex(
                id=VertexID(r, s),
                strong_edges=tuple(VertexID(r - 1, t) for t in range(3)),
            )
            p.on_message(BroadcastMessage(vertex=v, round=r, sender=s))
    served0 = p.metrics.counters.get("sync_served", 0)
    for lo in (1, 2, 3, 1, 1, 2):  # replays AND window rotation
        p.on_message(
            BroadcastMessage(vertex=None, round=lo, sender=2, kind="sync", origin=lo + 2)
        )
    assert p.metrics.counters["sync_throttled"] == 5
    served_once = p.metrics.counters["sync_served"] - served0
    assert served_once > 0  # exactly one window served
    # cooldown elapses -> the same requester can be served again
    p._sync_last_serve[2] -= 31.0
    p.on_message(
        BroadcastMessage(vertex=None, round=1, sender=2, kind="sync", origin=3)
    )
    assert p.metrics.counters["sync_served"] > served0 + served_once
    # junk requester ids are ignored entirely
    p.on_message(
        BroadcastMessage(vertex=None, round=1, sender=99, kind="sync", origin=3)
    )
    assert 99 not in p._sync_last_serve


def test_sync_window_clamps_response():
    cfg = Config(n=4, coin="round_robin", sync_window=2, sync_serve_cooldown_s=0.0)
    broker = InMemoryTransport()
    got = []
    broker.subscribe(1, got.append)
    p = Process(cfg, 0, broker)
    p.submit(Block((b"x",)))
    p.start()
    for r in range(1, 6):
        for s in range(1, 4):
            v = Vertex(
                id=VertexID(r, s),
                strong_edges=tuple(VertexID(r - 1, t) for t in range(3)),
            )
            p.on_message(BroadcastMessage(vertex=v, round=r, sender=s))
    broker.pump()  # flush p's own startup proposal
    got.clear()
    p.on_message(
        BroadcastMessage(vertex=None, round=1, sender=1, kind="sync", origin=100)
    )
    broker.pump()
    served = [m for m in got if m.kind == "val"]
    assert served, "no vertices served"
    assert {m.vertex.round for m in served} <= {1, 2}  # window clamp


def test_lost_round_broadcasts_recovered_via_sync():
    """Liveness under total message loss of a round: every node's round-1
    broadcast is dropped, so all buffers are EMPTY while everyone stalls
    waiting for quorum — the empty-buffer trigger + own-round window must
    re-circulate the lost vertices."""
    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=False,
        sync_patience=3,
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
    )
    broker = InMemoryTransport()
    delivered = [[] for _ in range(4)]
    procs = [
        Process(cfg, i, broker, on_deliver=delivered[i].append)
        for i in range(4)
    ]
    for p in procs:
        p.defer_steps = True
        for k in range(8):
            p.submit(Block((f"p{p.index}-b{k}".encode(),)))
    for p in procs:
        p.start()
    # drop every round-1 broadcast: each node now has only its own
    # round-1 vertex; nobody can reach quorum and nothing is buffered
    lost = len(broker.drain_pending())
    assert lost >= 12  # 4 broadcasts x 3 receivers
    assert all(p.dag.round_size(1) == 1 for p in procs)
    for _ in range(100):
        moved = broker.pump(10_000)
        for p in procs:
            p.step()
        if moved == 0 and all(p.round >= 8 for p in procs):
            break
    assert all(p.round >= 8 for p in procs), [p.round for p in procs]
    assert all(p.metrics.counters["sync_requested"] >= 1 for p in procs)
    assert all(len(d) > 0 for d in delivered)
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in d] for d in delivered
    ]
    k = min(len(l) for l in logs)
    assert all(l[:k] == logs[0][:k] for l in logs)


def test_idle_node_with_future_buffer_does_not_spam_sync():
    """A node that is missing nothing (buffered vertices are future-round
    with all predecessors present) and has no client blocks must not
    request sync — there is nothing sync could provide."""
    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=False,
        sync_patience=2,
        sync_request_cooldown_s=0.0,
    )
    broker = InMemoryTransport()
    procs = [Process(cfg, i, broker) for i in range(4)]
    for p in procs[:3]:
        p.submit(Block((f"p{p.index}".encode(),)))
    for p in procs:
        p.defer_steps = True
        p.start()  # node 3 stays at round 0: no blocks
    for _ in range(50):
        broker.pump(10_000)
        for p in procs:
            p.step()
    assert procs[3].round == 0 and procs[3].buffer  # future vertices held
    assert procs[3].metrics.counters.get("sync_requested", 0) == 0


def test_throttled_pump_does_not_trigger_sync_storm():
    """Regression (round 11): a chunk-limited pump delivers below the
    offered load, so every process sits with queued client blocks and an
    incomplete current round — the exact "waiting" shape that used to
    read as a partition once sync_patience elapsed, at which point all n
    processes broadcast requests whose vertex re-serves amplified n^2
    into a re-serve storm (the round-10 load drivers pinned
    sync_patience=0 to dodge it). The backlog-aware gate in
    Process._maybe_request_sync must recognize undelivered transport
    backlog as "throttled, not partitioned": zero sync requests, clean
    agreement, normal progress — with a hair-trigger patience."""
    from dag_rider_tpu.consensus.simulator import Simulation

    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=True,
        sync_patience=4,  # tighter than the default 8: the gate does the work
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
    )
    sim = Simulation(cfg)
    sim.submit_blocks(16)
    for _ in range(150):
        sim.run(max_messages=3)  # starvation-level throttle (< one round)
    assert (
        sum(
            p.metrics.counters.get("sync_requested", 0)
            for p in sim.processes
        )
        == 0
    )
    sim.check_agreement()
    assert max(p.round for p in sim.processes) >= 5
