"""Aggregated round certificates (ISSUE 9 / round 13).

The certificate fast path is an EXECUTION STRATEGY for the verify seam,
not a protocol change: a round admitted through one aggregate BLS check
must produce the exact delivery log the per-vertex oracle produces, and
a Byzantine aggregator must cost a round its fast path, never its
liveness or its safety. This suite pins that four ways:

- crypto: ``multi_pairing_check`` agrees with the reference
  ``pairing_check`` on accept AND reject; the device/host MSM seams sum
  to the same point;
- unit: CertVerifier assembly/verification roundtrips, verdict
  memoization, and rejection of every crafted defect — bad bitmap,
  forged aggregate, substituted digests, malformed points;
- wire: DRv1 stays byte-stable for cert-less vertices, DRv2 carries the
  share, certificates roundtrip alone and inside "cert" messages;
- end-to-end: cert-on and cert-off paired runs deliver byte-identical
  logs (ids + digests) across committee sizes and both pump flavors,
  with the signature-op books showing the saved verifies; injected bad
  certificates and a silent aggregator degrade the round onto the
  per-vertex ladder.
"""

from __future__ import annotations

import dataclasses

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import (
    Block,
    BroadcastMessage,
    RoundCertificate,
    Vertex,
    VertexID,
)
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.verifier.base import CertSigner, KeyRegistry
from dag_rider_tpu.verifier.cert import CertVerifier

# ---------------------------------------------------------------------------
# knob plumbing
# ---------------------------------------------------------------------------


def test_cert_defaults_off(monkeypatch):
    monkeypatch.delenv("DAGRIDER_CERT", raising=False)
    assert Config(n=4).cert == "off"


def test_cert_env_resolution(monkeypatch):
    monkeypatch.setenv("DAGRIDER_CERT", "agg")
    assert Config(n=4).cert == "agg"


def test_cert_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("DAGRIDER_CERT", "agg")
    assert Config(n=4, cert="off").cert == "off"


def test_cert_validation():
    with pytest.raises(ValueError):
        Config(n=4, cert="maybe")
    with pytest.raises(ValueError):
        Config(n=4, cert_patience=0)


# ---------------------------------------------------------------------------
# crypto pins
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cert_keys():
    reg, _seeds, sks = KeyRegistry.generate_with_cert(4)
    return reg, sks


def _entries(sks, digests):
    return [
        (i, d, CertSigner(sk).sign_digest(d))
        for i, (sk, d) in enumerate(zip(sks, digests))
    ]


def _digests(tag: bytes, k: int = 4):
    return [bytes([i]) * 16 + tag.ljust(16, b".") for i in range(k)]


def test_multi_pairing_check_matches_reference(cert_keys):
    reg, sks = cert_keys
    digests = _digests(b"mpc")
    sigs = [bls.sign(sk, d) for sk, d in zip(sks, digests)]
    agg = bls.g1_sum([bls.g1_decompress(s) for s in sigs])
    pairs = [(agg, bls.g2_neg(bls.G2_GEN))] + [
        (bls.hash_to_g1(d), reg.bls_key_of(i))
        for i, d in enumerate(digests)
    ]
    assert bls.multi_pairing_check(pairs) is True
    assert bls.pairing_check(pairs) is True
    # one substituted message flips BOTH checks the same way
    bad = list(pairs)
    bad[1] = (bls.hash_to_g1(b"not-what-was-signed"), reg.bls_key_of(0))
    assert bls.multi_pairing_check(bad) is False
    assert bls.pairing_check(bad) is False


def test_msm_seams_agree_on_aggregate():
    from dag_rider_tpu.ops import bls_msm

    pts = [bls.g1_mul(k + 3) for k in range(5)]
    host = bls.g1_sum(pts)
    assert bls_msm.sum_points(pts) == host
    assert bls.g1_compress(host) == bls.g1_compress(
        bls_msm.sum_points(list(reversed(pts)))
    )


# ---------------------------------------------------------------------------
# CertVerifier unit
# ---------------------------------------------------------------------------


def test_certificate_roundtrip_and_memoization(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    digests = _digests(b"rt")
    cert = cv.make_certificate(7, _entries(sks, digests)[:3])
    assert cert is not None and cert.round == 7
    assert cert.signers == (0, 1, 2)
    assert cv.verify_certificate(cert) is True
    assert cv.stats["certs_valid"] == 1
    # in-process sharing: the second ask is a dict hit, not a pairing
    assert cv.verify_certificate(cert) is True
    assert cv.stats["verdict_hits"] == 1
    assert cv.stats["certs_checked"] == 2


def test_certificate_below_quorum_refused(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    assert cv.make_certificate(1, _entries(sks, _digests(b"q"))[:2]) is None


@pytest.mark.parametrize(
    "mutate",
    [
        # bad bitmap: claims a signer that never signed (share swapped
        # onto another index)
        lambda c: dataclasses.replace(c, signers=(0, 1, 3)),
        # bad bitmap: structurally broken lists
        lambda c: dataclasses.replace(c, signers=(0, 1, 1)),
        lambda c: dataclasses.replace(c, signers=(0, 1, 9)),
        lambda c: dataclasses.replace(c, signers=(0, 1)),
        # stale digests: one vertex substituted after aggregation
        lambda c: dataclasses.replace(
            c, digests=(c.digests[0], b"stale-digest!".ljust(32, b"?"), c.digests[2])
        ),
        # forged aggregate: a valid G1 point nobody's shares sum to
        lambda c: dataclasses.replace(
            c, agg_sig=bls.g1_compress(bls.g1_mul(0xBAD))
        ),
        # malformed aggregate bytes
        lambda c: dataclasses.replace(c, agg_sig=b"\xff" * 48),
    ],
)
def test_byzantine_certificate_always_detected(cert_keys, mutate):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    cert = cv.make_certificate(3, _entries(sks, _digests(b"byz"))[:3])
    assert cv.verify_certificate(cert) is True
    forged = mutate(cert)
    assert cv.verify_certificate(forged) is False
    assert cv.stats["certs_invalid"] == 1
    # a defect never raises and never poisons the good verdict
    assert cv.verify_certificate(cert) is True


def test_cert_verifier_requires_bls_registry():
    reg, _ = KeyRegistry.generate(4)
    with pytest.raises(ValueError):
        CertVerifier(reg, quorum=3)


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def _vertex(rnd=2, src=1, cert_sig=None):
    return Vertex(
        id=VertexID(rnd, src),
        block=Block((b"blk",)),
        strong_edges=tuple(VertexID(rnd - 1, s) for s in range(3)),
        cert_sig=cert_sig,
    )


def test_vertex_codec_stays_drv1_without_share():
    blob = codec.encode_vertex(_vertex())
    assert blob.startswith(b"DRv1")
    v, _ = codec.decode_vertex(blob)
    assert v.cert_sig is None and v.digest() == _vertex().digest()


def test_vertex_codec_drv2_carries_share(cert_keys):
    _, sks = cert_keys
    sig = CertSigner(sks[0]).sign_digest(_vertex().digest())
    v = _vertex(cert_sig=sig)
    blob = codec.encode_vertex(v)
    assert blob.startswith(b"DRv2")
    out, _ = codec.decode_vertex(blob)
    assert out.cert_sig == sig
    # the share rides OUTSIDE the signed bytes: digests agree across
    # wire forms, so cert-on and cert-off clusters hash identically
    assert out.digest() == _vertex().digest()


def test_certificate_and_cert_message_roundtrip(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    cert = cv.make_certificate(5, _entries(sks, _digests(b"wire"))[:3])
    out, _ = codec.decode_certificate(codec.encode_certificate(cert))
    assert out == cert
    msg = BroadcastMessage(
        vertex=None, round=5, sender=1, kind="cert", cert=cert
    )
    got = codec.decode_message(codec.encode_message(msg))[0]
    assert got.kind == "cert" and got.cert == cert
    batch = codec.decode_many(codec.encode_many([msg]))
    assert batch[0].cert == cert


# ---------------------------------------------------------------------------
# end-to-end: agg == per-vertex, byte for byte
# ---------------------------------------------------------------------------


def _run_cert(n, seed, *, cert, pump="vector", blocks=6):
    cfg = Config(
        n=n, coin="round_robin", propose_empty=False, pump=pump
    )
    sim = Simulation(cfg, verifier="cpu", cert=cert)
    for i in range(n):
        for k in range(blocks):
            sim.processes[i].submit(
                Block((f"s{seed}-p{i}-b{k}".encode().ljust(32, b"."),))
            )
    sim.run(max_messages=400_000)
    sim.check_agreement()
    logs = [
        [(v.id, v.digest()) for v in sim.deliveries[i]] for i in range(n)
    ]
    return logs, sim


@pytest.mark.parametrize(
    "n,seed",
    [(4, 0), (4, 1), (16, 0), pytest.param(32, 0, marks=pytest.mark.slow)],
)
def test_agg_commit_order_identical(n, seed):
    """Acceptance: certificate-admitted rounds commit the exact log the
    per-vertex oracle commits — same ids, same digests, same order —
    while the cluster verifies a fraction of the signatures."""
    agg_logs, agg_sim = _run_cert(n, seed, cert=True)
    ref_logs, ref_sim = _run_cert(n, seed, cert=False)
    assert any(agg_logs)
    assert agg_logs == ref_logs
    snaps = [p.metrics.snapshot() for p in agg_sim.processes]
    assert sum(s.get("certs_assembled", 0) for s in snaps) > 0
    assert sum(s.get("sigs_saved", 0) for s in snaps) > 0
    assert all(s.get("certs_rejected", 0) == 0 for s in snaps)
    agg_sigs = sum(s.get("verify_sigs_total", 0) for s in snaps)
    ref_sigs = sum(
        p.metrics.snapshot().get("verify_sigs_total", 0)
        for p in ref_sim.processes
    )
    assert agg_sigs < ref_sigs
    # cert-off snapshots stay free of cert gauges
    off = ref_sim.processes[0].metrics.snapshot()
    assert "cert_fastpath_fraction" not in off


def test_agg_equivalent_under_scalar_pump():
    agg_logs, _ = _run_cert(4, 2, cert=True, pump="scalar")
    ref_logs, _ = _run_cert(4, 2, cert=False, pump="scalar")
    assert any(agg_logs)
    assert agg_logs == ref_logs


def test_cert_fastpath_gauges_surface():
    _, sim = _run_cert(4, 3, cert=True)
    snap = sim.processes[1].metrics.snapshot()
    for key in (
        "certs_verified",
        "cert_fastpath_fraction",
        "sigs_saved",
        "certs_rejected",
        "cert_timeouts",
    ):
        assert key in snap
    assert 0.0 <= snap["cert_fastpath_fraction"] <= 1.0
    assert snap["cert_fastpath_fraction"] > 0


def test_cert_mode_requires_named_verifier():
    # an explicit ctor request on a keyless sim is a hard error ...
    with pytest.raises(ValueError):
        Simulation(Config(n=4), cert=True)


def test_cert_knob_on_keyless_sim_degrades_to_off():
    # ... but the knob (Config(cert="agg") / DAGRIDER_CERT=agg, as the
    # tier1-agg CI lane sets) must not break sims with no signature
    # machinery: they fall back to the reference per-vertex path.
    sim = Simulation(Config(n=4, cert="agg"))
    assert sim.cfg.cert == "off"
    assert sim.cert_verifier is None
    assert all(not p._cert for p in sim.processes)


# ---------------------------------------------------------------------------
# Byzantine aggregator at the process seam
# ---------------------------------------------------------------------------


def _cert_msg(cert, sender=0):
    return BroadcastMessage(
        vertex=None, round=cert.round, sender=sender, kind="cert", cert=cert
    )


def test_forged_certificate_rejected_and_round_degraded():
    """A forged aggregate from the wire is rejected by the aggregate
    check and the covered round falls back onto the per-vertex path:
    pooled vertices re-queue for individual verification, the books say
    rejected + degraded, and the process keeps delivering."""
    _, sim = _run_cert(4, 4, cert=True)
    p = sim.processes[1]
    r = p.round + 2
    if r % 4 == p.index:  # pick a round this process does NOT aggregate
        r += 1
    pooled = _vertex(rnd=r, src=(p.index + 1) % 4)
    p._cert_pool[r] = {pooled.id.source: pooled}
    forged = RoundCertificate(
        round=r,
        signers=(0, 1, 2),
        digests=tuple(_digests(b"forged", 3)),
        agg_sig=bls.g1_compress(bls.g1_mul(0xBAD)),
    )
    before = p.metrics.counters.get("certs_rejected", 0)
    # apply without stepping so the re-queued vertex is observable
    # before the verify queue drains it
    assert p._apply_certificate(forged) is False
    assert p.metrics.counters["certs_rejected"] == before + 1
    assert p.metrics.counters["cert_rounds_degraded"] >= 1
    assert r not in p._cert_pool and r in p._cert_done
    assert any(v.id == pooled.id for v in p._pending_verify)
    # replays of the same junk are now ignored, not re-checked
    checked = sim.cert_verifier.stats["certs_checked"]
    p._on_certificate(_cert_msg(forged))
    assert p.metrics.counters["certs_ignored"] >= 1
    assert sim.cert_verifier.stats["certs_checked"] == checked


def test_silent_aggregator_times_out_and_degrades():
    """Liveness rung: an aggregator that never gossips costs its round
    cert_patience quiescent steps, then the pooled vertices flow through
    the normal verify queue — a Byzantine aggregator cannot block."""
    _, sim = _run_cert(4, 5, cert=True)
    p = sim.processes[2]
    r = p.round + 2
    if r % 4 == p.index:
        r += 1
    pooled = _vertex(rnd=r, src=(p.index + 1) % 4)
    p._cert_pool[r] = {pooled.id.source: pooled}
    for _ in range(p.cfg.cert_patience + 1):
        p.step()
    assert p.metrics.counters["cert_timeouts"] == 1
    assert p.metrics.counters["cert_rounds_degraded"] >= 1
    assert r not in p._cert_pool and r in p._cert_done


def test_stale_certificate_for_pruned_round_ignored():
    _, sim = _run_cert(4, 6, cert=True)
    p = sim.processes[1]
    # at or below the GC floor (genesis when nothing pruned yet): the
    # certificate is dropped unexamined — no pairing, no reject
    stale = RoundCertificate(
        round=p.dag.base_round,
        signers=(0, 1, 2),
        digests=tuple(_digests(b"old", 3)),
        agg_sig=bls.g1_compress(bls.g1_mul(3)),
    )
    before = p.metrics.counters.get("certs_rejected", 0)
    p._on_certificate(_cert_msg(stale))
    assert p.metrics.counters.get("certs_rejected", 0) == before
    assert p.metrics.counters["certs_ignored"] >= 1
