"""Pipelined waves + eager optimistic delivery (ISSUE 16 tentpole).

The two knobs change WHEN consensus output becomes visible, never WHAT
it is:

- ``DAGRIDER_WAVE_PIPELINE`` (cfg.wave_pipeline) — every live wave whose
  commit round holds a quorum is attempted each step instead of once at
  the 4-round boundary. The committed leader sequence is unchanged: the
  chain-walk path checks run over immutable causal pasts (time-
  invariant), and the one-shot is spent at the boundary-equivalent
  attempt, so no wave decides that the oracle would have skipped.
- ``DAGRIDER_EAGER_DELIVER`` (cfg.eager_deliver) — each decided chain's
  canonical chunks are surfaced through ``on_deliver_early`` ahead of
  the (possibly deferred) flush, then reconciled against the canonical
  walk; the speculative stream must be a PREFIX of the final order with
  zero mismatches.

This suite pins the A/B invariant across n x seeds x adversaries and
the eager-prefix property, plus the supporting machinery (DAG quorum
frontier, hold-tail verifier window, adaptive batcher deadline).
"""

from __future__ import annotations

import pytest

from dag_rider_tpu.config import Config, MempoolConfig
from dag_rider_tpu.consensus.adversary import ByzantineProcess, make_behavior
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import Simulation


def _run(n, seed, adversary, pipeline, eager, cycles=12):
    cfg = Config(
        n=n,
        coin="round_robin",
        propose_empty=True,
        wave_pipeline=pipeline,
        eager_deliver=eager,
        # lockstep pump: wall-clock sync cooldowns and multi-step
        # patience would starve the anti-entropy recovery the withhold
        # adversary forces (honest count == quorum exactly at n=16)
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
        sync_patience=1,
    )
    nbyz = cfg.f if adversary else 0
    behaviors = {
        i: make_behavior(adversary, seed=seed + 1000 + i)
        for i in range(nbyz)
    }

    def factory(pcfg, i, ptp, **kwargs):
        if i in behaviors:
            return ByzantineProcess(
                pcfg, i, ptp, behavior=behaviors[i], **kwargs
            )
        return Process(pcfg, i, ptp, **kwargs)

    sim = Simulation(cfg, process_factory=factory if behaviors else None)
    sim.submit_blocks(per_process=2)
    for _ in range(cycles):
        sim.run(max_messages=n * (n - 1))
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in d]
        for d in sim.deliveries
    ]
    return logs, sim, nbyz


CASES = [
    (4, 1, None),
    (4, 2, "equivocate"),
    (4, 3, "withhold"),
    (16, 4, None),
    (16, 5, "equivocate"),
    (16, 6, "withhold"),
    (32, 7, None),
]


@pytest.mark.parametrize(
    "n,seed,adversary", CASES,
    ids=[f"n{n}-s{s}-{a or 'clean'}" for n, s, a in CASES],
)
def test_final_commit_order_byte_identical(n, seed, adversary):
    """The A/B invariant: knobs on vs knobs off, byte-identical FINAL
    commit order at every process, and the eager speculative stream
    reconciles with zero mismatches."""
    cycles = 12 if n <= 16 else 8
    if adversary == "withhold":
        # every round needs a sync round-trip to recover withheld
        # parents before the next can fill — budget accordingly
        cycles = 40
    off_logs, _, nbyz = _run(
        n, seed, adversary, pipeline=False, eager=False, cycles=cycles
    )
    on_logs, sim, _ = _run(
        n, seed, adversary, pipeline=True, eager=True, cycles=cycles
    )
    assert any(off_logs[nbyz:]), "oracle delivered nothing — vacuous run"
    for i in range(n):
        assert off_logs[i] == on_logs[i], f"process {i} order diverged"
    for i, p in enumerate(sim.processes):
        if i < nbyz:
            continue
        snap = p.metrics.snapshot()
        assert snap.get("eager_rollbacks_expected_zero", 0) == 0
        # every speculative delivery reconciled against the canonical
        # walk (the streams are equal, not merely prefix-consistent, at
        # quiescence)
        assert snap.get("eager_delivered", 0) == snap.get(
            "eager_reconciled", 0
        )


@pytest.mark.parametrize("n,seed", [(4, 41), (16, 42)])
def test_eager_stream_is_prefix_at_every_point(n, seed):
    """Drive the knobs-on cluster in small bursts and assert after EVERY
    burst that each process's eager sink is consistent with (and at
    least as long as) its canonical sink — delivered-prefix order, never
    reordered, never behind."""
    cfg = Config(
        n=n,
        coin="round_robin",
        propose_empty=True,
        wave_pipeline=True,
        eager_deliver=True,
    )
    sim = Simulation(cfg)
    sim.submit_blocks(per_process=2)
    for _ in range(14):
        sim.run(max_messages=n * n)
        for i, p in enumerate(sim.processes):
            canon = [v.id for v in sim.deliveries[i]]
            eager = [v.id for v in sim.eager_deliveries[i]]
            # eager runs AHEAD of (or level with) the canonical flush,
            # and the canonical stream is always a prefix of it
            assert len(eager) >= len(canon)
            assert eager[: len(canon)] == canon
    for i in range(n):
        # at quiescence the streams converge exactly
        assert [v.id for v in sim.eager_deliveries[i]] == [
            v.id for v in sim.deliveries[i]
        ]
        assert len(sim.deliveries[i]) > 0


def test_pipelined_waves_decide_no_later_and_gauge():
    """Pipelining may only move decisions EARLIER: after every burst,
    each pipelined process's decided_wave is >= its oracle twin's, and
    the waves_inflight gauge is maintained."""
    n, seed = 4, 77
    mk = lambda pipe: Simulation(  # noqa: E731
        Config(
            n=n,
            coin="round_robin",
            propose_empty=True,
            wave_pipeline=pipe,
        )
    )
    a, b = mk(False), mk(True)
    for sim in (a, b):
        sim.submit_blocks(per_process=2)
    for _ in range(12):
        a.run(max_messages=n * (n - 1))
        b.run(max_messages=n * (n - 1))
        for pa, pb in zip(a.processes, b.processes):
            assert pb.decided_wave >= pa.decided_wave
    assert a.processes[0].decided_wave >= 2
    # the gauge is maintained on the pipelined side (0 is legitimate at
    # a quiescent burst edge — every ready wave just decided)
    assert "waves_inflight" in b.processes[0].metrics.counters
    del seed


def test_quorum_frontier_backward_scan():
    from dag_rider_tpu.consensus.dag_state import DagState

    cfg = Config(n=4, propose_empty=True)
    sim = Simulation(cfg)
    sim.submit_blocks(per_process=1)
    for _ in range(6):
        sim.run(max_messages=100)
    dag = sim.processes[0].dag
    fr = dag.quorum_frontier(cfg.quorum)
    assert fr >= 1
    assert dag.round_size(fr) >= cfg.quorum
    for r in range(fr + 1, dag.max_round + 1):
        assert dag.round_size(r) < cfg.quorum
    # every round at/below the frontier is quorum-filled (monotonicity)
    for r in range(1, fr + 1):
        assert dag.round_size(r) >= cfg.quorum
    assert dag.quorum_frontier(10_000) == 0
    del DagState


def test_eager_mismatch_is_counted_and_disables_speculation():
    """Force a divergent speculative stream and check the failure path:
    expected-zero counter bumps once, flight-recorder events fire, and
    speculation stops (no further eager deliveries)."""
    from dag_rider_tpu.utils import slog

    log, records = slog.capture()
    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=True,
        wave_pipeline=True,
        eager_deliver=True,
    )
    sim = Simulation(cfg, log=log)
    sim.submit_blocks(per_process=1)
    for _ in range(6):
        sim.run(max_messages=100)
    p = sim.processes[0]
    assert p._eager, "speculation should still be live on a clean run"
    # corrupt the speculative log's unreconciled tail-to-be: inject a
    # bogus next-expected entry so the next canonical walk mismatches
    from dag_rider_tpu.core.types import VertexID

    p.eager_log.insert(p._eager_cursor, VertexID(999, 0))
    for _ in range(8):
        sim.run(max_messages=100)
    snap = p.metrics.snapshot()
    assert snap.get("eager_rollbacks_expected_zero") == 1
    assert not p._eager, "mismatch must disable further speculation"
    names = [r["event"] for r in records]
    assert "eager_mismatch" in names
    assert "invariant_violation" in names


def test_adaptive_deadline_tracks_latency_histogram():
    """cfg.adaptive_deadline drives the batcher's effective deadline to
    ~5% of the measured submit→deliver p50 (floored at 1 ms, capped at
    the configured value), publishes the deadline_ms_effective gauge,
    and emits deadline_adapted."""
    from dag_rider_tpu.mempool import Mempool
    from dag_rider_tpu.utils import slog
    from dag_rider_tpu.utils.metrics import Metrics

    log, records = slog.capture()
    m = Metrics()
    mp = Mempool(
        MempoolConfig(
            cap=256,
            batch_bytes=64,
            batch_deadline_ms=50.0,
            adaptive_deadline=True,
        ),
        metrics=m,
        log=log,
    )
    # 32 samples of ~200ms end-to-end latency
    for k in range(32):
        mp.submit((f"tx{k}".encode().ljust(16, b"."),), now=float(k))
        blocks = mp.build_blocks(now=float(k) + 0.06, force=True)
        for b in blocks:
            mp.observe_delivered(b, now=float(k) + 0.2)
    mp.build_blocks(now=100.0)
    # 5% of ~140-200ms is ~7-10ms, well under the 50ms ceiling
    assert 1.0 <= mp.batcher.deadline_ms < 50.0
    assert m.counters.get("deadline_ms_effective", 0) >= 1
    assert any(r["event"] == "deadline_adapted" for r in records)
    # non-adaptive config never touches the effective deadline
    mp2 = Mempool(
        MempoolConfig(cap=256, batch_bytes=64, batch_deadline_ms=50.0)
    )
    for k in range(32):
        mp2.submit((f"ty{k}".encode().ljust(16, b"."),), now=float(k))
        for b in mp2.build_blocks(now=float(k) + 0.06, force=True):
            mp2.observe_delivered(b, now=float(k) + 0.2)
    mp2.build_blocks(now=100.0)
    assert mp2.batcher.deadline_ms == 50.0
