"""End-to-end multi-node simulations: agreement under adversarial schedules
and injected faults — the integration story the reference never tests
(SURVEY.md §4) and the fault-injection capability §5 requires."""

import pytest

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import RandomizedScheduler, Simulation
from dag_rider_tpu.transport import FaultPlan, FaultyTransport, InMemoryTransport


def mk_cfg(n=4):
    return Config(n=n, coin="round_robin")


def test_seven_nodes_f2():
    sim = Simulation(mk_cfg(n=7))
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=8000)
    sim.check_agreement()
    assert all(p.metrics.counters["waves_decided"] >= 1 for p in sim.processes)


@pytest.mark.parametrize("seed", [1, 7, 42, 1337])
def test_agreement_under_random_schedules(seed):
    """Property test over message interleavings: any delivery order must
    preserve total-order agreement."""
    tp = InMemoryTransport()
    sim = Simulation(mk_cfg(), transport=tp)
    sim.submit_blocks(per_process=3)
    for p in sim.processes:
        p.start()
    RandomizedScheduler(tp, seed).run(max_messages=4000)
    sim.check_agreement()
    assert any(p.metrics.counters["waves_decided"] >= 1 for p in sim.processes)


@pytest.mark.parametrize("seed", [3, 11])
def test_agreement_with_drops_and_delays(seed):
    """Asynchrony: drop nothing permanently, but delay a fraction of
    messages arbitrarily; flush and re-run until quiescent. Agreement must
    hold throughout; progress resumes once messages land."""
    plan = FaultPlan(delay=0.15, seed=seed)
    tp = FaultyTransport(plan)
    sim = Simulation(mk_cfg(), transport=tp)
    sim.submit_blocks(per_process=3)
    sim.run(max_messages=2000)
    sim.check_agreement()
    # release held messages until none remain (eventual delivery)
    for _ in range(20):
        if tp.flush_delayed() == 0 and tp.pending == 0:
            break
        tp.pump(2000)
    sim.check_agreement()
    assert all(p.metrics.counters["waves_decided"] >= 1 for p in sim.processes)


def test_agreement_with_duplicates():
    plan = FaultPlan(duplicate=0.3, seed=5)
    tp = FaultyTransport(plan)
    sim = Simulation(mk_cfg(), transport=tp)
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=4000)
    sim.check_agreement()
    dups = sum(p.metrics.counters["msgs_duplicate"] for p in sim.processes)
    assert dups > 0  # duplicates arrived and were absorbed


def test_equivocating_sender_without_rbc_diverges_but_is_detected():
    """A Byzantine source sends conflicting vertices to different peers.
    *Without* the RBC stage honest processes can admit different payloads
    for the same slot — the digest-level ``check_agreement`` must catch
    exactly that divergence (it is the gap the round-1 id-only comparison
    masked), and equivocation is at least detected. The closed-gap
    behavior (divergence impossible) is tests/test_rbc.py's
    ``test_equivocating_sender_with_rbc_stays_consistent``."""
    plan = FaultPlan(equivocators=(3,), seed=9)
    tp = FaultyTransport(plan)
    sim = Simulation(mk_cfg(), transport=tp)
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=4000)
    ids = [sim.delivered_ids(i) for i in range(4)]
    k = min(map(len, ids))
    assert k > 0 and all(l[:k] == ids[0][:k] for l in ids), "id order broke"
    try:
        sim.check_agreement()
        diverged = False
    except AssertionError:
        diverged = True
    detected = sum(
        p.metrics.counters["equivocations_detected"] for p in sim.processes
    )
    assert detected + tp.stats["equivocated"] > 0
    # With this seed the conflicting payloads really do land at different
    # honest nodes — the digest check must refuse to call that agreement.
    assert diverged


def test_crash_fault_quorum_still_lives():
    """One process (f=1) never starts. The other three (=2f+1) must still
    advance rounds and decide waves."""
    sim = Simulation(mk_cfg())
    sim.submit_blocks(per_process=2)
    for p in sim.processes[:3]:
        p.start()
    sim.transport.pump(4000)
    live = sim.processes[:3]
    assert all(p.round >= 8 for p in live)
    assert all(p.metrics.counters["waves_decided"] >= 1 for p in live)
    logs = [sim.delivered_ids(i) for i in range(3)]
    k = min(map(len, logs))
    assert k > 0 and all(l[:k] == logs[0][:k] for l in logs)
