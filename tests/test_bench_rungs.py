"""bench.py helper coverage — the driver's benchmark entry points.

The ladder rungs are driven end-to-end on the chip (or the CPU
fallback), but their *mechanics* — time-box extension toward a vertex
target, the verifier-seam breakdown, pipeline-off shadowing — must not
regress silently between captures: a broken rung costs a whole relay
window (round-5 postmortem: the sim256_sync shadow crash truncated the
first on-chip ladder).
"""

import bench
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.tpu import TPUVerifier


def _built(n=8):
    reg, seeds = KeyRegistry.generate(n)
    return TPUVerifier(reg), [VertexSigner(s) for s in seeds]


def test_sim_rung_reports_breakdown_and_progress():
    v, signers = _built()
    e = bench._sim_rung(8, 2.0, v, signers, bucket=256, chunk=56)
    assert e["nodes"] == 8 and e["pipelined"] is True
    # a short box may not reach a committed wave (delivery needs 4+
    # rounds past compile) — assert on progress that must happen
    assert e["messages"] > 0 and e["max_round"] >= 1
    bd = e["verifier_breakdown"]
    assert bd["dispatches"] >= 1
    assert bd["sigs_dispatched"] >= bd["dispatches"]
    # the three shares partition the wall time (rounding slack)
    assert bd["prepare_s"] + bd["device_s"] <= e["seconds"] + 0.1
    assert v.fixed_bucket == 256


def test_sim_rung_extends_past_box_until_target_met():
    v, signers = _built()
    # 0.2s box alone cannot reach 40 vertices per view; the extension
    # must keep pumping past the box until the target is met OR the
    # max_s bound expires (slow/cold-cache hosts may hit the bound
    # first — the mechanism under test is the extension, not the speed)
    e = bench._sim_rung(
        8, 0.2, v, signers, bucket=256, chunk=56,
        target_per_view=40, max_s=60.0,
    )
    assert e["seconds"] > 0.2, "extension never engaged"
    assert (
        e["vertices_delivered_per_view"] >= 40 or e["seconds"] >= 60.0
    ), e
    assert e["messages"] > 0


def test_sim_rung_pipeline_off_runs_and_restores_seam():
    """The pipeline-off B side must run the synchronous path via the
    pipeline_enabled flag (which replaced the round-5 None shadow whose
    crash truncated a ladder) and restore the flag afterwards.
    Byte-identity of the two paths is covered deterministically by
    test_determinism.py::test_pipelined_coalesced_path_matches_sync_path
    — a wall-clock time-boxed rung pair cannot assert equality."""
    v, signers = _built()
    e_on = bench._sim_rung(8, 1.5, v, signers, bucket=256, chunk=56)
    e_off = bench._sim_rung(
        8, 1.5, v, signers, bucket=256, chunk=56, pipelined=False
    )
    assert e_on["pipelined"] is True and e_off["pipelined"] is False
    assert e_off["messages"] > 0 and e_off["max_round"] >= 1
    # the A side reports the window gauges; the B side reads empty
    assert e_on["verifier_breakdown"]["queue_depth"] >= 1
    assert 0.0 <= e_on["verifier_breakdown"]["overlap_fraction"] <= 1.0
    assert e_off["verifier_breakdown"]["queue_depth_max"] == 0
    # flag restored: the async seam is live again
    assert v.pipeline_enabled is True
    pending = v.dispatch_batch([])
    assert v.resolve_batch(pending) == []


def test_vec_ab_rung_asserts_equal_commit_order():
    """The round-12 scalar-vs-vector A/B: both sides must reach the
    target round, the entry must carry both throughput sides + the
    speedup ratio, and the rung itself enforces byte-identical per-view
    commit order (it raises on divergence — the tier1-vec CI smoke
    relies on that)."""
    e = bench._vec_ab_rung(8, 30.0, 8)
    assert e["commit_order_identical"] is True
    assert e["scalar"]["max_round"] >= 8
    assert e["vector"]["max_round"] >= 8
    assert e["scalar"]["msgs_per_sec"] > 0
    assert e["vector"]["msgs_per_sec"] > 0
    assert e["speedup"] > 0
    assert e["scalar"]["vertices_delivered_total"] > 0
