"""Checkpoint/resume + wire codec round trips.

SURVEY.md §5: the reference has no serialization; here a process must be
able to crash after any step and resume with the exact same delivered
prefix and continue to agreement with the rest of the cluster.
"""


from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.utils import checkpoint


def test_vertex_codec_roundtrip():
    v = Vertex(
        id=VertexID(5, 2),
        block=Block((b"tx1", b"", b"tx3" * 100)),
        strong_edges=(VertexID(4, 0), VertexID(4, 1), VertexID(4, 3)),
        weak_edges=(VertexID(2, 1),),
        signature=bytes(range(64)),
        coin_share=bytes(range(48)),
    )
    out, used = codec.decode_vertex(codec.encode_vertex(v))
    assert out == v
    assert used == len(codec.encode_vertex(v))
    bare = Vertex(id=VertexID(1, 0))
    assert codec.decode_vertex(codec.encode_vertex(bare))[0] == bare


def test_message_codec_roundtrip():
    v = Vertex(id=VertexID(3, 1), strong_edges=(VertexID(2, 0),))
    msg = BroadcastMessage(vertex=v, round=3, sender=1)
    out, _ = codec.decode_message(codec.encode_message(msg))
    assert out == msg


def test_frame_roundtrip():
    payload = b"hello world"
    buf = codec.frame(payload) + codec.frame(b"second")
    first = codec.read_frame(buf)
    assert first is not None and first[0] == payload
    second = codec.read_frame(buf, first[1])
    assert second is not None and second[0] == b"second"
    assert codec.read_frame(buf[:3]) is None  # incomplete header
    assert codec.read_frame(codec.frame(payload)[:-1]) is None  # short body


def test_checkpoint_resume_continues_to_agreement(tmp_path):
    """Run a cluster, checkpoint p0 mid-flight, rebuild p0 from disk, keep
    running: the resumed process must preserve its delivered prefix and the
    cluster must stay in agreement."""
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(3)
    sim.run(max_messages=300)  # partial run, likely mid-wave
    p0 = sim.processes[0]
    pre_log = list(p0.delivered_log)
    pre_round = p0.round
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)
    assert checkpoint.latest_round(ckpt) == pre_round

    # fresh process restored from disk, attached to a fresh cluster run
    cfg2 = Config(n=4)
    p0b = Process(cfg2, 0, InMemoryTransport())
    checkpoint.restore(p0b, ckpt)
    assert p0b.delivered_log == pre_log
    assert p0b.round == pre_round
    assert p0b.dag.max_round == p0.dag.max_round
    assert set(p0b.dag.vertices) == set(p0.dag.vertices)
    # the restored machine can keep stepping on its own state
    p0b.start()
    for k in range(3):
        p0b.submit(Block((f"post-restore-{k}".encode(),)))
    # delivered prefix unchanged or extended, never rewritten
    assert p0b.delivered_log[: len(pre_log)] == pre_log


def test_checkpoint_rejects_mismatched_identity(tmp_path):
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(1)
    sim.run(max_messages=50)
    path = str(tmp_path / "ck")
    checkpoint.save(sim.processes[1], path)
    other = Process(Config(n=4), 0, InMemoryTransport())
    try:
        checkpoint.restore(other, path)
    except ValueError as e:
        assert "different committee" in str(e)
    else:
        raise AssertionError("restore should reject wrong index")


class GatedCoin:
    """Round-robin coin with an explicit readiness gate — lets a test pin a
    wave in ``_pending_waves`` across a checkpoint/restore boundary."""

    def __init__(self, n: int, ready: bool = False):
        self.n = n
        self.is_ready = ready

    def ready(self, wave: int) -> bool:
        return self.is_ready

    def choose_leader(self, wave: int) -> int:
        return wave % self.n

    def my_share(self, wave):
        return None

    def observe_share(self, wave, source, share):
        pass


def test_checkpoint_restores_pending_waves(tmp_path):
    """Round-2 VERDICT weak #7: a wave pending on an unready coin at save
    time must commit directly after restore once the coin becomes ready —
    not wait for a later wave's retroactive leader chain."""
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    coins = {}

    def factory(i):
        coins[i] = GatedCoin(4)
        return coins[i]

    sim = Simulation(cfg, coin_factory=factory)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=20_000)
    p0 = sim.processes[0]
    assert p0._pending_waves, "test setup: no wave got stuck on the coin"
    assert p0.metrics.counters["waves_decided"] == 0
    pending = set(p0._pending_waves)
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)

    p0b = Process(
        Config(n=4, coin="round_robin", propose_empty=False),
        0,
        InMemoryTransport(),
        coin=GatedCoin(4, ready=False),
    )
    checkpoint.restore(p0b, ckpt)
    assert p0b._pending_waves == pending
    # coin becomes ready (the deferred shares "arrive"); one step must
    # commit the pending wave directly and a_deliver its causal history.
    p0b.coin.is_ready = True
    p0b._started = True
    p0b.step()
    assert p0b.metrics.counters["waves_decided"] >= len(pending)
    assert p0b.delivered_log, "pending wave committed but delivered nothing"


def test_checkpoint_pending_waves_backcompat(tmp_path):
    """Manifests written before the pending_waves key must re-arm every
    tried-but-undecided wave on restore."""
    import json, os

    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    coins = {}

    def factory(i):
        coins[i] = GatedCoin(4)
        return coins[i]

    sim = Simulation(cfg, coin_factory=factory)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=20_000)
    p0 = sim.processes[0]
    assert p0._pending_waves
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)
    mpath = os.path.join(ckpt, "manifest.json")
    with open(mpath) as fh:
        manifest = json.load(fh)
    del manifest["pending_waves"]  # simulate an old checkpoint
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)

    p0b = Process(
        Config(n=4, coin="round_robin", propose_empty=False),
        0,
        InMemoryTransport(),
        coin=GatedCoin(4, ready=True),
    )
    checkpoint.restore(p0b, ckpt)
    assert p0b._pending_waves == set(p0._pending_waves)


# -- round 20: crash-during-save atomicity + corruption containment -----


def _saved_checkpoint(tmp_path, name="ck"):
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(2)
    sim.run(max_messages=300)
    path = str(tmp_path / name)
    checkpoint.save(sim.processes[0], path, mempool=None)
    return sim.processes[0], path


def test_atomic_save_leaves_no_temp_files(tmp_path):
    """Every checkpoint file lands via tmp + os.replace: after save()
    returns there are no .tmp leftovers, and saving over an existing
    checkpoint replaces it atomically (manifest last = commit point)."""
    import os

    _, path = _saved_checkpoint(tmp_path)
    names = sorted(os.listdir(path))
    assert not [f for f in names if f.endswith(".tmp")], names
    assert checkpoint.MANIFEST in names
    # overwrite in place — still no temp droppings, still restorable
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(1)
    sim.run(max_messages=200)
    checkpoint.save(sim.processes[0], path)
    names = sorted(os.listdir(path))
    assert not [f for f in names if f.endswith(".tmp")], names
    fresh = Process(Config(n=4), 0, InMemoryTransport())
    checkpoint.restore(fresh, path)


def test_truncated_manifest_raises_corrupt_not_garbage(tmp_path):
    """A manifest torn mid-write (crash before rename could never produce
    this, but disk corruption can) is classified CorruptCheckpointError —
    the caller's signal to start empty and rejoin — and validation
    happens BEFORE mutation: the target process is untouched."""
    import os

    _, path = _saved_checkpoint(tmp_path)
    mpath = os.path.join(path, checkpoint.MANIFEST)
    raw = open(mpath, "rb").read()
    with open(mpath, "wb") as fh:
        fh.write(raw[: len(raw) // 2])
    fresh = Process(Config(n=4), 0, InMemoryTransport())
    try:
        checkpoint.restore(fresh, path)
    except checkpoint.CorruptCheckpointError:
        pass
    else:
        raise AssertionError("truncated manifest must raise corrupt")
    assert fresh.round == 0 and fresh.dag.max_round == 0, (
        "failed restore must not half-mutate the process"
    )
    assert fresh.delivered_log == []


def test_sidecar_hash_mismatch_raises_corrupt(tmp_path):
    """The torn window a crash CAN leave: old manifest over new sidecars
    (or bit rot in a sidecar). The manifest's sha256 map catches it."""
    import os

    _, path = _saved_checkpoint(tmp_path)
    vpath = os.path.join(path, checkpoint.VERTICES)
    blob = bytearray(open(vpath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(vpath, "wb") as fh:
        fh.write(bytes(blob))
    fresh = Process(Config(n=4), 0, InMemoryTransport())
    try:
        checkpoint.restore(fresh, path)
    except checkpoint.CorruptCheckpointError as e:
        assert "hash mismatch" in str(e)
    else:
        raise AssertionError("sidecar hash mismatch must raise corrupt")


def test_node_restores_to_empty_on_corrupt_checkpoint(tmp_path):
    """Node-level containment: a corrupt checkpoint at boot means start
    empty (rebuild + rejoin later), bump the checkpoint_corrupt counter,
    and emit the event — never crash, never half-restore."""
    import json
    import os

    from dag_rider_tpu.node import Node, generate_keys, _dump_secret_file
    from dag_rider_tpu.utils import slog

    keys_path = str(tmp_path / "keys.json")
    _dump_secret_file(keys_path, generate_keys(4, 2, seed="ck-corrupt"))
    ckpt_dir = str(tmp_path / "ckpt0")

    def mk(events):
        return Node(
            {
                "index": 0,
                "n": 4,
                "listen": "127.0.0.1:0",
                "peers": {},
                "keys": keys_path,
                "rbc": False,
                "verifier": "none",
                "coin": "round_robin",
                "checkpoint_dir": ckpt_dir,
                "auto_propose": False,
            },
            log=slog.EventLog(events.append),
        )

    events: list = []
    node = mk(events)
    node.start()
    node.submit(Block((b"pre-crash",)))
    node.stop()  # writes a valid checkpoint
    assert checkpoint.present(ckpt_dir)

    # corrupt the manifest the way bit rot would
    mpath = os.path.join(ckpt_dir, checkpoint.MANIFEST)
    with open(mpath, "w") as fh:
        fh.write('{"version": 1, "n": 4')  # torn JSON

    events2: list = []
    node2 = mk(events2)
    try:
        assert node2.process.round == 0
        assert node2.process.dag.max_round == 0
        snap = node2.process.metrics.snapshot()
        assert snap.get("checkpoint_corrupt", 0) == 1, snap
        names = [e["event"] for e in events2]
        assert "checkpoint_corrupt" in names, names
        assert "restored" not in names, names
        # the rebuilt node still runs
        node2.start()
        node2.submit(Block((b"post-corruption",)))
    finally:
        node2.stop()
