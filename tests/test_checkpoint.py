"""Checkpoint/resume + wire codec round trips.

SURVEY.md §5: the reference has no serialization; here a process must be
able to crash after any step and resume with the exact same delivered
prefix and continue to agreement with the rest of the cluster.
"""


from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.utils import checkpoint


def test_vertex_codec_roundtrip():
    v = Vertex(
        id=VertexID(5, 2),
        block=Block((b"tx1", b"", b"tx3" * 100)),
        strong_edges=(VertexID(4, 0), VertexID(4, 1), VertexID(4, 3)),
        weak_edges=(VertexID(2, 1),),
        signature=bytes(range(64)),
        coin_share=bytes(range(48)),
    )
    out, used = codec.decode_vertex(codec.encode_vertex(v))
    assert out == v
    assert used == len(codec.encode_vertex(v))
    bare = Vertex(id=VertexID(1, 0))
    assert codec.decode_vertex(codec.encode_vertex(bare))[0] == bare


def test_message_codec_roundtrip():
    v = Vertex(id=VertexID(3, 1), strong_edges=(VertexID(2, 0),))
    msg = BroadcastMessage(vertex=v, round=3, sender=1)
    out, _ = codec.decode_message(codec.encode_message(msg))
    assert out == msg


def test_frame_roundtrip():
    payload = b"hello world"
    buf = codec.frame(payload) + codec.frame(b"second")
    first = codec.read_frame(buf)
    assert first is not None and first[0] == payload
    second = codec.read_frame(buf, first[1])
    assert second is not None and second[0] == b"second"
    assert codec.read_frame(buf[:3]) is None  # incomplete header
    assert codec.read_frame(codec.frame(payload)[:-1]) is None  # short body


def test_checkpoint_resume_continues_to_agreement(tmp_path):
    """Run a cluster, checkpoint p0 mid-flight, rebuild p0 from disk, keep
    running: the resumed process must preserve its delivered prefix and the
    cluster must stay in agreement."""
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(3)
    sim.run(max_messages=300)  # partial run, likely mid-wave
    p0 = sim.processes[0]
    pre_log = list(p0.delivered_log)
    pre_round = p0.round
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)
    assert checkpoint.latest_round(ckpt) == pre_round

    # fresh process restored from disk, attached to a fresh cluster run
    cfg2 = Config(n=4)
    p0b = Process(cfg2, 0, InMemoryTransport())
    checkpoint.restore(p0b, ckpt)
    assert p0b.delivered_log == pre_log
    assert p0b.round == pre_round
    assert p0b.dag.max_round == p0.dag.max_round
    assert set(p0b.dag.vertices) == set(p0.dag.vertices)
    # the restored machine can keep stepping on its own state
    p0b.start()
    for k in range(3):
        p0b.submit(Block((f"post-restore-{k}".encode(),)))
    # delivered prefix unchanged or extended, never rewritten
    assert p0b.delivered_log[: len(pre_log)] == pre_log


def test_checkpoint_rejects_mismatched_identity(tmp_path):
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(1)
    sim.run(max_messages=50)
    path = str(tmp_path / "ck")
    checkpoint.save(sim.processes[1], path)
    other = Process(Config(n=4), 0, InMemoryTransport())
    try:
        checkpoint.restore(other, path)
    except ValueError as e:
        assert "different committee" in str(e)
    else:
        raise AssertionError("restore should reject wrong index")


class GatedCoin:
    """Round-robin coin with an explicit readiness gate — lets a test pin a
    wave in ``_pending_waves`` across a checkpoint/restore boundary."""

    def __init__(self, n: int, ready: bool = False):
        self.n = n
        self.is_ready = ready

    def ready(self, wave: int) -> bool:
        return self.is_ready

    def choose_leader(self, wave: int) -> int:
        return wave % self.n

    def my_share(self, wave):
        return None

    def observe_share(self, wave, source, share):
        pass


def test_checkpoint_restores_pending_waves(tmp_path):
    """Round-2 VERDICT weak #7: a wave pending on an unready coin at save
    time must commit directly after restore once the coin becomes ready —
    not wait for a later wave's retroactive leader chain."""
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    coins = {}

    def factory(i):
        coins[i] = GatedCoin(4)
        return coins[i]

    sim = Simulation(cfg, coin_factory=factory)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=20_000)
    p0 = sim.processes[0]
    assert p0._pending_waves, "test setup: no wave got stuck on the coin"
    assert p0.metrics.counters["waves_decided"] == 0
    pending = set(p0._pending_waves)
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)

    p0b = Process(
        Config(n=4, coin="round_robin", propose_empty=False),
        0,
        InMemoryTransport(),
        coin=GatedCoin(4, ready=False),
    )
    checkpoint.restore(p0b, ckpt)
    assert p0b._pending_waves == pending
    # coin becomes ready (the deferred shares "arrive"); one step must
    # commit the pending wave directly and a_deliver its causal history.
    p0b.coin.is_ready = True
    p0b._started = True
    p0b.step()
    assert p0b.metrics.counters["waves_decided"] >= len(pending)
    assert p0b.delivered_log, "pending wave committed but delivered nothing"


def test_checkpoint_pending_waves_backcompat(tmp_path):
    """Manifests written before the pending_waves key must re-arm every
    tried-but-undecided wave on restore."""
    import json, os

    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    coins = {}

    def factory(i):
        coins[i] = GatedCoin(4)
        return coins[i]

    sim = Simulation(cfg, coin_factory=factory)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=20_000)
    p0 = sim.processes[0]
    assert p0._pending_waves
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)
    mpath = os.path.join(ckpt, "manifest.json")
    with open(mpath) as fh:
        manifest = json.load(fh)
    del manifest["pending_waves"]  # simulate an old checkpoint
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)

    p0b = Process(
        Config(n=4, coin="round_robin", propose_empty=False),
        0,
        InMemoryTransport(),
        coin=GatedCoin(4, ready=True),
    )
    checkpoint.restore(p0b, ckpt)
    assert p0b._pending_waves == set(p0._pending_waves)
