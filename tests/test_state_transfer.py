"""Peer state transfer (snapshot sync) — elastic recovery past the GC
horizon.

With cfg.gc_depth set, peers refuse anti-entropy sync for pruned windows
(test_prune.py); a node that was down long enough can therefore never
catch up message-by-message. The recovery path: f+1 sync_nack floors
above our round flip ``state_transfer_needed``; the node runtime fetches
an UNTRUSTED peer's live window and replays it locally
(utils.checkpoint.restore_from_snapshot — signatures verified, admission
gate re-run, consensus state recomputed, lying floors rejected by the
window-width check).
"""

from __future__ import annotations

import dataclasses
import json
import struct
import time


from dag_rider_tpu import Config
from dag_rider_tpu.consensus import Process, Simulation
from dag_rider_tpu.core.types import Block, BroadcastMessage, VertexID
from dag_rider_tpu.transport import InMemoryTransport
from dag_rider_tpu.utils import checkpoint

GC = Config(n=4, coin="round_robin", propose_empty=True, gc_depth=16)


def _pruned_donor(target_round: int = 70) -> Simulation:
    sim = Simulation(GC)
    sim.submit_blocks(per_process=2)
    for _ in range(20 * target_round):
        sim.run(max_messages=100)
        if max(p.round for p in sim.processes) >= target_round:
            break
    assert sim.processes[0].dag.base_round > 0
    return sim


def test_snapshot_roundtrip_replays_window():
    sim = _pruned_donor()
    donor = sim.processes[0]
    blob = checkpoint.snapshot_bytes(donor)

    fresh = Process(GC, 0, InMemoryTransport())
    assert checkpoint.restore_from_snapshot(fresh, blob)
    assert fresh.dag.base_round == donor.dag.base_round
    assert fresh.dag.max_round == donor.dag.max_round
    assert fresh.round == donor.dag.max_round
    assert sorted(fresh.dag.vertices) == sorted(donor.dag.vertices)
    assert fresh.metrics.counters["state_transfers"] == 1
    # the replayed machine keeps running: feed it nothing and step —
    # no exception, and a wave decision becomes possible as traffic flows
    fresh._started = True
    fresh.step()


def test_snapshot_rejects_lying_floor():
    sim = _pruned_donor()
    donor = sim.processes[0]
    blob = checkpoint.snapshot_bytes(donor)
    (hlen,) = struct.unpack_from("<I", blob, 0)
    head = json.loads(blob[4 : 4 + hlen])
    # Byzantine donor claims a floor that leaves < gc_depth of window:
    # vertices below it are omitted-by-claim, shrinking usable history
    head["base_round"] = donor.dag.max_round - GC.gc_depth + 2
    forged_head = json.dumps(head).encode()
    forged = struct.pack("<I", len(forged_head)) + forged_head + blob[4 + hlen :]
    fresh = Process(GC, 0, InMemoryTransport())
    assert not checkpoint.restore_from_snapshot(fresh, forged)
    # untouched: still the genesis-only fresh process
    assert fresh.dag.base_round == 0 and fresh.dag.max_round == 0
    assert fresh.round == 0


def test_snapshot_rejects_wrong_committee_and_garbage():
    sim = _pruned_donor()
    blob = checkpoint.snapshot_bytes(sim.processes[0])
    other = Process(Config(n=7, gc_depth=16), 0, InMemoryTransport())
    assert not checkpoint.restore_from_snapshot(other, blob)
    fresh = Process(GC, 0, InMemoryTransport())
    assert not checkpoint.restore_from_snapshot(fresh, b"\x00\x01garbage")


def test_snapshot_drops_forged_vertex_signature():
    """A tampered vertex in the snapshot is dropped by signature
    verification while the rest of the window replays."""
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.cpu import CPUVerifier

    n = 4
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    cfg = Config(n=n, coin="round_robin", propose_empty=True, gc_depth=16)
    sim = Simulation(
        cfg,
        signer_factory=lambda i: signers[i],
        verifier_factory=lambda i: CPUVerifier(reg),
    )
    sim.submit_blocks(per_process=2)
    for _ in range(600):
        sim.run(max_messages=100)
        if max(p.round for p in sim.processes) >= 40:
            break
    donor = sim.processes[0]
    assert donor.dag.base_round > 0
    # tamper a frontier vertex (no dependents -> window stays intact)
    top = donor.dag.max_round
    victim = donor.dag.vertices_in_round(top)[0]
    forged = dataclasses.replace(victim, signature=b"\x99" * 64)
    del donor.dag.vertices[victim.id]
    donor.dag.vertices[forged.id] = forged

    blob = checkpoint.snapshot_bytes(donor)
    fresh = Process(cfg, 0, InMemoryTransport())
    assert checkpoint.restore_from_snapshot(
        fresh, blob, verifier=CPUVerifier(reg)
    )
    assert not fresh.dag.present(victim.id)  # forged copy filtered out
    # the rest of the window replayed (frontier may shrink by the one
    # dropped vertex when it was alone in its round)
    assert fresh.dag.max_round >= top - 1
    assert len(fresh.dag.vertices) >= len(donor.dag.vertices) - 2


def test_sync_nack_flow_flips_state_transfer_flag():
    sim = _pruned_donor()
    donor = sim.processes[0]
    base = donor.dag.base_round

    requester = Process(GC, 3, InMemoryTransport())
    requester.round = 1  # far below the cluster
    # donor refuses a below-horizon window and nacks
    outbox = []
    donor.transport.broadcast = lambda m: outbox.append(m)
    donor._sync_last_serve.clear()
    donor._serve_sync(
        BroadcastMessage(
            vertex=None, round=1, sender=3, kind="sync", origin=4
        )
    )
    nacks = [m for m in outbox if m.kind == "sync_nack"]
    assert nacks and nacks[0].round == base and nacks[0].origin == 3

    # f+1 distinct responders (f=1 -> 2) flip the flag; one is not enough
    requester._on_sync_nack(
        dataclasses.replace(nacks[0], sender=donor.index)
    )
    assert not requester.state_transfer_needed
    requester._on_sync_nack(dataclasses.replace(nacks[0], sender=1))
    assert requester.state_transfer_needed
    # a floor at/below our round clears that responder (stale signal)
    requester.round = base + 5
    requester._on_sync_nack(dataclasses.replace(nacks[0], sender=1))
    assert 1 not in requester._horizon_nacks


def test_node_rejoins_past_horizon_via_snapshot(tmp_path):
    """End to end over real gRPC: 3 of 4 nodes run far past the GC
    horizon; the 4th then joins fresh, gets refused+nacked on sync,
    fetches a snapshot, replays it, and delivers a suffix consistent
    with the cluster's order."""
    from dag_rider_tpu import node as node_mod

    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )

    def mk(i):
        return node_mod.Node(
            {
                "index": i,
                "n": 4,
                "listen": "127.0.0.1:0",
                "peers": {},
                "keys": str(keys_path),
                "rbc": False,  # plain gRPC: nack/fetch path under test
                "verifier": "cpu",
                "coin": "round_robin",
                "propose_empty": True,
                "gc_depth": 16,
                "auth_master": "ef" * 32,
            }
        )

    nodes = [mk(i) for i in range(3)]
    addrs = {i: f"127.0.0.1:{nd.net.bound_port}" for i, nd in enumerate(nodes)}
    late = None
    try:
        for i, nd in enumerate(nodes):
            nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
        for nd in nodes:
            nd.start()
        deadline = time.time() + 90
        while time.time() < deadline and (
            nodes[0].process.dag.base_round < 8
        ):
            time.sleep(0.1)
        assert nodes[0].process.dag.base_round >= 8, "cluster never pruned"

        late = mk(3)
        addrs[3] = f"127.0.0.1:{late.net.bound_port}"
        for i, nd in enumerate(nodes + [late]):
            nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
        late.start()
        late.submit(Block((b"late-tx",)))
        deadline = time.time() + 90
        while time.time() < deadline and not late.process.metrics.counters.get(
            "state_transfers"
        ):
            time.sleep(0.1)
        assert late.process.metrics.counters.get("state_transfers") == 1
        base3 = late.process.dag.base_round
        assert base3 > 0

        # and it actually rejoins: deliveries flow after the transfer
        deadline = time.time() + 60
        while time.time() < deadline and len(late.delivered) < 8:
            time.sleep(0.1)
        assert len(late.delivered) >= 8, "no deliveries after transfer"
        # order consistency: the late node's log is the cluster's order
        # filtered to rounds above its snapshot floor — every entry
        # appears in node0's log in the same relative order
        log0 = [
            (v.id.round, v.id.source, v.digest())
            for v in nodes[0].delivered
        ]
        log3 = [
            (v.id.round, v.id.source, v.digest()) for v in late.delivered
        ]
        pos = {e: i for i, e in enumerate(log0)}
        got = [pos[e] for e in log3 if e in pos]
        # allow the freshest tail of log3 to be ahead of node0's sink
        assert len(got) >= len(log3) - 8
        assert got == sorted(got), "relative delivery order diverged"
    finally:
        for nd in nodes + ([late] if late is not None else []):
            nd.stop()


def test_snapshot_rejects_rewind_and_requires_gc():
    """Round-4 review hardening: (a) a valid-but-old window must not
    rewind a live node (duplicate a_deliver), (b) without gc_depth the
    import semantics are unsound and the function refuses, (c) a
    duplicate (round, source) pair — equivocation smuggled past the
    donor's RBC — refuses atomically instead of raising mid-commit."""
    sim = _pruned_donor()
    donor = sim.processes[0]
    blob = checkpoint.snapshot_bytes(donor)

    # (a) receiver already ahead of the claimed floor -> refuse untouched
    ahead = Process(GC, 0, InMemoryTransport())
    ahead.round = donor.dag.max_round + 5
    before = dict(ahead.dag.vertices)
    assert not checkpoint.restore_from_snapshot(ahead, blob)
    assert ahead.round == donor.dag.max_round + 5
    assert dict(ahead.dag.vertices) == before

    # (b) no gc_depth -> refuse
    plain = Process(
        Config(n=4, coin="round_robin", propose_empty=True),
        0,
        InMemoryTransport(),
    )
    assert not checkpoint.restore_from_snapshot(plain, blob)

    # (c) duplicate id in the payload -> atomic refusal, no exception
    from dag_rider_tpu.core import codec as _codec

    dup = donor.dag.vertices_in_round(donor.dag.max_round)[0]
    payload = _codec.encode_vertex(dup)
    forged = blob + struct.pack("<I", len(payload)) + payload
    fresh = Process(GC, 0, InMemoryTransport())
    assert not checkpoint.restore_from_snapshot(fresh, forged)
    assert fresh.dag.base_round == 0 and fresh.round == 0


def test_stale_nacks_do_not_count_after_catching_up():
    """A floor recorded while briefly behind must not combine with one
    later Byzantine nack into a fake f+1 quorum (round-4 review)."""
    p = Process(GC, 0, InMemoryTransport())
    p.round = 40
    p._on_sync_nack(
        BroadcastMessage(
            vertex=None, round=50, sender=1, kind="sync_nack", origin=0
        )
    )
    assert not p.state_transfer_needed  # 1 < f+1
    p.round = 100  # caught up via normal sync
    p._on_sync_nack(
        BroadcastMessage(
            vertex=None, round=10**9, sender=2, kind="sync_nack", origin=0
        )
    )
    # the stale floor-50 entry was purged; one live nack is not a quorum
    assert not p.state_transfer_needed
    assert list(p._horizon_nacks) == [2]


def _full_local_rounds(p: Process, hi: int, sources=(0, 1, 2)) -> None:
    """Rounds 1..hi from `sources` directly into the DAG (source 3 is the
    permanently-absent straggler whose history peers have pruned)."""
    from dag_rider_tpu.core.types import Vertex

    for r in range(1, hi + 1):
        prev = tuple(VertexID(r - 1, s) for s in sources)
        for s in sources:
            p.dag.insert(Vertex(id=VertexID(r, s), strong_edges=prev))
    p.round = hi


def test_attested_peer_floor_unwedges_blocked_buffer():
    """ADVICE r4: a node whose round is AHEAD of peers' floors but whose
    buffer is blocked on pruned straggler rounds must act on nacks whose
    floor exceeds the requested lo — not re-request unservable history
    forever. f+1 distinct floors above lo attest a pruned horizon, and
    the requester stops targeting blockers at/below it. Admission is
    deliberately untouched (round-5 review): f+1 floors prove ONE
    honest peer pruned, not that no honest peer can serve — blocked
    vertices stay buffered (bounded memory, zero traffic) in case a
    lower-floor peer serves their predecessors later; dropping them
    could forfeit that recovery and fork our delivered log."""
    from dag_rider_tpu.core.types import Vertex

    cfg = Config(
        n=4, coin="round_robin", propose_empty=True, sync_patience=1
    )  # gc_depth=None: the LOCAL floor never advances (the wedge case)
    p = Process(cfg, 0, InMemoryTransport())
    _full_local_rounds(p, 10)
    # three stragglers from source 3, all blocked:
    v_low = Vertex(  # inside the soon-attested horizon
        id=VertexID(6, 3),
        block=Block((b"low",)),
        strong_edges=(VertexID(5, 0), VertexID(5, 1), VertexID(5, 3)),
    )
    v_strong = Vertex(  # att+1, strong pred in attested history
        id=VertexID(9, 3),
        block=Block((b"strong",)),
        strong_edges=(VertexID(8, 0), VertexID(8, 1), VertexID(8, 3)),
    )
    v_weak = Vertex(  # above the horizon, missing weak target under it
        id=VertexID(10, 3),
        block=Block((b"weak",)),
        strong_edges=(VertexID(9, 0), VertexID(9, 1), VertexID(9, 2)),
        weak_edges=(VertexID(7, 3),),
    )
    for v in (v_low, v_strong, v_weak):
        p.on_message(BroadcastMessage(vertex=v, round=v.round, sender=3))
    p._started = True
    p.step()
    assert {v_low.id, v_strong.id, v_weak.id} <= p._buffered_ids

    # stuck -> sync request fires at lo = min blocker round (5).
    # Requests are unicast (pull gossip, round 11): capture both seams,
    # and settle the receipt watermark — the backlog-aware patience gate
    # holds while receipts are still arriving, and the on_message calls
    # above count as receipts.
    outbox = []
    p.transport.broadcast = lambda m: outbox.append(m)
    p.transport.enqueue = lambda dest, m: outbox.append(m)
    p._rx_at_patience = p.metrics.counters.get("msgs_received", 0)
    p._maybe_request_sync()
    reqs = [m for m in outbox if m.kind == "sync"]
    assert reqs and reqs[0].round == 5
    assert p._sync_last_lo == 5

    # f+1 = 2 distinct responders nack with floor 8 (> lo, <= our round)
    for sender in (1, 2):
        p._on_sync_nack(
            BroadcastMessage(
                vertex=None, round=8, sender=sender, kind="sync_nack",
                origin=0,
            )
        )
    assert p._attested_floor == 8
    assert not p.state_transfer_needed  # floors <= our round: no rewind
    # admission untouched: everything stays buffered (a lower-floor
    # peer may yet serve the predecessors), nothing was admitted
    assert {v_low.id, v_strong.id, v_weak.id} <= p._buffered_ids
    assert not p.dag.present(v_weak.id)
    # but the requester stops asking for the attested-pruned window —
    # the actual wedge: before the fix this re-requested lo=5 forever
    outbox.clear()
    p._sync_last_request = float("-inf")  # cooldown passed
    p._stuck_steps = 10**6
    p._rx_at_patience = p.metrics.counters.get("msgs_received", 0)
    p._maybe_request_sync()
    reqs = [m for m in outbox if m.kind == "sync"]
    assert reqs == [] or reqs[0].round > 8
    # the machine keeps running; ordering never touches the hole
    for _ in range(5):
        p.step()
    # if a lower-floor peer later serves the missing history, recovery
    # still happens: deliver the round-5..9 stragglers and watch the
    # whole chain admit
    from dag_rider_tpu.core.types import Vertex as _V

    for r in range(5, 10):
        prev = tuple(VertexID(r - 1, s) for s in (0, 1, 2))
        p.on_message(
            BroadcastMessage(
                vertex=_V(id=VertexID(r, 3), strong_edges=prev),
                round=r,
                sender=3,
            )
        )
    p.step()
    assert p.dag.present(v_low.id) and p.dag.present(v_strong.id)
    assert p.dag.present(v_weak.id)


def test_attested_floor_clips_byzantine_inflation():
    """A single Byzantine nack with a huge floor must not drag the
    attested floor past what an honest responder corroborates: the
    (f+1)-th largest reported value is the bound."""
    p = Process(GC, 0, InMemoryTransport())
    p.round = 10
    p._sync_last_lo = 5
    p._on_sync_nack(
        BroadcastMessage(
            vertex=None, round=10**9, sender=1, kind="sync_nack", origin=0
        )
    )
    assert p._attested_floor == 0  # one claim is not a quorum
    p._on_sync_nack(
        BroadcastMessage(
            vertex=None, round=8, sender=2, kind="sync_nack", origin=0
        )
    )
    assert p._attested_floor == 8  # clipped to the corroborated value


def test_snapshot_corruption_fuzz_never_crashes_or_corrupts():
    """Seeded fuzz over the untrusted-snapshot surface: random bit
    flips, truncations and splices must either refuse (False, receiver
    bit-untouched) or succeed with a self-consistent window — never
    raise, never commit partial state."""
    import numpy as np

    sim = _pruned_donor()
    donor = sim.processes[0]
    blob = bytearray(checkpoint.snapshot_bytes(donor))
    rng = np.random.default_rng(17)
    for trial in range(60):
        mutated = bytearray(blob)
        mode = trial % 4
        if mode == 0:  # random bit flips
            for _ in range(int(rng.integers(1, 8))):
                i = int(rng.integers(0, len(mutated)))
                mutated[i] ^= 1 << int(rng.integers(0, 8))
        elif mode == 1:  # truncation
            mutated = mutated[: int(rng.integers(0, len(mutated)))]
        elif mode == 2:  # splice a random chunk
            i = int(rng.integers(0, len(mutated)))
            mutated[i:i] = bytes(rng.integers(0, 256, 16, dtype=np.uint8))
        else:  # duplicate a tail chunk
            i = int(rng.integers(0, len(mutated)))
            mutated = mutated + mutated[i:]
        fresh = Process(GC, 0, InMemoryTransport())
        ok = checkpoint.restore_from_snapshot(fresh, bytes(mutated))
        if not ok:
            assert fresh.dag.base_round == 0 and fresh.round == 0
            assert len(fresh.dag.vertices) == GC.n  # genesis only
        else:
            # accepted: the window must be internally consistent
            assert fresh.dag.max_round - fresh.dag.base_round >= GC.gc_depth
            for v in fresh.dag.vertices.values():
                assert v.round >= fresh.dag.base_round
            fresh._started = True
            fresh.step()  # and the machine must still run


def test_snapshot_valid_json_wrong_shape_refused():
    """Valid-JSON-but-not-a-dict headers must take the False path, not
    raise (round-4 review; the bitflip fuzz can't produce these)."""
    for head in (b"[]", b"42", b'"x"', b"null"):
        blob = struct.pack("<I", len(head)) + head
        fresh = Process(GC, 0, InMemoryTransport())
        assert not checkpoint.restore_from_snapshot(fresh, blob)
        assert fresh.round == 0
