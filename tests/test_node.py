"""CLI node runner: keygen round-trip + a live 4-node localhost cluster."""

import json
import threading
import time

import pytest

from dag_rider_tpu import node as node_mod
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import threshold as th
from dag_rider_tpu.core.types import Block


def test_keygen_roundtrip(tmp_path):
    blob = node_mod.generate_keys(4, 2)
    reg, seeds, coin_keys = node_mod.load_keys(blob)
    assert reg.n == 4 and len(seeds) == 4
    # the loaded coin keys actually work end to end
    shares = {i: th.sign_share(coin_keys.share_sks[i], 3) for i in range(2)}
    sigma = th.aggregate(shares, 2)
    assert th.verify_group(coin_keys.group_pk, 3, sigma)
    # serialization is strict: corrupt pk rejected
    bad = bytearray(bytes.fromhex(blob["bls_group_pk"]))
    bad[5] ^= 0xFF
    with pytest.raises(ValueError):
        bls.g2_deserialize(bytes(bad))


def test_g2_serialize_identity_and_roundtrip():
    assert bls.g2_deserialize(bls.g2_serialize(None)) is None
    p = bls.g2_mul(12345)
    assert bls.g2_deserialize(bls.g2_serialize(p)) == p


def test_four_node_cluster_delivers_and_checkpoints(tmp_path):
    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    n = 4
    nodes = []
    # bind first so peers are known, then fill in the peer tables
    cfgs = []
    for i in range(n):
        cfgs.append(
            {
                "index": i,
                "n": n,
                "listen": "127.0.0.1:0",
                "peers": {},
                "keys": str(keys_path),
                "rbc": True,
                "verifier": "none",
                "coin": "threshold_bls",
                "checkpoint_dir": str(tmp_path / f"ckpt{i}"),
                "checkpoint_every_s": 0,  # only on stop
                "submit_interval_s": 0,
                "propose_empty": False,
            }
        )
        nodes.append(node_mod.Node(cfgs[i]))
    addrs = {i: f"127.0.0.1:{nd.net.bound_port}" for i, nd in enumerate(nodes)}
    for i, nd in enumerate(nodes):
        nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
    try:
        for nd in nodes:
            nd.start()
        for nd in nodes:
            for k in range(10):
                nd.submit(Block((f"n{nd.process.index}-b{k}".encode(),)))
        deadline = time.time() + 60
        while time.time() < deadline and not all(
            len(nd.delivered) >= n for nd in nodes
        ):
            time.sleep(0.05)
        assert all(len(nd.delivered) >= n for nd in nodes), [
            len(nd.delivered) for nd in nodes
        ]
    finally:
        for nd in nodes:
            nd.stop()
    # agreement on the common delivered prefix (by digest)
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in nd.delivered]
        for nd in nodes
    ]
    k = min(len(l) for l in logs)
    assert k >= n and all(l[:k] == logs[0][:k] for l in logs)
    # shutdown checkpoints exist and carry the final round
    from dag_rider_tpu.utils import checkpoint

    for i, nd in enumerate(nodes):
        assert checkpoint.latest_round(str(tmp_path / f"ckpt{i}")) == nd.process.round
    # transport counters visible through the process metrics snapshot
    snap = nodes[0].process.metrics.snapshot()
    assert snap.get("net_sends", 0) > 0
