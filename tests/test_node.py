"""CLI node runner: keygen round-trip + a live 4-node localhost cluster."""

import json
import time

import pytest

from dag_rider_tpu import node as node_mod
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import threshold as th
from dag_rider_tpu.core.types import Block


def test_keygen_roundtrip(tmp_path):
    blob = node_mod.generate_keys(4, 2)
    reg, seeds, coin_keys = node_mod.load_keys(blob)
    assert reg.n == 4 and len(seeds) == 4
    # the loaded coin keys actually work end to end
    shares = {i: th.sign_share(coin_keys.share_sks[i], 3) for i in range(2)}
    sigma = th.aggregate(shares, 2)
    assert th.verify_group(coin_keys.group_pk, 3, sigma)
    # serialization is strict: corrupt pk rejected
    bad = bytearray(bytes.fromhex(blob["bls_group_pk"]))
    bad[5] ^= 0xFF
    with pytest.raises(ValueError):
        bls.g2_deserialize(bytes(bad))


def test_g2_serialize_identity_and_roundtrip():
    assert bls.g2_deserialize(bls.g2_serialize(None)) is None
    p = bls.g2_mul(12345)
    assert bls.g2_deserialize(bls.g2_serialize(p)) == p


def test_four_node_cluster_delivers_and_checkpoints(tmp_path):
    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    n = 4
    nodes = []
    # bind first so peers are known, then fill in the peer tables
    cfgs = []
    for i in range(n):
        cfgs.append(
            {
                "index": i,
                "n": n,
                "listen": "127.0.0.1:0",
                "peers": {},
                "keys": str(keys_path),
                "rbc": True,
                "verifier": "none",
                "coin": "threshold_bls",
                "checkpoint_dir": str(tmp_path / f"ckpt{i}"),
                "checkpoint_every_s": 0,  # only on stop
                "submit_interval_s": 0,
                # Liveness requires the DAG to keep advancing: with a
                # finite workload and propose_empty=False the DAG halts
                # at the last proposed round, and a wave whose coin
                # leader was skipped can never be retro-committed by a
                # later wave (observed as a ~30% stall at one delivery
                # per node). Real deployments propose empty vertices for
                # exactly this reason.
                "propose_empty": True,
            }
        )
        nodes.append(node_mod.Node(cfgs[i]))
    addrs = {i: f"127.0.0.1:{nd.net.bound_port}" for i, nd in enumerate(nodes)}
    for i, nd in enumerate(nodes):
        nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
    try:
        for nd in nodes:
            nd.start()
        for nd in nodes:
            for k in range(10):
                nd.submit(Block((f"n{nd.process.index}-b{k}".encode(),)))
        deadline = time.time() + 60
        while time.time() < deadline and not all(
            len(nd.delivered) >= n for nd in nodes
        ):
            time.sleep(0.05)
        assert all(len(nd.delivered) >= n for nd in nodes), [
            len(nd.delivered) for nd in nodes
        ]
    finally:
        for nd in nodes:
            nd.stop()
    # agreement on the common delivered prefix (by digest)
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in nd.delivered]
        for nd in nodes
    ]
    k = min(len(l) for l in logs)
    assert k >= n and all(l[:k] == logs[0][:k] for l in logs)
    # shutdown checkpoints exist and carry the final round
    from dag_rider_tpu.utils import checkpoint

    for i, nd in enumerate(nodes):
        assert checkpoint.latest_round(str(tmp_path / f"ckpt{i}")) == nd.process.round
    # transport counters visible through the process metrics snapshot
    snap = nodes[0].process.metrics.snapshot()
    assert snap.get("net_sends", 0) > 0


def _free_ports(k):
    import socket

    socks, ports = [], []
    for _ in range(k):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_node_restart_from_checkpoint_catches_up(tmp_path):
    """Elastic recovery end to end over real gRPC: stop one node (its
    checkpoint persists), let the quorum advance without it, restart it
    from the checkpoint and assert it syncs back to the cluster head."""
    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    n = 4
    ports = _free_ports(n)
    peers = {str(i): f"127.0.0.1:{ports[i]}" for i in range(n)}

    def cfg_for(i):
        return {
            "index": i,
            "n": n,
            "listen": f"127.0.0.1:{ports[i]}",
            "peers": {k: v for k, v in peers.items() if int(k) != i},
            "keys": str(keys_path),
            "rbc": False,  # plain path; RBC catch-up covered in test_sync
            "verifier": "none",
            "coin": "round_robin",
            "checkpoint_dir": str(tmp_path / f"ckpt{i}"),
            "checkpoint_every_s": 0,
            "submit_interval_s": 0.05,  # steady client load
            "propose_empty": False,
        }

    nodes = [node_mod.Node(cfg_for(i)) for i in range(n)]
    try:
        for nd in nodes:
            nd.start()
        deadline = time.time() + 30
        while time.time() < deadline and not all(
            nd.process.round >= 5 for nd in nodes
        ):
            time.sleep(0.05)
        assert all(nd.process.round >= 5 for nd in nodes)

        # stop node 3; checkpoint written on stop
        nodes[3].stop()
        r_at_stop = nodes[3].process.round
        target = max(nd.process.round for nd in nodes[:3]) + 8
        deadline = time.time() + 45
        while time.time() < deadline and not all(
            nd.process.round >= target for nd in nodes[:3]
        ):
            time.sleep(0.05)
        assert all(nd.process.round >= target for nd in nodes[:3])

        # restart node 3 from its checkpoint (same port, same config)
        nodes[3] = node_mod.Node(cfg_for(3))
        assert nodes[3].process.round == r_at_stop  # restored, not fresh
        nodes[3].start()
        deadline = time.time() + 60
        while time.time() < deadline and (
            nodes[3].process.round < max(nd.process.round for nd in nodes[:3]) - 2
        ):
            time.sleep(0.05)
        head = max(nd.process.round for nd in nodes[:3])
        assert nodes[3].process.round >= head - 2, (
            nodes[3].process.round,
            head,
        )
        assert nodes[3].process.metrics.counters["sync_requested"] >= 1
        assert any(
            nd.process.metrics.counters.get("sync_served", 0) > 0
            for nd in nodes[:3]
        )
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass
    # safety held throughout: delivered prefixes agree (compare digests)
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in nd.delivered]
        for nd in nodes[:3]
    ]
    k = min(len(l) for l in logs)
    assert k > 0 and all(l[:k] == logs[0][:k] for l in logs)


def test_churn_restored_logs_stay_prefix_consistent(tmp_path):
    """Compact churn soak: kill/restart a random node several times under
    steady load; every node's *restored* total-order log (survives
    restarts via checkpoints) must stay prefix-consistent."""
    import random

    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    ports = _free_ports(4)
    peers = {str(i): f"127.0.0.1:{ports[i]}" for i in range(4)}

    def cfg_for(i):
        return {
            "index": i,
            "n": 4,
            "listen": f"127.0.0.1:{ports[i]}",
            "peers": {k: v for k, v in peers.items() if int(k) != i},
            "keys": str(keys_path),
            "rbc": True,
            "verifier": "none",
            "coin": "threshold_bls",
            "checkpoint_dir": str(tmp_path / f"ck{i}"),
            "checkpoint_every_s": 1.5,
            "submit_interval_s": 0.05,
            "propose_empty": False,
        }

    nodes = [node_mod.Node(cfg_for(i)) for i in range(4)]
    rng = random.Random(7)
    try:
        for nd in nodes:
            nd.start()
        for _ in range(3):
            time.sleep(5)
            victim = rng.randrange(4)
            nodes[victim].stop()
            time.sleep(1.0)
            nodes[victim] = node_mod.Node(cfg_for(victim))
            nodes[victim].start()
        time.sleep(4)
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass
    logs = [
        [(vid.round, vid.source) for vid in nd.process.delivered_log]
        for nd in nodes
    ]
    k = min(len(l) for l in logs)
    assert k > 10, f"too little delivered under churn: {[len(l) for l in logs]}"
    assert all(l[:k] == logs[0][:k] for l in logs)


def test_node_through_remote_verifier_sidecar(tmp_path):
    """The north star's deployment shape end-to-end from the shipped CLI
    surface (round-3 VERDICT weak #6): nodes configured with
    verifier="remote" ship every round's batch to a Verifier sidecar and
    still reach agreement; the sidecar actually sees the traffic."""
    from dag_rider_tpu.verifier.base import KeyRegistry
    from dag_rider_tpu.verifier.cpu import CPUVerifier
    from dag_rider_tpu.verifier.sidecar import VerifierSidecarServer

    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    reg, _, _ = node_mod.load_keys(json.loads(keys_path.read_text()))
    backend = CPUVerifier(reg)
    calls = []
    orig = backend.verify_batch
    backend.verify_batch = lambda vs: (calls.append(len(vs)), orig(vs))[1]
    sidecar = VerifierSidecarServer(backend, "127.0.0.1:0")
    try:
        n = 4
        nodes = []
        for i in range(n):
            nodes.append(
                node_mod.Node(
                    {
                        "index": i,
                        "n": n,
                        "listen": "127.0.0.1:0",
                        "peers": {},
                        "keys": str(keys_path),
                        "rbc": True,
                        "verifier": "remote",
                        "verifier_address": f"127.0.0.1:{sidecar.bound_port}",
                        "coin": "round_robin",
                        "propose_empty": True,
                        # MAC'd frames on the networked path, same as a
                        # production committee
                        "auth_master": "ab" * 32,
                    }
                )
            )
        addrs = {
            i: f"127.0.0.1:{nd.net.bound_port}" for i, nd in enumerate(nodes)
        }
        for i, nd in enumerate(nodes):
            nd.net._peers.update({j: a for j, a in addrs.items() if j != i})
        try:
            for nd in nodes:
                nd.start()
            for nd in nodes:
                for k in range(6):
                    nd.submit(Block((f"n{nd.process.index}-b{k}".encode(),)))
            deadline = time.time() + 60
            while time.time() < deadline and not all(
                len(nd.delivered) >= n for nd in nodes
            ):
                time.sleep(0.05)
            assert all(len(nd.delivered) >= n for nd in nodes), [
                len(nd.delivered) for nd in nodes
            ]
            logs = [
                [(v.id.round, v.id.source, v.digest()) for v in nd.delivered]
                for nd in nodes
            ]
            k = min(len(l) for l in logs)
            assert all(l[:k] == logs[0][:k] for l in logs)
            assert calls and sum(calls) >= n * (n - 1)  # sidecar did the work
            assert all(
                nd.process.metrics.counters.get("net_auth_rejects", 0) == 0
                for nd in nodes
            )
        finally:
            for nd in nodes:
                nd.stop()
    finally:
        sidecar.stop()


def test_node_config_plumbs_gc_depth(tmp_path):
    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    nd = node_mod.Node(
        {
            "index": 0,
            "n": 4,
            "listen": "127.0.0.1:0",
            "peers": {},
            "keys": str(keys_path),
            "rbc": False,
            "verifier": "none",
            "coin": "round_robin",
            "gc_depth": 24,
        }
    )
    try:
        assert nd.process.cfg.gc_depth == 24
    finally:
        nd.net.close()
