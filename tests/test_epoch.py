"""Epoch reconfiguration (ISSUE 20): validator-set changes ordered
through consensus itself.

Covered here:

- EpochManager boundary math + deterministic seed chaining (every
  process derives the identical transition from the identical ordered
  log — no out-of-band coordination),
- control-op codec round-trips and the wire epoch tag (epoch-0 bytes
  stay byte-identical to the pre-epoch format),
- the mempool control lane (EPOCH_MAGIC bypasses shedding, ships in its
  own block),
- end-to-end sim: a committed rotate op advances every honest process
  at the same wave boundary; stale pre-boundary messages are rejected
  at the wire gate; planted share-book / wave-memo entries from the
  finished epoch are dropped at the boundary,
- threshold-key rotation A/B: the rotated cluster stays live past the
  boundary and its pre-boundary committed prefix is byte-identical to a
  static-membership run,
- DAG memory flatness across >= 3 epochs (vertices_live_max regression).
"""

from __future__ import annotations

import dataclasses

import pytest

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import Simulation
from dag_rider_tpu.consensus.coin import ThresholdCoin
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, BroadcastMessage, EpochOp
from dag_rider_tpu.epoch import (
    EpochManager,
    EpochTransition,
    derive_epoch_keys,
)


# ---------------------------------------------------------------------------
# manager: boundary math + deterministic seed chain
# ---------------------------------------------------------------------------


def _op(nonce=0, kind="rotate", target=0):
    return EpochOp(kind, target, nonce, b"")


def test_manager_schedules_next_multiple_with_slack():
    m = EpochManager(epoch_waves=4)
    assert m.observe_op(_op(), wave=1)
    # next multiple of 4 with >= MIN_SLACK_WAVES of runway past wave 1
    assert m.boundary_wave == 4
    # a second distinct op before the boundary joins the same transition
    assert m.observe_op(_op(nonce=1), wave=2)
    assert m.boundary_wave == 4
    # duplicates (same encoded bytes) are dropped
    assert not m.observe_op(_op(nonce=1), wave=3)


def test_manager_boundary_needs_slack():
    m = EpochManager(epoch_waves=4)
    m.observe_op(_op(), wave=3)  # 4 would leave only 1 wave of runway
    assert m.boundary_wave == 8


def test_manager_advance_chains_seed_deterministically():
    def run():
        m = EpochManager(epoch_waves=4)
        m.observe_op(_op(nonce=7), wave=2)
        assert m.should_advance(4)
        return m.advance()

    a, b = run(), run()
    assert a == b  # frozen dataclass equality: epoch, boundary, seed, ops
    assert a.epoch == 1 and a.boundary_wave == 4 and a.first_wave == 5
    # a different op history yields a different seed
    m = EpochManager(epoch_waves=4)
    m.observe_op(_op(nonce=8), wave=2)
    assert m.advance().seed != a.seed


def test_manager_advance_across_skipped_boundary_wave():
    """Delivery can jump past the boundary wave (skipped leaders):
    should_advance fires on the first delivered wave >= boundary."""
    m = EpochManager(epoch_waves=4)
    m.observe_op(_op(), wave=1)
    assert not m.should_advance(3)
    assert m.should_advance(6)  # wave 4 and 5 had no committed leader
    t = m.advance()
    assert t.boundary_wave == 4 and m.epoch == 1
    assert m.boundary_wave is None  # no pending ops -> no next boundary


def test_manager_hold_round():
    m = EpochManager(epoch_waves=4)
    assert not m.hold_round(100, 4)  # no boundary pending
    m.observe_op(_op(), wave=1)
    assert not m.hold_round(16, 4)  # rounds of wave 4 may proceed
    assert m.hold_round(17, 4)  # first round of wave 5 is held
    m.advance()
    assert not m.hold_round(17, 4)


def test_derive_epoch_keys_modes():
    m = EpochManager(epoch_waves=2)
    m.observe_op(_op(), wave=1)
    t = m.advance()
    assert derive_epoch_keys(t, 4, 2, "none", 0) is None
    seeded = [derive_epoch_keys(t, 4, 2, "seed", i) for i in range(4)]
    # one dealer run, deterministic: every process derives the same
    # group key and its own distinct share secret
    assert all(k.group_pk == seeded[0].group_pk for k in seeded)
    assert len({k.share_sks[i] for i, k in enumerate(seeded)}) == 4
    dkg = [derive_epoch_keys(t, 4, 2, "dkg", i) for i in range(4)]
    assert all(k.group_pk == dkg[0].group_pk for k in dkg)
    # dealerless: each participant holds only its own secret share
    assert dkg[0].share_sks[1] is None and dkg[1].share_sks[1] is not None
    # resharing is keyed off the same transition seed but is a
    # different protocol: it must not degenerate into the dealer keys
    assert dkg[0].group_pk != seeded[0].group_pk


# ---------------------------------------------------------------------------
# codec: control ops + wire epoch tag
# ---------------------------------------------------------------------------


def test_epoch_op_roundtrip_and_rejects():
    op = EpochOp("join", 5, 12, b"\x01\x02")
    enc = codec.encode_epoch_op(op)
    assert enc.startswith(codec.EPOCH_MAGIC)
    assert codec.decode_epoch_op(enc) == op
    assert codec.epoch_op_of(enc) == op
    assert codec.epoch_op_of(b"ordinary payload") is None
    assert codec.epoch_op_of(codec.EPOCH_MAGIC + b"\xff") is None  # torn


def test_wire_epoch_zero_is_byte_identical():
    msg = BroadcastMessage(vertex=None, round=3, sender=1, kind="fetch")
    tagged = dataclasses.replace(msg, epoch=0)
    assert codec.encode_message(msg) == codec.encode_message(tagged)


def test_wire_epoch_roundtrip():
    msg = BroadcastMessage(
        vertex=None, round=3, sender=1, kind="fetch", epoch=9
    )
    enc = codec.encode_message(msg)
    got, off = codec.decode_message(enc, 0)
    assert off == len(enc)
    assert got.epoch == 9 and got.kind == "fetch" and got.round == 3


# ---------------------------------------------------------------------------
# mempool control lane
# ---------------------------------------------------------------------------


def test_mempool_control_lane_bypasses_shed_and_ships_alone():
    from dag_rider_tpu.config import MempoolConfig
    from dag_rider_tpu.mempool import Mempool

    mp = Mempool(MempoolConfig(cap=2, admit_high=0.5))
    # saturate the pool past the shed watermark, then submit a control
    # op: payloads shed, the reconfiguration op must not
    r0 = mp.submit([b"p1", b"p2", b"p3"], client="c", now=1.0)
    assert r0.shed > 0
    op = codec.encode_epoch_op(_op(nonce=3))
    r = mp.submit([op], client="c", now=1.0)
    assert r.accepted == 1 and r.shed == 0
    blocks = mp.build_blocks(2.0, force=True)
    assert blocks[0].transactions == (op,)  # its own block, first out


# ---------------------------------------------------------------------------
# end-to-end sim: boundary crossing, stale gate, book cleanup
# ---------------------------------------------------------------------------


def _epoch_cfg(**kw):
    kw.setdefault("n", 4)
    kw.setdefault("coin", "round_robin")
    kw.setdefault("propose_empty", True)
    kw.setdefault("epoch", True)
    kw.setdefault("epoch_waves", 4)
    return Config(**kw)


def _pump_until(sim, pred, iters=400, chunk=300):
    for _ in range(iters):
        if pred():
            return True
        sim.run(max_messages=chunk)
    return pred()


def _min_epoch(sim):
    return min(p.epoch_mgr.epoch for p in sim.processes)


def test_sim_epoch_advances_everywhere_at_same_boundary():
    sim = Simulation(_epoch_cfg())
    sim.submit_blocks(per_process=2)
    op = codec.encode_epoch_op(_op(nonce=1))
    sim.processes[0].submit(Block((op,)))
    assert _pump_until(sim, lambda: _min_epoch(sim) >= 1)
    sim.check_agreement()
    boundaries = {
        p.epoch_mgr.history[-1].boundary_wave for p in sim.processes
    }
    seeds = {p.epoch_mgr.seed for p in sim.processes}
    assert len(boundaries) == 1 and len(seeds) == 1
    # the control op itself committed (it is an ordinary ordered tx)
    assert any(
        op in v.block.transactions
        for v in sim.deliveries[0]
        if v.block is not None
    )
    # the cluster keeps deciding waves after the boundary
    b = next(iter(boundaries))
    assert _pump_until(
        sim,
        lambda: min(p.decided_wave for p in sim.processes) >= b + 1,
    )


def test_sim_epoch_determinism():
    def run(seed):
        sim = Simulation(_epoch_cfg())
        sim.submit_blocks(per_process=2)
        sim.processes[0].submit(
            Block((codec.encode_epoch_op(_op(nonce=seed)),))
        )
        assert _pump_until(sim, lambda: _min_epoch(sim) >= 1)
        p = sim.processes[0]
        return p.epoch_mgr.seed, p.epoch_mgr.history[-1].boundary_wave

    assert run(5) == run(5)
    assert run(5)[0] != run(6)[0]  # op bytes feed the seed chain


def test_stale_epoch_message_rejected_at_wire_gate():
    sim = Simulation(_epoch_cfg())
    sim.submit_blocks(per_process=2)
    sim.processes[0].submit(Block((codec.encode_epoch_op(_op()),)))
    assert _pump_until(sim, lambda: _min_epoch(sim) >= 1)
    p = sim.processes[1]
    before = p.metrics.counters["epoch_stale_rejected"]
    donor = sim.deliveries[1][-1]
    p.on_message(
        BroadcastMessage(
            vertex=donor, round=donor.id.round, sender=donor.id.source,
            epoch=0,
        )
    )
    assert p.metrics.counters["epoch_stale_rejected"] == before + 1
    # control frames are gated too; sync stays exempt so a straggler
    # behind the boundary can still discover it is behind
    before = p.metrics.counters["epoch_stale_rejected"]
    p.on_message(
        BroadcastMessage(
            vertex=None, round=0, sender=2, kind="sync", epoch=0
        )
    )
    assert p.metrics.counters["epoch_stale_rejected"] == before


def test_boundary_drops_finished_epoch_books():
    """Satellite: wave-keyed books from the finished epoch must not
    survive the boundary — plant entries and watch them go."""
    sim = Simulation(_epoch_cfg())
    sim.submit_blocks(per_process=2)
    p = sim.processes[0]
    # planted leak: stale wave-attempt memo + pending-wave entries that
    # a finished epoch would otherwise carry forever
    p._wave_try_memo[1] = (0, 0)
    p._pending_waves.add(1)
    sim.processes[0].submit(Block((codec.encode_epoch_op(_op()),)))
    assert _pump_until(sim, lambda: _min_epoch(sim) >= 1)
    b = p.epoch_mgr.history[-1].boundary_wave
    assert all(w > b for w in p._wave_try_memo)
    assert all(w > b for w in p._pending_waves)


def test_threshold_coin_rotation_and_prune_books():
    """ThresholdCoin.rotate swaps the key schedule at first_wave and
    clears cached sigmas/attempts from that wave on; prune_below drops
    schedule entries and share books wholly below the GC floor."""
    from dag_rider_tpu.crypto import threshold as th

    keys = th.ThresholdKeys.generate(4, 2, seed=b"epoch-test-old")
    coin = ThresholdCoin(keys, 0, 4)
    coin._sigma[3] = b"sigma"
    coin._tried_at[3] = 1
    coin._shares[3] = {0: b"x"}
    coin._sigma[7] = b"sigma7"
    m = EpochManager(epoch_waves=4)
    m.observe_op(_op(), wave=2)
    t = m.advance()
    new = derive_epoch_keys(t, 4, 2, "seed", 0)
    coin.rotate(new, from_wave=t.first_wave)
    # pre-boundary sigma survives (waves < first_wave already settled),
    # post-boundary cache is invalidated
    assert 3 in coin._sigma and 7 not in coin._sigma
    assert coin._keys_for(t.boundary_wave) is keys
    assert coin._keys_for(t.first_wave) is new
    coin.prune_below(t.first_wave)
    assert 3 not in coin._sigma and 3 not in coin._shares
    assert all(first >= t.first_wave for first, _ in coin._schedule[1:])


def _threshold_factory(n, seed=b"epoch-ab"):
    from dag_rider_tpu.crypto import threshold as th

    keys = th.ThresholdKeys.generate(n, (n - 1) // 3 + 1, seed=seed)
    return lambda i: ThresholdCoin(keys, i, n)


@pytest.mark.slow
def test_rotation_ab_pre_boundary_prefix_identical():
    """Key-rotation acceptance: with real per-process threshold coins,
    an epoch boundary rotates every share key in lockstep; the cluster
    stays live past the boundary and the committed prefix up to the
    boundary wave is byte-identical to a static-membership run fed the
    same transactions."""
    n = 4
    wl = 4

    def run(epoch_on):
        cfg = _epoch_cfg(
            n=n, coin="threshold_bls", epoch=epoch_on, epoch_waves=4,
            epoch_rotate="seed",
        )
        sim = Simulation(cfg, coin_factory=_threshold_factory(n))
        sim.submit_blocks(per_process=2)
        op = codec.encode_epoch_op(_op(nonce=2))
        sim.processes[0].submit(Block((op,)))
        if epoch_on:
            ok = _pump_until(
                sim,
                lambda: _min_epoch(sim) >= 1
                and min(p.decided_wave for p in sim.processes) >= 5,
                iters=900,
            )
        else:
            ok = _pump_until(
                sim,
                lambda: min(p.decided_wave for p in sim.processes) >= 5,
                iters=900,
            )
        assert ok
        sim.check_agreement()
        return sim

    rot = run(True)
    static = run(False)
    assert all(
        p.metrics.counters["epoch_rotations"] >= 1 for p in rot.processes
    )
    b = rot.processes[0].epoch_mgr.history[-1].boundary_wave
    cut = b * wl

    def prefix(sim):
        return [
            (v.id.round, v.id.source, v.digest())
            for v in sim.deliveries[0]
            if v.id.round <= cut
        ]

    assert prefix(rot) == prefix(static)
    # no acked tx lost across the boundary: everything submitted to the
    # rotated run committed somewhere in its log
    delivered = {
        tx
        for v in rot.deliveries[0]
        if v.block is not None
        for tx in v.block.transactions
    }
    assert codec.encode_epoch_op(_op(nonce=2)) in delivered


def test_vertices_live_max_flat_across_three_epochs():
    """Satellite: DAG memory must stay flat as epochs settle — the GC
    floor advances with each boundary instead of accreting history."""
    cfg = _epoch_cfg(epoch_waves=2, gc_depth=16, epoch_gc=0)
    sim = Simulation(cfg)
    sim.submit_blocks(per_process=2)
    marks = []
    for k in range(3):
        sim.processes[0].submit(
            Block((codec.encode_epoch_op(_op(nonce=10 + k)),))
        )
        assert _pump_until(
            sim, lambda k=k: _min_epoch(sim) >= k + 1, iters=900
        )
        marks.append(
            max(
                p.metrics.counters["vertices_live_max"]
                for p in sim.processes
            )
        )
    assert _min_epoch(sim) >= 3
    # flatness: the high-water mark settles after the first epoch — the
    # window the GC keeps is bounded by waves+depth, not by history
    assert marks[-1] <= marks[0] + cfg.n * cfg.wave_length
    bound = cfg.n * (
        cfg.epoch_waves * cfg.wave_length + cfg.gc_depth + 4 * cfg.wave_length
    )
    assert marks[-1] <= bound
