"""Byzantine adversary suite + WAN transport, end to end.

Two layers of coverage:

- unit tests for the WAN machinery (per-link latency resolution, the
  virtual clock, partitions that hold-then-heal) and the behavior
  factory / scenario resolution rules;
- per-adversary :func:`run_scenario` runs at n=4 asserting BOTH that
  every invariant held (run_scenario raises otherwise) AND that the
  attack genuinely ran — the report's detection counters are non-zero,
  so a silently disarmed adversary cannot produce a vacuous green.

The matching negative (split equivocation without RBC really breaking
agreement) lives in tests/test_invariants.py.
"""

import pytest

from dag_rider_tpu.consensus.adversary import ADVERSARIES, make_behavior
from dag_rider_tpu.consensus.scenarios import (
    Scenario,
    build_topology,
    default_matrix,
    run_scenario,
)
from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.faults import (
    FaultPlan,
    FaultyTransport,
    LinkPlan,
    Partition,
    WanTopology,
)

# -- behavior factory --------------------------------------------------------


def test_factory_covers_every_advertised_adversary():
    for kind in ADVERSARIES:
        b = make_behavior(kind, seed=3)
        assert b.name == kind
        assert set(b.stats) >= {"mutated", "withheld", "extra_sent"}


def test_factory_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown adversary"):
        make_behavior("omniscient")


# -- WAN topology units ------------------------------------------------------


def test_regions_link_resolution():
    topo = WanTopology.regions(4, k=2)
    # node i -> region i % 2: (0, 2) share a region, (0, 1) do not
    assert topo.link(0, 2).rtt_s == pytest.approx(0.002)
    assert topo.link(0, 1).rtt_s == pytest.approx(0.04)
    # explicit per-link override beats the region rule
    topo.links[(0, 1)] = LinkPlan(rtt_s=0.5)
    assert topo.link(0, 1).rtt_s == pytest.approx(0.5)
    assert topo.link(1, 0).rtt_s == pytest.approx(0.04)


def test_partition_severs_only_cross_group_pairs():
    p = Partition(start_s=1.0, heal_s=2.0, groups=((0, 1), (2,)))
    assert p.severed(0, 2) and p.severed(2, 1)
    assert not p.severed(0, 1)
    assert not p.severed(0, 3)  # node 3 is in no group: unaffected
    assert not p.active(0.5) and p.active(1.0) and not p.active(2.0)


def _wan_transport(topo, n=2):
    # the inner broker excludes the sender from fan-out, so a broadcast
    # from node 0 only exercises the 0->1, 0->2, ... links
    tp = FaultyTransport(FaultPlan(), topology=topo)
    got = {i: [] for i in range(n)}
    for i in range(n):
        tp.subscribe(i, got[i].append)
    return tp, got


def test_virtual_clock_delivers_at_link_latency():
    topo = WanTopology(
        default=LinkPlan(rtt_s=0.0),
        links={(0, 1): LinkPlan(rtt_s=0.02)},  # one-way 10ms on 0->1 only
    )
    tp, got = _wan_transport(topo)
    tp.broadcast(BroadcastMessage(vertex=None, round=1, sender=0))
    tp.pump()
    assert got[1] == [] and tp.pending == 1  # in flight on the slow link
    assert tp.advance(0.005) == 0 and got[1] == []
    assert tp.advance(0.006) == 1  # now=11ms >= 10ms release
    assert len(got[1]) == 1 and tp.pending == 0
    assert tp.stats["held_link"] == 1
    # the default zero-latency link (1->0) delivers synchronously at pump
    tp.broadcast(BroadcastMessage(vertex=None, round=1, sender=1))
    tp.pump()
    assert len(got[0]) == 1 and tp.pending == 0


def test_partition_holds_then_heals():
    topo = WanTopology(
        default=LinkPlan(rtt_s=0.0),
        partitions=(
            Partition(start_s=0.0, heal_s=1.0, groups=((0, 1), (2,))),
        ),
    )
    tp, got = _wan_transport(topo, n=3)
    tp.broadcast(BroadcastMessage(vertex=None, round=1, sender=0))
    tp.pump()
    assert len(got[1]) == 1  # same side: unaffected
    assert got[2] == [] and tp.stats["held_partition"] == 1
    tp.advance(0.9)
    assert got[2] == []  # still dark
    tp.advance(0.2)  # crosses heal_s=1.0
    assert len(got[2]) == 1  # held, never lost
    # after heal the cut is gone entirely
    tp.broadcast(BroadcastMessage(vertex=None, round=2, sender=0))
    tp.pump()
    assert len(got[2]) == 2


def test_flush_delayed_fast_forwards_the_wan_clock():
    topo = WanTopology(default=LinkPlan(rtt_s=10.0))
    tp, got = _wan_transport(topo)
    tp.broadcast(BroadcastMessage(vertex=None, round=1, sender=0))
    tp.pump()
    assert got[1] == [] and tp.pending == 1
    assert tp.flush_delayed() == 1
    assert len(got[1]) == 1 and tp.pending == 0
    assert tp.now >= 5.0  # clock jumped past the release time


# -- scenario resolution rules ----------------------------------------------


def test_scenario_validates_names():
    with pytest.raises(ValueError, match="unknown adversary"):
        Scenario(adversary="omniscient")
    with pytest.raises(ValueError, match="unknown WAN profile"):
        Scenario(wan="interplanetary")


def test_scenario_resolution_defaults():
    assert Scenario().resolved_rbc() is False
    assert Scenario(adversary="equivocate_split").resolved_rbc() is True
    assert Scenario(adversary="equivocate").resolved_rbc() is False
    assert (
        Scenario(adversary="equivocate", wan="regions").resolved_rbc()
        is True
    )
    assert Scenario(adversary="garbage_coin").coin_kind() == "threshold_bls"
    assert Scenario(adversary="withhold").coin_kind() == "round_robin"
    assert build_topology(Scenario(), duration=1.0) is None
    topo = build_topology(Scenario(wan="partition", n=4), duration=1.0)
    assert len(topo.partitions) == 1
    # the cut severs the honest TAIL (byzantine nodes are low indices)
    assert topo.partitions[0].groups == ((0, 1, 2), (3,))


def test_default_matrix_covers_every_adversary_and_a_partition():
    scs = default_matrix(n=4)
    kinds = {sc.adversary for sc in scs}
    assert kinds >= set(ADVERSARIES)
    assert any(sc.wan == "partition" for sc in scs)


# -- end-to-end scenarios (each one: invariants pass + attack non-vacuous) ---


def test_clean_lan_baseline():
    r = run_scenario(Scenario(n=4, seed=0))
    assert r["decided_waves"]["min"] >= 2
    assert r["audit"]["lost"] == 0 and r["audit"]["duplicates"] == 0
    assert r["monitor"]["observed"] > 0
    assert r["equivocations_detected"] == 0 and r["edge_rejects"] == 0


def test_equivocate_is_detected_and_contained():
    r = run_scenario(Scenario(n=4, adversary="equivocate", seed=0))
    assert r["byzantine"] == [0] and r["rbc"] is False
    assert r["behavior"]["mutated"] > 0
    # FIFO first-wins: every honest node flags the second variant
    assert r["equivocations_detected"] > 0
    assert r["decided_waves"]["min"] >= 1


def test_equivocate_split_is_safe_under_rbc():
    r = run_scenario(Scenario(n=4, adversary="equivocate_split", seed=0))
    assert r["rbc"] is True  # resolution rule: split forces Bracha
    assert r["behavior"]["mutated"] > 0  # variants really were forged
    assert r["decided_waves"]["min"] >= 1
    # tests/test_invariants.py proves the same scenario FAILS without RBC


def test_withhold_forces_sync_recovery():
    r = run_scenario(Scenario(n=4, adversary="withhold", seed=0))
    assert r["behavior"]["withheld"] > 0
    # victims recover the withheld slots through anti-entropy
    assert r["sync_served"] > 0
    assert r["decided_waves"]["min"] >= 1


def test_invalid_edges_are_rejected_at_admission():
    r = run_scenario(Scenario(n=4, adversary="invalid_edges", seed=0))
    assert r["behavior"]["mutated"] > 0
    assert r["edge_rejects"] > 0  # every forgery bounced at the gate
    assert r["decided_waves"]["min"] >= 2  # and progress is undisturbed


def test_garbage_coin_shares_are_filtered():
    r = run_scenario(Scenario(n=4, adversary="garbage_coin", seed=0))
    assert r["coin"] == "threshold_bls"
    assert r["behavior"]["mutated"] > 0  # poisoned shares were emitted
    # aggregation failed at least once and the batch filter excised them
    assert r["coin_filtered"] > 0
    assert r["decided_waves"]["min"] >= 1


def test_partition_heals_without_loss():
    r = run_scenario(Scenario(n=4, wan="partition", seed=0))
    assert r["transport"]["held_partition"] > 0  # the cut really bit
    assert r["audit"]["lost"] == 0
    # the severed straggler catches up after heal + drain
    assert r["decided_waves"]["min"] >= 1


@pytest.mark.slow
def test_equivocate_under_regions_jitter():
    """Jittery inter-region links reorder the two variants per
    destination — the resolution rule turns RBC on, and agreement must
    hold end to end."""
    r = run_scenario(
        Scenario(n=4, adversary="equivocate", wan="regions", seed=0)
    )
    assert r["rbc"] is True
    assert r["behavior"]["mutated"] > 0
    assert r["decided_waves"]["min"] >= 1
