"""BLS12-381 Pallas group kernel: the in-kernel limb math IS the oracle
math.

Interpret-mode execution of the full ~30k-op addition body measures in
minutes per launch, so (unlike the small Ed25519 kernels) bit-identity is
asserted at the layer that actually carries the risk: the kernel body
helpers (_carry33/_mul33/_padd381_core) are plain traceable functions
over row lists — they are called here DIRECTLY on [1, T] rows and
compared against ops.field381 / ops.bls_msm.padd, which the host-oracle
tests already pin to the reference arithmetic. The pallas_call plumbing
(BlockSpecs, row packing) is covered structurally via jax.eval_shape;
execution on a real TPU backend is exercised by the bench MSM rung
(DAGRIDER_MSM_PALLAS).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dag_rider_tpu.ops import bls_msm, field381 as F
from dag_rider_tpu.ops import pallas_group381 as PG

T = 5  # odd lane count: no accidental power-of-two alignment luck


def _rows(arr: np.ndarray):
    """[T, 33] -> kernel row list of [1, T]."""
    return [jnp.asarray(arr[:, i][None, :]) for i in range(F.LIMBS)]


def _unrows(rows) -> np.ndarray:
    """row list of [1, T] -> [T, 33]."""
    return np.concatenate([np.asarray(r) for r in rows], axis=0).T


@pytest.fixture(scope="module")
def operands():
    rng = np.random.default_rng(7)
    # reduced-range signed limbs (the invariant every op accepts)
    a = rng.integers(-(1 << 7), (1 << 12), (T, F.LIMBS)).astype(np.int32)
    b = rng.integers(-(1 << 7), (1 << 12), (T, F.LIMBS)).astype(np.int32)
    return a, b


def test_carry_add_sub_match_field381(operands):
    a, b = operands
    np.testing.assert_array_equal(
        _unrows(PG._carry33(_rows(a + b))), np.asarray(F.carry(a + b))
    )
    np.testing.assert_array_equal(
        _unrows(PG._add33(_rows(a), _rows(b))), np.asarray(F.add(a, b))
    )
    np.testing.assert_array_equal(
        _unrows(PG._sub33(_rows(a), _rows(b))), np.asarray(F.sub(a, b))
    )
    np.testing.assert_array_equal(
        _unrows(PG._mul_small33(_rows(a), 12)),
        np.asarray(F.mul_small(a, 12)),
    )


def test_mul33_matches_field381_mul(operands):
    a, b = operands
    np.testing.assert_array_equal(
        _unrows(PG._mul33(_rows(a), _rows(b))), np.asarray(F.mul(a, b))
    )
    # and the reduction really is mod p
    got = F.from_limbs(np.asarray(F.canonical(F.mul(a, b)))[0])
    want = (
        F.from_limbs(a[0].astype(np.int64))
        * F.from_limbs(b[0].astype(np.int64))
    ) % F.P_INT
    assert got == want


def test_padd_core_matches_bls_msm_padd():
    """The whole kernel addition body vs the jnp complete addition, on
    REAL curve points (doubling, mixed, identity-involving cases all flow
    through the complete formulas)."""
    from dag_rider_tpu.crypto import bls12381 as bls

    pts = []
    acc = bls.G1_GEN
    for _ in range(T):
        pts.append(acc)
        acc = bls.g1_double(acc)
    a = np.stack([np.stack([F.to_limbs(p[0]), F.to_limbs(p[1]), F.ONE]) for p in pts])
    b = np.roll(a, 1, axis=0)
    b[0] = np.stack([F.ZERO, F.ONE, F.ZERO])  # identity operand too
    pa = tuple(jnp.asarray(a[:, c]) for c in range(3))
    pb = tuple(jnp.asarray(b[:, c]) for c in range(3))
    want = bls_msm.padd(pa, pb)

    rows_a = [[_rows(a[:, c])[i] for i in range(F.LIMBS)] for c in range(3)]
    rows_b = [[_rows(b[:, c])[i] for i in range(F.LIMBS)] for c in range(3)]
    got = PG._padd381_core(
        [rows_a[0], rows_a[1], rows_a[2]], [rows_b[0], rows_b[1], rows_b[2]]
    )
    for c in range(3):
        np.testing.assert_array_equal(_unrows(got[c]), np.asarray(want[c]))


def test_padd381_pallas_program_traces():
    """pallas_call plumbing: block specs, row packing, output shape."""
    n = 256
    spec = jax.ShapeDtypeStruct((PG.ROWS, n), jnp.int32)
    out = jax.eval_shape(lambda p, q: PG.padd381_xx(p, q), spec, spec)
    assert out.shape == (PG.ROWS, n) and out.dtype == jnp.int32
    ent = jax.ShapeDtypeStruct((64, 8, 3, F.LIMBS), jnp.int32)
    out = jax.eval_shape(lambda e: PG.tree_sum_xyz381(e), ent)
    assert out.shape == (64, 3, F.LIMBS)


def test_msm_kernel_pallas_impl_traces():
    """The full MSM program with the pallas tree engine traces end to
    end (impl plumbed through window_sums)."""
    t = 256
    nib = jax.ShapeDtypeStruct((t, 64), jnp.int32)
    co = jax.ShapeDtypeStruct((t, F.LIMBS), jnp.int32)
    out = jax.eval_shape(
        lambda n, x, y, z: bls_msm.msm_kernel(n, x, y, z, impl="pallas"),
        nib, co, co, co,
    )
    assert tuple(o.shape for o in out) == ((F.LIMBS,),) * 3


def test_msm_impl_selection(monkeypatch):
    assert bls_msm.msm_impl(64) == "jnp"  # sub-lane batches stay portable
    monkeypatch.setenv("DAGRIDER_MSM_PALLAS", "0")
    assert bls_msm.msm_impl(4096) == "jnp"
