"""DagState tests: the five reference path subtests run against the host
mirrors (``process_internal_test.go:20-83``), plus insert/query invariants."""

import pytest

from dag_rider_tpu import Config
from dag_rider_tpu.consensus import DagState
from dag_rider_tpu.core.types import Vertex, VertexID

from fixtures import figure1_vertices


@pytest.fixture()
def fig1_state():
    cfg = Config(n=4, max_rounds=8)
    st = DagState(cfg)
    for v in figure1_vertices():
        st.insert(v)
    return st


def test_path_strong_consecutive(fig1_state):
    assert fig1_state.path(VertexID(3, 0), VertexID(2, 2), strong_only=True)


def test_path_strong_two_rounds(fig1_state):
    assert fig1_state.path(VertexID(3, 2), VertexID(1, 3), strong_only=True)


def test_path_weak(fig1_state):
    assert fig1_state.path(VertexID(4, 0), VertexID(2, 3), strong_only=False)
    # weak edge must NOT count as a strong path
    assert not fig1_state.path(VertexID(4, 0), VertexID(2, 3), strong_only=True)


def test_path_hybrid(fig1_state):
    assert fig1_state.path(VertexID(4, 0), VertexID(1, 0), strong_only=False)


def test_path_negative(fig1_state):
    assert not fig1_state.path(VertexID(3, 2), VertexID(2, 3), strong_only=False)


def test_path_identity_and_direction(fig1_state):
    v = VertexID(2, 1)
    assert fig1_state.path(v, v)
    # paths only go down in rounds
    assert not fig1_state.path(VertexID(1, 0), VertexID(2, 0))


def test_present_and_round_size(fig1_state):
    assert fig1_state.present(VertexID(4, 0))
    assert not fig1_state.present(VertexID(5, 0))
    assert not fig1_state.present(VertexID(4, 9) if False else VertexID(7, 0))
    assert fig1_state.round_size(1) == 4
    assert fig1_state.round_size(9) == 0


def test_insert_validation():
    cfg = Config(n=4)
    st = DagState(cfg)
    st.insert(Vertex(id=VertexID(0, 0)))
    with pytest.raises(ValueError):
        st.insert(Vertex(id=VertexID(0, 0)))  # duplicate
    with pytest.raises(ValueError):
        # strong edge must target round-1
        st.insert(
            Vertex(id=VertexID(2, 1), strong_edges=(VertexID(0, 0),))
        )


def test_capacity_growth():
    cfg = Config(n=4, max_rounds=8)
    st = DagState(cfg)
    for i in range(4):
        st.insert(Vertex(id=VertexID(0, i)))
    prev = {VertexID(0, i) for i in range(4)}
    for r in range(1, 40):
        for i in range(4):
            st.insert(
                Vertex(
                    id=VertexID(r, i),
                    strong_edges=tuple(VertexID(r - 1, j) for j in range(4)),
                )
            )
    assert st.max_round == 39
    assert st.path(VertexID(39, 0), VertexID(0, 3), strong_only=True)


def test_dense_snapshot_matches_kernels(fig1_state):
    exists, strong = fig1_state.dense_snapshot()
    assert exists.shape == (5, 4) and strong.shape == (5, 4, 4)
    assert exists.all()
    # strong stack view for the wave-commit kernel: rounds (1, 4] top-first
    stack = fig1_state.strong_stack(4, 1)
    assert stack.shape == (3, 4, 4)
    assert (stack[0] == strong[4]).all()
    assert (stack[2] == strong[2]).all()
