"""Bracha reliable broadcast: consistency under equivocation, totality,
and the consensus pipeline running over the RBC stage.

The reference's transport is "reliable" by fiat (``transport.go:5``) and its
equivocation story is nonexistent (SURVEY.md D10); round-1 review showed an
equivocator could get *different signed payloads admitted at different
honest nodes*. These tests pin the fix: with RbcTransport, at most one
payload per (round, source) slot is ever delivered anywhere, and
``Simulation.check_agreement`` now compares delivered digests.
"""


from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.transport.rbc import RbcTransport


def mk_vertex(source=0, rnd=1, payload=b"tx"):
    return Vertex(
        id=VertexID(rnd, source),
        block=Block((payload,)),
        strong_edges=(VertexID(rnd - 1, 0), VertexID(rnd - 1, 1), VertexID(rnd - 1, 2)),
    )


def build_cluster(n=4, f=1):
    broker = InMemoryTransport()
    rbcs, sinks = [], []
    for i in range(n):
        rbc = RbcTransport(broker, i, n, f)
        sink = []
        rbc.subscribe(i, sink.append)
        rbcs.append(rbc)
        sinks.append(sink)
    return broker, rbcs, sinks


def test_honest_broadcast_delivers_everywhere_once():
    broker, rbcs, sinks = build_cluster()
    v = mk_vertex(source=0)
    rbcs[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    broker.pump()
    # sender does not self-deliver (its Process inserts its own vertex)
    assert sinks[0] == []
    for sink in sinks[1:]:
        assert [m.vertex.digest() for m in sink] == [v.digest()]


def test_equivocation_cannot_diverge_and_fetch_recovers_payload():
    """Byzantine p0 sends payload A to p1 and payload B to p2/p3, then
    echoes B itself. B reaches echo quorum; p1 must deliver B (via FETCH —
    it only ever held A), and nobody delivers A."""
    broker, rbcs, sinks = build_cluster()
    va = mk_vertex(source=0, payload=b"A")
    vb = mk_vertex(source=0, payload=b"B")
    broker.enqueue(1, BroadcastMessage(vertex=va, round=1, sender=0))
    for dest in (2, 3):
        broker.enqueue(dest, BroadcastMessage(vertex=vb, round=1, sender=0))
    # the equivocator's own (lying) echo for B
    broker.broadcast(
        BroadcastMessage(
            vertex=None,
            round=1,
            sender=0,
            kind="echo",
            origin=0,
            digest=vb.digest(),
        )
    )
    broker.pump()
    for i in (1, 2, 3):
        assert [m.vertex.digest() for m in sinks[i]] == [vb.digest()], i
    assert all(m.vertex.block.transactions == (b"B",) for s in sinks[1:] for m in s)


def test_forged_sender_cannot_hijack_a_slot():
    """A Byzantine peer (p3) sends a VAL whose vertex claims slot (1, p0)
    before p0's real broadcast. The forgery must be ignored (sender stamp
    != vertex source), and p0's genuine vertex must still deliver."""
    broker, rbcs, sinks = build_cluster()
    forged = mk_vertex(source=0, payload=b"forged")
    real = mk_vertex(source=0, payload=b"real")
    # forgery arrives first, stamped by its actual sender p3
    broker.broadcast(BroadcastMessage(vertex=forged, round=1, sender=3))
    broker.pump()
    assert all(not s for s in sinks)
    rbcs[0].broadcast(BroadcastMessage(vertex=real, round=1, sender=0))
    broker.pump()
    for sink in sinks[1:]:
        assert [m.vertex.block.transactions for m in sink] == [(b"real",)]


def test_minority_equivocation_delivers_nothing():
    """Conflicting VALs split 1/2 with no extra votes: neither digest can
    reach the 2f+1 echo quorum, so no honest process delivers anything —
    consistency preserved by silence."""
    broker, rbcs, sinks = build_cluster()
    va = mk_vertex(source=0, payload=b"A")
    vb = mk_vertex(source=0, payload=b"B")
    broker.enqueue(1, BroadcastMessage(vertex=va, round=1, sender=0))
    broker.enqueue(2, BroadcastMessage(vertex=vb, round=1, sender=0))
    broker.enqueue(3, BroadcastMessage(vertex=vb, round=1, sender=0))
    broker.pump()
    assert all(not s for s in sinks)


def test_consensus_pipeline_over_rbc():
    """Full DAG-Rider over the RBC stage: agreement (by digest) and waves
    decided, with RBC control traffic riding the same broker."""
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    sim = Simulation(cfg, rbc=True)
    sim.submit_blocks(per_process=6)
    sim.run(max_messages=30_000)
    sim.check_agreement()
    assert any(p.metrics.counters["waves_decided"] >= 1 for p in sim.processes)
    delivered = sum(len(d) for d in sim.deliveries)
    assert delivered > 0
    ignored = sum(p.metrics.counters["msgs_ignored_kind"] for p in sim.processes)
    assert ignored == 0, "control traffic must never reach a Process"


def test_equivocating_sender_with_rbc_stays_consistent():
    """The round-1 gap scenario, closed: a FaultyTransport equivocator
    under the RBC stage cannot get divergent payloads admitted; delivered
    content is identical at all honest processes (digest-level agreement)."""
    plan = FaultPlan(equivocators=(3,), seed=9)
    tp = FaultyTransport(plan)
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    sim = Simulation(cfg, transport=tp, rbc=True)
    sim.submit_blocks(per_process=4)
    sim.run(max_messages=30_000)
    sim.check_agreement()
    assert tp.stats["equivocated"] > 0  # the attack actually fired
    delivered = sum(len(d) for d in sim.deliveries)
    assert delivered > 0
