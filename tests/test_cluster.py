"""Multi-process cluster harness (round 20).

Fast half: the harness's pure pieces — workspace/directory layout, WAL
and log parsing (torn tails included), re-injection set arithmetic, the
rejoin-aware audit, and the seeded fault planner.

Slow half (tier1-cluster CI lane): a real n=4 committee as separate OS
processes over UDS sockets, load over the wire, one genuine SIGKILL +
restart-from-checkpoint + rejoin, and the zero-loss/agreement audit.
Marked via conftest's _SLOW registry.
"""

from __future__ import annotations

import json
import os

import pytest

from dag_rider_tpu.cluster import audit as caudit
from dag_rider_tpu.cluster import runner as crunner
from dag_rider_tpu.cluster.directory import (
    ClusterSpec,
    NodeFiles,
    allocate_addresses,
    build_cluster,
)
from dag_rider_tpu.cluster.supervisor import seeded_kill_plan
from dag_rider_tpu.consensus import invariants


# -- directory / spec ---------------------------------------------------


def test_build_cluster_lays_out_workspace(tmp_path):
    root = str(tmp_path / "clu")
    spec = build_cluster(root, 4, transport="uds", seed=3)
    assert spec.n == 4 and len(spec.addresses) == 4
    assert all(a.startswith("unix:") for a in spec.addresses)
    assert os.path.exists(os.path.join(spec.root, "keys.json"))
    for i, nf in enumerate(spec.nodes):
        cfg = json.load(open(nf.config))
        assert cfg["node"]["index"] == i
        assert cfg["node"]["listen"] == spec.addresses[i]
        # full static peer directory, excluding self
        assert sorted(cfg["node"]["peers"]) == [
            str(j) for j in range(4) if j != i
        ]
        assert cfg["files"]["submits_wal"] == nf.submits_wal
        assert os.path.isdir(nf.checkpoint_dir)
        assert os.path.isdir(nf.flight_dir)
    # round-trips through cluster.json
    reloaded = ClusterSpec.load(root)
    assert reloaded.to_json() == spec.to_json()


def test_build_cluster_rejects_sub_quorum_committee(tmp_path):
    with pytest.raises(ValueError):
        build_cluster(str(tmp_path / "x"), 3)


def test_tcp_addresses_are_distinct_localhost_ports(tmp_path):
    addrs = allocate_addresses(str(tmp_path), 4, "tcp")
    assert len(set(addrs)) == 4
    assert all(a.startswith("127.0.0.1:") for a in addrs)


def test_seeded_kill_plan_is_deterministic_and_spares_node0():
    a = seeded_kill_plan(11, 4, victims=2)
    b = seeded_kill_plan(11, 4, victims=2)
    assert a == b
    assert all(ev["node"] != 0 for ev in a)
    kills = [ev for ev in a if ev["action"] == "kill"]
    restarts = [ev for ev in a if ev["action"] == "restart"]
    assert len(kills) == 2 and len(restarts) == 2
    assert len({ev["node"] for ev in kills}) == 2


# -- WAL / log parsing --------------------------------------------------


def test_wal_roundtrip_skips_torn_tail(tmp_path):
    wal = str(tmp_path / "submits.wal")
    with open(wal, "w") as fh:
        fh.write(b"tx-one".hex() + "\n")
        fh.write(b"tx-two".hex() + "\n")
        fh.write("dead-bee")  # torn final line: no newline, bad hex
    assert crunner.read_wal(wal) == [b"tx-one", b"tx-two"]
    assert crunner.read_wal(str(tmp_path / "missing")) == []


def test_delivery_log_parse_tolerates_torn_tail(tmp_path):
    dl = str(tmp_path / "delivery.jsonl")
    with open(dl, "w") as fh:
        fh.write(
            json.dumps(
                {"ts": 1.0, "r": 1, "s": 0, "d": "ab", "tx": [b"x".hex()]}
            )
            + "\n"
        )
        fh.write('{"ts": 2.0, "r": 2, "s":')  # kill -9 mid-append
    assert crunner.read_delivered_txs(dl) == {b"x"}
    recs = caudit.read_delivery_log(dl)
    assert len(recs) == 1 and recs[0]["r"] == 1


def test_hint_file_parse(tmp_path):
    hint = str(tmp_path / "delivered.hint")
    with open(hint, "w") as fh:
        fh.write(b"aa".hex() + "\n" + b"bb".hex() + "\nnot-hex\n")
    assert crunner.read_hint(hint) == {b"aa", b"bb"}
    assert crunner.read_hint(str(tmp_path / "none")) == set()


# -- rejoin-aware invariants -------------------------------------------


def _rec(r, s, tag):
    return (r, s, bytes([tag]) * 4)


def test_rejoin_embedding_accepts_recovery_gap():
    canonical = [_rec(1, 0, 1), _rec(1, 1, 2), _rec(2, 0, 3), _rec(3, 1, 4)]
    # pre-crash prefix + post-rejoin segment, gap over (1,1) and (2,0)
    rejoiner = [_rec(1, 0, 1), _rec(3, 1, 4)]
    invariants.check_rejoin_embedding(canonical, rejoiner, view=3)
    # slots past the canonical tail (shutdown skew) are exempt
    invariants.check_rejoin_embedding(
        canonical, rejoiner + [_rec(9, 0, 9)], view=3
    )


def test_rejoin_embedding_rejects_divergent_digest_and_reorder():
    canonical = [_rec(1, 0, 1), _rec(1, 1, 2), _rec(2, 0, 3)]
    with pytest.raises(invariants.InvariantViolation):
        invariants.check_rejoin_embedding(
            canonical, [_rec(1, 1, 9)], view=3
        )  # same slot, different payload digest
    with pytest.raises(invariants.InvariantViolation):
        invariants.check_rejoin_embedding(
            canonical, [_rec(2, 0, 3), _rec(1, 0, 1)], view=3
        )  # committed slots delivered in reversed order


# -- post-hoc audit over synthetic logs --------------------------------


def _write_cluster_fixture(tmp_path, *, node3_log=None, accepted, finals=None):
    """A minimal on-disk 4-node cluster a test can audit.

    ``node3_log`` overrides node 3's delivery log (None = same canonical
    sequence as everyone else)."""
    root = str(tmp_path / "fix")
    spec = ClusterSpec(
        root=root,
        n=4,
        transport="uds",
        addresses=["unix:/dev/null"] * 4,
        seed=0,
        accepted_log=os.path.join(root, "accepted.jsonl"),
    )
    canonical = [
        {"ts": 10.0 + k, "r": k + 1, "s": k % 4,
         "d": bytes([k + 1]).hex() * 4, "tx": [bytes([0xA0 + k]).hex()]}
        for k in range(4)
    ]
    for i in range(4):
        nf = NodeFiles.for_node(root, i)
        os.makedirs(nf.workdir, exist_ok=True)
        os.makedirs(nf.flight_dir, exist_ok=True)
        spec.nodes.append(nf)
        log = node3_log if (i == 3 and node3_log is not None) else canonical
        with open(nf.delivery_log, "w") as fh:
            for rec in log:
                fh.write(json.dumps(rec) + "\n")
        final = {"index": i, "decided_wave": 2, "retained": []}
        if finals:
            final.update(finals.get(i, {}))
        with open(nf.final_report, "w") as fh:
            json.dump(final, fh)
    with open(spec.accepted_log, "w") as fh:
        for k, tx in enumerate(accepted):
            fh.write(
                json.dumps({"tx": tx.hex(), "ts": 9.0 + k, "node": k % 4,
                            "client": "c0"}) + "\n"
            )
    return spec, canonical


def test_audit_clean_run_reports_ok(tmp_path):
    accepted = [bytes([0xA0 + k]) for k in range(4)]
    spec, _ = _write_cluster_fixture(tmp_path, accepted=accepted)
    report = caudit.audit_cluster(spec)
    assert report["ok"], report["violations"]
    assert report["lost_tx"] == 0
    assert report["accepted_tx"] == 4 and report["delivered_tx"] == 4
    assert report["submit_deliver_p50_ms"] > 0


def test_audit_flags_lost_transaction_and_divergence(tmp_path):
    accepted = [bytes([0xA0 + k]) for k in range(4)] + [b"\xee"]  # never delivered
    divergent = [
        {"ts": 10.0, "r": 1, "s": 0, "d": "ff" * 4, "tx": []},
    ]
    spec, _ = _write_cluster_fixture(
        tmp_path, node3_log=divergent, accepted=accepted
    )
    report = caudit.audit_cluster(spec)
    assert not report["ok"]
    checks = {v["check"] for v in report["violations"]}
    assert "zero_loss" in checks
    # node 3 delivered a different digest for slot (1, 0): caught by
    # prefix agreement AND cross-view uniqueness
    assert "agreement" in checks and "commit_uniqueness" in checks
    # the same run audited with node 3 as a REJOINER still fails — a
    # conflicting digest is divergence, not a recovery gap
    report2 = caudit.audit_cluster(spec, restarted=[3])
    checks2 = {v["check"] for v in report2["violations"]}
    assert "rejoin_embedding_p3" in checks2


def test_audit_retained_transactions_are_not_lost(tmp_path):
    accepted = [bytes([0xA0 + k]) for k in range(4)] + [b"\xee"]
    spec, _ = _write_cluster_fixture(
        tmp_path,
        accepted=accepted,
        finals={2: {"retained": [b"\xee".hex()]}},
    )
    report = caudit.audit_cluster(spec)
    assert report["ok"], report["violations"]
    assert report["in_flight_tx"] == 1 and report["lost_tx"] == 0


def test_audit_flags_flight_dumps_and_missing_finals(tmp_path):
    accepted = [bytes([0xA0 + k]) for k in range(4)]
    spec, _ = _write_cluster_fixture(tmp_path, accepted=accepted)
    with open(os.path.join(spec.nodes[1].flight_dir, "dump1.json"), "w") as fh:
        fh.write("{}")
    os.remove(spec.nodes[2].final_report)
    report = caudit.audit_cluster(spec)
    checks = {v["check"] for v in report["violations"]}
    assert "flight_dumps" in checks and "final_reports" in checks
    assert report["missing_finals"] == [2]


# -- the real thing: OS processes over UDS, SIGKILL mid-load ------------


def test_cluster_kill9_rejoin_zero_loss(tmp_path):
    """End-to-end: 4 OS processes over UDS sockets, wire-level load, one
    genuine SIGKILL mid-load, restart-from-checkpoint + WAL re-injection
    + snapshot rejoin, then the full audit: agreement (rejoiner as
    embedding), zero lost accepted transactions, no duplicates,
    liveness, empty flight recorders."""
    import threading

    from dag_rider_tpu.cluster.client import drive_load
    from dag_rider_tpu.cluster.supervisor import ClusterSupervisor

    spec = build_cluster(str(tmp_path / "clu"), 4, transport="uds", seed=5)
    sup = ClusterSupervisor(spec)
    sup.start_all()
    assert sup.wait_ready(30.0) == [], "cluster failed to boot"
    load: dict = {}
    loader = threading.Thread(
        target=lambda: load.update(
            drive_load(spec, duration_s=5.0, rate=120.0, seed=5)
        ),
        daemon=True,
    )
    loader.start()
    plan = seeded_kill_plan(5, 4, kill_at_s=1.5, restart_after_s=1.5)
    executed = sup.run_plan(plan)
    loader.join(timeout=60.0)
    sup.wait_ready(30.0)
    threading.Event().wait(1.5)  # settle: let in-flight waves commit
    sup.stop_all()

    assert sup.kill_counts and sup.restart_counts
    assert load.get("accepted", 0) > 0, load
    report = caudit.audit_cluster(
        spec, restarted=sup.restart_counts.keys()
    )
    assert report["ok"], report["violations"]
    assert report["lost_tx"] == 0 and report["duplicate_tx"] == 0
    assert report["flight_dump_files"] == 0
    assert len(executed) == 2
    victim = executed[0]["node"]
    # the rejoiner came back and committed: its post-restart log is
    # non-empty beyond wherever the kill tore it
    assert report["log_lengths"][victim] > 0
    assert report["decided_waves"], report
