"""BLS12-381 host implementation + (f+1)-of-n threshold coin.

The coin properties under test are the four the reference names
(``process/process.go:386-387``): agreement, termination (readiness once
f+1 shares arrive), unpredictability (below-threshold reveals nothing
usable), and fairness (leader depends on the wave).
"""

import itertools

import pytest

from dag_rider_tpu.consensus.coin import ThresholdCoin
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import threshold as th


# --- curve / pairing sanity -----------------------------------------------


def test_generators_and_orders():
    assert bls.g1_on_curve(bls.G1_GEN)
    assert bls.g2_on_curve(bls.G2_GEN)
    assert bls.g1_mul(bls.R, bls.G1_GEN) is None
    assert bls.g2_mul(bls.R, bls.G2_GEN) is None


def test_pairing_bilinearity():
    e = bls.pairing(bls.G1_GEN, bls.G2_GEN)
    assert e != bls.FP12_ONE
    assert bls.pairing(bls.g1_mul(5), bls.g2_mul(3)) == bls.fp12_pow(e, 15)
    # e(P, Q)^r == 1 (image has order r)
    assert bls.fp12_pow(e, bls.R) == bls.FP12_ONE


def test_sign_verify_roundtrip():
    sk = 0xDEADBEEF12345678
    pk = bls.pk_of(sk)
    sig = bls.sign(sk, b"message")
    assert bls.verify(pk, b"message", sig)
    assert not bls.verify(pk, b"other", sig)
    assert not bls.verify(pk, b"message", b"\x00" * 48)
    assert not bls.verify(bls.pk_of(sk + 1), b"message", sig)


def test_g1_compress_roundtrip():
    for k in (1, 2, 12345, bls.R - 1):
        p = bls.g1_mul(k)
        assert bls.g1_decompress(bls.g1_compress(p)) == p
    assert bls.g1_decompress(b"\x01" * 48) is None  # no compressed flag


def test_hash_to_g1_in_subgroup():
    p = bls.hash_to_g1(b"tag")
    assert bls.g1_on_curve(p)
    assert bls.g1_mul(bls.R, p) is None  # r-torsion after cofactor clearing
    assert bls.hash_to_g1(b"tag") == p  # deterministic
    assert bls.hash_to_g1(b"tag2") != p


# --- threshold scheme ------------------------------------------------------


@pytest.fixture(scope="module")
def keys():
    return th.ThresholdKeys.generate(4, 2)


def test_share_subset_agreement(keys):
    """Any f+1 subset combines to the same group signature (agreement)."""
    wave = 3
    shares = {i: th.sign_share(keys.share_sks[i], wave) for i in range(4)}
    sigmas = set()
    for combo in itertools.combinations(range(4), 2):
        sigma = th.aggregate({i: shares[i] for i in combo}, 2)
        assert sigma is not None
        assert th.verify_group(keys.group_pk, wave, sigma)
        sigmas.add(sigma)
    assert len(sigmas) == 1


def test_share_verification(keys):
    wave = 9
    sh = th.sign_share(keys.share_sks[1], wave)
    assert th.verify_share(keys.share_pks[1], wave, sh)
    assert not th.verify_share(keys.share_pks[2], wave, sh)
    assert not th.verify_share(keys.share_pks[1], wave + 1, sh)


def test_coin_ready_and_agreement(keys):
    wave = 5
    coins = [ThresholdCoin(keys, i, 4) for i in range(4)]
    shares = {i: coins[i].my_share(wave) for i in range(4)}
    leaders = set()
    for combo in itertools.combinations(range(4), 2):
        c = ThresholdCoin(keys, 0, 4)
        assert not c.ready(wave)
        for i in combo:
            c.observe_share(wave, i, shares[i])
        assert c.ready(wave)
        leaders.add(c.choose_leader(wave))
    assert len(leaders) == 1
    assert 0 <= leaders.pop() < 4


def test_coin_byzantine_share_filtered(keys):
    """A decodable-but-forged share must not corrupt or stall the coin."""
    wave = 6
    good = {i: th.sign_share(keys.share_sks[i], wave) for i in range(4)}
    honest_sigma = th.aggregate({0: good[0], 1: good[1]}, 2)
    c = ThresholdCoin(keys, 0, 4)
    # forged share: a valid G1 point that is NOT a share signature; sorted
    # first so the initial combination includes it and fails group verify.
    forged = bls.g1_compress(bls.g1_mul(42))
    c.observe_share(wave, 0, forged)
    c.observe_share(wave, 1, good[1])
    assert not c.ready(wave)  # only 1 honest share after filtering
    c.observe_share(wave, 2, good[2])
    assert c.ready(wave)
    assert c.choose_leader(wave) == th.leader_from_sigma(honest_sigma, 4)


def test_coin_wave_dependence(keys):
    """Different waves give (generally) different leaders — fairness smoke
    check: over 8 waves at n=4 at least two distinct leaders appear."""
    leaders = set()
    for wave in range(1, 9):
        shares = {
            i: th.sign_share(keys.share_sks[i], wave) for i in range(2)
        }
        sigma = th.aggregate(shares, 2)
        leaders.add(th.leader_from_sigma(sigma, 4))
    assert len(leaders) >= 2


# ----------------------------------------------------------------------
# Jacobian MSM + batched share verification (round-2 VERDICT weak #4)
# ----------------------------------------------------------------------


def test_g1_g2_msm_match_affine_oracle():
    import random

    rng = random.Random(5)
    pts1 = [bls.g1_mul(rng.randrange(1, bls.R)) for _ in range(7)]
    pts2 = [bls.g2_mul(rng.randrange(1, bls.R)) for _ in range(7)]
    ks = [rng.randrange(0, bls.R) for _ in range(7)]
    ks[3] = 0  # zero scalar must drop out
    want1 = None
    want2 = None
    for k, p1, p2 in zip(ks, pts1, pts2):
        want1 = bls.g1_add(want1, bls.g1_mul(k, p1))
        want2 = bls.g2_add(want2, bls.g2_mul(k, p2))
    assert bls.g1_msm(ks, pts1) == want1
    assert bls.g2_msm(ks, pts2) == want2
    # identity results
    assert bls.g1_msm([0, 0], pts1[:2]) is None
    assert bls.g1_msm([1, bls.R - 1], [pts1[0], pts1[0]]) is None  # P + (-P)


def test_batch_verify_shares_all_honest():
    keys = th.ThresholdKeys.generate(7, 3)
    wave = 4
    shares = {i: th.sign_share(keys.share_sks[i], wave) for i in range(5)}
    good = th.batch_verify_shares(keys.share_pks, wave, shares)
    assert good == shares


def test_batch_verify_shares_one_bad_localized():
    keys = th.ThresholdKeys.generate(7, 3)
    wave = 9
    shares = {i: th.sign_share(keys.share_sks[i], wave) for i in range(6)}
    shares[2] = th.sign_share(keys.share_sks[2], wave + 1)  # wrong message
    good = th.batch_verify_shares(keys.share_pks, wave, shares)
    assert set(good) == {0, 1, 3, 4, 5}


def test_batch_verify_shares_multiple_bad_and_undecodable():
    keys = th.ThresholdKeys.generate(8, 3)
    wave = 2
    shares = {i: th.sign_share(keys.share_sks[i], wave) for i in range(8)}
    shares[1] = th.sign_share(keys.share_sks[0], wave)  # wrong signer
    shares[4] = th.sign_share(keys.share_sks[4], wave + 7)  # wrong message
    shares[6] = b"\x00" * 48  # undecodable
    good = th.batch_verify_shares(keys.share_pks, wave, shares)
    assert set(good) == {0, 2, 3, 5, 7}


def test_batch_verify_shares_all_bad():
    keys = th.ThresholdKeys.generate(4, 2)
    shares = {
        i: th.sign_share(keys.share_sks[i], 99) for i in range(3)
    }  # all for the wrong wave
    assert th.batch_verify_shares(keys.share_pks, 1, shares) == {}


def test_generator_comb_matches_ladder():
    """g1_mul/g2_mul fixed-base comb (round-4 host speedup) is the same
    group element as the Jacobian ladder, edge scalars included."""
    import random

    from dag_rider_tpu.crypto import bls12381 as bls

    rng = random.Random(3)
    cases = [0, 1, 2, 15, 16, bls.R - 1, bls.R, bls.R + 5] + [
        rng.randrange(0, 2**256) for _ in range(20)
    ]
    for k in cases:
        assert bls.g1_mul(k) == bls._ec_mul(bls._FP_OPS, k, bls.G1_GEN), k
        assert bls.g2_mul(k) == bls._ec_mul(bls._FP2_OPS, k, bls.G2_GEN), k
    # non-generator bases keep the ladder path and stay correct
    p = bls.g1_mul(12345)
    assert bls.g1_mul(7, p) == bls._ec_mul(bls._FP_OPS, 7, p)
