"""Depth-K verifier pipeline equivalence (round-6 tentpole).

The pipeline changes WHEN the host blocks, never WHAT the device
computes: masks from the depth-K window (verifier/pipeline.py), the
chunk-streaming ``verify_rounds``, and the CPU oracle must be
byte-identical across randomized burst shapes, window depths
(K in {1, 2, 4}), and ``fixed_bucket`` settings — including empty rounds
and merges larger than the bucket (the over-cap chunking edge). The
commit order downstream of those masks is checked end-to-end through the
simulator at every depth.
"""

import dataclasses
import random

import pytest

from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.pipeline import VerifierPipeline
from dag_rider_tpu.verifier.tpu import TPUVerifier

N = 8


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(N)


def _signed_pool(keys, count, seed):
    """``count`` signed vertices over randomized rounds/sources/edges,
    with a deterministic sprinkle of corruptions (zeroed signature,
    foreign signer) the mask must reject."""
    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]
    rng = random.Random(seed)
    out = []
    for j in range(count):
        src = rng.randrange(N)
        r = rng.randrange(1, 6)
        v = Vertex(
            id=VertexID(r, src),
            block=Block((f"s{seed}j{j}".encode(),)),
            strong_edges=tuple(
                VertexID(r - 1, s) for s in range(rng.randrange(0, N))
            ),
        )
        v = signers[src].sign_vertex(v)
        roll = rng.random()
        if roll < 0.15:
            v = dataclasses.replace(v, signature=bytes(64))
        elif roll < 0.25:
            v = dataclasses.replace(
                v,
                signature=signers[(src + 1) % N].sign_vertex(v).signature,
            )
        out.append(v)
    return out


def _random_rounds(pool, rng):
    """Randomized burst shapes over the pool, with explicit empty rounds
    sprinkled in."""
    rounds, i = [], 0
    while i < len(pool):
        if rng.random() < 0.2:
            rounds.append([])
        k = rng.randint(1, 17)
        rounds.append(pool[i : i + k])
        i += k
    rounds.append([])
    return rounds


@pytest.mark.parametrize("depth", [1, 2, 4])
@pytest.mark.parametrize("bucket", [None, 16, 32])
def test_pipeline_masks_byte_identical(keys, depth, bucket):
    """Property: depth-K pipeline == chunk-streaming verify_rounds ==
    CPU oracle, for every (depth, bucket) combination. A 48-vertex pool
    against bucket 16/32 forces over-cap chunking; bucket None exercises
    the power-of-two ladder."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(1000 * depth + (bucket or 7))
    pool = _signed_pool(keys, 48, seed=100 * depth + (bucket or 7))
    rounds = _random_rounds(pool, rng)
    want = [cpu.verify_batch(r) for r in rounds]
    assert any(not all(m) for m in want if m), "no corruption landed"

    streamed = TPUVerifier(reg)
    streamed.fixed_bucket = bucket
    streamed.pipeline_depth = depth
    assert streamed.verify_rounds(rounds) == want

    pipe = VerifierPipeline(
        TPUVerifier(reg), depth=depth, fixed_bucket=bucket, warmup=False
    )
    assert pipe.verify_rounds(rounds) == want
    flat = [v for r in rounds for v in r]
    assert pipe.verify_batch(flat) == [m for ms in want for m in ms]
    assert pipe.verify_batch([]) == []


@pytest.mark.parametrize("depth", [1, 2, 4])
@pytest.mark.parametrize("bucket", [None, 16])
def test_sharded_pipeline_masks_byte_identical(keys, depth, bucket):
    """Round-7 tentpole: the MESH-sharded verifier through the depth-K
    window must produce the same bytes as the CPU oracle and the
    single-chip streamed path at every depth — chunk boundaries are set
    by the caller's bucket exactly as on one chip; only the padded
    dispatch size rounds up to the mesh multiple (invisible after the
    ``[:count]`` slice)."""
    import jax

    from dag_rider_tpu.parallel.mesh import make_mesh
    from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(7000 * depth + (bucket or 3))
    pool = _signed_pool(keys, 48, seed=700 * depth + (bucket or 3))
    rounds = _random_rounds(pool, rng)
    want = [cpu.verify_batch(r) for r in rounds]
    assert any(not all(m) for m in want if m), "no corruption landed"

    single = TPUVerifier(reg)
    single.fixed_bucket = bucket
    single.pipeline_depth = depth
    assert single.verify_rounds(rounds) == want

    sharded = ShardedTPUVerifier(reg, make_mesh(8))
    sharded.fixed_bucket = bucket
    sharded.pipeline_depth = depth
    assert sharded.verify_rounds(rounds) == want

    pipe = VerifierPipeline(
        ShardedTPUVerifier(reg, make_mesh(8)),
        depth=depth,
        fixed_bucket=bucket,
        warmup=False,
    )
    assert pipe.verify_rounds(rounds) == want
    flat = [v for r in rounds for v in r]
    assert pipe.verify_batch(flat) == [m for ms in want for m in ms]
    assert pipe.verify_batch([]) == []
    # the window really ran on the mesh, not a single-chip fallback
    assert pipe.stats().get("mesh_devices") == 8


def test_aot_warmup_is_mask_invariant(keys):
    """warmup()'s jit().lower().compile() executable must be a pure
    speed move: identical masks before/after, idempotent, accounted."""
    reg, _ = keys
    pool = _signed_pool(keys, 20, seed=7)
    cold = TPUVerifier(reg)
    cold.fixed_bucket = 16
    before = cold.verify_batch(pool)

    warm = TPUVerifier(reg)
    warm.fixed_bucket = 16
    dt = warm.warmup()
    assert dt >= 0.0 and warm._aot, "warmup compiled nothing"
    assert warm.verify_batch(pool) == before
    assert warm.warmup() == 0.0  # second call: shape already compiled
    assert warm.warmup_compile_s == dt


def test_window_gauges_and_serial_degeneration(keys):
    """The depth-4 window keeps chunks genuinely in flight (high-water
    >= 2), its gauges stay sane, and a depth-1 window degenerates to the
    serial dispatch-then-resolve shape with the same mask."""
    reg, _ = keys
    pool = _signed_pool(keys, 40, seed=3)
    pipe = VerifierPipeline(
        TPUVerifier(reg), depth=4, fixed_bucket=16, warmup=False
    )
    mask = pipe.verify_batch(pool)
    assert pipe.dispatches == 3  # ceil(40 / 16)
    assert pipe.sigs_dispatched == 40
    assert pipe.depth_hwm >= 2, "chunks never overlapped in flight"
    s = pipe.stats()
    assert 0.0 <= s["overlap_fraction"] <= 1.0
    assert s["seam_s"] >= s["wait_s"] >= 0.0

    serial = VerifierPipeline(
        TPUVerifier(reg), depth=1, fixed_bucket=16, warmup=False
    )
    assert serial.verify_batch(pool) == mask
    assert serial.depth_hwm == 1


def test_pipeline_enabled_off_caps_window_at_one(keys):
    """The bench's A/B flag: pipeline_enabled=False on the wrapped
    verifier forces the window to depth 1 — same mask, no overlap."""
    reg, _ = keys
    pool = _signed_pool(keys, 40, seed=5)
    base = TPUVerifier(reg)
    pipe = VerifierPipeline(base, depth=4, fixed_bucket=16, warmup=False)
    on = pipe.verify_batch(pool)
    assert pipe.last_max_depth >= 2
    base.pipeline_enabled = False
    try:
        assert pipe.verify_batch(pool) == on
        assert pipe.last_max_depth == 1
    finally:
        base.pipeline_enabled = True


def test_sim_commit_order_matches_cpu_at_every_depth(keys):
    """Acceptance: CPU-vs-device commit order stays byte-identical with
    the pipeline enabled at every tested depth, with per-cycle bursts
    larger than the fixed bucket so the depth-K window genuinely engages
    (n*(n-1) = 56 unique entries vs bucket 16 = 4 chunks in flight)."""
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]

    def run(factory, dedup=True):
        cfg = Config(n=N, coin="round_robin", propose_empty=True)
        sim = Simulation(
            cfg,
            verifier_factory=factory,
            signer_factory=lambda i: signers[i],
        )
        sim.dedup = dedup
        sim.submit_blocks(per_process=2)
        for _ in range(10):
            sim.run(max_messages=N * (N - 1))
        sim.check_agreement()
        log = [
            (v.id.round, v.id.source, v.digest())
            for v in sim.deliveries[0]
        ]
        return log, sim

    cpu_log, _ = run(lambda i: CPUVerifier(reg))
    assert len(cpu_log) > 10, "CPU reference run delivered too little"
    for depth in (1, 2, 4):
        shared = TPUVerifier(reg)
        shared.fixed_bucket = 16
        shared.pipeline_depth = depth
        # dedup off: the merged burst keeps all n*(n-1) copies, so a
        # cycle's dispatch genuinely exceeds the bucket and chunks
        # (deliveries are dedup-invariant — see the dedup tests)
        dev_log, sim = run(lambda i: shared, dedup=False)
        k = min(len(cpu_log), len(dev_log))
        assert k > 10 and cpu_log[:k] == dev_log[:k], f"depth {depth}"
        depths = [
            d
            for p in sim.processes
            for d in p.metrics.verify_queue_depth
        ]
        assert depths, "queue-depth gauge never observed"
        if depth > 1:
            assert max(depths) >= 2, "window never engaged"
        snap = sim.processes[0].metrics.snapshot()
        assert "verify_overlap_fraction" in snap
        assert "verify_queue_depth_p50" in snap


@pytest.mark.parametrize("depth", [2, 4])
def test_hold_tail_masks_fifo_across_calls(keys, depth):
    """ISSUE 16 tentpole 4: ``run_coalesced(..., hold_tail=True)`` may
    keep up to depth-1 chunks in flight ACROSS the call boundary — the
    cross-round verify window. Held results must emerge at the FRONT of
    a later call's mask (FIFO), ``drain()`` settles the remainder, and
    the concatenated stream is byte-identical to the CPU oracle."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(900 + depth)
    pool = _signed_pool(keys, 72, seed=900 + depth)
    want = cpu.verify_batch(pool)
    assert not all(want), "no corruption landed"

    pipe = VerifierPipeline(
        TPUVerifier(reg), depth=depth, fixed_bucket=8, warmup=False
    )
    got, held_once, i = [], False, 0
    while i < len(pool):
        k = rng.randint(1, 24)
        burst = pool[i : i + k]
        i += k
        mask = pipe.run_coalesced(burst, hold_tail=True)
        # held chunks can flush ahead of this burst's own results, but
        # never more than the window could have been holding
        assert len(mask) <= len(burst) + (depth - 1) * 8
        if len(mask) < len(burst):
            held_once = True
        got.extend(mask)
    got.extend(pipe.drain())
    assert held_once, "the window never held a tail across a call"
    assert got == want
    # a drained pipeline owes nothing more
    assert pipe.drain() == []


def test_hold_tail_depth_one_never_holds(keys):
    """depth=1 degenerates hold_tail to the synchronous path: every
    call settles its own burst in full."""
    reg, _ = keys
    pool = _signed_pool(keys, 24, seed=11)
    cpu = CPUVerifier(reg)
    pipe = VerifierPipeline(
        TPUVerifier(reg), depth=1, fixed_bucket=8, warmup=False
    )
    got = []
    for i in range(0, len(pool), 7):
        burst = pool[i : i + 7]
        mask = pipe.run_coalesced(burst, hold_tail=True)
        assert len(mask) == len(burst)
        got.extend(mask)
    assert got == cpu.verify_batch(pool)
    assert pipe.drain() == []
