"""Seeded fuzz suite for ``defer_delivery`` (ISSUE 16 satellite b).

The flag queues each decided wave's ordering/delivery walk for
:meth:`Process.flush_deliveries` instead of running it inline in
``_try_wave`` — the overlap seam the pipelined simulator (and now the
pipelined-wave path) leans on. Its contract has two halves, both pinned
here under randomized message interleavings and Byzantine senders:

- **byte-identity** — for the same seed (same delivery schedule), a
  deferred run's delivered log is byte-for-byte the inline run's log at
  every process, no matter when the flushes happen;
- **FIFO flush** — deferred walks run oldest-decision-first, so a
  partial flush surfaces a strict prefix of what the full flush would.

Adversaries run WITHOUT signatures or RBC on purpose: the suite pins
delivery *mechanics* (defer vs inline at one process), not cross-node
agreement — that is test_adversary.py's job.
"""

from __future__ import annotations

import random

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.adversary import ByzantineProcess, make_behavior
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import RandomizedScheduler
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.transport.memory import InMemoryTransport


def _build(n: int, seed: int, adversary):
    cfg = Config(
        n=n,
        propose_empty=True,
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
        sync_patience=1,
    )
    tp = InMemoryTransport()
    nbyz = cfg.f if adversary else 0
    behaviors = {
        i: make_behavior(adversary, seed=seed + 1000 + i)
        for i in range(nbyz)
    }
    sinks = [[] for _ in range(n)]
    procs = []
    for i in range(n):
        if i in behaviors:
            p = ByzantineProcess(
                cfg, i, tp, behavior=behaviors[i],
                on_deliver=sinks[i].append,
            )
        else:
            p = Process(cfg, i, tp, on_deliver=sinks[i].append)
        procs.append(p)
    return cfg, tp, procs, sinks


def _drive(n: int, seed: int, adversary, defer: bool, cycles: int):
    """One seeded run; the rng draws are identical for defer on/off (no
    draw depends on the flag), so both runs see the exact same message
    interleaving."""
    cfg, tp, procs, sinks = _build(n, seed, adversary)
    nbyz = cfg.f if adversary else 0
    for i, p in enumerate(procs):
        if i >= nbyz:
            p.submit(
                Block((f"s{seed}-p{i}".encode().ljust(32, b"."),))
            )
        p.defer_delivery = defer
        p.start()
    sched = RandomizedScheduler(tp, seed)
    rng = random.Random(seed * 31 + 7)
    for _ in range(cycles):
        sched.run(max_messages=rng.randint(1, 3 * n * n))
        for p in procs:
            p.step()
        if rng.random() < 0.4:
            # mid-run flush at an arbitrary point; a no-op inline
            for p in procs:
                p.flush_deliveries()
    # settle: a BOUNDED drain (propose_empty keeps the cluster
    # chattering forever, so true quiescence never comes), then flush
    # everything owed; both sides of the A/B run the same schedule
    for _ in range(12):
        if not sched.run(max_messages=6 * n * n):
            break
        for p in procs:
            p.step()
    for p in procs:
        p.step()
        p.flush_deliveries()
        p.defer_delivery = False
    logs = [
        [(v.id.round, v.id.source, v.digest()) for v in sink]
        for sink in sinks
    ]
    return logs, procs


CASES = [
    (4, 11, None),
    (4, 12, "equivocate"),
    (4, 13, "withhold"),
    (16, 21, None),
    (16, 22, "equivocate"),
    (16, 23, "withhold"),
    # n=32 drives are ~40s each on one core: slow-marked so the tier-1
    # lane keeps headroom; the tier1-finality CI step runs this file
    # without the marker filter.
    (32, 31, "equivocate"),
    (32, 32, "withhold"),
]


@pytest.mark.parametrize(
    "n,seed,adversary",
    [
        pytest.param(
            n, s, a,
            marks=([pytest.mark.slow] if n >= 32 else []),
            id=f"n{n}-s{s}-{a or 'clean'}",
        )
        for n, s, a in CASES
    ],
)
def test_defer_delivery_byte_identity(n, seed, adversary):
    cycles = 24 if n <= 16 else 10
    inline_logs, _ = _drive(n, seed, adversary, defer=False, cycles=cycles)
    defer_logs, procs = _drive(n, seed, adversary, defer=True, cycles=cycles)
    # the honest cluster must actually have committed something, or the
    # identity below is vacuous
    nbyz = (n - 1) // 3 if adversary else 0
    assert any(len(log) > 0 for log in inline_logs[nbyz:])
    for i, (a, b) in enumerate(zip(inline_logs, defer_logs)):
        assert a == b, f"process {i}: deferred log diverged from inline"
    for p in procs:
        assert not p._deferred_orders, "flush left deferred walks queued"


def test_flush_is_fifo_prefix():
    """A partial flush (flush after every single decision) surfaces the
    same stream as one big terminal flush — deferred walks are FIFO, so
    every intermediate delivered_log is a prefix of the final one."""
    n, seed = 4, 5
    cfg, tp, procs, sinks = _build(n, seed, None)
    for p in procs:
        p.submit(Block((f"fifo-p{p.index}".encode().ljust(32, b"."),)))
        p.defer_delivery = True
        p.start()
    sched = RandomizedScheduler(tp, seed)
    prefixes = []  # snapshots of process 0's log after each flush
    for _ in range(40):
        if not sched.run(max_messages=2 * n * n):
            break
        for p in procs:
            p.step()
        if procs[0]._deferred_orders:
            leaders, _, oldest = procs[0]._deferred_orders[0]
            assert oldest >= 1 and len(leaders) >= 1
            procs[0].flush_deliveries()
            prefixes.append(list(procs[0].delivered_log))
        for p in procs[1:]:
            p.flush_deliveries()
    assert len(prefixes) >= 2, "fuzz never caught a deferred walk"
    final = procs[0].delivered_log
    for snap in prefixes:
        assert snap == final[: len(snap)], "flush was not FIFO"


def test_deferred_orders_queue_in_decision_order():
    """The deferred queue is ordered by decision: each queued walk's
    oldest-leader round is monotone non-decreasing — the invariant
    maybe_prune's GC anchor and the FIFO flush both rely on."""
    n, seed = 4, 9
    cfg, tp, procs, sinks = _build(n, seed, None)
    for p in procs:
        p.defer_delivery = True
        p.start()
    sched = RandomizedScheduler(tp, seed)
    rounds_seen = []
    for _ in range(60):
        if not sched.run(max_messages=n * n):
            break
        for p in procs:
            p.step()
        queued = [oldest for _, _, oldest in procs[0]._deferred_orders]
        assert queued == sorted(queued)
        for r in queued:
            if not rounds_seen or r > rounds_seen[-1]:
                rounds_seen.append(r)
    assert len(rounds_seen) >= 2, "fuzz never queued two distinct walks"
    assert rounds_seen == sorted(rounds_seen)
    for p in procs:
        p.flush_deliveries()
