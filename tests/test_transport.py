"""Transport broker tests (the reference never tests its Transport at all,
SURVEY.md §4), including the D12 fixes: sender exclusion, subscribe/broadcast
race safety, decoupled delivery."""

import threading

from dag_rider_tpu.core.types import BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport import InMemoryTransport


def _msg(sender=0, rnd=1):
    return BroadcastMessage(
        vertex=Vertex(id=VertexID(rnd, sender)), round=rnd, sender=sender
    )


def test_fanout_excludes_sender():
    tp = InMemoryTransport()
    got = {i: [] for i in range(3)}
    for i in range(3):
        tp.subscribe(i, got[i].append)
    tp.broadcast(_msg(sender=0))
    assert tp.pending == 2
    tp.pump()
    assert len(got[0]) == 0 and len(got[1]) == 1 and len(got[2]) == 1


def test_fifo_order():
    tp = InMemoryTransport()
    got = []
    tp.subscribe(0, got.append)
    tp.subscribe(1, lambda m: None)
    for r in range(1, 6):
        tp.broadcast(_msg(sender=1, rnd=r))
    tp.pump()
    assert [m.round for m in got] == [1, 2, 3, 4, 5]


def test_duplicate_subscribe_rejected():
    tp = InMemoryTransport()
    tp.subscribe(0, lambda m: None)
    try:
        tp.subscribe(0, lambda m: None)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_handlers_may_broadcast_reentrantly():
    """A delivery handler that broadcasts (as Process does) must not
    deadlock — the broker enqueues outside handler execution."""
    tp = InMemoryTransport()
    seen = []

    def echo_once(m):
        seen.append(m)
        if m.round < 3:
            tp.broadcast(_msg(sender=0, rnd=m.round + 1))

    tp.subscribe(0, lambda m: None)
    tp.subscribe(1, echo_once)
    tp.broadcast(_msg(sender=0, rnd=1))
    tp.pump()
    assert [m.round for m in seen] == [1, 2, 3]


def test_concurrent_broadcast_and_subscribe_race_free():
    """D12: the reference iterates subscribers without a lock while
    Subscribe appends. Hammer both paths concurrently."""
    tp = InMemoryTransport()
    tp.subscribe(0, lambda m: None)
    stop = threading.Event()
    errors = []

    def blaster():
        while not stop.is_set():
            try:
                tp.broadcast(_msg(sender=0))
            except Exception as e:  # pragma: no cover
                errors.append(e)

    t = threading.Thread(target=blaster)
    t.start()
    try:
        for i in range(1, 50):
            tp.subscribe(i, lambda m: None)
    finally:
        stop.set()
        t.join()
    assert not errors
    tp.pump()


def test_pump_batch_requeues_tail_when_handler_raises():
    """Batched pump must keep pump_one's failure semantics: a handler
    exception mid-batch loses only the in-flight message — the popped
    but undelivered tail goes back to the queue head in order."""
    tp = InMemoryTransport()
    got = []
    boom = {"armed": True}

    def good(msg):
        got.append(msg.round)

    def bad(msg):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("handler bug")
        got.append(-msg.round)

    tp.subscribe(0, good)
    tp.subscribe(1, bad)
    for r in range(1, 4):
        tp.broadcast(BroadcastMessage(kind="val", vertex=None, round=r, sender=2))
    # queue: (0,r1) (1,r1) (0,r2) (1,r2) (0,r3) (1,r3)
    try:
        tp.pump()
    except RuntimeError:
        pass
    else:  # pragma: no cover
        raise AssertionError("handler exception must propagate")
    # (0,r1) delivered, (1,r1) lost in flight, tail requeued in order
    assert got == [1]
    assert tp.pump() == 4
    assert got == [1, 2, -2, 3, -3]
