"""Determinism asserts (SURVEY §5: race detection / JAX-side determinism).

Two independent constructions with identical seeds must produce
byte-identical outcomes: the randomized-schedule simulation (delivered
logs) and the device verify dispatch (accept masks, run twice on the
same backend).
"""

import dataclasses

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.simulator import RandomizedScheduler, Simulation
from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.tpu import TPUVerifier


def _run_once(seed: int):
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    broker = InMemoryTransport()
    sim = Simulation(cfg, transport=broker)
    sim.submit_blocks(per_process=8)
    for p in sim.processes:
        p.defer_steps = True
        p.start()
    sched = RandomizedScheduler(broker, seed=seed)
    for _ in range(5000):
        if not sched.run(max_messages=1):
            for p in sim.processes:
                p.step()
            if broker.pending == 0:
                break
    for p in sim.processes:
        p.step()
    return sim


def _logs(sim):
    return [
        [(v.id.round, v.id.source, v.digest()) for v in sim.deliveries[i]]
        for i in range(sim.cfg.n)
    ]


def test_same_seed_same_delivery():
    a = _run_once(1234)
    b = _run_once(1234)
    assert _logs(a) == _logs(b)
    assert any(log for log in _logs(a)), "nothing was delivered"


def test_different_seed_may_reorder_but_agrees_internally():
    # different interleavings still satisfy per-run agreement (the
    # canonical all-pairs prefix check, not a re-implementation)
    for seed in (1, 2):
        sim = _run_once(seed)
        sim.check_agreement()
        assert max(len(l) for l in _logs(sim)) > 0


def test_device_verify_is_deterministic():
    reg, seeds = KeyRegistry.generate(8)
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(8):
        v = Vertex(
            id=VertexID(1, i),
            block=Block((f"tx{i}".encode(),)),
            strong_edges=tuple(VertexID(0, s) for s in range(5)),
        )
        vs.append(signers[i].sign_vertex(v))
    vs[3] = dataclasses.replace(vs[3], signature=bytes(64))
    ver = TPUVerifier(reg)
    first = ver.verify_batch(vs)
    for _ in range(3):
        assert ver.verify_batch(vs) == first
    assert first == [True, True, True, False, True, True, True, True]


def test_cpu_vs_device_verifier_commit_order_byte_identical():
    """The north-star determinism claim (BASELINE.json): the same cluster
    run with the CPU oracle verifier and with the device verifier must
    a_deliver byte-identical total orders — all ordering decisions stay
    host-side; the verifier contributes only accept bits."""
    from dag_rider_tpu.verifier.cpu import CPUVerifier

    def run(verifier_cls):
        cfg = Config(n=4, coin="round_robin", propose_empty=False)
        reg, key_seeds = KeyRegistry.generate(4)
        signers = [VertexSigner(s) for s in key_seeds]
        shared = verifier_cls(reg)
        sim = Simulation(
            cfg,
            verifier_factory=lambda i: shared,
            signer_factory=lambda i: signers[i],
        )
        sim.submit_blocks(per_process=10)
        sim.run(max_messages=50_000)
        sim.check_agreement()
        return [
            [(v.id.round, v.id.source, v.digest()) for v in sim.deliveries[i]]
            for i in range(4)
        ]

    cpu_logs = run(CPUVerifier)
    dev_logs = run(TPUVerifier)
    assert any(cpu_logs), "nothing delivered"
    assert cpu_logs == dev_logs


def test_pipelined_coalesced_path_matches_sync_path():
    """The round-4 pipeline (async dispatch + deferred delivery flush in
    Simulation.run) must not change ANY delivery: same config driven once
    through the shared-verifier pipelined path and once through plain
    per-process synchronous verifiers gives byte-identical logs."""
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.cpu import CPUVerifier
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    n = 8
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    logs = []
    for mode in ("pipelined", "sync"):
        cfg = Config(n=n, coin="round_robin", propose_empty=True)
        if mode == "pipelined":
            shared = TPUVerifier(reg)
            shared.fixed_bucket = 128
            vf = lambda i: shared  # noqa: E731
        else:
            vf = lambda i: CPUVerifier(reg)  # noqa: E731
        sim = Simulation(
            cfg,
            verifier_factory=vf,
            signer_factory=lambda i: signers[i],
        )
        sim.submit_blocks(per_process=2)
        for _ in range(24):
            sim.run(max_messages=n * (n - 1))
        sim.check_agreement()
        logs.append(
            [
                (v.id.round, v.id.source, v.digest())
                for v in sim.deliveries[0]
            ]
        )
    assert len(logs[0]) > 50
    k = min(len(logs[0]), len(logs[1]))
    assert logs[0][:k] == logs[1][:k]


def test_dedup_coalesced_dispatch_is_delivery_identical():
    """Round-5 dedup: the shared-verifier coalescing dispatches each
    unique (digest, signature, source) once and fans the mask out to
    every sibling copy. Deliveries must be byte-identical with dedup on
    and off, and the device must see strictly fewer signatures."""
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    n = 8
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    logs, dispatched, applied = [], [], []
    for dedup in (True, False):
        cfg = Config(n=n, coin="round_robin", propose_empty=True)
        shared = TPUVerifier(reg)
        shared.fixed_bucket = 128
        sim = Simulation(
            cfg,
            verifier_factory=lambda i: shared,
            signer_factory=lambda i: signers[i],
        )
        sim.dedup = dedup
        sim.submit_blocks(per_process=2)
        for _ in range(12):
            sim.run(max_messages=n * (n - 1))
        sim.check_agreement()
        logs.append(
            [
                (v.id.round, v.id.source, v.digest())
                for v in sim.deliveries[0]
            ]
        )
        dispatched.append(shared.total_sigs_dispatched)
        applied.append(
            sum(p.metrics.verify_sigs_total for p in sim.processes)
        )
    assert len(logs[0]) > 20
    k = min(len(l) for l in logs)
    assert logs[0][:k] == logs[1][:k]
    # applied counts match (per-process semantics unchanged)...
    assert applied[0] == applied[1]
    # ...while the device dispatched ~1/(n-1) of the copies
    assert dispatched[0] * 2 < dispatched[1], (dispatched, applied)


def test_dedup_does_not_conflate_corrupted_copies():
    """A copy whose signature (or content) differs must keep its own
    mask bit: corrupting ONE process's copy of a vertex must reject only
    that copy."""
    import dataclasses

    from dag_rider_tpu.core.types import Block, Vertex, VertexID
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    reg, seeds = KeyRegistry.generate(4)
    signers = [VertexSigner(s) for s in seeds]
    v = Vertex(
        id=VertexID(3, 1),
        block=Block((b"tx",)),
        strong_edges=(VertexID(2, 0), VertexID(2, 1), VertexID(2, 2)),
    )
    v = signers[1].sign_vertex(v)
    bad = dataclasses.replace(v, signature=bytes(64))
    # the coalesced flat batch: three good copies + one corrupt, through
    # the PRODUCTION dedup (a private re-implementation here would keep
    # passing if the simulator's key ever drifted)
    flat = [v, v, bad, v]
    shared = TPUVerifier(reg)
    uniq, inv = Simulation._dedup(flat)
    assert len(uniq) == 2  # good + corrupt stay separate entries
    umask = shared.verify_batch(uniq)
    mask = [umask[j] for j in inv]
    assert mask == [True, True, False, True]
