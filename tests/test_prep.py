"""Parallel host-prep engine equivalence (round-8 tentpole).

The engine changes WHERE prep rows are computed (row-block worker
threads, writing into block offsets of the staging-ring slot) and WHEN
whole prep calls run (ahead, on the seam thread, overlapping earlier
chunks' device execution) — never WHAT is computed. Masks must be
byte-identical to serial prep at every (workers, depth, bucket)
combination, on the single-chip and mesh-sharded verifiers, and the
staging-ring aliasing discipline (a slot is never rewritten while a
dispatch that shipped it may still be executing) must survive the
prep-ahead ordering.
"""

import collections
import random

import numpy as np
import pytest

from test_pipeline import N, _random_rounds, _signed_pool

from dag_rider_tpu.verifier.base import KeyRegistry
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.pipeline import VerifierPipeline
from dag_rider_tpu.verifier.prep import (
    MIN_BLOCK_ROWS,
    PrepEngine,
    default_prep_workers,
)
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(N)


# -- engine unit half -----------------------------------------------------


def test_plan_partitions_exactly():
    """Every plan covers [0, size) contiguously, exactly once, and small
    sizes stay a single block (no thread handoff below the floor)."""
    for workers in (1, 2, 3, 4, 8):
        eng = PrepEngine(workers)
        try:
            for size in (0, 1, 15, 16, 17, 31, 32, 48, 64, 100, 257):
                blocks = eng.plan(size)
                assert blocks[0][0] == 0
                assert blocks[-1][1] == size or (size == 0 and blocks == [(0, 0)])
                for (alo, ahi), (blo, bhi) in zip(blocks, blocks[1:]):
                    assert ahi == blo, "gap or overlap between blocks"
                if workers == 1 or size < 2 * MIN_BLOCK_ROWS:
                    assert len(blocks) == 1
                assert len(blocks) <= max(1, min(workers, size // MIN_BLOCK_ROWS))
        finally:
            eng.close()


def test_run_blocks_writes_every_row_and_counts():
    eng = PrepEngine(4)
    try:
        out = np.zeros(100, dtype=np.int64)

        def fill(lo, hi):
            out[lo:hi] = np.arange(lo, hi)

        eng.run_blocks(fill, eng.plan(100))
        assert np.array_equal(out, np.arange(100))
        assert eng.last_blocks == 4
        assert eng.rows_total == 100 and eng.rows_parallel == 100
        assert eng.parallel_fraction() == 1.0
        # a sub-floor dispatch takes the serial path and dilutes the gauge
        eng.run_blocks(fill, eng.plan(10))
        assert eng.last_blocks == 1
        assert 0.0 < eng.parallel_fraction() < 1.0
    finally:
        eng.close()


def test_run_blocks_propagates_persistent_exception():
    """A fault that survives the serial retry still propagates — the
    block-pool boundary contains transient worker faults, it does not
    invent masks for batches that cannot be prepped."""
    eng = PrepEngine(4)
    try:

        def boom(lo, hi):
            raise RuntimeError("worker failed")

        with pytest.raises(RuntimeError, match="worker failed"):
            eng.run_blocks(boom, eng.plan(64))
        assert eng.serial_retries == 1
    finally:
        eng.close()


def test_run_blocks_serial_retry_recovers_transient_fault():
    """A parallel-only fault (raises for worker blocks, lo > 0) is
    caught at the block-pool boundary and the whole range re-runs
    serially; the result is byte-complete because _prep_block-style
    fns fully overwrite their rows."""
    eng = PrepEngine(4)
    try:
        out = np.zeros(64, dtype=np.int64)
        retry_calls = []

        def flaky(lo, hi):
            if lo > 0:
                raise RuntimeError("transient worker fault")
            if (lo, hi) == (0, 64):
                retry_calls.append((lo, hi))
            out[lo:hi] = np.arange(lo, hi)

        eng.run_blocks(flaky, eng.plan(64))
        assert np.array_equal(out, np.arange(64))
        assert eng.serial_retries == 1
        # the retry was exactly one serial full-range pass
        assert retry_calls == [(0, 64)]
    finally:
        eng.close()


def test_seam_executor_is_fifo():
    eng = PrepEngine(2)
    try:
        order = []
        futs = [eng.submit(order.append, i) for i in range(16)]
        for f in futs:
            f.result()
        assert order == list(range(16))
    finally:
        eng.close()


def test_env_knob_and_engine_rebuild(keys, monkeypatch):
    """DAGRIDER_PREP_WORKERS seeds the default; the per-verifier
    prep_workers override rebuilds the engine on the next prep."""
    monkeypatch.setenv("DAGRIDER_PREP_WORKERS", "3")
    assert default_prep_workers() == 3
    reg, _ = keys
    v = TPUVerifier(reg)
    assert v.prep_stats()["workers"] == 3
    v.prep_workers = 2
    assert v.prep_stats()["workers"] == 2
    monkeypatch.setenv("DAGRIDER_PREP_WORKERS", "0")
    with pytest.raises(ValueError):
        default_prep_workers()


def test_metrics_prep_gauges_and_amortized_marker():
    from dag_rider_tpu.utils.metrics import Metrics

    m = Metrics()
    assert "verify_prep_workers" not in m.snapshot()
    m.observe_prep(4, 0.75)
    m.mark_verify_amortized()
    snap = m.snapshot()
    assert snap["verify_prep_workers"] == 4
    assert snap["verify_prep_parallel_fraction"] == 0.75
    assert snap["verify_timings_amortized"] == 1


# -- byte-identity half ---------------------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 4])
@pytest.mark.parametrize("workers", [1, 2, 4])
def test_prep_masks_byte_identical(keys, workers, depth):
    """Property: row-block parallel prep + prep-ahead == serial prep ==
    CPU oracle at every (workers, depth, bucket) combination. Bucket 32
    forces over-cap chunking AND multi-block prep (32 rows = 2 blocks at
    4 workers); bucket 64 engages all 4 blocks on the padded tail."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    for bucket in (32, 64):
        rng = random.Random(10_000 * workers + 100 * depth + bucket)
        pool = _signed_pool(keys, 96, seed=rng.randrange(1 << 30))
        rounds = _random_rounds(pool, rng)
        want = [cpu.verify_batch(r) for r in rounds]
        assert any(not all(m) for m in want if m), "no corruption landed"

        streamed = TPUVerifier(reg)
        streamed.fixed_bucket = bucket
        streamed.pipeline_depth = depth
        streamed.prep_workers = workers
        assert streamed.verify_rounds(rounds) == want

        pipe = VerifierPipeline(
            TPUVerifier(reg), depth=depth, fixed_bucket=bucket, warmup=False
        )
        pipe.verifier.prep_workers = workers
        assert pipe.verify_rounds(rounds) == want
        flat = [v for r in rounds for v in r]
        assert pipe.verify_batch(flat) == [m for ms in want for m in ms]
        if workers > 1:
            assert pipe.stats()["prep_workers"] == workers
            assert pipe.verifier.prep_stats()["parallel_fraction"] > 0.0


@pytest.mark.parametrize("depth", [2, 4])
def test_sharded_prep_masks_byte_identical(keys, depth):
    """Round-8 acceptance, sharded side: the prep engine rides the
    placement hooks, so the MESH verifier at 4 workers must match the
    CPU oracle and its own serial prep — and the pipeline must observe
    the engine engaged (no silent single-thread fallback)."""
    import jax

    from dag_rider_tpu.parallel.mesh import make_mesh
    from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(5000 + depth)
    pool = _signed_pool(keys, 96, seed=800 + depth)
    rounds = _random_rounds(pool, rng)
    want = [cpu.verify_batch(r) for r in rounds]
    assert any(not all(m) for m in want if m), "no corruption landed"

    serial = ShardedTPUVerifier(reg, make_mesh(8))
    serial.fixed_bucket = 64
    serial.pipeline_depth = depth
    serial.prep_workers = 1
    assert serial.verify_rounds(rounds) == want

    pipe = VerifierPipeline(
        ShardedTPUVerifier(reg, make_mesh(8)),
        depth=depth,
        fixed_bucket=64,
        warmup=False,
    )
    pipe.verifier.prep_workers = 4
    assert pipe.verify_rounds(rounds) == want
    s = pipe.stats()
    assert s.get("mesh_devices") == 8, "fell back to single-chip dispatch"
    assert s["prep_workers"] == 4
    assert s["prep_parallel_fraction"] > 0.0, "prep never ran parallel"


def test_prep_engine_active_through_async_seam(keys):
    """Acceptance (structural): a multi-chunk burst through the pipeline
    at workers=4 must show the engine genuinely engaged — parallel
    row-block dispatches AND prep-ahead on the seam thread — not a
    silent serial fallback."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    pool = _signed_pool(keys, 160, seed=42)
    want = cpu.verify_batch(pool)

    pipe = VerifierPipeline(
        TPUVerifier(reg), depth=2, fixed_bucket=64, warmup=False
    )
    pipe.verifier.prep_workers = 4
    assert pipe.verify_batch(pool) == want
    eng = pipe.verifier._prep()
    assert eng.workers == 4
    assert eng.dispatches_parallel > 0, "row-block pool never engaged"
    assert eng._seam is not None, "prep-ahead seam thread never engaged"
    s = pipe.stats()
    assert s["prep_workers"] == 4
    assert s["prep_parallel_fraction"] > 0.0


def test_streamed_verify_rounds_uses_prep_ahead(keys):
    """TPUVerifier's own over-cap streaming (no pipeline wrapper) also
    runs prep-ahead: same mask, seam thread engaged."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    pool = _signed_pool(keys, 160, seed=43)
    want = cpu.verify_batch(pool)
    v = TPUVerifier(reg)
    v.fixed_bucket = 64
    v.pipeline_depth = 2
    v.prep_workers = 4
    assert v.verify_rounds([pool]) == [want]
    assert v._prep()._seam is not None, "streaming path skipped prep-ahead"


class _RingWatchVerifier(TPUVerifier):
    """Snapshots every dispatched staging slot and asserts at resolve
    time that the live slot still holds the dispatched bytes — i.e. no
    later prep rewrote it while the dispatch could still be executing
    (the CPU PJRT client may alias host arrays zero-copy)."""

    def __init__(self, reg):
        super().__init__(reg)
        self.snaps = collections.deque()
        self.checked = 0

    def dispatch_prepped(self, prepped):
        out = super().dispatch_prepped(prepped)
        arrs = [a for a in prepped.args if isinstance(a, np.ndarray)]
        assert arrs, "expected numpy staging arrays in the dispatch args"
        self.snaps.append((arrs, [a.copy() for a in arrs]))
        return out

    def resolve_batch(self, handle):
        arrs, copies = self.snaps.popleft()  # FIFO == ring claim order
        for live, snap in zip(arrs, copies):
            assert np.array_equal(live, snap), (
                "staging slot rewritten while its dispatch was in flight"
            )
        self.checked += 1
        return super().resolve_batch(handle)


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_staging_ring_slot_not_rewritten_while_inflight(keys, depth):
    """Aliasing discipline under prep-ahead: with 4 workers and many
    over-cap chunks in flight, every resolved dispatch must still see
    the exact bytes it shipped."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    pool = _signed_pool(keys, 320, seed=9 * depth)
    want = cpu.verify_batch(pool)
    v = _RingWatchVerifier(reg)
    v.fixed_bucket = 64
    v.prep_workers = 4
    pipe = VerifierPipeline(v, depth=depth, warmup=False)
    assert pipe.verify_batch(pool) == want
    assert v.checked == 5  # ceil(320 / 64)
    assert not v.snaps
