"""Verifier chaos suite (round-9 tentpole acceptance).

The containment machinery (VerifierPipeline._contain, the chunk-streaming
TPUVerifier.verify_rounds loop, the PrepEngine block-pool boundary) is
tested against the faults it claims to absorb, injected by
verifier/faults.py at every seam the round-7 placement hooks expose:

- faults OFF (an armed injector whose plan never fires) must be
  byte-identical to never arming — the structural no-silent-fallback
  check, same discipline as test_prep.py's;
- an injected prep/dispatch/resolve fault must poison exactly one
  window: salvage the in-flight chunks, re-arm the staging ring,
  quarantine the failing chunk — and the full mask must still equal the
  CPU oracle once the fault clears (a bounded ``max_faults`` budget is
  the deterministic spelling of "the fault clears");
- unbounded faults must DRAIN, not wedge: with a clean quarantine tier
  the masks stay correct; without one the poisoned chunks fail closed to
  all-False but the caller still gets a full-length mask;
- the Simulation commit order under verify-stack chaos must equal the
  fault-free CPU order (the masks are a pure function of vertex bytes,
  so containment must be invisible downstream).

Transport-side: FaultyTransport must compose with any two-method
Transport (round-9 satellite — before, it reached into
InMemoryTransport internals) and its stats must surface in the
per-process metrics snapshot.
"""

import random

import pytest

from test_pipeline import N, _random_rounds, _signed_pool

from dag_rider_tpu.core.types import BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.base import Transport
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.faults import (
    VerifierFaultInjector,
    VerifierFaultPlan,
)
from dag_rider_tpu.verifier.pipeline import VerifierPipeline
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(N)


# -- faults off: arming is invisible ----------------------------------


def test_faults_off_is_byte_identical(keys):
    """An armed injector whose plan never fires must not change a single
    mask bit or gauge, and disarm() must restore the class seams."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(901)
    pool = _signed_pool(keys, 48, seed=901)
    rounds = _random_rounds(pool, rng)
    want = [cpu.verify_batch(r) for r in rounds]
    assert any(not all(m) for m in want if m), "no corruption landed"

    v = TPUVerifier(reg)
    v.fixed_bucket = 16
    v.pipeline_depth = 2
    inj = VerifierFaultInjector(VerifierFaultPlan())  # every p = 0.0
    inj.arm(v)
    try:
        assert v.verify_rounds(rounds) == want
    finally:
        inj.disarm()
    assert inj.faults_injected == 0
    assert all(c == 0 for c in inj.stats.values())
    assert v.poisoned_windows == 0
    assert v.quarantined_chunks == 0
    assert v.quarantine_rejected == 0
    # disarm really popped the instance shadows — class path is back
    assert "_prep_block" not in v.__dict__
    assert "dispatch_prepped" not in v.__dict__
    assert "resolve_batch" not in v.__dict__
    assert v.verify_rounds(rounds) == want


# -- bounded faults: contained, then byte-identical --------------------


@pytest.mark.parametrize(
    "kind", ["prep_raise", "dispatch_raise", "resolve_raise"]
)
def test_pipeline_contains_fault_and_recovers(keys, kind):
    """One injected fault per seam kind: the window is poisoned exactly
    once, the failing chunk quarantined, and the concatenated mask still
    equals the CPU oracle — no valid vertex is rejected once the fault
    clears (max_faults=1), and the window is clean for the next run."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    pool = _signed_pool(keys, 48, seed=902)
    want = cpu.verify_batch(pool)
    assert any(not ok for ok in want), "no corruption landed"

    base = TPUVerifier(reg)
    pipe = VerifierPipeline(base, depth=2, fixed_bucket=16, warmup=False)
    plan = VerifierFaultPlan(**{kind: 1.0}, max_faults=1, seed=902)
    inj = VerifierFaultInjector(plan)
    inj.arm(base)
    try:
        assert pipe.verify_batch(pool) == want
    finally:
        inj.disarm()
    assert inj.exhausted() and inj.stats[kind] == 1
    rs = pipe.resilience_stats()
    assert rs["poisoned_windows"] == 1
    assert rs["quarantined"] >= 1
    assert rs["quarantine_rejected"] == 0
    # containment gauges surface in stats() once something was contained
    s = pipe.stats()
    assert s["poisoned_windows"] == 1 and s["quarantined"] >= 1
    # the ring was re-armed: a clean pass right after is byte-identical
    assert pipe.verify_batch(pool) == want


@pytest.mark.parametrize("sharded", [False, True])
def test_streamed_rounds_contain_faults(keys, sharded):
    """The chunk-streaming verify_rounds window (no VerifierPipeline in
    the path) contains a resolve fault the same way, on the single-chip
    and the mesh-sharded verifier alike — containment lives above the
    round-7 placement hooks."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    rng = random.Random(903 + sharded)
    pool = _signed_pool(keys, 48, seed=903 + sharded)
    rounds = _random_rounds(pool, rng)
    want = [cpu.verify_batch(r) for r in rounds]

    if sharded:
        from dag_rider_tpu.parallel.mesh import make_mesh
        from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier

        v = ShardedTPUVerifier(reg, make_mesh(8))
    else:
        v = TPUVerifier(reg)
    v.fixed_bucket = 16
    v.pipeline_depth = 2
    inj = VerifierFaultInjector(
        VerifierFaultPlan(resolve_raise=1.0, max_faults=2, seed=903)
    )
    inj.arm(v)
    try:
        assert v.verify_rounds(rounds) == want
    finally:
        inj.disarm()
    assert inj.faults_injected == 2
    assert v.poisoned_windows >= 1
    assert v.quarantined_chunks >= 1
    assert v.quarantine_rejected == 0
    # clean pass after disarm: ring re-armed, no residue
    assert v.verify_rounds(rounds) == want


# -- unbounded faults: drain, never wedge ------------------------------


def test_unbounded_faults_drain_via_quarantine_tier(keys):
    """Every resolve raises, forever. With a clean quarantine tier every
    chunk is re-verified there, so the mask still equals the oracle —
    the pipeline drains instead of wedging."""
    reg, _ = keys
    cpu = CPUVerifier(reg)
    pool = _signed_pool(keys, 48, seed=904)
    want = cpu.verify_batch(pool)

    base = TPUVerifier(reg)
    pipe = VerifierPipeline(base, depth=2, fixed_bucket=16, warmup=False)
    pipe.quarantine_verifier = CPUVerifier(reg)
    inj = VerifierFaultInjector(VerifierFaultPlan(resolve_raise=1.0, seed=904))
    inj.arm(base)
    try:
        assert pipe.verify_batch(pool) == want
    finally:
        inj.disarm()
    rs = pipe.resilience_stats()
    assert rs["quarantined"] == 3  # ceil(48/16): every chunk quarantined
    assert rs["quarantine_rejected"] == 0
    assert pipe._pending() == 0, "window did not drain"


def test_unbounded_faults_without_tier_fail_closed_full_length(keys):
    """Same storm with NO quarantine tier: the quarantine retry hits the
    same faulting verifier and fail-closes. The caller still gets a
    full-length mask (drains, never wedges) and every bit is False —
    fail closed, never fail open."""
    reg, _ = keys
    pool = _signed_pool(keys, 48, seed=905)
    base = TPUVerifier(reg)
    pipe = VerifierPipeline(base, depth=2, fixed_bucket=16, warmup=False)
    inj = VerifierFaultInjector(VerifierFaultPlan(resolve_raise=1.0, seed=905))
    inj.arm(base)
    try:
        mask = pipe.verify_batch(pool)
    finally:
        inj.disarm()
    assert mask == [False] * len(pool)
    rs = pipe.resilience_stats()
    assert rs["quarantine_rejected"] == 3
    assert pipe._pending() == 0, "window did not drain"
    # and the fault clearing un-rejects them: nothing is permanent
    cpu = CPUVerifier(reg)
    assert pipe.verify_batch(pool) == cpu.verify_batch(pool)


# -- simulation: chaos is invisible in the commit order ----------------


@pytest.mark.parametrize("kind", ["dispatch_raise", "resolve_raise"])
def test_sim_commit_order_under_chaos_matches_fault_free(keys, kind):
    """Acceptance: a verify-stack fault mid-consensus must not change
    the commit order — containment re-verifies the poisoned chunks, the
    masks stay a pure function of vertex bytes, and the delivered log
    equals the fault-free CPU run's. The resilience gauges surface in
    the per-process metrics snapshot."""
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]

    def run(factory, dedup=True):
        cfg = Config(n=N, coin="round_robin", propose_empty=True)
        sim = Simulation(
            cfg,
            verifier_factory=factory,
            signer_factory=lambda i: signers[i],
        )
        sim.dedup = dedup
        sim.submit_blocks(per_process=2)
        for _ in range(10):
            sim.run(max_messages=N * (N - 1))
        sim.check_agreement()
        log = [
            (v.id.round, v.id.source, v.digest())
            for v in sim.deliveries[0]
        ]
        return log, sim

    cpu_log, _ = run(lambda i: CPUVerifier(reg))
    assert len(cpu_log) > 10, "CPU reference run delivered too little"

    shared = TPUVerifier(reg)
    shared.fixed_bucket = 16
    shared.pipeline_depth = 2
    # one fault, then clean: quarantine re-verifies on the (now clean)
    # same verifier, so the masks — and the order — cannot move
    inj = VerifierFaultInjector(
        VerifierFaultPlan(**{kind: 1.0}, max_faults=1, seed=906)
    )
    inj.arm(shared)
    try:
        # dedup off: bursts keep all n*(n-1) copies, so cycles genuinely
        # chunk past the bucket (same shape as test_pipeline's run)
        dev_log, sim = run(lambda i: shared, dedup=False)
    finally:
        inj.disarm()
    assert inj.faults_injected == 1, "chaos never hit the verify path"
    k = min(len(cpu_log), len(dev_log))
    assert k > 10 and cpu_log[:k] == dev_log[:k]
    snap = sim.processes[0].metrics.snapshot()
    assert snap.get("verify_quarantined", 0) >= 1
    assert "verify_retries" in snap and "sidecar_rpc_failures" in snap


# -- transport chaos satellites ----------------------------------------


class _PushTransport(Transport):
    """Minimal push-style transport: broadcast delivers synchronously to
    every other subscriber. Nothing beyond the two-method interface —
    the wrapper must compose with exactly this."""

    def __init__(self):
        self.handlers = {}

    def subscribe(self, index, handler):
        self.handlers[index] = handler

    def broadcast(self, msg):
        for i, h in self.handlers.items():
            if i != msg.sender:
                h(msg)


def test_faulty_transport_wraps_generic_transport():
    """Round-9 satellite: FaultyTransport over ANY Transport. Faults are
    rolled at delivery via the subscribe-captured handlers, delayed
    messages flush to the REAL handlers without a second roll, and the
    pump passthroughs are inert for a push-style inner."""
    plan = FaultPlan(delay=1.0, seed=1)
    tp = FaultyTransport(plan, inner=_PushTransport())
    got = {1: [], 2: []}
    tp.subscribe(1, got[1].append)
    tp.subscribe(2, got[2].append)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    tp.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    # delay=1.0: both deliveries held, none dropped or duplicated
    assert got[1] == [] and got[2] == []
    assert tp.stats["delayed"] == 2 and tp.stats["dropped"] == 0
    # push-style inner: nothing to pump, but the two held messages ARE
    # pending deliveries (round 11: sync patience reads this gauge to
    # tell "throttled" from "partitioned")
    assert tp.pump_one() is False and tp.pump() == 0 and tp.pending == 2
    # flush reaches the real handlers; delay=1.0 would hold them forever
    # if the flush re-rolled the plan
    assert tp.flush_delayed() == 2
    assert tp.pending == 0
    assert len(got[1]) == 1 and len(got[2]) == 1
    assert got[1][0].vertex == v

    # drop=1.0 over the same generic inner: counted, never delivered
    tp2 = FaultyTransport(FaultPlan(drop=1.0, seed=2), inner=_PushTransport())
    sunk = []
    tp2.subscribe(1, sunk.append)
    tp2.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    assert sunk == [] and tp2.stats["dropped"] == 1


def test_transport_fault_stats_surface_in_metrics_snapshot():
    """Round-9 satellite: a chaos run's FaultyTransport.stats land in
    every process's metrics snapshot as transport_* counters."""
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    plan = FaultPlan(duplicate=0.3, seed=5)
    tp = FaultyTransport(plan)
    sim = Simulation(
        Config(n=4, coin="round_robin"), transport=tp
    )
    sim.submit_blocks(per_process=2)
    sim.run(max_messages=4000)
    sim.check_agreement()
    assert tp.stats["duplicated"] > 0
    for p in sim.processes:
        snap = p.metrics.snapshot()
        assert snap["transport_duplicated"] == tp.stats["duplicated"]
        assert snap["transport_dropped"] == 0
    # clean-transport runs keep their snapshots free of transport_* keys
    clean = Simulation(Config(n=4, coin="round_robin"))
    clean.submit_blocks(per_process=1)
    clean.run(max_messages=1000)
    assert not any(
        k.startswith("transport_")
        for k in clean.processes[0].metrics.snapshot()
    )
