"""Golden tests of the dense DAG kernels against the Figure-1 fixture.

Mirrors the reference's ``TestPath`` subtests
(``process/process_internal_test.go:8-84``) — strong path across consecutive
rounds, strong path spanning 2 rounds, weak path, hybrid path, negative case —
plus quorum/admission/wave-commit kernel coverage the reference lacks.

All (round, source) pairs are 0-based-source translations of the reference's
1-based cases.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dag_rider_tpu.ops import (
    admission_mask,
    closure_from,
    closure_from_full,
    leader_reach,
    pairwise_reach,
    reach_chain,
    round_complete,
    strong_edge_quorum,
    wave_commit_votes,
)

from fixtures import N, ROUNDS, figure1_tensors


@pytest.fixture(scope="module")
def fig1():
    exists, strong, weak = figure1_tensors()
    return jnp.asarray(exists), jnp.asarray(strong), jnp.asarray(weak)


def _path(strong, weak, frm, to, strong_only):
    """path(from, to) via the closure kernels (one-hot seed)."""
    seeds = jnp.zeros((ROUNDS, N), dtype=bool).at[frm[0], frm[1]].set(True)
    if strong_only:
        reached = closure_from(seeds, strong)
    else:
        reached = closure_from_full(seeds, strong, weak)
    return bool(reached[to[0], to[1]])


# --- the five reference TestPath subtests (process_internal_test.go:20-83) ---


def test_strong_path_consecutive_rounds(fig1):
    _, strong, weak = fig1
    assert _path(strong, weak, (3, 0), (2, 2), strong_only=True)


def test_strong_path_separated_by_two_rounds(fig1):
    _, strong, weak = fig1
    assert _path(strong, weak, (3, 2), (1, 3), strong_only=True)


def test_weak_path(fig1):
    _, strong, weak = fig1
    assert _path(strong, weak, (4, 0), (2, 3), strong_only=False)


def test_hybrid_path(fig1):
    _, strong, weak = fig1
    assert _path(strong, weak, (4, 0), (1, 0), strong_only=False)


def test_no_path_exists(fig1):
    _, strong, weak = fig1
    assert not _path(strong, weak, (3, 2), (2, 3), strong_only=False)


# --- reach_chain: matmul-chain reachability -------------------------------


def test_reach_chain_single_hop(fig1):
    _, strong, _ = fig1
    reach = np.asarray(reach_chain(strong[3:4]))
    # (3,0) -> (2,0) and (2,2) only.
    assert reach[0].tolist() == [True, False, True, False]


def test_reach_chain_two_hops(fig1):
    _, strong, _ = fig1
    # rounds 3 -> 1: stack is [strong[3], strong[2]].
    reach = np.asarray(reach_chain(strong[jnp.array([3, 2])]))
    # (3,2) has strong edges to (2,{0,1,2}); their union of round-1 targets
    # is {0,1,3} | {0,1,3} | {0,2,3} = {0,1,2,3}.
    assert reach[2].all()
    # (3,0) -> (2,{0,2}) -> {0,1,3} | {0,2,3} = {0,1,2,3} minus... = all but none
    assert reach[0].tolist() == [True, True, True, True]


def test_closure_matches_pairwise_chain(fig1):
    _, strong, _ = fig1
    chains = np.asarray(pairwise_reach(strong))
    # chain[r][i, j]: (r, i) strongly reaches (0, j). Cross-check via closure.
    for r in range(ROUNDS):
        for i in range(N):
            seeds = jnp.zeros((ROUNDS, N), dtype=bool).at[r, i].set(True)
            reached = np.asarray(closure_from(seeds, strong))
            assert (reached[0] == chains[r][i]).all(), (r, i)


# --- quorum / admission kernels -------------------------------------------


def test_round_complete():
    assert bool(round_complete(jnp.array([1, 1, 1, 0], dtype=bool), quorum=3))
    assert not bool(
        round_complete(jnp.array([1, 1, 0, 0], dtype=bool), quorum=3)
    )


def test_strong_edge_quorum():
    pred = jnp.array(
        [[1, 1, 1, 0], [1, 1, 0, 0], [1, 1, 1, 1]], dtype=bool
    )
    got = np.asarray(strong_edge_quorum(pred, quorum=3))
    assert got.tolist() == [True, False, True]


def test_admission_mask(fig1):
    exists, _, _ = fig1
    exists = np.asarray(exists).copy()
    exists[3, :] = [True, False, True, True]  # (3,1) missing
    exists_j = jnp.asarray(exists)
    # Buffered round-4 candidates: one referencing (3,1) (inadmissible),
    # one referencing only present vertices (admissible).
    strong_pred = jnp.array(
        [[True, True, False, False], [True, False, True, True]], dtype=bool
    )
    weak_pred = jnp.zeros((2, ROUNDS, N), dtype=bool)
    # give candidate 1 a weak edge to an existing vertex (1,2)
    weak_pred = weak_pred.at[1, 1, 2].set(True)
    got = np.asarray(
        admission_mask(strong_pred, exists_j[3], weak_pred, exists_j)
    )
    assert got.tolist() == [False, True]
    # now make the weak target missing
    exists[1, 2] = False
    got = np.asarray(
        admission_mask(strong_pred, jnp.asarray(exists)[3], weak_pred,
                       jnp.asarray(exists))
    )
    assert got.tolist() == [False, False]


# --- wave commit (Algorithm 3 kernels) ------------------------------------


def test_wave_commit_votes(fig1):
    exists, strong, _ = fig1
    # Wave 1 = rounds 1..4. strong_wave maps round 4 -> 3 -> 2 -> 1.
    strong_wave = strong[jnp.array([4, 3, 2])]
    # Leader source 0 at round 1: only (4,0) exists with edges in round 4,
    # so at most 1 vote — no commit at quorum 3.
    commit, votes = wave_commit_votes(
        strong_wave, exists[4], jnp.int32(0), quorum=3
    )
    assert not bool(commit)
    assert np.asarray(votes).tolist() == [True, False, False, False]
    # With quorum 1 (degenerate), the same votes commit.
    commit1, _ = wave_commit_votes(
        strong_wave, exists[4], jnp.int32(0), quorum=1
    )
    assert bool(commit1)


def test_leader_reach(fig1):
    _, strong, _ = fig1
    # From (3,0) down to round 1: reaches which sources?
    reach = np.asarray(leader_reach(strong[jnp.array([3, 2])], jnp.int32(0)))
    assert reach.tolist() == [True, True, True, True]


def test_closure_from_genesis_anchoring(fig1):
    _, strong, _ = fig1
    # Every round-1 vertex reaches genesis sources {0,1,2} and not 3.
    for i in range(N):
        seeds = jnp.zeros((ROUNDS, N), dtype=bool).at[1, i].set(True)
        reached = np.asarray(closure_from(seeds, strong))
        assert reached[0].tolist() == [True, True, True, False]
