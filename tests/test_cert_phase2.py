"""Certificate path phase 2 (ISSUE 12): round-batched share signing,
device multi-pairing, and multi-round cert-of-certs.

All three optimizations are EXECUTION STRATEGIES over the ISSUE-9
certificate path, so every test here is an identity pin against the
slower oracle:

- signing: ``sign_many`` must equal mapping ``sign`` byte-for-byte on
  every backend (host loop, cffi native ladder, device limb kernels),
  across batch sizes, keys, domains, and the degenerate scalars;
- pairing: the device Miller-product lane must return the exact verdict
  the host replay returns on the full Byzantine certificate matrix;
- spans: a cluster committing through cert-of-certs must deliver the
  byte-identical log of the per-round-cert path AND the per-vertex
  path, while the books show rounds settled by spans and the verifier
  shows fewer product checks than rounds covered.
"""

from __future__ import annotations

import dataclasses
import random

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import (
    Block,
    BroadcastMessage,
    SpanCertificate,
)
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.utils import checkpoint
from dag_rider_tpu.utils.metrics import Metrics
from dag_rider_tpu.verifier.base import CertSigner, KeyRegistry
from dag_rider_tpu.verifier.cert import CertVerifier

# ---------------------------------------------------------------------------
# knob plumbing
# ---------------------------------------------------------------------------


def test_span_knob_resolution(monkeypatch):
    monkeypatch.delenv("DAGRIDER_CERT_SPAN", raising=False)
    assert Config(n=4).cert_span == 0
    monkeypatch.setenv("DAGRIDER_CERT_SPAN", "4")
    assert Config(n=4).cert_span == 4
    assert Config(n=4, cert_span=2).cert_span == 2  # explicit beats env
    with pytest.raises(ValueError):
        Config(n=4, cert_span=-1)


def test_selfcheck_knob_resolution(monkeypatch):
    monkeypatch.delenv("DAGRIDER_CERT_SELFCHECK", raising=False)
    assert Config(n=4).cert_selfcheck is True
    monkeypatch.setenv("DAGRIDER_CERT_SELFCHECK", "0")
    assert Config(n=4).cert_selfcheck is False
    assert Config(n=4, cert_selfcheck=True).cert_selfcheck is True


def test_sign_and_pair_knobs_validate(monkeypatch):
    monkeypatch.setenv("DAGRIDER_CERT_SIGN", "gpu")
    with pytest.raises(ValueError):
        bls.sign_many([3], [b"x"])
    monkeypatch.setenv("DAGRIDER_CERT_PAIR", "quantum")
    reg, _seeds, _sks = KeyRegistry.generate_with_cert(4)
    with pytest.raises(ValueError):
        CertVerifier(reg, quorum=3)


# ---------------------------------------------------------------------------
# tentpole 1: round-batched share signing == sequential, byte for byte
# ---------------------------------------------------------------------------


def _sign_batch(rng, size):
    sks = [rng.randrange(1, bls.R * 2) for _ in range(size)]
    msgs = [bytes([rng.randrange(256) for _ in range(32)]) for _ in range(size)]
    return sks, msgs


@pytest.mark.parametrize("backend", ["host", "native"])
@pytest.mark.parametrize("seed", [0, 1])
def test_sign_many_matches_sequential(backend, seed):
    rng = random.Random(1000 + seed)
    for size in (1, 2, 7, 33):
        sks, msgs = _sign_batch(rng, size)
        want = [bls.sign(sk, m) for sk, m in zip(sks, msgs)]
        assert bls.sign_many(sks, msgs, backend=backend) == want


@pytest.mark.parametrize("backend", ["host", "native"])
def test_sign_many_edge_scalars_and_cache_hits(backend):
    # sk = 0 mod r -> the infinity encoding; repeated messages exercise
    # the hash-to-curve cache-hit path; a custom domain must be honored
    # on every internal fallback too
    sks = [bls.R, 5, 5, bls.R * 2, 7]
    msgs = [b"a" * 32, b"b" * 32, b"b" * 32, b"c" * 32, b"b" * 32]
    for domain in (b"dagrider-coin-v1", b"phase2-test-domain"):
        want = [
            bls.g1_compress(bls.g1_mul(sk, bls.hash_to_g1(m, domain)))
            for sk, m in zip(sks, msgs)
        ]
        assert bls.sign_many(sks, msgs, domain=domain, backend=backend) == want


def test_sign_many_device_lane_matches_sequential():
    """The jitted limb-kernel ladder, once per suite (compile-heavy): a
    scalar pinned near r keeps the bit-width — and therefore the traced
    scan — identical across reruns."""
    rng = random.Random(77)
    sks = [bls.R - 1, bls.R, rng.randrange(1, bls.R), rng.randrange(1, bls.R)]
    msgs = [bytes([i]) * 32 for i in range(4)]
    msgs[3] = msgs[2]  # one cache-hit row
    want = [bls.sign(sk, m) for sk, m in zip(sks, msgs)]
    assert bls.sign_many(sks, msgs, backend="device") == want


def test_cert_signer_sign_digests_matches_sign_digest():
    _reg, _seeds, sks = KeyRegistry.generate_with_cert(4)
    signer = CertSigner(sks[0])
    digests = [bytes([i]) * 32 for i in range(5)]
    assert signer.sign_digests(digests) == [
        signer.sign_digest(d) for d in digests
    ]


def test_hash_g1_cache_counters():
    bls.hash_g1_cache_clear()
    msg = b"phase2-cache-counter-probe".ljust(32, b".")
    bls.hash_to_g1(msg)
    bls.hash_to_g1(msg)
    stats = bls.hash_g1_cache_stats()
    assert stats["misses"] >= 1 and stats["hits"] >= 1


def test_metrics_snapshot_surfaces_hash_cache_gauges():
    m = Metrics()
    m.counters["cert_path_enabled"] = 1
    snap = m.snapshot()
    assert "hash_g1_cache_hits" in snap
    assert "hash_g1_cache_misses" in snap


# ---------------------------------------------------------------------------
# tentpole 2: device multi-pairing verdicts == host, full Byzantine matrix
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cert_keys():
    reg, _seeds, sks = KeyRegistry.generate_with_cert(4)
    return reg, sks


def _entries(sks, digests):
    return [
        (i, d, CertSigner(sk).sign_digest(d))
        for i, (sk, d) in enumerate(zip(sks, digests))
    ]


def _digests(tag: bytes, k: int = 4):
    return [bytes([i]) * 16 + tag.ljust(16, b".") for i in range(k)]


_CERT_MUTATIONS = [
    lambda c: dataclasses.replace(c, signers=(0, 1, 3)),
    lambda c: dataclasses.replace(c, signers=(0, 1, 1)),
    lambda c: dataclasses.replace(c, signers=(0, 1, 9)),
    lambda c: dataclasses.replace(c, signers=(0, 1)),
    lambda c: dataclasses.replace(
        c,
        digests=(c.digests[0], b"stale-digest!".ljust(32, b"?"), c.digests[2]),
    ),
    lambda c: dataclasses.replace(
        c, agg_sig=bls.g1_compress(bls.g1_mul(0xBAD))
    ),
    lambda c: dataclasses.replace(c, agg_sig=b"\xff" * 48),
]


def test_device_pairing_matches_host_on_byzantine_matrix(cert_keys):
    """Every verdict of tests/test_cert.py's mutation matrix, plus the
    valid cases at two pair counts, must be identical across the pairing
    seams. Verifiers are separate objects so neither memo can leak a
    verdict to the other."""
    reg, sks = cert_keys
    host = CertVerifier(reg, quorum=3, pair="host")
    dev = CertVerifier(reg, quorum=3, pair="device")
    full = host.make_certificate(3, _entries(sks, _digests(b"byz")))
    quorum_only = host.make_certificate(4, _entries(sks, _digests(b"byq"))[:3])
    cases = [full, quorum_only] + [m(quorum_only) for m in _CERT_MUTATIONS]
    for cert in cases:
        h = host.verify_certificate(cert)
        d = dev.verify_certificate(cert)
        assert h == d, f"verdict split on {cert!r}"
    assert host.verify_certificate(full) is True  # sanity: matrix has passes
    assert dev.stats["pairing_checks"] > 0


# ---------------------------------------------------------------------------
# verify_many: batched receiver checks with localization
# ---------------------------------------------------------------------------


def test_verify_many_all_valid_single_product_check(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    c1 = cv.make_certificate(5, _entries(sks, _digests(b"vm1"))[:3])
    c2 = cv.make_certificate(6, _entries(sks, _digests(b"vm2"))[:3])
    assert cv.verify_many([c1, c2]) == [True, True]
    assert cv.stats["pairing_checks"] == 1
    # the combined verdict is memoized: a replay costs nothing
    assert cv.verify_many([c1, c2]) == [True, True]
    assert cv.stats["pairing_checks"] == 1


def test_verify_many_combined_pass_never_memoizes_members(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    c1 = cv.make_certificate(5, _entries(sks, _digests(b"nm1"))[:3])
    c2 = cv.make_certificate(6, _entries(sks, _digests(b"nm2"))[:3])
    cv.verify_many([c1, c2])
    checks = cv.stats["pairing_checks"]
    # an individual ask afterwards pays its own pairing: the combined
    # pass proved the batch's claims, not each member's well-formedness
    assert cv.verify_certificate(c1) is True
    assert cv.stats["pairing_checks"] == checks + 1


def test_verify_many_localizes_the_bad_member(cert_keys):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    good = cv.make_certificate(5, _entries(sks, _digests(b"lc1"))[:3])
    forged = dataclasses.replace(
        cv.make_certificate(6, _entries(sks, _digests(b"lc2"))[:3]),
        agg_sig=bls.g1_compress(bls.g1_mul(0xBAD)),
    )
    oracle = CertVerifier(reg, quorum=3)
    want = [
        oracle.verify_certificate(good),
        oracle.verify_certificate(forged),
    ]
    assert cv.verify_many([good, forged]) == want == [True, False]


# ---------------------------------------------------------------------------
# tentpole 3: cert-of-certs unit
# ---------------------------------------------------------------------------


def _span_fixture(cert_keys, tag=b"sp", first=5):
    reg, sks = cert_keys
    cv = CertVerifier(reg, quorum=3)
    certs = [
        cv.make_certificate(
            first + i, _entries(sks, _digests(tag + bytes([i])))[:3]
        )
        for i in range(2)
    ]
    return cv, certs


def test_make_span_requires_gap_free_rounds(cert_keys):
    cv, certs = _span_fixture(cert_keys)
    span = cv.make_span(5, certs)
    assert span is not None
    assert span.first_round == 5 and span.last_round == 6
    assert cv.make_span(4, certs) is None  # wrong anchor
    assert cv.make_span(5, [certs[0], certs[0]]) is None  # gap/duplicate
    assert cv.make_span(5, []) is None


def test_verify_span_accepts_and_memoizes(cert_keys):
    cv, certs = _span_fixture(cert_keys, tag=b"sv")
    span = cv.make_span(5, certs)
    assert cv.verify_span(span) is True
    checks = cv.stats["pairing_checks"]
    assert cv.verify_span(span) is True
    assert cv.stats["pairing_checks"] == checks  # memo hit


def test_verify_span_combined_verdict_isolated_from_certs(cert_keys):
    cv, certs = _span_fixture(cert_keys, tag=b"si")
    span = cv.make_span(5, certs)
    assert cv.verify_span(span) is True
    checks = cv.stats["pairing_checks"]
    # the span verdict never stands in for a component cert's own
    assert cv.verify_certificate(certs[0]) is True
    assert cv.stats["pairing_checks"] == checks + 1


@pytest.mark.parametrize(
    "mutate",
    [
        # forged combined aggregate
        lambda s: dataclasses.replace(
            s, agg_sig=bls.g1_compress(bls.g1_mul(0xBAD))
        ),
        # malformed aggregate bytes
        lambda s: dataclasses.replace(s, agg_sig=b"\xff" * 48),
        # one round's digest substituted after folding
        lambda s: dataclasses.replace(
            s,
            digests=(
                s.digests[0],
                (b"swapped!".ljust(32, b"?"),) + s.digests[1][1:],
            ),
        ),
        # inner round below quorum
        lambda s: dataclasses.replace(
            s,
            signers=(s.signers[0][:2], s.signers[1]),
            digests=(s.digests[0][:2], s.digests[1]),
        ),
        # unknown signer inside a round
        lambda s: dataclasses.replace(
            s, signers=(s.signers[0], (0, 1, 9))
        ),
        # genesis anchor is not certifiable
        lambda s: dataclasses.replace(s, first_round=0),
    ],
)
def test_byzantine_span_always_detected(cert_keys, mutate):
    cv, certs = _span_fixture(cert_keys, tag=b"sb")
    span = cv.make_span(5, certs)
    assert cv.verify_span(span) is True
    assert cv.verify_span(mutate(span)) is False
    # a defect never poisons the good verdict
    assert cv.verify_span(span) is True


# ---------------------------------------------------------------------------
# wire + checkpoint round-trips
# ---------------------------------------------------------------------------


def test_span_codec_roundtrip(cert_keys):
    cv, certs = _span_fixture(cert_keys, tag=b"wire")
    span = cv.make_span(5, certs)
    out, _ = codec.decode_span_certificate(codec.encode_span_certificate(span))
    assert out == span
    msg = BroadcastMessage(
        vertex=None, round=6, sender=2, kind="cert_span", span=span
    )
    got = codec.decode_message(codec.encode_message(msg))[0]
    assert got.kind == "cert_span" and got.span == span
    assert codec.decode_many(codec.encode_many([msg]))[0].span == span
    # the new kind is appended: every pre-existing kind keeps its code,
    # so mixed-version clusters still parse each other's old messages
    assert codec._KINDS.index("cert_span") == len(codec._KINDS) - 1


def test_checkpoint_roundtrips_cert_and_span_books(tmp_path, cert_keys):
    cv, certs = _span_fixture(cert_keys, tag=b"ck")
    logs, sim = _run(4, 11, cert=True, span=2, blocks=3)
    p = sim.processes[1]
    # force non-trivial books, including a banked partial epoch
    p._span_bank = {7: {certs[0].round: certs[0]}}
    path = str(tmp_path / "p1")
    checkpoint.save(p, path)
    cfg2 = Config(
        n=4, coin="round_robin", propose_empty=False, pump="vector",
        cert_span=2,
    )
    sim2 = Simulation(cfg2, verifier="cpu", cert=True)
    fresh = sim2.processes[1]
    checkpoint.restore(fresh, path)
    assert fresh._cert_done == p._cert_done
    assert fresh._certs_sent == p._certs_sent
    assert fresh._spans_sent == p._spans_sent
    assert fresh._span_done == p._span_done
    assert fresh._span_bank == p._span_bank


# ---------------------------------------------------------------------------
# end-to-end: span commit order == per-round certs == per-vertex oracle
# ---------------------------------------------------------------------------


def _run(n, seed, *, cert, span=0, selfcheck=True, pump="vector", blocks=6):
    cfg = Config(
        n=n, coin="round_robin", propose_empty=False, pump=pump,
        cert_span=span, cert_selfcheck=selfcheck,
    )
    sim = Simulation(cfg, verifier="cpu", cert=cert)
    for i in range(n):
        for k in range(blocks):
            sim.processes[i].submit(
                Block((f"s{seed}-p{i}-b{k}".encode().ljust(32, b"."),))
            )
    sim.run(max_messages=400_000)
    sim.check_agreement()
    logs = [
        [(v.id, v.digest()) for v in sim.deliveries[i]] for i in range(n)
    ]
    return logs, sim


def _cluster_count(sim, key):
    return sum(p.metrics.counters.get(key, 0) for p in sim.processes)


@pytest.mark.parametrize(
    "n,seed",
    [(4, 0), (4, 1), (16, 0), pytest.param(32, 0, marks=pytest.mark.slow)],
)
def test_span_commit_order_identical(n, seed):
    """Acceptance: the cert-of-certs overlay changes which CHECK settles
    a round, never what commits — span-on, span-off, and cert-off runs
    deliver byte-identical logs while the span books fill."""
    span_logs, span_sim = _run(n, seed, cert=True, span=2)
    cert_logs, _cert_sim = _run(n, seed, cert=True, span=0)
    ref_logs, _ref_sim = _run(n, seed, cert=False)
    assert any(ref_logs)
    assert span_logs == cert_logs == ref_logs
    assert _cluster_count(span_sim, "spans_assembled") > 0
    assert _cluster_count(span_sim, "spans_verified") > 0
    assert _cluster_count(span_sim, "span_rounds_settled") > 0


def test_selfcheck_off_is_safe_and_cheaper():
    """With DAGRIDER_CERT_SELFCHECK off the aggregator gossips without
    the pre-flight verify: commit order is unchanged (peers verify
    independently) and the shared verifier performs fewer product
    checks for the same number of assembled certificates."""
    on_logs, on_sim = _run(4, 2, cert=True, span=2, selfcheck=True)
    off_logs, off_sim = _run(4, 2, cert=True, span=2, selfcheck=False)
    ref_logs, _ = _run(4, 2, cert=False)
    assert on_logs == off_logs == ref_logs
    assert _cluster_count(off_sim, "certs_assembled") > 0
    assert (
        off_sim.cert_verifier.stats["pairing_checks"]
        <= on_sim.cert_verifier.stats["pairing_checks"]
    )


def test_span_replay_consumer_under_one_check_per_round(cert_keys):
    """The catch-up story the ops/round acceptance gate names: a fresh
    verifier replaying R rounds through R/k span checks performs fewer
    product checks than rounds covered (< 1 per round), with identical
    per-round claims."""
    reg, sks = cert_keys
    maker = CertVerifier(reg, quorum=3)
    k, epochs = 4, 2
    certs = [
        maker.make_certificate(
            r, _entries(sks, _digests(b"rp" + bytes([r])))[:3]
        )
        for r in range(1, k * epochs + 1)
    ]
    spans = [
        maker.make_span(e * k + 1, certs[e * k : (e + 1) * k])
        for e in range(epochs)
    ]
    consumer = CertVerifier(reg, quorum=3)
    assert all(consumer.verify_span(s) for s in spans)
    rounds = k * epochs
    assert consumer.stats["pairing_checks"] < rounds
    assert consumer.stats["pairing_checks"] == epochs
    # and the spans restate exactly the per-round claims
    for e, s in enumerate(spans):
        for i in range(k):
            assert s.signers[i] == certs[e * k + i].signers
            assert s.digests[i] == certs[e * k + i].digests


def test_byzantine_span_rejected_without_degradation():
    """A forged span from the wire is rejected by the combined check and
    costs nothing: no round degrades (round certificates remain the
    liveness anchor) and the cluster keeps its delivered prefix."""
    _, sim = _run(4, 3, cert=True, span=2)
    p = sim.processes[1]
    k = 2
    e = (p.round // k) + 2  # epoch comfortably beyond settled rounds
    first = e * k + 1
    forged = SpanCertificate(
        first_round=first,
        signers=((0, 1, 2), (0, 1, 2)),
        digests=(
            tuple(bytes([i]) * 32 for i in range(3)),
            tuple(bytes([i + 8]) * 32 for i in range(3)),
        ),
        agg_sig=bls.g1_compress(bls.g1_mul(0xBAD)),
    )
    degraded = p.metrics.counters.get("cert_rounds_degraded", 0)
    pre_log = list(p.delivered_log)
    p._on_span(
        BroadcastMessage(
            vertex=None, round=first + k - 1, sender=3,
            kind="cert_span", span=forged,
        )
    )
    assert p.metrics.counters.get("spans_rejected", 0) >= 1
    assert p.metrics.counters.get("cert_rounds_degraded", 0) == degraded
    assert p.delivered_log[: len(pre_log)] == pre_log


def test_span_for_settled_rounds_ignored(cert_keys):
    """A span covering only already-settled rounds is dropped without a
    product check — a receiver never re-opens a settled round."""
    _, sim = _run(4, 5, cert=True, span=2)
    p = sim.processes[2]
    settled = sorted(
        r for r in p._cert_done if (r - 1) % 2 == 0 and r + 1 in p._cert_done
    )
    if not settled:
        pytest.skip("run settled no aligned epoch on this seed")
    first = settled[0]
    e = (first - 1) // 2
    p._span_done.discard(e)
    cv, certs = _span_fixture(cert_keys, tag=b"ig", first=first)
    span = cv.make_span(first, certs)
    checks = sim.cert_verifier.stats["pairing_checks"]
    ignored = p.metrics.counters.get("spans_ignored", 0)
    p._on_span(
        BroadcastMessage(
            vertex=None, round=first + 1, sender=3,
            kind="cert_span", span=span,
        )
    )
    assert p.metrics.counters.get("spans_ignored", 0) == ignored + 1
    assert sim.cert_verifier.stats["pairing_checks"] == checks
