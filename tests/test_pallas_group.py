"""Pallas group-kernel logic vs the jnp oracle — bit-identical.

The kernels' limb math is exercised by calling the kernel bodies
directly with mock Refs (plain array wrappers) — same code path Mosaic
compiles, minus the pallas_call plumbing, which the interpreter would
run ~1000x slower than the suite budget allows. The compiled-Mosaic
plumbing (BlockSpecs, grids, lane tiling) is validated on the real chip
by the bench verify phases, whose masks are asserted against signed and
corrupted batches there.
"""

import numpy as np
import jax.numpy as jnp

from dag_rider_tpu.crypto import ed25519 as host
from dag_rider_tpu.ops import comb, field as F, pallas_group as PG


class _Ref:
    """Minimal stand-in for a pallas VMEM ref: slice-read, slice-write."""

    def __init__(self, arr):
        self.arr = np.array(arr)

    @property
    def shape(self):
        return self.arr.shape

    def __getitem__(self, idx):
        return jnp.asarray(self.arr[idx])

    def __setitem__(self, idx, val):
        self.arr[idx] = np.asarray(val)


def _host_points(m, start=1):
    pts, acc = [], host.B
    for _ in range(start - 1):
        acc = host.point_add(acc, host.B)
    out = np.zeros((m, 4, 22), np.int32)
    for i in range(m):
        X, Y, Z, T = acc
        out[i, 0] = F.to_limbs(X % F.P_INT)
        out[i, 1] = F.to_limbs(Y % F.P_INT)
        out[i, 2] = F.to_limbs(Z % F.P_INT)
        out[i, 3] = F.to_limbs(T % F.P_INT)
        acc = host.point_add(acc, host.B)
    return out


def _lm(pts):  # [m, 4, 22] -> limb-major [88, m]
    return np.moveaxis(pts, 0, -1).reshape(PG.ROWS, pts.shape[0])


def _run_padd(p_np, q_np):
    out = _Ref(np.zeros_like(_lm(p_np)))
    PG._padd_xx_kernel(_Ref(_lm(p_np)), _Ref(_lm(q_np)), out)
    return out.arr


def test_padd_xx_kernel_matches_packed_jnp():
    m = 8
    p_np = _host_points(m, start=1)
    q_np = _host_points(m, start=m + 1)
    got = _run_padd(p_np, q_np)
    want = comb.padd_cached(
        jnp.asarray(p_np), comb.to_cached(jnp.asarray(q_np))
    )
    assert (got == _lm(np.asarray(want))).all()


def test_padd_xx_kernel_identity():
    p_np = _host_points(2, start=3)
    ident = np.zeros((2, 4, 22), np.int32)
    ident[:, 1] = F.ONE
    ident[:, 2] = F.ONE
    out = _run_padd(ident, p_np).reshape(4, 22, 2)

    def affine(pt4x22):
        X = F.from_limbs(pt4x22[0]) % F.P_INT
        Y = F.from_limbs(pt4x22[1]) % F.P_INT
        Z = F.from_limbs(pt4x22[2]) % F.P_INT
        zi = pow(Z, F.P_INT - 2, F.P_INT)
        return X * zi % F.P_INT, Y * zi % F.P_INT

    for i in range(2):
        assert affine(out[:, :, i]) == affine(p_np[i])


def test_pow22523_kernel_matches_field():
    rng = np.random.default_rng(5)
    zs = np.stack(
        [F.to_limbs(int(v)) for v in rng.integers(1, 2**62, size=4)]
    ).astype(np.int32)
    out = _Ref(np.zeros((PG.L, 4), np.int32))
    PG._pow22523_kernel(_Ref(np.moveaxis(zs, 0, 1)), out)
    want = np.asarray(F.pow22523(jnp.asarray(zs)))
    assert (out.arr == np.moveaxis(want, 0, 1)).all()


def test_finish_kernel_matches_jnp_tail():
    """_finish_kernel (decompress + rhs add + projective equality) vs the
    jnp tail, on real signature data: a valid case, a wrong-lhs case, a
    non-square y (no root), and the x==0-with-sign-bit reject arm."""
    import jax

    from dag_rider_tpu.crypto import ed25519
    from dag_rider_tpu.ops import curve

    sk, pk = host.generate_keypair(b"\x07" * 32)
    msg = b"finish-kernel-test"
    sig = host.sign(sk, msg)
    a_pt = host.point_decompress(pk)
    r_y_int = int.from_bytes(sig[:32], "little")
    r_sign = r_y_int >> 255
    r_y_int &= (1 << 255) - 1
    s = int.from_bytes(sig[32:], "little")
    import hashlib

    k = (
        int.from_bytes(
            hashlib.sha512(sig[:32] + pk + msg).digest(), "little"
        )
        % ed25519.L
    )
    lhs_pt = host.scalar_mult(s, host.B)
    ka_pt = host.scalar_mult(k, a_pt)

    def limbs(pt):
        X, Y, Z, T = pt
        return np.stack(
            [
                F.to_limbs(X % F.P_INT),
                F.to_limbs(Y % F.P_INT),
                F.to_limbs(Z % F.P_INT),
                F.to_limbs(T % F.P_INT),
            ]
        )

    cases = []  # (y_limbs, sign, lhs, ka)
    cases.append((F.to_limbs(r_y_int), r_sign, limbs(lhs_pt), limbs(ka_pt)))
    # wrong lhs: equality must fail
    cases.append(
        (F.to_limbs(r_y_int), r_sign, limbs(host.B), limbs(ka_pt))
    )
    # y with no curve point (2 is a non-square candidate on this curve)
    cases.append((F.to_limbs(2), 0, limbs(lhs_pt), limbs(ka_pt)))
    # x == 0 with sign bit set: y = 1 gives x = 0; sign 1 must reject
    cases.append((F.to_limbs(1), 1, limbs(lhs_pt), limbs(ka_pt)))

    m = len(cases)
    y_t = np.zeros((22, m), np.int32)
    sign_t = np.zeros((1, m), np.int32)
    acc = np.zeros((m, 2, 4, 22), np.int32)
    for j, (y, sg, lhs, ka) in enumerate(cases):
        y_t[:, j] = y
        sign_t[0, j] = sg
        acc[j, 0] = lhs
        acc[j, 1] = ka
    acc_t = np.moveaxis(acc.reshape(m, 8, 22), 0, -1).reshape(176, m)
    out = _Ref(np.zeros((1, m), np.int32))
    PG._finish_kernel(_Ref(y_t), _Ref(sign_t), _Ref(acc_t), out)
    got = out.arr[0].astype(bool)

    jacc = jnp.asarray(acc)
    r_pt, r_valid = curve.decompress(
        jnp.asarray(y_t.T), jnp.asarray(sign_t[0])
    )
    rhs = curve.padd(r_pt, comb.unpack_point(jacc[:, 1]))
    want = np.asarray(
        curve.points_equal(comb.unpack_point(jacc[:, 0]), rhs) & r_valid
    )
    assert (got == want).all()
    assert got.tolist() == [True, False, False, False]


def test_tree_pairing_matches_jnp_tree():
    # The tree pairs first half + second half each level in both
    # implementations; replay the pallas pairing with kernel-body calls
    # and compare against comb.tree_sum_packed bit-for-bit.
    m = 4
    pts = _host_points(m)
    x = _lm(pts)
    while x.shape[1] > 1:
        half = x.shape[1] // 2
        out = _Ref(np.zeros((PG.ROWS, half), np.int32))
        PG._padd_xx_kernel(_Ref(x[:, :half]), _Ref(x[:, half:]), out)
        x = out.arr
    want = np.asarray(comb.tree_sum_packed(jnp.asarray(pts)[None]))[0]
    assert (x[:, 0] == want.reshape(PG.ROWS)).all()
