"""Limb-field arithmetic (ops/field.py) vs Python bignum oracle.

The TPU verifier's correctness reduces to this field layer: every op must
be exact mod p for all reduced representations, including the signed-limb
and near-boundary cases that only arise deep inside point-op chains.
"""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dag_rider_tpu.ops import field as F

P = F.P_INT


def limbs(x):
    return jnp.asarray(F.to_limbs(x)[None])


def value(arr, i=0):
    return F.from_limbs(np.asarray(F.canonical(arr))[i])


EDGE = [0, 1, 2, 19, P - 1, P - 2, P - 19, 2**252, 2**255 - 20, (P + 1) // 2]


@pytest.fixture(scope="module")
def rng():
    return random.Random(0xDA6)


def batch_of(values):
    return jnp.asarray(np.stack([F.to_limbs(v) for v in values]))


def test_add_sub_mul_random(rng):
    xs = [rng.randrange(P) for _ in range(32)] + EDGE
    ys = [rng.randrange(P) for _ in range(32)] + list(reversed(EDGE))
    A, B = batch_of(xs), batch_of(ys)
    add = jax.jit(F.add)(A, B)
    sub = jax.jit(F.sub)(A, B)
    mul = jax.jit(F.mul)(A, B)
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert value(add, i) == (x + y) % P
        assert value(sub, i) == (x - y) % P
        assert value(mul, i) == (x * y) % P


def test_mul_extreme_limb_patterns():
    """Representations with saturated limbs (the pattern that exposed the
    dropped col-43 carry: every limb near 2^12, top limb at 2^12)."""
    patterns = [
        np.full(F.LIMBS, 4095, dtype=np.int32),
        np.full(F.LIMBS, -4095, dtype=np.int32),
        np.array([16383] + [8191] * 20 + [8191], dtype=np.int32),
        np.array([-16383] + [-8191] * 21, dtype=np.int32),
        np.array([2560] + [4095] * 18 + [-1, 0, 4096], dtype=np.int32),
    ]
    A = jnp.asarray(np.stack(patterns))
    got = jax.jit(F.mul)(A, A)
    for i, pat in enumerate(patterns):
        want = pow(F.from_limbs(pat) % P, 2, P)
        assert value(got, i) == want, f"pattern {i}"


def test_invariant_preserved_deep_chain(rng):
    """|limb0| < 2^14 and |limb_i| < 2^13 must survive arbitrary op chains
    (mul inputs assume it; violation silently overflows int32)."""
    x = rng.randrange(P)
    y = rng.randrange(P)
    A, B = limbs(x), limbs(y)
    vx = x
    for i in range(40):
        A = F.mul(F.sub(A, B), F.add(A, B))
        vx = ((vx - y) % P) * ((vx + y) % P) % P
        raw = np.asarray(A)[0]
        assert abs(int(raw[0])) < 2**14, f"limb0 blown at step {i}"
        assert (np.abs(raw[1:]) < 2**13).all(), f"limb blown at step {i}"
    assert value(A) == vx


def test_inversion_and_pow(rng):
    xs = [rng.randrange(1, P) for _ in range(8)] + [1, P - 1, 2]
    A = batch_of(xs)
    inv = jax.jit(F.invert)(A)
    p22 = jax.jit(F.pow22523)(A)
    for i, x in enumerate(xs):
        assert value(inv, i) == pow(x, P - 2, P)
        assert value(p22, i) == pow(x, 2**252 - 3, P)
    assert value(jax.jit(F.invert)(limbs(0))) == 0


def test_canonical_uniqueness():
    """Different representations of the same residue must canonicalize to
    identical limbs — eq/is_zero depend on it."""
    reps = [
        F.to_limbs(19),
        (F.to_limbs(19 + 0) + F.P_LIMBS).astype(np.int32),  # 19 + p
        np.array([19 - 4096, 1] + [0] * 20, dtype=np.int32),  # borrow form
    ]
    outs = [np.asarray(F.canonical(jnp.asarray(r[None])))[0] for r in reps]
    for o in outs[1:]:
        assert np.array_equal(o, outs[0])
    # values in [p, 2^255) reduce
    assert value(jnp.asarray(F.to_limbs(P)[None])) == 0
    assert value(jnp.asarray(F.to_limbs(P + 5)[None])) == 5
    assert value(jnp.asarray(F.to_limbs(2**255 - 1)[None])) == 18


def test_predicates(rng):
    x = rng.randrange(1, P)
    A = limbs(x)
    assert bool(np.asarray(F.is_zero(F.sub(A, A)))[0])
    assert not bool(np.asarray(F.is_zero(A))[0])
    assert bool(np.asarray(F.eq(A, A))[0])
    assert int(np.asarray(F.parity(A))[0]) == x & 1
    got = np.asarray(F.select(jnp.asarray([True]), A, limbs(1)))
    assert np.array_equal(got, np.asarray(A))


def test_mul_small(rng):
    xs = [rng.randrange(P) for _ in range(4)] + EDGE[:4]
    A = batch_of(xs)
    for k in (0, 1, 2, 19, 4095):
        got = jax.jit(F.mul_small, static_argnums=1)(A, k)
        for i, x in enumerate(xs):
            assert value(got, i) == x * k % P
