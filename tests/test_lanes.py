"""Sharded dissemination lanes (ISSUE 17): digest-only ordering.

``DAGRIDER_LANES`` moves payload bytes off the consensus path — the
vertex carries a constant-size certified digest, worker lanes move the
batch, delivery resolves the digest back — and must change NOTHING the
client can observe: commit order and delivered transaction bytes are
pinned identical to the inline oracle across n x adversary x pump (the
seeded fuzz matrix here), the carrier codec round-trips byte-exactly,
lane state survives a checkpoint/restore (and pre-lanes checkpoints
restore with lanes empty), and the two lane-layer Byzantine strategies
(batch withholding, garbage availability acks) degrade to fetch-on-miss
or the inline path with zero transaction loss.
"""

from __future__ import annotations

import hashlib

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.adversary import ByzantineProcess, make_behavior
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.scenarios import Scenario, run_scenario
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, LaneRef, Vertex, VertexID
from dag_rider_tpu.lanes import LaneCoordinator
from dag_rider_tpu.transport.lanebus import LaneBus
from dag_rider_tpu.utils import checkpoint
from dag_rider_tpu.utils.metrics import Metrics


# -- carrier codec ----------------------------------------------------------


def test_lane_ref_codec_roundtrip():
    ref = LaneRef(
        producer=3,
        seq=17,
        digest=bytes(range(32)),
        count=9,
        nbytes=4096,
        signers=(0, 2, 3),
        agg_sig=bytes(range(48)),
    )
    tx = codec.encode_lane_ref(ref)
    assert tx.startswith(codec.LANE_MAGIC)
    assert codec.decode_lane_ref(tx) == ref
    # unsigned shape (keyless simulator)
    bare = LaneRef(0, 0, b"\x00" * 32, 1, 64)
    assert codec.decode_lane_ref(codec.encode_lane_ref(bare)) == bare


def test_lane_ref_of_shapes():
    ref = LaneRef(1, 2, b"\xab" * 32, 3, 128, signers=(0, 1, 2))
    tx = codec.encode_lane_ref(ref)
    assert codec.lane_ref_of(Block((tx,))) == ref
    # ordinary client payloads are never refs
    assert codec.decode_lane_ref(b"client tx") is None
    assert codec.lane_ref_of(Block((b"a", b"b"))) is None
    # a carrier must be the ONLY transaction
    assert codec.lane_ref_of(Block((tx, b"extra"))) is None
    # strict decode rejects trailing garbage...
    with pytest.raises(ValueError):
        codec.decode_lane_ref(tx + b"x")
    # ...but the delivery-path helper treats a malformed magic-prefixed
    # tx (Byzantine-crafted — honest publishes round-trip) as a payload
    # block rather than crashing resolve
    assert codec.lane_ref_of(Block((tx + b"x",))) is None
    assert codec.lane_ref_of(Block((codec.LANE_MAGIC + b"\x01",))) is None


# -- coordinator unit behavior ---------------------------------------------


def _cluster(n=4, min_bytes=64, workers=2):
    cfg = Config(
        n=n, lanes=True, lane_batch_bytes=min_bytes, lane_workers=workers
    )
    bus = LaneBus(n, workers=workers)
    coords = [
        LaneCoordinator(cfg, i, bus.endpoint(i), metrics=Metrics())
        for i in range(n)
    ]
    return cfg, bus, coords


def _big_block(tag: bytes, nbytes: int = 512) -> Block:
    return Block((tag.ljust(nbytes, b"."),))


def test_publish_certifies_and_resolves():
    _, bus, coords = _cluster()
    block = _big_block(b"payload-a")
    pending = coords[0].begin_publish(block)
    assert pending is not None
    assert pending.transactions == block.transactions  # queue-reader view
    carrier = coords[0].materialize(pending)
    ref = codec.lane_ref_of(carrier)
    assert ref is not None
    assert ref.producer == 0 and ref.count == 1
    assert len(ref.signers) == coords[0].quorum
    assert coords[0].metrics.counters["lane_batches_certified"] == 1
    # every process resolves the carrier back to the exact payload
    for c in coords:
        v = Vertex(id=VertexID(1, 0), block=carrier)
        assert c.resolve_vertex(v).block == block
    # non-carrier vertices pass through untouched (inline oracle path)
    plain = Vertex(id=VertexID(1, 1), block=block)
    assert coords[1].resolve_vertex(plain) is plain


def test_small_and_magic_aliasing_blocks_ship_inline():
    _, _, coords = _cluster(min_bytes=256)
    assert coords[0].begin_publish(Block((b"tiny",))) is None
    assert coords[0].begin_publish(Block(())) is None
    alias = Block(((codec.LANE_MAGIC + b"x").ljust(512, b"!"),))
    assert coords[0].begin_publish(alias) is None
    # materialize passes plain blocks straight through
    assert coords[0].materialize(alias) is alias


def test_under_quorum_publish_degrades_to_inline():
    _, _, coords = _cluster()
    coords[0]._broadcast_batch = lambda digest, payload: 0  # withhold from all
    block = _big_block(b"withheld")
    out = coords[0].materialize(coords[0].begin_publish(block))
    assert out == block  # the inline oracle, byte-identical
    assert coords[0].metrics.counters["lane_publish_degraded"] == 1
    assert coords[0].metrics.counters["lane_batches_certified"] == 0


def test_fetch_on_miss_recovers_from_certified_holder():
    _, _, coords = _cluster()
    block = _big_block(b"fetch-me")
    carrier = coords[0].materialize(coords[0].begin_publish(block))
    ref = codec.lane_ref_of(carrier)
    # simulate a receiver that never saw the batch (washed out / late
    # join): wipe its store, then resolve — must pull from a signer
    victim = coords[3]
    with victim._lock:
        victim._store.clear()
    v = Vertex(id=VertexID(1, 0), block=carrier)
    assert victim.resolve_vertex(v).block == block
    assert victim.metrics.counters["lane_fetch_misses"] == 1
    served = sum(c.stats()["served"] for c in coords)
    assert served >= 1
    # unrecoverable (no holder anywhere) fails loudly, not silently
    ghost = LaneRef(0, 99, b"\x13" * 32, 1, 64, signers=(0, 1, 2))
    phantom = Block((codec.encode_lane_ref(ghost),))
    with pytest.raises(RuntimeError):
        victim.resolve_vertex(Vertex(id=VertexID(2, 0), block=phantom))


def test_coordinator_checkpoint_roundtrip():
    _, _, coords = _cluster()
    block = _big_block(b"persist")
    carrier = coords[0].materialize(coords[0].begin_publish(block))
    state = coords[0].checkpoint_state()
    assert state["seq"] == 1 and len(state["batches"]) >= 1

    _, _, fresh = _cluster()
    fresh[0].restore_state(state)
    assert fresh[0]._seq == 1
    assert fresh[0].peek_block(carrier) == block
    # corrupt batch bytes are re-hashed on the way in and dropped
    bad = {
        "version": 1,
        "seq": 5,
        "batches": [[state["batches"][0][0], "deadbeef"]],
    }
    fresh[1].restore_state(bad)
    assert fresh[1].stats()["store"] == 0 and fresh[1]._seq == 5
    # pre-lanes checkpoints restore with lanes empty
    fresh[2].restore_state(None)
    assert fresh[2].stats()["store"] == 0 and fresh[2]._seq == 0


# -- seeded fuzz matrix: lanes must be invisible ----------------------------


def _delivery_fingerprint(sim):
    """(commit order, delivered-bytes digest) per process. The digest
    hashes the length-prefixed client transaction bytes actually
    surfaced — NOT vertex digests — so a carrier that resolved to the
    wrong payload cannot hide."""
    orders, digests = [], []
    for d in sim.deliveries:
        orders.append([(v.id.round, v.id.source) for v in d])
        h = hashlib.sha256()
        for v in d:
            for tx in v.block.transactions:
                h.update(len(tx).to_bytes(4, "little"))
                h.update(tx)
        digests.append(h.hexdigest())
    return orders, digests


def _run_cluster(n, seed, adversary, pump, lanes, cycles):
    cfg = Config(
        n=n,
        coin="round_robin",
        propose_empty=True,
        pump=pump,
        lanes=lanes,
        lane_batch_bytes=256,
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
        sync_patience=1,
    )
    nbyz = cfg.f if adversary else 0
    behaviors = {
        i: make_behavior(adversary, seed=seed + 1000 + i)
        for i in range(nbyz)
    }

    def factory(pcfg, i, ptp, **kwargs):
        if i in behaviors:
            return ByzantineProcess(
                pcfg, i, ptp, behavior=behaviors[i], **kwargs
            )
        return Process(pcfg, i, ptp, **kwargs)

    sim = Simulation(cfg, process_factory=factory if behaviors else None)
    sim.submit_blocks(2, tx_bytes=600)  # above the 256-byte lane floor
    for _ in range(cycles):
        sim.run(max_messages=n * (n - 1))
    return sim


MATRIX = [
    (4, 11, None),
    (4, 12, "equivocate"),
    (4, 13, "withhold"),
    (16, 14, None),
    (16, 15, "equivocate"),
    (16, 16, "withhold"),
    (32, 17, None),
]


@pytest.mark.parametrize("pump", ["scalar", "vector"])
@pytest.mark.parametrize("n,seed,adversary", MATRIX)
def test_lanes_identical_to_inline_oracle(n, seed, adversary, pump):
    """The headline invariant: same commit order AND same delivered
    transaction bytes, lanes vs inline, per honest process."""
    cycles = 10 if n >= 32 else 14
    ref = _run_cluster(n, seed, adversary, pump, False, cycles)
    lane = _run_cluster(n, seed, adversary, pump, True, cycles)
    ref_orders, ref_digests = _delivery_fingerprint(ref)
    lane_orders, lane_digests = _delivery_fingerprint(lane)
    nbyz = Config(n=n).f if adversary else 0
    for i in range(nbyz, n):
        assert lane_orders[i] == ref_orders[i], f"commit order @ p{i}"
        assert lane_digests[i] == ref_digests[i], f"delivered bytes @ p{i}"
    assert any(len(o) > 0 for o in ref_orders[nbyz:])  # non-vacuous
    # ...and the lane path genuinely ran: every honest submit cleared
    # the batch floor, so certified batches must exist cluster-wide
    certified = sum(
        p.metrics.counters.get("lane_batches_certified", 0)
        + p.metrics.counters.get("lane_publish_degraded", 0)
        for p in lane.processes
    )
    assert certified > 0


def test_sub_threshold_blocks_bypass_lanes_entirely():
    """Blocks under the batch floor never touch the lane machinery —
    the legacy 32-byte shapes are literally the inline path."""
    sim = _run_cluster(4, 21, None, "scalar", True, 10)
    # the matrix harness pads past the floor; rerun small by hand
    cfg = Config(
        n=4, lanes=True, lane_batch_bytes=1024, propose_empty=True
    )
    small = Simulation(cfg)
    small.submit_blocks(2, tx_bytes=32)
    for _ in range(10):
        small.run(max_messages=12)
    assert sum(
        p.metrics.counters.get("lane_batches_certified", 0)
        for p in small.processes
    ) == 0
    assert any(len(d) > 0 for d in small.deliveries)
    del sim


# -- lane-layer Byzantine strategies ----------------------------------------


def test_lane_withhold_scenario_recovers_every_byte():
    r = run_scenario(Scenario(n=4, adversary="lane_withhold", seed=3))
    assert r["invariants"] == {
        "agreement": True,
        "commit_uniqueness": True,
        "zero_loss": True,
        "liveness": True,
    }
    assert r["lanes"] is True
    assert r["behavior"]["withheld"] > 0  # the attack genuinely ran
    # withheld batches either forced pull-based recovery or starved the
    # ack quorum into the inline degrade — both are zero-loss outcomes
    assert r["lane_fetch_misses"] + r["lane_publish_degraded"] > 0
    assert r["audit"]["lost"] == 0


def test_lane_garbage_ack_scenario_still_certifies():
    r = run_scenario(Scenario(n=4, adversary="lane_garbage_ack", seed=5))
    assert r["invariants"]["zero_loss"] and r["invariants"]["agreement"]
    assert r["behavior"]["mutated"] > 0  # garbage acks were emitted
    # digest-keyed collection shrugs them off: honest producers still
    # reach self + (n-1-f) = 2f+1 and certify every batch
    assert r["lane_batches_certified"] > 0
    assert r["lane_publish_degraded"] == 0
    assert r["audit"]["lost"] == 0


def test_lane_adversaries_registered():
    for kind in ("lane_withhold", "lane_garbage_ack"):
        b = make_behavior(kind, seed=1)
        assert set(b.stats) >= {"mutated", "withheld", "extra_sent"}


# -- checkpoint integration -------------------------------------------------


def _lane_sim(n=4):
    cfg = Config(
        n=n, lanes=True, lane_batch_bytes=256, propose_empty=True
    )
    sim = Simulation(cfg)
    sim.submit_blocks(3, tx_bytes=600)
    sim.run(max_messages=400)  # partial: likely mid-dissemination
    return cfg, sim


def test_checkpoint_roundtrips_lane_state(tmp_path):
    """Kill-and-restore mid-dissemination loses no accepted
    transaction: the lane store rides the manifest, pending publishes
    degrade to inline via their serialized payload blocks."""
    cfg, sim = _lane_sim()
    p0 = sim.processes[0]
    pre_store = p0.lanes.stats()["store"]
    pre_seq = p0.lanes._seq
    pre_queue = [b.transactions for b in p0.blocks_to_propose]
    ckpt = str(tmp_path / "p0")
    checkpoint.save(p0, ckpt)

    from dag_rider_tpu.transport.lanebus import LaneBus as _LB
    from dag_rider_tpu.transport.memory import InMemoryTransport

    cfg2 = Config(n=4, lanes=True, lane_batch_bytes=256)
    p0b = Process(cfg2, 0, InMemoryTransport())
    bus2 = _LB(4, workers=2)
    p0b.attach_lanes(
        LaneCoordinator(cfg2, 0, bus2.endpoint(0), metrics=p0b.metrics)
    )
    checkpoint.restore(p0b, ckpt)
    assert p0b.lanes.stats()["store"] == pre_store
    assert p0b.lanes._seq == pre_seq
    # in-flight publishes came back as plain payload blocks — the
    # accepted transactions, not the (lost) dissemination handles
    assert [b.transactions for b in p0b.blocks_to_propose] == pre_queue
    assert all(isinstance(b, Block) for b in p0b.blocks_to_propose)
    # every certified batch held pre-crash still resolves post-restore
    for d_hex, _ in p0.lanes.checkpoint_state()["batches"]:
        digest = bytes.fromhex(d_hex)
        with p0b.lanes._lock:
            assert digest in p0b.lanes._store


def test_pre_lanes_checkpoint_restores_with_lanes_empty(tmp_path):
    """A manifest written by a lanes-off build has no "lanes" key; a
    lanes-on restart must restore it cleanly with an empty store."""
    cfg = Config(n=4, lanes=False)
    sim = Simulation(cfg)
    sim.submit_blocks(2)
    sim.run(max_messages=200)
    ckpt = str(tmp_path / "old")
    checkpoint.save(sim.processes[0], ckpt)

    from dag_rider_tpu.transport.memory import InMemoryTransport

    cfg2 = Config(n=4, lanes=True, lane_batch_bytes=256)
    p = Process(cfg2, 0, InMemoryTransport())
    bus = LaneBus(4, workers=2)
    p.attach_lanes(
        LaneCoordinator(cfg2, 0, bus.endpoint(0), metrics=p.metrics)
    )
    checkpoint.restore(p, ckpt)
    assert p.lanes.stats()["store"] == 0
    assert p.round == sim.processes[0].round


def test_lanes_off_checkpoint_unchanged(tmp_path):
    """A lanes-off process writes no "lanes" manifest key at all."""
    import json
    import os

    cfg = Config(n=4, lanes=False)
    sim = Simulation(cfg)
    sim.submit_blocks(1)
    sim.run(max_messages=100)
    ckpt = str(tmp_path / "off")
    checkpoint.save(sim.processes[0], ckpt)
    with open(os.path.join(ckpt, "manifest.json")) as fh:
        assert "lanes" not in json.load(fh)


# -- mempool byte accounting ------------------------------------------------


def test_mempool_tracks_delivered_bytes():
    from dag_rider_tpu.mempool import Mempool

    mp = Mempool(clock=lambda: 0.0)
    txs = [b"x" * 100, b"y" * 50]
    mp.submit(txs)
    mp.observe_delivered(Block(tuple(txs)), now=1.0)
    s = mp.stats()
    assert s["delivered_txs"] == 2
    assert s["delivered_bytes"] == 150
    # peers' unknown payloads never count
    mp.observe_delivered(Block((b"z" * 999,)), now=2.0)
    assert mp.stats()["delivered_bytes"] == 150
