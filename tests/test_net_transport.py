"""gRPC transport + Verifier sidecar integration tests (localhost)."""

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.net import GrpcTransport
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.sidecar import RemoteVerifier, VerifierSidecarServer


@pytest.fixture
def grpc_cluster():
    """4 GrpcTransports wired over localhost with real port discovery."""
    n = 4
    transports = []
    for i in range(n):
        transports.append(GrpcTransport(i, "127.0.0.1:0", {}))
    addrs = {i: f"127.0.0.1:{t.bound_port}" for i, t in enumerate(transports)}
    for t in transports:
        t._peers.update(addrs)
    yield transports
    for t in transports:
        t.close()


def _pump_all(transports, rounds=200):
    for _ in range(rounds):
        moved = False
        for t in transports:
            moved |= t.pump(16) > 0
        if not moved:
            break


def test_grpc_broadcast_reaches_all_peers(grpc_cluster):
    transports = grpc_cluster
    got = {i: [] for i in range(4)}
    for i, t in enumerate(transports):
        t.subscribe(i, got[i].append)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    transports[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    import time

    deadline = time.time() + 5
    while time.time() < deadline and any(
        not got[i] for i in range(1, 4)
    ):
        _pump_all(transports, rounds=1)
        time.sleep(0.01)
    assert not got[0], "sender must not receive its own broadcast"
    for i in range(1, 4):
        assert got[i] and got[i][0].vertex == v, f"peer {i} missed delivery"


def test_grpc_cluster_reaches_consensus(grpc_cluster):
    """Full 4-process consensus over real gRPC sockets."""
    import time

    transports = grpc_cluster
    cfg = Config(n=4)
    delivered = [[] for _ in range(4)]
    procs = [
        Process(cfg, i, transports[i], on_deliver=delivered[i].append)
        for i in range(4)
    ]
    for p in procs:
        for k in range(2):
            p.submit(Block((f"p{p.index}-b{k}".encode(),)))
    for p in procs:
        p.start()
    deadline = time.time() + 20
    while time.time() < deadline and not all(
        len(d) >= 4 for d in delivered
    ):
        _pump_all(transports, rounds=2)
        time.sleep(0.005)
    assert all(len(d) >= 4 for d in delivered), [len(d) for d in delivered]
    # agreement on the common prefix
    logs = [[v.id for v in d] for d in delivered]
    k = min(len(l) for l in logs)
    assert all(l[:k] == logs[0][:k] for l in logs)


def test_sidecar_roundtrip_matches_local():
    reg, seeds = KeyRegistry.generate(4)
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(4):
        v = Vertex(
            id=VertexID(2, i),
            block=Block((f"tx{i}".encode(),)),
            strong_edges=(VertexID(1, 0), VertexID(1, 1), VertexID(1, 2)),
        )
        vs.append(signers[i].sign_vertex(v))
    vs.append(vs[0])  # duplicate fine
    import dataclasses

    vs.append(dataclasses.replace(vs[1], signature=b"\x00" * 64))

    local = CPUVerifier(reg)
    server = VerifierSidecarServer(local)
    try:
        remote = RemoteVerifier(server.address)
        assert remote.verify_batch(vs) == local.verify_batch(vs)
        assert remote.verify_batch([]) == []
        remote.close()
    finally:
        server.stop()


def test_remote_verifier_fails_closed():
    remote = RemoteVerifier("127.0.0.1:1", timeout=0.5)  # nothing listening
    v = Vertex(id=VertexID(1, 0))
    assert remote.verify_batch([v, v]) == [False, False]
    remote.close()
