"""gRPC transport + Verifier sidecar integration tests (localhost)."""

import pytest

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.net import GrpcTransport
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.sidecar import RemoteVerifier, VerifierSidecarServer


@pytest.fixture
def grpc_cluster():
    """4 GrpcTransports wired over localhost with real port discovery."""
    n = 4
    transports = []
    for i in range(n):
        transports.append(GrpcTransport(i, "127.0.0.1:0", {}))
    addrs = {i: f"127.0.0.1:{t.bound_port}" for i, t in enumerate(transports)}
    for t in transports:
        t._peers.update(addrs)
    yield transports
    for t in transports:
        t.close()


def _pump_all(transports, rounds=200):
    for _ in range(rounds):
        moved = False
        for t in transports:
            moved |= t.pump(16) > 0
        if not moved:
            break


def test_grpc_broadcast_reaches_all_peers(grpc_cluster):
    transports = grpc_cluster
    got = {i: [] for i in range(4)}
    for i, t in enumerate(transports):
        t.subscribe(i, got[i].append)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    transports[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    import time

    deadline = time.time() + 5
    while time.time() < deadline and any(
        not got[i] for i in range(1, 4)
    ):
        _pump_all(transports, rounds=1)
        time.sleep(0.01)
    assert not got[0], "sender must not receive its own broadcast"
    for i in range(1, 4):
        assert got[i] and got[i][0].vertex == v, f"peer {i} missed delivery"


def test_grpc_cluster_reaches_consensus(grpc_cluster):
    """Full 4-process consensus over real gRPC sockets."""
    import time

    transports = grpc_cluster
    cfg = Config(n=4)
    delivered = [[] for _ in range(4)]
    procs = [
        Process(cfg, i, transports[i], on_deliver=delivered[i].append)
        for i in range(4)
    ]
    for p in procs:
        for k in range(2):
            p.submit(Block((f"p{p.index}-b{k}".encode(),)))
    for p in procs:
        p.start()
    deadline = time.time() + 20
    while time.time() < deadline and not all(
        len(d) >= 4 for d in delivered
    ):
        _pump_all(transports, rounds=2)
        time.sleep(0.005)
    assert all(len(d) >= 4 for d in delivered), [len(d) for d in delivered]
    # agreement on the common prefix
    logs = [[v.id for v in d] for d in delivered]
    k = min(len(l) for l in logs)
    assert all(l[:k] == logs[0][:k] for l in logs)


def test_sidecar_roundtrip_matches_local():
    reg, seeds = KeyRegistry.generate(4)
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(4):
        v = Vertex(
            id=VertexID(2, i),
            block=Block((f"tx{i}".encode(),)),
            strong_edges=(VertexID(1, 0), VertexID(1, 1), VertexID(1, 2)),
        )
        vs.append(signers[i].sign_vertex(v))
    vs.append(vs[0])  # duplicate fine
    import dataclasses

    vs.append(dataclasses.replace(vs[1], signature=b"\x00" * 64))

    local = CPUVerifier(reg)
    server = VerifierSidecarServer(local)
    try:
        remote = RemoteVerifier(server.address)
        assert remote.verify_batch(vs) == local.verify_batch(vs)
        assert remote.verify_batch([]) == []
        remote.close()
    finally:
        server.stop()


def test_remote_verifier_fails_closed():
    remote = RemoteVerifier("127.0.0.1:1", timeout=0.5)  # nothing listening
    v = Vertex(id=VertexID(1, 0))
    assert remote.verify_batch([v, v]) == [False, False]
    remote.close()


# ----------------------------------------------------------------------
# Observability + retry (round-2 VERDICT weak #8)
# ----------------------------------------------------------------------


def test_grpc_send_counters_on_success(grpc_cluster):
    import time

    transports = grpc_cluster
    got = []
    transports[1].subscribe(1, got.append)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    transports[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    deadline = time.time() + 5
    while time.time() < deadline and (
        transports[0].metrics.counters.get("net_sends_ok", 0) < 3
    ):
        time.sleep(0.01)
    c = transports[0].metrics.counters
    assert c["net_sends"] == 3
    assert c["net_sends_ok"] == 3
    assert c.get("net_drops", 0) == 0


def test_grpc_retry_then_drop_on_dead_peer():
    import time

    # peer 1 points at a port with nothing listening
    t0 = GrpcTransport(
        0,
        "127.0.0.1:0",
        {1: "127.0.0.1:1"},
        retries=2,
        retry_backoff_s=0.01,
        rpc_timeout_s=0.3,
    )
    try:
        v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
        t0.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
        deadline = time.time() + 10
        while time.time() < deadline and t0.metrics.counters.get("net_drops", 0) < 1:
            time.sleep(0.02)
        c = t0.metrics.counters
        assert c["net_send_errors"] == 3  # initial + 2 retries
        assert c["net_retries"] == 2
        assert c["net_drops"] == 1
    finally:
        t0.close()


def test_grpc_attach_metrics_merges_counters(grpc_cluster):
    import time

    from dag_rider_tpu.utils.metrics import Metrics

    transports = grpc_cluster
    transports[2].subscribe(2, lambda m: None)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    transports[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    deadline = time.time() + 5
    while time.time() < deadline and (
        transports[0].metrics.counters.get("net_sends_ok", 0) < 3
    ):
        time.sleep(0.01)
    shared = Metrics()
    shared.inc("vertices_admitted", 7)  # pre-existing consensus counter
    transports[0].attach_metrics(shared)
    snap = shared.snapshot()
    assert snap["net_sends"] == 3 and snap["vertices_admitted"] == 7
    # post-attach traffic lands in the shared Metrics
    transports[0].broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    deadline = time.time() + 5
    while time.time() < deadline and shared.counters.get("net_sends", 0) < 6:
        time.sleep(0.01)
    assert shared.counters["net_sends"] == 6


def test_grpc_16_node_cluster_with_rbc_reaches_consensus():
    """BASELINE rung #2 shape at n=16, over real gRPC sockets, with the
    Bracha RBC stage in the path (round-2 VERDICT next #10)."""
    import time

    from dag_rider_tpu.transport.rbc import RbcTransport

    n = 16
    cfg = Config(n=n, coin="round_robin", propose_empty=False)
    nets = [GrpcTransport(i, "127.0.0.1:0", {}) for i in range(n)]
    addrs = {i: f"127.0.0.1:{t.bound_port}" for i, t in enumerate(nets)}
    for t in nets:
        t._peers.update(addrs)
    try:
        rbcs = [RbcTransport(nets[i], i, n, cfg.f) for i in range(n)]
        delivered = [[] for _ in range(n)]
        procs = [
            Process(
                cfg, i, rbcs[i], on_deliver=delivered[i].append
            )
            for i in range(n)
        ]
        for p in procs:
            p.defer_steps = True  # burst delivery, one step per pump pass
            # 10 blocks/process: wave 2's boundary is round 8, so the
            # cluster must outlive round 8 for a multi-wave leader chain
            # (wave 1 alone delivers only the leader's 1-vertex history).
            for k in range(10):
                p.submit(Block((f"p{p.index}-b{k}".encode(),)))
        for p in procs:
            p.start()
        deadline = time.time() + 120
        while time.time() < deadline and not all(
            len(d) >= n for d in delivered
        ):
            moved = False
            for t in nets:
                moved |= t.pump(64) > 0
            for p in procs:
                p.step()
            if not moved:
                time.sleep(0.002)
        assert all(len(d) >= n for d in delivered), [
            len(d) for d in delivered
        ]
        logs = [[(v.id.round, v.id.source, v.digest()) for v in d] for d in delivered]
        k = min(len(l) for l in logs)
        assert all(l[:k] == logs[0][:k] for l in logs)
        # RBC really was in the path: every process echoed and readied
        assert all(r._delivered for r in rbcs)
        # transport observability: sends counted on every node
        assert all(t.metrics.counters["net_sends"] > 0 for t in nets)
    finally:
        for t in nets:
            t.close()


def test_failure_detector_marks_peer_down_and_recovers():
    """SURVEY §5 failure detection: consecutive send failures mark a peer
    down; the first success marks it up again."""
    import time

    victim = GrpcTransport(1, "127.0.0.1:0", {})
    victim_addr = f"127.0.0.1:{victim.bound_port}"
    victim.subscribe(1, lambda m: None)
    victim.close()  # peer starts dead

    t0 = GrpcTransport(
        0,
        "127.0.0.1:0",
        {1: victim_addr},
        retries=0,
        rpc_timeout_s=0.3,
    )
    try:
        v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
        msg = BroadcastMessage(vertex=v, round=1, sender=0)
        deadline = time.time() + 15
        while (
            time.time() < deadline
            and t0.peer_status().get(1) != "down"
        ):
            t0.broadcast(msg)
            time.sleep(0.05)
        assert t0.peer_status() == {1: "down"}
        assert t0.metrics.counters["net_peer_down"] == 1

        # peer comes back on the same address
        revived = GrpcTransport(1, victim_addr, {})
        if revived.bound_port == 0:  # port was re-grabbed meanwhile
            revived.close()
            pytest.skip("ephemeral port reused by another process")
        try:
            revived.subscribe(1, lambda m: None)
            deadline = time.time() + 15
            while (
                time.time() < deadline
                and t0.peer_status().get(1) != "up"
            ):
                t0.broadcast(msg)
                time.sleep(0.05)
            assert t0.peer_status() == {1: "up"}
            assert t0.metrics.counters["net_peer_recovered"] >= 1
        finally:
            revived.close()
    finally:
        t0.close()


# ----------------------------------------------------------------------
# Authenticated control frames (round-3 VERDICT missing #5)
# ----------------------------------------------------------------------


def _auth_cluster(n, cfg):
    """n GrpcTransports with pairwise-MAC frame auth + RBC stages."""
    from dag_rider_tpu.transport.auth import FrameAuth
    from dag_rider_tpu.transport.rbc import RbcTransport

    auths = FrameAuth.derive(b"cluster-master-secret", n)
    nets = [
        GrpcTransport(i, "127.0.0.1:0", {}, auth=auths[i]) for i in range(n)
    ]
    addrs = {i: f"127.0.0.1:{t.bound_port}" for i, t in enumerate(nets)}
    for t in nets:
        t._peers.update(addrs)
    rbcs = [RbcTransport(nets[i], i, n, cfg.f) for i in range(n)]
    return nets, rbcs


def test_authenticated_cluster_reaches_consensus():
    """Positive path: MAC'd frames (incl. relayed catch-up VALs) flow."""
    import time

    n = 4
    cfg = Config(n=n, coin="round_robin", propose_empty=False)
    nets, rbcs = _auth_cluster(n, cfg)
    try:
        delivered = [[] for _ in range(n)]
        procs = [
            Process(cfg, i, rbcs[i], on_deliver=delivered[i].append)
            for i in range(n)
        ]
        for p in procs:
            p.defer_steps = True
            # 10 blocks/process: the cluster must outlive round 8 (wave
            # 2's boundary) for a multi-wave leader chain to deliver n+
            # vertices everywhere.
            for k in range(10):
                p.submit(Block((f"p{p.index}-b{k}".encode(),)))
        for p in procs:
            p.start()
        deadline = time.time() + 60
        while time.time() < deadline and not all(
            len(d) >= n for d in delivered
        ):
            moved = False
            for t in nets:
                moved |= t.pump(64) > 0
            for p in procs:
                p.step()
            if not moved:
                time.sleep(0.002)
        assert all(len(d) >= n for d in delivered)
        logs = [
            [(v.id.round, v.id.source, v.digest()) for v in d]
            for d in delivered
        ]
        k = min(len(l) for l in logs)
        assert all(l[:k] == logs[0][:k] for l in logs)
        assert all(
            t.metrics.counters.get("net_auth_rejects", 0) == 0 for t in nets
        )
    finally:
        for t in nets:
            t.close()


def test_forged_ready_quorum_over_grpc_does_not_deliver():
    """THE attack the round-3 VERDICT names: a Byzantine peer crafts
    ECHO+READY frames stamped with every honest process's identity and
    fires them at one victim over the open gRPC endpoint, trying to
    fabricate a Bracha quorum for a vertex nobody broadcast. With frame
    auth the forged votes are rejected at the wire (wrong/absent MACs or
    sender != authenticated relayer) and nothing is delivered."""
    import struct
    import time

    import grpc as _grpc

    from dag_rider_tpu.core import codec
    from dag_rider_tpu.transport.auth import FrameAuth

    n = 4
    cfg = Config(n=n, coin="round_robin", propose_empty=False)
    nets, rbcs = _auth_cluster(n, cfg)
    try:
        sunk = []
        rbcs[0].subscribe(0, sunk.append)  # victim's delivery sink

        ghost = Vertex(
            id=VertexID(1, 2),
            block=Block((b"forged",)),
            strong_edges=tuple(VertexID(0, s) for s in range(cfg.quorum)),
        )
        digest = ghost.digest()
        victim_addr = f"127.0.0.1:{nets[0].bound_port}"
        chan = _grpc.insecure_channel(victim_addr)
        call = chan.unary_unary(
            "/dagrider.Transport/Deliver",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        # Byzantine node 3 DOES know its own pair key with the victim —
        # forge votes claiming senders 1 and 2 under node 3's MAC, plus
        # tagless and garbage-tagged variants.
        atk = FrameAuth.derive(b"cluster-master-secret", n)[3]
        frames = []
        for sender in (1, 2, 3):
            for kind in ("echo", "ready"):
                body = codec.encode_message(
                    BroadcastMessage(
                        vertex=None,
                        round=1,
                        sender=sender,
                        kind=kind,
                        origin=2,
                        digest=digest,
                    )
                )
                # relayer=3 with valid MAC (sender mismatch must reject
                # for sender in {1,2}; sender==3 is a legit single vote)
                frames.append(
                    struct.pack("<I", 3) + body + atk.tag(0, body)
                )
                # relayer claimed as the forged sender, MAC forged
                frames.append(
                    struct.pack("<I", sender) + body + b"\x00" * 32
                )
                # no auth wrapper at all
                frames.append(body)
        # the forged VAL itself, relayed by 3 with a valid MAC (val relays
        # are allowed through auth; Bracha still needs a READY quorum)
        val_body = codec.encode_message(
            BroadcastMessage(vertex=ghost, round=1, sender=2, kind="val")
        )
        frames.append(struct.pack("<I", 3) + val_body + atk.tag(0, val_body))
        for f in frames:
            call(f, timeout=5)
        deadline = time.time() + 3
        while time.time() < deadline:
            nets[0].pump(64)
            time.sleep(0.01)
        # one Byzantine identity cannot make a 2f+1 READY quorum:
        assert sunk == []
        slot = (1, 2)
        readies = rbcs[0]._readies.get((slot, digest), set())
        assert 3 not in readies or len(readies) < cfg.quorum
        assert 1 not in readies and 2 not in readies
        assert nets[0].metrics.counters.get("net_auth_rejects", 0) >= 8
        chan.close()
    finally:
        for t in nets:
            t.close()


def test_update_peer_repoints_stale_channel():
    """A peer that restarts on a NEW address is unreachable through the
    cached gRPC channel until update_peer drops it (round-4 soak
    finding; stable-address deployments reconnect automatically)."""
    import time

    a = GrpcTransport(0, "127.0.0.1:0", {})
    b1 = GrpcTransport(1, "127.0.0.1:0", {})
    a._peers.update({1: f"127.0.0.1:{b1.bound_port}"})
    b1._peers.update({0: f"127.0.0.1:{a.bound_port}"})
    got = []
    b1.subscribe(1, got.append)
    v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
    a.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    deadline = time.time() + 5
    while time.time() < deadline and not got:
        b1.pump(8)
        time.sleep(0.01)
    assert got, "baseline delivery failed"
    b1.close()

    # peer 1 restarts on a different port
    b2 = GrpcTransport(1, "127.0.0.1:0", {})
    b2._peers.update({0: f"127.0.0.1:{a.bound_port}"})
    got2 = []
    b2.subscribe(1, got2.append)
    a.update_peer(1, f"127.0.0.1:{b2.bound_port}")
    a.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
    deadline = time.time() + 5
    while time.time() < deadline and not got2:
        b2.pump(8)
        time.sleep(0.01)
    assert got2, "delivery after update_peer failed"
    a.close()
    b2.close()


def test_snapshot_rpc_hardening():
    """Round-5 review: the Snapshot endpoint must (a) serve authenticated
    fresh requests from a serialized-once cache, (b) throttle per relayer
    without letting replayed captures charge the victim's slot, (c) bound
    global egress with a token bucket, (d) refuse stale timestamps with a
    distinct counter, MAC-checked first."""
    import struct as _struct
    import time as _time

    import grpc

    from dag_rider_tpu.transport.auth import FrameAuth
    from dag_rider_tpu.transport.net import _SNAP_DOMAIN

    auths = FrameAuth.derive(b"m", 8)
    calls = [0]

    def provider():
        calls[0] += 1
        return b"w" * 256

    # Donor A: long interval so every throttle assertion is deterministic
    # however slow the host is (no wall-clock races).
    donor = GrpcTransport(
        0, "127.0.0.1:0", {}, auth=auths[0], snapshot_provider=provider,
        snapshot_min_interval_s=60.0,
    )
    peers = {0: f"127.0.0.1:{donor.bound_port}"}
    fetchers = [
        GrpcTransport(i, "127.0.0.1:0", dict(peers), auth=auths[i])
        for i in (1, 2, 3)
    ]
    try:
        # burst of 3 distinct relayers: all served (bucket), 1 serialization
        for f in fetchers:
            assert f.fetch_snapshot(0) == b"w" * 256
        assert calls[0] == 1, f"cache missed: {calls[0]}"
        # 4th distinct relayer in the same burst: global bucket empty
        extra = GrpcTransport(4, "127.0.0.1:0", dict(peers), auth=auths[4])
        try:
            assert extra.fetch_snapshot(0) is None
        finally:
            extra.close()
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_global_throttled", 0) >= 1, snap
        # same relayer again inside the interval: per-relayer throttle
        assert fetchers[0].fetch_snapshot(0) is None
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_throttled", 0) >= 1, snap
    finally:
        donor.close()
        for f in fetchers:
            f.close()

    # Donor B: tiny interval so replay/stale classification is exercised
    # without sleeping through a refill.
    donor = GrpcTransport(
        0, "127.0.0.1:0", {}, auth=auths[0], snapshot_provider=provider,
        snapshot_min_interval_s=0.01,
    )
    raw = grpc.insecure_channel(f"127.0.0.1:{donor.bound_port}")
    try:
        call = raw.unary_unary(
            "/dagrider.Transport/Snapshot",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        # replayed capture: a relayer's ts is consumed on first serve ->
        # the exact replay gets a distinct refusal that does NOT charge
        # the victim's throttle slot
        ts = _struct.pack("<d", _time.time())
        req2 = (
            _struct.pack("<I", 5)
            + ts
            + auths[5].tag(0, _SNAP_DOMAIN + ts)
        )
        assert bytes(call(req2, timeout=5)) != b""  # fresh ts: served
        assert bytes(call(req2, timeout=5)) == b""  # exact replay refused
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_replays", 0) == 1, snap
        # an OLDER ts from the same relayer: classified stale (clock
        # step / reordered capture), not replay
        older = _struct.pack("<d", _time.time() - 30)
        req_older = (
            _struct.pack("<I", 5)
            + older
            + auths[5].tag(0, _SNAP_DOMAIN + older)
        )
        assert bytes(call(req_older, timeout=5)) == b""
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_stale_refusals", 0) == 1, snap
        # out-of-freshness-window but MAC-valid: stale counter too
        old = _struct.pack("<d", _time.time() - 3600)
        req_old = (
            _struct.pack("<I", 3)
            + old
            + auths[3].tag(0, _SNAP_DOMAIN + old)
        )
        assert bytes(call(req_old, timeout=5)) == b""
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_stale_refusals", 0) == 2, snap
        # garbage of the right length: reject WITHOUT touching stale counter
        junk = b"\xff" * len(req_old)
        assert bytes(call(junk, timeout=5)) == b""
        snap = donor.metrics.snapshot()
        assert snap.get("net_snapshot_stale_refusals", 0) == 2, snap
        assert snap.get("net_snapshot_rejects", 0) >= 1, snap
    finally:
        raw.close()
        donor.close()


# -- round 20: cluster-mode seams (Submit door, WAN faults, unicast) ----


def test_snapshot_cached_serve_then_stale_cache_refresh():
    """The Snapshot cache serves repeated fetches from one serialization
    inside the TTL, then refreshes — a later fetch observes new donor
    state, which is what lets a rejoiner chase a moving head."""
    import time as _time

    from dag_rider_tpu.transport.auth import FrameAuth

    state = {"blob": b"A" * 64, "calls": 0}

    def provider():
        state["calls"] += 1
        return state["blob"]

    # frame auth so each fetcher has a relayer identity: the throttle is
    # then per-relayer + token bucket, not the strict anonymous cap
    auths = FrameAuth.derive(b"m", 3)
    donor = GrpcTransport(
        0, "127.0.0.1:0", {}, auth=auths[0],
        snapshot_provider=provider,
        snapshot_min_interval_s=0.3,
    )
    peers = {0: f"127.0.0.1:{donor.bound_port}"}
    f1 = GrpcTransport(1, "127.0.0.1:0", dict(peers), auth=auths[1])
    f2 = GrpcTransport(2, "127.0.0.1:0", dict(peers), auth=auths[2])
    try:
        assert f1.fetch_snapshot(0) == b"A" * 64
        # donor state moves on; within the TTL the cache still serves
        # the old blob from ONE serialization
        state["blob"] = b"B" * 64
        assert f2.fetch_snapshot(0) == b"A" * 64
        assert state["calls"] == 1, "cache must serve the second fetch"
        _time.sleep(0.35)  # TTL expiry
        assert f1.fetch_snapshot(0) == b"B" * 64, "stale cache must refresh"
        assert state["calls"] == 2
    finally:
        donor.close()
        f1.close()
        f2.close()


def test_snapshot_rpc_serves_pruned_window_for_rejoin():
    """Snapshot-while-pruned: the donor has GC'd past genesis, so a node
    that was dead too long can only rejoin via the Snapshot RPC — fetch
    the live window over the wire and replay it into a fresh process."""
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.transport.memory import InMemoryTransport
    from dag_rider_tpu.utils import checkpoint

    gc_cfg = Config(n=4, coin="round_robin", propose_empty=True, gc_depth=16)
    sim = Simulation(gc_cfg)
    sim.submit_blocks(per_process=2)
    for _ in range(600):
        sim.run(max_messages=100)
        if max(p.round for p in sim.processes) >= 50:
            break
    donor_proc = sim.processes[0]
    assert donor_proc.dag.base_round > 0, "donor must have pruned"

    donor = GrpcTransport(
        0, "127.0.0.1:0", {},
        snapshot_provider=lambda: checkpoint.snapshot_bytes(donor_proc),
        snapshot_min_interval_s=0.01,
    )
    fetcher = GrpcTransport(
        1, "127.0.0.1:0", {0: f"127.0.0.1:{donor.bound_port}"}
    )
    try:
        blob = fetcher.fetch_snapshot(0)
        assert blob, "pruned-window snapshot must be served"
        fresh = Process(gc_cfg, 1, InMemoryTransport())
        assert checkpoint.restore_from_snapshot(fresh, blob)
        assert fresh.dag.base_round == donor_proc.dag.base_round
        assert fresh.round == donor_proc.dag.max_round
    finally:
        donor.close()
        fetcher.close()


def test_submit_door_roundtrip_and_failure_containment():
    """The client Submit front door: closed by default, serves the bound
    sink's bytes when open, contains sink exceptions as empty (=refusal)
    responses, and counts every call."""
    import grpc as _grpc

    node = GrpcTransport(0, "127.0.0.1:0", {})
    chan = _grpc.insecure_channel(f"127.0.0.1:{node.bound_port}")
    call = chan.unary_unary(
        "/dagrider.Transport/Submit",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    try:
        # door closed: gRPC-level unimplemented, not a crash
        with pytest.raises(_grpc.RpcError):
            call(b"{}", timeout=5)

        seen = []

        def sink(req: bytes) -> bytes:
            seen.append(req)
            if req == b"boom":
                raise ValueError("malformed frame")
            return b"ok:" + req

        node.set_submit_sink(sink)
        assert bytes(call(b"hello", timeout=5)) == b"ok:hello"
        assert bytes(call(b"boom", timeout=5)) == b"", (
            "sink exception must become an empty refusal"
        )
        assert seen == [b"hello", b"boom"]
        snap = node.metrics.snapshot()
        assert snap.get("net_client_submits", 0) == 2, snap
        # door closes again: refuse without invoking the old sink
        node.set_submit_sink(None)
        with pytest.raises(_grpc.RpcError):
            call(b"late", timeout=5)
        assert seen == [b"hello", b"boom"]
    finally:
        chan.close()
        node.close()


def test_enqueue_is_unicast_but_protocol_gate_opts_out():
    """GrpcTransport.enqueue sends to exactly one peer (the Byzantine
    per-destination seam), but resolve_unicast must NOT route honest
    protocol traffic through it — single-copy sync over a lossy socket
    loses whole patience windows during recovery."""
    import time as _time

    from dag_rider_tpu.transport.base import resolve_unicast

    transports = [GrpcTransport(i, "127.0.0.1:0", {}) for i in range(3)]
    addrs = {
        i: f"127.0.0.1:{t.bound_port}" for i, t in enumerate(transports)
    }
    for t in transports:
        t._peers.update(addrs)
    got = {i: [] for i in range(3)}
    for i, t in enumerate(transports):
        t.subscribe(i, got[i].append)
    try:
        # honest routing refuses the unicast seam on this transport
        assert resolve_unicast(transports[0]) is None
        assert GrpcTransport.protocol_unicast is False
        # ...but the seam itself works, one destination only
        v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
        transports[0].enqueue(1, BroadcastMessage(vertex=v, round=1, sender=0))
        deadline = _time.time() + 5
        while _time.time() < deadline and not got[1]:
            _pump_all(transports, rounds=1)
            _time.sleep(0.01)
        assert got[1] and got[1][0].vertex == v
        assert not got[2], "enqueue must not broadcast"
        # the adversary seam deliberately ignores the honest gate
        from dag_rider_tpu.consensus.adversary import _resolve_enqueue

        assert _resolve_enqueue(transports[0]) is not None
    finally:
        for t in transports:
            t.close()


def test_wan_fault_drop_is_not_charged_to_failure_detector():
    """A WAN drop is weather, not a dead peer: the send never happens,
    net_wan_drops counts it, and the failure detector's consecutive-
    failure ledger stays clean."""
    from dag_rider_tpu.transport.net import WanFault

    sink = GrpcTransport(1, "127.0.0.1:0", {})
    src = GrpcTransport(
        0,
        "127.0.0.1:0",
        {1: f"127.0.0.1:{sink.bound_port}"},
        send_fault=WanFault(seed=1, drop=1.0),
    )
    got = []
    sink.subscribe(1, got.append)
    try:
        v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
        for _ in range(5):
            src.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
        snap = src.metrics.snapshot()
        assert snap.get("net_wan_drops", 0) == 5, snap
        assert snap.get("net_sends", 0) == 0, "dropped before the socket"
        assert src._consec_fail.get(1, 0) == 0, (
            "drops must not charge the failure detector"
        )
        sink.pump(16)
        assert not got
    finally:
        src.close()
        sink.close()


def test_wan_fault_delay_still_delivers():
    """Delayed sends are late, not lost: the message arrives after the
    seeded hold and net_wan_delays records the weather."""
    import time as _time

    from dag_rider_tpu.transport.net import WanFault

    sink = GrpcTransport(1, "127.0.0.1:0", {})
    src = GrpcTransport(
        0,
        "127.0.0.1:0",
        {1: f"127.0.0.1:{sink.bound_port}"},
        send_fault=WanFault(seed=2, delay_ms=(5.0, 20.0), delay_rate=1.0),
    )
    got = []
    sink.subscribe(1, got.append)
    try:
        v = Vertex(id=VertexID(1, 0), strong_edges=(VertexID(0, 1),))
        src.broadcast(BroadcastMessage(vertex=v, round=1, sender=0))
        deadline = _time.time() + 5
        while _time.time() < deadline and not got:
            sink.pump(16)
            _time.sleep(0.01)
        assert got and got[0].vertex == v
        snap = src.metrics.snapshot()
        assert snap.get("net_wan_delays", 0) == 1, snap
    finally:
        src.close()
        sink.close()
