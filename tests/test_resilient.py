"""Degradation-ladder tests (round-9 tentpole): retry, fallback,
health-probe promotion, and sidecar kill-and-restart recovery.

The ladder's contract: fail-closed per ATTEMPT (no attempt ever admits a
vertex it could not check), reject only after the WHOLE chain is
exhausted, and promote a recovered tier automatically — so a transient
backend failure costs latency, never valid vertices, and the commit
order downstream is identical to a fault-free run.
"""

import time

import pytest

from test_pipeline import N, _signed_pool

from dag_rider_tpu.verifier.base import (
    KeyRegistry,
    Verifier,
    VerifierUnavailableError,
    VertexSigner,
)
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.faults import (
    VerifierFaultInjector,
    VerifierFaultPlan,
)
from dag_rider_tpu.verifier.pipeline import VerifierPipeline
from dag_rider_tpu.verifier.resilient import (
    ResilientVerifier,
    default_verify_fallback,
    default_verify_retry,
)
from dag_rider_tpu.verifier.sidecar import RemoteVerifier, VerifierSidecarServer
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(N)


class _FlakyTier(Verifier):
    """CPU-oracle tier with a kill switch: raises while ``broken``,
    answers its ping accordingly. The controllable stand-in for a
    sidecar/device tier."""

    def __init__(self, reg):
        self._cpu = CPUVerifier(reg)
        self.broken = False
        self.calls = 0
        self.probes = 0

    def ping(self) -> bool:
        self.probes += 1
        return not self.broken

    def verify_batch(self, vertices):
        self.calls += 1
        if self.broken:
            raise VerifierUnavailableError("tier down")
        return self._cpu.verify_batch(vertices)


# -- ladder mechanics ---------------------------------------------------


def test_ladder_retries_falls_back_and_promotes(keys):
    """A tier failure is retried, then the call falls to the floor (same
    mask — no valid vertex rejected); the downed tier is probed in the
    background and promoted the moment it answers again."""
    reg, _ = keys
    pool = _signed_pool(keys, 24, seed=11)
    want = CPUVerifier(reg).verify_batch(pool)
    flaky = _FlakyTier(reg)
    ladder = ResilientVerifier(
        [flaky, CPUVerifier(reg)],
        retries=1,
        backoff_s=0.001,
        probe_interval_s=0.02,
    )
    assert ladder.verify_batch(pool) == want
    assert ladder.last_tier == 0 and ladder.fallbacks_total == 0

    flaky.broken = True
    assert ladder.verify_batch(pool) == want, "fallback changed the mask"
    assert ladder.last_tier == 1
    assert ladder.retries_total == 1  # one re-attempt on tier 0
    assert ladder.fallbacks_total == 1
    assert ladder.tier_health() == [False, True]
    # while down, calls skip the broken tier entirely
    calls_before = flaky.calls
    assert ladder.verify_batch(pool) == want
    assert flaky.calls == calls_before

    flaky.broken = False
    deadline = time.time() + 10
    while time.time() < deadline and not ladder.tier_health()[0]:
        time.sleep(0.01)
    assert ladder.tier_health() == [True, True], "probe never promoted"
    assert flaky.probes >= 1
    assert ladder.verify_batch(pool) == want
    assert ladder.last_tier == 0

    rs = ladder.resilience_stats()
    assert rs["retries"] == 1 and rs["fallbacks"] == 1
    assert rs["exhausted"] == 0
    assert rs["tier_health"] == [1, 1]


def test_ladder_exhaustion_fails_closed_then_recovers(keys):
    """Whole-ladder failure rejects the batch (all-False, full length)
    but does NOT brick the verifier: tiers marked down are still tried
    when nothing is healthy, so the first call after the fault clears
    succeeds — no valid vertex is permanently rejected."""
    reg, _ = keys
    pool = _signed_pool(keys, 12, seed=13)
    want = CPUVerifier(reg).verify_batch(pool)
    flaky = _FlakyTier(reg)
    flaky.ping = None  # exercise the empty-verify probe path
    flaky.broken = True
    ladder = ResilientVerifier(
        [flaky], retries=0, backoff_s=0.001, probe_interval_s=0.02
    )
    assert ladder.verify_batch(pool) == [False] * len(pool)
    assert ladder.exhausted_total == 1
    assert ladder.last_tier == 1  # len(tiers) = whole ladder exhausted
    # verify_rounds fail-closes with the same shape contract
    assert ladder.verify_rounds([pool[:3], [], pool[3:5]]) == [
        [False] * 3,
        [],
        [False] * 2,
    ]
    flaky.broken = False
    # even before any probe lands, the stale down mark must not brick
    # the verifier: all-down falls back to trying every tier
    assert ladder.verify_batch(pool) == want
    assert ladder.last_tier == 0
    deadline = time.time() + 10
    while time.time() < deadline and not ladder.tier_health()[0]:
        time.sleep(0.01)
    assert ladder.tier_health() == [True]


def test_ladder_wires_pipeline_quarantine_to_next_tier(keys):
    """Constructor wiring: a pipeline tier's quarantined chunks go to
    the ladder's NEXT tier. Under an unbounded resolve-fault storm the
    CPU floor answers every quarantine, so the mask stays correct and
    the ladder itself never even sees an exception — containment one
    level below the ladder."""
    reg, _ = keys
    pool = _signed_pool(keys, 48, seed=15)
    want = CPUVerifier(reg).verify_batch(pool)
    base = TPUVerifier(reg)
    pipe = VerifierPipeline(base, depth=2, fixed_bucket=16, warmup=False)
    floor = CPUVerifier(reg)
    ladder = ResilientVerifier([pipe, floor], retries=0)
    assert pipe.quarantine_verifier is floor

    inj = VerifierFaultInjector(VerifierFaultPlan(resolve_raise=1.0, seed=15))
    inj.arm(base)
    try:
        assert ladder.verify_batch(pool) == want
    finally:
        inj.disarm()
    assert ladder.last_tier == 0 and ladder.fallbacks_total == 0
    rs = ladder.resilience_stats()
    assert rs["quarantined"] == 3 and rs["quarantine_rejected"] == 0
    assert rs["poisoned_windows"] >= 1


# -- sidecar: retry, failure taxonomy, kill-and-restart -----------------


def test_remote_retry_distinguishes_transport_from_invalid(keys):
    """Round-9 satellite: sidecar_rpc_failures counts TRANSPORT failures
    only — a batch of invalid signatures is a verdict (mask bits), not
    an rpc failure; an injected RPC fault is retried (reconnect +
    backoff) and succeeds once the fault clears."""
    reg, _ = keys
    pool = _signed_pool(keys, 12, seed=17)
    want = CPUVerifier(reg).verify_batch(pool)
    assert any(not ok for ok in want), "no corruption landed"

    server = VerifierSidecarServer(CPUVerifier(reg))
    remote = RemoteVerifier(
        server.address, retries=2, backoff_s=0.001, seed=1
    )
    try:
        # invalid signatures: False bits, ZERO rpc failures
        assert remote.verify_batch(pool) == want
        assert remote.rpc_failures == 0 and remote.retries_total == 0

        # transport faults: two injected failures, absorbed by retries
        inj = VerifierFaultInjector(
            VerifierFaultPlan(rpc_error=1.0, max_faults=2, seed=5)
        )
        inj.arm_remote(remote)
        try:
            assert remote.verify_batch(pool) == want
            assert remote.rpc_failures == 2 and remote.retries_total == 2
            assert remote.stats() == {
                "sidecar_rpc_failures": 2,
                "retries": 2,
            }
        finally:
            inj.disarm()

        # exhaustion: default contract fail-closes; the ladder flag
        # raises instead so a chain can take over
        storm = VerifierFaultInjector(
            VerifierFaultPlan(rpc_error=1.0, seed=6)
        )
        storm.arm_remote(storm_target := remote)
        try:
            assert storm_target.verify_batch(pool) == [False] * len(pool)
            assert storm_target.ping() is False
            storm_target.raise_on_unavailable = True
            with pytest.raises(VerifierUnavailableError):
                storm_target.verify_batch(pool)
        finally:
            storm.disarm()
            remote.raise_on_unavailable = False
        assert remote.ping() is True
        assert remote.verify_batch(pool) == want
    finally:
        remote.close()
        server.stop()


def test_sidecar_kill_and_restart_ladder_recovers(keys):
    """Round-9 satellite: kill the sidecar mid-stream — the ladder falls
    to its CPU floor with an identical mask; restart the sidecar on the
    SAME address — the background probe reconnects and promotes it, and
    the next call rides the sidecar again."""
    reg, _ = keys
    pool = _signed_pool(keys, 32, seed=19)
    want = CPUVerifier(reg).verify_batch(pool)

    server = VerifierSidecarServer(CPUVerifier(reg))
    port = server.bound_port
    remote = RemoteVerifier(
        server.address, timeout=2.0, retries=1, backoff_s=0.001, seed=2
    )
    ladder = ResilientVerifier(
        [remote, CPUVerifier(reg)],
        retries=0,
        backoff_s=0.001,
        probe_interval_s=0.05,
    )
    assert remote.raise_on_unavailable, "ladder must force raise semantics"
    revived = None
    try:
        assert ladder.verify_batch(pool) == want
        assert ladder.last_tier == 0

        server.stop()  # kill mid-stream
        assert ladder.verify_batch(pool) == want, "kill cost valid vertices"
        assert ladder.last_tier == 1 and ladder.fallbacks_total >= 1
        assert remote.rpc_failures >= 1
        rs = ladder.resilience_stats()
        assert rs["sidecar_health"] == 0
        assert rs["sidecar_rpc_failures"] >= 1

        revived = VerifierSidecarServer(
            CPUVerifier(reg), listen_addr=f"127.0.0.1:{port}"
        )
        if revived.bound_port == 0:
            pytest.skip("ephemeral port reused by another process")
        deadline = time.time() + 15
        while time.time() < deadline and not ladder.tier_health()[0]:
            time.sleep(0.02)
        assert ladder.tier_health()[0], "sidecar tier never promoted back"
        assert ladder.verify_batch(pool) == want
        assert ladder.last_tier == 0
        assert ladder.resilience_stats()["sidecar_health"] == 1
    finally:
        remote.close()
        if revived is not None:
            revived.stop()
        else:
            server.stop()


def test_sim_commit_order_with_sidecar_failover(keys):
    """Acceptance: a sidecar killed MID-CONSENSUS must not move the
    commit order — the ladder's floor computes the same masks, so the
    delivered log equals the fault-free CPU run's, and the resilience
    gauges land in the per-process metrics snapshot."""
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]

    def run(factory, mid_run=None):
        cfg = Config(n=N, coin="round_robin", propose_empty=True)
        sim = Simulation(
            cfg,
            verifier_factory=factory,
            signer_factory=lambda i: signers[i],
        )
        sim.submit_blocks(per_process=2)
        for cycle in range(10):
            sim.run(max_messages=N * (N - 1))
            if mid_run is not None and cycle == 3:
                mid_run()
        sim.check_agreement()
        log = [
            (v.id.round, v.id.source, v.digest())
            for v in sim.deliveries[0]
        ]
        return log, sim

    cpu_log, _ = run(lambda i: CPUVerifier(reg))
    assert len(cpu_log) > 10, "CPU reference run delivered too little"

    server = VerifierSidecarServer(CPUVerifier(reg))
    remote = RemoteVerifier(
        server.address, timeout=2.0, retries=0, backoff_s=0.001, seed=3
    )
    # long probe interval: the sidecar stays down for the rest of the
    # run, so every post-kill cycle exercises the floor
    ladder = ResilientVerifier(
        [remote, CPUVerifier(reg)],
        retries=0,
        backoff_s=0.001,
        probe_interval_s=60.0,
    )
    try:
        lad_log, sim = run(lambda i: ladder, mid_run=server.stop)
    finally:
        remote.close()
        server.stop()
    assert ladder.fallbacks_total >= 1, "the kill never hit the verify path"
    k = min(len(cpu_log), len(lad_log))
    assert k > 10 and cpu_log[:k] == lad_log[:k]
    snap = sim.processes[0].metrics.snapshot()
    assert snap.get("verify_fallback_tier") == 1
    assert snap.get("sidecar_health") == 0
    assert snap.get("sidecar_rpc_failures", 0) >= 1


# -- knobs --------------------------------------------------------------


def test_verify_knob_env_defaults_and_validation(monkeypatch):
    monkeypatch.delenv("DAGRIDER_VERIFY_RETRY", raising=False)
    assert default_verify_retry() == 1
    monkeypatch.setenv("DAGRIDER_VERIFY_RETRY", "3")
    assert default_verify_retry() == 3
    monkeypatch.setenv("DAGRIDER_VERIFY_RETRY", "-1")
    with pytest.raises(ValueError):
        default_verify_retry()

    monkeypatch.delenv("DAGRIDER_VERIFY_FALLBACK", raising=False)
    assert default_verify_fallback() == ""
    for off in ("0", "off", "none", "false"):
        monkeypatch.setenv("DAGRIDER_VERIFY_FALLBACK", off)
        assert default_verify_fallback() == ""
    monkeypatch.setenv("DAGRIDER_VERIFY_FALLBACK", "CPU")
    assert default_verify_fallback() == "cpu"
    monkeypatch.setenv("DAGRIDER_VERIFY_FALLBACK", "gpu")
    with pytest.raises(ValueError):
        default_verify_fallback()

    with pytest.raises(ValueError):
        ResilientVerifier([])
