"""Round-10 mempool subsystem.

Unit coverage for the three stages (pool / admission / batcher), the
facade's in-flight dedup + staged-blocks backpressure, the seeded load
generator, and the Histogram helper — then the e2e properties the
subsystem exists for: open-loop overload sheds-not-crashes with zero
lost accepted transactions, batched delivery order is byte-identical to
the legacy one-block path under identical payload bytes, and a process
killed mid-load resumes from its checkpoint with every accepted
transaction intact and nothing delivered twice.
"""

import pytest

from dag_rider_tpu.config import Config, MempoolConfig
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.mempool import Mempool
from dag_rider_tpu.mempool.admission import (
    ACCEPT,
    SHED,
    THROTTLE,
    AdmissionController,
)
from dag_rider_tpu.mempool.batcher import BlockBatcher
from dag_rider_tpu.mempool.loadgen import (
    ClusterLoadDriver,
    LoadGenerator,
    replay,
    smoke,
)
from dag_rider_tpu.mempool.pool import TransactionPool
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.utils import checkpoint
from dag_rider_tpu.utils.metrics import Histogram

SIM_CFG = dict(
    coin="round_robin",
    propose_empty=True,
    gc_depth=24,
    # default sync_patience: the backlog-aware gate in
    # Process._maybe_request_sync keeps the driver's chunked pumping
    # from reading as a partition. Cooldowns are wall-clock rate limits;
    # zeroing them keeps the replay/determinism tests wall-time-free.
    sync_request_cooldown_s=0.0,
    sync_serve_cooldown_s=0.0,
)


# -- config -----------------------------------------------------------------


def test_mempool_config_validates():
    for bad in (
        dict(cap=0),
        dict(batch_bytes=0),
        dict(batch_deadline_ms=-1.0),
        dict(admit_low=0.9, admit_high=0.5),
        dict(admit_high=1.5),
        dict(ttl_s=0.0),
        dict(source_rate=-1.0),
        dict(throttle_rate=0.0),
        dict(max_batch_txs=0),
        dict(max_staged_blocks=0),
    ):
        with pytest.raises(ValueError):
            MempoolConfig(**bad)


def test_mempool_config_env_and_dict(monkeypatch):
    monkeypatch.setenv("DAGRIDER_MEMPOOL_CAP", "123")
    monkeypatch.setenv("DAGRIDER_BATCH_BYTES", "456")
    monkeypatch.setenv("DAGRIDER_BATCH_DEADLINE_MS", "7.5")
    monkeypatch.setenv("DAGRIDER_ADMIT_WATERMARKS", "0.3,0.7")
    cfg = MempoolConfig.from_env()
    assert (cfg.cap, cfg.batch_bytes) == (123, 456)
    assert (cfg.batch_deadline_ms, cfg.admit_low, cfg.admit_high) == (
        7.5,
        0.3,
        0.7,
    )
    # dict overrides layer on top of the env base
    cfg2 = MempoolConfig.from_dict({"cap": 9})
    assert cfg2.cap == 9 and cfg2.batch_bytes == 456
    with pytest.raises(ValueError):
        MempoolConfig.from_dict({"nope": 1})
    monkeypatch.setenv("DAGRIDER_ADMIT_WATERMARKS", "bogus")
    with pytest.raises(ValueError):
        MempoolConfig.from_env()


# -- pool -------------------------------------------------------------------


def test_pool_dedup_fifo_and_round_robin():
    pool = TransactionPool(MempoolConfig(cap=16, batch_bytes=1024))
    assert pool.add(b"a1", "a", 0.0) == "ok"
    assert pool.add(b"a1", "a", 0.0) == "dup"
    assert pool.add(b"a2", "a", 0.0) == "ok"
    assert pool.add(b"b1", "b", 0.0) == "ok"
    # take is round-robin one-per-lane: lanes interleave, each lane FIFO
    txs = pool.take(1024, 3)
    assert sorted(txs) == [b"a1", b"a2", b"b1"]
    assert txs.index(b"a1") < txs.index(b"a2")
    assert len(pool) == 0


def test_pool_cap_and_ttl():
    pool = TransactionPool(MempoolConfig(cap=2, batch_bytes=64, ttl_s=5.0))
    assert pool.add(b"x", "c", 0.0) == "ok"
    assert pool.add(b"y", "c", 0.0) == "ok"
    assert pool.add(b"z", "c", 0.0) == "full"
    assert pool.dropped_full == 1
    assert pool.expire(4.9) == []
    expired = pool.expire(5.1)
    assert sorted(expired) == [b"x", b"y"]
    assert len(pool) == 0 and pool.expired == 2


def test_pool_oversized_tx_ships_alone():
    pool = TransactionPool(MempoolConfig(cap=8, batch_bytes=16))
    big = b"B" * 64
    pool.add(big, "c", 0.0)
    pool.add(b"small", "c", 0.0)
    assert pool.take(16, 8) == [big]  # never wedges, ships alone
    assert pool.take(16, 8) == [b"small"]


def test_pool_restore_preserves_lanes():
    cfg = MempoolConfig(cap=8, batch_bytes=64)
    pool = TransactionPool(cfg)
    pool.add(b"t1", "a", 0.0)
    pool.add(b"t2", "b", 0.0)
    entries = [(e.client, e.tx) for e in pool.pending()]
    fresh = TransactionPool(cfg)
    assert fresh.restore(entries, 1.0) == 2
    assert [(e.client, e.tx) for e in fresh.pending()] == entries


# -- admission --------------------------------------------------------------


def test_admission_watermark_ladder():
    cfg = MempoolConfig(admit_low=0.5, admit_high=0.9, throttle_rate=2.0)
    adm = AdmissionController(cfg)
    assert adm.decide("c", 0.1, 0.0) and adm.state == ACCEPT
    # throttle band: token bucket at throttle_rate caps the source
    assert adm.decide("c", 0.7, 1.0) and adm.state == THROTTLE
    burst = sum(adm.decide("c", 0.7, 1.0) for _ in range(100))
    assert burst < 100  # the bucket ran dry
    assert not adm.decide("c", 0.95, 2.0) and adm.state == SHED
    assert adm.shed_watermark >= 1


def test_admission_per_source_rate_cap():
    cfg = MempoolConfig(source_rate=5.0, source_burst=5.0)
    adm = AdmissionController(cfg)
    ok_a = sum(adm.decide("a", 0.0, 0.0) for _ in range(50))
    assert ok_a == 5  # burst allowance, then dry at t=0
    assert adm.shed_rate == 45
    # an independent source has its own bucket
    assert adm.decide("b", 0.0, 0.0)
    # refill: one second at 5/s buys 5 more
    assert sum(adm.decide("a", 0.0, 1.0) for _ in range(50)) == 5


# -- batcher ----------------------------------------------------------------


def _packed(cfg=None, txs=()):
    cfg = cfg or MempoolConfig(cap=64, batch_bytes=64, batch_deadline_ms=50.0)
    pool = TransactionPool(cfg)
    for i, tx in enumerate(txs):
        pool.add(tx, f"c{i % 2}", 0.0)
    return cfg, pool, BlockBatcher(cfg, pool)


def test_batcher_size_and_deadline_triggers():
    _, _, b = _packed(txs=[b"x" * 32, b"y" * 32])  # 64 bytes => size fires
    assert b.ready(0.0)
    blk = b.build(0.0)
    assert blk is not None and len(blk.transactions) == 2
    _, _, b2 = _packed(txs=[b"z" * 8])  # under batch_bytes
    assert not b2.ready(0.01)  # 10ms < 50ms deadline
    assert b2.ready(0.06)  # deadline fired: partial block ships
    assert b2.build(0.06) is not None


def test_batcher_drain_rearms_deadline_and_limit():
    cfg, pool, b = _packed(
        txs=[bytes([i]) * 32 for i in range(9)]
    )  # 288 bytes = 4 full blocks + 1 straggler
    out = b.drain(99.0)  # deadline long past
    # 4 size-triggered blocks AND the overdue straggler in the same
    # call: the deadline trigger re-arms against the remaining pool
    # (ISSUE 16 satellite — the old size-only re-check stranded aged
    # traffic for a full extra drain cycle)
    assert len(out) == 5 and len(pool) == 0
    assert b.drain(99.0) == []  # nothing left
    cfg2, pool2, b2 = _packed(txs=[bytes([i]) * 32 for i in range(8)])
    assert len(b2.drain(99.0, limit=2)) == 2
    assert len(pool2) == 4  # the rest stays pooled
    assert len(b2.drain(0.0, force=True)) == 2
    assert 0.9 <= b2.mean_fill() <= 1.0


def test_batcher_drain_young_tail_stays_pooled():
    """The re-armed deadline is still a deadline: once the remaining
    pool holds only YOUNG under-size traffic, the drain stops — no run
    of near-empty blocks from a deep-but-fresh pool."""
    cfg = MempoolConfig(cap=64, batch_bytes=64, batch_deadline_ms=50.0)
    pool = TransactionPool(cfg)
    # two lanes aged past the deadline, one fresh lane
    pool.add(b"a" * 8, "old0", 0.0)
    pool.add(b"b" * 8, "old1", 0.0)
    pool.add(b"c" * 8, "fresh", 0.10)
    b = BlockBatcher(cfg, pool)
    out = b.drain(0.06)  # 60ms: lanes old0/old1 overdue, fresh is 0ms old
    # round-robin packing folds every overdue lane's traffic into the
    # first partial; the fresh lane's tx rides along in the same block
    # (it was pooled when the trigger fired) — the point is the drain
    # neither stalls overdue lanes NOR keeps building once the pool
    # holds only young traffic
    assert out and len(pool) == 0
    pool.add(b"d" * 8, "fresh2", 0.07)
    assert b.drain(0.08) == []  # 10ms old, under size: no trigger


def test_batcher_multiple_aged_lanes_one_drain():
    """Regression (ISSUE 16 satellite): several client lanes each
    independently aged past batch_deadline_ms with a per-block tx cap
    forcing multiple partial builds — ALL overdue traffic ships in one
    drain call instead of one lane per cycle."""
    cfg = MempoolConfig(
        cap=64, batch_bytes=4096, batch_deadline_ms=50.0, max_batch_txs=1
    )
    pool = TransactionPool(cfg)
    for i in range(3):
        pool.add(bytes([i]) * 8, f"lane{i}", 0.0)
    b = BlockBatcher(cfg, pool)
    out = b.drain(0.10)  # all three lanes 100ms old, all under size
    assert len(out) == 3 and len(pool) == 0


# -- histogram --------------------------------------------------------------


def test_histogram_percentiles_nearest_rank():
    h = Histogram()
    for v in range(1, 101):
        h.observe(float(v))
    assert h.percentile(50) == 50.0
    assert h.percentile(99) == 99.0
    assert h.percentile(100) == 100.0
    assert h.mean() == pytest.approx(50.5)
    with pytest.raises(ValueError):
        Histogram().percentile(50)


# -- mempool facade ---------------------------------------------------------


def test_mempool_inflight_dedup_until_delivered():
    mp = Mempool(MempoolConfig(cap=64, batch_bytes=8, batch_deadline_ms=0.0))
    assert mp.submit((b"tx-dup",), now=0.0).accepted == 1
    assert mp.submit((b"tx-dup",), now=0.0).deduped == 1  # still pooled
    blocks = mp.build_blocks(now=1.0)
    assert blocks and b"tx-dup" in blocks[0].transactions
    # batched-awaiting-delivery: STILL deduped (would deliver twice)
    assert mp.submit((b"tx-dup",), now=1.0).deduped == 1
    mp.observe_delivered(blocks[0], now=2.0)
    assert mp.delivered_txs == 1 and len(mp.latency) == 1
    # books closed: the payload may now be resubmitted
    assert mp.submit((b"tx-dup",), now=3.0).accepted == 1


def test_mempool_staged_backpressure_gate():
    cfg = MempoolConfig(
        cap=1024, batch_bytes=8, batch_deadline_ms=0.0, max_staged_blocks=4
    )
    mp = Mempool(cfg)
    mp.submit([f"t{i:03d}".encode() for i in range(64)], now=0.0)
    assert mp.build_blocks(now=0.0, staged=4) == []  # backlog full: hold
    assert len(mp.build_blocks(now=0.0, staged=3)) == 1
    assert len(mp.build_blocks(now=0.0, staged=0)) == 4
    # force (shutdown flush) ignores the bound
    assert len(mp.build_blocks(now=0.0, staged=99, force=True)) > 4
    assert mp.pool.depth_bytes == 0


def test_mempool_stats_and_checkpoint_roundtrip():
    mp = Mempool(MempoolConfig(cap=8, batch_bytes=1024))
    mp.submit((b"aaaa", b"bbbb"), client="c1", now=0.0)
    stats = mp.stats()
    assert stats["depth"] == 2 and stats["admitted"] == 2
    for key in ("shed", "batch_fill", "state", "delivered_txs"):
        assert key in stats
    state = mp.checkpoint_state()
    fresh = Mempool(mp.cfg)
    assert fresh.restore_state(state, now=5.0) == 2
    assert {e.tx for e in fresh.pool.pending()} == {b"aaaa", b"bbbb"}
    # restored entries re-enter the in-flight dedup horizon
    assert fresh.submit((b"aaaa",), now=5.0).deduped == 1


# -- load generator ---------------------------------------------------------


def test_loadgen_is_seed_deterministic():
    def first_events(seed):
        gen = LoadGenerator(clients=4, rate=500.0, seed=seed)
        return gen.events_until(1.0)

    assert first_events(3) == first_events(3)
    assert first_events(3) != first_events(4)


def test_loadgen_burst_profile_spikes():
    gen = LoadGenerator(
        clients=4,
        rate=1000.0,
        seed=1,
        profile="burst",
        burst_factor=8.0,
        burst_every_s=1.0,
        burst_len_s=0.25,
    )
    in_burst = len(gen.events_until(0.25))  # burst window [0, 0.25)
    off_burst = len(gen.events_until(1.0)) # off window [0.25, 1.0)
    # 8x rate over the window: the spike must dominate per-second rate
    assert in_burst / 0.25 > 2 * (off_burst / 0.75)


def test_loadgen_rejects_bad_profile():
    with pytest.raises(ValueError):
        LoadGenerator(profile="nope")
    with pytest.raises(ValueError):
        LoadGenerator(rate=0.0)


# -- end to end -------------------------------------------------------------


def test_smoke_cluster_commits_under_burst():
    rep = smoke(n=4, seconds=1.0, rate=2000.0, seed=7)
    assert rep["committed_tx"] > 0
    assert rep["audit"]["lost"] == 0 and rep["audit"]["duplicates"] == 0


def test_overload_sheds_not_crashes():
    sim = Simulation(Config(n=4, **SIM_CFG))
    gen = LoadGenerator(clients=8, rate=20_000.0, seed=3, profile="burst")
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(
            cap=256, batch_bytes=256, batch_deadline_ms=20.0, max_batch_txs=64
        ),
    )
    rep = drv.run(1.0)
    sim.check_agreement()
    audit = rep["audit"]
    assert rep["shed_tx"] > 0, "overload run never shed"
    assert audit["lost"] == 0 and audit["duplicates"] == 0


def test_batched_delivery_byte_identical_to_legacy_path():
    """Acceptance: same payload bytes through the batcher vs fed directly
    to Process.submit (legacy one-block path) deliver in the SAME order,
    byte for byte."""
    sim = Simulation(Config(n=4, **SIM_CFG))
    gen = LoadGenerator(clients=8, rate=3000.0, seed=11)
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(cap=4096, batch_bytes=256, batch_deadline_ms=20.0),
    )
    drv.run(1.0)
    sim.check_agreement()
    batched = drv.delivered_txs(0)
    assert batched, "nothing committed in the batched run"

    sim2 = Simulation(Config(n=4, **SIM_CFG))
    replay(sim2, drv.submission_log)
    sim2.check_agreement()
    accepted = drv.accepted
    legacy = [
        tx
        for v in sim2.deliveries[0]
        for tx in v.block.transactions
        if tx in accepted
    ]
    assert batched == legacy


def test_chaos_transport_zero_loss():
    from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport

    sim = Simulation(
        Config(n=4, **SIM_CFG),
        transport=FaultyTransport(
            FaultPlan(delay=0.05, duplicate=0.05, seed=2)
        ),
    )
    gen = LoadGenerator(clients=8, rate=4000.0, seed=2, profile="burst")
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(cap=512, batch_bytes=256, batch_deadline_ms=20.0),
    )
    rep = drv.run(1.0)
    sim.check_agreement()
    audit = rep["audit"]
    assert sim.transport.stats["delayed"] > 0  # faults actually fired
    assert audit["lost"] == 0 and audit["duplicates"] == 0
    assert rep["committed_tx"] > 0


def test_checkpoint_resume_under_load(tmp_path):
    """Satellite 4: kill a process mid-loadgen and restore — every
    accepted transaction survives (pending set intact, delivered prefix
    intact) and nothing already a_delivered reappears as pending."""
    cfg = Config(n=4, **SIM_CFG)
    sim = Simulation(cfg)
    gen = LoadGenerator(clients=8, rate=4000.0, seed=5, profile="burst")
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(cap=4096, batch_bytes=256, batch_deadline_ms=20.0),
    )
    drv.run(0.5, drain=False)  # mid-flight, pools still loaded
    mp0, p0 = drv.mempools[0], sim.processes[0]
    pending_before = {e.tx for e in mp0.pool.pending()}
    assert pending_before, "kill point must catch a non-empty pool"
    path = str(tmp_path / "ckpt")
    checkpoint.save(p0, path, mempool=mp0)

    # "crash": a brand-new process + mempool rebuilt purely from disk
    p2 = Process(cfg, 0, InMemoryTransport())
    mp2 = Mempool(mp0.cfg)
    checkpoint.restore(p2, path, mempool=mp2)
    assert {e.tx for e in mp2.pool.pending()} == pending_before
    assert p2.delivered_log == p0.delivered_log
    assert list(p2.blocks_to_propose) == list(p0.blocks_to_propose)
    # nothing delivered twice: a_delivered payloads are NOT pending again
    delivered = {
        tx for v in sim.deliveries[0] for tx in v.block.transactions
    }
    assert not (pending_before & delivered)
    # ... and the restored books still dedup a resubmission of them
    staged = {tx for b in p2.blocks_to_propose for tx in b.transactions}
    for tx in list(pending_before)[:3]:
        assert mp2.submit((tx,), now=99.0).deduped == 1
    # zero-loss across the kill for everything mempool 0 accepted: the
    # loadgen payload head encodes its client ("s5c<k>-...") and client
    # k feeds mempool k % n, so k in {0, 4} is exactly p0's intake.
    # Every such tx must be delivered, pending again, staged for
    # proposal, or riding a restored DAG vertex — nowhere is "gone".
    in_dag = {
        tx for v in p2.dag.vertices.values() for tx in v.block.transactions
    }
    p0_intake = {
        tx for tx in drv.accepted if tx.split(b"-")[0] in (b"s5c0", b"s5c4")
    }
    assert p0_intake  # the scope is non-trivial
    assert not (p0_intake - (delivered | pending_before | staged | in_dag))


def test_checkpoint_without_mempool_restores_empty(tmp_path):
    """Pre-round-10 checkpoints (no mempool.json) restore cleanly."""
    cfg = Config(n=4)
    sim = Simulation(cfg)
    sim.submit_blocks(2)
    sim.run(max_messages=200)
    path = str(tmp_path / "ckpt")
    checkpoint.save(sim.processes[0], path)  # no mempool kwarg
    p2 = Process(cfg, 0, InMemoryTransport())
    mp2 = Mempool(MempoolConfig(cap=64, batch_bytes=64))
    checkpoint.restore(p2, path, mempool=mp2)
    assert len(mp2.pool) == 0
    assert p2.delivered_log == sim.processes[0].delivered_log


# -- node wiring ------------------------------------------------------------


def test_node_mempool_front_door_and_auto_propose(tmp_path):
    from dag_rider_tpu import node as node_mod
    from dag_rider_tpu.mempool import SubmitResult

    keys_path = tmp_path / "keys.json"
    node_mod.main(
        ["keygen", "--n", "4", "--threshold", "2", "--out", str(keys_path)]
    )
    base = {
        "n": 4,
        "listen": "127.0.0.1:0",
        "peers": {},
        "keys": str(keys_path),
        "rbc": False,
        "verifier": "none",
        "coin": "round_robin",
    }
    nd = node_mod.Node(
        {**base, "index": 0, "mempool": {"cap": 99, "batch_bytes": 128}}
    )
    try:
        assert nd.mempool is not None and nd.mempool.cfg.cap == 99
        # satellite 2: auto-propose defaults OFF when a mempool fronts
        # the node — client traffic decides what blocks carry
        assert nd.auto_propose is False
        res = nd.submit(Block((b"client-tx",)))
        assert isinstance(res, SubmitResult) and res.accepted == 1
        assert nd.submit(Block((b"client-tx",))).deduped == 1
    finally:
        nd.net.close()
    # legacy node: no mempool, auto-propose stays on
    nd2 = node_mod.Node({**base, "index": 1})
    try:
        assert nd2.mempool is None and nd2.auto_propose is True
        assert nd2.submit(Block((b"legacy",))) is None
        # explicit override wins over the default
        nd3 = node_mod.Node(
            {**base, "index": 2, "mempool": True, "auto_propose": True}
        )
        try:
            assert nd3.mempool is not None and nd3.auto_propose is True
        finally:
            nd3.net.close()
    finally:
        nd2.net.close()
