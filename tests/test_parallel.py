"""Multi-chip parallel package: sharded verify and the fused round step.

Runs on the virtual 8-device CPU mesh (tests/conftest.py forces
``--xla_force_host_platform_device_count=8``). Asserts the north-star
invariant for the sharded path: the accept mask is the *same bits* whether
computed by the host CPUVerifier, the single-device TPUVerifier, or the
mesh-sharded ShardedTPUVerifier — sharding must never change results, only
placement (SURVEY.md §2b).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests import fixtures
from dag_rider_tpu.core.types import Block, Vertex, VertexID
from dag_rider_tpu.ops import dag_kernels
from dag_rider_tpu.parallel.mesh import batch_sharding, make_mesh, replicated
from dag_rider_tpu.parallel.round_step import make_round_step
from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier
from dag_rider_tpu.verifier.tpu import TPUVerifier


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


@pytest.fixture(scope="module")
def keys():
    return KeyRegistry.generate(8)


@pytest.fixture(scope="module")
def batch(keys):
    reg, seeds = keys
    signers = [VertexSigner(s) for s in seeds]
    vs = []
    for i in range(8):
        v = Vertex(
            id=VertexID(2, i),
            block=Block((f"tx-{i}".encode(),)),
            strong_edges=tuple(VertexID(1, s) for s in range(6)),
        )
        vs.append(signers[i].sign_vertex(v))
    # corruptions: bad signature, swapped signature, tampered payload
    vs.append(dataclasses.replace(vs[0], signature=b"\x01" * 64))
    vs.append(dataclasses.replace(vs[1], signature=vs[2].signature))
    vs.append(dataclasses.replace(vs[3], block=Block((b"tampered",))))
    return vs


def test_mesh_shapes(mesh):
    assert mesh.devices.shape == (8,)
    assert mesh.axis_names == ("batch",)
    s = batch_sharding(mesh)
    assert s.spec == jax.sharding.PartitionSpec("batch")
    assert replicated(mesh).spec == jax.sharding.PartitionSpec()


def test_sharded_mask_equals_single_device_and_cpu(keys, batch):
    reg, _ = keys
    cpu = CPUVerifier(reg).verify_batch(batch)
    tpu = TPUVerifier(reg).verify_batch(batch)
    sharded = ShardedTPUVerifier(reg).verify_batch(batch)  # comb tables
    windowed = ShardedTPUVerifier(reg, comb=False).verify_batch(batch)
    assert cpu == tpu == sharded == windowed
    assert sharded[:8] == [True] * 8
    assert sharded[8:] == [False] * 3


def test_sharded_batch_actually_sharded(keys, batch, mesh):
    """The dispatch input must lay out over the 8 mesh devices (one shard
    per device), not replicate."""
    reg, _ = keys
    v = ShardedTPUVerifier(reg, mesh)
    size = v._bucket_size(len(batch))
    assert size % 8 == 0
    args = v._prepare(batch, size)
    arr = jax.device_put(jnp.asarray(args[0]), batch_sharding(mesh))
    assert len(arr.sharding.device_set) == 8


def test_sharded_bucket_rounds_to_mesh_multiple(keys):
    """Satellite bugfix: every padded size — fixed bucket included —
    must round UP to a multiple of the mesh batch axis, so shard padding
    is byte-identical to the 1-chip program shape even on meshes that
    don't divide the power-of-two ladder."""
    reg, _ = keys
    v5 = ShardedTPUVerifier(reg, make_mesh(5))
    assert v5._round_bucket(16) == 20
    assert v5._bucket_size(11) % 5 == 0 and v5._bucket_size(11) >= 16
    assert v5._bucket_size(33) % 5 == 0 and v5._bucket_size(33) >= 64
    v8 = ShardedTPUVerifier(reg, make_mesh(8))
    # pow-2 meshes: the ladder already divides, rounding is the identity
    for n in (1, 11, 16, 33, 100):
        assert v8._bucket_size(n) == max(16, 1 << (n - 1).bit_length())
    # warmup/dispatch sizing goes through the same rounding hook
    v5.fixed_bucket = 16
    assert v5._round_bucket(int(v5.fixed_bucket)) == 20


def test_sharded_async_seam_dispatches_on_mesh(keys, batch, mesh):
    """Tentpole acceptance: dispatch_batch/resolve_batch/warmup are the
    MESH versions — the AOT entry is keyed on the mesh shape, the
    in-flight mask physically spans all 8 devices (a silent single-chip
    fallback would fail here), resolve is FIFO-safe, and the mask equals
    the CPU oracle's."""
    reg, _ = keys
    sv = ShardedTPUVerifier(reg, mesh)
    sv.fixed_bucket = 16
    sv.warmup()
    assert any(
        len(k) == 4 and k[-1] == (8,) for k in sv._aot
    ), "AOT program not keyed on mesh shape"
    assert sv.warmup() == 0.0  # idempotent at the same (size, impl, mesh)

    pending = sv.dispatch_batch(batch)
    mask_arr, count = pending
    assert count == len(batch)
    assert len(mask_arr.sharding.device_set) == 8, (
        "dispatched mask does not span the mesh — single-chip fallback"
    )
    want = CPUVerifier(reg).verify_batch(batch)
    assert sv.resolve_batch(pending) == want

    # two in flight, resolved FIFO — the pipeline's steady-state shape
    p1 = sv.dispatch_batch(batch[:6])
    p2 = sv.dispatch_batch(batch[6:])
    assert sv.resolve_batch(p1) + sv.resolve_batch(p2) == want

    # per-shard gauges: 11 real rows pad to 16 → 2 rows/shard, the last
    # three shards ride empty (imbalance (2-0)/2 = 1.0)
    assert sv.mesh_devices == 8
    assert sv.last_shard_batch == 2
    assert 0.0 <= sv.last_shard_imbalance <= 1.0


def test_sharded_sim_commit_order_matches_cpu(mesh):
    """End-to-end acceptance: Simulation's ``verifier="sharded"`` option
    commits in exactly the CPU oracle's order (same deterministic
    registry under both spellings)."""
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    def run(kind):
        cfg = Config(n=4, coin="round_robin", propose_empty=True)
        sim = Simulation(cfg, verifier=kind)
        sim.submit_blocks(per_process=2)
        for _ in range(8):
            sim.run(max_messages=12)
        sim.check_agreement()
        return [
            (v.id.round, v.id.source, v.digest()) for v in sim.deliveries[0]
        ], sim

    cpu_log, _ = run("cpu")
    sharded_log, sim = run("sharded")
    assert len(cpu_log) > 4, "reference run delivered too little"
    k = min(len(cpu_log), len(sharded_log))
    assert k > 4 and cpu_log[:k] == sharded_log[:k]
    shared = sim.processes[0].verifier
    assert shared.mesh_devices == 8
    assert all(p.verifier is shared for p in sim.processes)
    imb = [
        s
        for p in sim.processes
        for s in p.metrics.verify_shard_imbalance
    ]
    assert imb, "shard-imbalance gauge never observed"
    snap = sim.processes[0].metrics.snapshot()
    assert "verify_shard_imbalance_p50" in snap


def test_round_step_matches_host_twins_on_figure1(keys, batch, mesh):
    """The fused sharded round step must agree bit-for-bit with (a) the
    unsharded verifier mask and (b) the host-side wave-commit twin, on the
    golden Figure-1 wave."""
    reg, _ = keys
    quorum = 3
    step = make_round_step(mesh, quorum=quorum)

    tpu = TPUVerifier(reg)
    size = 16  # multiple of the mesh, >= len(batch)
    args = tuple(jnp.asarray(a) for a in tpu._prepare(batch, size))

    exists, strong, _ = fixtures.figure1_tensors()
    # wave 1: rounds (4,3,2] adjacency, top first; leader at round 1
    strong_wave = jnp.asarray(strong[4:1:-1])
    exists_r4 = jnp.asarray(exists[4])
    for leader in range(4):
        accept, commit, votes = step(
            *args, strong_wave, exists_r4, jnp.int32(leader)
        )
        # (a) verify mask identical to the unsharded dispatch
        expected_mask = tpu.verify_batch(batch)
        assert [bool(m) for m in np.asarray(accept)[: len(batch)]] == expected_mask
        # (b) wave-commit identical to the host numpy twin
        reach = np.eye(4, dtype=bool)
        for k in range(3):
            reach = (
                reach.astype(np.int32) @ np.asarray(strong_wave[k]).astype(np.int32)
            ) > 0
        host_votes = reach[:, leader] & np.asarray(exists_r4)
        assert (np.asarray(votes) == host_votes).all()
        assert bool(commit) == (int(host_votes.sum()) >= quorum)


def test_round_step_kernel_matches_unfused_kernels(mesh):
    """wave_commit_votes inside the fused step == the standalone kernel."""
    exists, strong, _ = fixtures.figure1_tensors()
    strong_wave = jnp.asarray(strong[4:1:-1])
    exists_r4 = jnp.asarray(exists[4])
    commit, votes = dag_kernels.wave_commit_votes(
        strong_wave, exists_r4, jnp.int32(0), quorum=3
    )
    # Figure 1: only (4,0) exists with edges; reference fixture gives round-4
    # vertex p0 a path to round-1 p0 via rounds 3,2.
    reach = np.eye(4, dtype=bool)
    for k in range(3):
        reach = (
            reach.astype(np.int32) @ np.asarray(strong_wave[k]).astype(np.int32)
        ) > 0
    host_votes = reach[:, 0] & np.asarray(exists_r4)
    assert (np.asarray(votes) == host_votes).all()
    assert bool(commit) == (int(host_votes.sum()) >= 3)


# ----------------------------------------------------------------------
# Mesh-sharded MSM (BASELINE rung #5; round-2 VERDICT next #9)
# ----------------------------------------------------------------------


def test_sharded_msm_matches_host_oracle(mesh):
    import random

    from dag_rider_tpu.crypto import bls12381 as bls
    from dag_rider_tpu.parallel.msm import ShardedMSM

    rng = random.Random(9)
    t = 32  # 4 points/device on the 8-device mesh; T=1024 is the bench's
    pts = [bls.g1_mul(rng.randrange(1, bls.R)) for _ in range(t)]
    ks = [rng.randrange(0, bls.R) for _ in range(t)]
    ks[5] = 0
    pts[7] = None  # identity slots must drop out
    want = bls.g1_msm(ks, pts)
    sm = ShardedMSM(mesh)
    assert sm(ks, pts) == want
    # plugs into the aggregate seam
    from dag_rider_tpu.crypto import threshold as th

    keys = th.ThresholdKeys.generate(8, 3)
    shares = {i: th.sign_share(keys.share_sks[i], 2) for i in range(4)}
    sigma_dev = th.aggregate(shares, 3, msm=sm)
    sigma_host = th.aggregate(shares, 3)
    assert sigma_dev == sigma_host
    assert th.verify_group(keys.group_pk, 2, sigma_dev)


def test_sharded_verifier_large_batch_matches_cpu_oracle(mesh):
    """Scale check at a 128-vertex batch (16/device on the 8-device mesh):
    the sharded device masks must equal the CPU oracle's bit for bit,
    including corrupted rows — large-bucket padding/slicing bugs must
    surface here, not in the one-shot TPU bench."""
    import dataclasses

    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.cpu import CPUVerifier

    n = 128
    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    quorum = 2 * ((n - 1) // 3) + 1
    vs = []
    for i in range(n):
        v = Vertex(
            id=VertexID(1, i),
            block=Block((f"tx-{i}".encode(),)),
            strong_edges=tuple(VertexID(0, s) for s in range(quorum)),
        )
        vs.append(signers[i].sign_vertex(v))
    # corruptions sprinkled across shard boundaries
    vs[0] = dataclasses.replace(vs[0], signature=bytes(64))
    vs[17] = dataclasses.replace(vs[17], signature=vs[18].signature)
    vs[127] = dataclasses.replace(vs[127], block=Block((b"tampered",)))
    want = CPUVerifier(reg).verify_batch(vs)
    got = ShardedTPUVerifier(reg, mesh).verify_batch(vs)
    assert got == want
    assert want.count(False) == 3 and not want[0] and not want[17] and not want[127]


def test_sharded_comb_pallas_path_traces(keys, batch):
    """Round-3 VERDICT weak #4: the sharded comb now runs the Pallas
    kernels per shard via shard_map (Mosaic cannot lower under GSPMD).
    Mosaic only *executes* on a real TPU and interpret mode under
    shard_map costs minutes per launch, so on the CPU mesh this asserts
    the pallas-impl shard_map program TRACES to the right output
    abstractly (jax.eval_shape — catches spec/shape/tracing breakage),
    while the bit-identical jnp impl goes through the SAME shard_map
    wrapper under the full oracle tests above. On-chip, _comb_impl
    selects "pallas" per shard automatically (>= 128 lane shards)."""
    import jax

    reg, _ = keys
    sv = ShardedTPUVerifier(reg)
    size = sv._bucket_size(len(batch))
    u8, i32 = sv._prepare(batch, size, comb=True)
    tables, b_tab = sv._comb_tables()
    out = jax.eval_shape(
        sv._sharded_comb_kernel("pallas"),
        jax.ShapeDtypeStruct(u8.shape, u8.dtype),
        jax.ShapeDtypeStruct(i32.shape, i32.dtype),
        jax.ShapeDtypeStruct(tables.shape, tables.dtype),
        jax.ShapeDtypeStruct(b_tab.shape, b_tab.dtype),
    )
    assert out.shape == (size,) and out.dtype == jnp.bool_
    # and the auto-selection rule behind it
    from dag_rider_tpu.verifier.tpu import _comb_impl

    assert _comb_impl(64) == "jnp"  # sub-lane shards stay portable
