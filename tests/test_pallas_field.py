"""Pallas field-mul kernel vs the jnp oracle (interpret mode on CPU).

SURVEY §2a/§7's "limb decomposition in Pallas" item: the kernel must be
bit-identical to ops.field.mul — same reduced-limb representation out,
same canonical value — before any on-chip timing matters.
"""

import numpy as np

from dag_rider_tpu.ops import field as F
from dag_rider_tpu.ops import pallas_field


def _rand_reduced(rng, n):
    """Random reduced-invariant operands incl. negative limbs."""
    limbs = rng.integers(-(2**13) + 1, 2**13, size=(n, F.LIMBS)).astype(
        np.int32
    )
    limbs[:, 0] = rng.integers(-(2**14) + 1, 2**14, size=n)
    return limbs


def test_pallas_mul_matches_field_mul_bitwise():
    rng = np.random.default_rng(0)
    a = _rand_reduced(rng, 640)
    b = _rand_reduced(rng, 640)
    want = np.asarray(F.mul(a, b))
    got = np.asarray(pallas_field.mul(a, b, interpret=True))
    assert (want == got).all()
    # canonical values agree too (not just the representation)
    for i in range(0, 640, 97):
        assert F.from_limbs(np.asarray(F.canonical(got[i]))) == (
            F.from_limbs(a[i]) * F.from_limbs(b[i])
        ) % F.P_INT


def test_pallas_mul_edge_values():
    cases = [0, 1, 2, 19, F.P_INT - 1, F.P_INT - 19, 2**255 - 20, 2**252]
    a = np.stack([F.to_limbs(x % F.P_INT) for x in cases])
    b = np.stack([F.to_limbs((3 * x + 7) % F.P_INT) for x in cases])
    want = np.asarray(F.mul(a, b))
    got = np.asarray(pallas_field.mul(a, b, interpret=True))
    assert (want == got).all()


def test_pallas_mul_nonaligned_batch_and_nd_shapes():
    rng = np.random.default_rng(1)
    a = _rand_reduced(rng, 6 * 5).reshape(6, 5, F.LIMBS)
    b = _rand_reduced(rng, 6 * 5).reshape(6, 5, F.LIMBS)
    want = np.asarray(F.mul(a, b))
    got = np.asarray(pallas_field.mul(a, b, interpret=True))
    assert (want == got).all()
