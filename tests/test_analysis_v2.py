"""driderlint v2 non-vacuity + cross-validation suite (round 17).

Same contract as tests/test_analysis.py: every interprocedural checker
is proven by a PLANTED violation fed through the production
``run(files, root)`` entry, the clean-tree gate proves today's repo
passes with zero unexplained allows, and the static/dynamic lock-site
cross-validation ties the two lock views together — every site the
dynamic race harness registers must be known to the static graph (the
reverse gap is coverage intel, printed, not a failure).

The release-checker fixtures reproduce the ADVICE `bench.py:734`
defect class verbatim: the pre-round-8 sim256 rung shape (fixed_bucket
set, restore at the bottom, nothing covering the middle) is kept here
as the permanent regression fixture.
"""

import ast
import os

import pytest

from dag_rider_tpu.analysis import flow, ladder, locks, races, release, shapes
from dag_rider_tpu.analysis.core import discover, run_static

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def F(path, src):
    """One synthetic (relpath, tree, source) triple."""
    return (path, ast.parse(src), src)


def _msgs(findings):
    return [f.message for f in findings]


@pytest.fixture(scope="module")
def tree_files():
    return discover(REPO)


@pytest.fixture(scope="module")
def tree_graph(tree_files):
    return flow.build(tree_files)


# -- flow: the interprocedural core ----------------------------------------


def test_flow_resolves_method_and_module_calls():
    files = [
        F(
            "dag_rider_tpu/alpha.py",
            "def helper():\n    return 1\n"
            "class A:\n"
            "    def top(self):\n        return self.mid()\n"
            "    def mid(self):\n        return helper()\n",
        ),
        F(
            "dag_rider_tpu/beta.py",
            "from dag_rider_tpu import alpha\n"
            "def entry():\n    a = alpha.A()\n    return a.top()\n",
        ),
    ]
    g = flow.build(files)
    reach = g.reachable("dag_rider_tpu.beta.entry")
    assert "dag_rider_tpu.alpha.A.top" in reach
    assert "dag_rider_tpu.alpha.A.mid" in reach
    assert "dag_rider_tpu.alpha.helper" in reach


def test_flow_function_local_imports_resolve():
    files = [
        F("dag_rider_tpu/gamma.py", "def target():\n    return 7\n"),
        F(
            "dag_rider_tpu/delta.py",
            "def entry():\n"
            "    from dag_rider_tpu.gamma import target\n"
            "    return target()\n",
        ),
    ]
    g = flow.build(files)
    assert "dag_rider_tpu.gamma.target" in g.reachable(
        "dag_rider_tpu.delta.entry"
    )


def test_flow_covers_real_degradation_seams(tree_graph):
    p = "dag_rider_tpu.consensus.process.Process."
    assert p + "_drain_buffer_vector" in tree_graph.reachable(
        p + "_drain_buffer"
    )
    assert p + "_degrade_cert_round" in tree_graph.reachable(
        p + "_apply_certificate"
    )


# -- locks: static lock-order proofs ---------------------------------------

_CYCLE_SRC = """
import threading

_A = threading.Lock()
_B = threading.Lock()

def f():
    with _A:
        g()

def g():
    with _B:
        pass

def h():
    with _B:
        f()
"""


def test_locks_planted_cycle_detected():
    got = locks.run([F("dag_rider_tpu/evil_locks.py", _CYCLE_SRC)], REPO)
    assert any("lock-order cycle" in m for m in _msgs(got)), _msgs(got)


def test_locks_one_direction_is_clean():
    src = _CYCLE_SRC.replace("def h():\n    with _B:\n        f()", "")
    got = locks.run([F("dag_rider_tpu/ok_locks.py", src)], REPO)
    assert got == []


def test_locks_self_deadlock_detected():
    src = (
        "import threading\n"
        "_L = threading.Lock()\n"
        "def f():\n"
        "    with _L:\n"
        "        with _L:\n"
        "            pass\n"
    )
    got = locks.run([F("dag_rider_tpu/evil_self.py", src)], REPO)
    assert any("self-deadlock" in m for m in _msgs(got))
    # the same shape on an RLock is legal
    rsrc = src.replace("threading.Lock", "threading.RLock")
    assert locks.run([F("dag_rider_tpu/ok_rlock.py", rsrc)], REPO) == []


def test_locks_interprocedural_edge_through_helper():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._a:\n"
        "            self.helper()\n"
        "    def helper(self):\n"
        "        with self._b:\n"
        "            self.outer2()\n"
        "    def outer2(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    got = locks.run([F("dag_rider_tpu/evil_helper.py", src)], REPO)
    assert any("lock-order cycle" in m for m in _msgs(got)), _msgs(got)


def test_static_lock_graph_covers_tree_sites(tree_files):
    sites = locks.lock_sites(tree_files)
    # the dynamic harness's own registry modules are excluded; every
    # other package Lock()/RLock() creation must be indexed
    assert len(sites) >= 10
    assert all(":" in s for s in sites)


# -- release: exception-safe borrow/restore --------------------------------

#: the pre-round-8 bench.py sim256 shape — ADVICE bench.py:734, kept
#: verbatim as the checker's permanent regression fixture
_SIM256_LEAK_SRC = """
def sim256_rung(verifier, batches, bucket):
    prev = verifier.fixed_bucket
    verifier.fixed_bucket = bucket
    verifier.warmup()
    masks = verifier.verify_batch(batches)
    verifier.fixed_bucket = prev
    return masks
"""


def test_release_flags_unwrapped_sim256_shape():
    got = release.run(
        [F("dag_rider_tpu/evil_rel.py", _SIM256_LEAK_SRC)], REPO
    )
    assert any(
        "fixed_bucket" in m and "leak" in m for m in _msgs(got)
    ) or any("finally-restore" in m for m in _msgs(got)), _msgs(got)


def test_release_fixed_shape_is_clean():
    src = (
        "def sim256_rung(verifier, batches, bucket):\n"
        "    prev = verifier.fixed_bucket\n"
        "    try:\n"
        "        verifier.fixed_bucket = bucket\n"
        "        verifier.warmup()\n"
        "        masks = verifier.verify_batch(batches)\n"
        "    finally:\n"
        "        verifier.fixed_bucket = prev\n"
        "    return masks\n"
    )
    assert release.run([F("dag_rider_tpu/ok_rel.py", src)], REPO) == []


def test_release_registry_attr_on_shared_receiver():
    src = (
        "def rung(verifier):\n"
        "    verifier.prep_workers = 4\n"
        "    verifier.run()\n"
    )
    got = release.run([F("dag_rider_tpu/evil_rel2.py", src)], REPO)
    assert any("prep_workers" in m for m in _msgs(got))


def test_release_exempts_init_and_local_constructor():
    src = (
        "class V:\n"
        "    def __init__(self, backend):\n"
        "        backend.prep_workers = 1\n"
        "        self.fixed_bucket = 256\n"
        "def make():\n"
        "    v = V(None)\n"
        "    v.fixed_bucket = 128\n"
        "    return v\n"
    )
    assert release.run([F("dag_rider_tpu/ok_rel2.py", src)], REPO) == []


def test_release_arm_without_finally():
    src = (
        "def chaos(inj, verifier):\n"
        "    inj.arm(verifier)\n"
        "    verifier.run()\n"
        "    inj.disarm()\n"
    )
    got = release.run([F("dag_rider_tpu/evil_rel3.py", src)], REPO)
    assert any("arm" in m and "finally" in m for m in _msgs(got))


def test_release_arm_with_finally_is_clean():
    src = (
        "def chaos(inj, verifier):\n"
        "    try:\n"
        "        inj.arm(verifier)\n"
        "        verifier.run()\n"
        "    finally:\n"
        "        inj.disarm()\n"
    )
    assert release.run([F("dag_rider_tpu/ok_rel3.py", src)], REPO) == []


# -- shapes: jit recompile hazards -----------------------------------------

_SHAPES_EVIL_SRC = """
import functools
import jax
import jax.numpy as jnp

@jax.jit
def bad(x, y):
    if x > 0:
        y = y + 1
    n = x.shape[0]
    while n > 2:
        n //= 2
    v = float(x)
    z = x.item()
    for e in x:
        y = y + e
    return y

@functools.partial(jax.jit, static_argnames=("impl",))
def kern(a, impl="jnp"):
    return a

def caller(a):
    return kern(a, impl=["not", "hashable"])
"""


def test_shapes_flags_each_hazard_class():
    got = _msgs(
        shapes.run([F("dag_rider_tpu/ops/evil_shapes.py", _SHAPES_EVIL_SRC)], REPO)
    )
    assert any("Python if on a traced value" in m for m in got)
    assert any("while on a shape-derived bound" in m for m in got)
    assert any("float() on a traced value" in m for m in got)
    assert any(".item() on a traced value" in m for m in got)
    assert any("for over a traced value" in m for m in got)
    assert any("unhashable static arg" in m for m in got)


def test_shapes_clean_idioms_not_flagged():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "@jax.jit\n"
        "def good(x, mask=None):\n"
        "    if mask is not None:\n"  # trace-time identity: fine
        "        x = jnp.where(mask, x, 0)\n"
        "    n = x.shape[0]\n"
        "    if n > 4:\n"  # shape-derived if: the bucketing idiom
        "        x = x[:4]\n"
        "    return lax.fori_loop(0, 4, lambda i, a: a + x[i], 0.0)\n"
    )
    assert shapes.run([F("dag_rider_tpu/ops/ok_shapes.py", src)], REPO) == []


def test_shapes_ignores_files_outside_ops_parallel():
    got = shapes.run(
        [F("dag_rider_tpu/consensus/evil_shapes.py", _SHAPES_EVIL_SRC)],
        REPO,
    )
    assert got == []


# -- ladder: degradation totality ------------------------------------------

_LADDER_SRC = """
def entry(x):
    if x:
        return fast(x)
    return oracle(x)

def fast(x):
    return x

def oracle(x):
    return x

def unrelated():
    return 0
"""


def _ladder_files():
    return [F("dag_rider_tpu/lad.py", _LADDER_SRC)]


def test_ladder_intact_rung_is_clean():
    lad = ladder.Ladder(
        "DAGRIDER_PUMP",  # any registered knob
        "dag_rider_tpu.lad.entry",
        "dag_rider_tpu.lad.fast",
        "dag_rider_tpu.lad.oracle",
    )
    assert ladder.run(_ladder_files(), REPO, ladders=[lad]) == []


def test_ladder_flags_unregistered_knob():
    lad = ladder.Ladder(
        "DAGRIDER_NO_SUCH_KNOB",
        "dag_rider_tpu.lad.entry",
        "dag_rider_tpu.lad.fast",
        "dag_rider_tpu.lad.oracle",
    )
    got = _msgs(ladder.run(_ladder_files(), REPO, ladders=[lad]))
    assert any("not registered" in m for m in got)


def test_ladder_flags_missing_and_severed():
    missing = ladder.Ladder(
        "DAGRIDER_PUMP",
        "dag_rider_tpu.lad.entry",
        "dag_rider_tpu.lad.gone",
        "dag_rider_tpu.lad.oracle",
    )
    got = _msgs(ladder.run(_ladder_files(), REPO, ladders=[missing]))
    assert any("missing function" in m for m in got)
    severed = ladder.Ladder(
        "DAGRIDER_PUMP",
        "dag_rider_tpu.lad.entry",
        "dag_rider_tpu.lad.fast",
        "dag_rider_tpu.lad.unrelated",  # exists, not reachable
    )
    got = _msgs(ladder.run(_ladder_files(), REPO, ladders=[severed]))
    assert any("degradation edge severed" in m for m in got)


def test_ladder_shipped_table_holds_on_tree(tree_files, tree_graph):
    assert ladder.run(tree_files, REPO, graph=tree_graph) == []


# -- static/dynamic lock-site cross-validation -----------------------------


def test_dynamic_lock_sites_subset_of_static(tree_files):
    """Every site the dynamic harness hands a tracked lock for must be
    known to the static lock graph; statically-known sites the dynamic
    suites never exercised are printed as coverage intel."""
    installed_here = not races.active()
    if installed_here:
        races.install(auto_guard=False)
    try:
        # exercise a couple of lock-creating constructors so the test
        # is meaningful even outside the DAGRIDER_RACE=1 CI lane (under
        # that lane, SITES also carries every suite that ran before us)
        from dag_rider_tpu.obs.flight import FlightRecorder
        from dag_rider_tpu.transport.memory import InMemoryTransport

        FlightRecorder(out_dir=None)
        InMemoryTransport()
        dynamic = set(races.SITES)
    finally:
        races.drain_violations()
        if installed_here:
            races.uninstall()

    static = set(locks.lock_sites(tree_files))
    assert dynamic, "harness registered no lock sites at all"
    missing = dynamic - static
    assert not missing, (
        "dynamically-registered lock sites invisible to the static "
        f"graph (static extraction has a hole): {sorted(missing)}"
    )
    unexercised = static - dynamic
    print(
        f"\nlock-site coverage: {len(dynamic)} exercised dynamically, "
        f"{len(unexercised)} statically known but not exercised here: "
        f"{sorted(unexercised)}"
    )


# -- clean tree + runner ----------------------------------------------------


def test_driderlint_v2_clean_on_this_repo():
    kept, _suppressed, unused = run_static(REPO)
    assert kept == [], [str(f) for f in kept]
    assert unused == []


def test_runner_budget_flag(capsys):
    from dag_rider_tpu.analysis.__main__ import main

    assert main(["--budget-s", "120"]) == 0
    # an absurdly tight budget must fail even on a clean tree
    assert main(["--budget-s", "0.000001"]) == 1
    out = capsys.readouterr().out
    assert "BUDGET" in out
