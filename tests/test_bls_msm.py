"""Device BLS12-381 G1 MSM vs the host reference implementation.

field381 limb arithmetic, the complete-formula group law, and the MSM
kernel must agree exactly with crypto/bls12381.py's python-int arithmetic;
threshold aggregation through the device MSM must produce byte-identical
group signatures (the configs #4-5 acceleration path of BASELINE.json).
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import threshold as th
from dag_rider_tpu.ops import bls_msm, field381 as F

rng = random.Random(1234)


def rand_fe():
    return rng.randrange(F.P_INT)


def rand_point():
    return bls.g1_mul(rng.randrange(1, bls.R))


def to_dev(x):
    return jnp.asarray(F.to_limbs(x))


def canon_int(limbs):
    return F.from_limbs(np.asarray(F.canonical(limbs)))


# --- field381 ----------------------------------------------------------------


def test_limb_roundtrip_and_canonical():
    for _ in range(20):
        x = rand_fe()
        assert F.from_limbs(F.to_limbs(x)) == x
        assert canon_int(to_dev(x)) == x


def test_field_ring_ops_match_host():
    for _ in range(12):
        a, b = rand_fe(), rand_fe()
        assert canon_int(F.add(to_dev(a), to_dev(b))) == (a + b) % F.P_INT
        assert canon_int(F.sub(to_dev(a), to_dev(b))) == (a - b) % F.P_INT
        assert canon_int(F.mul(to_dev(a), to_dev(b))) == a * b % F.P_INT
        assert canon_int(F.square(to_dev(a))) == a * a % F.P_INT
        assert canon_int(F.neg(to_dev(a))) == (-a) % F.P_INT
        assert canon_int(F.mul_small(to_dev(a), 12)) == 12 * a % F.P_INT


def test_field_mul_worst_case_reduced_inputs():
    """Repeated muls keep the reduced invariant (no silent int32 overflow):
    chain 50 multiplies and compare against the host product chain."""
    a = rand_fe()
    acc_dev = to_dev(a)
    acc_host = a
    for _ in range(50):
        acc_dev = F.mul(acc_dev, acc_dev)
        acc_host = acc_host * acc_host % F.P_INT
    assert canon_int(acc_dev) == acc_host


def test_field_eq_iszero():
    a = rand_fe()
    assert bool(F.eq(to_dev(a), to_dev(a)))
    assert not bool(F.eq(to_dev(a), to_dev((a + 1) % F.P_INT)))
    assert bool(F.is_zero(F.sub(to_dev(a), to_dev(a))))


# --- group law ---------------------------------------------------------------


def dev_point(pt):
    if pt is None:
        return bls_msm.identity()
    return (to_dev(pt[0]), to_dev(pt[1]), to_dev(1))


def dev_to_affine(p):
    x, y, z = (canon_int(c) for c in p)
    if z == 0:
        return None
    zi = pow(z, F.P_INT - 2, F.P_INT)
    return (x * zi % F.P_INT, y * zi % F.P_INT)


@pytest.mark.parametrize("case", ["generic", "double", "inverse", "identity"])
def test_complete_addition_matches_host(case):
    p1 = rand_point()
    if case == "generic":
        p2 = rand_point()
    elif case == "double":
        p2 = p1
    elif case == "inverse":
        p2 = bls.g1_neg(p1)
    else:
        p2 = None
    got = dev_to_affine(bls_msm.padd(dev_point(p1), dev_point(p2)))
    want = bls.g1_add(p1, p2)
    assert got == want, case


def test_scalar_mul_matches_host():
    for k in [1, 2, 15, 16, 0xDEADBEEF, bls.R - 1, rng.randrange(bls.R)]:
        p = rand_point()
        nib = jnp.asarray(bls_msm._nibbles(k % bls.R))
        got = dev_to_affine(bls_msm.scalar_mul(nib, dev_point(p)))
        assert got == bls.g1_mul(k, p), hex(k)


def test_scalar_zero_gives_identity():
    p = rand_point()
    nib = jnp.asarray(bls_msm._nibbles(0))
    assert dev_to_affine(bls_msm.scalar_mul(nib, dev_point(p))) is None


# --- MSM ---------------------------------------------------------------------


def host_msm(scalars, points):
    acc = None
    for k, pt in zip(scalars, points):
        acc = bls.g1_add(acc, bls.g1_mul(k, pt))
    return acc


@pytest.mark.parametrize("t", [1, 3, 5, 8])
def test_msm_matches_host(t):
    scalars = [rng.randrange(bls.R) for _ in range(t)]
    points = [rand_point() for _ in range(t)]
    assert bls_msm.msm(scalars, points) == host_msm(scalars, points)


def test_msm_with_identity_and_zero_scalar():
    points = [rand_point(), None, rand_point()]
    scalars = [5, 7, 0]
    assert bls_msm.msm(scalars, points) == host_msm(scalars, points)


# --- threshold aggregation through the device MSM ---------------------------


def test_aggregate_device_msm_byte_identical():
    keys = th.ThresholdKeys.generate(4, 2)
    wave = 3
    shares = {i: th.sign_share(keys.share_sks[i], wave) for i in range(3)}
    host_sigma = th.aggregate(shares, 2)
    dev_sigma = th.aggregate(shares, 2, msm=bls_msm.msm)
    assert host_sigma == dev_sigma
    assert th.verify_group(keys.group_pk, wave, dev_sigma)


def test_threshold_coin_with_device_msm():
    from dag_rider_tpu.consensus.coin import ThresholdCoin

    keys = th.ThresholdKeys.generate(4, 2)
    coins = [
        ThresholdCoin(keys, i, 4, msm=bls_msm.msm) for i in range(4)
    ]
    wave = 1
    shares = {i: coins[i].my_share(wave) for i in range(4)}
    for i, coin in enumerate(coins):
        for src, sh in shares.items():
            coin.observe_share(wave, src, sh)
    leaders = {c.choose_leader(wave) for c in coins if c.ready(wave)}
    assert len(leaders) == 1
