"""Structured event log + its Process wiring (SURVEY §5 L5 layer)."""

import json
import logging

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import BroadcastMessage, Vertex, VertexID
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.utils.slog import EventLog, NOOP, capture, stdlib_sink


def test_noop_log_is_disabled_and_cheap():
    assert not NOOP.enabled
    NOOP.event("anything", x=1)  # must not raise, must not allocate a sink


def test_capture_records_context_and_fields():
    log, records = capture()
    child = log.child(process=3)
    child.event("admit", round=2, source=1)
    assert records[0]["event"] == "admit"
    assert records[0]["process"] == 3
    assert records[0]["round"] == 2
    assert "ts" in records[0]


def test_stdlib_sink_emits_json_lines(caplog):
    logger = logging.getLogger("test-dagrider-slog")
    log = EventLog(stdlib_sink(logger), node="n0")
    with caplog.at_level(logging.DEBUG, logger="test-dagrider-slog"):
        log.event("wave_decided", wave=4)
    rec = json.loads(caplog.records[0].getMessage())
    assert rec["event"] == "wave_decided" and rec["node"] == "n0"


def test_process_emits_lifecycle_events():
    log, records = capture()
    cfg = Config(n=4, coin="round_robin", propose_empty=False)
    sim = Simulation(cfg, log=log)
    sim.submit_blocks(per_process=10)
    sim.run(max_messages=20_000)
    names = {r["event"] for r in records}
    assert {"round_advance", "admit", "wave_decided", "delivered"} <= names
    decided = [r for r in records if r["event"] == "wave_decided"]
    assert all("leader" in r and "votes" in r and "process" in r for r in decided)


def test_process_logs_rejections():
    log, records = capture()
    cfg = Config(n=4, coin="round_robin")
    p = Process(cfg, 0, InMemoryTransport(), log=log)
    # stamp mismatch
    v = Vertex(id=VertexID(1, 1), strong_edges=tuple(VertexID(0, s) for s in range(3)))
    p.on_message(BroadcastMessage(vertex=v, round=2, sender=1))
    # bad edges
    bad = Vertex(id=VertexID(1, 2), strong_edges=(VertexID(0, 0),))
    p.on_message(BroadcastMessage(vertex=bad, round=1, sender=2))
    names = [r["event"] for r in records]
    assert "reject_stamp" in names and "reject_edges" in names
