"""Mutation tests for the reusable protocol invariants.

Every checker in consensus/invariants.py is shown NON-VACUOUS: for each
property there is a planted violation it must catch (and a near-miss it
must accept). The live end of the suite plants a real violation — split
equivocation with the RBC stage disabled genuinely breaks agreement —
and asserts the online monitor catches it at the offending delivery.
"""

import pytest

from dag_rider_tpu.consensus.invariants import (
    InvariantMonitor,
    InvariantViolation,
    check_agreement,
    check_commit_uniqueness,
    check_liveness,
    check_zero_loss,
    delivery_records,
    transaction_audit,
)
from dag_rider_tpu.core.types import Block, Vertex, VertexID


def _rec(r, s, tag):
    return (r, s, f"digest-{tag}".encode())


def _vertex(r, s, payload=b"tx"):
    return Vertex(
        id=VertexID(r, s), block=Block((payload,)), strong_edges=(), weak_edges=()
    )


# -- agreement ---------------------------------------------------------------


def test_agreement_accepts_lagging_prefix():
    log = [_rec(1, 0, "a"), _rec(1, 1, "b"), _rec(2, 0, "c")]
    check_agreement({0: log, 1: log[:1], 2: log[:2], 3: []})


def test_agreement_catches_planted_divergence():
    a = [_rec(1, 0, "a"), _rec(1, 1, "b")]
    b = [_rec(1, 0, "a"), _rec(1, 1, "MUTANT")]
    with pytest.raises(InvariantViolation, match="divergence between p0 and p2"):
        check_agreement({0: a, 1: a[:1], 2: b})


def test_agreement_lagging_view_does_not_mask_divergence():
    # p0 is too short to conflict with anyone; p1 vs p2 still diverge
    a = [_rec(1, 0, "a"), _rec(1, 1, "b")]
    b = [_rec(1, 0, "a"), _rec(1, 1, "x")]
    with pytest.raises(InvariantViolation, match="divergence"):
        check_agreement({0: a[:1], 1: a, 2: b})


# -- commit uniqueness -------------------------------------------------------


def test_commit_uniqueness_accepts_consistent_logs():
    log = [_rec(1, 0, "a"), _rec(1, 1, "b")]
    check_commit_uniqueness({0: log, 1: log[:1]})


def test_commit_uniqueness_catches_cross_view_equivocation():
    # same slot, different digests, at DIFFERENT log positions: the
    # pairwise prefix check alone would pass these two logs
    a = [_rec(1, 0, "a"), _rec(1, 1, "b")]
    b = [_rec(1, 0, "a"), _rec(2, 0, "c"), _rec(1, 1, "MUTANT")]
    check_agreement({0: a[:1], 1: b[:1]})  # sanity: prefixes agree
    with pytest.raises(InvariantViolation, match="equivocation committed"):
        check_commit_uniqueness({0: a, 1: b})


def test_commit_uniqueness_catches_double_delivery():
    log = [_rec(1, 0, "a"), _rec(1, 0, "a")]
    with pytest.raises(InvariantViolation, match="twice"):
        check_commit_uniqueness({0: log})


# -- zero loss ---------------------------------------------------------------


def test_zero_loss_accepts_delivered_and_retained():
    audit = transaction_audit(
        accepted=[b"t1", b"t2", b"t3"],
        delivered_by_view=[[b"t1", b"t2"], [b"t1"]],
        retained=[b"t3"],
    )
    assert audit["lost"] == 0 and audit["in_flight"] == 1
    check_zero_loss(audit)


def test_zero_loss_catches_planted_loss():
    audit = transaction_audit(
        accepted=[b"t1", b"t2"], delivered_by_view=[[b"t1"]], retained=[]
    )
    assert audit["lost"] == 1
    with pytest.raises(InvariantViolation, match="lost"):
        check_zero_loss(audit)


def test_zero_loss_catches_planted_duplicate():
    audit = transaction_audit(
        accepted=[b"t1"], delivered_by_view=[[b"t1", b"t1"]], retained=[]
    )
    assert audit["duplicates"] == 1
    with pytest.raises(InvariantViolation, match="duplicate"):
        check_zero_loss(audit)


# -- liveness ----------------------------------------------------------------


def test_liveness_accepts_progress():
    check_liveness({0: 5, 1: 4, 2: 5}, min_max=3, min_each=2)


def test_liveness_catches_stalled_cluster():
    with pytest.raises(InvariantViolation, match="max honest decided wave"):
        check_liveness({0: 0, 1: 0}, min_max=1)


def test_liveness_catches_stuck_straggler():
    with pytest.raises(InvariantViolation, match="p2 decided wave 0"):
        check_liveness({0: 5, 1: 5, 2: 0}, min_max=1, min_each=1)


# -- delivery_records projection --------------------------------------------


def test_delivery_records_projects_identity_and_content():
    v1, v2 = _vertex(1, 0, b"x"), _vertex(1, 0, b"y")
    r1, r2 = delivery_records([v1])[0], delivery_records([v2])[0]
    assert r1[:2] == r2[:2] == (1, 0)
    assert r1[2] != r2[2]  # same slot, different payload -> different record


# -- online monitor ----------------------------------------------------------


def test_monitor_accepts_clean_interleaving():
    mon = InvariantMonitor(3)
    v1, v2 = _vertex(1, 0), _vertex(1, 1)
    for view in range(3):
        mon.observe(view, v1)
        mon.observe(view, v2)
    assert mon.stats() == {
        "observed": 6,
        "canonical_len": 2,
        "slots_committed": 2,
    }


def test_monitor_catches_equivocation_commit():
    mon = InvariantMonitor(2)
    mon.observe(0, _vertex(1, 0, b"x"))
    with pytest.raises(InvariantViolation, match="equivocation committed"):
        mon.observe(1, _vertex(1, 0, b"MUTANT"))


def test_monitor_catches_double_delivery():
    mon = InvariantMonitor(2)
    v = _vertex(1, 0)
    mon.observe(0, v)
    with pytest.raises(InvariantViolation, match="twice"):
        mon.observe(0, v)


def test_monitor_catches_order_divergence():
    mon = InvariantMonitor(2)
    v1, v2 = _vertex(1, 0), _vertex(1, 1)
    mon.observe(0, v1)
    mon.observe(0, v2)
    mon.observe(1, v1)
    # view 1 skips v2 and delivers a round-2 vertex at position 1
    with pytest.raises(InvariantViolation, match="order divergence"):
        mon.observe(1, _vertex(2, 0))


def test_monitor_exclusion_ignores_byzantine_views():
    mon = InvariantMonitor(2, exclude=(1,))
    mon.observe(0, _vertex(1, 0, b"x"))
    mon.observe(1, _vertex(1, 0, b"MUTANT"))  # excluded: no raise
    assert mon.observed == 1


def test_monitor_wrap_composes_with_existing_callback():
    mon = InvariantMonitor(1)
    seen = []
    cb = mon.wrap(0, seen.append)
    v = _vertex(1, 0)
    cb(v)
    assert seen == [v] and mon.observed == 1


# -- live planted violation --------------------------------------------------


def test_split_equivocation_without_rbc_trips_the_monitor():
    """The end-to-end non-vacuousness proof: a split equivocator (disjoint
    payload variants to disjoint halves) with the RBC stage OFF really
    does commit an equivocation — the online monitor must abort the run
    at the offending delivery. The same scenario under rbc=True passes
    (see tests/test_adversary.py), which is exactly the gap Bracha
    closes."""
    from dag_rider_tpu.consensus.scenarios import Scenario, run_scenario

    with pytest.raises(InvariantViolation, match="equivocation committed"):
        run_scenario(
            Scenario(n=4, adversary="equivocate_split", rbc=False, seed=0)
        )


def test_simulation_check_agreement_raises_invariant_violation():
    """Simulation.check_agreement now delegates to the invariants module:
    the raise type must be InvariantViolation (an AssertionError subclass,
    so legacy pytest.raises(AssertionError) callers keep passing)."""
    assert issubclass(InvariantViolation, AssertionError)
