"""Device curve ops (ops/curve.py) vs the host RFC 8032 oracle.

Every device primitive must agree with crypto/ed25519.py exactly — this is
what makes the CPU and TPU Verifier accept masks byte-identical
(BASELINE.json north star).
"""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dag_rider_tpu.crypto import ed25519 as H
from dag_rider_tpu.ops import curve as C
from dag_rider_tpu.ops import field as F
from dag_rider_tpu.verifier.tpu import bytes_to_limbs_batch, scalar_to_nibbles

P = F.P_INT


def to_limb_point(pt):
    """Host extended point -> batched limb point (batch 1, affine Z=1)."""
    X, Y, Z, _ = pt
    zi = pow(Z, P - 2, P)
    x, y = X * zi % P, Y * zi % P
    return tuple(
        jnp.asarray(F.to_limbs(v)[None]) for v in (x, y, 1, x * y % P)
    )


def affine(limb_pt, i=0):
    X, Y, Z, _ = (
        F.from_limbs(np.asarray(F.canonical(c))[i]) for c in limb_pt
    )
    zi = pow(Z, P - 2, P)
    return (X * zi % P, Y * zi % P)


def host_affine(pt):
    X, Y, Z, _ = pt
    zi = pow(Z, P - 2, P)
    return (X * zi % P, Y * zi % P)


@pytest.fixture(scope="module")
def rng():
    return random.Random(0xC0DE)


def test_padd_pdouble_match_host(rng):
    k1, k2 = rng.randrange(1, H.L), rng.randrange(1, H.L)
    p_h, q_h = H.scalar_mult(k1, H.B), H.scalar_mult(k2, H.B)
    p_l, q_l = to_limb_point(p_h), to_limb_point(q_h)
    assert affine(jax.jit(C.padd)(p_l, q_l)) == host_affine(
        H.point_add(p_h, q_h)
    )
    assert affine(jax.jit(C.pdouble)(p_l)) == host_affine(H.point_double(p_h))


def test_identity_chains():
    """Long double/add chains on the neutral element stay the neutral
    element (the degenerate reps here exposed the col-43 carry bug)."""
    fn = jax.jit(
        lambda a: C.padd(
            C.pdouble(C.pdouble(C.pdouble(C.pdouble(a)))), C.identity((1,))
        )
    )
    acc = C.identity((1,))
    for _ in range(16):
        acc = fn(acc)
    x, y = affine(acc)
    assert (x, y) == (0, 1)


def test_scalar_mul_var(rng):
    ks = [0, 1, 2, 15, 16, 255, rng.randrange(H.L), H.L - 1, 2**252]
    b_l = tuple(
        jnp.repeat(c, len(ks), axis=0) for c in to_limb_point(H.B)
    )
    nib = jnp.asarray(np.stack([scalar_to_nibbles(k) for k in ks]))
    got = jax.jit(C.scalar_mul_var)(nib, b_l)
    for i, k in enumerate(ks):
        want = (0, 1) if k == 0 else host_affine(H.scalar_mult(k, H.B))
        assert affine(got, i) == want, f"k={k}"


def test_scalar_mul_base(rng):
    ks = [0, 1, rng.randrange(H.L), H.L - 1]
    nib = jnp.asarray(np.stack([scalar_to_nibbles(k) for k in ks]))
    got = jax.jit(C.scalar_mul_base)(nib)
    for i, k in enumerate(ks):
        want = (0, 1) if k == 0 else host_affine(H.scalar_mult(k, H.B))
        assert affine(got, i) == want, f"k={k}"


def test_decompress_matches_host(rng):
    """Valid points, invalid (non-square) encodings, and the x=0/sign=1
    arm must all match host point_decompress."""
    encs = []
    for _ in range(6):
        k = rng.randrange(1, H.L)
        encs.append(H.point_compress(H.scalar_mult(k, H.B)))
    encs.append(int.to_bytes(2, 32, "little"))  # y=2: not on curve
    encs.append(int.to_bytes(1 | (1 << 255), 32, "little"))  # x=0, sign=1
    encs.append(int.to_bytes(1, 32, "little"))  # identity (x=0, sign=0)

    raw = np.zeros((len(encs), 32), dtype=np.uint8)
    signs = np.zeros(len(encs), dtype=np.int32)
    for i, e in enumerate(encs):
        buf = bytearray(e)
        signs[i] = buf[31] >> 7
        buf[31] &= 0x7F
        raw[i] = np.frombuffer(bytes(buf), dtype=np.uint8)
    y = jnp.asarray(bytes_to_limbs_batch(raw))
    pt, valid = jax.jit(C.decompress)(y, jnp.asarray(signs))
    for i, e in enumerate(encs):
        host_pt = H.point_decompress(e)
        assert bool(np.asarray(valid)[i]) == (host_pt is not None), f"enc {i}"
        if host_pt is not None:
            assert affine(pt, i) == host_affine(host_pt), f"enc {i}"


def test_points_equal():
    p = to_limb_point(H.scalar_mult(7, H.B))
    q = to_limb_point(H.scalar_mult(7, H.B))
    r = to_limb_point(H.scalar_mult(8, H.B))
    assert bool(np.asarray(C.points_equal(p, q))[0])
    assert not bool(np.asarray(C.points_equal(p, r))[0])
    # projective scaling: 2*(X,Y,Z,T) is the same point
    two = jnp.asarray(F.to_limbs(2)[None])
    scaled = tuple(F.mul(c, two) for c in p)
    assert bool(np.asarray(C.points_equal(p, scaled))[0])
