"""Bounded ring-buffer trace recorder.

The recorder IS a slog sink: installing tracing means building one
`EventLog` whose sink (possibly tee'd with a flight recorder and a
stdlib bridge) appends here. Capacity follows the metrics module's
bounded-state rule (`SAMPLE_WINDOW`): a long run overwrites its oldest
events instead of growing — `dropped` counts what the window lost.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List

from dag_rider_tpu.config import env_int


class TraceRecorder:
    """Thread-safe last-K ring of event records (callable as a Sink)."""

    def __init__(self, capacity: int = 0):
        if capacity <= 0:
            capacity = env_int("DAGRIDER_TRACE_RING")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.total = 0  # appends ever seen; dropped = total - len(ring)

    def __call__(self, rec: Dict[str, object]) -> None:
        with self._lock:
            self._ring.append(rec)
            self.total += 1

    def events(self) -> List[Dict[str, object]]:
        """Snapshot of the retained window, oldest first."""
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self.total - len(self._ring))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.total = 0

    def write_json(self, path: str) -> str:
        """Dump the retained window as a JSON list (obs_report input)."""
        with open(path, "w") as f:
            json.dump(self.events(), f, default=repr)
        return path
