"""Causal tracing + flight recording on the slog.EventLog seam.

Knob-gated (`DAGRIDER_TRACE`): when on, the simulator / node /
scenario runners build one :class:`Tracing` bundle — an ``EventLog``
whose sink tees into a bounded :class:`TraceRecorder` ring and a
:class:`FlightRecorder` trigger watch — and hand its ``log`` to every
component exactly where a caller-provided log would go. All tracing
cost therefore collapses to the ``EventLog.event`` attribute test when
the knob is off, and commit order is unaffected either way (events
observe; they never feed consensus state).

Transaction sampling is a pure function of the payload
(``crc32(tx) / 2**32 < rate``): every process samples the *same*
transactions with no RNG and no clock, keeping the determinism rules
intact and making cross-process joins trivial.
"""

from __future__ import annotations

import time
import zlib
from typing import Callable, List, Optional, Tuple

from dag_rider_tpu.config import env_flag, env_float, env_str
from dag_rider_tpu.obs.flight import TRIGGERS, FlightRecorder
from dag_rider_tpu.obs.recorder import TraceRecorder
from dag_rider_tpu.utils import slog

__all__ = [
    "HIGH_FREQ_EVENTS",
    "TRACE_EVENTS",
    "TRIGGERS",
    "FlightRecorder",
    "TraceRecorder",
    "Tracing",
    "block_key",
    "build_tracing",
    "sample_tx",
    "trace_enabled",
    "tx_key",
]

_SCALE = float(2**32)

#: Per-message / per-round debug chatter excluded from the trace ring:
#: these fire once per delivered message (admit/delivered), per sync-
#: storm message, or n times per round (round_advance — ~2/3 of a traced
#: ring at n=16), so recording them costs a record build + two ring
#: appends on the consensus hot path — the bulk of trace-on overhead —
#: while the causal chains and flight post-mortems join on none of them
#: (wave_decided + phase spans + tx_propose already carry progression).
#: ``capture()`` logs and stdlib bridges still see everything (their
#: EventLog has no name filter).
HIGH_FREQ_EVENTS = frozenset(
    {
        "admit",
        "delivered",
        "behind_horizon",
        "attested_floor",
        "round_advance",
        # one per received lane batch — same per-message cadence as
        # ``admit`` once dissemination lanes are on (ISSUE 17)
        "lane_batch",
    }
)

#: What the tracing bundle records: the full registered schema minus
#: the per-message chatter.
TRACE_EVENTS = frozenset(slog.KNOWN_EVENTS - HIGH_FREQ_EVENTS)


def trace_enabled() -> bool:
    return env_flag("DAGRIDER_TRACE")


def tx_key(tx: bytes) -> int:
    """Deterministic join key for one transaction payload."""
    return zlib.crc32(tx)


def block_key(encoded: bytes) -> int:
    """Deterministic join key for one encoded block."""
    return zlib.crc32(encoded)


def sample_tx(tx: bytes, rate: float) -> bool:
    """Payload-hash sampling: same verdict at every process."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return zlib.crc32(tx) / _SCALE < rate


class Tracing:
    """One wired tracing bundle: the log to install + its recorders."""

    __slots__ = ("log", "recorder", "flight", "sample_rate")

    def __init__(
        self,
        log: slog.EventLog,
        recorder: TraceRecorder,
        flight: FlightRecorder,
        sample_rate: float,
    ):
        self.log = log
        self.recorder = recorder
        self.flight = flight
        self.sample_rate = sample_rate


def build_tracing(
    *,
    base_sink: Optional[slog.Sink] = None,
    clock: Callable[[], float] = time.time,
    ring: int = 0,
    flight_dir: Optional[str] = None,
    flight_events: int = 0,
    sample_rate: Optional[float] = None,
    context: Optional[dict] = None,
) -> Tracing:
    """Build the trace ring + flight recorder + EventLog tee.

    Knob defaults (`DAGRIDER_TRACE_RING`, `DAGRIDER_FLIGHT_DIR`,
    `DAGRIDER_FLIGHT_EVENTS`, `DAGRIDER_TRACE_SAMPLE`) apply wherever
    an argument is left at its zero value; ``base_sink`` preserves a
    pre-existing sink (e.g. the node's stdlib bridge) in the tee.
    """
    recorder = TraceRecorder(ring)
    flight = FlightRecorder(
        flight_dir if flight_dir is not None else env_str("DAGRIDER_FLIGHT_DIR"),
        capacity=flight_events,
        clock=clock,
    )
    rate = (
        env_float("DAGRIDER_TRACE_SAMPLE") if sample_rate is None else sample_rate
    )
    sink = slog.tee(base_sink, recorder, flight.sink)
    log = slog.EventLog(
        sink, clock=clock, names=TRACE_EVENTS, **(context or {})
    )
    return Tracing(log, recorder, flight, rate)


def sampled_keys(
    txs: Tuple[bytes, ...], rate: float
) -> List[int]:
    """Join keys of the sampled transactions in one block/batch."""
    return [tx_key(t) for t in txs if sample_tx(t, rate)]
