"""Latency attribution: decompose submit→deliver into phases.

The sampled lifecycle stamps chain through two join keys:

    tx_submit  {tx}                 client payload enters the mempool
    tx_batch   {tx, block}          payload packed into a built block
    tx_propose {block, round, source}   block rides a proposed vertex
    tx_deliver {round, source}      that vertex reaches the total order

``tx`` is the payload crc32, ``block`` the encoded-block crc32, and
``(round, source)`` uniquely names a vertex in the DAG — so a complete
chain decomposes a transaction's submit→deliver latency into three
stages that sum EXACTLY (every stamp shares one EventLog clock):

    mempool_queue  = batch.ts   - submit.ts    (admission + batcher hold)
    propose_stage  = propose.ts - batch.ts     (blocks_to_propose wait)
    wave_commit    = deliver.ts - propose.ts   (RBC + DAG + wave lag)

The wave_commit window is then *attributed* across the host phase
spans (phase_pump / phase_verify / phase_cert occupancy over the run's
wall span); the unattributed remainder is transport/wait — wave
structure itself, not host work.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

PERCENTILES = (50.0, 90.0, 99.0)


def _percentile_index(count: int, q: float) -> int:
    """Nearest-rank index into a sorted sequence of ``count`` items."""
    if count <= 0:
        raise ValueError("no samples")
    rank = max(1, int(round(q / 100.0 * count + 0.5)))
    return min(rank, count) - 1


def chains(events: Sequence[Dict[str, object]]) -> List[Dict[str, float]]:
    """Join lifecycle stamps into complete per-transaction chains."""
    submit: Dict[object, float] = {}
    batch: Dict[object, tuple] = {}  # tx -> (block, ts)
    propose: Dict[object, tuple] = {}  # block -> ((round, source), ts)
    deliver: Dict[tuple, float] = {}  # (round, source) -> ts
    for e in events:
        name, ts = e.get("event"), e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if name == "tx_submit":
            submit[e.get("tx")] = float(ts)
        elif name == "tx_batch":
            batch[e.get("tx")] = (e.get("block"), float(ts))
        elif name == "tx_propose":
            propose[e.get("block")] = (
                (e.get("round"), e.get("source")),
                float(ts),
            )
        elif name == "tx_deliver":
            deliver[(e.get("round"), e.get("source"))] = float(ts)
    out: List[Dict[str, float]] = []
    for tx, t_submit in submit.items():
        if tx not in batch:
            continue
        blk, t_batch = batch[tx]
        if blk not in propose:
            continue
        vertex, t_propose = propose[blk]
        if vertex not in deliver:
            continue
        t_deliver = deliver[vertex]
        out.append(
            {
                "total_s": t_deliver - t_submit,
                "mempool_queue_s": t_batch - t_submit,
                "propose_stage_s": t_propose - t_batch,
                "wave_commit_s": t_deliver - t_propose,
            }
        )
    return out


def phase_occupancy(events: Sequence[Dict[str, object]]) -> Dict[str, float]:
    """Total busy seconds per host phase + the run's wall span."""
    sums = {"phase_pump": 0.0, "phase_verify": 0.0, "phase_cert": 0.0}
    lo: Optional[float] = None
    hi: Optional[float] = None
    for e in events:
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        lo = float(ts) if lo is None else min(lo, float(ts))
        hi = float(ts) if hi is None else max(hi, float(ts))
        name = e.get("event")
        dur = e.get("dur_s")
        if name in sums and isinstance(dur, (int, float)):
            sums[name] += float(dur)
    return {
        "pump_s": sums["phase_pump"],
        "verify_s": sums["phase_verify"],
        "cert_s": sums["phase_cert"],
        "wall_s": (hi - lo) if lo is not None and hi is not None else 0.0,
    }


def decompose(events: Sequence[Dict[str, object]]) -> Dict[str, object]:
    """The full latency-attribution report over one event stream."""
    chain = chains(events)
    occ = phase_occupancy(events)
    report: Dict[str, object] = {
        "txs": len(chain),
        "phase_occupancy": occ,
        "percentiles": {},
    }
    if not chain:
        return report
    chain.sort(key=lambda c: c["total_s"])
    wall = occ["wall_s"]
    # host-phase share of any wall-clock interval (capped at 1: phases
    # are per-process, the wall span is global)
    shares = {
        k: min(1.0, occ[f"{k}_s"] / wall) if wall > 0 else 0.0
        for k in ("pump", "verify", "cert")
    }
    host_share = min(1.0, sum(shares.values()))
    pcts: Dict[str, object] = {}
    for q in PERCENTILES:
        c = chain[_percentile_index(len(chain), q)]
        wave = c["wave_commit_s"]
        row = dict(c)
        row["wave_host_pump_s"] = wave * shares["pump"]
        row["wave_verify_s"] = wave * shares["verify"]
        row["wave_cert_s"] = wave * shares["cert"]
        row["wave_transport_wait_s"] = wave * (1.0 - host_share)
        pcts[f"p{int(q)}"] = row
    report["percentiles"] = pcts
    return report


def format_report(report: Dict[str, object]) -> str:
    """Human table for the CLI."""
    lines: List[str] = []
    occ = report.get("phase_occupancy") or {}
    lines.append(
        "phase occupancy: pump {pump_s:.3f}s  verify {verify_s:.3f}s  "
        "cert {cert_s:.3f}s  over {wall_s:.3f}s wall".format(
            **{k: float(occ.get(k, 0.0)) for k in
               ("pump_s", "verify_s", "cert_s", "wall_s")}
        )
    )
    lines.append(f"complete submit→deliver chains: {report.get('txs', 0)}")
    pcts = report.get("percentiles") or {}
    if pcts:
        cols = (
            "total_s",
            "mempool_queue_s",
            "propose_stage_s",
            "wave_commit_s",
            "wave_host_pump_s",
            "wave_verify_s",
            "wave_cert_s",
            "wave_transport_wait_s",
        )
        header = "pct     " + "".join(f"{c[:-2]:>21}" for c in cols)
        lines.append(header)
        for name in sorted(pcts, key=lambda p: float(p[1:])):
            row = pcts[name]
            lines.append(
                f"{name:<8}"
                + "".join(f"{float(row.get(c, 0.0)):>21.4f}" for c in cols)
            )
    return "\n".join(lines)
