"""Perfetto / Chrome-trace JSON export.

Converts a list of slog event records into the Trace Event Format
(`chrome://tracing`, https://ui.perfetto.dev): events carrying a
``dur_s`` field become complete ("X") spans, everything else an
instant ("i"). pid groups by the ``index``/``node`` context a process
EventLog binds; ts is microseconds relative to the first event so the
viewer opens at t=0.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

#: record keys that map onto trace-event structure, not args
_STRUCTURAL = ("event", "ts", "dur_s", "index", "node")


def _tid(rec: Dict[str, object]) -> int:
    name = str(rec.get("event", ""))
    if name.startswith("phase_"):
        return 1  # phase spans on their own row per process
    return 0


def chrome_trace(events: List[Dict[str, object]]) -> Dict[str, object]:
    stamped = [e for e in events if isinstance(e.get("ts"), (int, float))]

    def _start(e: Dict[str, object]) -> float:
        # span records stamp their END; the viewer baseline must cover
        # the earliest span START or its ts goes negative
        dur = e.get("dur_s")
        if isinstance(dur, (int, float)):
            return float(e["ts"]) - float(dur)
        return float(e["ts"])

    t0 = min((_start(e) for e in stamped), default=0.0)
    out: List[Dict[str, object]] = []
    for rec in stamped:
        pid = rec.get("index", rec.get("node", 0))
        try:
            pid = int(pid)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            pid = 0
        ev: Dict[str, object] = {
            "name": str(rec.get("event", "?")),
            "ph": "i",
            "s": "t",
            "ts": (float(rec["ts"]) - t0) * 1e6,
            "pid": pid,
            "tid": _tid(rec),
            "args": {k: v for k, v in rec.items() if k not in _STRUCTURAL},
        }
        dur = rec.get("dur_s")
        if isinstance(dur, (int, float)):
            # complete span: ts is the START of the phase
            ev["ph"] = "X"
            ev["dur"] = float(dur) * 1e6
            ev["ts"] = (float(rec["ts"]) - float(dur) - t0) * 1e6
            ev.pop("s")
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(events: List[Dict[str, object]], path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(events), f, default=repr)
    return path


def load_events(path: str) -> List[Dict[str, object]]:
    """Load obs JSON back into an event list: accepts a raw trace list
    (TraceRecorder.write_json), a flight dump (events live under
    ``"events"``), or a Chrome trace (args re-flattened)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return data
    if isinstance(data, dict) and isinstance(data.get("events"), list):
        return data["events"]
    if isinstance(data, dict) and isinstance(data.get("traceEvents"), list):
        out = []
        for ev in data["traceEvents"]:
            rec: Dict[str, object] = {
                "event": ev.get("name"),
                "ts": float(ev.get("ts", 0.0)) / 1e6,
            }
            if "dur" in ev:
                rec["dur_s"] = float(ev["dur"]) / 1e6
                rec["ts"] = float(rec["ts"]) + float(rec["dur_s"])
            rec.update(ev.get("args") or {})
            if "pid" in ev:
                rec.setdefault("index", ev["pid"])
            out.append(rec)
        return out
    raise ValueError(f"unrecognized obs JSON shape in {path}")


def load_flight(path: str) -> Optional[Dict[str, object]]:
    """The full flight record when ``path`` is a flight dump, else
    None."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and data.get("kind") == "flight":
        return data
    return None
