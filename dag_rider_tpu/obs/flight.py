"""Flight recorder: every red run leaves a post-mortem artifact.

A last-N event ring rides the same slog sink tee as the trace
recorder; when a trigger event flows past — an invariant violation,
resilient-ladder exhaustion, or a pump error — the ring plus a metrics
snapshot from every registered source is dumped to disk as one JSON
file that `scripts/obs_report.py` can load. The scenario runner and
chaos harness also call :meth:`dump` explicitly when an audit raises
post-hoc (the violation may surface in a checker, not an event).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from dag_rider_tpu.config import env_int, env_str

#: Event names that auto-dump (ISSUE 13: InvariantViolation,
#: resilient-ladder tier exhaustion, pump_errors).
TRIGGERS = frozenset({"invariant_violation", "verify_exhausted", "pump_error"})


class FlightRecorder:
    """Last-N ring + trigger watch + metrics-snapshot dump."""

    def __init__(
        self,
        out_dir: Optional[str] = None,
        *,
        capacity: int = 0,
        clock: Callable[[], float] = time.time,
        triggers: frozenset = TRIGGERS,
        max_dumps: int = 8,
    ):
        if out_dir is None:
            out_dir = env_str("DAGRIDER_FLIGHT_DIR")
        if capacity <= 0:
            capacity = env_int("DAGRIDER_FLIGHT_EVENTS")
        self.out_dir = out_dir
        self.capacity = capacity
        self.clock = clock
        self.triggers = triggers
        self.max_dumps = max_dumps  # a crash loop must not fill the disk
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0
        self._seq = 0
        self._sources: List[Tuple[str, Callable[[], Dict[str, object]]]] = []
        self.dumps: List[str] = []

    def add_metrics_source(
        self, name: str, snapshot: Callable[[], Dict[str, object]]
    ) -> None:
        """Register a snapshot callable (e.g. a process's
        ``metrics.snapshot``) captured at dump time."""
        self._sources.append((name, snapshot))

    def sink(self, rec: Dict[str, object]) -> None:
        """Slog sink: retain the event; dump when it is a trigger."""
        trigger = rec.get("event") in self.triggers
        with self._lock:
            self._ring.append(rec)
            self._total += 1
        if trigger:
            self.dump(str(rec.get("event")), trigger=rec)

    def dump(
        self,
        reason: str,
        trigger: Optional[Dict[str, object]] = None,
    ) -> Optional[str]:
        """Write the post-mortem JSON; returns its path (None when no
        output directory is configured or the dump budget is spent)."""
        if not self.out_dir:
            return None
        with self._lock:
            if self._seq >= self.max_dumps:
                return None
            seq = self._seq
            self._seq += 1
            events = list(self._ring)
            dropped = max(0, self._total - len(events))
        metrics: Dict[str, Dict[str, object]] = {}
        for name, snap in self._sources:
            try:
                metrics[name] = snap()
            except Exception as e:  # a broken source must not kill the dump
                metrics[name] = {"snapshot_error": repr(e)}
        record = {
            "kind": "flight",
            "reason": reason,
            "ts": self.clock(),
            "trigger": trigger,
            "dropped": dropped,
            "events": events,
            "metrics": metrics,
        }
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"flight_{seq:03d}_{reason}.json")
        with open(path, "w") as f:
            json.dump(record, f, default=repr)
        self.dumps.append(path)
        return path
