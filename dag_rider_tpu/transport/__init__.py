from dag_rider_tpu.transport.base import Handler, Transport
from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.transport.rbc import RbcTransport

__all__ = [
    "Handler",
    "Transport",
    "FaultPlan",
    "FaultyTransport",
    "InMemoryTransport",
    "RbcTransport",
]
