"""Fault-injection transport wrapper.

The reference never injects or tolerates a single fault — ``faulty`` is only
an arithmetic parameter (SURVEY.md §5: "required to claim BFT capability at
all"). This wrapper layers Byzantine network behavior over any Transport:

- drop: lose a message to some destination,
- delay: hold a message back (re-queued on ``flush_delayed``),
- duplicate: deliver twice,
- equivocate: substitute a conflicting vertex for a chosen sender.

All decisions come from a seeded RNG — runs are reproducible.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, List, Optional

from dag_rider_tpu.core.types import BroadcastMessage, Vertex
from dag_rider_tpu.transport.base import Handler, Transport
from dag_rider_tpu.transport.memory import InMemoryTransport


@dataclasses.dataclass
class FaultPlan:
    """Probabilities per (message, destination) decision."""

    drop: float = 0.0
    delay: float = 0.0
    duplicate: float = 0.0
    # source indices considered Byzantine for equivocation injection
    equivocators: tuple = ()
    seed: int = 0


class FaultyTransport(Transport):
    """Wraps an InMemoryTransport, applying a FaultPlan on broadcast."""

    def __init__(self, plan: FaultPlan, inner: Optional[InMemoryTransport] = None):
        self.inner = inner if inner is not None else InMemoryTransport()
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.delayed: List[tuple] = []
        self.stats = {"dropped": 0, "delayed": 0, "duplicated": 0, "equivocated": 0}
        self._mutator: Optional[Callable[[Vertex], Vertex]] = None

    def set_equivocation_mutator(self, fn: Callable[[Vertex], Vertex]) -> None:
        """How to corrupt an equivocator's vertex (defaults to payload swap)."""
        self._mutator = fn

    def subscribe(self, index: int, handler: Handler) -> None:
        self.inner.subscribe(index, handler)

    def broadcast(self, msg: BroadcastMessage) -> None:
        dests = [d for d in self.inner.subscribers() if d != msg.sender]
        for dest in dests:
            out = msg
            if (
                msg.kind == "val"
                and msg.vertex is not None
                and msg.sender in self.plan.equivocators
                and self.rng.random() < 0.5
            ):
                out = dataclasses.replace(msg, vertex=self._equivocate(msg.vertex))
                self.stats["equivocated"] += 1
            roll = self.rng.random()
            if roll < self.plan.drop:
                self.stats["dropped"] += 1
                continue
            if roll < self.plan.drop + self.plan.delay:
                self.stats["delayed"] += 1
                self.delayed.append((dest, out))
                continue
            self._enqueue(dest, out)
            if self.rng.random() < self.plan.duplicate:
                self.stats["duplicated"] += 1
                self._enqueue(dest, out)

    def _equivocate(self, v: Vertex) -> Vertex:
        if self._mutator is not None:
            return self._mutator(v)
        from dag_rider_tpu.core.types import Block

        return dataclasses.replace(
            v, block=Block((b"equivocation-" + bytes(str(v.id), "ascii"),))
        )

    def _enqueue(self, dest: int, msg: BroadcastMessage) -> None:
        self.inner.enqueue(dest, msg)

    def flush_delayed(self) -> int:
        """Release all held-back messages into the queue (asynchrony: every
        message is eventually delivered)."""
        n = len(self.delayed)
        for dest, msg in self.delayed:
            self._enqueue(dest, msg)
        self.delayed.clear()
        return n

    # pump passthrough so Simulation can drive us
    def pump_one(self) -> bool:
        return self.inner.pump_one()

    def pump(self, max_messages: Optional[int] = None) -> int:
        return self.inner.pump(max_messages)

    @property
    def pending(self) -> int:
        return self.inner.pending
