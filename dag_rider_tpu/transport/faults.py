"""Fault-injection transport wrapper.

The reference never injects or tolerates a single fault — ``faulty`` is only
an arithmetic parameter (SURVEY.md §5: "required to claim BFT capability at
all"). This wrapper layers Byzantine network behavior over any Transport:

- drop: lose a message to some destination,
- delay: hold a message back (delivered on ``flush_delayed``),
- duplicate: deliver twice,
- equivocate: substitute a conflicting vertex for a chosen sender.

All decisions come from a seeded RNG — runs are reproducible.

Faults are applied at DELIVERY time, per (message, destination): subscribe
captures each process's real handler and registers a wrapping handler with
the inner transport, so the wrapper needs nothing from the inner beyond
the two-method Transport interface — any broadcast/subscribe transport
composes (round 9; before that the wrapper reached into
InMemoryTransport.enqueue/subscribers and the "any Transport" claim was
false). For the in-memory default the observable schedule is unchanged:
FIFO delivery order equals enqueue order equals broadcast order, so the
seeded roll sequence — and therefore every seed-pinned chaos test —
is identical to the old broadcast-time injection.

WAN mode (round 11): pass ``topology=`` a :class:`WanTopology` and the
single uniform roll is replaced by per-link (src, dst) behavior — an RTT
matrix with jitter, per-link drop/duplicate overrides, and scheduled
:class:`Partition`\\ s that *heal*: traffic across a severed cut is held
(not lost) and released once the partition ends. The wrapper gains a
virtual clock — drive it with :meth:`advance`; ``flush_delayed`` still
means "eventual delivery NOW" and drains everything held. The legacy
single-roll path is untouched when no topology is given, so the seeded
roll sequence of existing chaos tests stays byte-identical.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
from typing import Callable, Dict, List, Optional, Tuple

from dag_rider_tpu.core.types import BroadcastMessage, Vertex
from dag_rider_tpu.transport.base import Handler, Transport
from dag_rider_tpu.transport.memory import InMemoryTransport


@dataclasses.dataclass
class FaultPlan:
    """Probabilities per (message, destination) decision."""

    drop: float = 0.0
    delay: float = 0.0
    duplicate: float = 0.0
    # source indices considered Byzantine for equivocation injection
    equivocators: tuple = ()
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class LinkPlan:
    """Per-(src, dst) link behavior for WAN mode. ``rtt_s`` is the round
    trip; one-way latency is ``rtt_s / 2`` plus uniform jitter in
    ``[0, jitter_s)``. ``drop``/``duplicate`` are per-delivery
    probabilities on this link."""

    rtt_s: float = 0.0
    jitter_s: float = 0.0
    drop: float = 0.0
    duplicate: float = 0.0


@dataclasses.dataclass(frozen=True)
class Partition:
    """A scheduled cut that heals: while ``start_s <= now < heal_s``,
    traffic between different ``groups`` is held and released at
    ``heal_s`` (asynchrony: delayed, never lost). Nodes absent from
    every group are unaffected."""

    start_s: float
    heal_s: float
    groups: Tuple[Tuple[int, ...], ...]

    def active(self, now: float) -> bool:
        return self.start_s <= now < self.heal_s

    def severed(self, src: int, dst: int) -> bool:
        gs = gd = None
        for gi, members in enumerate(self.groups):
            if src in members:
                gs = gi
            if dst in members:
                gd = gi
        return gs is not None and gd is not None and gs != gd


class WanTopology:
    """Per-link plans + partition schedule for FaultyTransport WAN mode.

    Resolution order for ``link(src, dst)``: an explicit ``links``
    override, else the intra/inter-region pair when built via
    :meth:`regions`, else ``default``.
    """

    def __init__(
        self,
        default: LinkPlan = LinkPlan(),
        links: Optional[Dict[Tuple[int, int], LinkPlan]] = None,
        partitions: Tuple[Partition, ...] = (),
    ) -> None:
        self.default = default
        self.links: Dict[Tuple[int, int], LinkPlan] = dict(links or {})
        self.partitions = tuple(partitions)
        self._region: Optional[List[int]] = None
        self._inter: Optional[LinkPlan] = None

    @classmethod
    def regions(
        cls,
        n: int,
        k: int = 2,
        *,
        intra: LinkPlan = LinkPlan(rtt_s=0.002),
        inter: LinkPlan = LinkPlan(rtt_s=0.04, jitter_s=0.01),
        partitions: Tuple[Partition, ...] = (),
    ) -> "WanTopology":
        """Round-robin region assignment (node i -> region i % k): cheap
        intra-region links, expensive inter-region ones — the classic
        geo-replicated shape."""
        topo = cls(default=intra, partitions=partitions)
        topo._region = [i % k for i in range(n)]
        topo._inter = inter
        return topo

    def link(self, src: int, dst: int) -> LinkPlan:
        lp = self.links.get((src, dst))
        if lp is not None:
            return lp
        if self._region is not None:
            r = self._region
            if (
                0 <= src < len(r)
                and 0 <= dst < len(r)
                and r[src] != r[dst]
            ):
                return self._inter or self.default
        return self.default

    def heal_time(self, src: int, dst: int, now: float) -> Optional[float]:
        """Latest heal time of any partition currently severing
        (src, dst), or None if the pair is connected at ``now``."""
        t: Optional[float] = None
        for p in self.partitions:
            if p.active(now) and p.severed(src, dst):
                t = p.heal_s if t is None else max(t, p.heal_s)
        return t


class FaultyTransport(Transport):
    """Wraps any Transport (in-memory by default), applying a FaultPlan
    to each delivery — and, with ``topology=``, per-link WAN behavior."""

    def __init__(
        self,
        plan: FaultPlan,
        inner: Optional[Transport] = None,
        topology: Optional[WanTopology] = None,
    ):
        self.inner: Transport = (
            inner if inner is not None else InMemoryTransport()
        )
        self.plan = plan
        self.topology = topology
        self.rng = random.Random(plan.seed)
        #: (dest, real handler, message) held back by a delay roll
        self.delayed: List[tuple] = []
        self.stats = {"dropped": 0, "delayed": 0, "duplicated": 0, "equivocated": 0}
        if topology is not None:
            # WAN gauges only exist in WAN mode: chaos tests snapshot the
            # legacy stats dict and its key set must not change under them
            self.stats["held_link"] = 0
            self.stats["held_partition"] = 0
        #: virtual clock + in-flight heap for WAN mode:
        #: (release time, seq, dest, handler, msg)
        self.now = 0.0
        self._held: List[tuple] = []
        self._seq = 0
        self._handlers: Dict[int, Handler] = {}
        self._batch_handlers: Dict[int, Callable] = {}
        self._mutator: Optional[Callable[[Vertex], Vertex]] = None
        # Grouped-pump passthrough (round 13): installed as an INSTANCE
        # attribute only for delay-free, topology-free plans, so the
        # Simulation's `callable(pump_grouped)` probe silently falls back
        # to per-message pumping whenever a roll could hold a message
        # back — delayed entries need their per-message handler captured,
        # which only the scalar path records.
        if (
            topology is None
            and plan.delay == 0.0
            and callable(getattr(self.inner, "pump_grouped", None))
        ):
            self.pump_grouped = self._pump_grouped

    def set_equivocation_mutator(self, fn: Callable[[Vertex], Vertex]) -> None:
        """How to corrupt an equivocator's vertex (defaults to payload swap)."""
        self._mutator = fn

    def subscribe(self, index: int, handler: Handler) -> None:
        self._handlers[index] = handler

        def wrapped(msg: BroadcastMessage) -> None:
            self._deliver(index, handler, msg)

        self.inner.subscribe(index, wrapped)

    def subscribe_many(
        self, index: int, handler: Callable[[list], None]
    ) -> None:
        """Register a batch handler: the inner's grouped pump hands VAL
        runs to a wrapper that pays the SAME per-message roll structure
        as :meth:`_deliver` (equivocation coin, one main roll, duplicate
        roll on delivery) and forwards the survivors as one batch call.
        Grouping permutes delivery order across destinations, so the
        roll->(message, destination) assignment differs from the scalar
        pump for the same seed — fault RATES are identical, seed-pinned
        schedules are per-pump. Falls back silently when the inner has
        no batch seam (the per-message path stays correct on its own)."""
        self._batch_handlers[index] = handler
        sub_many = getattr(self.inner, "subscribe_many", None)
        if not callable(sub_many):
            return

        def wrapped(msgs: list) -> None:
            self._deliver_batch(index, handler, msgs)

        sub_many(index, wrapped)

    def broadcast(self, msg: BroadcastMessage) -> None:
        self.inner.broadcast(msg)

    def _deliver(self, dest: int, handler: Handler, msg: BroadcastMessage) -> None:
        """One (message, destination) delivery through the plan. The
        roll structure per delivery — optional equivocation coin, one
        main drop/delay roll, a duplicate roll only when delivered — is
        the original broadcast-time sequence verbatim (WAN mode takes
        its own per-link branch instead of the single uniform roll)."""
        out = msg
        if (
            msg.kind == "val"
            and msg.vertex is not None
            and msg.sender in self.plan.equivocators
            and self.rng.random() < 0.5
        ):
            out = dataclasses.replace(msg, vertex=self._equivocate(msg.vertex))
            self.stats["equivocated"] += 1
        if self.topology is not None:
            self._deliver_wan(dest, handler, out)
            return
        roll = self.rng.random()
        if roll < self.plan.drop:
            self.stats["dropped"] += 1
            return
        if roll < self.plan.drop + self.plan.delay:
            self.stats["delayed"] += 1
            self.delayed.append((dest, handler, out))
            return
        handler(out)
        if self.rng.random() < self.plan.duplicate:
            self.stats["duplicated"] += 1
            handler(out)

    def _deliver_batch(
        self, dest: int, handler: Callable[[list], None], msgs: list
    ) -> None:
        """A VAL run for one destination through the plan, message by
        message: drops leave the batch, duplicates appear twice, an
        equivocation coin may substitute a conflicting vertex. Survivors
        go out as ONE batch call. A delay roll (possible only when the
        inner's grouped pump is driven directly — the Simulation never
        selects it for delay plans) parks the message with its
        per-message handler so flush_delayed replays it unchanged."""
        plan = self.plan
        rng = self.rng
        stats = self.stats
        out: list = []
        for msg in msgs:
            m = msg
            if (
                msg.vertex is not None
                and msg.sender in plan.equivocators
                and rng.random() < 0.5
            ):
                m = dataclasses.replace(
                    msg, vertex=self._equivocate(msg.vertex)
                )
                stats["equivocated"] += 1
            roll = rng.random()
            if roll < plan.drop:
                stats["dropped"] += 1
                continue
            if roll < plan.drop + plan.delay:
                stats["delayed"] += 1
                self.delayed.append((dest, self._handlers[dest], m))
                continue
            out.append(m)
            if rng.random() < plan.duplicate:
                stats["duplicated"] += 1
                out.append(m)
        if out:
            handler(out)

    def _deliver_wan(
        self, dest: int, handler: Handler, msg: BroadcastMessage
    ) -> None:
        """Per-link delivery: roll the LINK's drop, schedule at the
        link's one-way latency (+jitter), and hold severed traffic until
        the partition heals. Held messages release in timestamp order
        via :meth:`advance` (or all at once via flush_delayed)."""
        link = self.topology.link(msg.sender, dest)
        if link.drop and self.rng.random() < link.drop:
            self.stats["dropped"] += 1
            return
        latency = link.rtt_s / 2.0
        if link.jitter_s:
            latency += self.rng.uniform(0.0, link.jitter_s)
        release = self.now + latency
        heal = self.topology.heal_time(msg.sender, dest, self.now)
        if heal is not None:
            release = max(release, heal)
            self.stats["held_partition"] += 1
        copies = 1
        if link.duplicate and self.rng.random() < link.duplicate:
            self.stats["duplicated"] += 1
            copies = 2
        for _ in range(copies):
            if release <= self.now:
                handler(msg)
            else:
                if heal is None:
                    self.stats["held_link"] += 1
                heapq.heappush(
                    self._held, (release, self._seq, dest, handler, msg)
                )
                self._seq += 1

    def advance(self, dt: float) -> int:
        """Move the WAN virtual clock forward and deliver every held
        message that comes due, in release order. Returns deliveries.
        No-op without a topology (the legacy delayed list is released
        explicitly via flush_delayed, not by time)."""
        self.now += dt
        return self._release_due()

    def _release_due(self) -> int:
        done = 0
        while self._held and self._held[0][0] <= self.now:
            _, _, _dest, handler, msg = heapq.heappop(self._held)
            handler(msg)
            done += 1
        return done

    def _equivocate(self, v: Vertex) -> Vertex:
        if self._mutator is not None:
            return self._mutator(v)
        from dag_rider_tpu.core.types import Block

        return dataclasses.replace(
            v, block=Block((b"equivocation-" + bytes(str(v.id), "ascii"),))
        )

    def flush_delayed(self) -> int:
        """Deliver all held-back messages (asynchrony: every message is
        eventually delivered). Straight to the captured real handlers —
        a delayed message already paid its fault rolls and must not roll
        again on the way out. In WAN mode this also fast-forwards the
        clock past every in-flight release (including partition holds) —
        the "eventually" drain; use :meth:`advance` for paced release."""
        held, self.delayed = self.delayed, []
        for _dest, handler, msg in held:
            handler(msg)
        count = len(held)
        if self._held:
            self.now = max(self.now, max(r for r, *_ in self._held))
            count += self._release_due()
        return count

    # pump passthrough so Simulation can drive us; inners without a pump
    # loop (push-style transports deliver inside broadcast) have nothing
    # to drive and nothing pending
    def pump_one(self) -> bool:
        fn = getattr(self.inner, "pump_one", None)
        return bool(fn()) if callable(fn) else False

    def pump(self, max_messages: Optional[int] = None) -> int:
        fn = getattr(self.inner, "pump", None)
        return int(fn(max_messages)) if callable(fn) else 0

    def _pump_grouped(self, max_messages: Optional[int] = None) -> int:
        """Bound to ``self.pump_grouped`` in ``__init__`` for delay-free,
        topology-free plans only: VAL runs reach :meth:`_deliver_batch`
        through the inner's batch seam, everything else flows through
        the per-message wrappers exactly as under :meth:`pump`."""
        return int(self.inner.pump_grouped(max_messages))

    @property
    def fanout_sentinel(self) -> bool:
        """Forwarded to the inner transport: fan-out expansion happens
        where the queue lives, and this wrapper intercepts at delivery
        time — after expansion — so sentinel compression is invisible to
        the fault rolls."""
        return bool(getattr(self.inner, "fanout_sentinel", False))

    @fanout_sentinel.setter
    def fanout_sentinel(self, value: bool) -> None:
        if hasattr(self.inner, "fanout_sentinel"):
            self.inner.fanout_sentinel = bool(value)

    @property
    def pending(self) -> int:
        """Undelivered backlog: the inner queue plus everything this
        wrapper is holding (delay rolls, WAN in-flight/partition holds).
        Held messages ARE pending deliveries — sync patience reads this
        to tell "throttled" from "partitioned"."""
        return (
            int(getattr(self.inner, "pending", 0))
            + len(self.delayed)
            + len(self._held)
        )
