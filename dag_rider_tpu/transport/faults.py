"""Fault-injection transport wrapper.

The reference never injects or tolerates a single fault — ``faulty`` is only
an arithmetic parameter (SURVEY.md §5: "required to claim BFT capability at
all"). This wrapper layers Byzantine network behavior over any Transport:

- drop: lose a message to some destination,
- delay: hold a message back (delivered on ``flush_delayed``),
- duplicate: deliver twice,
- equivocate: substitute a conflicting vertex for a chosen sender.

All decisions come from a seeded RNG — runs are reproducible.

Faults are applied at DELIVERY time, per (message, destination): subscribe
captures each process's real handler and registers a wrapping handler with
the inner transport, so the wrapper needs nothing from the inner beyond
the two-method Transport interface — any broadcast/subscribe transport
composes (round 9; before that the wrapper reached into
InMemoryTransport.enqueue/subscribers and the "any Transport" claim was
false). For the in-memory default the observable schedule is unchanged:
FIFO delivery order equals enqueue order equals broadcast order, so the
seeded roll sequence — and therefore every seed-pinned chaos test —
is identical to the old broadcast-time injection.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional

from dag_rider_tpu.core.types import BroadcastMessage, Vertex
from dag_rider_tpu.transport.base import Handler, Transport
from dag_rider_tpu.transport.memory import InMemoryTransport


@dataclasses.dataclass
class FaultPlan:
    """Probabilities per (message, destination) decision."""

    drop: float = 0.0
    delay: float = 0.0
    duplicate: float = 0.0
    # source indices considered Byzantine for equivocation injection
    equivocators: tuple = ()
    seed: int = 0


class FaultyTransport(Transport):
    """Wraps any Transport (in-memory by default), applying a FaultPlan
    to each delivery."""

    def __init__(self, plan: FaultPlan, inner: Optional[Transport] = None):
        self.inner: Transport = (
            inner if inner is not None else InMemoryTransport()
        )
        self.plan = plan
        self.rng = random.Random(plan.seed)
        #: (dest, real handler, message) held back by a delay roll
        self.delayed: List[tuple] = []
        self.stats = {"dropped": 0, "delayed": 0, "duplicated": 0, "equivocated": 0}
        self._handlers: Dict[int, Handler] = {}
        self._mutator: Optional[Callable[[Vertex], Vertex]] = None

    def set_equivocation_mutator(self, fn: Callable[[Vertex], Vertex]) -> None:
        """How to corrupt an equivocator's vertex (defaults to payload swap)."""
        self._mutator = fn

    def subscribe(self, index: int, handler: Handler) -> None:
        self._handlers[index] = handler

        def wrapped(msg: BroadcastMessage) -> None:
            self._deliver(index, handler, msg)

        self.inner.subscribe(index, wrapped)

    def broadcast(self, msg: BroadcastMessage) -> None:
        self.inner.broadcast(msg)

    def _deliver(self, dest: int, handler: Handler, msg: BroadcastMessage) -> None:
        """One (message, destination) delivery through the plan. The
        roll structure per delivery — optional equivocation coin, one
        main drop/delay roll, a duplicate roll only when delivered — is
        the original broadcast-time sequence verbatim."""
        out = msg
        if (
            msg.kind == "val"
            and msg.vertex is not None
            and msg.sender in self.plan.equivocators
            and self.rng.random() < 0.5
        ):
            out = dataclasses.replace(msg, vertex=self._equivocate(msg.vertex))
            self.stats["equivocated"] += 1
        roll = self.rng.random()
        if roll < self.plan.drop:
            self.stats["dropped"] += 1
            return
        if roll < self.plan.drop + self.plan.delay:
            self.stats["delayed"] += 1
            self.delayed.append((dest, handler, out))
            return
        handler(out)
        if self.rng.random() < self.plan.duplicate:
            self.stats["duplicated"] += 1
            handler(out)

    def _equivocate(self, v: Vertex) -> Vertex:
        if self._mutator is not None:
            return self._mutator(v)
        from dag_rider_tpu.core.types import Block

        return dataclasses.replace(
            v, block=Block((b"equivocation-" + bytes(str(v.id), "ascii"),))
        )

    def flush_delayed(self) -> int:
        """Deliver all held-back messages (asynchrony: every message is
        eventually delivered). Straight to the captured real handlers —
        a delayed message already paid its fault rolls and must not roll
        again on the way out."""
        held, self.delayed = self.delayed, []
        for _dest, handler, msg in held:
            handler(msg)
        return len(held)

    # pump passthrough so Simulation can drive us; inners without a pump
    # loop (push-style transports deliver inside broadcast) have nothing
    # to drive and nothing pending
    def pump_one(self) -> bool:
        fn = getattr(self.inner, "pump_one", None)
        return bool(fn()) if callable(fn) else False

    def pump(self, max_messages: Optional[int] = None) -> int:
        fn = getattr(self.inner, "pump", None)
        return int(fn(max_messages)) if callable(fn) else 0

    @property
    def pending(self) -> int:
        return int(getattr(self.inner, "pending", 0))
