"""gRPC networked Transport — the multi-host deployment backend.

The reference's broker only works inside one OS process (Go channels,
``process/transport.go``); SURVEY.md §2c calls for "(a) process-to-process
consensus traffic stays host-side (gRPC), preserving Transport as an
interface with in-memory (test) and networked implementations". This is
that networked implementation.

No generated protobuf stubs: the wire payload is the framework's own
canonical codec (core/codec.py) carried through gRPC's generic byte-level
method handlers — one unary method ``/dagrider.Transport/Deliver``. That
keeps the build dependency-free (no grpc_tools in the image) while staying
a real gRPC service (HTTP/2, deadlines, auth hooks all available).

Delivery model matches InMemoryTransport: incoming RPCs land in an inbox;
the owner thread pumps them into the Process. The consensus state machine
stays single-threaded (SURVEY.md D4's fix) — only the inbox is shared.
"""

from __future__ import annotations

import math
import random
import struct
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

import grpc

from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.base import Handler, Transport
from dag_rider_tpu.utils.metrics import Metrics

_SERVICE = "dagrider.Transport"
_METHOD = f"/{_SERVICE}/Deliver"
_SNAPSHOT_METHOD = f"/{_SERVICE}/Snapshot"
_SUBMIT_METHOD = f"/{_SERVICE}/Submit"

_identity = lambda b: b  # noqa: E731 — bytes in, bytes out


_SNAP_DOMAIN = b"dagrider-snapshot-req-v2"  # v2: timestamped request body


class WanFault:
    """Seeded WAN delay/drop policy applied at the gRPC send seam.

    Called once per network attempt with the destination peer; returns a
    verdict: negative = drop this attempt (the bytes never leave the
    host), positive = hold the attempt for that many seconds before it
    goes out, zero = send immediately. Seeded so a cluster scenario's
    fault schedule replays; ``delay_ms`` is a (low, high) uniform window
    and ``rate`` the fraction of attempts delayed at all.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        delay_ms: Tuple[float, float] = (0.0, 0.0),
        delay_rate: float = 1.0,
        drop: float = 0.0,
    ) -> None:
        lo, hi = float(delay_ms[0]), float(delay_ms[1])
        if lo < 0 or hi < lo:
            raise ValueError(f"delay_ms needs 0 <= low <= high, got {delay_ms}")
        if not 0.0 <= drop <= 1.0:
            raise ValueError(f"drop must be in [0, 1], got {drop}")
        if not 0.0 <= delay_rate <= 1.0:
            raise ValueError(f"delay_rate must be in [0, 1], got {delay_rate}")
        self._rng = random.Random(seed)
        self._delay = (lo, hi)
        self._delay_rate = delay_rate
        self._drop = drop
        self._lock = threading.Lock()

    def __call__(self, peer: int) -> float:
        # _send runs on the owner thread AND retry-timer threads; the
        # generator state must not interleave or the seeded schedule
        # stops being a schedule.
        with self._lock:
            if self._drop and self._rng.random() < self._drop:
                return -1.0
            lo, hi = self._delay
            if hi > 0 and (
                self._delay_rate >= 1.0
                or self._rng.random() < self._delay_rate
            ):
                return self._rng.uniform(lo, hi) / 1e3
        return 0.0


class _DeliverHandler(grpc.GenericRpcHandler):
    def __init__(
        self,
        sink: Callable[[bytes], None],
        snapshot_provider: Optional[Callable[[], bytes]] = None,
        auth=None,
        snapshot_min_interval_s: float = 1.0,
        snapshot_freshness_s: Optional[float] = 300.0,
        metrics_inc: Optional[Callable[[str], None]] = None,
        wall_clock: Callable[[], float] = time.time,
        submit_sink: Optional[Callable[[], Optional[Callable]]] = None,
    ):
        self._sink = sink
        self._snapshot = snapshot_provider
        self._auth = auth
        # late-bound client front door (cluster runner): a zero-arg
        # getter so the owner can wire the sink after construction
        self._submit_sink = submit_sink if submit_sink is not None else (
            lambda: None
        )
        self._inc = metrics_inc if metrics_inc is not None else lambda _n: None
        # Injectable wall clock (tests/virtual time): freshness is a
        # cross-host comparison, so it NEEDS wall time in production —
        # but the default must be overridable or the freshness window is
        # untestable without real sleeps.
        self._wall = wall_clock
        # <= 0 normalizes to the unthrottled / uncheck-everything intent
        # (and keeps the token-bucket divisor positive): interval 0 means
        # "no per-relayer throttle", freshness 0 means "no freshness
        # check" — NOT "refuse everything", which a literal 0 window
        # would do (every real ts is >0 seconds old on arrival).
        if snapshot_min_interval_s <= 0.0:
            snapshot_min_interval_s = 1e-9
        if snapshot_freshness_s is not None and snapshot_freshness_s <= 0.0:
            snapshot_freshness_s = None
        self._snap_lock = threading.Lock()
        # Authenticated requesters are throttled PER RELAYER: one
        # Byzantine committee member hammering Snapshot must not starve
        # an honest laggard whose state-transfer fetch is its only
        # recovery path once f+1 peers have pruned past it. The table is
        # naturally bounded at n entries — only relayers whose MAC
        # verifies (known pair keys) ever land in it. Unauthenticated
        # deployments fall back to a stricter GLOBAL cap (no identity to
        # key the table on).
        self._snap_last_by: Dict[int, float] = {}
        #: relayer -> highest timestamp accepted. Requests must carry a
        #: STRICTLY increasing ts per relayer: a captured request's ts
        #: was already consumed, so replays are refused WITHOUT charging
        #: the victim's throttle slot — an on-path replay stream cannot
        #: starve the honest requester out of its own budget.
        self._snap_ts_by: Dict[int, float] = {}
        self._snap_last_global = float("-inf")
        self._snap_min_interval = snapshot_min_interval_s
        # Freshness window is generous (5 min default, operator-tunable,
        # None disables): its job is bounding the replay/state horizon,
        # not tight clock agreement — a recovering node with pre-NTP
        # clock drift is exactly the node that needs the RPC. Skew
        # refusals are counted distinctly (net_snapshot_stale_refusals,
        # incremented only for MAC-valid requests) so a wedged-by-skew
        # committee member is diagnosable on the donor.
        self._snap_freshness = snapshot_freshness_s
        # Serialized-window cache: bounds donor-side SERIALIZATION work
        # at one provider call per TTL no matter how many authenticated
        # relayers ask (built under the lock — concurrent misses at TTL
        # expiry wait instead of each re-serializing).
        self._snap_cache: Optional[bytes] = None
        self._snap_cache_t = float("-inf")
        # Global egress token bucket: per-relayer fairness alone lets f
        # Byzantine members each pull a full-window blob per interval
        # (~f blobs/s of response bandwidth from 44-byte requests). The
        # bucket bounds sustained egress at ~1 blob/interval (burst 3).
        # Starvation under the bucket is probabilistic, not permanent:
        # an honest laggard retrying each interval competes with at
        # most f in-interval requesters for the refill, so expected
        # recovery is O(f) attempts, vs the unbounded wedge a hard
        # per-requester denial would be.
        self._snap_tokens = 3.0
        self._snap_tok_t = time.monotonic()

    def service(self, handler_call_details):
        if handler_call_details.method == _METHOD:

            def unary(request: bytes, context) -> bytes:
                self._sink(request)
                return b"\x01"

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        if handler_call_details.method == _SUBMIT_METHOD:
            # Client mempool front door (cluster mode): clients are not
            # committee members, so this endpoint is not MAC-gated — the
            # sink behind it is the node's own admission control, whose
            # whole job is surviving untrusted load (throttle/shed).
            sink = self._submit_sink()
            if sink is None:
                return None

            def submit(request: bytes, context) -> bytes:
                self._inc("net_client_submits")
                try:
                    return sink(request)
                except Exception:  # noqa: BLE001 — a malformed client
                    # frame must not crash the server thread; empty =
                    # refusal, the client treats it as not-accepted.
                    return b""

            return grpc.unary_unary_rpc_method_handler(
                submit,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        if (
            handler_call_details.method == _SNAPSHOT_METHOD
            and self._snapshot is not None
        ):
            # Peer state transfer: serve the live DAG window. The payload
            # is self-certifying (signed vertices) — see
            # utils.checkpoint.restore_from_snapshot's trust model — so
            # INTEGRITY needs nothing here; AVAILABILITY does: each
            # response serializes the whole window, so requests are
            # MAC-gated with a freshness window (when frame auth is
            # configured) and rate-limited per authenticated relayer —
            # a 0-byte request must not be a cheap CPU/bandwidth
            # amplifier, and on plaintext gRPC a captured request must
            # expire rather than burn the donor's budget forever. Empty
            # response = refusal; the honest recovery path just retries
            # after a pump cycle.
            def snap(request: bytes, context) -> bytes:
                now = time.monotonic()
                if self._auth is not None:
                    from dag_rider_tpu.transport.auth import TAG_BYTES

                    if len(request) != 4 + 8 + TAG_BYTES:
                        self._inc("net_snapshot_rejects")
                        return b""
                    (relayer,) = struct.unpack_from("<I", request)
                    (ts,) = struct.unpack_from("<d", request, 4)
                    if not math.isfinite(ts):
                        # NaN compares False with everything: it would
                        # sail through the freshness AND replay gates,
                        # then poison _snap_ts_by for that relayer.
                        self._inc("net_snapshot_rejects")
                        return b""
                    # MAC first: the freshness/replay/throttle counters
                    # below must describe authenticated committee
                    # members, not unauthenticated noise, or the
                    # skew-diagnosis signal is meaningless.
                    if not self._auth.check(
                        relayer,
                        _SNAP_DOMAIN + request[4:12],
                        request[12:],
                    ):
                        self._inc("net_snapshot_rejects")
                        return b""
                    if (
                        self._snap_freshness is not None
                        and abs(self._wall() - ts) > self._snap_freshness
                    ):
                        self._inc("net_snapshot_stale_refusals")
                        return b""
                    with self._snap_lock:
                        prev_ts = self._snap_ts_by.get(
                            relayer, float("-inf")
                        )
                        if ts == prev_ts:
                            # Exact capture replay: refuse without
                            # touching the relayer's throttle state, so
                            # a replay stream can never starve the
                            # victim out of its own budget.
                            self._inc("net_snapshot_replays")
                            return b""
                        if ts < prev_ts:
                            # Older-than-accepted: a reordered capture
                            # OR the requester's clock stepped backward
                            # (e.g. first NTP sync mid-recovery) —
                            # indistinguishable here, so count it as
                            # staleness, not attack. The requester side
                            # keeps its ts monotone within a process
                            # (fetch_snapshot), so honest lockout is
                            # bounded to a restart-plus-backward-step,
                            # itself capped by the freshness window.
                            self._inc("net_snapshot_stale_refusals")
                            return b""
                        last = self._snap_last_by.get(relayer, float("-inf"))
                        if now - last < self._snap_min_interval:
                            self._inc("net_snapshot_throttled")
                            return b""
                        # refill, then check one global egress token
                        gap = now - self._snap_tok_t
                        self._snap_tokens = min(
                            3.0,
                            self._snap_tokens
                            + gap / self._snap_min_interval,
                        )
                        self._snap_tok_t = now
                        if self._snap_tokens < 1.0:
                            self._inc("net_snapshot_global_throttled")
                            return b""
                        # All gates passed: serve, then commit throttle
                        # state only on SUCCESS — a failing provider
                        # must not burn the requester's token/slot/ts
                        # on an empty response.
                        blob = self._serve_cached()
                        if blob:
                            self._snap_tokens -= 1.0
                            self._snap_last_by[relayer] = now
                            self._snap_ts_by[relayer] = ts
                        return blob
                # No identity to throttle on: stricter global cap.
                with self._snap_lock:
                    gap = now - self._snap_last_global
                    if gap < 2.0 * self._snap_min_interval:
                        self._inc("net_snapshot_throttled")
                        return b""
                    blob = self._serve_cached()
                    if blob:
                        self._snap_last_global = now
                    return blob

            return grpc.unary_unary_rpc_method_handler(
                snap,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        return None

    def _serve_cached(self) -> bytes:
        """Serve the window blob, serialized at most once per TTL.

        Caller holds ``_snap_lock`` — concurrent misses at TTL expiry
        wait here instead of each re-serializing (the donor-side cost a
        request flood could otherwise amplify). Returns b"" (refusal)
        if the provider fails; the expired cache is released before the
        rebuild so a multi-MB stale blob isn't pinned across a failing
        provider."""
        now = time.monotonic()
        if (
            self._snap_cache is not None
            and now - self._snap_cache_t < self._snap_min_interval
        ):
            return self._snap_cache
        self._snap_cache = None
        try:
            blob = self._snapshot()
        except Exception:  # noqa: BLE001 — a failing provider must not
            # crash the server thread; empty = refuse. Negative-cache
            # the failure for one TTL: without it, every request during
            # a provider outage would invoke the (possibly expensive,
            # possibly repeatedly-failing) serialization at line rate —
            # unthrottled, since refusals deliberately charge no
            # throttle state.
            blob = b""
        self._snap_cache = blob
        self._snap_cache_t = time.monotonic()
        return blob


class GrpcTransport(Transport):
    """One endpoint per process.

    Unlike the in-memory broker (one shared object), each process owns a
    GrpcTransport bound to its listen address with a peer table of the
    other processes' addresses — the deployment shape of a real committee.
    """

    def __init__(
        self,
        index: int,
        listen_addr: str,
        peers: Dict[int, str],
        *,
        max_workers: int = 4,
        retries: int = 2,
        retry_backoff_s: float = 0.05,
        rpc_timeout_s: float = 5.0,
        metrics: Optional[Metrics] = None,
        auth=None,
        snapshot_provider: Optional[Callable[[], bytes]] = None,
        snapshot_min_interval_s: float = 1.0,
        snapshot_freshness_s: Optional[float] = 300.0,
        wall_clock: Callable[[], float] = time.time,
        send_fault: Optional[Callable[[int], float]] = None,
        log=None,
    ):
        from dag_rider_tpu.utils.slog import NOOP

        #: obs seam (round 16): peer up/down transitions emit typed
        #: events alongside the net_peer_* counters
        self.log = log if log is not None else NOOP
        self.index = index
        #: injectable wall clock for snapshot-request timestamps (the
        #: donor-side freshness gate compares against the same clock)
        self._wall = wall_clock
        self._peers = dict(peers)
        #: Optional FrameAuth (transport/auth.py): every outgoing frame
        #: carries a per-peer MAC and every incoming frame must carry a
        #: valid MAC for its *claimed* sender — the authenticated
        #: point-to-point links Bracha's quorum math assumes (round-3
        #: VERDICT missing #5: without this, any peer could forge other
        #: processes' ECHO/READY votes on the open Deliver endpoint).
        self._auth = auth
        self._handler: Optional[Handler] = None
        self._lock = threading.Lock()
        self._inbox: Deque[BroadcastMessage] = deque()
        self._channels: Dict[int, grpc.Channel] = {}
        self._stubs: Dict[int, Callable] = {}
        self._inflight: Dict[int, object] = {}
        self._inflight_seq = 0
        self._retries = retries
        self._retry_backoff_s = retry_backoff_s
        self._rpc_timeout_s = rpc_timeout_s
        self._timers: set = set()
        self._closed = False
        self._snap_req_ts = float("-inf")  # monotone request-ts floor
        #: injected WAN policy (cluster chaos): per-attempt delay/drop
        #: applied before the bytes reach gRPC — see :class:`WanFault`
        self._send_fault = send_fault
        #: late-bound client Submit sink (set_submit_sink)
        self._submit_fn: Optional[Callable[[bytes], bytes]] = None
        # Retry-backoff jitter (seeded per endpoint so scenarios replay):
        # a restarted peer coming back mid-burst must not see every
        # sender's exhausted retry chains re-fire in lockstep.
        self._jitter = random.Random(0x6A17 + index)
        # Observability (round-2 VERDICT weak #8: RpcErrors were silently
        # swallowed — a flaky peer degraded to permanent round lag with
        # zero counter movement). Shared with the process's Metrics when
        # one is passed / attached, so net_* counters appear in the same
        # snapshot as the consensus counters.
        self.metrics = metrics if metrics is not None else Metrics()
        # Failure detection (SURVEY §5): consecutive send failures per
        # peer; a peer is reported down after `down_after` in a row and
        # up again on the first success. Detection only — consensus
        # tolerates the faults; operators get the signal.
        self.down_after = 3
        self._consec_fail: Dict[int, int] = {}
        from concurrent import futures

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers(
            (
                _DeliverHandler(
                    self._on_rpc,
                    snapshot_provider,
                    auth,
                    snapshot_min_interval_s=snapshot_min_interval_s,
                    # operator knob: fleets with known clock skew widen
                    # the window (or None to disable freshness checking
                    # entirely) rather than wedge recovering nodes
                    snapshot_freshness_s=snapshot_freshness_s,
                    metrics_inc=self._inc,
                    wall_clock=wall_clock,
                    submit_sink=lambda: self._submit_fn,
                ),
            )
        )
        self.bound_port = self._server.add_insecure_port(listen_addr)
        self._server.start()

    def attach_metrics(self, metrics: Metrics) -> None:
        """Point net_* counters at an external Metrics (e.g. the owning
        Process's) so one snapshot shows transport + consensus health.
        Merge and swap happen under the transport lock — completion
        callbacks increment concurrently via :meth:`_inc`."""
        with self._lock:
            for name, val in list(self.metrics.counters.items()):
                metrics.inc(name, val)
            self.metrics = metrics

    def _inc(self, name: str) -> None:
        with self._lock:
            self.metrics.inc(name)

    # -- wire ----------------------------------------------------------------

    def _on_rpc(self, payload: bytes) -> None:
        if self._auth is not None:
            # Authenticated frame: <u32 relayer> || codec message || MAC,
            # MAC'd with the (relayer, me) pair key. The relayer is the
            # transport-level sender; it differs from msg.sender only for
            # relayed VALs (FETCH retransmissions and catch-up sync serve
            # other processes' original signed vertices — those are
            # self-certifying via the vertex signature + RBC digest
            # votes). For every control kind, msg.sender must BE the
            # authenticated relayer, or a single Byzantine peer could
            # forge other processes' ECHO/READY votes / sync identities.
            from dag_rider_tpu.transport.auth import TAG_BYTES

            if len(payload) < 4 + TAG_BYTES:
                self._inc("net_auth_rejects")
                return
            (relayer,) = struct.unpack_from("<I", payload)
            body, tag = payload[4:-TAG_BYTES], payload[-TAG_BYTES:]
            if not self._auth.check(relayer, body, tag):
                self._inc("net_auth_rejects")
                return
            try:
                msg, _ = codec.decode_message(body)
            except Exception:
                return  # malformed bytes from a Byzantine peer: drop
            if msg.kind != "val" and msg.sender != relayer:
                self._inc("net_auth_rejects")
                return
        else:
            try:
                msg, _ = codec.decode_message(payload)
            except Exception:
                return  # malformed bytes from a Byzantine peer: drop
        with self._lock:
            self._inbox.append(msg)

    def _stub(self, peer: int):
        # Called from the owner thread AND retry-timer threads: channel
        # creation must be locked or two threads can race a first send to
        # the same peer and leak the losing channel.
        with self._lock:
            if peer not in self._stubs:
                chan = grpc.insecure_channel(self._peers[peer])
                self._channels[peer] = chan
                self._stubs[peer] = chan.unary_unary(
                    _METHOD,
                    request_serializer=_identity,
                    response_deserializer=_identity,
                )
            return self._stubs[peer]

    # -- Transport interface -------------------------------------------------

    def subscribe(self, index: int, handler: Handler) -> None:
        if index != self.index:
            raise ValueError(
                f"GrpcTransport {self.index} can only host its own process"
            )
        if self._handler is not None:
            raise ValueError("already subscribed")
        self._handler = handler

    def unsubscribe(self) -> None:
        """Release the process slot so a rebuilt state machine can
        subscribe (corrupt-checkpoint recovery swaps in a fresh
        Process over the same live socket)."""
        self._handler = None

    def broadcast(self, msg: BroadcastMessage) -> None:
        payload = codec.encode_message(msg)
        if self._auth is not None:
            prefix = struct.pack("<I", self.index)
            for peer in sorted(self._peers):
                if peer == self.index:
                    continue
                self._send(
                    peer,
                    prefix + payload + self._auth.tag(peer, payload),
                    attempt=0,
                )
            return
        for peer in sorted(self._peers):
            if peer == self.index:
                continue
            self._send(peer, payload, attempt=0)

    #: keep this enqueue OUT of honest protocol routing
    #: (base.resolve_unicast): single-copy sync serves over a real
    #: socket lose whole patience windows to transient send failures
    #: during recovery — measured as a restarted node chasing a moving
    #: head it never caught. The Byzantine seam ignores this gate.
    protocol_unicast = False

    def enqueue(self, dest: int, msg: BroadcastMessage) -> None:
        """Point-to-point send — the per-destination seam Byzantine
        behaviors resolve (consensus/adversary._resolve_enqueue), so
        selective strategies like ``withhold`` stay per-destination
        across a real process boundary instead of degrading to
        broadcast-or-nothing."""
        if dest == self.index or dest not in self._peers:
            return
        payload = codec.encode_message(msg)
        if self._auth is not None:
            payload = (
                struct.pack("<I", self.index)
                + payload
                + self._auth.tag(dest, payload)
            )
        self._send(dest, payload, attempt=0)

    def set_submit_sink(self, fn: Optional[Callable[[bytes], bytes]]) -> None:
        """Open (or close, with None) the client Submit front door:
        ``fn`` receives the raw request bytes and returns the response
        bytes. Wired late by the cluster node runner — the sink needs
        the fully built node, which needs this transport first."""
        self._submit_fn = fn

    def _send(self, peer: int, payload: bytes, attempt: int) -> None:
        if self._closed:
            return
        if self._send_fault is not None:
            verdict = self._send_fault(peer)
            if verdict < 0:
                # injected WAN loss: the attempt never leaves the host.
                # Deliberately NOT charged to the failure detector — a
                # lossy link is not a down peer, and consensus recovers
                # through later broadcasts / anti-entropy.
                self._inc("net_wan_drops")
                return
            if verdict > 0:
                self._inc("net_wan_delays")
                timer = threading.Timer(
                    verdict,
                    lambda: (
                        self._timers.discard(timer),
                        self._send_now(peer, payload, attempt),
                    ),
                )
                timer.daemon = True
                self._timers.add(timer)
                timer.start()
                return
        self._send_now(peer, payload, attempt)

    def _send_now(self, peer: int, payload: bytes, attempt: int) -> None:
        if self._closed:
            return
        self._inc("net_sends")
        try:
            # async send; the future must be retained until it settles
            # (grpc cancels calls whose handle is dropped). Consensus
            # tolerates drops — a missing vertex only delays admission
            # until a later broadcast covers it — but every failure is
            # counted and retried with backoff before giving up.
            fut = self._stub(peer).future(payload, timeout=self._rpc_timeout_s)
        except (grpc.RpcError, ValueError):
            # ValueError: update_peer closed the cached channel between
            # _stub() and .future() — same remedy as an RPC error (the
            # retry re-resolves through _stub, which builds the new
            # channel)
            self._on_failure(peer, payload, attempt)
            return
        with self._lock:
            self._inflight_seq += 1
            key = self._inflight_seq
            self._inflight[key] = fut
        fut.add_done_callback(
            lambda f, k=key, p=peer, a=attempt: self._on_done(f, k, p, payload, a)
        )

    def _on_done(self, fut, key: int, peer: int, payload: bytes, attempt: int) -> None:
        with self._lock:
            self._inflight.pop(key, None)
        if self._closed:
            # close() cancels in-flight calls; a clean shutdown must not
            # leave the counter signature of a flaky peer behind.
            return
        try:
            exc = fut.exception()
        except Exception:  # cancelled: treat as failure
            exc = fut
        if exc is None:
            with self._lock:
                self.metrics.inc("net_sends_ok")
                was_down = self._consec_fail.get(peer, 0) >= self.down_after
                self._consec_fail[peer] = 0
            if was_down:
                self._inc("net_peer_recovered")
                self.log.event("net_peer_recovered", peer=peer)
            return
        self._on_failure(peer, payload, attempt)

    def _on_failure(self, peer: int, payload: bytes, attempt: int) -> None:
        if self._closed:
            return
        if attempt >= self._retries:
            # The failure detector counts *logical messages* whose whole
            # retry chain was exhausted — a single message's transient
            # retry burst must not trip the down threshold by itself.
            with self._lock:
                self.metrics.inc("net_send_errors")
                self.metrics.inc("net_drops")
                self._consec_fail[peer] = self._consec_fail.get(peer, 0) + 1
                fails = self._consec_fail[peer]
                just_down = fails == self.down_after
                # Channel recycle for restart recovery: once a peer
                # trips down (and every 8th exhausted chain after),
                # drop the cached channel so a later send re-dials
                # fresh. A peer that died and came back ON THE SAME
                # ADDRESS then reconnects within a few chains instead
                # of waiting out gRPC's internal subchannel backoff
                # (up to ~2 min idle after a long outage) — and the old
                # channel is closed, not leaked. Throttled: re-dialing
                # on EVERY chain while the peer stays dead churns a
                # fresh channel (threads, fds, connect timeouts) per
                # logical message and measurably drags the live quorum.
                chan = None
                if fails == self.down_after or (
                    fails > self.down_after and fails % 8 == 0
                ):
                    chan = self._channels.pop(peer, None)
                    self._stubs.pop(peer, None)
            if chan is not None:
                chan.close()
            if just_down:
                self._inc("net_peer_down")
                self.log.event(
                    "net_peer_down",
                    peer=peer,
                    consecutive=self.down_after,
                )
            return
        with self._lock:
            self.metrics.inc("net_send_errors")
            self.metrics.inc("net_retries")
            # +/-25% seeded jitter: a restarted peer must not absorb
            # every sender's backed-off retries in one synchronized
            # thundering burst.
            jitter = 0.75 + 0.5 * self._jitter.random()
        delay = self._retry_backoff_s * (2**attempt) * jitter
        timer = threading.Timer(
            delay, lambda: (self._timers.discard(timer),
                            self._send(peer, payload, attempt + 1))
        )
        timer.daemon = True
        self._timers.add(timer)
        timer.start()

    # -- pump (same contract as InMemoryTransport) ---------------------------

    def pump_one(self) -> bool:
        with self._lock:
            if not self._inbox:
                return False
            msg = self._inbox.popleft()
        if self._handler is not None:
            self._handler(msg)
        return True

    def pump(self, max_messages: Optional[int] = None) -> int:
        delivered = 0
        while (
            max_messages is None or delivered < max_messages
        ) and self.pump_one():
            delivered += 1
        return delivered

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._inbox)

    def fetch_snapshot(
        self, peer: int, timeout_s: float = 30.0
    ) -> Optional[bytes]:
        """Blocking state-transfer fetch from one peer; None on failure
        or refusal (empty response). Caller validates the bytes
        (checkpoint.restore_from_snapshot) and tries other peers."""
        if peer == self.index or peer not in self._peers:
            return None
        self._inc("net_snapshot_fetches")
        req = b""
        if self._auth is not None:
            # Coarse wall-clock timestamp under the MAC: the donor
            # rejects stale requests, so a captured frame on plaintext
            # gRPC cannot be replayed indefinitely to burn its budget.
            # Kept strictly monotone within this process so a backward
            # clock step (first NTP sync mid-recovery) cannot make our
            # own requests read as stale/replayed at the donor.
            with self._lock:
                t = max(self._wall(), self._snap_req_ts + 1e-3)
                self._snap_req_ts = t
            ts = struct.pack("<d", t)
            req = (
                struct.pack("<I", self.index)
                + ts
                + self._auth.tag(peer, _SNAP_DOMAIN + ts)
            )
        try:
            self._stub(peer)  # ensures the peer channel exists (locked)
            with self._lock:
                chan = self._channels.get(peer)
            if chan is None:  # update_peer raced the fetch: treat as fail
                self._inc("net_snapshot_errors")
                return None
            call = chan.unary_unary(
                _SNAPSHOT_METHOD,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            blob = call(req, timeout=timeout_s)
        except (grpc.RpcError, ValueError):
            # ValueError: update_peer closed the channel mid-fetch — same
            # contract as an RPC failure (caller tries the next peer)
            self._inc("net_snapshot_errors")
            return None
        return bytes(blob) if blob else None

    def update_peer(self, peer: int, addr: str) -> None:
        """Repoint a peer to a new address, dropping the cached channel.

        Deployments normally use STABLE addresses (the node config's
        peer table), where a restarted peer reappears on the same
        host:port and the existing channel reconnects by itself. This
        exists for the dynamic case (ephemeral ports, rescheduled pods):
        without it, the cached stub keeps sending into the dead old
        address forever while the peer table lies about the new one.
        """
        with self._lock:
            self._peers[peer] = addr
            chan = self._channels.pop(peer, None)
            self._stubs.pop(peer, None)
            # _consec_fail is deliberately kept: a peer marked down stays
            # down until a send SUCCEEDS against the new address, so
            # peer_status honors its contract and net_peer_recovered
            # fires exactly once on the actual recovery.
        if chan is not None:
            chan.close()

    def peer_status(self) -> Dict[int, str]:
        """Failure-detector view: peer -> "up" | "down" (down = at least
        ``down_after`` consecutive send failures with no success since)."""
        with self._lock:
            return {
                peer: (
                    "down"
                    if self._consec_fail.get(peer, 0) >= self.down_after
                    else "up"
                )
                for peer in self._peers
                if peer != self.index
            }

    def close(self) -> None:
        self._closed = True
        for t in list(self._timers):
            t.cancel()
        self._server.stop(grace=None)
        with self._lock:
            channels = list(self._channels.values())
        for chan in channels:
            chan.close()
