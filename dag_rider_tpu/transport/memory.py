"""In-memory broadcast broker with a deterministic pump.

Counterpart of the reference's ``Transport`` broker
(``process/transport.go:11-32``) with D12 fixed:

- one lock guards both ``subscribe`` and ``broadcast`` (the reference's
  ``Broadcast`` iterates ``subs`` lockless while ``Subscribe`` appends);
- the sender is excluded from fan-out (a process inserts its own vertex
  directly — the reference loops messages back to the sender);
- delivery is decoupled from broadcast: ``broadcast`` only enqueues, and a
  pump (:meth:`pump` / :meth:`pump_one`) drains the queue FIFO. This gives
  deterministic, replayable schedules for tests — the reference's
  channel-fanout schedule is whatever the Go runtime decides.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Tuple

from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.base import Handler, Transport


class InMemoryTransport(Transport):
    """N processes in one OS process, zero networking — the simulation
    backend for integration tests (SURVEY.md §4 "multi-node story")."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[int, Handler] = {}
        self._queue: Deque[Tuple[int, BroadcastMessage]] = deque()
        self._fanout: list[int] = []  # sorted handler ids, cached
        self.delivered_count = 0

    def subscribe(self, index: int, handler: Handler) -> None:
        with self._lock:
            if index in self._handlers:
                raise ValueError(f"process {index} already subscribed")
            self._handlers[index] = handler
            self._fanout = sorted(self._handlers)

    def broadcast(self, msg: BroadcastMessage) -> None:
        with self._lock:
            sender = msg.sender
            self._queue.extend(
                (dest, msg) for dest in self._fanout if dest != sender
            )

    # -- composition hooks (used by FaultyTransport / schedulers) ----------

    def subscribers(self) -> list[int]:
        with self._lock:
            return sorted(self._handlers)

    def enqueue(self, dest: int, msg: BroadcastMessage) -> None:
        """Queue a message for one destination (bypassing fan-out) — the
        seam fault-injection wrappers compose through."""
        with self._lock:
            if dest not in self._handlers:
                raise KeyError(f"no subscriber {dest}")
            self._queue.append((dest, msg))

    def drain_pending(self) -> list[Tuple[int, BroadcastMessage]]:
        """Atomically remove and return all queued (dest, msg) pairs —
        schedulers reorder these and requeue."""
        with self._lock:
            items = list(self._queue)
            self._queue.clear()
        return items

    def requeue(self, items) -> None:
        with self._lock:
            self._queue.extend(items)

    # -- pump --------------------------------------------------------------

    def pump_one(self) -> bool:
        """Deliver the oldest queued message. Returns False if idle."""
        with self._lock:
            if not self._queue:
                return False
            dest, msg = self._queue.popleft()
            handler = self._handlers[dest]
        handler(msg)  # outside the lock: handlers may broadcast
        self.delivered_count += 1
        return True

    def pump(self, max_messages: int | None = None) -> int:
        """Deliver until the queue drains (or ``max_messages``).

        Messages are popped in batches under ONE lock acquisition and
        delivered outside it — per-message lock round-trips were ~10% of
        the n=256 host profile. Handlers may broadcast re-entrantly
        (their sends append under the lock and are picked up by the next
        batch pop), and delivery order is unchanged: batches pop from
        the head in FIFO order.
        """
        delivered = 0
        handlers = self._handlers
        while max_messages is None or delivered < max_messages:
            want = 1024 if max_messages is None else min(
                1024, max_messages - delivered
            )
            with self._lock:
                batch = [
                    self._queue.popleft()
                    for _ in range(min(want, len(self._queue)))
                ]
            if not batch:
                break
            done = 0
            try:
                for dest, msg in batch:
                    handlers[dest](msg)
                    done += 1
            finally:
                # A handler that raises mid-batch must lose at most the
                # ONE in-flight message (pump_one semantics): requeue the
                # undelivered tail at the head, count the delivered
                # prefix.
                if done < len(batch):
                    with self._lock:
                        self._queue.extendleft(reversed(batch[done + 1 :]))
                self.delivered_count += done
                delivered += done
        return delivered

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)
