"""In-memory broadcast broker with a deterministic pump.

Counterpart of the reference's ``Transport`` broker
(``process/transport.go:11-32``) with D12 fixed:

- one lock guards both ``subscribe`` and ``broadcast`` (the reference's
  ``Broadcast`` iterates ``subs`` lockless while ``Subscribe`` appends);
- the sender is excluded from fan-out (a process inserts its own vertex
  directly — the reference loops messages back to the sender);
- delivery is decoupled from broadcast: ``broadcast`` only enqueues, and a
  pump (:meth:`pump` / :meth:`pump_one`) drains the queue FIFO. This gives
  deterministic, replayable schedules for tests — the reference's
  channel-fanout schedule is whatever the Go runtime decides.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Tuple

from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.base import Handler, Transport


class InMemoryTransport(Transport):
    """N processes in one OS process, zero networking — the simulation
    backend for integration tests (SURVEY.md §4 "multi-node story")."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[int, Handler] = {}
        self._queue: Deque[Tuple[int, BroadcastMessage]] = deque()
        self._fanout: list[int] = []  # sorted handler ids, cached
        self.delivered_count = 0

    def subscribe(self, index: int, handler: Handler) -> None:
        with self._lock:
            if index in self._handlers:
                raise ValueError(f"process {index} already subscribed")
            self._handlers[index] = handler
            self._fanout = sorted(self._handlers)

    def broadcast(self, msg: BroadcastMessage) -> None:
        with self._lock:
            sender = msg.sender
            self._queue.extend(
                (dest, msg) for dest in self._fanout if dest != sender
            )

    # -- composition hooks (used by FaultyTransport / schedulers) ----------

    def subscribers(self) -> list[int]:
        with self._lock:
            return sorted(self._handlers)

    def enqueue(self, dest: int, msg: BroadcastMessage) -> None:
        """Queue a message for one destination (bypassing fan-out) — the
        seam fault-injection wrappers compose through."""
        with self._lock:
            if dest not in self._handlers:
                raise KeyError(f"no subscriber {dest}")
            self._queue.append((dest, msg))

    def drain_pending(self) -> list[Tuple[int, BroadcastMessage]]:
        """Atomically remove and return all queued (dest, msg) pairs —
        schedulers reorder these and requeue."""
        with self._lock:
            items = list(self._queue)
            self._queue.clear()
        return items

    def requeue(self, items) -> None:
        with self._lock:
            self._queue.extend(items)

    # -- pump --------------------------------------------------------------

    def pump_one(self) -> bool:
        """Deliver the oldest queued message. Returns False if idle."""
        with self._lock:
            if not self._queue:
                return False
            dest, msg = self._queue.popleft()
            handler = self._handlers[dest]
        handler(msg)  # outside the lock: handlers may broadcast
        self.delivered_count += 1
        return True

    def pump(self, max_messages: int | None = None) -> int:
        """Deliver until the queue drains (or ``max_messages``)."""
        delivered = 0
        while (max_messages is None or delivered < max_messages) and self.pump_one():
            delivered += 1
        return delivered

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)
