"""In-memory broadcast broker with a deterministic pump.

Counterpart of the reference's ``Transport`` broker
(``process/transport.go:11-32``) with D12 fixed:

- one lock guards both ``subscribe`` and ``broadcast`` (the reference's
  ``Broadcast`` iterates ``subs`` lockless while ``Subscribe`` appends);
- the sender is excluded from fan-out (a process inserts its own vertex
  directly — the reference loops messages back to the sender);
- delivery is decoupled from broadcast: ``broadcast`` only enqueues, and a
  pump (:meth:`pump` / :meth:`pump_one`) drains the queue FIFO. This gives
  deterministic, replayable schedules for tests — the reference's
  channel-fanout schedule is whatever the Go runtime decides.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.base import Handler, Transport


class InMemoryTransport(Transport):
    """N processes in one OS process, zero networking — the simulation
    backend for integration tests (SURVEY.md §4 "multi-node story")."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[int, Handler] = {}
        #: optional per-destination BATCH handlers (on_messages shape)
        #: used only by :meth:`pump_grouped`
        self._batch_handlers: Dict[int, Callable] = {}
        self._queue: Deque[Tuple[int, BroadcastMessage]] = deque()
        self._fanout: list[int] = []  # sorted handler ids, cached
        #: When True, :meth:`broadcast` queues ONE ``(-1, msg, start)``
        #: sentinel per send instead of n-1 ``(dest, msg)`` pairs; pumps
        #: expand it lazily in subscriber order (sender skipped) with
        #: budget-exact splitting, so delivery order and ``max_messages``
        #: boundaries are entry-for-entry identical to eager fan-out.
        #: Requires a subscriber set that is fixed before traffic flows
        #: (expansion reads the CURRENT fan-out list) — the Simulation
        #: flips it on only after construction wires every process.
        self.fanout_sentinel = False
        self.delivered_count = 0

    def subscribe(self, index: int, handler: Handler) -> None:
        with self._lock:
            if index in self._handlers:
                raise ValueError(f"process {index} already subscribed")
            self._handlers[index] = handler
            self._fanout = sorted(self._handlers)

    def subscribe_many(
        self, index: int, handler: Callable[[list], None]
    ) -> None:
        """Register a batch handler (one call, a list of messages) for a
        destination that already has a per-message subscription.
        :meth:`pump_grouped` prefers it for VAL runs; everything else
        still flows through the per-message handler."""
        with self._lock:
            if index not in self._handlers:
                raise KeyError(f"no subscriber {index}")
            self._batch_handlers[index] = handler

    def broadcast(self, msg: BroadcastMessage) -> None:
        with self._lock:
            if self.fanout_sentinel:
                self._queue.append((-1, msg, 0))
            else:
                sender = msg.sender
                self._queue.extend(
                    (dest, msg) for dest in self._fanout if dest != sender
                )

    def _pop_expanded(self, want: int) -> list:
        """Pop up to ``want`` deliverable ``(dest, msg)`` pairs off the
        queue head (call with the lock HELD), expanding fan-out
        sentinels in subscriber order. A sentinel that straddles the
        budget boundary is split: the delivered prefix joins the batch
        and a resumed sentinel for the remaining subscribers goes back
        at the head, so chunked pumping sees the exact same per-message
        boundaries as an eagerly fanned-out queue."""
        q = self._queue
        batch: list = []
        while q and len(batch) < want:
            e = q.popleft()
            if e[0] >= 0:
                batch.append(e)
                continue
            msg, start = e[1], e[2]
            sender = msg.sender
            pairs = [
                (i, d)
                for i, d in enumerate(self._fanout[start:], start)
                if d != sender
            ]
            room = want - len(batch)
            if len(pairs) > room:
                q.appendleft((-1, msg, pairs[room][0]))
                pairs = pairs[:room]
            batch.extend((d, msg) for _, d in pairs)
        return batch

    # -- composition hooks (used by FaultyTransport / schedulers) ----------

    def subscribers(self) -> list[int]:
        with self._lock:
            return sorted(self._handlers)

    def enqueue(self, dest: int, msg: BroadcastMessage) -> None:
        """Queue a message for one destination (bypassing fan-out) — the
        seam fault-injection wrappers compose through."""
        with self._lock:
            if dest not in self._handlers:
                raise KeyError(f"no subscriber {dest}")
            self._queue.append((dest, msg))

    def drain_pending(self) -> list[Tuple[int, BroadcastMessage]]:
        """Atomically remove and return all queued (dest, msg) pairs —
        schedulers reorder these and requeue. Sentinels expand here:
        schedulers address individual copies."""
        with self._lock:
            items: list = []
            for e in self._queue:
                if e[0] >= 0:
                    items.append(e)
                else:
                    msg = e[1]
                    items.extend(
                        (d, msg)
                        for d in self._fanout[e[2] :]
                        if d != msg.sender
                    )
            self._queue.clear()
        return items

    def requeue(self, items) -> None:
        with self._lock:
            self._queue.extend(items)

    # -- pump --------------------------------------------------------------

    def pump_one(self) -> bool:
        """Deliver the oldest queued message. Returns False if idle."""
        with self._lock:
            batch = self._pop_expanded(1)
            if not batch:
                return False
            dest, msg = batch[0]
            handler = self._handlers[dest]
        handler(msg)  # outside the lock: handlers may broadcast
        self.delivered_count += 1
        return True

    def pump(self, max_messages: int | None = None) -> int:
        """Deliver until the queue drains (or ``max_messages``).

        Messages are popped in batches under ONE lock acquisition and
        delivered outside it — per-message lock round-trips were ~10% of
        the n=256 host profile. Handlers may broadcast re-entrantly
        (their sends append under the lock and are picked up by the next
        batch pop), and delivery order is unchanged: batches pop from
        the head in FIFO order.
        """
        delivered = 0
        handlers = self._handlers
        while max_messages is None or delivered < max_messages:
            want = 1024 if max_messages is None else min(
                1024, max_messages - delivered
            )
            with self._lock:
                batch = self._pop_expanded(want)
            if not batch:
                break
            done = 0
            try:
                for dest, msg in batch:
                    handlers[dest](msg)
                    done += 1
            finally:
                # A handler that raises mid-batch must lose at most the
                # ONE in-flight message (pump_one semantics): requeue the
                # undelivered tail at the head, count the delivered
                # prefix.
                if done < len(batch):
                    with self._lock:
                        self._queue.extendleft(reversed(batch[done + 1 :]))
                self.delivered_count += done
                delivered += done
        return delivered

    def _flush_groups(self, groups: Dict[int, list]) -> int:
        """Deliver pending VAL runs, one batch call per destination in
        first-appearance order; entries leave ``groups`` only once
        delivered, so on an exception the leftover dict is exactly what
        the caller must requeue."""
        count = 0
        while groups:
            dest = next(iter(groups))
            msgs = groups[dest]
            bh = self._batch_handlers.get(dest)
            if bh is not None:
                bh(msgs)
            else:
                h = self._handlers[dest]
                for m in msgs:
                    h(m)
            del groups[dest]
            count += len(msgs)
        return count

    def pump_grouped(self, max_messages: int | None = None) -> int:
        """Deliver like :meth:`pump`, but each run of consecutive VAL
        messages is handed out as ONE batch call per destination
        (:meth:`subscribe_many`), destinations in first-appearance
        order; any non-VAL message is a barrier — pending runs flush,
        then the control message is delivered singly in its exact FIFO
        queue position.

        Caller contract: grouping permutes VAL delivery order ACROSS
        destinations within a run (per-destination FIFO is always
        preserved), which is invisible exactly when VAL delivery has no
        transport side effects — processes in deferred-step vector
        mode, where delivery only queues to the inbox. The Simulation
        checks that before choosing this pump. On a raising handler the
        in-flight control message is lost (scalar pump semantics) or
        the in-flight VAL group is requeued whole (redelivery is safe:
        processes dedup), and everything not yet delivered requeues at
        the head.
        """
        delivered = 0
        handlers = self._handlers
        while max_messages is None or delivered < max_messages:
            # Unlike :meth:`pump`'s 1024 chunk, take the whole remaining
            # budget per chunk: chunk size is invisible (popped batches
            # are FIFO and re-entrant broadcasts append BEHIND the
            # pre-existing queue either way), and big chunks turn the
            # per-destination runs from slivers into real batches.
            want = 65536 if max_messages is None else min(
                65536, max_messages - delivered
            )
            # Pop-and-group under ONE lock hold: VAL copies land
            # straight in their per-destination runs (fan-out sentinels
            # append their copies without ever materializing (dest, msg)
            # pairs), and the first control message ends the chunk — it
            # is delivered singly after the pending runs flush, which is
            # exactly the barrier position it held in the queue.
            groups: Dict[int, list] = {}
            ctrl: Optional[Tuple[int, BroadcastMessage]] = None
            got = 0
            with self._lock:
                q = self._queue
                fanout = self._fanout
                while q and got < want:
                    e = q.popleft()
                    d0 = e[0]
                    if d0 >= 0:
                        msg = e[1]
                        if msg.kind != "val":
                            ctrl = e
                            got += 1
                            break
                        g = groups.get(d0)
                        if g is None:
                            g = groups[d0] = []
                        g.append(msg)
                        got += 1
                        continue
                    msg, start = e[1], e[2]
                    sender = msg.sender
                    if msg.kind != "val":
                        # control broadcast: re-materialize its copies
                        # at the head; the next iterations barrier them
                        # one by one in FIFO position
                        q.extendleft(
                            reversed(
                                [
                                    (d, msg)
                                    for d in fanout[start:]
                                    if d != sender
                                ]
                            )
                        )
                        continue
                    i = start
                    last = len(fanout)
                    room = want - got
                    while i < last and room:
                        d = fanout[i]
                        i += 1
                        if d == sender:
                            continue
                        g = groups.get(d)
                        if g is None:
                            g = groups[d] = []
                        g.append(msg)
                        got += 1
                        room -= 1
                    if i < last:
                        # budget hit mid-fan-out: resume sentinel keeps
                        # the remaining copies at the exact queue head
                        q.appendleft((-1, msg, i))
            if not got:
                break
            done = 0
            ctrl_pending = ctrl is not None
            try:
                done += self._flush_groups(groups)
                if ctrl is not None:
                    # in flight from here: lost if its handler raises
                    # (scalar pump semantics)
                    ctrl_pending = False
                    handlers[ctrl[0]](ctrl[1])
                    done += 1
            finally:
                undelivered = [
                    (d, m) for d, msgs in groups.items() for m in msgs
                ]
                if ctrl_pending:
                    # flush raised before the barrier was in flight:
                    # the control goes back AFTER the leftover runs it
                    # followed in the queue
                    undelivered.append(ctrl)
                if undelivered:
                    with self._lock:
                        self._queue.extendleft(reversed(undelivered))
                self.delivered_count += done
                delivered += done
        return delivered

    @property
    def pending(self) -> int:
        with self._lock:
            if not self.fanout_sentinel:
                return len(self._queue)
            n = 0
            for e in self._queue:
                if e[0] >= 0:
                    n += 1
                else:
                    msg = e[1]
                    n += sum(
                        1
                        for d in self._fanout[e[2] :]
                        if d != msg.sender
                    )
            return n
