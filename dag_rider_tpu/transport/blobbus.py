"""Minimal authenticated byte bus for pre-consensus protocols.

The consensus Transport (net.py) carries the framework's canonical
BroadcastMessage codec; setup-time protocols — today the joint-Feldman
DKG (crypto/dkg.py), whose traffic is commitment vectors and encrypted
scalars, not vertices — need a plain (sender, kind, payload) channel.
This is that channel: the same dependency-free generic-handler gRPC
pattern as net.py, one unary method, with the same FrameAuth MAC wrap
(direction-bound, transport/auth.py) when auth is configured.

Deliberately simpler than GrpcTransport: no retry ladder (setup tools
poll-and-retransmit at the protocol layer), no failure detector, no
pump thread — callers poll :meth:`recv`.
"""

from __future__ import annotations

import struct
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import grpc

_SERVICE = "dagrider.BlobBus"
_METHOD = f"/{_SERVICE}/Post"
_identity = lambda b: b  # noqa: E731


def _frame(sender: int, kind: str, payload: bytes) -> bytes:
    k = kind.encode()
    return struct.pack("<IH", sender, len(k)) + k + payload


def _unframe(data: bytes) -> Optional[Tuple[int, str, bytes]]:
    if len(data) < 6:
        return None
    sender, klen = struct.unpack_from("<IH", data)
    if len(data) < 6 + klen:
        return None
    try:
        kind = data[6 : 6 + klen].decode()
    except UnicodeDecodeError:
        return None
    return sender, kind, data[6 + klen :]


class BlobBus:
    """One endpoint per participant; peers maps index -> host:port."""

    def __init__(
        self,
        index: int,
        listen_addr: str,
        peers: Dict[int, str],
        *,
        auth=None,
        max_workers: int = 4,
    ):
        self.index = index
        self._peers = dict(peers)
        self._auth = auth
        self._lock = threading.Lock()
        self._inbox: Deque[Tuple[int, str, bytes]] = deque()
        self._stubs: Dict[int, object] = {}
        self._channels: Dict[int, grpc.Channel] = {}
        from concurrent import futures

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )

        bus = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method != _METHOD:
                    return None

                def unary(request: bytes, context) -> bytes:
                    bus._on_post(request)
                    return b"\x01"

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )

        self._server.add_generic_rpc_handlers((_Handler(),))
        self.bound_port = self._server.add_insecure_port(listen_addr)
        self._server.start()

    def _on_post(self, data: bytes) -> None:
        if self._auth is not None:
            from dag_rider_tpu.transport.auth import TAG_BYTES

            if len(data) < TAG_BYTES:
                return
            body, tag = data[:-TAG_BYTES], data[-TAG_BYTES:]
            parsed = _unframe(body)
            if parsed is None:
                return
            # the frame's own sender stamp is the MAC'd claimed sender —
            # a DKG complaint/reveal must be attributable
            if not self._auth.check(parsed[0], body, tag):
                return
        else:
            parsed = _unframe(data)
            if parsed is None:
                return
        with self._lock:
            self._inbox.append(parsed)

    def _stub(self, peer: int):
        with self._lock:
            if peer not in self._stubs:
                chan = grpc.insecure_channel(self._peers[peer])
                self._channels[peer] = chan
                self._stubs[peer] = chan.unary_unary(
                    _METHOD,
                    request_serializer=_identity,
                    response_deserializer=_identity,
                )
            return self._stubs[peer]

    def send(self, peer: int, kind: str, payload: bytes) -> bool:
        if peer not in self._peers or peer == self.index:
            return False  # incomplete peer table: a verdict, not a crash
        body = _frame(self.index, kind, payload)
        if self._auth is not None:
            body += self._auth.tag(peer, body)
        try:
            self._stub(peer)(body, timeout=5.0)
            return True
        except grpc.RpcError:
            return False  # protocol layer retransmits

    def broadcast(self, kind: str, payload: bytes) -> int:
        ok = 0
        for peer in sorted(self._peers):
            if peer != self.index and self.send(peer, kind, payload):
                ok += 1
        return ok

    def recv(self) -> List[Tuple[int, str, bytes]]:
        with self._lock:
            out = list(self._inbox)
            self._inbox.clear()
        return out

    def wait(self, seconds: float) -> None:
        time.sleep(seconds)

    def close(self) -> None:
        self._server.stop(grace=None)
        with self._lock:
            chans = list(self._channels.values())
        for c in chans:
            c.close()
