"""Pairwise-MAC frame authentication for networked transports.

Bracha reliable broadcast is proven under *authenticated point-to-point
links* (see transport/rbc.py): process j must not be able to inject an
ECHO/READY/FETCH/sync frame that appears to come from process i. The
reference has no networking at all; rounds 1-3's gRPC transport accepted
any payload on ``Deliver`` and trusted ``msg.sender`` (round-3 VERDICT
missing #5) — over a network, a single Byzantine peer could forge a READY
quorum and void the 2f+1 intersection argument.

The authenticated-links primitive is a MAC per ordered pair, NOT a
transferable signature: votes are only ever *consumed* by their direct
receiver (quorum counting is local), so nothing needs third-party
verifiability, and a pairwise HMAC-SHA256 costs ~1 us per frame where the
host Ed25519 costs ~9 ms — per-frame signatures would dominate the whole
consensus host path. Vertex payloads themselves stay Ed25519-signed by
their author (the Verifier seam), which is the transferable part the
protocol actually relies on.

Keys come from a dealer (``FrameAuth.derive``) — the same trust model the
threshold-BLS coin already uses (crypto/threshold.py ``ThresholdKeys``):
``k_ij = HMAC(master, "pair" || min(i,j) || max(i,j))``, each node holding
only its own row. The pair key is symmetric (k_ij == k_ji), so the MAC'd
data binds the *direction* — ``sender || receiver || payload`` — making the
primitive self-contained against reflection (bouncing i's frame back at i)
rather than relying on downstream per-kind sender checks. Replayed frames
in the same direction still verify (the MAC covers content, not
freshness); that is safe here because every consumer is idempotent or
rate-limited: Bracha votes land in per-(slot, digest) *sets*, and sync
serves are cooldown-throttled (Process._serve_sync).
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import Dict, List

TAG_BYTES = 32
# v2: the MAC'd data grew direction bytes (sender || receiver). The domain
# bump makes the wire incompatibility with v1 binaries explicit and
# greppable — mixed-version frames fail auth either way (rolling upgrades
# must replace the whole committee), but a version tag beats a silent
# net_auth_rejects storm that reads like an attack.
_DOMAIN = b"dagrider-frame-v2"


def _pair_key(master: bytes, i: int, j: int) -> bytes:
    lo, hi = (i, j) if i < j else (j, i)
    return hmac.new(
        master, b"pair" + struct.pack("<II", lo, hi), hashlib.sha256
    ).digest()


class FrameAuth:
    """One node's MAC state: its index plus the key for every peer."""

    def __init__(self, index: int, keys: Dict[int, bytes]):
        self.index = index
        self._keys = dict(keys)

    @staticmethod
    def derive(master: bytes, n: int) -> List["FrameAuth"]:
        """Dealer: one FrameAuth per node from a shared master secret."""
        return [
            FrameAuth(
                i,
                {j: _pair_key(master, i, j) for j in range(n) if j != i},
            )
            for i in range(n)
        ]

    @staticmethod
    def for_node(master: bytes, index: int, n: int) -> "FrameAuth":
        """One node's row (what a deployment config hands each process)."""
        return FrameAuth(
            index,
            {j: _pair_key(master, index, j) for j in range(n) if j != index},
        )

    def tag(self, peer: int, payload: bytes) -> bytes:
        """MAC for a frame this node sends to ``peer``.

        The direction (sender=self, receiver=peer) is bound into the MAC'd
        data so the symmetric pair key cannot be used to reflect a frame
        back at its author or redirect it to a third party.
        """
        return hmac.new(
            self._keys[peer],
            _DOMAIN + struct.pack("<II", self.index, peer) + payload,
            hashlib.sha256,
        ).digest()

    def check(self, claimed_sender: int, payload: bytes, tag: bytes) -> bool:
        """Verify a received frame against the claimed sender's pair key,
        with this node bound as the receiver. Constant-time compare;
        unknown senders fail closed."""
        key = self._keys.get(claimed_sender)
        if key is None or len(tag) != TAG_BYTES:
            return False
        want = hmac.new(
            key,
            _DOMAIN + struct.pack("<II", claimed_sender, self.index) + payload,
            hashlib.sha256,
        ).digest()
        return hmac.compare_digest(want, tag)
