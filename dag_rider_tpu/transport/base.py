"""Transport — the pluggable broadcast boundary.

The reference's only plugin seam (``process/transport.go:6-9``): a process
receives a Transport at construction and never touches the network
otherwise. We keep that seam and fix its defects (SURVEY.md D12):

- delivery is via registered per-process handlers, not bare channels;
- no delivery to the sender (a process inserts its own vertex directly);
- implementations must be race-free between ``broadcast`` and ``subscribe``.

Implementations: in-memory broker with a deterministic pump
(:mod:`dag_rider_tpu.transport.memory`), fault-injection wrapper
(:mod:`dag_rider_tpu.transport.faults`), and a socket transport for
multi-host deployments (:mod:`dag_rider_tpu.transport.net`).
"""

from __future__ import annotations

import abc
from typing import Callable

from dag_rider_tpu.core.types import BroadcastMessage

Handler = Callable[[BroadcastMessage], None]


class Transport(abc.ABC):
    """Reliable point-to-all broadcast abstraction (r_bcast / r_deliver).

    Like the reference (``transport.go:5``), the transport itself is the
    "reliable" layer by fiat for in-process deployments; Byzantine-grade
    reliable broadcast (echo/ready amplification) layers on top — see
    :mod:`dag_rider_tpu.transport.rbc`.
    """

    @abc.abstractmethod
    def broadcast(self, msg: BroadcastMessage) -> None:
        """Queue ``msg`` for delivery to every subscriber except the sender."""

    @abc.abstractmethod
    def subscribe(self, index: int, handler: Handler) -> None:
        """Register ``handler`` as process ``index``'s delivery callback."""
