"""Transport — the pluggable broadcast boundary.

The reference's only plugin seam (``process/transport.go:6-9``): a process
receives a Transport at construction and never touches the network
otherwise. We keep that seam and fix its defects (SURVEY.md D12):

- delivery is via registered per-process handlers, not bare channels;
- no delivery to the sender (a process inserts its own vertex directly);
- implementations must be race-free between ``broadcast`` and ``subscribe``.

Implementations: in-memory broker with a deterministic pump
(:mod:`dag_rider_tpu.transport.memory`), fault-injection wrapper
(:mod:`dag_rider_tpu.transport.faults`), and a socket transport for
multi-host deployments (:mod:`dag_rider_tpu.transport.net`).
"""

from __future__ import annotations

import abc
from typing import Callable, Optional

from dag_rider_tpu.core.types import BroadcastMessage

Handler = Callable[[BroadcastMessage], None]


def resolve_unicast(transport) -> Optional[Callable]:
    """Find a per-destination send seam for PROTOCOL traffic: unwrap
    ``.inner`` chains until something exposes ``enqueue(dest, msg)``
    (InMemoryTransport does; FaultyTransport composes through it —
    handlers registered with the inner broker are the fault-wrapped
    ones, so unicast sends still pay delivery-time fault rolls).

    Stops with None at any layer that declares ``requires_broadcast``
    (RbcTransport): Bracha's totality/catch-up depends on every peer
    seeing repeat VALs, so honest senders must not tunnel past it.
    A layer may also declare ``protocol_unicast = False`` to keep its
    ``enqueue`` OUT of honest protocol routing while still exposing it
    to the Byzantine adversary seam (GrpcTransport: single-copy sync
    over a real socket loses whole patience windows to send failures
    during recovery, so honest serves keep broadcast redundancy).
    (The adversary seam in consensus/adversary.py unwraps
    unconditionally — NOT honoring these contracts is the attack.)

    Returns None when the stack has no such seam; callers degrade to
    broadcast."""
    seen: set = set()
    tp = transport
    while tp is not None and id(tp) not in seen:
        seen.add(id(tp))
        if getattr(tp, "requires_broadcast", False):
            return None
        fn = getattr(tp, "enqueue", None)
        if callable(fn) and getattr(tp, "protocol_unicast", True):
            return fn
        tp = getattr(tp, "inner", None)
    return None


class Transport(abc.ABC):
    """Reliable point-to-all broadcast abstraction (r_bcast / r_deliver).

    Like the reference (``transport.go:5``), the transport itself is the
    "reliable" layer by fiat for in-process deployments; Byzantine-grade
    reliable broadcast (echo/ready amplification) layers on top — see
    :mod:`dag_rider_tpu.transport.rbc`.
    """

    @abc.abstractmethod
    def broadcast(self, msg: BroadcastMessage) -> None:
        """Queue ``msg`` for delivery to every subscriber except the sender."""

    @abc.abstractmethod
    def subscribe(self, index: int, handler: Handler) -> None:
        """Register ``handler`` as process ``index``'s delivery callback."""
