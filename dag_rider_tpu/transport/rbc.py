"""Byzantine reliable broadcast (Bracha echo/ready) over any Transport.

The reference's broker is "reliable" by fiat (``process/transport.go:5``):
an equivocating sender can hand *different signed vertices* to different
honest processes, and nothing reconciles them. This layer closes that gap
with Bracha's protocol (Bracha 1987, "Asynchronous Byzantine agreement
protocols"), per (round, source) slot:

- VAL: the sender's vertex payload (the original broadcast).
- ECHO(slot, digest): sent once per slot, for the *first* VAL received.
- READY(slot, digest): sent on 2f+1 matching ECHOs, or amplified on f+1
  matching READYs.
- deliver: on 2f+1 matching READYs *and* a held payload with that digest.
- FETCH(slot, digest): payload retransmission request — a process that saw
  a READY quorum for a digest whose VAL it never received (it got the
  equivocator's other copy, or the VAL was dropped) asks; any process
  holding the payload re-broadcasts the original VAL message.

Guarantees (n >= 3f+1, authenticated point-to-point links):
- *Consistency*: two quorums of 2f+1 intersect in an honest process that
  echoed exactly one digest — so at most one digest per slot can reach
  READY quorum, and no two honest processes deliver different contents.
- *Totality*: if any honest process delivers, its 2f+1 READYs include f+1
  honest ones, which push every honest process past the amplification
  threshold; FETCH covers the payload.

One RbcTransport wraps the shared (or networked) inner transport per
process: the Process subscribes to *it*, it subscribes to the inner
transport, and only fully-amplified VAL messages flow upward. Sender
authenticity of control messages is the inner transport's concern (the
in-memory broker stamps are taken at face value; the gRPC transport would
pin them to the peer connection).
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from dag_rider_tpu.core.types import BroadcastMessage
from dag_rider_tpu.transport.base import Handler, Transport

Slot = Tuple[int, int]  # (round, source) — one broadcast instance


class RbcTransport(Transport):
    """Per-process Bracha reliable-broadcast stage."""

    #: honest senders must not tunnel unicast past this stage: totality
    #: and decided-slot catch-up both hinge on peers seeing repeat VALs
    #: (ready refresh) — see transport.base.resolve_unicast
    requires_broadcast = True

    def __init__(self, inner: Transport, index: int, n: int, f: int):
        self.inner = inner
        self.index = index
        self.n = n
        self.f = f
        self.quorum = 2 * f + 1
        self._handler: Optional[Handler] = None
        # payloads held per slot, keyed by digest (an equivocator may
        # produce several; at most one can ever reach READY quorum)
        self._val: Dict[Slot, Dict[bytes, BroadcastMessage]] = {}
        self._echoed: Set[Slot] = set()
        self._readied: Set[Slot] = set()
        self._delivered: Set[Slot] = set()
        # digest that reached READY quorum per slot (unique by consistency)
        self._decided: Dict[Slot, bytes] = {}
        self._serves: Dict[Slot, int] = {}
        # READY-refresh flood control: rate limit per slot rather than a
        # lifetime budget — an exhaustible budget could be drained by
        # replayed VALs, permanently breaking catch-up for that slot.
        self.ready_refresh_cooldown_s = 0.2
        self._ready_refresh_at: Dict[Slot, float] = {}
        self._echoes: Dict[Tuple[Slot, bytes], Set[int]] = {}
        self._readies: Dict[Tuple[Slot, bytes], Set[int]] = {}
        #: slots below this round are retired (see prune_below): their
        #: state is dropped and new frames for them are discarded, so a
        #: replayed VAL cannot regrow the books.
        self.floor = 0

    def prune_below(self, floor: int) -> int:
        """Retire per-slot Bracha state for rounds below ``floor``.

        The owning Process calls this with its GC floor
        (Process.maybe_prune; checkpoint/snapshot restore re-establishes
        it): below the floor, vertices are excluded from delivery at
        every correct process and sync windows are refused, so
        echo/ready bookkeeping for those slots is dead weight — the same
        unbounded-growth class DagState.prune_below retires. The floor
        also gates _on_inner: frames for retired slots are dropped, not
        re-admitted into fresh state.

        Liveness across DIVERGING floors (peers prune at different
        times, so a pruned peer can no longer refresh READY for a
        laggard's catch-up slot): with at most f peers pruned past a
        slot, 2f of the remaining peers' READYs reach the laggard, whose
        own amplification (f+1 READYs -> READY) completes the 2f+1
        quorum; once f+1 peers have pruned past it, those same peers
        nack the laggard's sync window and the f+1-nack quorum routes it
        to peer state transfer instead (Process._on_sync_nack) — the
        boundary is exact, no wedge gap. Returns entries removed."""
        if floor <= self.floor:
            return 0
        self.floor = floor
        removed = 0
        for d in (self._val, self._decided, self._serves, self._ready_refresh_at):
            for k in [k for k in d if k[0] < floor]:
                del d[k]
                removed += 1
        for s in (self._echoed, self._readied, self._delivered):
            for k in [k for k in s if k[0] < floor]:
                s.discard(k)
                removed += 1
        for book in (self._echoes, self._readies):
            for k in [k for k in book if k[0][0] < floor]:
                del book[k]
                removed += 1
        return removed

    # -- Transport interface ------------------------------------------------

    def subscribe(self, index: int, handler: Handler) -> None:
        if index != self.index:
            raise ValueError(
                f"RbcTransport {self.index} hosts only its own process"
            )
        if self._handler is not None:
            raise ValueError("already subscribed")
        self._handler = handler
        self.inner.subscribe(index, self._on_inner)

    def unsubscribe(self) -> None:
        """Release this slot and the inner transport's — a rebuilt
        Process (corrupt-checkpoint recovery) re-subscribes the chain."""
        self._handler = None
        unsub = getattr(self.inner, "unsubscribe", None)
        if unsub is not None:
            unsub()

    def broadcast(self, msg: BroadcastMessage) -> None:
        """r_bcast: send VAL and join the echo voting for our own vertex
        (the inner broker excludes the sender from fan-out, so the sender's
        ECHO/READY participation happens locally here). Consensus-level
        control messages (sync) ride the wire without Bracha processing."""
        self.inner.broadcast(msg)
        if msg.kind == "val" and msg.vertex is not None:
            self._on_val(msg)

    @property
    def pending(self) -> int:
        """Inner-broker backlog passthrough — sync patience reads this
        to tell a throttled pump from a real partition."""
        return int(getattr(self.inner, "pending", 0))

    # -- protocol -----------------------------------------------------------

    def _on_inner(self, msg: BroadcastMessage) -> None:
        if (
            self.floor
            and msg.kind in ("val", "echo", "ready", "fetch")
            and msg.round < self.floor
        ):
            return  # retired slot (see prune_below): drop, don't regrow
        if msg.kind == "val" and msg.vertex is not None:
            self._on_val(msg)
        elif msg.kind == "echo":
            self._on_echo(msg)
        elif msg.kind == "ready":
            self._on_ready(msg)
        elif msg.kind == "fetch":
            self._on_fetch(msg)
        elif self._handler is not None:
            # consensus-level control (sync requests) passes straight up;
            # the Process validates and handles it.
            self._handler(msg)

    def _ctrl(self, kind: str, slot: Slot, digest: bytes) -> None:
        self.inner.broadcast(
            BroadcastMessage(
                vertex=None,
                round=slot[0],
                sender=self.index,
                kind=kind,
                origin=slot[1],
                digest=digest,
            )
        )

    def _vote(
        self,
        book: Dict[Tuple[Slot, bytes], Set[int]],
        slot: Slot,
        digest: bytes,
        voter: int,
    ) -> int:
        voters = book.setdefault((slot, digest), set())
        voters.add(voter)
        return len(voters)

    def _on_val(self, msg: BroadcastMessage) -> None:
        v = msg.vertex
        # Slot authenticity: a VAL for slot (r, s) must arrive stamped by
        # s itself (FETCH retransmissions preserve the original stamps, so
        # they pass too). Without this, any Byzantine peer could front-run
        # an honest node's slot with a forged vertex and censor the honest
        # broadcast forever.
        if msg.sender != v.id.source or msg.round != v.id.round:
            return
        slot = (v.id.round, v.id.source)
        digest = v.digest()
        self._val.setdefault(slot, {}).setdefault(digest, msg)
        if slot not in self._echoed:
            self._echoed.add(slot)
            self._vote(self._echoes, slot, digest, self.index)
            self._ctrl("echo", slot, digest)
            self._maybe_ready(slot, digest)
        elif self._decided.get(slot) == digest:
            # Catch-up support: a repeat VAL for a slot we already decided
            # is a laggard being served (Process._serve_sync re-broadcasts
            # old vertices). Our Bracha instance is long done and would
            # never re-send READY, so the laggard could hold the payload
            # yet never re-reach a READY quorum. Re-sending our READY
            # (rate-limited per slot) lets 2f+1 up-to-date peers rebuild
            # that quorum — consistency is untouched because only the
            # decided digest is ever refreshed.
            import time as _time

            now = _time.monotonic()
            if (
                now - self._ready_refresh_at.get(slot, float("-inf"))
                >= self.ready_refresh_cooldown_s
            ):
                self._ready_refresh_at[slot] = now
                self._ctrl("ready", slot, digest)
        self._maybe_deliver(slot)

    def _on_echo(self, msg: BroadcastMessage) -> None:
        if msg.origin is None or msg.digest is None:
            return
        slot = (msg.round, msg.origin)
        self._vote(self._echoes, slot, msg.digest, msg.sender)
        self._maybe_ready(slot, msg.digest)

    def _on_ready(self, msg: BroadcastMessage) -> None:
        if msg.origin is None or msg.digest is None:
            return
        slot = (msg.round, msg.origin)
        n = self._vote(self._readies, slot, msg.digest, msg.sender)
        if n >= self.quorum:
            self._decided.setdefault(slot, msg.digest)
        self._maybe_ready(slot, msg.digest)
        self._maybe_deliver(slot)

    def _on_fetch(self, msg: BroadcastMessage) -> None:
        if msg.origin is None or msg.digest is None:
            return
        slot = (msg.round, msg.origin)
        held = self._val.get(slot, {}).get(msg.digest)
        # Bounded re-serving: a single response can be lost or re-corrupted
        # in flight (totality would silently fail one-shot), but serving
        # every fetch forever would let a Byzantine peer amplify traffic.
        if held is not None and self._serves.get(slot, 0) < 2 * self.n:
            self._serves[slot] = self._serves.get(slot, 0) + 1
            self.inner.broadcast(held)  # original stamps preserved

    def _maybe_ready(self, slot: Slot, digest: bytes) -> None:
        if slot in self._readied:
            return
        echoes = len(self._echoes.get((slot, digest), ()))
        readies = len(self._readies.get((slot, digest), ()))
        if echoes >= self.quorum or readies >= self.f + 1:
            self._readied.add(slot)
            n = self._vote(self._readies, slot, digest, self.index)
            if n >= self.quorum:
                self._decided.setdefault(slot, digest)
            self._ctrl("ready", slot, digest)
            self._maybe_deliver(slot)

    def _maybe_deliver(self, slot: Slot) -> None:
        if slot in self._delivered:
            return
        digest = self._decided.get(slot)
        if digest is None:
            return
        held = self._val.get(slot, {}).get(digest)
        if held is None:
            # READY quorum for a payload we never saw (equivocation or
            # drop): ask for a retransmission. Re-asked on every subsequent
            # VAL/READY for the slot, so a lost response is retried.
            self._ctrl("fetch", slot, digest)
            return
        self._delivered.add(slot)
        if self._handler is not None and held.sender != self.index:
            self._handler(held)
