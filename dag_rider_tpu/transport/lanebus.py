"""In-memory lane channel for payload dissemination (ISSUE 17).

The consensus Transport orders metadata; dissemination lanes move the
payload bytes on a separate channel so block weight never rides the
pump. This is the in-process form of that channel, deliberately shaped
like :mod:`dag_rider_tpu.transport.blobbus` — the same
``(sender, kind, value)`` triples and the same ``send`` / ``broadcast``
/ ``subscribe`` surface — so the item-1 cluster crossing swaps a wire
bus in without touching the lane coordinator above it.
:func:`encode_frame` / :func:`decode_frame` pin the wire layout that
crossing will serialize each triple with.

Two deliberate in-process choices, both load-bearing for the
``ladder.lanes`` A/B:

- **Only publishes are pool tasks.** A publish (encode + hash + sign +
  disseminate + collect acks) runs as ONE task on the shared worker
  pool; message delivery inside it is a direct handler call on the
  calling thread. The alternative — a pool task per (receiver, message)
  — costs ~n² executor round-trips per consensus round and drowns the
  win in scheduling overhead. With one task per publish, ``workers``
  concurrent publishes overlap their payload hashing (hashlib releases
  the GIL on large buffers), which is exactly the per-process worker
  lane the design names.
- **Values pass by reference and digests are memoized per object**
  (:meth:`LaneBus.digest_of`). On a real wire every receiver hashes the
  bytes it received; in-process every receiver holds the same immutable
  object, so the hub computes the digest once and shares the verdict —
  the same dedup argument the simulator already applies to signature
  verification (``Simulation`` shares verify verdicts across its n
  views). Re-slicing a concatenated frame per receiver would defeat the
  memo and silently reintroduce the n² hashing.

Handler exceptions propagate up the inline delivery chain into the
publish task and re-raise at ``Future.result()`` /
:meth:`LaneBus.flush` — a lane worker must never die silently under a
test.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

#: digest-memo capacity in payload objects (bounds the strong refs the
#: identity-keyed memo must hold to keep ``id()`` stable)
_MEMO_CAP = 4096

#: shared worker pools, one per distinct width — lane buses are created
#: per Simulation and a test session builds hundreds of them; pooling by
#: width bounds the live thread count at a handful instead of leaking
#: ``workers`` threads per sim
_POOLS: Dict[int, ThreadPoolExecutor] = {}
_POOLS_LOCK = threading.Lock()


def _pool(workers: int) -> ThreadPoolExecutor:
    with _POOLS_LOCK:
        p = _POOLS.get(workers)
        if p is None:
            p = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix=f"lane{workers}"
            )
            _POOLS[workers] = p
        return p


def encode_frame(sender: int, kind: str, payload: bytes) -> bytes:
    """blobbus wire layout, verbatim: u32 sender, u16 kind length, kind,
    payload — what the cluster bus serializes each delivery triple to."""
    k = kind.encode()
    return struct.pack("<IH", sender, len(k)) + k + payload


def decode_frame(data: bytes) -> Optional[Tuple[int, str, bytes]]:
    if len(data) < 6:
        return None
    sender, klen = struct.unpack_from("<IH", data, 0)
    if len(data) < 6 + klen:
        return None
    kind = data[6 : 6 + klen].decode()
    return sender, kind, data[6 + klen :]


class LaneEndpoint:
    """One process's handle on the lane bus (blobbus-shaped)."""

    def __init__(self, bus: "LaneBus", index: int) -> None:
        self.bus = bus
        self.index = index
        self._handler: Optional[Callable[[int, str, Any], None]] = None

    def subscribe(
        self, handler: Callable[[int, str, Any], None]
    ) -> None:
        self._handler = handler

    def send(self, peer: int, kind: str, value: Any) -> bool:
        """Unicast one delivery; False for an unknown peer or self.
        Synchronous: the peer's handler has run by the time this
        returns, so a fetch send is a complete request/response."""
        if peer == self.index:
            return False
        return self.bus._deliver(self.index, peer, kind, value)

    def broadcast(self, kind: str, value: Any) -> int:
        """Deliver to every other endpoint; returns the send count."""
        sent = 0
        for peer in range(self.bus.n):
            if peer != self.index and self.bus._deliver(
                self.index, peer, kind, value
            ):
                sent += 1
        return sent


class LaneBus:
    """The in-memory hub: n endpoints over one shared worker pool."""

    def __init__(self, n: int, workers: int = 1) -> None:
        self.n = n
        self.workers = workers
        self._pool = _pool(workers)
        self._lock = threading.Lock()
        self._endpoints: Dict[int, LaneEndpoint] = {}
        self._pending: List[Future] = []
        self._memo: "OrderedDict[int, Tuple[bytes, bytes]]" = OrderedDict()
        #: digest -> decoded payload Block (delivery-side analog of the
        #: digest memo: all n views deliver the same immutable batch, so
        #: the hub decodes it once — re-decoding per view would put n
        #: payload copies per vertex back on the consensus pump)
        self._blocks: "OrderedDict[bytes, object]" = OrderedDict()
        self.frames_sent = 0

    def endpoint(self, index: int) -> LaneEndpoint:
        with self._lock:
            ep = self._endpoints.get(index)
            if ep is None:
                ep = LaneEndpoint(self, index)
                self._endpoints[index] = ep
            return ep

    def digest_of(self, payload: bytes) -> bytes:
        """sha256 of ``payload``, memoized per object (module docstring:
        the in-process analog of n receivers hashing in parallel)."""
        key = id(payload)
        with self._lock:
            hit = self._memo.get(key)
            if hit is not None and hit[0] is payload:
                self._memo.move_to_end(key)
                return hit[1]
        digest = hashlib.sha256(payload).digest()
        with self._lock:
            self._memo[key] = (payload, digest)
            while len(self._memo) > _MEMO_CAP:
                self._memo.popitem(last=False)
        return digest

    def seed_block(self, digest: bytes, block: object) -> None:
        """Pre-seed the decoded-block memo with the publisher's original
        Block (its encoding hashes to ``digest`` by construction). Every
        view's delivery resolve then returns the very object the inline
        path would have delivered — no decode, no payload copy, anywhere
        on the consensus pump."""
        with self._lock:
            if digest not in self._blocks:
                self._blocks[digest] = block
                while len(self._blocks) > _MEMO_CAP:
                    self._blocks.popitem(last=False)

    def block_of(self, digest: bytes, body: bytes):
        """Decode ``body`` as a payload Block, memoized by digest.
        Safe to share across views: digests are verified against bodies
        before anything lands in a lane store, Blocks are immutable, and
        the inline path already delivers one shared Block object to all
        n views (the in-memory consensus transport passes vertices by
        reference)."""
        with self._lock:
            hit = self._blocks.get(digest)
            if hit is not None:
                self._blocks.move_to_end(digest)
                return hit
        from dag_rider_tpu.core.types import Block

        block, _ = Block.decode(body)
        with self._lock:
            self._blocks[digest] = block
            while len(self._blocks) > _MEMO_CAP:
                self._blocks.popitem(last=False)
        return block

    def submit(self, fn: Callable, *args) -> Future:
        """Run ``fn`` (a publish) on the lane pool; joined by
        :meth:`flush` or the caller's ``Future.result()``."""
        fut = self._pool.submit(fn, *args)
        with self._lock:
            self._pending.append(fut)
        return fut

    def _deliver(self, sender: int, dest: int, kind: str, value: Any) -> bool:
        with self._lock:
            ep = self._endpoints.get(dest)
        if ep is None or ep._handler is None:
            return False
        self.frames_sent += 1
        # direct call on the calling thread — no lock held (the handler
        # may send in turn: acks answer batches, batches answer fetches)
        ep._handler(sender, kind, value)
        return True

    def flush(self) -> None:
        """Join every in-flight publish task, re-raising the first
        handler/publish exception (loop in case a joined task submitted
        another)."""
        while True:
            with self._lock:
                futs, self._pending = self._pending, []
            if not futs:
                return
            for f in futs:
                if f.cancelled():
                    # work-stolen by the publisher's materialize — the
                    # publish ran (to completion) on the driver instead
                    continue
                f.result()
