"""Watermark-driven admission control: accept → throttle → shed.

The backpressure story the ROADMAP's "heavy traffic" north star needs:
the pool's fill fraction drives a three-state ladder, and the current
state is surfaced to callers on every submit (SubmitResult.state) so a
well-behaved client can slow down *before* its traffic is dropped.

- fill < admit_low       — **accept**: everything in, subject only to
  the optional per-source hard rate cap (``source_rate``);
- admit_low <= fill < admit_high — **throttle**: each source is cut to
  ``throttle_rate`` tx/s via a token bucket (fair degradation: a
  firehose source saturates its own bucket, quiet sources still get
  their trickle through);
- fill >= admit_high     — **shed**: everything is refused until the
  batcher drains the pool back below the high watermark.

Deterministic by construction: no wall-clock reads — every decision
takes an explicit ``now``, so simulations and tests drive it on a
virtual clock and replay byte-identically.
"""

from __future__ import annotations

from typing import Dict

from dag_rider_tpu.config import MempoolConfig

ACCEPT = "accept"
THROTTLE = "throttle"
SHED = "shed"


class _TokenBucket:
    """Per-source rate limiter: refills at ``rate`` tx/s up to
    ``burst``; each admitted transaction spends one token."""

    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.last = now

    def spend(self, rate: float, burst: float, now: float) -> bool:
        if now > self.last:
            self.tokens = min(burst, self.tokens + (now - self.last) * rate)
            self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionController:
    """The accept/throttle/shed ladder over one pool's fill signal."""

    def __init__(self, cfg: MempoolConfig) -> None:
        self.cfg = cfg
        self._buckets: Dict[str, _TokenBucket] = {}
        #: the ladder state of the most recent decision — the
        #: backpressure signal callers read
        self.state = ACCEPT
        # lifetime counters
        self.accepted = 0
        self.shed_watermark = 0
        self.shed_rate = 0

    def _state_of(self, fill: float) -> str:
        if fill >= self.cfg.admit_high:
            return SHED
        if fill >= self.cfg.admit_low:
            return THROTTLE
        return ACCEPT

    def decide(self, client: str, fill: float, now: float) -> bool:
        """One transaction's verdict. Updates ``state`` as a side effect
        (the ladder state is a property of the pool, not of the client)."""
        self.state = state = self._state_of(fill)
        if state == SHED:
            self.shed_watermark += 1
            return False
        if state == THROTTLE:
            rate = self.cfg.throttle_rate
            if self.cfg.source_rate > 0:
                rate = min(rate, self.cfg.source_rate)
        elif self.cfg.source_rate > 0:
            rate = self.cfg.source_rate
        else:
            self.accepted += 1
            return True
        bucket = self._buckets.get(client)
        if bucket is None:
            bucket = self._buckets[client] = _TokenBucket(
                self.cfg.source_burst, now
            )
        if bucket.spend(rate, self.cfg.source_burst, now):
            self.accepted += 1
            return True
        self.shed_rate += 1
        return False

    def forget_idle(self, now: float, idle_s: float = 300.0) -> None:
        """Drop buckets for sources silent longer than ``idle_s`` — the
        per-source map must not grow one entry per client forever (same
        bounded-state rule the DAG GC enforces)."""
        dead = [c for c, b in self._buckets.items() if now - b.last > idle_s]
        for c in dead:
            del self._buckets[c]
