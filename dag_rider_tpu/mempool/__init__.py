"""Mempool: the ingestion edge between clients and consensus.

Round 10. DAG-Rider orders *blocks*; everything about which client
bytes ride in a block is decided here, Narwhal-style (data path
separate from the ordering path):

    client tx --> admission (accept/throttle/shed) --> pool (bounded,
    dedup, per-client FIFO lanes, TTL) --> batcher (size-or-deadline
    Block packing) --> Process.submit --> ... a_deliver

:class:`Mempool` is the facade gluing the three stages under one lock
(``Node.submit`` runs on client threads, the pump thread drains), plus
the end-to-end accounting: every accepted transaction's submit time is
held until its block is a_delivered, yielding the submit→a_deliver
latency histogram — the first *client-level* latency number in the
repo (verify timings measure the crypto seam, not what a client sees;
and under the simulator's dedup'd shared verifier those are amortized
anyway — utils.metrics.Metrics.mark_verify_amortized).

Deterministic: no hidden wall-clock reads — every method takes an
explicit ``now`` or falls back to the injected ``clock``, so the
simulator drives whole clusters on a virtual clock and replays
byte-identically (the byte-identity acceptance test in
tests/test_mempool.py depends on this).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional

from dag_rider_tpu.config import MempoolConfig, env_float
from dag_rider_tpu.core.codec import EPOCH_MAGIC
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.mempool.admission import AdmissionController
from dag_rider_tpu.mempool.batcher import BlockBatcher
from dag_rider_tpu.mempool.pool import TransactionPool
from dag_rider_tpu.obs import block_key, sample_tx, tx_key
from dag_rider_tpu.utils.slog import NOOP, EventLog

__all__ = [
    "Mempool",
    "MempoolConfig",
    "SubmitResult",
    "AdmissionController",
    "BlockBatcher",
    "TransactionPool",
]


class SubmitResult(NamedTuple):
    """Per-call admission outcome + the backpressure signal.

    ``state`` is the admission ladder's current rung
    ("accept" | "throttle" | "shed") — a client seeing "throttle"
    should back off *now*, before its traffic starts landing in
    ``shed``.
    """

    accepted: int
    deduped: int
    shed: int
    state: str


class Mempool:
    """Admission + pool + batcher under one lock, with latency books."""

    def __init__(
        self,
        cfg: Optional[MempoolConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        log: Optional[EventLog] = None,
        trace_sample: Optional[float] = None,
    ) -> None:
        self.cfg = cfg if cfg is not None else MempoolConfig.from_env()
        self.clock = clock
        #: round-16 obs seam: admission decisions + sampled tx lifecycle
        #: stamps (tx_submit / tx_batch) ride the structured event log
        self.log = log if log is not None else NOOP
        self.trace_sample = (
            env_float("DAGRIDER_TRACE_SAMPLE")
            if trace_sample is None
            else trace_sample
        )
        self._trace_state = "accept"
        #: optional utils.metrics.Metrics — submit→a_deliver samples are
        #: forwarded to its histogram so they ride the node's snapshot
        self.metrics = metrics
        self._lock = threading.RLock()
        self.pool = TransactionPool(self.cfg)
        self.admission = AdmissionController(self.cfg)
        self.batcher = BlockBatcher(self.cfg, self.pool)
        #: tx bytes -> accept time, held from admission until the block
        #: carrying it is a_delivered (or the entry is TTL'd / evicted).
        #: Doubles as the dedup horizon for in-flight-but-batched txs.
        self._inflight: Dict[bytes, float] = {}
        #: epoch control-op lane (ISSUE 20): EPOCH_MAGIC transactions
        #: bypass the admission ladder (shedding a membership change
        #: under load is the exact moment you need it) and ship in
        #: their own block ahead of payload batches — never inside a
        #: lane carrier, so the delivery-time boundary scan always
        #: sees the magic inline.
        self._control: List[bytes] = []
        #: in-flight bound: a wedged cluster must not grow this forever
        self._inflight_cap = 4 * self.cfg.cap
        from dag_rider_tpu.utils.metrics import Histogram

        self.latency = Histogram()
        self.delivered_txs = 0
        #: payload bytes of OUR delivered transactions — the numerator
        #: of committed-bytes/s in the lanes A/B rung (ISSUE 17)
        self.delivered_bytes = 0

    # -- front door --------------------------------------------------------

    def submit(
        self,
        txs: Iterable[bytes],
        *,
        client: str = "client0",
        now: Optional[float] = None,
    ) -> SubmitResult:
        """Admit transactions from one source. Never raises on overload:
        shed counts come back in the result, and ``state`` is the
        backpressure signal ("throttle"/"shed" → the caller should slow
        down)."""
        accepted = deduped = shed = 0
        trace = self.log.enabled
        with self._lock:
            t = self.clock() if now is None else now
            self.pool.expire(t)  # age out before measuring fill
            for tx in txs:
                if tx in self._inflight:
                    # pending OR batched-and-awaiting-delivery: either
                    # way re-admitting would deliver the payload twice
                    deduped += 1
                    self.pool.deduped += 1
                    continue
                if tx.startswith(EPOCH_MAGIC):
                    accepted += 1
                    self._control.append(tx)
                    self._note_inflight(tx, t)
                    if trace and sample_tx(tx, self.trace_sample):
                        self.log.event(
                            "tx_submit", tx=tx_key(tx), client=client
                        )
                    continue
                if not self.admission.decide(client, self.pool.fill, t):
                    shed += 1
                    continue
                verdict = self.pool.add(tx, client, t)
                if verdict == "ok":
                    accepted += 1
                    self._note_inflight(tx, t)
                    if trace and sample_tx(tx, self.trace_sample):
                        self.log.event(
                            "tx_submit", tx=tx_key(tx), client=client
                        )
                elif verdict == "dup":
                    deduped += 1
                else:  # "full": admission raced the hard wall
                    shed += 1
            state = self.admission.state
            if trace:
                if state != self._trace_state:
                    self.log.event(
                        "mempool_state",
                        state=state,
                        prev=self._trace_state,
                        fill=round(self.pool.fill, 4),
                    )
                if shed:
                    self.log.event(
                        "mempool_shed", shed=shed, client=client, state=state
                    )
            self._trace_state = state
            return SubmitResult(accepted, deduped, shed, state)

    def _note_inflight(self, tx: bytes, t: float) -> None:
        if len(self._inflight) >= self._inflight_cap:
            # evict the oldest accept record (dict preserves insertion
            # order): its latency sample is lost, exactly-once dedup for
            # that payload ends early — bounded state wins
            self._inflight.pop(next(iter(self._inflight)))
        self._inflight[tx] = t

    # -- pump side ---------------------------------------------------------

    def build_blocks(
        self,
        now: Optional[float] = None,
        *,
        force: bool = False,
        staged: int = 0,
    ) -> List[Block]:
        """TTL-evict, then drain triggered batches. The pump calls this
        each cycle and feeds the blocks to ``Process.submit``.

        ``staged`` is the consumer's current backlog (depth of
        ``Process.blocks_to_propose``); builds stop once backlog plus
        fresh blocks reach ``cfg.max_staged_blocks``, so overload piles
        up *here* — where the watermarks can shed — instead of in the
        unbounded proposal queue. ``force`` (shutdown/checkpoint flush)
        ignores the bound."""
        with self._lock:
            t = self.clock() if now is None else now
            if self.cfg.adaptive_deadline:
                self._adapt_deadline()
            for tx in self.pool.expire(t):
                self._inflight.pop(tx, None)
            control: List[Block] = []
            if self._control:
                # control lane flush: one dedicated block, ahead of any
                # payload batch and exempt from the staging bound — a
                # reconfiguration op must reach its boundary even when
                # the payload path is backlogged
                control.append(Block(tuple(self._control)))
                self._control = []
            limit: Optional[int] = None
            if not force:
                limit = max(0, self.cfg.max_staged_blocks - staged)
                if limit == 0:
                    return control
            blocks = control + self.batcher.drain(
                t, force=force, limit=limit
            )
            if blocks and self.log.enabled:
                for b in blocks:
                    keys = [
                        tx_key(tx)
                        for tx in b.transactions
                        if sample_tx(tx, self.trace_sample)
                    ]
                    if keys:
                        bk = block_key(b.encode())
                        for k in keys:
                            self.log.event("tx_batch", tx=k, block=bk)
            return blocks

    def _adapt_deadline(self) -> None:
        """Retune the batcher's effective deadline from the live
        submit→deliver histogram (ISSUE 16 tentpole 3,
        cfg.adaptive_deadline). The hold deadline should be a small tax
        on what the client already waits end to end: target 5% of the
        measured p50, floored at 1 ms (never busy-ship every single
        transaction) and capped at the configured ``batch_deadline_ms``
        (never hold LONGER than the operator allowed). Until enough
        samples exist the configured value stands. Caller holds the
        lock."""
        if self.latency.count < 16:
            return
        p50_ms = self.latency.percentile(50.0) * 1e3
        eff = min(
            float(self.cfg.batch_deadline_ms), max(1.0, 0.05 * p50_ms)
        )
        prev = self.batcher.deadline_ms
        if abs(eff - prev) < 0.5:
            return
        self.batcher.deadline_ms = eff
        if self.metrics is not None:
            # gauge, not a counter: latest effective value wins
            self.metrics.counters["deadline_ms_effective"] = int(
                round(eff)
            )
        self.log.event(
            "deadline_adapted",
            deadline_ms=round(eff, 3),
            prev_ms=round(prev, 3),
            p50_ms=round(p50_ms, 3),
        )

    def observe_delivered(
        self, block: Block, now: Optional[float] = None
    ) -> None:
        """a_deliver callback: close the latency books for every
        transaction of ours this block carried (peers' blocks carry
        unknown payloads and are skipped by the inflight lookup)."""
        with self._lock:
            t = self.clock() if now is None else now
            for tx in block.transactions:
                t0 = self._inflight.pop(tx, None)
                if t0 is None:
                    continue
                self.delivered_txs += 1
                self.delivered_bytes += len(tx)
                s = max(0.0, t - t0)
                self.latency.observe(s)
                if self.metrics is not None:
                    self.metrics.observe_submit_deliver(s)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Gauge snapshot (cheap: counters + maintained sums only; the
        latency percentiles live in the metrics histogram)."""
        with self._lock:
            adm, pool = self.admission, self.pool
            return {
                "depth": len(pool),
                "depth_bytes": pool.depth_bytes,
                "admitted": pool.admitted,
                "deduped": pool.deduped,
                "shed": adm.shed_watermark
                + adm.shed_rate
                + pool.dropped_full,
                "shed_watermark": adm.shed_watermark,
                "shed_rate": adm.shed_rate,
                "shed_full": pool.dropped_full,
                "expired": pool.expired,
                "delivered_txs": self.delivered_txs,
                "delivered_bytes": self.delivered_bytes,
                "blocks_built": self.batcher.blocks_built,
                "txs_packed": self.batcher.txs_packed,
                "batch_fill": round(self.batcher.mean_fill(), 4),
                "state": adm.state,
            }

    # -- checkpoint support ------------------------------------------------

    def checkpoint_state(self) -> dict:
        """Pending (accepted, not yet batched) transactions with their
        lanes — what utils.checkpoint persists so a restart loses no
        accepted transaction. Batched-but-undelivered payloads are
        already covered by the Process manifest (blocks_to_propose) or
        by the DAG itself."""
        with self._lock:
            return {
                "version": 1,
                "pending": [
                    [e.client, e.tx.hex()] for e in self.pool.pending()
                ],
                # un-flushed control ops survive a restart too
                "control": [tx.hex() for tx in self._control],
            }

    def restore_state(
        self, state: dict, now: Optional[float] = None
    ) -> int:
        """Re-admit a checkpoint's pending set (fresh TTL stamps; see
        TransactionPool.restore). Returns the restored count."""
        with self._lock:
            t = self.clock() if now is None else now
            entries = [
                (client, bytes.fromhex(tx))
                for client, tx in state.get("pending", [])
            ]
            restored = self.pool.restore(entries, t)
            for client, tx in entries:
                if tx in self.pool:
                    self._note_inflight(tx, t)
            for hx in state.get("control", []):
                tx = bytes.fromhex(hx)
                if tx not in self._inflight:
                    self._control.append(tx)
                    self._note_inflight(tx, t)
                    restored += 1
            return restored
