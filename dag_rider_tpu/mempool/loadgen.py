"""Seeded open-loop workload generation + whole-cluster load driving.

Two pieces:

- :class:`LoadGenerator` — a seeded, open-loop (arrivals do not wait on
  the system; that is what makes overload *possible*, and overload is
  what admission control exists for), multi-client arrival schedule
  with Poisson, bursty-Poisson, or uniform profiles. Deterministic for
  a given seed: bench and chaos runs replay the exact same traffic.

- :class:`ClusterLoadDriver` — drives a ``Simulation`` through
  per-process :class:`~dag_rider_tpu.mempool.Mempool` front doors:
  inject due arrivals, tick the batchers, feed built blocks to the
  processes, pump consensus; repeat. Runs on a **virtual clock** by
  default (fully deterministic — the byte-identity test replays the
  recorded block schedule and demands the same delivered order) or on
  the wall clock for bench rungs (real submit→a_deliver latency). The
  driver also keeps per-transaction lifecycle books, so a chaos run
  can *prove* shed-not-crash: every accepted transaction is delivered,
  pending, or in flight — never silently lost.

CLI smoke (the tier1-mempool CI lane):

    python -m dag_rider_tpu.mempool.loadgen --n 4 --seconds 2 --rate 2000
"""

from __future__ import annotations

import argparse
import heapq
import json
import random
import time
from typing import Dict, List, Optional, Tuple

from dag_rider_tpu.config import Config, MempoolConfig
from dag_rider_tpu.core.types import Block

PROFILES = ("poisson", "burst", "uniform")


class LoadGenerator:
    """Open-loop multi-client arrival schedule. ``rate`` is the total
    offered tx/s split evenly across ``clients``; the burst profile
    multiplies each client's rate by ``burst_factor`` during a
    ``burst_len_s`` window every ``burst_every_s`` (phase-aligned across
    clients — the worst case for admission)."""

    def __init__(
        self,
        *,
        clients: int = 8,
        rate: float = 1000.0,
        tx_bytes: int = 32,
        seed: int = 0,
        profile: str = "poisson",
        burst_factor: float = 8.0,
        burst_every_s: float = 1.0,
        burst_len_s: float = 0.25,
    ) -> None:
        if clients < 1:
            raise ValueError(f"clients must be >= 1, got {clients}")
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if profile not in PROFILES:
            raise ValueError(f"profile must be one of {PROFILES}, got {profile!r}")
        self.clients = clients
        self.rate = rate
        self.tx_bytes = tx_bytes
        self.seed = seed
        self.profile = profile
        self.burst_factor = burst_factor
        self.burst_every_s = burst_every_s
        self.burst_len_s = burst_len_s
        self._rngs = [
            random.Random((seed << 20) ^ (c * 2654435761)) for c in range(clients)
        ]
        self._seq = [0] * clients
        self.emitted = 0
        #: (next arrival time, client) min-heap
        self._next: List[Tuple[float, int]] = [
            (self._gap(c, 0.0), c) for c in range(clients)
        ]
        heapq.heapify(self._next)

    def _client_rate(self, c: int, t: float) -> float:
        r = self.rate / self.clients
        if (
            self.profile == "burst"
            and (t % self.burst_every_s) < self.burst_len_s
        ):
            r *= self.burst_factor
        return r

    def _gap(self, c: int, t: float) -> float:
        r = self._client_rate(c, t)
        if self.profile == "uniform":
            return 1.0 / r
        return self._rngs[c].expovariate(r)

    def _payload(self, c: int) -> bytes:
        self._seq[c] += 1
        head = f"s{self.seed}c{c}-{self._seq[c]:08d}".encode()
        return head.ljust(self.tx_bytes, b".")

    def events_until(self, t: float) -> List[Tuple[float, int, bytes]]:
        """Pop every arrival scheduled at or before ``t`` (advances the
        schedule — call with monotonically non-decreasing ``t``)."""
        out: List[Tuple[float, int, bytes]] = []
        while self._next and self._next[0][0] <= t:
            ts, c = heapq.heappop(self._next)
            out.append((ts, c, self._payload(c)))
            self.emitted += 1
            heapq.heappush(self._next, (ts + self._gap(c, ts), c))
        return out


class ClusterLoadDriver:
    """Pump a Simulation under open-loop mempool-fronted load.

    ``wall=False`` (default): virtual clock stepping ``dt`` per pump
    cycle — deterministic, used by tests and the chaos audit.
    ``wall=True``: real time — used by the bench rung so the latency
    histogram measures what a client would see.

    The driver's chunked pumping deliberately throttles delivery below
    the offered load; sync patience is backlog-aware (a process with
    undelivered transport backlog is throttled, not partitioned —
    Process._maybe_request_sync), so the anti-entropy machinery no
    longer mistakes the throttle for a partition and the round-10
    ``sync_patience=0`` workaround is gone: a genuinely dark peer under
    this driver still gets anti-entropy recovery. Virtual-clock runs
    that must replay byte-identically across wall time should still pin
    ``sync_request_cooldown_s``/``sync_serve_cooldown_s`` (they are
    wall-clock rate limits) or sync_patience itself.
    """

    def __init__(
        self,
        sim,
        gen: LoadGenerator,
        *,
        mcfg: Optional[MempoolConfig] = None,
        dt: float = 0.005,
        chunk: Optional[int] = None,
        wall: bool = False,
    ) -> None:
        self.sim = sim
        self.gen = gen
        self.dt = dt
        # messages pumped per cycle. With propose_empty the cluster
        # never quiesces on its own, so this bounds how many DAG rounds
        # one virtual tick advances: a round-r broadcast burst is
        # ~n*(n-1) deliveries, so default = ~2 rounds per tick.
        self.chunk = chunk if chunk else 2 * sim.cfg.n * sim.cfg.n
        self.wall = wall
        self._vt = 0.0
        clock = time.monotonic if wall else (lambda: self._vt)
        self.mempools = sim.attach_mempools(mcfg, clock=clock)
        #: (cycle, process index, block) in submission order — the
        #: replay schedule for the byte-identity check
        self.submission_log: List[Tuple[int, int, Block]] = []
        #: every accepted payload (admission said yes) — the set the
        #: zero-loss audit accounts for
        self.accepted: set = set()
        self.shed_txs = 0
        self.cycles = 0

    def _inject(self, t: float) -> None:
        # t is elapsed-since-start (the generator's schedule origin); the
        # mempool clock is absolute in wall mode, so let the mempool
        # stamp with its own clock there — mixing the two frames once
        # produced hours-long "latencies" (absolute minus elapsed)
        now = None if self.wall else t
        n = self.sim.cfg.n
        for _, c, tx in self.gen.events_until(t):
            mp = self.mempools[c % n]
            res = mp.submit((tx,), client=f"c{c}", now=now)
            if res.accepted:
                self.accepted.add(tx)
            else:
                self.shed_txs += res.shed + res.deduped

    def _flush_batches(self, t: float, force: bool = False) -> None:
        now = None if self.wall else t
        for i, mp in enumerate(self.mempools):
            staged = len(self.sim.processes[i].blocks_to_propose)
            for b in mp.build_blocks(now=now, force=force, staged=staged):
                self.sim.processes[i].submit(b)
                self.submission_log.append((self.cycles, i, b))

    def run(
        self,
        duration_s: float,
        *,
        drain: bool = True,
        drain_s: Optional[float] = None,
    ) -> dict:
        """Offered load for ``duration_s`` (virtual or wall seconds),
        then — with ``drain`` — force-flush the batchers and pump so
        every in-flight block gets its chance to commit. ``drain_s``
        wall-bounds the drain for time-boxed bench rungs (a cut-short
        drain leaves transactions ``in_flight`` in the audit — still
        accounted for, never lost)."""
        start = time.monotonic()
        # a FaultyTransport's delay-held messages are released once per
        # cycle: delayed = reordered by ~one tick, not partitioned away
        # forever (a 5% permanent hold wedges quorum within a few rounds
        # and the whole run measures a stall, not consensus under churn)
        flush = getattr(self.sim.transport, "flush_delayed", None)
        while True:
            t = (time.monotonic() - start) if self.wall else self._vt
            if t >= duration_s:
                break
            self._inject(t)
            self._flush_batches(t)
            self.sim.run(max_messages=self.chunk)
            if callable(flush):
                flush()
            if not self.wall:
                self._vt += self.dt
            self.cycles += 1
        if drain:
            t = (time.monotonic() - start) if self.wall else self._vt
            self._flush_batches(t, force=True)
            self._drain(drain_s)
        return self.report(duration_s)

    def _drain(self, drain_s: Optional[float] = None) -> None:
        """Pump until the flushed blocks' waves commit: a wave is 4
        rounds and needs one more wave of leader support, so ~16 rounds
        of headroom; bounded — propose_empty keeps the cluster
        chattering forever, quiescence never comes. A FaultyTransport's
        held-back messages are released each sub-chunk (asynchrony:
        delivery is late, never never). Exits early once every accepted
        transaction's latency books are closed (no mempool holds an
        in-flight record)."""
        n = self.sim.cfg.n
        flush = getattr(self.sim.transport, "flush_delayed", None)
        budget = float("inf") if drain_s is None else drain_s
        t0 = time.monotonic()
        for _ in range(4):
            remaining = 16 * n * n
            while remaining > 0:
                if callable(flush):
                    flush()
                pumped = self.sim.run(max_messages=min(remaining, n * n))
                if pumped == 0 and not (
                    callable(flush) and self.sim.transport.delayed
                ):
                    break  # true quiescence (propose_empty off)
                remaining -= pumped
                if time.monotonic() - t0 > budget:
                    return
            if not any(len(mp._inflight) for mp in self.mempools):
                return

    # -- accounting --------------------------------------------------------

    def delivered_txs(self, view: int) -> List[bytes]:
        """This view's a_delivered payloads that originated from the
        driver, in total order."""
        return [
            tx
            for v in self.sim.deliveries[view]
            for tx in v.block.transactions
            if tx in self.accepted
        ]

    def audit(self) -> dict:
        """Zero-loss accounting: every accepted transaction must be
        delivered, pending in a pool, queued for proposal, or sitting in
        a DAG vertex. ``lost`` > 0 or ``duplicates`` > 0 is a bug."""
        delivered: set = set()
        for i in range(self.sim.cfg.n):
            delivered.update(self.delivered_txs(i))
        pending: set = set()
        for mp in self.mempools:
            pending.update(e.tx for e in mp.pool.pending())
        staged: set = set()
        for p in self.sim.processes:
            for b in p.blocks_to_propose:
                staged.update(b.transactions)
            for v in p.dag.vertices.values():
                staged.update(v.block.transactions)
        lost = self.accepted - delivered - pending - staged
        dup_max = 0
        for i in range(self.sim.cfg.n):
            seen: Dict[bytes, int] = {}
            for tx in self.delivered_txs(i):
                seen[tx] = seen.get(tx, 0) + 1
            dups = sum(1 for k in seen.values() if k > 1)
            dup_max = max(dup_max, dups)
        return {
            "accepted": len(self.accepted),
            "delivered": len(delivered & self.accepted),
            "pending": len(pending & self.accepted),
            "in_flight": len((staged & self.accepted) - delivered),
            "lost": len(lost),
            "duplicates": dup_max,
        }

    def report(self, duration_s: float) -> dict:
        """Rung-shaped summary: committed-tx/s over the load window plus
        the merged submit→a_deliver percentiles across every mempool."""
        from dag_rider_tpu.utils.metrics import Histogram

        merged = Histogram()
        for mp in self.mempools:
            for s in mp.latency.samples:
                merged.observe(s)
        committed = len(self.delivered_txs(0))
        stats = [mp.stats() for mp in self.mempools]
        out = {
            "n": self.sim.cfg.n,
            "offered_tx": self.gen.emitted,
            "accepted_tx": len(self.accepted),
            "shed_tx": sum(s["shed"] for s in stats),
            "deduped_tx": sum(s["deduped"] for s in stats),
            "expired_tx": sum(s["expired"] for s in stats),
            "committed_tx": committed,
            "committed_tx_per_sec": round(committed / duration_s, 1)
            if duration_s > 0
            else 0.0,
            "blocks_built": sum(s["blocks_built"] for s in stats),
            "batch_fill": round(
                sum(s["batch_fill"] for s in stats) / max(1, len(stats)), 4
            ),
            "audit": self.audit(),
        }
        if len(merged):
            out["submit_deliver_p50_ms"] = round(1e3 * merged.percentile(50), 3)
            out["submit_deliver_p99_ms"] = round(1e3 * merged.percentile(99), 3)
        return out


def replay(sim, submission_log, *, chunk: Optional[int] = None) -> None:
    """Feed a recorded block schedule straight into ``Process.submit``
    (the legacy one-block path: no mempool, no batcher) at the same pump
    cycles that produced it, then pump to quiescence. With identical
    payload bytes the delivered transaction order must match the
    batched run byte for byte — consensus is a deterministic function
    of the proposed blocks and the delivery schedule."""
    n = sim.cfg.n
    chunk = chunk if chunk else 2 * n * n
    by_cycle: Dict[int, List[Tuple[int, Block]]] = {}
    last = 0
    for cycle, i, block in submission_log:
        by_cycle.setdefault(cycle, []).append((i, block))
        last = max(last, cycle)
    for cycle in range(last + 1):
        for i, block in by_cycle.get(cycle, ()):
            sim.processes[i].submit(block)
        sim.run(max_messages=chunk)
    for _ in range(4):
        sim.run(max_messages=16 * n * n)


def smoke(
    n: int = 4,
    seconds: float = 2.0,
    rate: float = 2000.0,
    seed: int = 7,
    profile: str = "burst",
) -> dict:
    """4-node sim under bursty load on the virtual clock: asserts clean
    agreement, zero lost accepted transactions, and no duplicate
    delivery — the CI lane's loadgen smoke."""
    from dag_rider_tpu.consensus.simulator import Simulation

    cfg = Config(
        n=n,
        coin="round_robin",
        propose_empty=True,
        gc_depth=24,
    )
    sim = Simulation(cfg)
    gen = LoadGenerator(
        clients=2 * n, rate=rate, tx_bytes=32, seed=seed, profile=profile
    )
    drv = ClusterLoadDriver(
        sim,
        gen,
        mcfg=MempoolConfig(cap=4096, batch_bytes=256, batch_deadline_ms=20.0),
    )
    rep = drv.run(seconds)
    sim.check_agreement()
    audit = rep["audit"]
    assert audit["lost"] == 0, f"lost accepted transactions: {audit}"
    assert audit["duplicates"] == 0, f"duplicate deliveries: {audit}"
    assert rep["committed_tx"] > 0, f"nothing committed: {rep}"
    return rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dag_rider_tpu.mempool.loadgen")
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--rate", type=float, default=2000.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--profile", choices=list(PROFILES), default="burst"
    )
    args = ap.parse_args(argv)
    rep = smoke(
        n=args.n,
        seconds=args.seconds,
        rate=args.rate,
        seed=args.seed,
        profile=args.profile,
    )
    print(json.dumps(rep, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
