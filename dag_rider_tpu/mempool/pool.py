"""Bounded, dedup'ing transaction pool with per-client FIFO lanes.

The pre-round-10 ingestion edge was a bare Python list of whole blocks
(``Node._submit_queue``): unbounded, no dedup, no fairness, no aging.
This pool is the buffer the batcher (batcher.py) packs blocks from:

- **bounded** — at most ``cap`` pending transactions; adds beyond that
  are refused (the admission layer normally sheds before this hard wall
  is hit, so hitting it is itself a gauge of mis-set watermarks);
- **dedup'ing** — a transaction's bytes are its identity; re-submitting
  pending bytes is a no-op (retry storms must not multiply payloads);
- **per-client FIFO lanes** — each source keeps its own arrival order,
  and the batcher drains lanes round-robin so one firehose client
  cannot starve the others out of a block;
- **TTL eviction** — accepted-but-never-packed transactions older than
  ``ttl_s`` are dropped (a stalled cluster must not pin client payloads
  forever; the eviction count is surfaced so callers see the loss).

Not thread-safe on its own: the :class:`dag_rider_tpu.mempool.Mempool`
facade serializes all access under one lock.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Tuple

from dag_rider_tpu.config import MempoolConfig


@dataclasses.dataclass
class PoolEntry:
    """One pending transaction: payload bytes + provenance + age."""

    tx: bytes
    client: str
    enqueued_at: float


class TransactionPool:
    """The pending set. See module docstring for the four properties."""

    def __init__(self, cfg: MempoolConfig) -> None:
        self.cfg = cfg
        #: tx bytes -> entry; membership here IS the dedup check
        self._by_tx: Dict[bytes, PoolEntry] = {}
        #: per-client FIFO of tx keys (the lanes)
        self._lanes: Dict[str, Deque[bytes]] = {}
        #: lane rotation for round-robin draining (client names, in
        #: first-seen order; rotated as the batcher takes)
        self._lane_order: Deque[str] = deque()
        #: global arrival FIFO of (enqueued_at, tx) for TTL scans —
        #: entries taken by the batcher go stale here and are skipped
        #: lazily (enqueued_at must still match the live entry, so a
        #: re-added duplicate of an old payload never inherits its age)
        self._arrivals: Deque[Tuple[float, bytes]] = deque()
        self._bytes = 0
        # lifetime counters (the mempool gauges' raw material)
        self.admitted = 0
        self.deduped = 0
        self.expired = 0
        self.dropped_full = 0

    def __len__(self) -> int:
        return len(self._by_tx)

    @property
    def depth_bytes(self) -> int:
        return self._bytes

    @property
    def fill(self) -> float:
        """Pool occupancy fraction in [0, 1] — the admission signal."""
        return len(self._by_tx) / self.cfg.cap

    def __contains__(self, tx: bytes) -> bool:
        return tx in self._by_tx

    def add(self, tx: bytes, client: str, now: float) -> str:
        """Try to enqueue one transaction: ``"ok" | "dup" | "full"``."""
        if tx in self._by_tx:
            self.deduped += 1
            return "dup"
        if len(self._by_tx) >= self.cfg.cap:
            self.dropped_full += 1
            return "full"
        self._by_tx[tx] = PoolEntry(tx, client, now)
        lane = self._lanes.get(client)
        if lane is None:
            lane = self._lanes[client] = deque()
            self._lane_order.append(client)
        lane.append(tx)
        self._arrivals.append((now, tx))
        self._bytes += len(tx)
        self.admitted += 1
        return "ok"

    def _remove(self, tx: bytes) -> PoolEntry:
        entry = self._by_tx.pop(tx)
        self._bytes -= len(tx)
        return entry

    def expire(self, now: float) -> List[bytes]:
        """Drop pending transactions older than ttl_s; returns the
        evicted payloads (callers release latency bookkeeping)."""
        cutoff = now - self.cfg.ttl_s
        out: List[bytes] = []
        while self._arrivals and self._arrivals[0][0] <= cutoff:
            at, tx = self._arrivals.popleft()
            entry = self._by_tx.get(tx)
            if entry is None or entry.enqueued_at != at:
                continue  # already taken (or re-added fresher): stale record
            self._remove(tx)
            out.append(tx)
        self.expired += len(out)
        return out

    def oldest_age(self, now: float) -> float:
        """Age of the oldest pending transaction (0.0 when empty) — the
        batcher's deadline trigger."""
        while self._arrivals:
            at, tx = self._arrivals[0]
            entry = self._by_tx.get(tx)
            if entry is None or entry.enqueued_at != at:
                self._arrivals.popleft()  # stale: taken by the batcher
                continue
            return max(0.0, now - at)
        return 0.0

    def take(self, max_bytes: int, max_txs: int) -> List[bytes]:
        """Pop up to ``max_bytes`` worth of transactions, round-robin
        one per client lane (fairness across sources). Always yields at
        least one transaction when non-empty, even if that single
        payload exceeds ``max_bytes`` — an oversized transaction must
        ship alone, not wedge the pool."""
        out: List[bytes] = []
        size = 0
        # one pass of empties is tolerated per take; lanes are removed
        # from rotation the moment they drain so the loop terminates
        while self._lane_order and len(out) < max_txs:
            client = self._lane_order[0]
            lane = self._lanes[client]
            # lane fronts may be stale only via expire(), which removes
            # from _by_tx but not the lane; skip those
            while lane and lane[0] not in self._by_tx:
                lane.popleft()
            if not lane:
                self._lane_order.popleft()
                del self._lanes[client]
                continue
            tx = lane[0]
            if out and size + len(tx) > max_bytes:
                break
            lane.popleft()
            self._remove(tx)
            out.append(tx)
            size += len(tx)
            self._lane_order.rotate(-1)
        return out

    # -- checkpoint support ------------------------------------------------

    def pending(self) -> List[PoolEntry]:
        """Every live entry in lane order (client FIFO preserved) — the
        checkpoint payload."""
        out: List[PoolEntry] = []
        for client in self._lane_order:
            for tx in self._lanes[client]:
                entry = self._by_tx.get(tx)
                if entry is not None:
                    out.append(entry)
        return out

    def restore(self, entries, now: float) -> int:
        """Re-admit checkpointed entries (fresh age stamps: they were
        accepted before the restart and must not be TTL'd for downtime
        the client didn't cause). Returns the count restored; dups and
        over-cap entries fall out through the normal add() accounting."""
        restored = 0
        for client, tx in entries:
            if self.add(tx, client, now) == "ok":
                restored += 1
        return restored
