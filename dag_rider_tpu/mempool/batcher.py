"""Block builder: packs pooled transactions into ``Block`` payloads.

DAG-Rider a_bcasts one block per vertex, so the bytes a vertex carries
are decided here. Two triggers, whichever fires first:

- **size** — the pool holds at least ``batch_bytes`` of payload: ship a
  full block (throughput mode; fill fraction ~1.0);
- **deadline** — the oldest pending transaction has waited
  ``batch_deadline_ms``: ship whatever is there (latency mode; bounds
  client-perceived commit latency at low load).

Packing is round-robin across client lanes (TransactionPool.take), so
block space is shared fairly under contention. The fill fraction of
every built block is recorded — persistently low fill with high
latency means the deadline is too tight for the offered load; high
fill with deep pools means ``batch_bytes`` is too small.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

from dag_rider_tpu.config import MempoolConfig
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.mempool.pool import TransactionPool

#: fill-fraction sample window (mirrors utils.metrics.SAMPLE_WINDOW's
#: bounded-deque rule without importing the metrics module here)
_FILL_WINDOW = 4096


class BlockBatcher:
    """Size-or-deadline block builder over one TransactionPool."""

    def __init__(self, cfg: MempoolConfig, pool: TransactionPool) -> None:
        self.cfg = cfg
        self.pool = pool
        #: the EFFECTIVE deadline: starts at the configured value and is
        #: retuned live by Mempool when cfg.adaptive_deadline is on (the
        #: configured batch_deadline_ms stays the ceiling)
        self.deadline_ms = float(cfg.batch_deadline_ms)
        self.blocks_built = 0
        self.txs_packed = 0
        self.fill_fractions: Deque[float] = deque(maxlen=_FILL_WINDOW)

    def ready(self, now: float) -> bool:
        if not len(self.pool):
            return False
        if self.pool.depth_bytes >= self.cfg.batch_bytes:
            return True
        return self.pool.oldest_age(now) * 1e3 >= self.deadline_ms

    def build(self, now: float, force: bool = False) -> Optional[Block]:
        """One block if a trigger fired (or ``force`` and non-empty)."""
        if not force and not self.ready(now):
            return None
        txs = self.pool.take(self.cfg.batch_bytes, self.cfg.max_batch_txs)
        if not txs:
            return None
        self.blocks_built += 1
        self.txs_packed += len(txs)
        self.fill_fractions.append(
            min(1.0, sum(len(t) for t in txs) / self.cfg.batch_bytes)
        )
        return Block(tuple(txs))

    def drain(
        self,
        now: float,
        force: bool = False,
        limit: Optional[int] = None,
    ) -> List[Block]:
        """Every block whose trigger has fired, up to ``limit``. The
        triggers are re-checked against the REMAINING pool before each
        build: several client lanes that independently aged past the
        deadline each earn their own partial block in one call (the old
        size-only re-check spent the deadline trigger on the first
        build, so lane 2's overdue traffic waited a full extra drain
        cycle — the one-partial-per-drain bug). Termination: build()
        always takes at least one transaction, so the pool strictly
        shrinks. ``force`` flushes everything regardless of triggers
        (but still honors ``limit``)."""
        out: List[Block] = []
        while limit is None or len(out) < limit:
            if not force and out and not self.ready(now):
                break
            block = self.build(now, force=force)
            if block is None:
                break
            out.append(block)
        return out

    def mean_fill(self) -> float:
        if not self.fill_fractions:
            return 0.0
        return sum(self.fill_fractions) / len(self.fill_fractions)
