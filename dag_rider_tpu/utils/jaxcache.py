"""Shared persistent XLA compilation-cache setup.

The limb-field/curve programs cost ~20-40s each to compile; every entry
point (test suite, bench, driver dryrun) wants the same repo-local cache so
repeated runs skip XLA entirely. One helper, called from all of them, so
the config knobs cannot drift apart.
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_persistent_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (default:
    ``<repo>/.jax_cache``) and cache every entry regardless of size or
    compile time."""
    import jax

    jax.config.update("jax_compilation_cache_dir", path or _DEFAULT)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
