"""ctypes seam to the native host library (native/challenge.cpp).

The build's native surface (SURVEY §2a: host-side native code in C++):
batched Ed25519 challenge-scalar computation for the verify host path.
The library is compiled on demand with ``g++ -O2 -shared -fPIC`` into the
package's ``native/`` directory and loaded with ctypes — no pybind11 /
build-system dependency. Everything degrades to the pure-Python hashlib
path when the toolchain or the compiled object is unavailable, and the
hashlib path remains the differential-testing oracle
(tests/test_native.py asserts byte-identical outputs).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_DIR, "challenge.cpp")
_SO = os.path.join(_DIR, "libdagrider_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """Compile to a temp file, then atomically rename into place — two
    processes racing a cold/stale cache must never load a half-written
    object."""
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        proc = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            capture_output=True,
            timeout=120,
        )
        if proc.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _stale() -> bool:
    try:
        if not os.path.exists(_SO):
            return True
        # No source in the deployment (prebuilt-only): use the .so as-is.
        if not os.path.exists(_SRC):
            return False
        return os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    except OSError:
        return True


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable.
    Never raises — every failure degrades to the pure-Python path."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _stale() and not _build():
                return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.dagrider_challenge_batch.argtypes = [
            u8p, u8p, u8p, u64p, ctypes.c_uint64, u8p,
        ]
        lib.dagrider_challenge_batch.restype = None
        _lib = lib
        return _lib


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def challenge_batch(
    rs: np.ndarray, pks: np.ndarray, msgs: Sequence[bytes]
) -> Optional[np.ndarray]:
    """k_i = SHA-512(R_i || A_i || M_i) mod L for the whole batch.

    rs/pks: uint8[n, 32]; msgs: n byte strings. Returns uint8[n, 32]
    little-endian scalars, or None when the native library is absent.

    Thread-safe and re-entrant: every buffer the C call reads or writes
    is allocated per call (the copies above this line are part of the
    contract, not an optimization), the library keeps no global state,
    and ctypes releases the GIL for the duration of the foreign call —
    the parallel host-prep engine (verifier/prep.py) relies on exactly
    this, invoking it concurrently from row-block worker threads so N
    blocks hash in genuinely parallel native code.
    """
    lib = load()
    if lib is None:
        return None
    n = len(msgs)
    if rs.shape != (n, 32) or pks.shape != (n, 32):
        raise ValueError("rs/pks must be uint8[n, 32]")
    rs = np.ascontiguousarray(rs, dtype=np.uint8)
    pks = np.ascontiguousarray(pks, dtype=np.uint8)
    blob = b"".join(msgs)
    offs = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    data = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(1, dtype=np.uint8)
    out = np.zeros((n, 32), dtype=np.uint8)
    lib.dagrider_challenge_batch(
        _u8(rs),
        _u8(pks),
        _u8(data),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.c_uint64(n),
        _u8(out),
    )
    return out
