"""Checkpoint / resume for a Process.

The reference keeps all state in memory with no serialization (SURVEY.md
§5 "checkpoint/resume: absent"); a crashed reference process loses its DAG
and cannot rejoin. Here the DAG's dense tensor encoding doubles as the
checkpoint format (SURVEY.md §7): ``exists``/``strong`` go into one
compressed ``.npz``, vertex payloads/signatures ride the canonical wire
codec (core/codec.py), and scalar cursors (round, decided_wave, delivered
log) go into a JSON manifest. A resumed process continues from the exact
commit point: delivered_log, buffered vertices and pending blocks are all
restored, so no vertex is a_delivered twice across a crash.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Optional

import numpy as np

from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, VertexID

MANIFEST = "manifest.json"
TENSORS = "dag.npz"
VERTICES = "vertices.bin"
MEMPOOL = "mempool.json"


class CorruptCheckpointError(ValueError):
    """A checkpoint directory failed validation before restore touched
    the process: torn manifest (kill -9 mid-save on a pre-atomic-rename
    layout), sidecar hash mismatch, or undecodable vertex bytes. The
    caller's process is guaranteed untouched — the node runtime treats
    this as "no usable checkpoint", starts empty, and recovers via the
    snapshot-sync rejoin path."""


def present(path: str) -> bool:
    """Does ``path`` hold *something that claims to be* a checkpoint?

    Distinct from :func:`latest_round` (which answers None for both
    "absent" and "unreadable"): the node runtime must distinguish a
    first boot (no manifest — start empty silently) from a torn or
    corrupt checkpoint (manifest present but restore fails — bump the
    ``checkpoint_corrupt`` counter so operators see the data loss)."""
    return os.path.exists(os.path.join(path, MANIFEST))


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_atomic(path: str, writer) -> str:
    """Write via ``writer(fh)`` to ``path + ".tmp"``, fsync, rename into
    place. Returns the sha256 hex of the written bytes. A kill -9 at any
    point leaves either the previous file or the new one — never a torn
    hybrid (os.replace is atomic on POSIX)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        writer(fh)
        fh.flush()
        os.fsync(fh.fileno())
    digest = _file_sha256(tmp)
    os.replace(tmp, path)
    return digest


def save(process, path: str, *, mempool=None) -> None:
    """Write a consistent snapshot of ``process`` into directory ``path``.

    Must be called from the process's own thread (the state machine is
    synchronous — SURVEY.md D4's fix keeps all mutation on one thread, so
    a call between step()s sees a consistent state).

    ``mempool`` (round 10): a :class:`dag_rider_tpu.mempool.Mempool`
    whose pending (accepted-but-not-yet-batched) transactions ride a
    sibling ``mempool.json`` — restart must lose no accepted
    transaction. Batched-but-undelivered payloads are already covered
    by ``blocks_to_propose`` / the DAG payloads in this snapshot, so
    pool + manifest together account for every accepted byte.
    """
    os.makedirs(path, exist_ok=True)
    exists, strong = process.dag.dense_snapshot()

    # Sidecars first, each atomically (tmp + fsync + rename), manifest
    # LAST: the manifest rename is the commit point. A kill -9 anywhere
    # in this sequence leaves either the previous complete checkpoint or
    # the new one — the only torn window is "new sidecars under the old
    # manifest", which the manifest's sidecar hashes detect at restore
    # (CorruptCheckpointError -> node starts empty and rejoins via
    # snapshot sync; accepted transactions are covered by the WAL, not
    # the checkpoint, so this loses no data).
    def _write_tensors(fh):
        np.savez_compressed(fh, exists=exists, strong=strong)

    def _write_vertices(fh):
        for v in process.dag.vertices.values():
            payload = codec.encode_vertex(v)
            fh.write(struct.pack("<I", len(payload)))
            fh.write(payload)
        # buffered (not yet admitted) vertices, tagged separately
        for v in process.buffer:
            payload = codec.encode_vertex(v)
            fh.write(struct.pack("<I", len(payload) | 0x80000000))
            fh.write(payload)

    hashes = {
        TENSORS: _write_atomic(os.path.join(path, TENSORS), _write_tensors),
        VERTICES: _write_atomic(
            os.path.join(path, VERTICES), _write_vertices
        ),
    }
    if mempool is not None:
        pool_state = mempool.checkpoint_state()

        def _write_mempool(fh):
            fh.write(json.dumps(pool_state).encode())

        hashes[MEMPOOL] = _write_atomic(
            os.path.join(path, MEMPOOL), _write_mempool
        )
    manifest = {
        "version": 1,
        "index": process.index,
        "n": process.cfg.n,
        "round": process.round,
        "decided_wave": process.decided_wave,
        # GC window cursors (absent in pre-round-4 manifests -> defaults):
        # the vertex payloads above only cover rounds >= base_round, so a
        # pruned node's checkpoint is automatically compacted to the live
        # window.
        "base_round": process.dag.base_round,
        "delivered_trimmed": process.delivered_trimmed,
        "delivered_log": [
            [vid.round, vid.source] for vid in process.delivered_log
        ],
        "waves_tried": sorted(process._waves_tried),
        "pending_waves": sorted(process._pending_waves),
        "blocks_to_propose": [
            [tx.hex() for tx in b.transactions]
            for b in process.blocks_to_propose
        ],
        # Certificate-path books (ISSUE 12): settled rounds must survive
        # a restart or a resumed aggregator would re-gossip certificates
        # (harmless but noisy) and a resumed receiver would re-pool
        # settled rounds. Banked span certs ride the canonical cert
        # codec as hex so a mid-epoch span aggregator resumes banking
        # instead of silently abandoning the epoch. Absent in older
        # manifests -> empty defaults.
        "cert_done": sorted(process._cert_done),
        "certs_sent": sorted(process._certs_sent),
        "spans_sent": sorted(process._spans_sent),
        "span_done": sorted(process._span_done),
        "span_bank": {
            str(e): [
                codec.encode_certificate(bank[r]).hex() for r in sorted(bank)
            ]
            for e, bank in process._span_bank.items()
        },
        "metrics": process.metrics.snapshot(),
        # Epoch reconfiguration cursor (ISSUE 20): epoch id, chained
        # seed, pending boundary + op batch. None/absent (pre-epoch
        # manifests) restores as static membership / epoch 0.
        "epoch": process.epoch_state(),
    }
    # Lane state (ISSUE 17): certified batch bytes + sequence cursor. A
    # crash between certification and delivery must not lose the payload
    # — the carrier ref in the DAG snapshot only names the digest; the
    # bytes live in the lane store. Pending (mid-dissemination) blocks
    # need no lane entry: ``blocks_to_propose`` above serialized their
    # original transactions, so restore degrades them to the inline
    # path. Absent in pre-lanes manifests -> lanes restore empty.
    if getattr(process, "lanes", None) is not None:
        manifest["lanes"] = process.lanes.checkpoint_state()
    # Sidecar digests: restore verifies these before touching the
    # process, so "old manifest over new sidecars" (or bit rot) is
    # detected instead of silently restoring a frankenstate.
    manifest["sha256"] = hashes

    def _write_manifest(fh):
        fh.write(json.dumps(manifest).encode())

    _write_atomic(os.path.join(path, MANIFEST), _write_manifest)


def restore(process, path: str, *, mempool=None) -> None:
    """Load a snapshot into a freshly constructed (same cfg/index) Process.

    The process must not have been started; its genesis-only DAG is
    replaced wholesale by the checkpointed one.

    ``mempool``: re-admits the checkpoint's pending transaction set
    (see :func:`save`); checkpoints written before round 10 have no
    ``mempool.json`` and restore cleanly with an empty pool.

    Raises :class:`CorruptCheckpointError` (a ValueError subclass) when
    the directory fails validation — torn/unparseable manifest, sidecar
    hash mismatch, undecodable vertex bytes, out-of-bounds cursors. All
    validation runs BEFORE the process is mutated: on any raise the
    caller's (genesis-only) process is untouched and safe to run empty.
    """
    try:
        with open(os.path.join(path, MANIFEST)) as fh:
            manifest = json.load(fh)
        if not isinstance(manifest, dict):
            raise ValueError("manifest is not an object")
    except (OSError, ValueError) as exc:
        raise CorruptCheckpointError(
            f"unreadable checkpoint manifest in {path}: {exc}"
        ) from exc
    try:
        n_claim, idx_claim = manifest["n"], manifest["index"]
    except KeyError as exc:
        raise CorruptCheckpointError(
            f"checkpoint manifest missing key {exc}"
        ) from exc
    if n_claim != process.cfg.n or idx_claim != process.index:
        raise ValueError(
            "checkpoint is for a different committee/process: "
            f"n={n_claim} index={idx_claim}"
        )
    # Sidecar integrity gate (absent in pre-round-20 manifests): a
    # kill -9 between sidecar and manifest renames leaves the OLD
    # manifest naming hashes the NEW sidecars no longer match.
    for name, want in (manifest.get("sha256") or {}).items():
        side = os.path.join(path, str(name))
        try:
            got = _file_sha256(side)
        except OSError as exc:
            raise CorruptCheckpointError(
                f"checkpoint sidecar {name} unreadable: {exc}"
            ) from exc
        if got != want:
            raise CorruptCheckpointError(
                f"checkpoint sidecar {name} hash mismatch "
                f"(manifest {want[:12]}.., file {got[:12]}..)"
            )
    try:
        with open(os.path.join(path, VERTICES), "rb") as fh:
            data = fh.read()
        offset = 0
        admitted, buffered = [], []
        while offset < len(data):
            (tag,) = struct.unpack_from("<I", data, offset)
            offset += 4
            ln = tag & 0x7FFFFFFF
            v, _ = codec.decode_vertex(data[offset : offset + ln])
            offset += ln
            (buffered if tag & 0x80000000 else admitted).append(v)
    except (OSError, struct.error, ValueError, IndexError) as exc:
        raise CorruptCheckpointError(
            f"checkpoint vertex log undecodable: {exc}"
        ) from exc
    # Cursor/bounds validation BEFORE any mutation (the raise-after-
    # reset path would otherwise leave the caller's process torn).
    try:
        base_claim = int(manifest.get("base_round", 0))
        round_claim = int(manifest["round"])
        wave_claim = int(manifest["decided_wave"])
        delivered_claim = [
            (int(r), int(s)) for r, s in manifest["delivered_log"]
        ]
        # epoch cursor (ISSUE 20): dry-parse before any mutation so a
        # torn epoch section fails the whole restore atomically
        epoch_claim = manifest.get("epoch")
        if epoch_claim is not None:
            int(epoch_claim.get("epoch", 0))
            bytes.fromhex(epoch_claim.get("seed") or "")
            for wave, kind, target, nonce, payload in epoch_claim.get(
                "pending_ops", []
            ):
                int(wave), str(kind), int(target), int(nonce)
                bytes.fromhex(payload)
    except (KeyError, TypeError, ValueError, AttributeError) as exc:
        raise CorruptCheckpointError(
            f"checkpoint manifest cursors invalid: {exc}"
        ) from exc
    top_claim = max(
        [base_claim] + [v.round for v in admitted], default=base_claim
    )
    n = process.cfg.n
    for r, s in delivered_claim:
        if not (0 <= s < n) or r < base_claim or r > top_claim:
            raise CorruptCheckpointError(
                f"corrupt checkpoint: delivered entry ({r}, {s}) out of "
                f"bounds for n={n}, base_round={base_claim}"
            )
    del round_claim, wave_claim  # validated for type only
    # Rebuild the DAG in round order so insert()'s invariants hold. The
    # admission gate re-runs for every round>=1 vertex: the hot paths
    # (dense-mirror fancy indexing in dag.insert / _drain_buffer) rely on
    # gate-validated edge bounds, and a corrupted or crafted checkpoint
    # must fail safe (vertex dropped) rather than alias numpy indices.
    process.dag.reset()
    process.dag.base_round = manifest.get("base_round", 0)
    process.dag.max_round = process.dag.base_round
    process.delivered_trimmed = manifest.get("delivered_trimmed", 0)
    for v in sorted(admitted, key=lambda v: (v.round, v.source)):
        if v.round >= 1 and not process.edges_valid(v):
            process.log.event(
                "restore_drop_invalid", round=v.round, source=v.source
            )
            continue
        process.dag.insert(v)
        if v.round >= 1:
            process._note_seen(v)
            process._observe_coin_share(v)
    for v in buffered:
        if not process.edges_valid(v):
            process.log.event(
                "restore_drop_invalid", round=v.round, source=v.source
            )
            continue
        process._admit_to_buffer(v)
        process._note_seen(v)
    process.round = manifest["round"]
    process.decided_wave = manifest["decided_wave"]
    process._waves_tried = set(manifest["waves_tried"])
    # A wave pending on an unready coin at save time must re-enter
    # _try_wave after restore, or its direct commit is silently skipped
    # (round-2 VERDICT weak #7). Older manifests lack the key; recompute
    # conservatively: every tried-but-undecided wave re-arms (re-trying a
    # decided wave is a no-op — _try_wave guards on decided_wave).
    process._pending_waves = set(
        manifest.get(
            "pending_waves",
            [
                w
                for w in manifest["waves_tried"]
                if w > manifest["decided_wave"]
            ],
        )
    )
    # Bounds were pre-validated against the manifest's claimed window;
    # re-check against the dense state actually built (an edges_valid
    # drop can shrink max_round below the claim — a delivered entry
    # pointing past it would alias a numpy index into a silent order
    # divergence, so fail loudly instead).
    base = process.dag.base_round
    log = []
    for r, s in delivered_claim:
        if r > process.dag.max_round:
            raise CorruptCheckpointError(
                f"corrupt checkpoint: delivered entry ({r}, {s}) beyond "
                f"restored window top {process.dag.max_round}"
            )
        log.append(VertexID(r, s))
    process.delivered_log = log
    process._rebuild_delivered_mask()
    # A reliable-broadcast stage's slot floor must follow the restored
    # window, or replayed frames for retired rounds regrow its books
    # until the next wave decision prunes (round-4 review).
    tp_prune = getattr(process.transport, "prune_below", None)
    if tp_prune is not None:
        tp_prune(process.dag.base_round)
    process.blocks_to_propose.clear()
    for txs in manifest["blocks_to_propose"]:
        process.blocks_to_propose.append(
            Block(tuple(bytes.fromhex(tx) for tx in txs))
        )
    process._cert_done = set(manifest.get("cert_done", []))
    process._certs_sent = set(manifest.get("certs_sent", []))
    process._spans_sent = set(manifest.get("spans_sent", []))
    process._span_done = set(manifest.get("span_done", []))
    span_bank = {}
    for e, rows in manifest.get("span_bank", {}).items():
        bank = {}
        for hx in rows:
            c, _ = codec.decode_certificate(bytes.fromhex(hx))
            bank[c.round] = c
        span_bank[int(e)] = bank
    process._span_bank = span_bank
    # Lane store: entries are re-hashed on load (corrupt bytes dropped,
    # recovered later via fetch-on-miss). Pre-lanes manifests carry no
    # "lanes" key and restore with an empty store.
    if getattr(process, "lanes", None) is not None:
        process.lanes.restore_state(manifest.get("lanes"))
    if mempool is not None:
        mp_path = os.path.join(path, MEMPOOL)
        if os.path.exists(mp_path):
            with open(mp_path) as fh:
                mempool.restore_state(json.load(fh))
    # Epoch cursor last: restoring it rotates the coin schedule, which
    # must see the already-restored decided/wave state. Pre-epoch
    # manifests carry no key and leave the manager at epoch 0.
    process.restore_epoch_state(manifest.get("epoch"))


# ---------------------------------------------------------------------------
# Peer state transfer (snapshot sync) — the elastic-recovery path for a node
# that fell below the cluster's GC horizon (anti-entropy sync is refused for
# pruned windows; see Process._serve_sync / _on_sync_nack).
#
# Trust model: the donor is UNTRUSTED. The snapshot carries only
# self-certifying data — the donor's live vertex window (every round>=1
# vertex is Ed25519-signed by its author) plus the window floor. The
# receiver verifies every signature, re-runs the admission gate, and
# REPLAYS consensus locally (coin shares ride on the vertices), so
# decided/delivered state is recomputed, never imported. A lying floor
# (higher than honest) would shrink the window below gc_depth and is
# rejected; a censored window breaks admission/quorum chains and fails the
# same check — the caller then tries another peer.
# ---------------------------------------------------------------------------


def snapshot_bytes(process) -> bytes:
    """Serialize the live DAG window for peer state transfer.

    May be called from a serving thread (the Snapshot RPC handler) while
    the pump thread mutates the DAG: the vertex objects are immutable, so
    the only hazard is the dict changing size mid-copy — retried with a
    base-cursor consistency check (a single C-level ``list()`` copy per
    attempt keeps the race window tiny)."""
    for _ in range(8):
        base = process.dag.base_round
        try:
            vertices = list(process.dag.vertices.values())
        except RuntimeError:  # resized mid-iteration: retry
            continue
        top = process.dag.max_round
        if process.dag.base_round != base:
            continue  # pruned mid-copy: the window moved, retry
        head_obj = {
            "version": 1,
            "n": process.cfg.n,
            "base_round": base,
            "max_round": top,
        }
        # Epoch cursor (ISSUE 20): the joiner must land on the donor's
        # epoch + chained seed or its rotated coin keys diverge from the
        # survivors'. Omitted entirely pre-epoch, so epoch-less
        # snapshots stay byte-identical to the previous format.
        _es = getattr(process, "epoch_state", None)
        epoch_state = _es() if _es is not None else None
        if epoch_state is not None:
            head_obj["epoch"] = epoch_state
        head = json.dumps(head_obj).encode()
        out = [struct.pack("<I", len(head)), head]
        for v in vertices:
            if v.round < base:
                continue  # retired while copying
            payload = codec.encode_vertex(v)
            out.append(struct.pack("<I", len(payload)))
            out.append(payload)
        return b"".join(out)
    return b""  # persistently racing prunes: refuse this request


# -- span-attested snapshot envelope (ISSUE 20) -----------------------------
#
# Layout: magic, u32 span count, then each span certificate u32-length-
# prefixed in the canonical cert codec, then the plain snapshot blob.
# A plain (un-enveloped) blob is still accepted everywhere — attestation
# is an overlay, exactly like the span path it reuses: the receiver pays
# ONE combined pairing check per span and may then admit every vertex
# whose digest the verified span restates WITHOUT a per-vertex signature
# check (the quorum already BLS-co-signed those digests), instead of
# replaying the window vertex by vertex.

SNAP_ATTEST_MAGIC = b"DRsnapA1"


def wrap_attested(blob: bytes, spans) -> bytes:
    """Envelope ``blob`` with its attesting span-certificate chain."""
    out = [SNAP_ATTEST_MAGIC, struct.pack("<I", len(spans))]
    for s in spans:
        enc = codec.encode_span_certificate(s)
        out.append(struct.pack("<I", len(enc)))
        out.append(enc)
    out.append(blob)
    return b"".join(out)


def unwrap_attested(data: bytes):
    """Split an attested envelope into (spans, inner blob).

    Plain blobs pass through as ``(None, data)``. A magic-prefixed blob
    that does not parse cleanly — truncated span section, trailing
    garbage inside a span, short header — raises ValueError: a torn
    envelope must refuse wholesale, never degrade to "unattested"."""
    if not data.startswith(SNAP_ATTEST_MAGIC):
        return None, data
    off = len(SNAP_ATTEST_MAGIC)
    if off + 4 > len(data):
        raise ValueError("attested snapshot: truncated span count")
    (count,) = struct.unpack_from("<I", data, off)
    off += 4
    spans = []
    for _ in range(count):
        if off + 4 > len(data):
            raise ValueError("attested snapshot: truncated span section")
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        if off + ln > len(data):
            raise ValueError("attested snapshot: span overruns envelope")
        span, used = codec.decode_span_certificate(data[off : off + ln])
        if used != ln:
            raise ValueError("attested snapshot: span section malformed")
        spans.append(span)
        off += ln
    return spans, data[off:]


def attested_snapshot_bytes(process) -> bytes:
    """:func:`snapshot_bytes` enveloped with the donor's verified span
    chain (``Process._span_chain``) for the covered window. Falls back
    to the plain blob when the donor holds no spans (span path off, or
    a window younger than the first assembled span)."""
    blob = snapshot_bytes(process)
    if not blob:
        return b""
    chain = getattr(process, "_span_chain", None)
    if not chain:
        return blob
    base = process.dag.base_round
    spans = [
        chain[e] for e in sorted(chain) if chain[e].last_round > base
    ]
    if not spans:
        return blob
    process.metrics.inc("snapshot_spans_attached", len(spans))
    process.log.event(
        "snapshot_attested", spans=len(spans), base=base
    )
    return wrap_attested(blob, spans)


def restore_from_snapshot(
    process, blob: bytes, verifier=None, span_verifier=None
) -> bool:
    """Rebuild a process (fresh OR live-but-stuck — the node runtime
    calls this on its started process from the pump thread) from an
    untrusted peer snapshot. ATOMIC: the window is validated and staged
    into a scratch DagState first, and the process is only touched on
    full success — a malicious or broken snapshot returns False with the
    caller's state completely intact (a single Byzantine donor must not
    be able to wipe a victim's live DAG).

    Refused outright (False) when: cfg.gc_depth is None (the exclusion
    rule is what makes the import sound), the claimed floor does not
    strictly exceed our round (a no-progress/rewind snapshot would
    duplicate deliveries — normal sync covers that case), the window is
    thinner than gc_depth after filtering, a duplicate (round, source)
    appears, or the bytes/committee are wrong.

    ``verifier``: the Verifier seam used to batch-check every round>=1
    vertex signature; None skips signature checks (signature-less
    deployments only — matching the reference's no-crypto mode).

    ``span_verifier`` (ISSUE 20): a CertVerifier used to check an
    attested envelope's span chain — one combined pairing per span.
    Vertices whose digests a verified span restates are admitted
    without a per-vertex signature check (the quorum already BLS-
    co-signed those digests); a digest mismatch against a verified span
    means the donor tampered with the window and refuses it wholesale.
    With ``span_verifier=None`` the envelope's spans are ignored and
    every vertex pays the ordinary signature check — attestation only
    ever removes work, never trust.
    """
    from dag_rider_tpu.consensus.dag_state import DagState
    from dag_rider_tpu.core.types import Vertex as _V

    try:
        spans, blob = unwrap_attested(blob)
    except (ValueError, struct.error):
        # torn or tampered envelope: refused wholesale, never degraded
        # to "unattested"
        process.metrics.inc("snapshot_attest_rejects")
        process.log.event("snapshot_attest_reject", reason="envelope")
        return False
    try:
        (hlen,) = struct.unpack_from("<I", blob, 0)
        head = json.loads(blob[4 : 4 + hlen])
        offset = 4 + hlen
        vertices = []
        while offset < len(blob):
            (ln,) = struct.unpack_from("<I", blob, offset)
            offset += 4
            v, _ = codec.decode_vertex(blob[offset : offset + ln])
            offset += ln
            vertices.append(v)
    except Exception:
        return False
    if not isinstance(head, dict):
        return False  # valid JSON, wrong shape (e.g. a bare list/number)
    if head.get("n") != process.cfg.n or head.get("version") != 1:
        return False
    try:
        base = int(head.get("base_round", 0))
        head_max = int(head.get("max_round", 1 << 62))
        # dry-parse the optional epoch section (ISSUE 20) BEFORE any
        # commit below — a malformed section refuses wholesale, it must
        # never leave the DAG imported under the wrong coin keys
        _ep = head.get("epoch")
        if _ep is not None:
            int(_ep.get("epoch", 0))
            bytes.fromhex(_ep.get("seed") or "")
            for _w, _k, _t, _nc, _pl in _ep.get("pending_ops", []):
                int(_w), str(_k), int(_t), int(_nc)
                bytes.fromhex(_pl)
    except (TypeError, ValueError, AttributeError, KeyError):
        return False
    if base < 0:
        return False
    gc = process.cfg.gc_depth
    if gc is None:
        # State transfer is only sound under the GC ordering-exclusion
        # rule (it is what makes rounds below the floor undeliverable
        # everywhere); without it, importing a window and resetting
        # delivery state could duplicate or lose deliveries.
        return False
    if base <= process.round:
        # No-progress (or REWIND) snapshot: our round already covers the
        # claimed floor, so ordinary anti-entropy sync can serve us — and
        # accepting it would reset delivered state for rounds we already
        # emitted (duplicate a_deliver). Only windows strictly above our
        # progress are state-transfer material.
        return False
    # Span attestation (ISSUE 20): verify the chain — ONE combined
    # pairing per span — then fold every attested (round, source) ->
    # digest claim into a lookup the admission pass below consults.
    span_good = {}
    if spans is not None and span_verifier is not None:
        for s in spans:
            process.metrics.inc("snapshot_pairing_checks")
            if not span_verifier.verify_span(s):
                process.metrics.inc("snapshot_attest_rejects")
                process.log.event(
                    "snapshot_attest_reject",
                    reason="span",
                    first_round=s.first_round,
                )
                return False
            process.metrics.inc("snapshot_spans_verified")
            for i in range(len(s.signers)):
                r = s.first_round + i
                for src, dg in zip(s.signers[i], s.digests[i]):
                    span_good[(r, src)] = dg
    signed = [v for v in vertices if v.round >= 1]
    if span_good:
        # Attested digests substitute for per-vertex signature checks:
        # the span's quorum BLS-co-signed exactly these (round, source,
        # digest) claims, so a byte-identical vertex needs no second
        # proof of authorship — this is what makes a joiner's sync cost
        # ~1 pairing per settled span instead of a vertex-by-vertex
        # replay. A MISMATCHED digest is donor tampering: refuse.
        need, pre = [], set()
        for v in signed:
            want = span_good.get((v.round, v.source))
            if want is None:
                need.append(v)
            elif want == (v.__dict__.get("_digest") or v.digest()):
                pre.add(v.id)
            else:
                process.metrics.inc("snapshot_attest_rejects")
                process.log.event(
                    "snapshot_attest_reject",
                    reason="digest",
                    round=v.round,
                    source=v.source,
                )
                return False
    else:
        need, pre = signed, set()
    if verifier is not None:
        ok = verifier.verify_batch(need)
        good = pre | {v.id for v, m in zip(need, ok) if m}
    else:
        good = {v.id for v in signed}
    usable = [
        v
        for v in sorted(vertices, key=lambda v: (v.round, v.source))
        if v.round >= max(base, 1)
        and v.id in good
        and process.edges_valid(v)
    ]

    # ---- stage into a scratch DAG (the live process stays untouched) ----
    staged = DagState(process.cfg)
    staged.base_round = base
    staged.max_round = base
    have: set = set()
    if base == 0:
        for i in range(process.cfg.n):
            staged.insert(_V(id=VertexID(0, i)))
        have = {(0, i) for i in range(process.cfg.n)}
    accepted = []
    for v in usable:
        # Edges must be satisfied within the snapshot itself (round base
        # is the axiom row — its predecessors were retired by the donor's
        # GC, which the ordering-exclusion rule already finalized; weak
        # targets at or below the floor are final for the same reason).
        if v.round > base:
            _, ss, wr, ws = v.edge_arrays()
            if any((v.round - 1, int(s)) not in have for s in ss) or any(
                int(r) > base and (int(r), int(s2)) not in have
                for r, s2 in zip(wr, ws)
            ):
                continue
        try:
            staged.insert(v)
        except ValueError:
            # duplicate (round, source) in the snapshot: an equivocating
            # pair smuggled past the donor's RBC, or plain corruption —
            # either way the window is ambiguous; refuse it wholesale
            # (the ATOMIC contract: the live process stays untouched).
            return False
        have.add((v.round, v.source))
        accepted.append(v)
    top = staged.max_round
    if top > head_max:
        return False  # header inconsistent with its own payload
    # Window-width check: an honest donor's window spans >= gc_depth
    # rounds AFTER filtering (floor = decided_r1 - gc_depth and the
    # frontier sits at or above decided_r1). A lying floor, a censored
    # window, or broken admission chains all fail here and the snapshot
    # is refused wholesale. (gc is non-None here — gc-less configs were
    # refused up front.)
    if top - base < gc:
        return False

    # ---- commit: swap the staged window in and reset replay state ----
    # Replay cursors: the consensus state machine resumes at the
    # frontier; wave decisions from here retro-walk the imported window
    # (pruned leaders below the floor terminate the chain), and the GC
    # ordering rule keeps this node's deliveries the exact suffix every
    # correct process emits above the horizon. Live admission state from
    # the pre-transfer view (buffer, memos, pending verifies) is dropped
    # wholesale — live traffic re-supplies anything still relevant.
    process.dag = staged
    process.buffer = []
    process._buffered_ids = set()
    process._blocked_on = {}
    process._pending_verify = []
    process._pending_verify_ids = set()
    process._stuck_steps = 0
    process._seen_digests = {}
    for v in accepted:
        process._note_seen(v)
    for v in accepted:
        process._observe_coin_share(v)
    process.round = top
    process.decided_wave = 0
    process._waves_tried = set()
    process._pending_waves = set()
    process._deferred_orders.clear()
    process.delivered_log = []
    process.delivered_trimmed = 0
    process._rebuild_delivered_mask()
    process.state_transfer_needed = False
    process._horizon_nacks.clear()
    tp_prune = getattr(process.transport, "prune_below", None)
    if tp_prune is not None:
        tp_prune(base)
    if base >= 1:
        # a live laggard's pre-transfer share books are below the new
        # floor too (same class as the RBC books two lines up)
        process.coin.prune_below(process.cfg.wave_of_round(base))
    # Epoch cursor (ISSUE 20): land on the donor's epoch + seed so this
    # node's rotated coin keys match the survivors' — the snapshot head
    # is covered by the same trust argument as the window itself (a
    # lying epoch/seed diverges the coin and fails liveness locally,
    # never corrupts peers). Pre-epoch heads carry no key -> epoch 0.
    _res = getattr(process, "restore_epoch_state", None)
    if _res is not None:
        try:
            _res(head.get("epoch"))
        except (KeyError, TypeError, ValueError, AttributeError):
            # malformed epoch section from an untrusted donor: the DAG
            # import above is still sound (it never depended on epoch
            # state), so keep it and stay at the local epoch cursor
            process.log.event(
                "snapshot_attest_reject", reason="epoch_head"
            )
    inserted = len(accepted)
    process.metrics.inc("state_transfers")
    process.log.event(
        "state_transfer", base=base, top=top, vertices=inserted
    )
    return True


def latest_round(path: str) -> Optional[int]:
    """Peek a checkpoint's round cursor without loading it."""
    try:
        with open(os.path.join(path, MANIFEST)) as fh:
            return json.load(fh)["round"]
    except (OSError, KeyError, json.JSONDecodeError):
        return None
