"""Checkpoint / resume for a Process.

The reference keeps all state in memory with no serialization (SURVEY.md
§5 "checkpoint/resume: absent"); a crashed reference process loses its DAG
and cannot rejoin. Here the DAG's dense tensor encoding doubles as the
checkpoint format (SURVEY.md §7): ``exists``/``strong`` go into one
compressed ``.npz``, vertex payloads/signatures ride the canonical wire
codec (core/codec.py), and scalar cursors (round, decided_wave, delivered
log) go into a JSON manifest. A resumed process continues from the exact
commit point: delivered_log, buffered vertices and pending blocks are all
restored, so no vertex is a_delivered twice across a crash.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Optional

import numpy as np

from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, VertexID

MANIFEST = "manifest.json"
TENSORS = "dag.npz"
VERTICES = "vertices.bin"


def save(process, path: str) -> None:
    """Write a consistent snapshot of ``process`` into directory ``path``.

    Must be called from the process's own thread (the state machine is
    synchronous — SURVEY.md D4's fix keeps all mutation on one thread, so
    a call between step()s sees a consistent state).
    """
    os.makedirs(path, exist_ok=True)
    exists, strong = process.dag.dense_snapshot()
    np.savez_compressed(
        os.path.join(path, TENSORS), exists=exists, strong=strong
    )
    with open(os.path.join(path, VERTICES), "wb") as fh:
        for v in process.dag.vertices.values():
            payload = codec.encode_vertex(v)
            fh.write(struct.pack("<I", len(payload)))
            fh.write(payload)
        # buffered (not yet admitted) vertices, tagged separately
        for v in process.buffer:
            payload = codec.encode_vertex(v)
            fh.write(struct.pack("<I", len(payload) | 0x80000000))
            fh.write(payload)
    manifest = {
        "version": 1,
        "index": process.index,
        "n": process.cfg.n,
        "round": process.round,
        "decided_wave": process.decided_wave,
        # GC window cursors (absent in pre-round-4 manifests -> defaults):
        # the vertex payloads above only cover rounds >= base_round, so a
        # pruned node's checkpoint is automatically compacted to the live
        # window.
        "base_round": process.dag.base_round,
        "delivered_trimmed": process.delivered_trimmed,
        "delivered_log": [
            [vid.round, vid.source] for vid in process.delivered_log
        ],
        "waves_tried": sorted(process._waves_tried),
        "pending_waves": sorted(process._pending_waves),
        "blocks_to_propose": [
            [tx.hex() for tx in b.transactions]
            for b in process.blocks_to_propose
        ],
        "metrics": process.metrics.snapshot(),
    }
    tmp = os.path.join(path, MANIFEST + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, os.path.join(path, MANIFEST))


def restore(process, path: str) -> None:
    """Load a snapshot into a freshly constructed (same cfg/index) Process.

    The process must not have been started; its genesis-only DAG is
    replaced wholesale by the checkpointed one.
    """
    with open(os.path.join(path, MANIFEST)) as fh:
        manifest = json.load(fh)
    if manifest["n"] != process.cfg.n or manifest["index"] != process.index:
        raise ValueError(
            "checkpoint is for a different committee/process: "
            f"n={manifest['n']} index={manifest['index']}"
        )
    with open(os.path.join(path, VERTICES), "rb") as fh:
        data = fh.read()
    offset = 0
    admitted, buffered = [], []
    while offset < len(data):
        (tag,) = struct.unpack_from("<I", data, offset)
        offset += 4
        ln = tag & 0x7FFFFFFF
        v, _ = codec.decode_vertex(data[offset : offset + ln])
        offset += ln
        (buffered if tag & 0x80000000 else admitted).append(v)
    # Rebuild the DAG in round order so insert()'s invariants hold. The
    # admission gate re-runs for every round>=1 vertex: the hot paths
    # (dense-mirror fancy indexing in dag.insert / _drain_buffer) rely on
    # gate-validated edge bounds, and a corrupted or crafted checkpoint
    # must fail safe (vertex dropped) rather than alias numpy indices.
    process.dag.reset()
    process.dag.base_round = manifest.get("base_round", 0)
    process.dag.max_round = process.dag.base_round
    process.delivered_trimmed = manifest.get("delivered_trimmed", 0)
    for v in sorted(admitted, key=lambda v: (v.round, v.source)):
        if v.round >= 1 and not process.edges_valid(v):
            process.log.event(
                "restore_drop_invalid", round=v.round, source=v.source
            )
            continue
        process.dag.insert(v)
        if v.round >= 1:
            process._seen_digests[v.id] = v.digest()
            process._observe_coin_share(v)
    for v in buffered:
        if not process.edges_valid(v):
            process.log.event(
                "restore_drop_invalid", round=v.round, source=v.source
            )
            continue
        process._admit_to_buffer(v)
        process._seen_digests[v.id] = v.digest()
    process.round = manifest["round"]
    process.decided_wave = manifest["decided_wave"]
    process._waves_tried = set(manifest["waves_tried"])
    # A wave pending on an unready coin at save time must re-enter
    # _try_wave after restore, or its direct commit is silently skipped
    # (round-2 VERDICT weak #7). Older manifests lack the key; recompute
    # conservatively: every tried-but-undecided wave re-arms (re-trying a
    # decided wave is a no-op — _try_wave guards on decided_wave).
    process._pending_waves = set(
        manifest.get(
            "pending_waves",
            [
                w
                for w in manifest["waves_tried"]
                if w > manifest["decided_wave"]
            ],
        )
    )
    # Bounds-validate before touching dense state: a crafted/corrupted
    # manifest entry must fail the restore loudly, not alias a numpy
    # index (negative source) into a silent order divergence.
    n = process.cfg.n
    base = process.dag.base_round
    log = []
    for r, s in manifest["delivered_log"]:
        if not (0 <= s < n) or r < base or r > process.dag.max_round:
            raise ValueError(
                f"corrupt checkpoint: delivered entry ({r}, {s}) out of "
                f"bounds for n={n}, base_round={base}"
            )
        log.append(VertexID(r, s))
    process.delivered_log = log
    process._rebuild_delivered_mask()
    process.blocks_to_propose.clear()
    for txs in manifest["blocks_to_propose"]:
        process.blocks_to_propose.append(
            Block(tuple(bytes.fromhex(tx) for tx in txs))
        )


def latest_round(path: str) -> Optional[int]:
    """Peek a checkpoint's round cursor without loading it."""
    try:
        with open(os.path.join(path, MANIFEST)) as fh:
            return json.load(fh)["round"]
    except (OSError, KeyError, json.JSONDecodeError):
        return None
