"""Structured event logging — the L5 observability layer.

The reference logs three Debug lines through charon's zap wrapper
(``process/process.go:109,213,220``); SURVEY §5 asks the build to do
better. This is a structured *event* log: named events with key-value
fields and per-logger context (process index, node name), with a
pluggable sink so the same call sites serve tests (capture list), CLI
runs (stdlib logging), and production (anything that accepts one dict).

Zero cost when disabled: the default sink is None and ``event()`` is a
single attribute test — consensus hot loops can log unconditionally.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional

# A sink receives one flat dict per event.
Sink = Callable[[Dict[str, object]], None]


class EventLog:
    """Named events + bound context, fanned into one sink."""

    __slots__ = ("sink", "context")

    def __init__(self, sink: Optional[Sink] = None, **context: object):
        self.sink = sink
        self.context = context

    def event(self, name: str, **fields: object) -> None:
        if self.sink is None:
            return
        rec: Dict[str, object] = {"event": name, "ts": time.time()}
        rec.update(self.context)
        rec.update(fields)
        self.sink(rec)

    def child(self, **context: object) -> "EventLog":
        """Same sink, extended context (e.g. per-process index)."""
        merged = dict(self.context)
        merged.update(context)
        return EventLog(self.sink, **merged)

    @property
    def enabled(self) -> bool:
        return self.sink is not None


#: Shared disabled logger — the default for every component.
NOOP = EventLog()


def capture() -> tuple:
    """(log, records): an EventLog whose events append to ``records``."""
    records: List[Dict[str, object]] = []
    return EventLog(records.append), records


def stdlib_sink(
    logger: Optional[logging.Logger] = None, level: int = logging.DEBUG
) -> Sink:
    """Bridge into stdlib logging: one JSON line per event."""
    lg = logger if logger is not None else logging.getLogger("dag_rider_tpu")

    def sink(rec: Dict[str, object]) -> None:
        if lg.isEnabledFor(level):  # skip the JSON encode when filtered
            lg.log(level, "%s", json.dumps(rec, default=repr, sort_keys=True))

    return sink
