"""Structured event logging — the L5 observability layer.

The reference logs three Debug lines through charon's zap wrapper
(``process/process.go:109,213,220``); SURVEY §5 asks the build to do
better. This is a structured *event* log: named events with key-value
fields and per-logger context (process index, node name), with a
pluggable sink so the same call sites serve tests (capture list), CLI
runs (stdlib logging), and production (anything that accepts one dict).

Zero cost when disabled: the default sink is None and ``event()`` is a
single attribute test — consensus hot loops can log unconditionally.

The timestamp clock is injectable (same convention as the round-14
``transport/net.py`` wall-clock injection): pass ``clock=`` to pin event
stamps to a virtual or frozen clock in tests; the default stays
``time.time`` by reference, never read at import.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional

# A sink receives one flat dict per event.
Sink = Callable[[Dict[str, object]], None]

#: Every event name any module may emit (round 16, mirroring
#: ``utils.metrics.KNOWN_COUNTERS``). The driderlint events checker
#: (analysis/events.py) rejects a literal ``log.event("...")`` whose
#: name is not registered here — a typo'd event name silently creates a
#: record no trace report, flight dump, or dashboard will ever join on.
KNOWN_EVENTS = frozenset(
    {
        # consensus/process.py — admission, rounds, waves, sync
        "admit",
        "attested_floor",
        "behind_horizon",
        "delivered",
        "equivocation",
        "pruned",
        "reject_edges",
        "reject_signature",
        "reject_stamp",
        "round_advance",
        "sync_refuse_pruned",
        "sync_request",
        "sync_serve",
        "wave_decided",
        "wave_pending_chain_coin",
        "wave_pending_coin",
        "wave_skip",
        # pipelined waves + eager optimistic delivery (ISSUE 16)
        "eager_deliver",
        "eager_reconciled",
        "eager_mismatch",
        "deadline_adapted",
        # aggregated certificates + cert-of-certs
        "cert_assembled",
        "cert_degraded",
        "cert_reject",
        "cert_timeout",
        "span_assembled",
        "span_reject",
        "span_timeout",
        # node.py lifecycle + checkpointing
        "checkpointed",
        "pump_error",
        "restore_drop_invalid",
        "restored",
        "state_transfer",
        "state_transfer_attempt_failed",
        "state_transfer_failed",
        "state_transferred",
        "stop_drain_error",
        "stop_pump_hung",
        # mempool admission decisions (round 16, satellite b)
        "mempool_state",
        "mempool_shed",
        # resilient-verifier ladder transitions (round 16, satellite b)
        "verify_retry",
        "verify_fallback",
        "verify_tier_down",
        "verify_tier_recovered",
        "verify_exhausted",
        "verify_window_poisoned",
        "verify_quarantined",
        # dissemination lanes (ISSUE 17)
        "lane_batch",
        "lane_certified",
        "lane_degrade",
        "lane_fetch",
        "lane_restore",
        # transport wire health
        "net_peer_down",
        "net_peer_recovered",
        # cluster harness (ISSUE 19): crash-recovery lifecycle
        "checkpoint_corrupt",
        "cluster_reinject",
        # obs/ causal tracing (round 16 tentpole): sampled transaction
        # lifecycle stamps + per-cycle phase spans
        "tx_submit",
        "tx_batch",
        "tx_propose",
        "tx_deliver",
        "phase_pump",
        "phase_verify",
        "phase_cert",
        # flight-recorder triggers + bookkeeping
        "invariant_violation",
        "flight_dump",
        # epoch reconfiguration (ISSUE 20)
        "epoch_scheduled",
        "epoch_advanced",
        "epoch_stale",
        "snapshot_attested",
        "snapshot_attest_reject",
    }
)


class EventLog:
    """Named events + bound context, fanned into one sink.

    ``names`` (optional) is an event-name filter: when set, events not
    in the set return after ONE frozenset membership test — no record
    build, no clock read, no sink call. The obs tracing bundle uses it
    to keep per-message debug chatter (``admit``/``delivered`` fire once
    per delivered message) off the hot path while the lifecycle/phase/
    transition events it joins on are recorded; ``names=None`` (the
    default, and what :func:`capture` builds) records everything.
    """

    __slots__ = ("sink", "clock", "context", "names")

    def __init__(
        self,
        sink: Optional[Sink] = None,
        *,
        clock: Callable[[], float] = time.time,
        names: Optional[frozenset] = None,
        **context: object,
    ):
        self.sink = sink
        self.clock = clock
        self.names = names
        self.context = context

    def event(self, name: str, **fields: object) -> None:
        if self.sink is None:
            return
        if self.names is not None and name not in self.names:
            return
        rec: Dict[str, object] = {"event": name, "ts": self.clock()}
        rec.update(self.context)
        rec.update(fields)
        self.sink(rec)

    def child(self, **context: object) -> "EventLog":
        """Same sink, clock, and name filter, extended context (e.g.
        per-process index)."""
        merged = dict(self.context)
        merged.update(context)
        return EventLog(
            self.sink, clock=self.clock, names=self.names, **merged
        )

    @property
    def enabled(self) -> bool:
        return self.sink is not None

    def wants(self, name: str) -> bool:
        """Would an event of this name actually be recorded? Hot loops
        emitting per-message events cache this to skip even the call
        (kwargs packing alone is measurable at consensus pump rates)."""
        return self.sink is not None and (
            self.names is None or name in self.names
        )


#: Shared disabled logger — the default for every component.
NOOP = EventLog()


def capture(clock: Callable[[], float] = time.time) -> tuple:
    """(log, records): an EventLog whose events append to ``records``."""
    records: List[Dict[str, object]] = []
    return EventLog(records.append, clock=clock), records


def tee(*sinks: Optional[Sink]) -> Sink:
    """Fan one event stream into several sinks (None entries skipped) —
    how a trace ring, a flight-recorder trigger watch, and a stdlib
    bridge share the same EventLog."""
    live = [s for s in sinks if s is not None]

    def sink(rec: Dict[str, object]) -> None:
        for s in live:
            s(rec)

    return sink


def stdlib_sink(
    logger: Optional[logging.Logger] = None, level: int = logging.DEBUG
) -> Sink:
    """Bridge into stdlib logging: one JSON line per event."""
    lg = logger if logger is not None else logging.getLogger("dag_rider_tpu")

    def sink(rec: Dict[str, object]) -> None:
        if lg.isEnabledFor(level):  # skip the JSON encode when filtered
            lg.log(level, "%s", json.dumps(rec, default=repr, sort_keys=True))

    return sink
