"""Consensus observability counters.

The reference has none (SURVEY.md §5: logging only, 3 Debug call sites).
These counters feed the BASELINE.json metric surface: rounds advanced,
waves decided/skipped, vertices delivered, verify-batch latency.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List


class Metrics:
    """Per-process counters + verify-latency samples."""

    def __init__(self) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.verify_batch_seconds: List[float] = []
        self.verify_batch_sizes: List[int] = []
        self.wave_commit_seconds: List[float] = []

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe_verify_batch(self, size: int, seconds: float) -> None:
        self.verify_batch_sizes.append(size)
        self.verify_batch_seconds.append(seconds)

    def observe_wave_commit(self, seconds: float) -> None:
        """Duration of one decided wave's commit + total-order pass (the
        BASELINE.json 'p50 wave-commit latency' sample source)."""
        self.wave_commit_seconds.append(seconds)

    @staticmethod
    def _p50(samples: List[float]) -> float:
        s = sorted(samples)
        return s[len(s) // 2]

    def sigs_per_sec(self) -> float:
        total_t = sum(self.verify_batch_seconds)
        if total_t == 0:
            return 0.0
        return sum(self.verify_batch_sizes) / total_t

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.counters)
        if self.verify_batch_sizes:
            out["verify_sigs_per_sec"] = self.sigs_per_sec()
            out["verify_batch_p50_ms"] = 1e3 * self._p50(self.verify_batch_seconds)
            out["verify_batch_mean_size"] = sum(self.verify_batch_sizes) / len(
                self.verify_batch_sizes
            )
        if self.wave_commit_seconds:
            out["wave_commit_p50_ms"] = 1e3 * self._p50(self.wave_commit_seconds)
        return out


class Timer:
    """Context manager: ``with Timer() as t: ...; t.seconds``."""

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        self.seconds = 0.0
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._start
