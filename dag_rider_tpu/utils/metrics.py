"""Consensus observability counters.

The reference has none (SURVEY.md §5: logging only, 3 Debug call sites).
These counters feed the BASELINE.json metric surface: rounds advanced,
waves decided/skipped, vertices delivered, verify-batch latency.

Sample lists are bounded (deque windows): a long-running node must not
leak a float per verify batch / wave commit for its lifetime — the same
bounded-state rule the DAG/RBC/coin GC enforces (round 4). Totals that
consumers sum (verify sig counts, cumulative verify seconds) are kept as
running counters instead, so throughput math is exact over the whole run
while percentiles window to the recent samples.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from typing import Deque, Dict

#: per-series sample-window size: big enough that bench boxes (minutes)
#: keep every sample, small enough to bound week-long nodes
SAMPLE_WINDOW = 65536

#: Every counter name any module may bump (round 14). The driderlint
#: metrics checker (analysis/metricsreg.py) rejects a literal
#: ``metrics.inc("...")`` / ``self._inc("...")`` / ``counters["..."]``
#: whose name is not registered here — a typo'd counter silently
#: creating a new defaultdict key is the observability analogue of the
#: typo'd-knob bug MempoolConfig.from_dict exists to kill.
KNOWN_COUNTERS = frozenset(
    {
        # consensus/process.py — admission, rounds, waves, sync
        "msgs_received",
        "msgs_rejected_stamp",
        "msgs_below_gc_horizon",
        "equivocations_detected",
        "msgs_duplicate",
        "msgs_rejected_edges",
        "msgs_ignored_kind",
        "msgs_rejected_signature",
        "vertices_admitted",
        "vertices_proposed",
        "vertices_delivered",
        "vertices_pruned",
        "rounds_advanced",
        "waves_decided",
        "waves_skipped",
        # pipelined waves + eager delivery (ISSUE 16)
        "waves_inflight",
        "eager_delivered",
        "eager_reconciled",
        "eager_rollbacks_expected_zero",
        "deadline_ms_effective",
        "sync_requested",
        "sync_attested_floor_raises",
        "sync_nacks",
        "sync_throttled",
        "sync_refused_pruned",
        "sync_served",
        "state_transfers",
        "pump_errors",
        # aggregated round certificates (ISSUE 9)
        "certs_ignored",
        "certs_rejected",
        "certs_verified",
        "certs_assembled",
        "sigs_saved",
        "cert_rounds_degraded",
        "cert_timeouts",
        "cert_path_enabled",
        # cert-of-certs overlay + hash-to-curve cache (ISSUE 12)
        "spans_assembled",
        "spans_verified",
        "spans_rejected",
        "spans_ignored",
        "span_rounds_settled",
        "span_timeouts",
        "span_path_enabled",
        "hash_g1_cache_hits",
        "hash_g1_cache_misses",
        # lanes/ — sharded dissemination (ISSUE 17)
        "lane_batches_certified",
        "lane_publish_degraded",
        "lane_fetch_misses",
        "lane_batches_stored",
        "lane_fetch_served",
        "lane_acks_rejected",
        "lane_store_evicted",
        "committed_bytes_per_s",
        # transport/net.py — wire health
        "net_sends",
        "net_sends_ok",
        "net_send_errors",
        "net_drops",
        "net_retries",
        "net_auth_rejects",
        "net_peer_down",
        "net_peer_recovered",
        "net_snapshot_rejects",
        "net_snapshot_stale_refusals",
        "net_snapshot_replays",
        "net_snapshot_throttled",
        "net_snapshot_global_throttled",
        "net_snapshot_fetches",
        "net_snapshot_errors",
        # transport/net.py — injected WAN faults (cluster harness)
        "net_wan_drops",
        "net_wan_delays",
        # cluster/ — multi-process harness (ISSUE 19)
        "net_client_submits",
        "checkpoint_corrupt",
        "cluster_reinjects",
        # epoch reconfiguration (ISSUE 20)
        "epoch_path_enabled",
        "epoch_current",
        "epoch_ctrl_txs",
        "epoch_boundaries",
        "epoch_rotations",
        "epoch_barrier_holds",
        "epoch_stale_rejected",
        "vertices_live_max",
        # span-attested snapshot sync (ISSUE 20)
        "snapshot_spans_attached",
        "snapshot_spans_verified",
        "snapshot_attest_rejects",
        "snapshot_pairing_checks",
    }
)


class Histogram:
    """Percentiles over a bounded reservoir (round-10 satellite).

    A deque-windowed sample set plus exact running count/total — the
    same windowed-percentiles/exact-totals split the rest of this
    module uses. ``percentile(q)`` is the nearest-rank estimate over
    the *window*; ``count``/``total`` stay exact for the whole run.
    """

    def __init__(self, maxlen: int = SAMPLE_WINDOW) -> None:
        self.samples: Deque[float] = deque(maxlen=maxlen)
        self.count = 0
        self.total = 0.0

    def __len__(self) -> int:
        return len(self.samples)

    def observe(self, value: float) -> None:
        self.samples.append(value)
        self.count += 1
        self.total += value

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile, q in (0, 100]. Raises on an empty
        reservoir — callers gate on ``len(h)`` like every other
        conditional snapshot section."""
        s = sorted(self.samples)
        if not s:
            raise ValueError("percentile of an empty histogram")
        rank = max(1, -(-len(s) * q // 100))  # ceil without math import
        return s[int(rank) - 1]

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Metrics:
    """Per-process counters + windowed latency samples."""

    def __init__(self) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.verify_batch_seconds: Deque[float] = deque(maxlen=SAMPLE_WINDOW)
        self.verify_batch_sizes: Deque[int] = deque(maxlen=SAMPLE_WINDOW)
        self.wave_commit_seconds: Deque[float] = deque(maxlen=SAMPLE_WINDOW)
        self.wave_interval_seconds: Deque[float] = deque(
            maxlen=SAMPLE_WINDOW
        )
        self._last_wave_commit_at: float | None = None
        #: in-flight dispatch window high-water per coalesced verify
        #: cycle (depth-K pipeline — verifier/pipeline.py)
        self.verify_queue_depth: Deque[int] = deque(maxlen=SAMPLE_WINDOW)
        #: per-dispatch shard fill imbalance on the mesh-sharded verify
        #: path (0.0 = every shard got equal real rows; 1.0 = at least
        #: one shard was all padding while another was full)
        self.verify_shard_imbalance: Deque[float] = deque(
            maxlen=SAMPLE_WINDOW
        )
        #: exact running totals (never windowed) — the sums consumers use
        self.verify_sigs_total = 0
        self.verify_seconds_total = 0.0
        #: host/device overlap accounting for the pipelined verify seam:
        #: wait = host blocked in resolve (unhidden device time), seam =
        #: verify-seam wall time. overlap_fraction() = 1 - wait/seam.
        self.verify_wait_seconds_total = 0.0
        self.verify_seam_seconds_total = 0.0
        #: parallel host-prep engine gauges (verifier/prep.py): worker
        #: count of the shared verifier's engine and the lifetime share
        #: of prepped rows that took the parallel row-block path
        self.verify_prep_workers = 0
        self.verify_prep_parallel_fraction: float | None = None
        #: round-9 resilience gauges (verifier/resilient.py + the
        #: containment seams): absolute counters mirrored from the
        #: shared verify stack, None until a resilient run reported
        self.verify_retries: int | None = None
        self.verify_fallback_tier: int | None = None
        self.verify_quarantined: int | None = None
        self.sidecar_rpc_failures: int | None = None
        #: 1 = the sidecar tier answered its last probe, 0 = down,
        #: None = no sidecar tier in the stack
        self.sidecar_health: int | None = None
        #: transport chaos counters (FaultyTransport.stats), absolute
        self.transport_faults: Dict[str, int] | None = None
        #: round-10 client-level latency: submit → a_deliver per
        #: transaction through the mempool front door. END-TO-END and
        #: per-process-real, unlike the verify timing series: under the
        #: simulator's dedup'd shared verifier the per-process verify
        #: timings remain AMORTIZED (each process is charged a
        #: size-proportional share of one union dispatch — see
        #: mark_verify_amortized / ADVICE r5 #2), so summing them never
        #: yields cluster cost; the submit→deliver histogram has no such
        #: caveat — each sample is one real client transaction's wait.
        self.submit_deliver_seconds = Histogram()
        #: latest mempool gauge dict (Mempool.stats) — None until a
        #: mempool is attached to this process's node
        self.mempool: Dict | None = None
        #: round-12 host-pump accounting (ISSUE 8): messages delivered
        #: through the consensus pump and the wall seconds the driver
        #: spent pumping + stepping, plus which path ran. None until a
        #: pump-aware driver (Simulation.run / node pump loop) reports.
        self.pump_msgs_total = 0
        self.pump_seconds_total = 0.0
        self.pump_path: str | None = None

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe_verify_batch(self, size: int, seconds: float) -> None:
        self.verify_batch_sizes.append(size)
        self.verify_batch_seconds.append(seconds)
        self.verify_sigs_total += size
        self.verify_seconds_total += seconds

    def observe_verify_queue_depth(self, depth: int) -> None:
        """High-water in-flight dispatch count of one coalesced verify
        cycle (1 = the serial dispatch-then-resolve shape; >= 2 means
        host prep genuinely overlapped device execution)."""
        self.verify_queue_depth.append(depth)

    def observe_shard_imbalance(self, fraction: float) -> None:
        """Shard fill imbalance of one mesh-sharded dispatch
        ((max - min real rows per shard) / shard rows — 0.0 when the
        batch filled every shard equally). Persistent high values mean
        the bucket is oversized for the burst and chips idle on padding."""
        self.verify_shard_imbalance.append(fraction)

    def observe_verify_overlap(self, wait_s: float, seam_s: float) -> None:
        """This process's share of a pipelined cycle: seconds the host
        blocked in resolve vs the cycle's verify-seam wall time."""
        self.verify_wait_seconds_total += wait_s
        self.verify_seam_seconds_total += seam_s

    def overlap_fraction(self) -> float | None:
        """Fraction of verify-seam wall time the host spent doing useful
        work (prep of later chunks, delivery walks) instead of blocked
        on the device. None until a pipelined cycle ran."""
        if self.verify_seam_seconds_total <= 0.0:
            return None
        return max(
            0.0,
            min(
                1.0,
                1.0
                - self.verify_wait_seconds_total
                / self.verify_seam_seconds_total,
            ),
        )

    def observe_prep(self, workers: int, parallel_fraction: float) -> None:
        """Latest host-prep engine gauges (TPUVerifier.prep_stats):
        configured worker count and the fraction of all prepped rows
        that actually ran row-block parallel — the no-silent-fallback
        signal (workers > 1 with fraction 0.0 means every dispatch was
        below the block floor or the engine never engaged)."""
        self.verify_prep_workers = int(workers)
        self.verify_prep_parallel_fraction = float(parallel_fraction)

    def observe_resilience(
        self,
        retries: int,
        fallback_tier: int,
        quarantined: int,
        sidecar_health: int | None = None,
        rpc_failures: int = 0,
    ) -> None:
        """Latest resilience gauges of the shared verify stack
        (ResilientVerifier.resilience_stats / the pipeline's containment
        counters): cumulative retry count, the tier index that answered
        the most recent call (0 = preferred tier, len(tiers) = whole
        ladder exhausted), chunks re-verified in quarantine, sidecar
        probe health, and transport-level sidecar RPC failures — the
        counter that distinguishes a dead sidecar from a batch of
        invalid signatures (both read all-False at mask level)."""
        self.verify_retries = int(retries)
        self.verify_fallback_tier = int(fallback_tier)
        self.verify_quarantined = int(quarantined)
        self.sidecar_rpc_failures = int(rpc_failures)
        if sidecar_health is not None:
            self.sidecar_health = int(sidecar_health)

    def observe_transport_faults(self, stats: Dict[str, int]) -> None:
        """Absolute FaultyTransport.stats counters
        (dropped/delayed/duplicated/equivocated) — chaos runs surface
        their injected network faults next to the verifier gauges."""
        self.transport_faults = dict(stats)

    def observe_submit_deliver(self, seconds: float) -> None:
        """One accepted transaction's submit→a_deliver latency (the
        mempool closes these books at delivery time)."""
        self.submit_deliver_seconds.observe(seconds)

    def observe_mempool(self, stats: Dict) -> None:
        """Latest mempool gauges (Mempool.stats): depth, admitted/
        shed/deduped/expired counters, batch fill, backpressure state."""
        self.mempool = dict(stats)

    def observe_pump(self, msgs: int, seconds: float, path: str) -> None:
        """Host consensus-pump accounting from the driving loop:
        cumulative messages delivered, wall seconds spent in
        pump + step, and the active path ("scalar" | "vector"). The
        1.2 s/round floor ISSUE 8 attacks becomes first-class
        observable as host_pump_ms_per_round / pump_msgs_per_s in the
        snapshot instead of hand-derived in PROFILE."""
        self.pump_msgs_total += int(msgs)
        self.pump_seconds_total += float(seconds)
        self.pump_path = path

    def mark_verify_amortized(self) -> None:
        """Flag this process's verify timings as AMORTIZED: under the
        simulator's dedup'd shared verifier one process pays the wall
        time for a union batch whose masks all n processes consume, so
        per-process verify_seconds/sigs do not sum to cluster cost
        (ADVICE r5 #2). Consumers must treat the per-process series as
        attribution of shared work, not as independent spend."""
        self.counters["verify_timings_amortized"] = 1

    def observe_wave_commit(self, seconds: float) -> None:
        """Duration of one decided wave's commit + total-order pass (the
        decide-walk HALF of the BASELINE.json 'p50 wave-commit latency'
        story — see :meth:`observe_wave_decided` for the end-to-end
        cadence)."""
        self.wave_commit_seconds.append(seconds)

    def observe_wave_decided(self) -> None:
        """Stamp a wave DECISION: wall time between consecutive decided
        waves is the END-TO-END cadence, including the ~4 rounds of
        verify + consensus a wave costs — the quantity round-3's staged
        proxy (4 dispatches + commit kernels) modeled. Called at decide
        time, NOT at the (possibly deferred and batched) ordering flush:
        two waves flushed together must not record a ~0 interval. The
        decide-walk sample (observe_wave_commit) deliberately excludes
        verify — it is amortized across the round pipeline — and
        reporting both keeps the two from being conflated."""
        now = time.monotonic()
        if self._last_wave_commit_at is not None:
            self.wave_interval_seconds.append(now - self._last_wave_commit_at)
        self._last_wave_commit_at = now

    @staticmethod
    def _p50(samples) -> float:
        s = sorted(samples)
        return s[len(s) // 2]

    def sigs_per_sec(self) -> float:
        if self.verify_seconds_total == 0:
            return 0.0
        return self.verify_sigs_total / self.verify_seconds_total

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.counters)
        # Byzantine-detection counter is part of the stable snapshot/rung
        # schema even when zero (defaultdict counters only appear once
        # incremented): chaos and adversary rungs assert DETECTION
        # counts next to the transport_* fault stats, and "0 detected"
        # must be distinguishable from "not surfaced".
        out.setdefault("equivocations_detected", 0)
        if self.verify_batch_sizes:
            out["verify_sigs_total"] = self.verify_sigs_total
            out["verify_sigs_per_sec"] = self.sigs_per_sec()
            out["verify_batch_p50_ms"] = 1e3 * self._p50(self.verify_batch_seconds)
            out["verify_batch_mean_size"] = sum(self.verify_batch_sizes) / len(
                self.verify_batch_sizes
            )
        if self.verify_queue_depth:
            out["verify_queue_depth_p50"] = self._p50(self.verify_queue_depth)
            out["verify_queue_depth_max"] = max(self.verify_queue_depth)
        if self.verify_shard_imbalance:
            out["verify_shard_imbalance_p50"] = round(
                self._p50(self.verify_shard_imbalance), 4
            )
            out["verify_shard_imbalance_max"] = round(
                max(self.verify_shard_imbalance), 4
            )
        if self.verify_seam_seconds_total > 0.0:
            out["verify_overlap_fraction"] = round(
                self.overlap_fraction(), 4
            )
        if self.verify_prep_workers:
            out["verify_prep_workers"] = self.verify_prep_workers
            out["verify_prep_parallel_fraction"] = round(
                self.verify_prep_parallel_fraction or 0.0, 4
            )
        if self.verify_retries is not None:
            out["verify_retries"] = self.verify_retries
            out["verify_fallback_tier"] = self.verify_fallback_tier or 0
            out["verify_quarantined"] = self.verify_quarantined or 0
            out["sidecar_rpc_failures"] = self.sidecar_rpc_failures or 0
        if self.sidecar_health is not None:
            out["sidecar_health"] = self.sidecar_health
        if self.transport_faults is not None:
            for k, v in self.transport_faults.items():
                out[f"transport_{k}"] = v
        if len(self.submit_deliver_seconds):
            h = self.submit_deliver_seconds
            out["submit_deliver_p50_ms"] = round(1e3 * h.percentile(50), 3)
            out["submit_deliver_p90_ms"] = round(1e3 * h.percentile(90), 3)
            out["submit_deliver_p99_ms"] = round(1e3 * h.percentile(99), 3)
            out["submit_deliver_count"] = h.count
        if self.mempool is not None:
            #: backpressure state as a numeric gauge next to the counters
            ladder = {"accept": 0, "throttle": 1, "shed": 2}
            for k, v in self.mempool.items():
                if k == "state":
                    out["mempool_backpressure"] = ladder.get(v, -1)
                elif isinstance(v, (int, float)):
                    out[f"mempool_{k}"] = v
        if self.pump_path is not None:
            # numeric gauge (same convention as mempool_backpressure)
            out["pump_path"] = {"scalar": 0, "vector": 1}.get(
                self.pump_path, -1
            )
            if self.pump_seconds_total > 0.0:
                out["pump_msgs_per_s"] = round(
                    self.pump_msgs_total / self.pump_seconds_total, 1
                )
                rounds = self.counters.get("rounds_advanced", 0)
                if rounds:
                    out["host_pump_ms_per_round"] = round(
                        1e3 * self.pump_seconds_total / rounds, 3
                    )
                committed = (self.mempool or {}).get("delivered_bytes", 0)
                if committed:
                    # payload bytes committed per second of ordering-path
                    # (pump) time — the lanes A/B headline (ISSUE 17):
                    # with dissemination on worker lanes, this must keep
                    # scaling as block weight grows while the pump floor
                    # stays flat
                    out["committed_bytes_per_s"] = round(
                        committed / self.pump_seconds_total
                    )
        if "cert_path_enabled" in self.counters:
            # aggregated round-certificate gauges (ISSUE 9): the cert
            # counters are part of the stable schema whenever the fast
            # path is wired — "0 certs" must be distinguishable from
            # "cert path absent"
            for k in (
                "certs_assembled",
                "certs_verified",
                "certs_rejected",
                "cert_timeouts",
                "cert_rounds_degraded",
                "sigs_saved",
            ):
                out.setdefault(k, 0)
            admitted = self.counters.get("vertices_admitted", 0)
            out["cert_fastpath_fraction"] = round(
                self.counters.get("sigs_saved", 0) / admitted, 4
            ) if admitted else 0.0
            # hash-to-curve cache effectiveness (ISSUE 12 satellite):
            # process-global by construction (the cache lives in the
            # crypto layer), surfaced as gauges wherever the cert path
            # is on so a bench run can see its hit rate next to the
            # signing numbers. Lazy import keeps cert-off snapshots free
            # of the BLS module.
            from dag_rider_tpu.crypto import bls12381 as _bls

            h2g1 = _bls.hash_g1_cache_stats()
            out["hash_g1_cache_hits"] = h2g1["hits"]
            out["hash_g1_cache_misses"] = h2g1["misses"]
            if "span_path_enabled" in self.counters:
                for k in (
                    "spans_assembled",
                    "spans_verified",
                    "spans_rejected",
                    "spans_ignored",
                    "span_rounds_settled",
                    "span_timeouts",
                ):
                    out.setdefault(k, 0)
        if self.wave_commit_seconds:
            out["wave_commit_p50_ms"] = 1e3 * self._p50(self.wave_commit_seconds)
        if self.wave_interval_seconds:
            out["wave_interval_p50_ms"] = 1e3 * self._p50(
                self.wave_interval_seconds
            )
        return out


class Timer:
    """Context manager: ``with Timer() as t: ...; t.seconds``."""

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        self.seconds = 0.0
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._start
