from dag_rider_tpu.utils.metrics import Metrics, Timer

__all__ = ["Metrics", "Timer"]
