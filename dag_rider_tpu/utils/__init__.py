from dag_rider_tpu.utils.metrics import Metrics, Timer
from dag_rider_tpu.utils.slog import NOOP, EventLog, capture, stdlib_sink

__all__ = ["Metrics", "Timer", "NOOP", "EventLog", "capture", "stdlib_sink"]
