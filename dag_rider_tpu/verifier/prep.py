"""Parallel host-prep engine (round-8 tentpole).

PROFILE.md round 7 leaves the verify hot path HOST-bound: `_prepare`
runs single-threaded at ~115k rows/s clean and degrades to ~9.5 ms/round
under consensus contention, while the dedup analysis caps the in-loop
applied rate at ~58k msg/s of host path. The device is no longer the
ceiling — one Python thread feeding it is. This module owns the two
threading seams that lift that ceiling without touching WHAT is
computed:

- **row-block pool** — one prep call is partitioned into contiguous row
  blocks, each block running the full per-row pipeline (byte parsing,
  the s < L / r_y < p lexicographic compares, SHA-512 challenge
  scalars, limb/nibble packing) and writing its finished rows straight
  into the block's offsets of the caller-provided destination arrays —
  normally a staging-ring slot (`TPUVerifier._stage`), so the parallel
  path adds NO extra copy and inherits the ring's aliasing discipline
  unchanged. Every per-row computation is row-local (see
  `TPUVerifier._prep_block`), so any partition of [0, size) is
  byte-identical to the serial full-range call. The heavy kernels all
  drop the GIL: numpy ufuncs/matmuls internally, and the native
  `challenge_batch` for the whole duration of its ctypes call
  (utils/native.py) — threads, not processes, so workers can share the
  destination arrays zero-copy.
- **seam executor** — a single dedicated FIFO thread
  (:meth:`PrepEngine.submit`) that the pipeline callers
  (``VerifierPipeline.run_coalesced``, the chunk-streaming
  ``TPUVerifier.verify_rounds``) queue whole `prep_batch` calls on:
  chunk k+2's prep runs concurrently with chunk k+1's prep (queued
  behind it) and chunk k's device execution, deepening the overlap the
  depth-K window already buys. One thread — never more — so
  staging-ring slots are still claimed strictly in chunk order and the
  ring's ``pipeline_depth + 2`` slots cover the at-most-2 outstanding
  preps plus the depth-K in-flight dispatches.

Knobs: ``DAGRIDER_PREP_WORKERS`` (env, default 1 = serial — the
pre-round-8 shape) and ``verify_prep_workers`` (node.py config) /
``TPUVerifier.prep_workers`` (attribute) for per-instance overrides.
Gauges (`workers`, `last_blocks`, `parallel_fraction`) surface through
``TPUVerifier.prep_stats`` into pipeline stats, the bench's
``verifier_breakdown`` and the per-process metrics snapshot.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional, Tuple

from dag_rider_tpu import config

#: Smallest row block worth a thread handoff: below this the numpy
#: slices are so small that submit/wake costs exceed the work moved.
#: Also the floor bucket size (tpu._MIN_BUCKET), so sub-bucket
#: dispatches always take the serial path.
MIN_BLOCK_ROWS = 16


def default_prep_workers() -> int:
    """Worker count for the parallel host-prep engine:
    DAGRIDER_PREP_WORKERS, default 1 (serial — byte-identical by
    construction, and the right call on one-core hosts). N > 1 splits
    every big-enough prep into up to N row blocks."""
    return config.env_int("DAGRIDER_PREP_WORKERS")


class PrepEngine:
    """Row-block worker pool + FIFO seam executor for host prep.

    One engine per verifier (the staging ring it feeds is per-verifier
    state). ``workers`` is the total parallelism of one prep call: the
    calling thread always takes the first block, so the pool holds
    ``workers - 1`` threads and ``workers=1`` builds no pool at all —
    that configuration is structurally the serial code path, not a
    simulation of it.
    """

    def __init__(self, workers: Optional[int] = None):
        self.workers = (
            int(workers) if workers is not None else default_prep_workers()
        )
        if self.workers < 1:
            raise ValueError(f"prep workers must be >= 1, got {workers!r}")
        self._pool = (
            ThreadPoolExecutor(
                max_workers=self.workers - 1,
                thread_name_prefix="dagrider-prep",
            )
            if self.workers > 1
            else None
        )
        #: lazy single-thread FIFO executor for whole-prep-call
        #: overlap on the pipeline seam (see submit())
        self._seam: Optional[ThreadPoolExecutor] = None
        #: gauges — cumulative over the engine's lifetime. Guarded by
        #: _gauge_lock: run_blocks legitimately overlaps itself (the
        #: caller thread preps chunk k+1 while the seam thread preps
        #: k+2 into a DIFFERENT ring slot), so the read-modify-write
        #: bumps below race without it — the round-14 race harness
        #: caught exactly this under tests/test_chaos.py.
        self._gauge_lock = threading.Lock()
        self.last_blocks = 1
        self.dispatches = 0
        self.dispatches_parallel = 0
        self.rows_total = 0
        self.rows_parallel = 0
        self.serial_retries = 0

    # -- row-block half ---------------------------------------------------

    def plan(self, size: int) -> List[Tuple[int, int]]:
        """Contiguous near-equal row blocks partitioning [0, size).

        Deterministic in (size, workers) — though byte-identity never
        depends on the partition, only on every row being covered
        exactly once. Small dispatches stay one block: splitting 16
        rows four ways is pure overhead."""
        blocks = (
            1 if self.workers <= 1 else min(self.workers, size // MIN_BLOCK_ROWS)
        )
        if blocks <= 1:
            return [(0, max(size, 0))]
        step = -(-size // blocks)  # ceil
        return [(lo, min(lo + step, size)) for lo in range(0, size, step)]

    def run_blocks(
        self,
        fn: Callable[[int, int], None],
        blocks: List[Tuple[int, int]],
    ) -> None:
        """Run ``fn(lo, hi)`` over every block; the calling thread takes
        the first block, the pool the rest. Blocks until all blocks are
        done.

        A PARALLEL failure is contained at this boundary (round 9): all
        outstanding blocks are waited out (never retried concurrently —
        they share the destination arrays), then the whole range is
        re-run serially ONCE. `_prep_block` fully overwrites its rows,
        so the serial pass is byte-identical no matter which blocks had
        partially written. Only if the serial pass also fails does the
        exception surface — the staging slot is then considered
        unwritten and the dispatch must not ship."""
        size = blocks[-1][1]
        with self._gauge_lock:
            self.dispatches += 1
            self.rows_total += size
            self.last_blocks = len(blocks)
        if len(blocks) == 1:
            fn(*blocks[0])
            return
        with self._gauge_lock:
            self.dispatches_parallel += 1
            self.rows_parallel += size
        futs = [self._pool.submit(fn, lo, hi) for lo, hi in blocks[1:]]
        failed = False
        try:
            fn(*blocks[0])
        except Exception:  # noqa: BLE001 — retried serially below
            failed = True
        for f in futs:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — retried serially below
                failed = True
        if failed:
            with self._gauge_lock:
                self.serial_retries += 1
            fn(0, size)

    # -- pipeline-seam half ----------------------------------------------

    def submit(self, fn: Callable, *args) -> Future:
        """Queue a whole prep call on the engine's dedicated seam thread.

        Exactly one thread, FIFO: submission order IS staging-ring claim
        order, which the ring's aliasing discipline requires (a slot's
        previous dispatch must have resolved before the slot is claimed
        again — callers keep at most 2 preps outstanding and only submit
        a new one after draining the window below depth). The seam
        thread may itself fan out into the row-block pool; the two pools
        are disjoint, so the nesting cannot deadlock."""
        if self._seam is None:
            self._seam = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dagrider-prep-seam"
            )
        return self._seam.submit(fn, *args)

    # -- gauges / lifecycle ----------------------------------------------

    def parallel_fraction(self) -> float:
        """Fraction of all prepped rows that took the parallel row-block
        path (0.0 = everything ran serially — the no-silent-fallback
        gauge the structural tests assert on)."""
        if self.rows_total <= 0:
            return 0.0
        return self.rows_parallel / self.rows_total

    def close(self) -> None:
        """Shut both executors down (waits for in-flight work). Called
        when a verifier rebuilds its engine at a new worker count; safe
        to call twice."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._seam is not None:
            self._seam.shutdown(wait=True)
            self._seam = None
