"""Aggregated round-certificate verification (ISSUE 9).

One :class:`CertVerifier` is shared by every process of an in-process
cluster, the same sharing shape as the per-vertex Verifier seam: the
round's designated aggregator sums the quorum's per-vertex BLS signatures
(through the MSM seam — device kernel, mesh-sharded variant, or the host
group law) into one 48-byte G1 point, and every receiver checks the whole
round with ONE aggregate pairing

    e(agg, -G2) * prod_i e(H(digest_i), pk_i) == 1

via :func:`crypto.bls12381.multi_pairing_check` (precomputed per-key
Miller lines, shared squarings, one final exponentiation) instead of one
ed25519 verify per vertex.

Soundness note: per-producer signatures over DISTINCT messages (each
process signs its own vertex digest) are what make the aggregate binding —
any common-message scheme would let the aggregator attribute vertices to
processes that never signed them. The aggregate check therefore pays k+1
pairings at the receiver; what is flat in n is the DEVICE work (one MSM,
one wire certificate) and the signature-op count, which is the claim the
bench rungs measure.

Verdicts are memoized by certificate content: in an in-process cluster the
aggregator's own pre-gossip self-check makes every receiver's verdict a
dict hit, so the cluster pays each aggregate pairing once — mirroring the
simulator's dedup'd shared per-vertex dispatch.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from dag_rider_tpu import config
from dag_rider_tpu.core.types import RoundCertificate, SpanCertificate
from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.verifier.base import KeyRegistry

#: memoized verdicts kept per verifier (bounded like the crypto-layer
#: precompute caches)
_VERDICT_CACHE_MAX = 4096


def _resolve_msm(msm: Optional[str]) -> str:
    choice = msm if msm is not None else config.env_choice("DAGRIDER_CERT_MSM")
    if choice not in ("host", "device", "sharded"):
        raise ValueError(
            f'cert MSM must be "host", "device" or "sharded", got {choice!r}'
        )
    return choice


def _resolve_pair(pair: Optional[str]) -> str:
    choice = (
        pair if pair is not None else config.env_choice("DAGRIDER_CERT_PAIR")
    )
    if choice not in ("host", "device"):
        raise ValueError(
            f'cert pairing must be "host" or "device", got {choice!r}'
        )
    return choice


class CertVerifier:
    """Validates :class:`RoundCertificate`\\ s against a key registry and
    aggregates signature shares for the assembling side.

    Args:
        registry: the cluster PKI; must carry ``bls_public_keys``.
        quorum: minimum signer count a certificate must cover (2f+1).
        msm: "host" (group-law fallback) | "device" (ops/bls_msm kernel)
            | "sharded" (parallel/msm over the mesh); None reads
            DAGRIDER_CERT_MSM, defaulting to host.
        pair: "host" (crypto/bls12381 Miller replay) | "device"
            (ops/bls_pairing lane-parallel line evaluations); None reads
            DAGRIDER_CERT_PAIR, defaulting to host. Bit-identical
            verdicts by construction (ISSUE 12 tentpole 2).
    """

    def __init__(
        self,
        registry: KeyRegistry,
        quorum: int,
        msm: Optional[str] = None,
        pair: Optional[str] = None,
    ) -> None:
        if not registry.bls_public_keys:
            raise ValueError(
                "CertVerifier needs a registry with BLS certificate keys "
                "(KeyRegistry.generate_with_cert)"
            )
        self.registry = registry
        self.quorum = int(quorum)
        self.msm = _resolve_msm(msm)
        self.pair = _resolve_pair(pair)
        self._sharded = None
        self._verdicts: dict = {}
        #: Optional callback fired once per certificate, on its FIRST
        #: successful verification (memo hits stay silent — the event
        #: already fired). The eager-delivery seam for single-owner
        #: stacks (node.py): "a round-certificate quorum just formed"
        #: is exactly this edge. The simulator's verifier is shared
        #: across processes, so it wires eagerness through the
        #: Process.on_deliver_early seam instead.
        self.on_certified: Optional[Callable[[RoundCertificate], None]] = None
        self.stats = {
            "certs_checked": 0,
            "certs_valid": 0,
            "certs_invalid": 0,
            "verdict_hits": 0,
            "pairing_checks": 0,
        }

    # -- aggregation (the assembling side) ------------------------------

    def _sum_points(self, points: Sequence[tuple]) -> Optional[tuple]:
        if self.msm == "device":
            from dag_rider_tpu.ops import bls_msm

            return bls_msm.sum_points(points)
        if self.msm == "sharded":
            if self._sharded is None:
                from dag_rider_tpu.parallel.msm import ShardedMSM

                self._sharded = ShardedMSM()
            return self._sharded.sum_points(points)
        return bls.g1_sum(points)

    def aggregate(self, sigs: Sequence[bytes]) -> Optional[bytes]:
        """Compressed G1 sum of per-vertex certificate signatures, or
        None when any share is malformed (the aggregator only ever feeds
        shares it produced or directly verified vertices for, so None
        here means local corruption, not a protocol event)."""
        points = []
        for s in sigs:
            pt = bls.g1_decompress(s)
            if pt is None:
                return None
            points.append(pt)
        acc = self._sum_points(points)
        return bls.g1_compress(acc)

    def make_certificate(
        self, rnd: int, entries: Sequence[Tuple[int, bytes, bytes]]
    ) -> Optional[RoundCertificate]:
        """Assemble a certificate from (source, digest, cert_sig)
        triples of directly verified round-``rnd`` vertices. Returns None
        below quorum or on a malformed share."""
        if len(entries) < self.quorum:
            return None
        entries = sorted(entries)
        agg = self.aggregate([sig for _, _, sig in entries])
        if agg is None:
            return None
        return RoundCertificate(
            round=rnd,
            signers=tuple(src for src, _, _ in entries),
            digests=tuple(d for _, d, _ in entries),
            agg_sig=agg,
        )

    # -- verification (the receiving side) ------------------------------

    def _structurally_valid(self, cert: RoundCertificate) -> bool:
        s = cert.signers
        if len(s) < self.quorum or len(s) != len(cert.digests):
            return False
        # strictly increasing => sorted, unique, and a stable wire form
        if any(b <= a for a, b in zip(s, s[1:])):
            return False
        return 0 <= s[0] and s[-1] < self.registry.n

    def verify_certificate(self, cert: RoundCertificate) -> bool:
        """One aggregate check for the whole round. False for ANY defect
        — bad bitmap, unknown signer, forged aggregate, substituted
        digests — never an exception: like the per-vertex seam, a bad
        input yields a reject bit."""
        self.stats["certs_checked"] += 1
        key = cert.signing_key()
        hit = self._verdicts.get(key)
        if hit is not None:
            self.stats["verdict_hits"] += 1
            return hit
        ok = self._check(cert)
        if len(self._verdicts) >= _VERDICT_CACHE_MAX:
            self._verdicts.clear()
        self._verdicts[key] = ok
        self.stats["certs_valid" if ok else "certs_invalid"] += 1
        if ok and self.on_certified is not None:
            self.on_certified(cert)
        return ok

    def _pairing_check(self, pairs: Sequence[tuple]) -> bool:
        """Route one product check through the pairing seam; the counter
        is what the span path's <1-check-per-round claim is measured on
        (bench.py cert_phase2 rung)."""
        self.stats["pairing_checks"] += 1
        if self.pair == "device":
            from dag_rider_tpu.ops import bls_pairing

            return bls_pairing.multi_pairing_check(pairs)
        return bls.multi_pairing_check(pairs)

    def _cert_pairs(self, cert: RoundCertificate) -> Optional[List[tuple]]:
        """The certificate's product-check pair list
        ``[(agg, -G2)] + [(H(d_i), pk_i) ...]``, or None for any
        structural defect (bad bitmap, unknown signer, bad point)."""
        if not self._structurally_valid(cert):
            return None
        agg = bls.g1_decompress(cert.agg_sig)
        if agg is None:
            return None
        pairs: List[tuple] = [(agg, bls.g2_neg(bls.G2_GEN))]
        for src, digest in zip(cert.signers, cert.digests):
            pk = self.registry.bls_key_of(src)
            if pk is None:
                return None
            pairs.append((bls.hash_to_g1(digest), pk))
        return pairs

    def _check(self, cert: RoundCertificate) -> bool:
        pairs = self._cert_pairs(cert)
        if pairs is None:
            return False
        return self._pairing_check(pairs)

    def verify_many(self, certs: Sequence[RoundCertificate]) -> List[bool]:
        """Batched receiver-side verification: every pending certificate's
        pair list merges into ONE combined product check.

        A combined pass is sound for *admission* — by aggregate
        unforgeability every claimed (digest, pk) pair across the batch
        was signed — but it does NOT prove each component certificate
        individually well-formed (offsetting defects cancel in the
        product), so only the combined verdict is memoized, keyed by the
        sorted member identities. Per-cert verdicts come from
        :meth:`verify_certificate` on the localization path when the
        combined check fails."""
        verdicts: List[Optional[bool]] = []
        fresh: List[int] = []
        for i, cert in enumerate(certs):
            hit = self._verdicts.get(cert.signing_key())
            if hit is not None:
                self.stats["certs_checked"] += 1
                self.stats["verdict_hits"] += 1
                verdicts.append(hit)
            else:
                verdicts.append(None)
                fresh.append(i)
        if len(fresh) < 2:
            for i in fresh:
                verdicts[i] = self.verify_certificate(certs[i])
            return [bool(v) for v in verdicts]
        combined_key = ("many",) + tuple(
            sorted(certs[i].signing_key() for i in fresh)
        )
        if self._verdicts.get(combined_key):
            # combined verdicts are only ever memoized True
            for i in fresh:
                self.stats["certs_checked"] += 1
                self.stats["verdict_hits"] += 1
                verdicts[i] = True
            return [bool(v) for v in verdicts]
        pair_lists = [self._cert_pairs(certs[i]) for i in fresh]
        if all(pl is not None for pl in pair_lists):
            all_pairs: List[tuple] = []
            for pl in pair_lists:
                all_pairs.extend(pl)  # type: ignore[arg-type]
            if self._pairing_check(all_pairs):
                if len(self._verdicts) >= _VERDICT_CACHE_MAX:
                    self._verdicts.clear()
                self._verdicts[combined_key] = True
                for i in fresh:
                    self.stats["certs_checked"] += 1
                    self.stats["certs_valid"] += 1
                    verdicts[i] = True
                    if self.on_certified is not None:
                        self.on_certified(certs[i])
                return [bool(v) for v in verdicts]
        # a structural defect or a failed combined product: localize with
        # individual (memoized) checks — identical verdicts to the oracle
        for i in fresh:
            verdicts[i] = self.verify_certificate(certs[i])
        return [bool(v) for v in verdicts]

    # -- cert-of-certs (ISSUE 12 tentpole 3) ----------------------------

    def make_span(
        self, first_round: int, certs: Sequence[RoundCertificate]
    ) -> Optional[SpanCertificate]:
        """Fold consecutive VERIFIED round certificates into one
        cert-of-certs: the span aggregate is the G1 sum of the round
        aggregates (through the same MSM seam as share aggregation), so
        one combined pairing covers every (digest, pk) pair in the span.
        Returns None unless the certs cover exactly ``first_round,
        first_round + 1, ...`` gap-free."""
        if not certs:
            return None
        rounds = [c.round for c in certs]
        if rounds != list(range(first_round, first_round + len(certs))):
            return None
        points = []
        for c in certs:
            pt = bls.g1_decompress(c.agg_sig)
            if pt is None:
                return None
            points.append(pt)
        agg = bls.g1_compress(self._sum_points(points))
        return SpanCertificate(
            first_round=first_round,
            signers=tuple(c.signers for c in certs),
            digests=tuple(c.digests for c in certs),
            agg_sig=agg,
        )

    def _span_structurally_valid(self, span: SpanCertificate) -> bool:
        k = len(span.signers)
        if k < 1 or len(span.digests) != k or span.first_round < 1:
            return False
        for s, d in zip(span.signers, span.digests):
            if len(s) < self.quorum or len(s) != len(d):
                return False
            if any(b <= a for a, b in zip(s, s[1:])):
                return False
            if s[0] < 0 or s[-1] >= self.registry.n:
                return False
        return True

    def verify_span(self, span: SpanCertificate) -> bool:
        """ONE combined product check for the whole span — the
        steady-state consumer pays 1/k pairing checks per round. False
        for any defect, never an exception; the verdict is memoized
        under the span's own tagged key (never folded back into
        per-round verdicts — see :meth:`verify_many` on why)."""
        self.stats["certs_checked"] += 1
        key = span.signing_key()
        hit = self._verdicts.get(key)
        if hit is not None:
            self.stats["verdict_hits"] += 1
            return hit
        ok = self._span_check(span)
        if len(self._verdicts) >= _VERDICT_CACHE_MAX:
            self._verdicts.clear()
        self._verdicts[key] = ok
        self.stats["certs_valid" if ok else "certs_invalid"] += 1
        return ok

    def _span_check(self, span: SpanCertificate) -> bool:
        if not self._span_structurally_valid(span):
            return False
        agg = bls.g1_decompress(span.agg_sig)
        if agg is None:
            return False
        pairs: List[tuple] = [(agg, bls.g2_neg(bls.G2_GEN))]
        for signers, digests in zip(span.signers, span.digests):
            for src, digest in zip(signers, digests):
                pk = self.registry.bls_key_of(src)
                if pk is None:
                    return False
                pairs.append((bls.hash_to_g1(digest), pk))
        return self._pairing_check(pairs)
