"""Depth-K asynchronous verifier pipeline.

BENCH_r05 measured the device seam at 228.5 sigs/s with 179 ms per
dispatch over 72 dispatches: the FIXED per-dispatch cost (H2D transfer,
cache lookup, blocking resolve immediately after dispatch) dominates, not
the math. The async halves already exist (``TPUVerifier.dispatch_batch``
/ ``resolve_batch``) but every caller used them at depth 1 — dispatch,
one slice of host work, resolve — and ``verify_rounds`` fell back to a
fully synchronous chunk loop.

:class:`VerifierPipeline` owns the in-flight window those halves imply:

- **coalescing** — a merged burst (the simulator's per-pump union of all
  n processes' ``take_verify_batch`` output, already deduped) is sliced
  into ``fixed_bucket``-sized chunks, one compiled program shape for the
  whole run;
- **depth-K window** — up to K chunk dispatches stay in flight, so chunk
  k+1's host prep (SHA-512 challenge scalars, limb packing — the
  expensive host half) overlaps chunk k's device execution;
- **FIFO resolve** — masks come back in submission order, and each chunk
  boundary is identical to the synchronous path's, so the concatenated
  mask — and therefore the commit order downstream of it — is
  byte-identical to ``verify_batch`` / ``CPUVerifier``
  (tests/test_pipeline.py);
- **AOT warmup** — construction calls the verifier's :meth:`warmup`,
  which ``jit(...).lower(...).compile()``-s the fixed-bucket program so
  the first consensus round never eats a ~35 s XLA compile.

The mask is still a pure function of (vertex bytes, registry); the
pipeline only changes WHEN the host blocks, never WHAT it computes.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, List, Optional, Sequence

from dag_rider_tpu import config
from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.utils.slog import NOOP, EventLog
from dag_rider_tpu.verifier.base import Verifier


def default_depth() -> int:
    """In-flight window depth: DAGRIDER_VERIFY_DEPTH, default 2.

    Depth 1 degenerates to the synchronous dispatch-then-resolve shape;
    2 is enough to overlap host prep with device execution (the two
    alternate); deeper windows only help when chunk execution time
    varies."""
    return config.env_int("DAGRIDER_VERIFY_DEPTH")


class VerifierPipeline(Verifier):
    """Depth-K dispatch window over an async-capable verifier.

    Wraps any verifier exposing the ``dispatch_batch``/``resolve_batch``
    seam (``TPUVerifier`` and subclasses) and is itself a drop-in
    :class:`Verifier`: ``verify_batch``/``verify_rounds`` stream through
    the window, so a :class:`~dag_rider_tpu.consensus.process.Process`
    can hold a pipeline directly (node.py's device configuration).
    """

    def __init__(
        self,
        verifier,
        depth: Optional[int] = None,
        *,
        fixed_bucket: Optional[int] = None,
        warmup: bool = True,
        log: EventLog = NOOP,
    ):
        self.log = log
        if not callable(getattr(verifier, "dispatch_batch", None)) or not (
            callable(getattr(verifier, "resolve_batch", None))
        ):
            raise TypeError(
                "VerifierPipeline needs an async-capable verifier "
                "(dispatch_batch/resolve_batch)"
            )
        self.verifier = verifier
        # explicit depth > the verifier's own pipeline_depth > env default
        self.depth = (
            int(depth)
            if depth is not None
            else int(getattr(verifier, "pipeline_depth", 0) or default_depth())
        )
        if self.depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth!r}")
        # the verifier sizes its host staging ring from pipeline_depth —
        # it must cover THIS window or a slot could be rewritten while
        # its dispatch is still in flight (CPU PJRT may alias host
        # buffers zero-copy into the program)
        if getattr(verifier, "pipeline_depth", self.depth) < self.depth:
            verifier.pipeline_depth = self.depth
        if fixed_bucket is not None:
            verifier.fixed_bucket = fixed_bucket
        #: (pending handle, chunk) FIFO — the chunk rides along so a
        #: dispatch/resolve fault can quarantine exactly the vertices it
        #: poisoned (round-9 containment)
        self._inflight: Deque[tuple] = deque()
        #: masks already produced by fault containment, FIFO-ordered
        #: ahead of everything in _inflight; _resolve_oldest consumes
        #: these first so the concatenated mask keeps chunk order
        self._salvaged: Deque[List[bool]] = deque()
        #: next tier for quarantined chunks (wired by ResilientVerifier);
        #: None = one serial retry on the wrapped verifier, then reject
        self.quarantine_verifier: Optional[Verifier] = None
        #: fault-containment gauges (round 9)
        self.poisoned_windows = 0
        self.quarantined = 0
        self.quarantine_rejected = 0
        #: cumulative window accounting (the bench's amortization gauges)
        self.dispatches = 0
        self.sigs_dispatched = 0
        self.wait_s = 0.0  # host blocked in resolve (unhidden device time)
        self.seam_s = 0.0  # verify-seam wall time, overlap callback excluded
        self.depth_hwm = 0  # high-water in-flight count
        #: most recent run_coalesced cycle (the simulator's per-cycle share)
        self.last_seam_s = 0.0
        self.last_wait_s = 0.0
        self.last_max_depth = 0
        self.warmup_compile_s = 0.0
        if warmup and hasattr(verifier, "warmup"):
            self.warmup_compile_s = verifier.warmup()

    # -- passthroughs: tune the wrapped verifier through the pipeline ----

    @property
    def fixed_bucket(self) -> Optional[int]:
        return getattr(self.verifier, "fixed_bucket", None)

    @fixed_bucket.setter
    def fixed_bucket(self, value: Optional[int]) -> None:
        self.verifier.fixed_bucket = value

    @property
    def registry(self):
        return self.verifier.registry

    # -- window mechanics ------------------------------------------------

    def _dispatch(self, chunk: Sequence[Vertex]) -> None:
        try:
            handle = self.verifier.dispatch_batch(chunk)
        except Exception:  # noqa: BLE001 — prep/dispatch fault contained
            self._contain(chunk, failed_first=False)
            return
        self._inflight.append((handle, chunk))
        self._book_dispatch(len(chunk))

    def _dispatch_prepped(self, prepped, chunk: Sequence[Vertex]) -> None:
        """Ship a batch already prepped on the engine's seam thread
        (TPUVerifier.prep_batch_async) — same window accounting as
        _dispatch, prep already paid."""
        try:
            handle = self.verifier.dispatch_prepped(prepped)
        except Exception:  # noqa: BLE001 — dispatch fault contained
            self._contain(chunk, failed_first=False)
            return
        self._inflight.append((handle, chunk))
        self._book_dispatch(prepped.count)

    def _book_dispatch(self, count: int) -> None:
        self.dispatches += 1
        self.sigs_dispatched += count
        d = len(self._inflight)
        if d > self.depth_hwm:
            self.depth_hwm = d
        if d > self.last_max_depth:
            self.last_max_depth = d

    def _pending(self) -> int:
        """Masks still owed to the caller: contained (already computed)
        plus in flight on the device."""
        return len(self._salvaged) + len(self._inflight)

    def _resolve_oldest(self) -> List[bool]:
        if self._salvaged:
            # containment already produced this chunk's mask; it is
            # older than anything in _inflight by construction
            return self._salvaged.popleft()
        handle, chunk = self._inflight.popleft()
        t0 = time.perf_counter()
        try:
            out = self.verifier.resolve_batch(handle)
        except Exception:  # noqa: BLE001 — resolve fault contained
            self._contain(chunk, failed_first=True)
            out = self._salvaged.popleft()
        dt = time.perf_counter() - t0
        self.wait_s += dt
        self.last_wait_s += dt
        # device share of the verifier's cumulative seam breakdown (its
        # own sync verify_batch books the same quantity for itself)
        if hasattr(self.verifier, "total_dispatch_s"):
            self.verifier.total_dispatch_s += dt
        return out

    # -- fault containment (round 9) --------------------------------------

    def _quarantine(self, chunk: Sequence[Vertex]) -> List[bool]:
        """Re-verify a chunk out of a poisoned window exactly once: on
        the ladder's next tier when ResilientVerifier wired one, else a
        fresh serial pass on the wrapped verifier. A second failure
        rejects the chunk — fail closed, never fail open."""
        self.quarantined += 1
        self.log.event("verify_quarantined", chunk=len(chunk))
        vs = list(chunk)
        try:
            if self.quarantine_verifier is not None:
                return self.quarantine_verifier.verify_batch(vs)
            return self.verifier.verify_batch(vs)
        except Exception:  # noqa: BLE001 — second failure fail-closes
            self.quarantine_rejected += 1
            return [False] * len(vs)

    def _contain(self, chunk: Sequence[Vertex], failed_first: bool) -> None:
        """A dispatch or resolve exception poisoned the window: resolve
        every salvageable in-flight entry (a second fault quarantines
        that chunk too), re-arm the staging ring (fresh slots — the
        aliasing discipline survives orphaned dispatches, see
        TPUVerifier.reset_staging), then quarantine the failing chunk.
        The resulting masks land on ``_salvaged`` in FIFO chunk order:
        ``failed_first`` is True for a resolve fault (the failed chunk
        was the oldest, already popped) and False for a dispatch fault
        (the failed chunk never entered the window)."""
        self.poisoned_windows += 1
        self.log.event(
            "verify_window_poisoned", inflight=len(self._inflight)
        )
        entries = []  # (mask-or-None, chunk) in FIFO order
        while self._inflight:
            h, ch = self._inflight.popleft()
            try:
                entries.append((self.verifier.resolve_batch(h), ch))
            except Exception:  # noqa: BLE001 — quarantined after reset
                entries.append((None, ch))
        if callable(getattr(self.verifier, "reset_staging", None)):
            self.verifier.reset_staging()
        masks: List[List[bool]] = []
        if failed_first:
            masks.append(self._quarantine(chunk))
        for m, ch in entries:
            masks.append(m if m is not None else self._quarantine(ch))
        if not failed_first:
            masks.append(self._quarantine(chunk))
        self._salvaged.extend(masks)

    def drain(self) -> List[bool]:
        """Resolve everything still owed — salvaged containment masks
        plus the in-flight window — and return the concatenated mask.
        The reset seam for callers recovering from an external failure:
        after drain() the window is empty and the next dispatch starts
        clean."""
        out: List[bool] = []
        while self._pending():
            out.extend(self._resolve_oldest())
        return out

    def run_coalesced(
        self,
        vertices: Sequence[Vertex],
        overlap: Optional[Callable[[], None]] = None,
        hold_tail: bool = False,
    ) -> List[bool]:
        """One coalesced cycle: chunk ``vertices`` at the verifier's
        fixed bucket, stream the chunks through the depth-K window, run
        ``overlap()`` once after the last dispatch (host work with no
        causal dependency on the in-flight masks — the simulator's
        deferred delivery flush), resolve FIFO, return the full mask.

        Chunk boundaries are exactly ``verify_rounds``' synchronous
        boundaries, so padding — and therefore the mask — is
        byte-identical to the serial path. ``seam_s``/``last_seam_s``
        exclude the overlap callback's duration (the callee accounts for
        its own time).

        ``hold_tail`` (ISSUE 16 tentpole 4) keeps up to ``depth - 1``
        chunks in flight across the call boundary instead of draining
        the window at the cycle edge: the returned mask then covers only
        the RESOLVED front of this call's input, and the held chunks'
        masks emerge at the FRONT of the next call's mask (or via
        :meth:`drain`), in the same FIFO order. Callers owning the
        round loop (the simulator's pipelined path) use it so the
        device keeps crunching round r+1's tail while the host pumps
        round r+2 — the depth-K window spans round boundaries rather
        than re-filling from empty each cycle."""
        t0 = time.perf_counter()
        self.last_wait_s = 0.0
        self.last_max_depth = len(self._inflight)
        # pipeline_enabled off (bench's sync A/B side) caps the window at
        # 1: dispatch-then-resolve, the pre-pipeline serial shape
        depth = (
            self.depth
            if getattr(self.verifier, "pipeline_enabled", True)
            else 1
        )
        cap = getattr(self.verifier, "fixed_bucket", None) or len(vertices)
        cap = max(int(cap), 1)
        mask: List[bool] = []
        chunks = [vertices[i : i + cap] for i in range(0, len(vertices), cap)]
        async_prep = (
            depth > 1
            and len(chunks) > 1
            and callable(getattr(self.verifier, "prep_batch_async", None))
            and callable(getattr(self.verifier, "dispatch_prepped", None))
        )
        if async_prep:
            # Prep-ahead on the engine's seam thread: chunk k+2's prep
            # runs while chunk k+1's prep is queued behind it and chunk
            # k executes on the device. At most 2 preps outstanding, and
            # a new prep is submitted only AFTER the window has drained
            # below depth and the current chunk has dispatched — so when
            # prep j+2 claims staging slot (j+2) mod (pipeline_depth+2),
            # that slot's previous dispatch (chunk <= j-depth) has
            # already resolved.
            preps: Deque = deque()
            nxt = 0
            while nxt < len(chunks) and len(preps) < 2:
                preps.append(
                    (self.verifier.prep_batch_async(chunks[nxt]), chunks[nxt])
                )
                nxt += 1
            while preps:
                fut, chunk = preps.popleft()
                try:
                    prepped = fut.result()
                except Exception:  # noqa: BLE001 — prep fault contained
                    self._contain(chunk, failed_first=False)
                else:
                    while self._pending() >= depth:
                        mask.extend(self._resolve_oldest())
                    self._dispatch_prepped(prepped, chunk)
                if nxt < len(chunks):
                    preps.append(
                        (
                            self.verifier.prep_batch_async(chunks[nxt]),
                            chunks[nxt],
                        )
                    )
                    nxt += 1
        else:
            for chunk in chunks:
                while self._pending() >= depth:
                    mask.extend(self._resolve_oldest())
                self._dispatch(chunk)
        overlap_s = 0.0
        if overlap is not None:
            t1 = time.perf_counter()
            overlap()
            overlap_s = time.perf_counter() - t1
        keep = max(0, depth - 1) if hold_tail else 0
        while self._pending() > keep:
            mask.extend(self._resolve_oldest())
        self.last_seam_s = max(0.0, (time.perf_counter() - t0) - overlap_s)
        self.seam_s += self.last_seam_s
        return mask

    # -- Verifier interface ----------------------------------------------

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        if not vertices:
            return []
        return self.run_coalesced(list(vertices))

    def verify_rounds(
        self, rounds: Sequence[Sequence[Vertex]]
    ) -> List[List[bool]]:
        lens = [len(r) for r in rounds]
        flat = [v for r in rounds for v in r]
        mask = self.run_coalesced(flat) if flat else []
        out, pos = [], 0
        for ln in lens:
            out.append(mask[pos : pos + ln])
            pos += ln
        return out

    # -- gauges ----------------------------------------------------------

    def overlap_fraction(self) -> Optional[float]:
        """Share of the verify seam's wall time during which the host was
        doing useful work instead of blocked on the device:
        ``1 - wait_s / seam_s``. 0 ~= the serial dispatch-then-resolve
        shape; higher = more of the device time hidden behind host prep
        and delivery walks. None until something ran."""
        if self.seam_s <= 0.0:
            return None
        return max(0.0, min(1.0, 1.0 - self.wait_s / self.seam_s))

    def stats(self) -> dict:
        out = {
            "depth": self.depth,
            "queue_depth_max": self.depth_hwm,
            "dispatches": self.dispatches,
            "sigs_dispatched": self.sigs_dispatched,
            "wait_s": round(self.wait_s, 4),
            "seam_s": round(self.seam_s, 4),
            "overlap_fraction": (
                None
                if self.overlap_fraction() is None
                else round(self.overlap_fraction(), 3)
            ),
            "warmup_compile_s": round(self.warmup_compile_s, 2),
        }
        # host-prep engine gauges (round 8): worker count and the share
        # of prepped rows that actually took the parallel row-block path
        # — the structural no-silent-fallback signal
        if callable(getattr(self.verifier, "prep_stats", None)):
            ps = self.verifier.prep_stats()
            out["prep_workers"] = ps["workers"]
            out["prep_parallel_fraction"] = round(ps["parallel_fraction"], 3)
        # mesh gauges when the wrapped verifier dispatches sharded
        # (ShardedTPUVerifier): devices, per-shard rows of the latest
        # dispatch, and its shard fill imbalance (0.0 = every shard full)
        mesh_devices = getattr(self.verifier, "mesh_devices", 0)
        if mesh_devices:
            out["mesh_devices"] = mesh_devices
            out["shard_batch"] = getattr(self.verifier, "last_shard_batch", 0)
            out["shard_imbalance"] = round(
                getattr(self.verifier, "last_shard_imbalance", 0.0), 3
            )
        # fault-containment gauges (round 9), only once something was
        # actually contained — the clean path's stats dict is unchanged
        rs = self.resilience_stats()
        if rs["poisoned_windows"] or rs["quarantined"]:
            out["poisoned_windows"] = rs["poisoned_windows"]
            out["quarantined"] = rs["quarantined"]
            out["quarantine_rejected"] = rs["quarantine_rejected"]
        return out

    def resilience_stats(self) -> dict:
        """Round-9 containment gauges, pipeline window + wrapped
        verifier's own chunk-streaming path combined. Same key shape as
        ResilientVerifier.resilience_stats so consumers (Simulation's
        metrics fan-out, the bench's verifier_breakdown) read either."""
        return {
            "retries": getattr(self.verifier, "retries_total", 0),
            "fallback_tier": 0,
            "fallbacks": 0,
            "poisoned_windows": self.poisoned_windows
            + getattr(self.verifier, "poisoned_windows", 0),
            "quarantined": self.quarantined
            + getattr(self.verifier, "quarantined_chunks", 0),
            "quarantine_rejected": self.quarantine_rejected
            + getattr(self.verifier, "quarantine_rejected", 0),
            "sidecar_rpc_failures": getattr(self.verifier, "rpc_failures", 0),
            "sidecar_health": None,
        }
