"""The Verifier seam — the north-star plugin boundary.

BASELINE.json: the per-vertex reliable-broadcast signature verification is
"lifted behind a new batched Verifier interface, introduced as a sibling to
the existing Transport plugin boundary" (reference ``process/transport.go:6``
is the only seam the reference has). A Process takes a Verifier at
construction and hands it *whole batches* of vertices; backends:

- :class:`dag_rider_tpu.verifier.cpu.CPUVerifier` — host RFC 8032 path,
- :class:`dag_rider_tpu.verifier.tpu.TPUVerifier` — vmapped JAX limb-field
  path, one DAG round per device dispatch.

Both must produce **byte-identical accept masks**, which is what makes the
CPU-vs-TPU commit order byte-identical (the consensus state machine is a
deterministic function of the accept masks and the delivery schedule).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import List, Optional, Sequence

from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.crypto import ed25519


@dataclasses.dataclass(frozen=True)
class KeyRegistry:
    """source index -> Ed25519 public key (32 bytes). The PKI the
    reference's TODO names (``process.go:388``)."""

    public_keys: tuple

    @staticmethod
    def generate(n: int, seed_prefix: bytes = b"dagrider-test-key-"):
        """Deterministic test PKI: seeds derived from the index. NOT for
        production use (seeds are guessable by construction)."""
        import hashlib

        seeds, pubs = [], []
        for i in range(n):
            seed = hashlib.sha256(seed_prefix + str(i).encode()).digest()
            sk, pk = ed25519.generate_keypair(seed)
            seeds.append(sk)
            pubs.append(pk)
        return KeyRegistry(tuple(pubs)), seeds

    def key_of(self, source: int) -> Optional[bytes]:
        """Public key of ``source``, or None when out of range — the seam
        must be total: a bad source yields a reject bit, never an
        IndexError or (worse) negative-index aliasing to another node's
        key."""
        if not 0 <= source < len(self.public_keys):
            return None
        return self.public_keys[source]

    @property
    def n(self) -> int:
        return len(self.public_keys)

    #: source index -> BLS12-381 G2 public key (affine fp2 tuple) for the
    #: aggregated round-certificate path (ISSUE 9). Empty when the
    #: deployment has no certificate keys — everything cert-related gates
    #: on this being populated.
    bls_public_keys: tuple = ()

    def bls_key_of(self, source: int):
        """BLS certificate public key of ``source`` — total, like
        :meth:`key_of`."""
        if not 0 <= source < len(self.bls_public_keys):
            return None
        return self.bls_public_keys[source]

    @staticmethod
    def generate_with_cert(
        n: int, seed_prefix: bytes = b"dagrider-test-key-"
    ):
        """The :meth:`generate` test PKI plus per-process BLS certificate
        keys. Returns (registry, ed25519 seeds, bls secret keys); the
        BLS secrets are what :class:`CertSigner` wraps."""
        import hashlib

        from dag_rider_tpu.crypto import bls12381 as bls

        reg, seeds = KeyRegistry.generate(n, seed_prefix)
        sks, pks = [], []
        for i in range(n):
            sk = (
                int.from_bytes(
                    hashlib.sha256(
                        seed_prefix + b"|bls|" + str(i).encode()
                    ).digest(),
                    "big",
                )
                % bls.R
            )
            sks.append(sk)
            pks.append(bls.pk_of(sk))
        reg = dataclasses.replace(reg, bls_public_keys=tuple(pks))
        return reg, seeds, sks


class VertexSigner:
    """Signs this process's own vertices (held by the Process). The key
    expansion (incl. deriving the public key) is done once here, not per
    signature."""

    def __init__(self, seed: bytes):
        self._a, self._prefix, self._A_enc = ed25519.expand_seed(seed)

    @property
    def public_key(self) -> bytes:
        return self._A_enc

    def sign_vertex(self, v: Vertex) -> Vertex:
        sig = ed25519.sign_expanded(
            self._a, self._prefix, self._A_enc, v.signing_bytes()
        )
        return dataclasses.replace(v, signature=sig)


class CertSigner:
    """BLS-signs this process's own vertex digests for the aggregated
    round-certificate path (ISSUE 9). Separate from :class:`VertexSigner`
    on purpose: the ed25519 vertex signature stays the per-vertex oracle;
    the BLS signature only ever feeds certificate aggregation."""

    def __init__(self, sk: int):
        self._sk = sk

    def sign_digest(self, digest: bytes) -> bytes:
        from dag_rider_tpu.crypto import bls12381 as bls

        return bls.sign(self._sk, digest)

    def sign_digests(self, digests: Sequence[bytes]) -> List[bytes]:
        """Round-batched share signing (ISSUE 12 tentpole 1): one
        :func:`bls12381.sign_many` call amortizes the hash-to-curve field
        maps and scalar ladders across every digest, routed by
        DAGRIDER_CERT_SIGN. Byte-identical to mapping
        :meth:`sign_digest` — tests/test_cert_phase2.py pins it."""
        from dag_rider_tpu.crypto import bls12381 as bls

        return bls.sign_many([self._sk] * len(digests), digests)

    #: domain tag for lane availability acks (ISSUE 17) — keeps an ack
    #: share from ever being replayable as a vertex cert share: both are
    #: BLS signatures under the same key, but a cert share signs a raw
    #: vertex digest while an ack signs the tagged batch digest
    LANE_ACK_DOMAIN = b"dagrider-lane-ack-v1|"

    def sign_availability(self, digest: bytes) -> bytes:
        """Sign a lane-batch availability ack: the attestation that this
        process holds (and has integrity-checked) the payload bytes
        hashing to ``digest``. 2f+1 of these aggregate into the batch
        availability certificate via :meth:`CertVerifier.aggregate` —
        the same G1 share machinery as round certificates."""
        from dag_rider_tpu.crypto import bls12381 as bls

        return bls.sign(self._sk, self.LANE_ACK_DOMAIN + digest)


class VerifierUnavailableError(RuntimeError):
    """A verifier backend could not be reached or could not complete an
    attempt (transport failure, dead sidecar, poisoned device state) — as
    opposed to a *verdict*: no statement about signature validity is
    implied. Backends raise it (when configured to) so a degradation
    ladder (verifier/resilient.py) can distinguish "try the next tier"
    from "these signatures are invalid"; without a ladder the same
    condition fail-closes to an all-False mask."""


class Verifier(abc.ABC):
    """Batched vertex-signature verification."""

    @abc.abstractmethod
    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        """Accept mask, same order as input. Must be a pure function of
        (vertex bytes, registry) — no randomness — so CPU and TPU backends
        agree bit-for-bit."""

    def verify_rounds(
        self, rounds: Sequence[Sequence[Vertex]]
    ) -> List[List[bool]]:
        """Accept masks for several rounds' batches. Semantically
        equivalent to mapping :meth:`verify_batch`; device backends
        override this to merge the rounds into one padded dispatch
        (amortizing the fixed per-dispatch cost — see PROFILE.md)."""
        return [self.verify_batch(r) for r in rounds]
