"""CPU Verifier backend — configs #1-2 of the benchmark ladder
(BASELINE.json: "16-node Ed25519 ... CPU Verifier baseline")."""

from __future__ import annotations

from typing import List, Sequence

from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.crypto import ed25519
from dag_rider_tpu.verifier.base import KeyRegistry, Verifier


class CPUVerifier(Verifier):
    """Pure-host RFC 8032 verification, one vertex at a time."""

    def __init__(self, registry: KeyRegistry):
        self.registry = registry

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        items = []
        for v in vertices:
            pk = self.registry.key_of(v.source)
            # missing key / missing signature degrade to un-verifiable
            # items that ed25519.verify rejects by length — the mask stays
            # total without a second rejection code path here.
            items.append((pk or b"", v.signing_bytes(), v.signature or b""))
        return ed25519.verify_batch(items)


class NullVerifier(Verifier):
    """Accept-everything backend — reproduces the reference's (absent)
    authentication for differential runs against reference semantics."""

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        return [True] * len(vertices)
