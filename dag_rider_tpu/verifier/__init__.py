from dag_rider_tpu.verifier.base import (
    KeyRegistry,
    Verifier,
    VerifierUnavailableError,
    VertexSigner,
)
from dag_rider_tpu.verifier.cpu import CPUVerifier, NullVerifier
from dag_rider_tpu.verifier.faults import (
    VerifierFaultInjector,
    VerifierFaultPlan,
)
from dag_rider_tpu.verifier.pipeline import VerifierPipeline
from dag_rider_tpu.verifier.resilient import ResilientVerifier

__all__ = [
    "KeyRegistry",
    "Verifier",
    "VertexSigner",
    "CPUVerifier",
    "NullVerifier",
    "VerifierPipeline",
    "ResilientVerifier",
    "VerifierUnavailableError",
    "VerifierFaultInjector",
    "VerifierFaultPlan",
]
