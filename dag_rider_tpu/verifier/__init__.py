from dag_rider_tpu.verifier.base import KeyRegistry, Verifier, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier, NullVerifier
from dag_rider_tpu.verifier.pipeline import VerifierPipeline

__all__ = [
    "KeyRegistry",
    "Verifier",
    "VertexSigner",
    "CPUVerifier",
    "NullVerifier",
    "VerifierPipeline",
]
