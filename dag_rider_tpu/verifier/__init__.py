from dag_rider_tpu.verifier.base import KeyRegistry, Verifier, VertexSigner
from dag_rider_tpu.verifier.cpu import CPUVerifier, NullVerifier

__all__ = [
    "KeyRegistry",
    "Verifier",
    "VertexSigner",
    "CPUVerifier",
    "NullVerifier",
]
