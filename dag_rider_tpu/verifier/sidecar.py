"""gRPC Verifier sidecar — the north star's deployment shape.

BASELINE.json: "The TPU Verifier impl ships whole-round vertex batches
over gRPC to a JAX sidecar that runs vmap'd Ed25519 ... batch-verify".
Two halves:

- :class:`VerifierSidecarServer` — hosts any Verifier backend (normally
  :class:`~dag_rider_tpu.verifier.tpu.TPUVerifier` pinned to the chip)
  behind one unary method ``/dagrider.Verifier/VerifyBatch``;
- :class:`RemoteVerifier` — a drop-in Verifier whose ``verify_batch``
  round-trips the batch to the sidecar.

Wire format (no protobuf codegen in the image — generic byte handlers,
like transport/net.py): request = concatenated length-prefixed frames of
codec-encoded vertices; response = one byte per vertex (0x00/0x01 mask).
The mask therefore stays byte-identical across in-process CPU, in-process
TPU, and remote-TPU verifier placements.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Sequence

import grpc

from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.verifier.base import Verifier, VerifierUnavailableError

_METHOD = "/dagrider.Verifier/VerifyBatch"
_identity = lambda b: b  # noqa: E731


def _encode_batch(vertices: Sequence[Vertex]) -> bytes:
    return b"".join(codec.frame(codec.encode_vertex(v)) for v in vertices)


def _decode_batch(payload: bytes) -> List[Vertex]:
    out: List[Vertex] = []
    offset = 0
    while offset < len(payload):
        item = codec.read_frame(payload, offset)
        if item is None:
            raise ValueError("truncated batch frame")
        blob, offset = item
        out.append(codec.decode_vertex(blob)[0])
    return out


class _VerifyHandler(grpc.GenericRpcHandler):
    def __init__(self, backend: Verifier):
        self._backend = backend

    def service(self, handler_call_details):
        if handler_call_details.method != _METHOD:
            return None

        def unary(request: bytes, context) -> bytes:
            try:
                batch = _decode_batch(request)
            except ValueError:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "malformed batch"
                )
            mask = self._backend.verify_batch(batch)
            return bytes(1 if ok else 0 for ok in mask)

        return grpc.unary_unary_rpc_method_handler(
            unary, request_deserializer=_identity, response_serializer=_identity
        )


class VerifierSidecarServer:
    """Hosts a Verifier backend on an insecure local port (the sidecar
    lives on the same machine/pod as the consensus host; transport auth is
    a deployment concern layered via gRPC creds if needed)."""

    def __init__(
        self,
        backend: Verifier,
        listen_addr: str = "127.0.0.1:0",
        *,
        warmup: bool = True,
        prep_workers: Optional[int] = None,
    ):
        from concurrent import futures

        # Parallel host-prep engine (verifier/prep.py): an explicit
        # worker count overrides the backend's env-derived default, set
        # before warmup so the first prep builds the right pool.
        if prep_workers is not None and hasattr(backend, "prep_workers"):
            backend.prep_workers = int(prep_workers)
        # Device-backed sidecars get entry-path parity with bench/tests:
        # the repo-local XLA compile cache plus an AOT warmup of the
        # fixed-bucket program BEFORE the port opens, so the first
        # VerifyBatch RPC never eats a cold ~35 s XLA compile. Host-only
        # backends (CPUVerifier oracle) skip both — no jax import.
        self.warmup_compile_s = 0.0
        if hasattr(backend, "warmup"):
            from dag_rider_tpu.utils.jaxcache import enable_persistent_cache

            enable_persistent_cache()
            if warmup:
                self.warmup_compile_s = backend.warmup()
        # one worker: device dispatches serialize anyway, and a single
        # thread keeps per-backend batching deterministic.
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=1))
        self._server.add_generic_rpc_handlers((_VerifyHandler(backend),))
        self.bound_port = self._server.add_insecure_port(listen_addr)
        self._server.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.bound_port}"

    def stop(self) -> None:
        self._server.stop(grace=None)


class RemoteVerifier(Verifier):
    """Verifier seam implementation that defers to a sidecar.

    Fail-closed **per attempt** (SURVEY.md D10: signatures before any
    state change): a transport failure — RPC error, deadline, or a
    malformed/mis-sized reply — must never admit a vertex. What happens
    after a failed attempt is configurable:

    - ``retries`` > 0 re-sends the same payload with exponential backoff
      plus seeded jitter, reconnecting the channel between attempts (a
      restarted sidecar gets a fresh connection instead of a wedged one);
    - once every attempt has failed, the default is the pre-round-9
      contract — the whole batch reads ``[False] * n``, indistinguishable
      from n invalid signatures at the mask level (the
      ``sidecar_rpc_failures`` counter is what tells the two apart in
      metrics);
    - with ``raise_on_unavailable=True`` exhaustion raises
      :class:`VerifierUnavailableError` instead, so a degradation ladder
      (verifier/resilient.py) can hand the batch to its next tier rather
      than permanently rejecting valid vertices on a sidecar blip.

    Either way no attempt ever accepts a vertex it could not check.
    """

    def __init__(
        self,
        address: str,
        *,
        timeout: float = 30.0,
        retries: int = 0,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        jitter: float = 0.5,
        seed: int = 0,
        raise_on_unavailable: bool = False,
    ):
        self._address = address
        self._timeout = timeout
        self._retries = max(0, int(retries))
        self._backoff_s = float(backoff_s)
        self._backoff_cap_s = float(backoff_cap_s)
        self._jitter = float(jitter)
        self._rng = random.Random(seed)
        self.raise_on_unavailable = raise_on_unavailable
        self._lock = threading.Lock()
        #: transport-level failures (RPC error/timeout/bad reply) — NOT
        #: invalid signatures; surfaced as metrics counter
        #: ``sidecar_rpc_failures`` so chaos runs can tell a dead sidecar
        #: from a batch of forgeries (both read all-False at mask level)
        self.rpc_failures = 0
        #: re-sends of a payload after a failed attempt
        self.retries_total = 0
        self._connect()

    def _connect(self) -> None:
        self._channel = grpc.insecure_channel(self._address)
        self._call = self._channel.unary_unary(
            _METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def reconnect(self) -> None:
        """Tear down and rebuild the channel — between retry attempts and
        when a health probe wants a fresh connection to a restarted
        sidecar (gRPC keeps a failed subchannel in backoff otherwise)."""
        with self._lock:
            self._channel.close()
            self._connect()

    def _invoke(self, payload: bytes) -> bytes:
        """One locked RPC attempt — the seam the chaos harness
        (verifier/faults.py) shadows to inject sidecar failures."""
        with self._lock:
            return self._call(payload, timeout=self._timeout)

    def ping(self) -> bool:
        """Health probe: round-trip an EMPTY batch (encodes to b"", the
        backend verifies nothing and answers b""). True iff the sidecar
        answered — used by the degradation ladder to promote this tier
        back after recovery. Never counts toward rpc_failures."""
        try:
            return self._invoke(b"") == b""
        except (grpc.RpcError, VerifierUnavailableError):
            return False

    def stats(self) -> dict:
        return {
            "sidecar_rpc_failures": self.rpc_failures,
            "retries": self.retries_total,
        }

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        if not vertices:
            return []
        payload = _encode_batch(vertices)
        delay = self._backoff_s
        for attempt in range(self._retries + 1):
            try:
                mask = self._invoke(payload)
            except (grpc.RpcError, VerifierUnavailableError):
                self.rpc_failures += 1
            else:
                if len(mask) == len(vertices):
                    return [b == 1 for b in mask]
                # a mis-sized reply is a transport fault, not a verdict
                self.rpc_failures += 1
            if attempt < self._retries:
                self.retries_total += 1
                time.sleep(delay * (1.0 + self._jitter * self._rng.random()))
                delay = min(delay * 2.0, self._backoff_cap_s)
                self.reconnect()
        if self.raise_on_unavailable:
            raise VerifierUnavailableError(
                f"sidecar {self._address} unavailable after "
                f"{self._retries + 1} attempt(s)"
            )
        return [False] * len(vertices)

    def close(self) -> None:
        self._channel.close()
