"""Degradation ladder over an ordered chain of verifier backends.

DAG-Rider's value proposition is progress under asynchrony and faults
(PAPER.md), yet before round 9 the verify hot path died on the first
transient: a sidecar blip fail-closed a whole batch with no retry and no
fallback, permanently rejecting valid vertices from the DAG.
:class:`ResilientVerifier` makes component failure a first-class input
(the Fides line of work — PAPERS.md, arXiv:2501.01062):

- **ladder** — an ordered chain of tiers, e.g. sidecar ->
  local TPU/sharded -> CPU reference. Each call starts at the highest
  healthy tier; an attempt that raises is retried with exponential
  backoff + seeded jitter, and when a tier's attempts are exhausted the
  call falls to the next tier.
- **fail-closed per attempt, reject only at exhaustion** — no attempt
  ever admits a vertex it could not check (SURVEY.md D10), but a batch
  reads all-False only after the WHOLE ladder failed. A sidecar blip
  therefore costs latency, not valid vertices.
- **health probes + promotion** — a tier marked down is probed in a
  background thread (``ping()`` when the tier has one — RemoteVerifier
  does — else a zero-cost empty verify); the first successful probe
  promotes the tier back, so recovery is automatic and the ladder does
  not stay pinned to its floor forever.
- **quarantine wiring** — tiers exposing a ``quarantine_verifier`` slot
  (VerifierPipeline, TPUVerifier) get their NEXT tier wired into it, so
  a chunk a poisoned pipeline window quarantines is re-verified once on
  the ladder's next tier instead of serially on the tier that just
  failed.

The mask stays a pure function of (vertex bytes, registry): every tier
computes byte-identical accept bits, so WHICH tier answered is
observable only in the gauges (``verify_fallback_tier`` et al.), never
in the commit order.

Knobs: ``DAGRIDER_VERIFY_RETRY`` (attempts per tier - 1, default 1) and
``DAGRIDER_VERIFY_FALLBACK`` ("cpu" to ladder node.py's device/remote
verifiers onto a CPUVerifier floor; default off) — node.py config keys
``verify_retry`` / ``verify_fallback`` override per node.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Sequence

from dag_rider_tpu import config
from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.utils.slog import NOOP, EventLog
from dag_rider_tpu.verifier.base import Verifier


def default_verify_retry() -> int:
    """Bounded retry count per ladder tier: DAGRIDER_VERIFY_RETRY,
    default 1 (one re-attempt before falling a tier)."""
    return config.env_int("DAGRIDER_VERIFY_RETRY")


def default_verify_fallback() -> str:
    """Fallback-tier selector for node.py: DAGRIDER_VERIFY_FALLBACK,
    default "" (no ladder — the pre-round-9 single-backend shape).
    "cpu" appends a CPUVerifier floor under the configured verifier."""
    val = config.env_str("DAGRIDER_VERIFY_FALLBACK").lower()
    if val in ("", "0", "off", "none", "false"):
        return ""
    if val != "cpu":
        raise ValueError(
            f"DAGRIDER_VERIFY_FALLBACK must be 'cpu' or off, got {val!r}"
        )
    return val


class ResilientVerifier(Verifier):
    """Ordered verifier chain with retry, fallback, and recovery.

    ``tiers[0]`` is the preferred backend, ``tiers[-1]`` the trusted
    floor. Tiers carrying a ``raise_on_unavailable`` flag
    (RemoteVerifier) have it forced on: the ladder must see transport
    failure as an exception, not as an all-False mask it would apply as
    a verdict.
    """

    def __init__(
        self,
        tiers: Sequence[Verifier],
        *,
        retries: Optional[int] = None,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        jitter: float = 0.5,
        seed: int = 0,
        probe_interval_s: float = 0.5,
        log: EventLog = NOOP,
    ):
        if not tiers:
            raise ValueError("ResilientVerifier needs at least one tier")
        self.tiers = list(tiers)
        #: obs seam (round 16): tier transitions that previously only
        #: bumped gauges now emit typed events; verify_exhausted is a
        #: flight-recorder trigger
        self.log = log
        self.retries = (
            default_verify_retry() if retries is None else max(0, int(retries))
        )
        self._backoff_s = float(backoff_s)
        self._backoff_cap_s = float(backoff_cap_s)
        self._jitter = float(jitter)
        self._rng = random.Random(seed)
        self.probe_interval_s = float(probe_interval_s)
        self._lock = threading.Lock()
        self._down = [False] * len(self.tiers)
        self._probing: set = set()
        #: gauges — cumulative over the ladder's lifetime
        self.retries_total = 0
        self.fallbacks_total = 0
        self.exhausted_total = 0  # batches rejected by the WHOLE ladder
        self.last_tier = 0
        #: optional CertVerifier (ISSUE 9): the aggregated-certificate
        #: check is a rung ABOVE this ladder — a bad certificate degrades
        #: the round back onto the per-vertex tiers below (the Process
        #: owns that transition); wiring the CertVerifier here folds its
        #: accept/reject gauges into the same resilience bundle.
        self.cert_verifier = None
        # a poisoned pipeline window re-verifies its quarantined chunk on
        # the ladder's NEXT tier (see module docstring)
        for i, tier in enumerate(self.tiers):
            if hasattr(tier, "raise_on_unavailable"):
                tier.raise_on_unavailable = True
            if hasattr(tier, "quarantine_verifier") and i + 1 < len(
                self.tiers
            ):
                tier.quarantine_verifier = self.tiers[i + 1]

    # -- health tracking --------------------------------------------------

    def tier_health(self) -> List[bool]:
        with self._lock:
            return [not d for d in self._down]

    def _mark_down(self, idx: int) -> None:
        self.log.event("verify_tier_down", tier=idx)
        with self._lock:
            self._down[idx] = True
            if idx in self._probing:
                return
            self._probing.add(idx)
        t = threading.Thread(
            target=self._probe_loop, args=(idx,), daemon=True,
            name=f"dagrider-verify-probe-{idx}",
        )
        t.start()

    def _probe_once(self, tier) -> bool:
        ping = getattr(tier, "ping", None)
        try:
            if callable(ping):
                return bool(ping())
            return tier.verify_batch([]) == []
        except Exception:  # noqa: BLE001 — a probe failure is the signal
            return False

    def _probe_loop(self, idx: int) -> None:
        """Background recovery watch for one downed tier: probe at a
        fixed cadence, promote back on the first success. RemoteVerifier
        tiers get a reconnect() first so the probe is not answered by a
        subchannel gRPC still holds in connection backoff."""
        tier = self.tiers[idx]
        while True:
            with self._lock:
                if not self._down[idx]:
                    self._probing.discard(idx)
                    return
            time.sleep(self.probe_interval_s)
            if callable(getattr(tier, "reconnect", None)):
                try:
                    tier.reconnect()
                except Exception:  # noqa: BLE001 — retried next cycle
                    continue
            if self._probe_once(tier):
                with self._lock:
                    self._down[idx] = False
                    self._probing.discard(idx)
                self.log.event("verify_tier_recovered", tier=idx)
                return

    # -- ladder mechanics -------------------------------------------------

    def _run(self, call, reject):
        """Walk the ladder: healthy tiers first with bounded retries;
        if every tier is marked down, try them all anyway (a stale down
        mark must not brick the verifier); reject only when the whole
        chain failed this call."""
        order = [
            i for i, healthy in enumerate(self.tier_health()) if healthy
        ] or list(range(len(self.tiers)))
        last_exc: Optional[BaseException] = None
        for pos, idx in enumerate(order):
            tier = self.tiers[idx]
            delay = self._backoff_s
            for attempt in range(self.retries + 1):
                try:
                    out = call(tier)
                except Exception as e:  # noqa: BLE001 — any tier failure
                    # falls through the ladder; validity is never implied
                    last_exc = e
                    if attempt < self.retries:
                        self.retries_total += 1
                        self.log.event(
                            "verify_retry",
                            tier=idx,
                            attempt=attempt + 1,
                            error=repr(e)[:200],
                        )
                        time.sleep(
                            delay
                            * (1.0 + self._jitter * self._rng.random())
                        )
                        delay = min(delay * 2.0, self._backoff_cap_s)
                else:
                    self.last_tier = idx
                    if pos > 0:
                        self.fallbacks_total += 1
                        self.log.event(
                            "verify_fallback", tier=idx, from_tier=order[0]
                        )
                    return out
            self._mark_down(idx)
        # the whole ladder failed: fail closed (attempt semantics were
        # preserved throughout — nothing was admitted along the way)
        self.exhausted_total += 1
        self.last_tier = len(self.tiers)
        self.log.event(
            "verify_exhausted",
            tiers=len(self.tiers),
            error=repr(last_exc)[:200] if last_exc is not None else None,
        )
        del last_exc
        return reject

    # -- Verifier interface ----------------------------------------------

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        if not vertices:
            return []
        vs = list(vertices)
        return self._run(
            lambda t: t.verify_batch(vs), [False] * len(vs)
        )

    def verify_rounds(
        self, rounds: Sequence[Sequence[Vertex]]
    ) -> List[List[bool]]:
        rs = [list(r) for r in rounds]
        return self._run(
            lambda t: t.verify_rounds(rs), [[False] * len(r) for r in rs]
        )

    # -- gauges ----------------------------------------------------------

    def resilience_stats(self) -> dict:
        """The round-9 gauge bundle (verify_retries / verify_fallback_tier
        / verify_quarantined / sidecar_health) aggregated across tiers —
        surfaced into pipeline stats, the bench's verifier_breakdown and
        the per-process metrics snapshot."""
        retries = self.retries_total
        quarantined = 0
        poisoned = 0
        rejected = 0
        rpc_failures = 0
        sidecar_health = None
        health = self.tier_health()
        for i, tier in enumerate(self.tiers):
            # a pipeline tier already folds its wrapped verifier in
            sub = getattr(tier, "resilience_stats", None)
            if callable(sub):
                s = sub()
                retries += s.get("retries", 0)
                quarantined += s.get("quarantined", 0)
                poisoned += s.get("poisoned_windows", 0)
                rejected += s.get("quarantine_rejected", 0)
            else:
                retries += getattr(tier, "retries_total", 0)
                quarantined += getattr(tier, "quarantined_chunks", 0)
                poisoned += getattr(tier, "poisoned_windows", 0)
                rejected += getattr(tier, "quarantine_rejected", 0)
            rpc = getattr(tier, "rpc_failures", None)
            if rpc is not None:
                rpc_failures += rpc
                if sidecar_health is None:
                    sidecar_health = 1 if health[i] else 0
        out = {
            "retries": retries,
            "fallback_tier": self.last_tier,
            "fallbacks": self.fallbacks_total,
            "poisoned_windows": poisoned,
            "quarantined": quarantined,
            "quarantine_rejected": rejected,
            "exhausted": self.exhausted_total,
            "sidecar_rpc_failures": rpc_failures,
            "sidecar_health": sidecar_health,
            "tier_health": [1 if h else 0 for h in health],
        }
        if self.cert_verifier is not None:
            cs = self.cert_verifier.stats
            out["cert_checks"] = cs["certs_checked"]
            out["cert_invalid"] = cs["certs_invalid"]
            out["cert_verdict_hits"] = cs["verdict_hits"]
        return out
