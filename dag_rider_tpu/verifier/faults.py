"""Verifier chaos harness — seeded fault injection at every verify seam.

transport/faults.py proves delivery faults (drop/delay/duplicate/
equivocate) against the consensus layer; this module does the same for
the VERIFY stack, so the round-9 resilience machinery (containment in
VerifierPipeline/TPUVerifier, the ResilientVerifier ladder, RemoteVerifier
retry) is tested against the faults it claims to absorb rather than
only on the clean path:

- **prep_raise** — `_prep_block` raises mid-fill: with one worker it
  surfaces from `prep_batch` (pipeline containment); with a pool the
  PrepEngine's serial retry absorbs it first (block-pool boundary).
- **dispatch_raise** — `dispatch_prepped` raises before shipping: the
  failing chunk never enters the window (containment, failed_first off).
- **resolve_raise** — `resolve_batch` raises: the oldest in-flight chunk
  is the poisoned one (containment, failed_first on).
- **rpc_error** — `RemoteVerifier._invoke` raises
  :class:`VerifierUnavailableError`: retry/reconnect, then the ladder.
  (ping() routes through _invoke too, so an armed sidecar also reads as
  unhealthy to the ladder's probe until the budget clears.)

Injection rides the round-7 placement hooks: arming shadows the seam
methods as INSTANCE attributes, which win at every internal call site
(`self._prep_block(...)`, `self.resolve_batch(...)`,
`self.verifier.dispatch_prepped(...)`) for TPUVerifier and
ShardedTPUVerifier alike; disarm() pops the shadows and the class path
is back, byte-identical. Faults are seeded (`VerifierFaultPlan.seed`)
and optionally budgeted (`max_faults`): a finite budget is the
deterministic way to model "the fault clears", which the chaos tests
use to prove no valid vertex stays rejected once it does. With a
worker pool, WHICH prep block rolls first depends on thread timing —
deterministic chaos tests pin ``prep_workers=1`` or rely on the budget,
not the roll order.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Optional

from dag_rider_tpu.verifier.base import VerifierUnavailableError


class VerifierFault(RuntimeError):
    """An injected verify-stack fault (chaos harness, never production)."""


@dataclasses.dataclass
class VerifierFaultPlan:
    """Per-seam fault probabilities in [0, 1], seeded like
    transport/faults.py's FaultPlan. ``max_faults`` bounds the TOTAL
    number of injected faults across all seams (None = unbounded): once
    spent, every seam behaves cleanly — "the fault clears"."""

    prep_raise: float = 0.0
    dispatch_raise: float = 0.0
    resolve_raise: float = 0.0
    rpc_error: float = 0.0
    max_faults: Optional[int] = None
    seed: int = 0


class VerifierFaultInjector:
    """Arms a VerifierFaultPlan onto live verifier objects.

    One injector = one seeded roll sequence + one fault budget, shared
    by every seam it arms (a ladder test arms the same injector on the
    sidecar AND the local tier so the budget spans both). ``stats``
    counts injected faults per seam, mirroring FaultyTransport.stats.
    """

    def __init__(self, plan: VerifierFaultPlan):
        self.plan = plan
        self._rng = random.Random(plan.seed)
        self._lock = threading.Lock()
        self.stats = {
            "prep_raise": 0,
            "dispatch_raise": 0,
            "resolve_raise": 0,
            "rpc_error": 0,
        }
        self.faults_injected = 0
        self._armed: list = []  # (obj, attr) instance shadows to pop

    def exhausted(self) -> bool:
        """True once the fault budget is spent — the plan's seams are
        clean from here on."""
        with self._lock:
            return (
                self.plan.max_faults is not None
                and self.faults_injected >= self.plan.max_faults
            )

    def _fire(self, kind: str, p: float) -> bool:
        """One seeded roll for one seam crossing. Locked: prep blocks
        may roll from pool threads, and the budget must never over-
        spend."""
        if p <= 0.0:
            return False
        with self._lock:
            if (
                self.plan.max_faults is not None
                and self.faults_injected >= self.plan.max_faults
            ):
                return False
            if self._rng.random() >= p:
                return False
            self.faults_injected += 1
            self.stats[kind] += 1
            return True

    # -- arming -----------------------------------------------------------

    def arm(self, verifier) -> None:
        """Shadow the prep/dispatch/resolve seams of a TPUVerifier (or
        subclass) with fault-rolling wrappers. Idempotent per verifier
        per injector; disarm() restores the class methods."""
        plan = self.plan

        orig_prep = verifier._prep_block

        def prep_block(vertices, lo, hi, comb, dest):
            if self._fire("prep_raise", plan.prep_raise):
                raise VerifierFault(f"injected prep fault at rows {lo}:{hi}")
            return orig_prep(vertices, lo, hi, comb, dest)

        verifier._prep_block = prep_block
        self._armed.append((verifier, "_prep_block"))

        orig_dispatch = verifier.dispatch_prepped

        def dispatch_prepped(prepped):
            if self._fire("dispatch_raise", plan.dispatch_raise):
                raise VerifierFault("injected dispatch fault")
            return orig_dispatch(prepped)

        verifier.dispatch_prepped = dispatch_prepped
        self._armed.append((verifier, "dispatch_prepped"))

        orig_resolve = verifier.resolve_batch

        def resolve_batch(pending):
            if self._fire("resolve_raise", plan.resolve_raise):
                raise VerifierFault("injected resolve fault")
            return orig_resolve(pending)

        verifier.resolve_batch = resolve_batch
        self._armed.append((verifier, "resolve_batch"))

    def arm_remote(self, remote) -> None:
        """Shadow RemoteVerifier._invoke so an attempt fails as a
        transport error (VerifierUnavailableError — exactly what a dead
        or unreachable sidecar produces after gRPC mapping) without
        needing to kill a real server per roll."""
        plan = self.plan
        orig_invoke = remote._invoke

        def invoke(payload):
            if self._fire("rpc_error", plan.rpc_error):
                raise VerifierUnavailableError("injected sidecar RPC fault")
            return orig_invoke(payload)

        remote._invoke = invoke
        self._armed.append((remote, "_invoke"))

    def disarm(self) -> None:
        """Pop every instance shadow this injector installed; the class
        methods are reachable again and the seams are byte-identical to
        never having been armed."""
        for obj, attr in self._armed:
            obj.__dict__.pop(attr, None)
        self._armed.clear()
