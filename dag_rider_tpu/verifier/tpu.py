"""TPU Verifier backend — the north-star device path.

BASELINE.json: "whole-round vertex batches ... vmap'd Ed25519 batch-verify
... one DAG round per device dispatch. Target: >= 50k vertex-signatures
verified/sec on a single v5e chip at n=256, with CPU-vs-TPU commit order
byte-identical."

Work split (SURVEY.md §7 hard part (b) — all *ordering* stays host-side,
the device returns only accept bits):

- host: byte parsing, SHA-512 challenge scalars (k), the s < L
  malleability check, y < p canonicity checks, public-key decompression
  (cached per KeyRegistry at construction), batch padding;
- device: point decompression of R, [s]B from the fixed-base comb table,
  windowed [k]A, the group equation [s]B == R + [k]A — all over the
  int32 limb field (ops/field.py) in one jitted dispatch per DAG round.

Batches are padded to power-of-two buckets so XLA compiles a handful of
program shapes, then results are sliced back. The accept mask is a pure
function of (vertex bytes, registry) — identical to CPUVerifier's, which
makes CPU-vs-TPU commit order byte-identical (tests/test_verifier_tpu.py).
"""

from __future__ import annotations

import functools
import hashlib
import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dag_rider_tpu import config
from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.crypto import ed25519
from dag_rider_tpu.ops import curve, field
from dag_rider_tpu.verifier.base import KeyRegistry, Verifier
from dag_rider_tpu.verifier.prep import PrepEngine

_MIN_BUCKET = 16


def _native_enabled() -> bool:
    """Native challenge hashing on by default; DAGRIDER_NATIVE=0 (or
    false/no/off) disables — the hashlib fallback is always available."""
    return config.env_flag("DAGRIDER_NATIVE")


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


_BIT_POW = (1 << np.arange(field.LIMB_BITS, dtype=np.int32)).astype(np.int32)


def bytes_to_limbs_batch(raw: np.ndarray) -> np.ndarray:
    """uint8[B, 32] little-endian -> int32[B, 22] 12-bit limbs, vectorized.

    Only the low 255 bits are kept (bit 255 is the sign bit in encodings
    that carry one; callers strip it from the byte array first if needed).
    One unpackbits + one matvec — no Python loop over bit positions.
    """
    bits = np.unpackbits(raw, axis=-1, bitorder="little")  # [B, 256]
    pad = field.LIMBS * field.LIMB_BITS - bits.shape[-1]  # 264 - 256
    bits = np.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    grouped = bits.reshape(*raw.shape[:-1], field.LIMBS, field.LIMB_BITS)
    return grouped.astype(np.int32) @ _BIT_POW


_L_BYTES_LE = np.frombuffer(
    ed25519.L.to_bytes(32, "little"), dtype=np.uint8
)
_P_BYTES_LE = np.frombuffer(field.P_INT.to_bytes(32, "little"), dtype=np.uint8)


def _lex_lt(rows: np.ndarray, bound_le: np.ndarray) -> np.ndarray:
    """Batched ``int(row, little) < int(bound, little)`` over uint8[B, 32].

    Big-endian lexicographic compare: the most significant differing byte
    decides; equal rows are not less-than.
    """
    be = rows[:, ::-1]
    bound_be = bound_le[::-1]
    diff = be != bound_be
    first = np.argmax(diff, axis=1)  # 0 when no byte differs
    rows_idx = np.arange(be.shape[0])
    return diff.any(axis=1) & (be[rows_idx, first] < bound_be[first])


def scalar_to_nibbles(x: int) -> np.ndarray:
    """256-bit int -> int32[64] little-endian 4-bit windows."""
    out = np.zeros(64, dtype=np.int32)
    for i in range(64):
        out[i] = (x >> (4 * i)) & 0xF
    return out


def nibbles_batch(raw: np.ndarray) -> np.ndarray:
    """uint8[B, 32] little-endian scalar bytes -> int32[B, 64] nibble
    windows, vectorized (nib[2i] = byte[i] & 0xF, nib[2i+1] = byte[i] >> 4)."""
    out = np.empty((*raw.shape[:-1], 64), dtype=np.int32)
    out[..., 0::2] = raw & 0xF
    out[..., 1::2] = raw >> 4
    return out


@functools.partial(jax.jit, static_argnames=())
def _device_verify(
    s_nibbles: jax.Array,
    k_nibbles: jax.Array,
    a_x: jax.Array,
    a_y: jax.Array,
    a_t: jax.Array,
    a_valid: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    prevalid: jax.Array,
) -> jax.Array:
    one = jnp.broadcast_to(jnp.asarray(field.ONE), a_x.shape)
    a_point = (a_x, a_y, one, a_t)
    return curve.verify_core(
        s_nibbles, k_nibbles, a_point, a_valid, r_y, r_sign, prevalid
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def _device_verify_comb(
    u8: jax.Array,
    i32: jax.Array,
    key_tables: jax.Array,
    b_table: jax.Array,
    impl: str = "jnp",
) -> jax.Array:
    """Unpack the two packed transfer arrays (see _prepare comb mode) and
    run the comb verify core."""
    from dag_rider_tpu.ops import comb

    s_nibbles = u8[:, :64].astype(jnp.int32)
    k_nibbles = u8[:, 64:128].astype(jnp.int32)
    r_sign = u8[:, 128].astype(jnp.int32)
    prevalid = u8[:, 129].astype(bool)
    a_valid = u8[:, 130].astype(bool)
    key_idx = i32[:, 0]
    r_y = i32[:, 1:]
    return comb.comb_verify_core(
        s_nibbles,
        k_nibbles,
        key_idx,
        key_tables,
        b_table,
        a_valid,
        r_y,
        r_sign,
        prevalid,
        impl=impl,
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def _device_verify_comb8(
    u8: jax.Array,
    i32: jax.Array,
    key_tables: jax.Array,
    b_table: jax.Array,
    impl: str = "jnp",
) -> jax.Array:
    """8-bit-window twin of :func:`_device_verify_comb` — u8 carries raw
    scalar BYTES (32+32) instead of nibble digits."""
    from dag_rider_tpu.ops import comb

    s_bytes = u8[:, :32].astype(jnp.int32)
    k_bytes = u8[:, 32:64].astype(jnp.int32)
    r_sign = u8[:, 64].astype(jnp.int32)
    prevalid = u8[:, 65].astype(bool)
    a_valid = u8[:, 66].astype(bool)
    key_idx = i32[:, 0]
    r_y = i32[:, 1:]
    return comb.comb_verify_core8(
        s_bytes,
        k_bytes,
        key_idx,
        key_tables,
        b_table,
        a_valid,
        r_y,
        r_sign,
        prevalid,
        impl=impl,
    )


#: Donated twins of the comb entry points for the AOT-warmed path
#: (TPUVerifier.warmup): the input arrays are freshly device_put, used
#: exactly once, so XLA may alias their buffers for outputs/temps
#: instead of allocating — the comb tables persist across dispatches
#: and stay undonated.
_device_verify_comb_aot = functools.partial(
    jax.jit, static_argnames=("impl",), donate_argnums=(0, 1)
)(_device_verify_comb.__wrapped__)
_device_verify_comb8_aot = functools.partial(
    jax.jit, static_argnames=("impl",), donate_argnums=(0, 1)
)(_device_verify_comb8.__wrapped__)


_B_TABLE_CACHED: Optional[np.ndarray] = None


def _b_table_cached() -> np.ndarray:
    global _B_TABLE_CACHED
    if _B_TABLE_CACHED is None:
        from dag_rider_tpu.ops import comb

        _B_TABLE_CACHED = comb.base_table_xyzt()
    return _B_TABLE_CACHED


# Keyed by the default backend's platform name: ~34 MB of device memory
# per entry, so a backend switch (cpu tests after a tpu run, or vice
# versa) must not serve arrays resident on the wrong device (ADVICE r3).
_B_TABLE8_DEV: dict = {}


def _b_table8_dev():
    """8-bit base-point table (registry-independent, device-resident) —
    built once per process *per backend* through the same device builder
    on a one-key "registry" holding B itself."""
    backend = jax.default_backend()
    if backend not in _B_TABLE8_DEV:
        from dag_rider_tpu.crypto import ed25519
        from dag_rider_tpu.ops import comb, field

        bx, by, _, bt = ed25519.B
        built = comb.build_key_tables8(
            jnp.asarray(field.to_limbs(bx)[None]),
            jnp.asarray(field.to_limbs(by)[None]),
            jnp.asarray(field.to_limbs(bt)[None]),
        )[0]
        _B_TABLE8_DEV[backend] = jax.jit(comb.pad_rows)(built)
    return _B_TABLE8_DEV[backend]


def _comb_impl(size: int) -> str:
    """Pallas kernels on a real TPU backend for lane-aligned batches;
    portable jnp everywhere else. Both are bit-identical — this is purely
    a speed selection (PROFILE.md round 3: the jnp tree is memory-bound
    on HLO temps; the kernels do one HBM pass per operand). The axon
    PJRT relay has registered the chip as platform "tpu" or "axon"
    depending on plugin version — accept both."""
    if not config.env_flag("DAGRIDER_PALLAS_GROUP"):
        return "jnp"
    if size >= 128 and jax.default_backend() in ("tpu", "axon"):
        return "pallas"
    return "jnp"


class PreppedBatch(NamedTuple):
    """Opaque handle between the prep_batch/dispatch_prepped halves of a
    dispatch: the device-ready transfer arrays (normally views of a
    staging-ring slot), the padded size, the real row count, and the
    prep wall seconds (booked at dispatch time, on the dispatching
    thread)."""

    args: tuple
    size: int
    count: int
    prep_s: float


class TPUVerifier(Verifier):
    """Batched Ed25519 verification on the accelerator.

    Also correct on CPU backends (the tests force JAX_PLATFORMS=cpu); the
    *backend* is wherever jax.default_backend() points, which is the TPU
    under the benchmark driver.
    """

    def __init__(self, registry: KeyRegistry, comb: Optional[bool] = None):
        """``comb=True`` (the default, DAGRIDER_COMB=0 to flip) uses the
        fixed-key comb path (ops/comb.py): per-key tables built on device
        once, ~2.5x fewer field muls per signature, identical accept
        masks. ``comb=False`` is the original windowed path — kept as the
        differential oracle and for registries too large for table HBM
        (~360 KB/key)."""
        if comb is None:
            comb = config.env_flag("DAGRIDER_COMB")
        self._comb = comb
        # Window width. 8-bit tables halve the gather rows and tree
        # levels but cost 16x the HBM (1.07 GB padded at n=256) and
        # measured NO faster on the relay (56.6k vs 62.0k sigs/s at 16k
        # merged — the bigger table's gather locality eats the row-count
        # saving; PROFILE.md round 3), so 4-bit is the default and 8-bit
        # stays as a correct, tested variant (DAGRIDER_COMB_BITS=8).
        bits_env = config.env_choice("DAGRIDER_COMB_BITS")
        self._comb_bits = int(bits_env) if bits_env else 4
        self._key_tables = None  # device tables, built lazily
        # AOT-compiled executables keyed (size, impl, bits) — see warmup()
        self._aot: dict = {}
        # reusable host staging rings per padded size — see _stage()
        self._staging: dict = {}
        self._staging_idx: dict = {}
        # parallel host-prep engine (verifier/prep.py), built lazily by
        # _prep() so a prep_workers override set after construction
        # still takes effect on first use
        self._prep_engine: Optional[PrepEngine] = None
        from dag_rider_tpu.verifier.pipeline import default_depth

        #: in-flight window depth for the chunk-streaming verify_rounds
        #: path (and the default for wrapping VerifierPipelines)
        self.pipeline_depth = default_depth()
        #: cumulative seconds spent in warmup()'s AOT lower+compile
        self.warmup_compile_s = 0.0
        self.registry = registry
        n = registry.n
        self._a_x = np.zeros((n, field.LIMBS), dtype=np.int32)
        self._a_y = np.zeros((n, field.LIMBS), dtype=np.int32)
        self._a_t = np.zeros((n, field.LIMBS), dtype=np.int32)
        self._a_valid = np.zeros(n, dtype=bool)
        for i, pk in enumerate(registry.public_keys):
            pt = ed25519.point_decompress(pk) if len(pk) == 32 else None
            if pt is None:
                continue
            x, y, _, t = pt  # Z == 1 from decompress
            self._a_x[i] = field.to_limbs(x)
            self._a_y[i] = field.to_limbs(y)
            self._a_t[i] = field.to_limbs(t)
            self._a_valid[i] = True

    # -- host-side batch preparation ------------------------------------

    def _prep_block(
        self,
        vertices: Sequence[Vertex],
        lo: int,
        hi: int,
        comb: bool,
        dest: Tuple[np.ndarray, ...],
    ) -> None:
        # Vectorized host prep (round-2 VERDICT weak #3: the per-vertex
        # Python loop must clear ~50k iterations/s at the north-star rate).
        # Structural checks, the s < L malleability compare and the
        # r_y < p canonicity compare are batched numpy; only the SHA-512
        # challenge hashing walks the batch (variable-length messages).
        #
        # Operates on rows [lo, hi) of one padded dispatch and writes the
        # finished rows straight into ``dest``'s block offsets. Every
        # computation here is ROW-LOCAL — parsing, the lexicographic
        # bound compares, the per-row challenge hash, limb packing — so a
        # row-block partition of [0, size) is byte-identical to one
        # full-range call: the invariant the parallel prep engine
        # (verifier/prep.py) rides. Rows >= len(vertices) are padding:
        # structurally invalid and zero-filled, exactly as serial prep
        # pads them. The numpy kernels and the native challenge_batch
        # release the GIL, so concurrent blocks genuinely overlap.
        rows = hi - lo
        sig_raw = np.zeros((rows, 64), dtype=np.uint8)
        pk_raw = np.zeros((rows, 32), dtype=np.uint8)
        k_raw = np.zeros((rows, 32), dtype=np.uint8)
        src = np.zeros(rows, dtype=np.int64)
        structural = np.zeros(rows, dtype=bool)
        msgs: List[bytes] = []
        for j in range(lo, min(hi, len(vertices))):
            v = vertices[j]
            jl = j - lo
            pk = self.registry.key_of(v.source)
            sig = v.signature
            if pk is None or sig is None or len(sig) != 64 or len(pk) != 32:
                msgs.append(b"")
                continue
            sig_raw[jl] = np.frombuffer(sig, dtype=np.uint8)
            pk_raw[jl] = np.frombuffer(pk, dtype=np.uint8)
            src[jl] = v.source
            structural[jl] = True
            msgs.append(v.signing_bytes())
        s_raw = sig_raw[:, 32:]
        r_raw = sig_raw[:, :32].copy()
        # s < L, batched: big-endian lexicographic compare against L.
        s_lt_l = _lex_lt(s_raw, _L_BYTES_LE)
        # r_y < p, batched (sign bit masked off first).
        r_sign = (r_raw[:, 31] >> 7).astype(np.int32)
        r_raw[:, 31] &= 0x7F
        r_lt_p = _lex_lt(r_raw, _P_BYTES_LE)
        prevalid = structural & s_lt_l & r_lt_p
        # k = SHA-512(R || A || M) mod L per valid row — one native C++
        # batch call when the library is available (utils/native.py;
        # differential-tested against the hashlib path, which remains the
        # fallback and oracle). Both are per-row pure functions, so a
        # per-block call hashes the same bytes a whole-batch call would.
        idx = np.flatnonzero(prevalid)
        if len(idx):
            k_rows = None
            if _native_enabled():
                from dag_rider_tpu.utils import native

                k_rows = native.challenge_batch(
                    sig_raw[idx, :32], pk_raw[idx], [msgs[j] for j in idx]
                )
            if k_rows is not None:
                k_raw[idx] = k_rows
            else:
                for j in idx:
                    k = (
                        int.from_bytes(
                            hashlib.sha512(
                                sig_raw[j, :32].tobytes()
                                + pk_raw[j].tobytes()
                                + msgs[j]
                            ).digest(),
                            "little",
                        )
                        % ed25519.L
                    )
                    k_raw[j] = np.frombuffer(
                        k.to_bytes(32, "little"), dtype=np.uint8
                    )
        r_y_limbs = bytes_to_limbs_batch(r_raw)
        if comb:
            u8, i32 = dest
            u8 = u8[lo:hi]
            i32 = i32[lo:hi]
            if self._comb_bits == 8:
                u8[:, :32] = np.where(prevalid[:, None], s_raw, 0)
                u8[:, 32:64] = k_raw
                u8[:, 64] = r_sign
                u8[:, 65] = prevalid
                u8[:, 66] = self._a_valid[src] & prevalid
            else:
                u8[:, :64] = nibbles_batch(
                    np.where(prevalid[:, None], s_raw, 0)
                )
                u8[:, 64:128] = nibbles_batch(k_raw)
                u8[:, 128] = r_sign
                u8[:, 129] = prevalid
                u8[:, 130] = self._a_valid[src] & prevalid
            i32[:, 0] = src
            i32[:, 1:] = r_y_limbs
            return
        s_nib, k_nib, a_x, a_y, a_t, valid, r_y, r_sg, pv = dest
        s_nib[lo:hi] = nibbles_batch(np.where(prevalid[:, None], s_raw, 0))
        k_nib[lo:hi] = nibbles_batch(k_raw)
        a_x[lo:hi] = self._a_x[src]
        a_y[lo:hi] = self._a_y[src]
        a_t[lo:hi] = self._a_t[src]
        valid[lo:hi] = self._a_valid[src] & prevalid
        r_y[lo:hi] = r_y_limbs
        r_sg[lo:hi] = r_sign
        pv[lo:hi] = prevalid

    def _prepare(
        self,
        vertices: Sequence[Vertex],
        size: int,
        comb: bool = False,
        out: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> Tuple[np.ndarray, ...]:
        """Host prep for one padded dispatch of ``size`` rows.

        comb mode packs two transfers instead of seven: the relay's
        per-transfer latency is a large share of the fixed dispatch cost
        (PROFILE.md round 3). u8 carries digits + flag bits; i32 carries
        key index + R.y limbs. 8-bit windows ship the raw scalar bytes;
        4-bit ships nibble digits. Every row and column of the output is
        fully overwritten, so the caller may hand in a reused staging
        pair (out=) — see _stage() for the aliasing discipline.

        The row fill itself runs through the prep engine
        (verifier/prep.py): one block when ``prep_workers`` is 1 or the
        dispatch is small (structurally the serial path), otherwise up
        to ``prep_workers`` row blocks filled concurrently, each writing
        its own offsets of the SAME output arrays. The partition is
        invisible in the bytes (see _prep_block)."""
        if comb:
            if out is not None:
                dest: Tuple[np.ndarray, ...] = out
            else:
                cols = 67 if self._comb_bits == 8 else 131
                dest = (
                    np.empty((size, cols), dtype=np.uint8),
                    np.empty((size, 23), dtype=np.int32),
                )
        else:
            dest = (
                np.empty((size, 64), dtype=np.int32),
                np.empty((size, 64), dtype=np.int32),
                np.empty((size, field.LIMBS), dtype=np.int32),
                np.empty((size, field.LIMBS), dtype=np.int32),
                np.empty((size, field.LIMBS), dtype=np.int32),
                np.empty(size, dtype=bool),
                np.empty((size, field.LIMBS), dtype=np.int32),
                np.empty(size, dtype=np.int32),
                np.empty(size, dtype=bool),
            )
        eng = self._prep()
        eng.run_blocks(
            lambda lo, hi: self._prep_block(vertices, lo, hi, comb, dest),
            eng.plan(size),
        )
        return dest

    def _comb_tables(self):
        """Device comb tables in the padded [rows, 128] gather layout
        (built once, first dispatch) + the base-point table."""
        if self._key_tables is None:
            from dag_rider_tpu.ops import comb

            if self._comb_bits == 8:
                built = comb.build_key_tables8(
                    jnp.asarray(self._a_x),
                    jnp.asarray(self._a_y),
                    jnp.asarray(self._a_t),
                )
                self._b_table_dev = _b_table8_dev()
            else:
                built = comb.build_key_tables(
                    jnp.asarray(self._a_x),
                    jnp.asarray(self._a_y),
                    jnp.asarray(self._a_t),
                )
                self._b_table_dev = jax.jit(comb.pad_rows)(
                    jnp.asarray(_b_table_cached())
                )
            self._key_tables = jax.jit(comb.pad_rows)(built)
        return self._key_tables, self._b_table_dev

    def _stage(self, size: int, cols: int) -> Tuple[np.ndarray, np.ndarray]:
        """Reusable (u8, i32) host staging pair for one dispatch.

        A small ring instead of a fresh np.empty per dispatch: the CPU
        PJRT client may alias a host array zero-copy into the program, so
        a slot must not be rewritten while a dispatch that shipped it can
        still be executing. The ring holds pipeline_depth + 2 slots and
        every supported window keeps at most pipeline_depth dispatches in
        flight, so a slot's previous dispatch has always resolved before
        the slot comes around again."""
        ring = self._staging.get(size)
        if (
            ring is None
            or ring[0][0].shape[1] != cols
            or len(ring) < self.pipeline_depth + 2
        ):
            ring = [
                (
                    np.empty((size, cols), dtype=np.uint8),
                    np.empty((size, 23), dtype=np.int32),
                )
                for _ in range(self.pipeline_depth + 2)
            ]
            self._staging[size] = ring
            self._staging_idx[size] = 0
        i = self._staging_idx[size]
        self._staging_idx[size] = (i + 1) % len(ring)
        return ring[i]

    def reset_staging(self) -> None:
        """Re-arm the staging ring after a poisoned window (round-9
        containment seam). The cursor no longer matches the in-flight
        count once a dispatch or resolve has failed, so the only way to
        keep the aliasing discipline is FRESH slots: the old ring list
        is dropped, not rewritten — any orphan dispatch still executing
        keeps its zero-copy views of the old arrays alive, and the next
        _stage() builds a new ring that cannot alias them."""
        self._staging.clear()
        self._staging_idx.clear()

    # -- dispatch seam hooks ---------------------------------------------
    # dispatch_batch/warmup route every placement-sensitive decision
    # through these overridables, so ShardedTPUVerifier (parallel/
    # sharded_verifier.py) inherits the async/AOT/staging machinery —
    # padding, chunk boundaries, FIFO resolve — unchanged, and only the
    # placement (mesh-rounded buckets, NamedSharding device_put, the
    # shard_map program, mesh-keyed AOT entries) differs. The mask stays
    # a pure function of (vertex bytes, registry) under every override.

    def _round_bucket(self, b: int) -> int:
        """Final padded-size adjustment (mesh subclasses round up to a
        multiple of the batch axis; single-chip is the identity)."""
        return int(b)

    def _select_impl(self, size: int) -> str:
        """Comb tree engine for a padded dispatch of ``size`` rows."""
        return _comb_impl(size)

    def _aot_key(self, size: int, impl: str) -> tuple:
        """Cache key for the AOT-compiled program at this shape."""
        return (size, impl, self._comb_bits)

    def _put(self, arr: np.ndarray) -> jax.Array:
        """Host staging array -> committed device input."""
        return jax.device_put(arr)

    def _comb_tables_dev(self):
        """(key_tables, b_table) placed where the dispatch needs them."""
        return self._comb_tables()

    def _comb_fn(self, impl: str):
        """The lazily-jitted comb entry point (non-AOT dispatches)."""
        fn = (
            _device_verify_comb8
            if self._comb_bits == 8
            else _device_verify_comb
        )
        return functools.partial(fn, impl=impl)

    def _windowed_dispatch(self, args) -> jax.Array:
        """The comb=False oracle path's device call."""
        return _device_verify(*(jnp.asarray(a) for a in args))

    def _aot_lower(self, size: int, impl: str, tables, b_tab):
        """lower+compile the comb program at the exact dispatch shape."""
        # the CPU client cannot alias these buffers (XLA warns and
        # ignores the donation) — donate only where it actually lands
        donate = jax.default_backend() != "cpu"
        if self._comb_bits == 8:
            cols = 67
            fn = _device_verify_comb8_aot if donate else _device_verify_comb8
        else:
            cols = 131
            fn = _device_verify_comb_aot if donate else _device_verify_comb
        return fn.lower(
            jax.ShapeDtypeStruct((size, cols), jnp.uint8),
            jax.ShapeDtypeStruct((size, 23), jnp.int32),
            tables,
            b_tab,
            impl=impl,
        ).compile()

    def _note_dispatch(self, size: int, count: int) -> None:
        """Per-dispatch gauge hook (mesh subclasses book shard balance)."""

    def warmup(self, bucket: Optional[int] = None) -> float:
        """AOT-compile the fixed-bucket device program:
        ``jit(...).lower(...).compile()`` at the exact (bucket, impl,
        window-bits) shape, stored for dispatch_batch to call directly.

        Run at construction time (VerifierPipeline), node startup, and
        VerifierSidecarServer startup so the first consensus round never
        eats the ~35 s XLA compile; with the repo-local persistent cache
        enabled the lower+compile is a disk hit after the first ever run.
        Returns the seconds spent (cumulative in ``warmup_compile_s``).
        The windowed (comb=False) oracle path keeps its lazy jit cache —
        it is never on the hot path."""
        if not self._comb:
            return 0.0
        size = self._round_bucket(
            int(bucket or self.fixed_bucket or _MIN_BUCKET)
        )
        impl = self._select_impl(size)
        key = self._aot_key(size, impl)
        if key in self._aot:
            return 0.0
        t0 = time.perf_counter()
        tables, b_tab = self._comb_tables_dev()
        self._aot[key] = self._aot_lower(size, impl, tables, b_tab)
        dt = time.perf_counter() - t0
        self.warmup_compile_s += dt
        return dt

    #: host-prep / device-dispatch seconds of the most recent
    #: verify_batch call — the host/device split the bench reports.
    last_prepare_s: float = 0.0
    last_dispatch_s: float = 0.0

    #: Cumulative verifier-seam accounting across a whole run: how much
    #: wall time went to host prep vs device dispatch+sync, over how
    #: many dispatches and signatures. The bench's sim rungs report
    #: these so an in-loop sigs/s shortfall is ATTRIBUTABLE (fixed
    #: per-dispatch relay cost vs host consensus work) instead of a
    #: bare number — VERDICT r04 #2's "measured breakdown".
    total_prepare_s: float = 0.0
    total_dispatch_s: float = 0.0
    total_dispatches: int = 0
    total_sigs_dispatched: int = 0

    #: When set, every dispatch pads to exactly this bucket (and
    #: verify_rounds chunks larger merges into it) — ONE compiled program
    #: shape for a whole consensus run, instead of a power-of-two ladder
    #: of ~35 s XLA compiles as burst sizes wander (bench ladder sim64).
    fixed_bucket: Optional[int] = None

    #: Explicit A/B switch for the async seam. False forces every
    #: consumer (Simulation.run, the chunk-streaming verify_rounds, a
    #: wrapping VerifierPipeline) onto the synchronous depth-1
    #: dispatch-then-resolve shape — the bench's pipeline-off rung.
    #: Replaces the round-5 instance-attribute None shadow of
    #: dispatch_batch/resolve_batch (and the _unshadowed MRO walk that
    #: let verify_batch reach past it).
    pipeline_enabled: bool = True

    #: Next-tier verifier for chunks quarantined out of a poisoned
    #: window. Wired by ResilientVerifier (verifier/resilient.py) so a
    #: chunk whose dispatch/resolve failed is re-verified once on the
    #: ladder's next tier; None = one serial retry on this verifier,
    #: then fail closed for that chunk.
    quarantine_verifier: Optional[Verifier] = None

    #: Fault-containment gauges (round 9): windows poisoned by a
    #: dispatch/resolve/prep exception, chunks re-verified in
    #: quarantine, and quarantine retries that failed too (those chunks
    #: read all-False — fail closed).
    poisoned_windows: int = 0
    quarantined_chunks: int = 0
    quarantine_rejected: int = 0

    #: Requested worker count for the parallel host-prep engine
    #: (verifier/prep.py). None defers to DAGRIDER_PREP_WORKERS (default
    #: 1 = serial). Assigning a new value rebuilds the engine on the
    #: next prep — only reassign between runs, never while preps are in
    #: flight. node.py's "verify_prep_workers" config lands here.
    prep_workers: Optional[int] = None

    def _prep(self) -> PrepEngine:
        """The verifier's prep engine, (re)built lazily so a
        ``prep_workers`` override picked up between runs takes effect —
        the bench's 1-vs-N A/B flips it on one verifier without losing
        the compiled programs or comb tables."""
        want = (
            int(self.prep_workers) if self.prep_workers is not None else None
        )
        eng = self._prep_engine
        if eng is None or (want is not None and eng.workers != want):
            if eng is not None:
                eng.close()
            eng = self._prep_engine = PrepEngine(want)
        return eng

    def prep_stats(self) -> dict:
        """Gauges of the parallel host-prep engine — surfaced through
        pipeline stats(), the bench's verifier_breakdown and the
        per-process metrics snapshot. ``parallel_fraction`` is the
        no-silent-fallback gauge: rows that actually took the row-block
        parallel path over all rows prepped."""
        eng = self._prep()
        return {
            "workers": eng.workers,
            "last_blocks": eng.last_blocks,
            "parallel_fraction": eng.parallel_fraction(),
            "rows_total": eng.rows_total,
            "rows_parallel": eng.rows_parallel,
            "serial_retries": eng.serial_retries,
        }

    def prep_batch(self, vertices: Sequence[Vertex]) -> "PreppedBatch":
        """Host half of :meth:`dispatch_batch`: bucket selection,
        staging-slot claim, and the (possibly row-parallel) _prepare.
        Returns a :class:`PreppedBatch` handle for
        :meth:`dispatch_prepped`.

        Safe to run on the prep engine's seam thread
        (:meth:`prep_batch_async`): the only verifier state it advances
        is the staging-ring cursor, and the seam executor serializes
        prep calls FIFO, so ring slots are claimed strictly in chunk
        order. Timing is carried in the handle and booked by
        dispatch_prepped on the dispatching thread."""
        if self.fixed_bucket and len(vertices) <= self.fixed_bucket:
            size = self._round_bucket(int(self.fixed_bucket))
        else:
            size = self._round_bucket(_bucket(len(vertices)))
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("verify_batch.prepare"):
            out = (
                self._stage(size, 67 if self._comb_bits == 8 else 131)
                if self._comb
                else None
            )
            args = self._prepare(vertices, size, comb=self._comb, out=out)
        return PreppedBatch(
            args, size, len(vertices), time.perf_counter() - t0
        )

    def prep_batch_async(self, vertices: Sequence[Vertex]):
        """:meth:`prep_batch` queued on the engine's dedicated FIFO seam
        thread; returns a Future of the PreppedBatch. The pipeline
        callers use this to run chunk k+2's prep concurrently with chunk
        k+1's prep and chunk k's device execution. Callers keep at most
        2 preps outstanding and submit a new one only after the window
        has drained below depth — with the staging ring's
        pipeline_depth + 2 slots that guarantees a slot's previous
        dispatch has resolved before the slot is claimed again."""
        return self._prep().submit(self.prep_batch, vertices)

    def dispatch_prepped(self, prepped: "PreppedBatch"):
        """Device half of :meth:`dispatch_batch`: ship an already-prepped
        batch, NO sync. Books the prep accounting carried in the handle
        (so counters mutate only on the dispatching thread even when
        prep ran on the seam thread)."""
        args, size, count, prep_s = prepped
        self.last_prepare_s = prep_s
        self.total_prepare_s += prep_s
        self.total_dispatches += 1
        self.total_sigs_dispatched += count
        self._note_dispatch(size, count)
        with jax.profiler.TraceAnnotation("verify_batch.dispatch"):
            if self._comb:
                u8, i32 = args
                tables, b_tab = self._comb_tables_dev()
                impl = self._select_impl(size)
                exe = self._aot.get(self._aot_key(size, impl))
                if exe is not None:
                    # AOT path (warmup()): committed single-use device
                    # buffers into the donated executable — no jit-cache
                    # lookup, and XLA reuses the input allocations
                    mask = exe(self._put(u8), self._put(i32), tables, b_tab)
                else:
                    mask = self._comb_fn(impl)(
                        self._put(u8), self._put(i32), tables, b_tab
                    )
            else:
                mask = self._windowed_dispatch(args)
        return mask, count

    # -- fault containment (round 9) --------------------------------------

    def _quarantine(self, vertices: Sequence[Vertex]) -> List[bool]:
        """Re-verify a chunk out of a poisoned window exactly once: on
        the ladder's next tier when one is wired (quarantine_verifier),
        else a fresh serial dispatch on this verifier. A second failure
        rejects the chunk — fail closed, never fail open."""
        self.quarantined_chunks += 1
        vs = list(vertices)
        try:
            if self.quarantine_verifier is not None:
                return self.quarantine_verifier.verify_batch(vs)
            return self._resolve_timed(self.dispatch_batch(vs))
        except Exception:  # noqa: BLE001 — second failure fail-closes
            self.quarantine_rejected += 1
            return [False] * len(vs)

    def _contain_stream(
        self, inflight, chunk: Sequence[Vertex], failed_first: bool
    ) -> List[bool]:
        """Contain a fault in the chunk-streaming window: salvage every
        in-flight entry (resolve it; a second fault quarantines that
        chunk too), re-arm the staging ring, then quarantine the failing
        chunk. Returns the masks in FIFO chunk order — ``failed_first``
        is True for a resolve fault (the failed chunk was the oldest,
        already popped) and False for a prep/dispatch fault (the failed
        chunk never entered the window, so salvaged chunks come first).
        """
        self.poisoned_windows += 1
        salvaged = []  # (mask-or-None, chunk) in FIFO order
        while inflight:
            h, ch = inflight.popleft()
            try:
                salvaged.append((self._resolve_timed(h), ch))
            except Exception:  # noqa: BLE001 — quarantined after reset
                salvaged.append((None, ch))
        self.reset_staging()
        out: List[bool] = []
        if failed_first:
            out.extend(self._quarantine(chunk))
        for m, ch in salvaged:
            out.extend(m if m is not None else self._quarantine(ch))
        if not failed_first:
            out.extend(self._quarantine(chunk))
        return out

    def _resolve_stream(self, inflight) -> List[bool]:
        """Resolve the oldest in-flight chunk, containing a resolve
        fault (the rest of the window is salvaged, the ring re-armed,
        and the failing chunk quarantined)."""
        h, ch = inflight.popleft()
        try:
            return self._resolve_timed(h)
        except Exception:  # noqa: BLE001 — contained, not propagated
            return self._contain_stream(inflight, ch, failed_first=True)

    def dispatch_batch(self, vertices: Sequence[Vertex]):
        """Asynchronous half of verify: host prep + device dispatch, NO
        sync. Returns an opaque (device_mask, count) pending handle for
        :meth:`resolve_batch`. Lets a caller overlap round k+1's host prep
        with round k's device execution — the steady-state pipeline shape
        of burst delivery (one dispatch per DAG round). Composed from the
        prep_batch/dispatch_prepped halves, which pipeline callers drive
        separately to overlap prep across chunks."""
        return self.dispatch_prepped(self.prep_batch(vertices))

    def verify_rounds(
        self, rounds: Sequence[Sequence[Vertex]]
    ) -> List[List[bool]]:
        """Verify several DAG rounds in ONE device dispatch.

        The per-dispatch cost has a large fixed component (host-device
        transfer latency dominates on relayed backends — see PROFILE.md),
        amortized by merging consecutive rounds' batches into a single
        padded dispatch and splitting the mask after. Used by the bench's
        merged steady-state phase and available to catch-up sync / burst
        consumers.

        Merges larger than the fixed bucket STREAM their chunks through
        the async seam with a depth-K in-flight window (K =
        pipeline_depth; 1 when pipeline_enabled is off): chunk k+1's
        host prep overlaps chunk k's device execution instead of the old
        serial dispatch-then-resolve loop. With the window open, chunk
        prep additionally runs ahead on the prep engine's seam thread
        (prep_batch_async) — chunk k+2's prep overlaps chunk k+1's prep
        and chunk k's execution. Chunk boundaries and FIFO resolve order
        are unchanged, so the mask stays byte-identical.

        A prep/dispatch/resolve exception is CONTAINED, not propagated
        (round 9): the window is salvaged, the staging ring re-armed,
        and the failing chunk quarantined (_contain_stream) — the merge
        always returns a full mask, wedging nothing upstream.
        """
        lens = [len(r) for r in rounds]
        flat = [v for r in rounds for v in r]
        if not flat:
            return [[] for _ in rounds]
        cap = self.fixed_bucket
        if cap and len(flat) > cap:
            from collections import deque

            depth = self.pipeline_depth if self.pipeline_enabled else 1
            chunks = [flat[i : i + cap] for i in range(0, len(flat), cap)]
            inflight: deque = deque()  # (pending handle, chunk) FIFO
            mask = []
            if depth > 1 and len(chunks) > 1:
                # Prep-ahead ordering discipline: at most 2 prep futures
                # outstanding, and a new prep is queued only AFTER the
                # window has been drained below depth and the current
                # chunk dispatched — so when prep(j) claims ring slot
                # (j mod (depth+2)), the slot's previous claimant
                # (chunk j-depth-2) has already resolved. See _stage().
                preps: deque = deque()
                nxt = 0
                while nxt < len(chunks) and len(preps) < 2:
                    preps.append(
                        (self.prep_batch_async(chunks[nxt]), chunks[nxt])
                    )
                    nxt += 1
                while preps:
                    fut, chunk = preps.popleft()
                    try:
                        prepped = fut.result()
                    except Exception:  # noqa: BLE001 — prep fault
                        mask.extend(
                            self._contain_stream(
                                inflight, chunk, failed_first=False
                            )
                        )
                        prepped = None
                    if prepped is not None:
                        while len(inflight) >= depth:
                            mask.extend(self._resolve_stream(inflight))
                        try:
                            inflight.append(
                                (self.dispatch_prepped(prepped), chunk)
                            )
                        except Exception:  # noqa: BLE001 — dispatch fault
                            mask.extend(
                                self._contain_stream(
                                    inflight, chunk, failed_first=False
                                )
                            )
                    if nxt < len(chunks):
                        preps.append(
                            (self.prep_batch_async(chunks[nxt]), chunks[nxt])
                        )
                        nxt += 1
            else:
                for chunk in chunks:
                    while len(inflight) >= depth:
                        mask.extend(self._resolve_stream(inflight))
                    try:
                        inflight.append((self.dispatch_batch(chunk), chunk))
                    except Exception:  # noqa: BLE001 — prep/dispatch fault
                        mask.extend(
                            self._contain_stream(
                                inflight, chunk, failed_first=False
                            )
                        )
            while inflight:
                mask.extend(self._resolve_stream(inflight))
        else:
            mask = self.verify_batch(flat)
        out, pos = [], 0
        for ln in lens:
            out.append(mask[pos : pos + ln])
            pos += ln
        return out

    @staticmethod
    def resolve_batch(pending) -> List[bool]:
        """Blocking half: device mask -> per-vertex host bools."""
        mask, count = pending
        return [bool(m) for m in np.asarray(mask)[:count]]

    def _resolve_timed(self, pending) -> List[bool]:
        """resolve_batch plus the device-seconds accounting the seam
        breakdown expects (verify_batch and the chunk-streaming
        verify_rounds both resolve through here)."""
        t0 = time.perf_counter()
        out = self.resolve_batch(pending)
        self.last_dispatch_s = time.perf_counter() - t0
        self.total_dispatch_s += self.last_dispatch_s
        return out

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        # Trace annotations are free when no profiler is attached; under
        # jax.profiler.trace() (bench.py DAGRIDER_PROFILE_DIR / SURVEY §5)
        # they label the host-prep vs device-dispatch split per round.
        if not vertices:
            return []
        return self._resolve_timed(self.dispatch_batch(vertices))
