"""The fused per-round device step — our "training step" analog.

One DAG round per device dispatch (the north-star shape): verify the
round's vertex-signature batch (data-parallel over the mesh's batch axis)
and evaluate the wave-commit quorum kernels (small [n, n] boolean matmuls,
replicated) in a single jitted program. The host state machine consumes
(accept_mask, commit, votes) and makes all ordering decisions
(SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dag_rider_tpu.ops import curve, dag_kernels, field


def make_round_step(mesh: Mesh, *, quorum: int):
    """Build the jitted sharded round step for a given mesh.

    Inputs (leading dim B sharded over "batch"; DAG tensors replicated):
      s_nibbles[B,64] k_nibbles[B,64] a_x/a_y/a_t[B,22] a_valid[B]
      r_y[B,22] r_sign[B] prevalid[B]  — the verify batch;
      strong_wave[3,n,n] exists_r4[n] leader[]  — the wave-commit inputs.

    Returns (accept_mask[B], commit[], votes[n]).
    """
    batch = NamedSharding(mesh, PartitionSpec("batch"))
    repl = NamedSharding(mesh, PartitionSpec())

    @functools.partial(
        jax.jit,
        in_shardings=(batch,) * 9 + (repl,) * 3,
        out_shardings=(batch, repl, repl),
    )
    def round_step(
        s_nibbles,
        k_nibbles,
        a_x,
        a_y,
        a_t,
        a_valid,
        r_y,
        r_sign,
        prevalid,
        strong_wave,
        exists_r4,
        leader,
    ):
        one = jnp.broadcast_to(jnp.asarray(field.ONE), a_x.shape)
        accept = curve.verify_core(
            s_nibbles,
            k_nibbles,
            (a_x, a_y, one, a_t),
            a_valid,
            r_y,
            r_sign,
            prevalid,
        )
        commit, votes = dag_kernels.wave_commit_votes(
            strong_wave, exists_r4, leader, quorum=quorum
        )
        return accept, commit, votes

    return round_step
