"""Device mesh helpers.

The reference's only comm stack is an in-memory channel broker
(``process/transport.go``) — host-side consensus traffic stays host-side
here too (gRPC / in-memory Transport). What *does* scale across chips is
the crypto batch work (SURVEY.md §2b): verify batches shard over a 1-D
"batch" mesh (data-parallel over a round's <= n vertices), and large-n MSM
work shards the same way. Collectives ride ICI via XLA — there is no
hand-written NCCL/MPI analog to port.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Tuple[str, ...] = ("batch",),
) -> Mesh:
    """A device mesh over the first ``n_devices`` (default: all).

    shape defaults to 1-D ``(n_devices,)`` — verify batches are purely
    data-parallel, so one axis is the common case; pass e.g. shape=(4, 2),
    axis_names=("batch", "shard") to split MSM work within a batch row.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = devs[:n_devices]
    if shape is None:
        shape = (n_devices,)
    import numpy as np

    return Mesh(np.asarray(devs).reshape(shape), axis_names)


def batch_sharding(mesh: Mesh, axis: str = "batch") -> NamedSharding:
    """Shard a batch-leading array over the mesh's batch axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
