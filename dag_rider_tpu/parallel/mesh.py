"""Device mesh helpers.

The reference's only comm stack is an in-memory channel broker
(``process/transport.go``) — host-side consensus traffic stays host-side
here too (gRPC / in-memory Transport). What *does* scale across chips is
the crypto batch work (SURVEY.md §2b): verify batches shard over a 1-D
"batch" mesh (data-parallel over a round's <= n vertices), and large-n MSM
work shards the same way. Collectives ride ICI via XLA — there is no
hand-written NCCL/MPI analog to port.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dag_rider_tpu import config


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` — the same
    static varying-axis check under its old name. Every shard_map in this
    package (sharded comb verify, sharded MSM) goes through here so the
    mesh paths run on both the chip host's jax and the 0.4.x CI/test
    containers."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Tuple[str, ...] = ("batch",),
) -> Mesh:
    """A device mesh over the first ``n_devices`` (default: all).

    shape defaults to 1-D ``(n_devices,)`` — verify batches are purely
    data-parallel, so one axis is the common case; pass e.g. shape=(4, 2),
    axis_names=("batch", "shard") to split MSM work within a batch row.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = devs[:n_devices]
    if shape is None:
        shape = (n_devices,)
    import numpy as np

    return Mesh(np.asarray(devs).reshape(shape), axis_names)


def mesh_from_env(default_devices: int = 8) -> Mesh:
    """The 1-D batch mesh for ``verifier: "sharded"`` deployments.

    ``DAGRIDER_MESH`` gives the batch-axis device count; unset means
    every visible device. On a CPU backend that has not been initialized
    yet (laptops, CI), the XLA host-device-count flag is injected first
    so the request still yields a real multi-device mesh — the virtual
    8-device fallback the tests run on. If jax already initialized with
    fewer devices than requested, the mesh clamps with a warning rather
    than failing the node."""
    want = config.env_opt_int("DAGRIDER_MESH")
    platform = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    flags = os.environ.get("XLA_FLAGS", "")
    if (
        platform.lower() == "cpu"
        and "xla_force_host_platform_device_count" not in flags
    ):
        # Before the first jax.devices() call this flag still takes
        # effect; after backend init it is ignored and the clamp below
        # applies. Only the CPU platform honors it at all.
        virtual = want if want is not None else default_devices
        if virtual > 1:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={virtual}"
            ).strip()
    have = jax.device_count()
    if want is None:
        want = have
    if want > have:
        warnings.warn(
            f"DAGRIDER_MESH={want} but only {have} device(s) visible; "
            f"clamping the mesh to {have}",
            stacklevel=2,
        )
        want = have
    return make_mesh(want)


def batch_sharding(mesh: Mesh, axis: str = "batch") -> NamedSharding:
    """Shard a batch-leading array over the mesh's batch axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
