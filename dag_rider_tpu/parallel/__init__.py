"""Multi-chip parallelism: device meshes, sharded verification, and the
sharded round step (SURVEY.md §2b/§2c build mapping)."""

from dag_rider_tpu.parallel.mesh import make_mesh, batch_sharding
from dag_rider_tpu.parallel.msm import ShardedMSM
from dag_rider_tpu.parallel.sharded_verifier import ShardedTPUVerifier

__all__ = ["make_mesh", "batch_sharding", "ShardedMSM", "ShardedTPUVerifier"]
