"""Mesh-sharded G1 multi-scalar multiplication — the n=1024 rung.

BASELINE.md config #5 is "1024-node full-wave MSM, multi-host pmap on
v5e-16"; this is the modern spelling: ``shard_map`` over a 1-D device
mesh. The T points are sharded over the mesh's "batch" axis, each device
runs the windowed scalar walk + a *local* pairwise tree reduction down to
one partial sum (all compute stays on-device, zero communication), then a
single ``all_gather`` of D partial points rides ICI and every device
folds the D partials with log2(D) complete additions. One collective per
MSM — the communication-optimal shape for a sum tree.

The per-point walk and the complete-addition group law are exactly
:mod:`dag_rider_tpu.ops.bls_msm` (RCB15 formulas over the fold-matrix
field of :mod:`ops.field381`); sharding changes the schedule, never the
math, so results are bit-identical to the single-device kernel and the
host oracle (tests/test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dag_rider_tpu.ops import bls_msm
from dag_rider_tpu.parallel.mesh import make_mesh, shard_map


def make_sharded_msm_kernel(mesh: Mesh, impl: str = "jnp"):
    """Compile a sharded MSM over ``mesh``: int32[T, 64] nibbles +
    int32[T, LIMBS] coords -> one projective point (replicated).
    ``impl`` selects the per-shard tree engine (see bls_msm.window_sums);
    shard_map is exactly what lets the Mosaic kernels run per shard."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("batch")),
        out_specs=(P(), P(), P()),
        # After the all_gather every device folds the same D partials, so
        # the outputs ARE replicated — but the static varying-axis tracker
        # can't prove it through the tree fold; disable that check only.
        check_vma=False,
    )
    def _local(nib, px, py, pz):
        # per-shard window sums (tables + gather + wide tree — the
        # round-4 MSM shape, see bls_msm.window_sums): [64, LIMBS] each
        wsums = bls_msm.window_sums(nib, (px, py, pz), impl=impl)
        # one collective: D per-window partials -> every device, then
        # fold over the device axis (tree_reduce carries odd remainders,
        # so non-power-of-two device counts fold correctly) and run the
        # tiny single-point Horner combine replicated.
        gathered = tuple(
            jax.lax.all_gather(c, "batch", tiled=False) for c in wsums
        )  # [D, 64, LIMBS] each
        folded = bls_msm.tree_reduce(gathered)  # [1, 64, LIMBS]
        return bls_msm.horner_combine(tuple(c[0] for c in folded))

    return jax.jit(_local)


class ShardedMSM:
    """Host seam with the same signature as :func:`ops.bls_msm.msm` —
    plugs into ``threshold.aggregate(msm=...)`` / ``ThresholdCoin``."""

    def __init__(self, mesh: Optional[Mesh] = None, impl: Optional[str] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = int(np.prod(self.mesh.devices.shape))
        self._impl = impl
        self._kernels: dict = {}

    def __call__(
        self, scalars: Sequence[int], points: Sequence[tuple]
    ) -> Optional[tuple]:
        # Same marshalling as the single-device path, padded so every
        # shard gets an equal power-of-two slice.
        t = bls_msm._pad(len(points), base=max(4, self.n_shards))
        impl = (
            self._impl
            if self._impl is not None
            else bls_msm.msm_impl(t // self.n_shards)
        )
        if impl not in self._kernels:
            self._kernels[impl] = make_sharded_msm_kernel(self.mesh, impl)
        nib, px, py, pz = bls_msm.pack_inputs(scalars, points, t)
        X, Y, Z = self._kernels[impl](
            jnp.asarray(nib), jnp.asarray(px), jnp.asarray(py), jnp.asarray(pz)
        )
        return bls_msm.unpack_point(X, Y, Z)

    def sum_points(self, points: Sequence[tuple]) -> Optional[tuple]:
        """All-ones MSM — mesh-sharded certificate signature aggregation
        (ISSUE 9), mirroring :func:`ops.bls_msm.sum_points`."""
        return self([1] * len(points), points)
