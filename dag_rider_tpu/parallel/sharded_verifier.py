"""Multi-chip sharded Verifier — data-parallel verify over a device mesh.

The n=1024 rung of the benchmark ladder (BASELINE.json: "1024-node
full-wave MSM, multi-host pmap on v5e-16" — here pjit/NamedSharding, the
modern spelling): one DAG round's vertex batch is laid out [B, ...] and
sharded over the mesh's "batch" axis, so each chip verifies B/n_chips
signatures; the accept mask gathers back to host. No cross-chip
collectives are needed in the verify itself (it is embarrassingly
data-parallel) — XLA inserts the result all-gather; ICI carries it.

First-class on the async seam (round 7): this class overrides ONLY the
placement hooks of :class:`~dag_rider_tpu.verifier.tpu.TPUVerifier`
(``_round_bucket``/``_put``/``_comb_fn``/``_aot_lower``/...), so
``dispatch_batch``/``resolve_batch``/``warmup``/the chunk-streaming
``verify_rounds`` — and therefore every caller: ``VerifierPipeline``,
``Simulation.run``'s coalesced window, node.py — ride the mesh without a
single duplicated line of dispatch logic. Before round 7 those methods
were silently inherited un-overridden and every async caller dispatched
single-chip; the hook seam makes that fallback structurally impossible
(tests/test_parallel.py asserts the dispatched mask spans the mesh).

The round-8 parallel host-prep engine (verifier/prep.py) rides the same
seam for free: ``prep_batch``/``prep_batch_async`` run entirely ABOVE the
placement hooks (row blocks write into the host staging slot before
``_put``/``_note_dispatch`` ever see it), so sharded dispatch gets
multi-worker prep and prep-ahead with zero code here — the staging slot
stays one full-batch host array and only `_put` splits it over the mesh.

Byte-identical masks: chunk boundaries come from the caller-visible
``fixed_bucket`` exactly as on the single-chip path; only the PAD size of
each dispatch rounds up to a multiple of the mesh batch axis, and padding
rows are sliced off before any consumer sees them. So CPU / 1-chip /
N-chip runs agree bit-for-bit at every pipeline depth (test_pipeline.py
on the virtual 8-device CPU mesh).

Round-9 fault containment inherits the same way: ``reset_staging`` /
``_quarantine`` / the contained ``verify_rounds`` streaming loop and the
``quarantine_verifier`` slot all live above the placement hooks, so a
poisoned sharded window salvages, re-arms its (full-batch host) staging
ring and quarantines exactly like single-chip — and the chaos harness
(verifier/faults.py) arms this class through the identical instance-
attribute shadows (tests/test_chaos.py runs its suite on both).
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dag_rider_tpu import config
from dag_rider_tpu.ops import curve, field
from dag_rider_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_map,
)
from dag_rider_tpu.verifier.base import KeyRegistry
from dag_rider_tpu.verifier.tpu import TPUVerifier, _bucket, _comb_impl


class ShardedTPUVerifier(TPUVerifier):
    """TPUVerifier whose device dispatch shards the batch over a mesh.

    Verification is embarrassingly data-parallel: every per-vertex input
    (digits, key index, R.y) shards over the mesh's "batch" axis while
    the comb tables replicate (every chip holds the registry's tables —
    they are read-only and gather-indexed by the local shard's rows).
    ``comb=False`` falls back to sharding the windowed program.
    """

    def __init__(
        self,
        registry: KeyRegistry,
        mesh: Optional[Mesh] = None,
        comb: Optional[bool] = None,
    ):
        super().__init__(registry, comb=comb)
        # Replicating the 8-bit tables (1.07 GB at n=256) on every chip
        # is the wrong trade for a mesh; the sharded comb program is
        # pinned to 4-bit windows.
        if self._comb_bits != 4:
            warnings.warn(
                f"ShardedTPUVerifier pins comb windows to 4 bits; ignoring "
                f"DAGRIDER_COMB_BITS={self._comb_bits}",
                stacklevel=2,
            )
        self._comb_bits = 4
        self.mesh = mesh if mesh is not None else make_mesh()
        self._n_shards = int(np.prod(self.mesh.devices.shape))
        self._mesh_key = tuple(int(d) for d in self.mesh.devices.shape)
        self._batch_sharding = batch_sharding(self.mesh)
        self._repl_tables = None

        #: per-shard gauges of the most recent dispatch (the bench's
        #: verifier_breakdown / pipeline stats() surface them)
        self.mesh_devices = self._n_shards
        self.last_shard_batch = 0
        self.last_shard_imbalance = 0.0
        self.total_shard_imbalance = 0.0

        sharding = self._batch_sharding

        @functools.partial(
            jax.jit,
            in_shardings=(sharding,) * 9,
            out_shardings=sharding,
        )
        def _sharded_verify(
            s_nibbles, k_nibbles, a_x, a_y, a_t, a_valid, r_y, r_sign, prevalid
        ):
            one = jnp.broadcast_to(jnp.asarray(field.ONE), a_x.shape)
            a_point = (a_x, a_y, one, a_t)
            return curve.verify_core(
                s_nibbles, k_nibbles, a_point, a_valid, r_y, r_sign, prevalid
            )

        self._sharded_verify = _sharded_verify

        #: impl -> compiled shard_map comb kernel, built lazily. shard_map
        #: (not GSPMD jit) because Mosaic pallas_call kernels do not lower
        #: under auto-partitioning — per-shard they run as-is, so the
        #: flagship single-chip Pallas path and the multi-chip path are
        #: the SAME program per shard (round-3 VERDICT weak #4; pattern
        #: proven by parallel/msm.py).
        self._comb_kernels = {}

    def _sharded_comb_kernel(self, impl: str):
        if impl not in self._comb_kernels:
            from jax.sharding import PartitionSpec as P

            @functools.partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P("batch"), P("batch"), P(), P()),
                out_specs=P("batch"),
                # pallas_call can't declare per-axis varying metadata, so
                # the static varying-axis tracker must stand down (same
                # as parallel/msm.py); the specs above are the truth.
                check_vma=False,
            )
            def _local(u8, i32, key_tables, b_table):
                from dag_rider_tpu.verifier.tpu import _device_verify_comb

                return _device_verify_comb.__wrapped__(
                    u8, i32, key_tables, b_table, impl=impl
                )

            self._comb_kernels[impl] = jax.jit(_local)
        return self._comb_kernels[impl]

    # -- placement hooks (see TPUVerifier's dispatch seam) ----------------

    def _round_bucket(self, b: int) -> int:
        # Pad every dispatch to a multiple of the mesh so each shard gets
        # an equal slice — the GSPMD/shard_map programs require it, and
        # the rounding must apply to the fixed bucket and the
        # power-of-two ladder alike or shard padding diverges from the
        # 1-chip program shape.
        b = int(b)
        if b % self._n_shards:
            b += self._n_shards - b % self._n_shards
        assert b % self._n_shards == 0
        return b

    def _bucket_size(self, n: int) -> int:
        """Padded dispatch size for an n-vertex batch: the single-chip
        power-of-two ladder, then mesh-rounded."""
        return self._round_bucket(_bucket(n))

    def _select_impl(self, size: int) -> str:
        # Per-shard impl selection mirrors the single-chip rule (Pallas
        # on a real TPU backend for lane-aligned shards, jnp elsewhere);
        # DAGRIDER_SHARDED_COMB_IMPL overrides — e.g. "pallas_interpret"
        # exercises the kernel bodies on the virtual CPU mesh
        # (dryrun_multichip / tests).
        return config.env_str("DAGRIDER_SHARDED_COMB_IMPL") or _comb_impl(
            max(1, size // self._n_shards)
        )

    def _aot_key(self, size: int, impl: str) -> tuple:
        # mesh shape in the key: a warmup for an 8-device mesh must not
        # be served to a reconfigured 4-device run of the same bucket
        return (size, impl, self._comb_bits, self._mesh_key)

    def _put(self, arr: np.ndarray) -> jax.Array:
        # one NamedSharding device_put = n_shards per-device sub-buffer
        # transfers; each staging-ring slot stays a full-batch host array
        # so the ring discipline (pipeline_depth + 2 slots) is unchanged
        return jax.device_put(arr, self._batch_sharding)

    def _comb_tables_dev(self):
        if self._repl_tables is None:
            tables, b_tab = self._comb_tables()
            repl = replicated(self.mesh)
            self._repl_tables = (
                jax.device_put(tables, repl),
                jax.device_put(b_tab, repl),
            )
        return self._repl_tables

    def _comb_fn(self, impl: str):
        return self._sharded_comb_kernel(impl)

    def _windowed_dispatch(self, args) -> jax.Array:
        return self._sharded_verify(*(jnp.asarray(a) for a in args))

    def _aot_lower(self, size: int, impl: str, tables, b_tab):
        # No donation on the mesh path: the per-shard input sub-buffers
        # are small and the sharded executable is also the lazy kernel —
        # one program, AOT'd at the fixed bucket with sharding-carrying
        # avals so dispatch skips the jit cache entirely.
        shd = self._batch_sharding
        return (
            self._sharded_comb_kernel(impl)
            .lower(
                jax.ShapeDtypeStruct((size, 131), jnp.uint8, sharding=shd),
                jax.ShapeDtypeStruct((size, 23), jnp.int32, sharding=shd),
                tables,
                b_tab,
            )
            .compile()
        )

    def _note_dispatch(self, size: int, count: int) -> None:
        sb = size // self._n_shards
        self.last_shard_batch = sb
        if sb:
            per = [
                min(max(count - i * sb, 0), sb) for i in range(self._n_shards)
            ]
            self.last_shard_imbalance = (max(per) - min(per)) / sb
        else:
            self.last_shard_imbalance = 0.0
        self.total_shard_imbalance += self.last_shard_imbalance
