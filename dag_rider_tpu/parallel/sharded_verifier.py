"""Multi-chip sharded Verifier — data-parallel verify over a device mesh.

The n=1024 rung of the benchmark ladder (BASELINE.json: "1024-node
full-wave MSM, multi-host pmap on v5e-16" — here pjit/NamedSharding, the
modern spelling): one DAG round's vertex batch is laid out [B, ...] and
sharded over the mesh's "batch" axis, so each chip verifies B/n_chips
signatures; the accept mask gathers back to host. No cross-chip
collectives are needed in the verify itself (it is embarrassingly
data-parallel) — XLA inserts the result all-gather; ICI carries it.

Byte-identical masks: the device program is the same
``curve.verify_core`` regardless of sharding, so CPU / 1-chip / N-chip
runs agree bit-for-bit (test_parallel.py asserts this on the virtual
8-device CPU mesh).
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dag_rider_tpu.core.types import Vertex
from dag_rider_tpu.ops import curve, field
from dag_rider_tpu.parallel.mesh import batch_sharding, make_mesh
from dag_rider_tpu.verifier.base import KeyRegistry
from dag_rider_tpu.verifier.tpu import TPUVerifier


class ShardedTPUVerifier(TPUVerifier):
    """TPUVerifier whose device dispatch shards the batch over a mesh.

    Verification is embarrassingly data-parallel: every per-vertex input
    (digits, key index, R.y) shards over the mesh's "batch" axis while
    the comb tables replicate (every chip holds the registry's tables —
    they are read-only and gather-indexed by the local shard's rows).
    ``comb=False`` falls back to sharding the windowed program.
    """

    def __init__(
        self,
        registry: KeyRegistry,
        mesh: Optional[Mesh] = None,
        comb: Optional[bool] = None,
    ):
        super().__init__(registry, comb=comb)
        # Replicating the 8-bit tables (1.07 GB at n=256) on every chip
        # is the wrong trade for a mesh; the sharded comb program is
        # pinned to 4-bit windows.
        if self._comb_bits != 4:
            warnings.warn(
                f"ShardedTPUVerifier pins comb windows to 4 bits; ignoring "
                f"DAGRIDER_COMB_BITS={self._comb_bits}",
                stacklevel=2,
            )
        self._comb_bits = 4
        self.mesh = mesh if mesh is not None else make_mesh()
        self._n_shards = int(np.prod(self.mesh.devices.shape))
        sharding = batch_sharding(self.mesh)

        @functools.partial(
            jax.jit,
            in_shardings=(sharding,) * 9,
            out_shardings=sharding,
        )
        def _sharded_verify(
            s_nibbles, k_nibbles, a_x, a_y, a_t, a_valid, r_y, r_sign, prevalid
        ):
            one = jnp.broadcast_to(jnp.asarray(field.ONE), a_x.shape)
            a_point = (a_x, a_y, one, a_t)
            return curve.verify_core(
                s_nibbles, k_nibbles, a_point, a_valid, r_y, r_sign, prevalid
            )

        self._sharded_verify = _sharded_verify

        #: impl -> compiled shard_map comb kernel, built lazily. shard_map
        #: (not GSPMD jit) because Mosaic pallas_call kernels do not lower
        #: under auto-partitioning — per-shard they run as-is, so the
        #: flagship single-chip Pallas path and the multi-chip path are
        #: the SAME program per shard (round-3 VERDICT weak #4; pattern
        #: proven by parallel/msm.py).
        self._comb_kernels = {}

    def _sharded_comb_kernel(self, impl: str):
        if impl not in self._comb_kernels:
            from jax.sharding import PartitionSpec as P

            @functools.partial(
                jax.shard_map,
                mesh=self.mesh,
                in_specs=(P("batch"), P("batch"), P(), P()),
                out_specs=P("batch"),
                # pallas_call can't declare per-axis varying metadata, so
                # the static varying-axis tracker must stand down (same
                # as parallel/msm.py); the specs above are the truth.
                check_vma=False,
            )
            def _local(u8, i32, key_tables, b_table):
                from dag_rider_tpu.verifier.tpu import _device_verify_comb

                return _device_verify_comb.__wrapped__(
                    u8, i32, key_tables, b_table, impl=impl
                )

            self._comb_kernels[impl] = jax.jit(_local)
        return self._comb_kernels[impl]

    def _bucket_size(self, n: int) -> int:
        # pad to a multiple of the mesh so every shard gets equal work
        b = self._n_shards
        while b < n or b < 16:
            b *= 2
        return b

    def verify_batch(self, vertices: Sequence[Vertex]) -> List[bool]:
        if not vertices:
            return []
        size = self._bucket_size(len(vertices))
        args = self._prepare(vertices, size, comb=self._comb)
        if self._comb:
            u8, i32 = args
            tables, b_tab = self._comb_tables()
            # Per-shard impl selection mirrors the single-chip rule
            # (Pallas on a real TPU backend for lane-aligned shards, jnp
            # elsewhere); DAGRIDER_SHARDED_COMB_IMPL overrides — e.g.
            # "pallas_interpret" exercises the kernel bodies on the
            # virtual CPU mesh (dryrun_multichip / tests).
            from dag_rider_tpu.verifier.tpu import _comb_impl

            impl = os.environ.get("DAGRIDER_SHARDED_COMB_IMPL") or _comb_impl(
                size // self._n_shards
            )
            mask = np.asarray(
                self._sharded_comb_kernel(impl)(
                    jnp.asarray(u8), jnp.asarray(i32), tables, b_tab
                )
            )
        else:
            mask = np.asarray(
                self._sharded_verify(*(jnp.asarray(a) for a in args))
            )
        return [bool(m) for m in mask[: len(vertices)]]
