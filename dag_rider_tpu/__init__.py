"""dag_rider_tpu — a TPU-native DAG-Rider (Byzantine Atomic Broadcast) framework.

A from-scratch JAX/XLA/Pallas implementation of the DAG-Rider protocol
(Keidar et al. 2021, "All You Need is DAG", arXiv:2102.08325) with the
capabilities of the reference Go prototype (xenowits/dag-rider), re-designed
TPU-first:

- Dense tensor DAG encoding (``exists[R, n]``, ``strong[R, n, n]``) replaces
  pointer-chasing + linear scans (reference ``process/process.go:374-384``).
- Reachability = boolean matmul chains on the MXU (reference ``path`` BFS,
  ``process/process.go:89-148``).
- A batched ``Verifier`` seam (sibling of the ``Transport`` plugin boundary,
  reference ``process/transport.go:6-9``): whole-round Ed25519 / BLS batch
  verification as vmapped JAX + Pallas kernels, one DAG round per dispatch.
- Host-side consensus state machine implementing the *paper* semantics
  (the reference's quoted pseudocode), not the reference's bugs (SURVEY.md §8).

Layer map (mirrors SURVEY.md §1):

- ``core``       — vertex/block data model, generic stack           (L0)
- ``transport``  — pluggable broadcast: in-memory broker, faults    (L1)
- ``consensus``  — DAG construction + wave commit (Alg. 1-3)        (L2/L3)
- ``crypto``     — host reference crypto: Ed25519, BLS12-381, coin  (L4)
- ``ops``        — JAX device kernels: graph reachability, field
                   arithmetic, Edwards curve, SHA-512               (TPU)
- ``verifier``   — the batched Verifier seam: CPU + TPU impls       (north star)
- ``parallel``   — mesh/sharding helpers for multi-chip MSM         (ICI/DCN)
- ``utils``      — metrics, checkpoint/resume, profiling
"""

from dag_rider_tpu.config import Config
from dag_rider_tpu.core.types import Block, Vertex, VertexID

__version__ = "0.1.0"

__all__ = ["Config", "Block", "Vertex", "VertexID", "__version__"]
